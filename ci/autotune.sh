#!/bin/sh
# CI gate: autotuner smoke (docs/perf.md "Autotuning"). Tiny exhaustive
# grid over the zoo mlp on CPU through the in-process bench harness:
# asserts (a) the memcheck pruner statically rejects the over-budget K=16
# candidate without ever executing it, (b) a winner whose measured img/s
# >= the built-in default's is persisted to the tuning DB, and (c) a
# FRESH Module.fit with no knob arguments resolves the winner's knobs
# from the DB (obs-logged) with zero extra retraces (assert_no_retrace).
#
# The gate writes a SCRATCH DB — refreshing the committed AUTOTUNE_db.json
# is the operator workflow:
#   python -m mxnet_tpu.autotune --model mlp --objective img_per_sec \
#       --batch 48 --write-db   # then commit AUTOTUNE_db.json
set -e
cd "$(dirname "$0")/.."
DB="$(mktemp -t autotune_ci_XXXXXX.json)"
rm -f "$DB"
trap 'rm -f "$DB"' EXIT
JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" PYTHONPATH=. \
    MXTPU_AUTOTUNE_DB="$DB" \
    python tools/autotune_gate.py
echo "autotune PASS"
