#!/bin/sh
# Local CI: same stages as ci/pipeline.yml (ref role: Jenkinsfile).
set -e
cd "$(dirname "$0")/.."
make -C src
make -C src/capi
c++ -O2 -std=c++14 -I cpp-package/include cpp-package/example/train_mlp.cpp \
    -L lib -lmxnet_tpu -Wl,-rpath,'$ORIGIN' -o lib/train_mlp_cpp
# C++ LeNet through the generated op wrappers (built by make -C src/capi;
# run gated on holdout accuracy >= 0.95)
PYTHONPATH=. JAX_PLATFORMS=cpu ./lib/lenet_cpp
# Perl XS binding consumes the same ABI (non-C language proof)
make -C perl-package
(cd perl-package && PYTHONPATH=.. JAX_PLATFORMS=cpu perl predict.pl)
JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    python -m pytest tests/ -q
python -c "import __graft_entry__ as g; g.dryrun_multichip(8)"
echo "CI PASS"
