#!/bin/sh
# Local CI: same stages as ci/pipeline.yml (ref role: Jenkinsfile).
set -e
cd "$(dirname "$0")/.."
make -C src
make -C src/capi
c++ -O2 -std=c++14 -I cpp-package/include cpp-package/example/train_mlp.cpp \
    -L lib -lmxnet_tpu -Wl,-rpath,'$ORIGIN' -o lib/train_mlp_cpp
# C++ LeNet through the generated op wrappers (built by make -C src/capi;
# run gated on holdout accuracy >= 0.95)
PYTHONPATH=. JAX_PLATFORMS=cpu ./lib/lenet_cpp
# Perl XS binding consumes the same ABI (non-C language proof)
make -C perl-package
(cd perl-package && PYTHONPATH=.. JAX_PLATFORMS=cpu perl predict.pl)
JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    python -m pytest tests/ -q
# static lints over the model zoo's compiled step programs
# (docs/static_analysis.md; tier-1 keeps a faster 2-model smoke)
./ci/tracecheck.sh
# combined compile-once static audit (docs/static_analysis.md "Roofline
# lints"): each zoo + sharded program compiles ONCE and the same
# executable feeds all three per-program analyzers — flopcheck's kernel
# inventory + roofline lints + drift gate vs FLOPCHECK_baseline.json,
# memcheck's HBM lints + resident sets vs MEMCHECK_baseline.json, and
# commscheck's collective inventory vs COMMSCHECK_baseline.json
# (ci/memcheck.sh and ci/commscheck.sh stay for standalone runs)
./ci/flopcheck.sh
# zoo-dispatch gate (docs/perf.md "Packed accumulators"): every zoo
# model must report a non-fallback K-step dispatch path (or a named,
# documented reason) — precheck sweep over the whole zoo + real
# steps_per_dispatch fits on the cheap models, tracecheck-clean
./ci/zoo_dispatch.sh
# autotuner smoke (docs/perf.md "Autotuning"): tiny grid over mlp —
# memcheck pruner rejects the over-budget candidate without executing
# it, a measured winner >= the default persists to the tuning DB, and a
# fresh Module.fit resolves it (obs-logged) with zero extra retraces
./ci/autotune.sh
# serving-tier smoke: AOT buckets + dynamic batcher at low QPS, zero
# tracecheck findings on the serving program set (docs/serving.md)
./ci/serve.sh
# fleet-tier smoke (docs/serving.md "Fleet tier"): 2 replicas behind the
# priority-aware router at a QPS one replica cannot hold, mid-run
# drain+rejoin; zero failed/shed requests, per-class p99 cap, zero
# static findings across every replica's program set
./ci/fleet.sh
# flagship-LM gate (docs/perf.md "Flagship LM"): dp2 x sp2 ring-attention
# fit parity vs single device, MID-FIT decode hot reload (zero recompiles,
# bitwise vs a fresh engine), zero retraces, and zero analyzer findings
# over the co-resident train + serve program set
./ci/lm.sh
# observability gate (docs/observability.md): fused fit + batcher serve
# under MXTPU_TRACE=1 — Chrome-trace schema validation (stages present,
# spans nested, dispatch/request IDs consistent), registry snapshot
# carries every legacy health key, tracing-off cost A/B
./ci/obs.sh
# real-data input-tier smoke (docs/perf.md "Device-fed input pipeline"):
# small real-JPEG epoch through reader -> decode workers -> prefetch ->
# fused scan; gates the real/synthetic throughput ratio floor
# (MXTPU_REALDATA_MIN_RATIO), zero tracecheck findings, and populated
# DataHealth/PipelineStats
./ci/realdata.sh
# elastic-distributed gate (docs/robustness.md "Elastic distributed
# training"): REAL 3-process dist_sync run that SIGKILLs a worker
# mid-epoch — emergency checkpoint, ring re-form at N-1 with re-derived
# shards, accuracy floor, bitwise-consistent survivors, bitwise fresh
# resume, and a collective-throughput floor vs 1 worker
# (MXTPU_DIST_MIN_SCALE); emits DIST_r*.json
./ci/dist.sh
# chaos gate (docs/robustness.md "Chaos harness"): RED self-test first
# (a deliberately inverted invariant must fail a run), then seeded
# composed-fault plans through all four scenarios — train/data/dist/
# serve, each in a watchdogged subprocess — with zero violations and
# zero hangs, committed-regression replays, and the shrinker loop;
# emits CHAOS_r*.json
./ci/chaos.sh
# multichip gate (docs/perf.md "Data-parallel scaling"): MEASURED — 8-device
# fused-fit img/s + scaling efficiency vs 1 device (floor
# MXTPU_MULTICHIP_MIN_EFF, default 0.7), guard + bitwise checkpoint/resume
# composition, collective/donation audit of the sharded program set; emits
# MULTICHIP_r*.json
python -c "import __graft_entry__ as g; g.dryrun_multichip(8)"
# chip stage: hard convergence gates + the ImageNet recipe compile-check
# (uses the real TPU when attached; tools default to the ambient platform).
# The full-size gate (defaults: 2400 imgs, 6 epochs) passes too but takes
# ~27 min on a 1-core host; CI runs the mid-size config.
python tools/convergence_gate_realdata.py \
    --n-per-class 100 --epochs 5 --min-acc 0.9
python example/image-classification/train_imagenet.py --validate-recipe
echo "CI PASS"
