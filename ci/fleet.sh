#!/bin/sh
# CI gate: fleet-tier smoke (docs/serving.md "Fleet tier"). Two single-chip
# mlp replicas behind a FleetRouter on CPU, open-loop load above one
# replica's achieved rps (the per-dispatch device time is emulated — see
# BENCH_FLEET_DEVICE_MS in bench.py — so replica capacity is wall-bound
# and real on a 1-core host), mixed interactive/batch classes, and a
# MID-RUN drain + warm rejoin of one replica. Asserts:
#   (a) zero failed requests in BOTH phases (drain/join must shed nothing),
#   (b) p99 per class under a deliberately generous cap,
#   (c) zero unsuppressed tracecheck/memcheck/commscheck findings across
#       EVERY replica's program set,
#   (d) the drain+join event completed,
#   (e) a loose scaling sanity floor (the committed BENCH_fleet_rNN.json
#       pins the real >= 1.8x number; this is a works-at-all smoke).
#
# Usage: ci/fleet.sh [p99_cap_ms]   (default 3000)
set -e
cd "$(dirname "$0")/.."
CAP_MS="${1:-3000}"
echo "ci/fleet.sh: 2 mlp replicas, qps 500, mid-run drain+rejoin"
JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" PYTHONPATH=. \
    BENCH_FLEET=1 BENCH_FLEET_REPLICAS=2 \
    BENCH_FLEET_REQS=240 BENCH_FLEET_SINGLE_REQS=100 \
    python bench.py | tail -n 1 | CAP_MS="$CAP_MS" python -c '
import json, os, sys
r = json.loads(sys.stdin.readline())
cap = float(os.environ["CAP_MS"])
bad = []
if r["failed"] or r["single_phase_failed"]:
    bad.append("%d fleet / %d single-phase requests failed"
               % (r["failed"], r["single_phase_failed"]))
if r["shed"]:
    bad.append("%d requests shed (drain/death must re-queue, not shed)"
               % r["shed"])
if r["drain_event"] != "drain+join ok":
    bad.append("drain/join event: %s" % r["drain_event"])
if r["tracecheck_findings"]:
    bad.append("%d static findings across the replica program sets"
               % r["tracecheck_findings"])
for cls in ("interactive", "batch"):
    if cls in r and r[cls]["p99_ms"] > cap:
        bad.append("%s p99 %.1f ms over the %.0f ms smoke cap"
                   % (cls, r[cls]["p99_ms"], cap))
if r["scaling"] < 1.2:
    bad.append("fleet rps only %.2fx one replica (smoke floor 1.2x; "
               "the committed bench pins >= 1.8x)" % r["scaling"])
if bad:
    sys.exit("ci/fleet.sh FAIL (%s): %s" % (r["metric"], "; ".join(bad)))
print("  %s: scaling %.2fx (%.1f vs %.1f rps), interactive p99 %.1f ms, "
      "batch p99 %.1f ms, requeued %d, shed 0, findings 0"
      % (r["metric"], r["scaling"], r["rps_fleet"], r["rps_single"],
         r["interactive"]["p99_ms"], r["batch"]["p99_ms"], r["requeued"]))
'
echo "fleet smoke PASS"
