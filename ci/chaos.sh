#!/bin/sh
# CI gate: seeded deterministic chaos harness (docs/robustness.md "Chaos
# harness"). Proves the gate can turn RED (a deliberately inverted
# invariant must fail a run), then drives MXTPU_CHAOS_ROUNDS seeded
# fault plans through each of the four scenarios — fused-fit train,
# data tier, REAL 3-process dist_sync, FleetRouter+DecodeLoop serve —
# each in a watchdogged subprocess, demanding zero invariant violations
# and zero hangs; replays every committed regression plan under
# tests/chaos_plans/; and exercises the shrinker's reduction loop.
# Emits CHAOS_r18.json.
set -e
cd "$(dirname "$0")/.."
JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" PYTHONPATH=. \
    python tools/chaos_gate.py
echo "chaos PASS"
