#!/bin/sh
# CI gate: elastic multi-process distributed training (docs/robustness.md
# "Elastic distributed training"). Launches a REAL 3-worker dist_sync run
# that SIGKILLs its highest rank mid-epoch (kv.worker_die), and asserts —
# inside each surviving worker — the emergency checkpoint, the ring
# re-form at N-1 with re-derived data shards, training to the accuracy
# floor, bitwise-consistent survivor replicas, and a bitwise-identical
# fresh resume; then gates the collective throughput (net of the
# configured MXTPU_DIST_DEAD_FOR detection stall) against a
# single-worker baseline (floor MXTPU_DIST_MIN_SCALE, default 0.10).
# Emits DIST_r17.json.
set -e
cd "$(dirname "$0")/.."
JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" PYTHONPATH=. \
    python tools/dist_gate.py
echo "dist PASS"
