#!/bin/sh
# CI gate: static HBM audit of the model zoo's compiled step programs
# (docs/static_analysis.md "Memory lints"). Compiles every zoo program
# WITHOUT executing it, runs the memory lints (hbm-budget /
# donation-waste / temp-blowup / resident-set), and compares each
# program's peak/temp bytes against the committed MEMCHECK_baseline.json
# with a tolerance band (MXTPU_MEMCHECK_TOL, default 10%) — any program
# growing past tolerance fails with the buffer breakdown in the message.
#
# Baseline-update workflow (docs/static_analysis.md):
#   python -m mxnet_tpu.memcheck --zoo --write-baseline MEMCHECK_baseline.json
# and commit the diff alongside the change that moved the numbers.
#
# Usage: ci/memcheck.sh [model,model,...]   (default: the whole zoo,
# gated against the baseline; an explicit subset skips the baseline)
set -e
cd "$(dirname "$0")/.."
MODELS="$1"
if [ -n "$MODELS" ]; then
    set -- --models "$MODELS"
else
    set -- --zoo --baseline MEMCHECK_baseline.json
fi
JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" PYTHONPATH=. \
    python -m mxnet_tpu.memcheck "$@"
echo "memcheck PASS"
