#!/bin/sh
# CI gate: serving-tier smoke (docs/serving.md). For mlp and lenet, AOT-
# compile a two-bucket engine, drive the dynamic batcher at low QPS on CPU
# through bench.py's BENCH_SERVE mode, and assert (a) zero unsuppressed
# tracecheck findings on the serving program set, (b) every request
# completed, (c) p99 latency under a deliberately generous cap — this is a
# "the serving tier works and stays lint-clean" gate, not a perf gate
# (BENCH_serve_rNN.json tracks the number).
#
# Usage: ci/serve.sh [p99_cap_ms]   (default 2000)
set -e
cd "$(dirname "$0")/.."
CAP_MS="${1:-2000}"
for MODEL in mlp lenet; do
    echo "ci/serve.sh: $MODEL (buckets 1,8; qps 50)"
    JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" PYTHONPATH=. \
        BENCH_SERVE=1 BENCH_SERVE_MODEL="$MODEL" \
        BENCH_SERVE_QPS=50 BENCH_SERVE_REQS=60 BENCH_SERVE_CLIENTS=3 \
        MXTPU_SERVE_BUCKETS="1,8" \
        python bench.py | tail -n 1 | CAP_MS="$CAP_MS" python -c '
import json, os, sys
r = json.loads(sys.stdin.readline())
cap = float(os.environ["CAP_MS"])
bad = []
if r["tracecheck_findings"]:
    bad.append("tracecheck findings on the serving program set: %d"
               % r["tracecheck_findings"])
if r["failed"]:
    bad.append("%d requests failed" % r["failed"])
if r["p99_ms"] > cap:
    bad.append("p99 %.1f ms over the %.0f ms smoke cap" % (r["p99_ms"], cap))
if r["p50_ms"] >= 50.0:
    # regression guard for the old 50 ms wait() poll quantum: at this low
    # QPS a served request must resolve well inside one former poll step
    bad.append("p50 %.1f ms not sub-poll-interval (< 50 ms) — wait() is "
               "quantizing latency again" % r["p50_ms"])
if bad:
    sys.exit("ci/serve.sh FAIL (%s): %s" % (r["metric"], "; ".join(bad)))
print("  %s: p50 %.2f ms, p99 %.2f ms, %.1f req/s, findings 0"
      % (r["metric"], r["p50_ms"], r["p99_ms"], r["throughput_rps"]))
'
done

# sampled+quantized phase: the SAME open-loop client, but the engine
# loads int8 weights (MXTPU_SERVE_QUANT) — the quantized program set must
# stay lint-clean and shed nothing
echo "ci/serve.sh: mlp int8-quantized (buckets 1,8; qps 50)"
JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" PYTHONPATH=. \
    BENCH_SERVE=1 BENCH_SERVE_MODEL=mlp \
    BENCH_SERVE_QPS=50 BENCH_SERVE_REQS=60 BENCH_SERVE_CLIENTS=3 \
    MXTPU_SERVE_BUCKETS="1,8" MXTPU_SERVE_QUANT=int8 \
    python bench.py | tail -n 1 | CAP_MS="$CAP_MS" python -c '
import json, os, sys
r = json.loads(sys.stdin.readline())
bad = []
if r["tracecheck_findings"]:
    bad.append("tracecheck findings on the quantized program set: %d"
               % r["tracecheck_findings"])
if r["failed"]:
    bad.append("%d requests failed on the quantized engine" % r["failed"])
if r["p99_ms"] > float(os.environ["CAP_MS"]):
    bad.append("quantized p99 %.1f ms over the smoke cap" % r["p99_ms"])
if bad:
    sys.exit("ci/serve.sh FAIL (%s int8): %s" % (r["metric"], "; ".join(bad)))
print("  %s (int8): p50 %.2f ms, p99 %.2f ms, findings 0"
      % (r["metric"], r["p50_ms"], r["p99_ms"]))
'

# decode-path phase: sampled decode through all four legs (docs/serving.md
# "Production decode path") — the quality gate runs INSIDE bench.py
# (check_quality raises = nonzero exit), so this asserts the structural
# facts: zero findings, the int8 HBM win, spec token-identity
echo "ci/serve.sh: decode path (sampling/int8/prefix/spec)"
JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" PYTHONPATH=. \
    BENCH_DECODE=1 \
    python bench.py | tail -n 1 | python -c '
import json, sys
r = json.loads(sys.stdin.readline())
bad = []
if r["tracecheck_findings"]:
    bad.append("tracecheck findings on the decode program set: %d"
               % r["tracecheck_findings"])
legs = r["legs"]
int8 = legs["int8"]
if int8["weight_hbm_reduction"] < 0.40:
    bad.append("int8 weight-HBM reduction %.2f below the 40%% floor"
               % int8["weight_hbm_reduction"])
spec = [v for k, v in legs.items() if k.startswith("spec_k")][0]
if not spec["token_identical"]:
    bad.append("speculative decode diverged from target-only sampling")
if legs["prefix"]["prefix_hits"] < 1:
    bad.append("prefix cache never hit")
if bad:
    sys.exit("ci/serve.sh FAIL (%s): %s" % (r["metric"], "; ".join(bad)))
print("  %s: base %.0f tok/s, int8 -%.0f%% weight HBM (agree %.3f), "
      "prefix x%.2f, spec identical" % (
          r["metric"], r["value"],
          int8["weight_hbm_reduction"] * 100, int8["top1_agreement"],
          legs["prefix"]["x_vs_greedy_f32"]))
'
echo "serve smoke PASS"
