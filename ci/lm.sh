#!/bin/sh
# CI gate: flagship LM train-to-serve (docs/perf.md "Flagship LM"). A
# small transformer LM through Module.fit's fused K-step scan on the
# FORCED-HOST dp2 x sp2 mesh (8 virtual CPU devices). Asserts:
#   (a) multi-axis fit parity — final params match the single-device fit
#       (the composed data x seq mesh changes the schedule, not the math),
#   (b) MID-FIT hot reload — an epoch-end callback swaps live params into
#       a serving DecodeLoop with ZERO recompiles and the greedy decode
#       bitwise-identical to a fresh engine built from the same snapshot,
#   (c) zero unexpected retraces across both fits,
#   (d) zero analyzer findings: comms lints over the dp x sp scan program
#       + memcheck.lint_resident_set over the co-resident train + serve
#       program set (fused scan + every compiled serving bucket).
#
# The committed BENCH_lm_r16.json pins the measured tokens/sec + MFU
# numbers; this is the works-everywhere correctness half of that gate.
set -e
cd "$(dirname "$0")/.."
echo "ci/lm.sh: dp2 x sp2 LM fit parity + mid-fit hot reload"
JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" PYTHONPATH=. \
    XLA_FLAGS="${XLA_FLAGS:---xla_force_host_platform_device_count=8}" \
    python tools/lm_gate.py
echo "lm PASS"
