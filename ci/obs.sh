#!/bin/sh
# CI gate: unified observability (docs/observability.md). Runs a small
# fused fit and a batcher serve run under MXTPU_TRACE=1 and
# schema-validates the emitted Chrome trace (expected stages present,
# spans properly nested, dispatch/request correlation IDs consistent),
# asserts the metrics-registry snapshot carries every legacy health key
# (the five process-global counter objects as views), and A/Bs that
# tracing-off keeps obs.span a flag-check no-op (ns-bounded) with no
# measurable per-dispatch fit cost.
set -e
cd "$(dirname "$0")/.."
JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" PYTHONPATH=. \
    python tools/obs_gate.py
echo "obs PASS"
