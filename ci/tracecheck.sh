#!/bin/sh
# CI gate: static-analyze the model zoo's compiled step programs
# (docs/static_analysis.md). Runs the tracecheck CLI over every shipped
# model's step / scan / guarded-step / guarded-scan lowering — no step
# program executes — and fails on any NEW unsuppressed finding
# (host-sync, donation, const-capture, dtype-f64, dtype-weak).
#
# Usage: ci/tracecheck.sh [model,model,...]   (default: the whole zoo)
set -e
cd "$(dirname "$0")/.."
MODELS="$1"
if [ -n "$MODELS" ]; then
    set -- --models "$MODELS"
else
    set -- --zoo
fi
JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" PYTHONPATH=. \
    python -m mxnet_tpu.tracecheck "$@"
echo "tracecheck PASS"
