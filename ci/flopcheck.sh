#!/bin/sh
# CI gate: the COMBINED compile-once static audit (docs/static_analysis.md
# "Roofline lints"). The flopcheck CLI compiles every zoo program plus the
# PR 7 sharded gate set ONCE and feeds the same executables to all three
# per-program analyzers:
#
#   flopcheck  — kernel inventory + roofline lints (memory-bound-hot /
#                layout-copy / tiny-dispatch / predicted-mfu), drift gate
#                vs FLOPCHECK_baseline.json (kernel count, predicted step
#                ms, predicted MFU, top-hotspot identity; tolerance
#                MXTPU_FLOPCHECK_TOL, default 10%)
#   memcheck   — HBM lints + per-model resident sets, peak/temp bytes vs
#                MEMCHECK_baseline.json (zoo programs)
#   commscheck — collective inventory + comms lints, per-dispatch
#                collective count/bytes vs COMMSCHECK_baseline.json
#
# This replaces three separate compile-everything sweeps (ci/memcheck.sh
# and ci/commscheck.sh stay on disk for standalone runs and baseline
# refreshes); the compile phase logs the wall-clock the sharing saved.
#
# Baseline-update workflow (docs/static_analysis.md):
#   XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
#     python -m mxnet_tpu.flopcheck --zoo --sharded \
#     --write-baseline FLOPCHECK_baseline.json
# and commit the diff alongside the change that moved the numbers.
#
# Usage: ci/flopcheck.sh [model,model,...]   (default: zoo + sharded set
# gated against all three baselines; an explicit subset skips the
# sharded set and the baselines)
set -e
cd "$(dirname "$0")/.."
MODELS="$1"
if [ -n "$MODELS" ]; then
    set -- --models "$MODELS"
else
    set -- --zoo --sharded \
        --baseline FLOPCHECK_baseline.json \
        --memcheck-baseline MEMCHECK_baseline.json \
        --commscheck-baseline COMMSCHECK_baseline.json
fi
JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" \
    XLA_FLAGS="${XLA_FLAGS:---xla_force_host_platform_device_count=8}" \
    PYTHONPATH=. python -m mxnet_tpu.flopcheck "$@"
echo "flopcheck PASS"
