#!/bin/sh
# CI gate: every zoo model reports a non-fallback K-step dispatch path
# (docs/perf.md "Packed accumulators") — the packed-accumulator protocol's
# no-silent-k=1 contract. Precheck sweep over the whole zoo (the exact
# predicate fit consults, nothing executes) + real steps_per_dispatch=2
# fits on the cheap models (mlp, lenet, ssd, transformer) that must land
# a compiled scan and leave the program registry tracecheck-clean.
set -e
cd "$(dirname "$0")/.."
JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" PYTHONPATH=. \
    python tools/zoo_dispatch_gate.py
echo "zoo-dispatch PASS"
