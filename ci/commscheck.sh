#!/bin/sh
# CI gate: static collective-communication audit of the zoo's 28 compiled
# step programs PLUS the PR 7 sharded gate set (dp lenet scan, dp x tp
# resnet18, dp x sp ring transformer) — docs/static_analysis.md
# "Communication lints". Compiles every program WITHOUT executing it,
# runs the comms lints (resharding-copy / replicated-large /
# gather-in-loop / comms-bound), and compares each program's per-dispatch
# collective count and payload bytes against the committed
# COMMSCHECK_baseline.json with a tolerance band (MXTPU_COMMSCHECK_TOL,
# default 10%; counts are HLO-deterministic, so there is no absolute
# slack and a collective appearing where the baseline pinned zero fails
# at any tolerance) — a refactor that sneaks an all-gather into the scan
# body or triples the psum payload fails HERE, with byte count and
# source provenance, before any multichip run.
#
# Baseline-update workflow (docs/static_analysis.md):
#   XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
#     python -m mxnet_tpu.commscheck --zoo --sharded \
#     --write-baseline COMMSCHECK_baseline.json
# and commit the diff alongside the change that moved the numbers.
#
# Usage: ci/commscheck.sh [model,model,...]   (default: zoo + sharded
# set, gated against the baseline; an explicit subset skips both the
# sharded set and the baseline)
set -e
cd "$(dirname "$0")/.."
MODELS="$1"
if [ -n "$MODELS" ]; then
    set -- --models "$MODELS"
else
    set -- --zoo --sharded --baseline COMMSCHECK_baseline.json
fi
JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" \
    XLA_FLAGS="${XLA_FLAGS:---xla_force_host_platform_device_count=8}" \
    PYTHONPATH=. python -m mxnet_tpu.commscheck "$@"
echo "commscheck PASS"
