#!/bin/sh
# Real-data input-tier smoke gate (docs/perf.md "Device-fed input
# pipeline"): a small real-JPEG epoch through the full mxnet_tpu.data
# tier — sharded reader -> 2 decode workers -> superbatch stack ->
# prefetch-to-device -> fused K-step scan — must reach the
# MXTPU_REALDATA_MIN_RATIO floor of the synthetic device-resident number
# on the SAME model/batch/K, with zero tracecheck findings and populated
# DataHealth/PipelineStats. bench.py exits nonzero below the floor; the
# python block asserts the observability fields so a silent
# instrumentation regression fails CI, not just a slow epoch.
set -e
cd "$(dirname "$0")/.."
make -C src >/dev/null

OUT=$(JAX_PLATFORMS=cpu BENCH_REAL_DATA=1 \
      BENCH_RD_MODEL=lenet BENCH_RD_IMAGE=48 BENCH_RD_BATCH=32 \
      BENCH_STEPS_PER_DISPATCH=2 BENCH_RD_IMAGES=128 \
      BENCH_RD_MEASURE=4,12 MXTPU_DATA_WORKERS=2 BENCH_ROUNDS=1 \
      python bench.py | tail -1)
echo "$OUT"
echo "$OUT" | python -c '
import json, sys
r = json.loads(sys.stdin.read())
assert r["ratio"] >= r["min_ratio"], (r["ratio"], r["min_ratio"])
assert r["tracecheck_findings"] == 0, r["tracecheck_findings"]
p = r["pipeline"]
for stage in ("read_s", "decode_s", "stack_s", "h2d_s"):
    assert p.get(stage, 0) > 0, (stage, p)
assert "stall_frac" in p and "queue_depth_avg" in p, p
h = r["data_health"]
for key in ("retries", "skipped_records", "failures"):
    assert key in h, h
assert r["workers"] == 2, r
print("REALDATA SMOKE PASS: %.1f img/s, ratio %.3f (floor %.2f), "
      "stall_frac %.3f" % (r["value"], r["ratio"], r["min_ratio"],
                           p["stall_frac"]))
'
