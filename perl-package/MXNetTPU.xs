/*
 * MXNetTPU.xs — minimal Perl binding over the compiled C ABI
 * (ref role: perl-package/ AI::MXNet, 16.9k LoC of Perl over SWIG glue;
 * SURVEY.md §2.7). Proves libmxnet_tpu.so is consumable from a non-C
 * managed language: the Perl consumer (predict.pl) builds a symbol,
 * binds an executor, and runs inference through these stubs.
 *
 * Build: see perl-package/Makefile (xsubpp -> cc -shared).
 */
#include "EXTERN.h"
#include "perl.h"
#include "XSUB.h"

#include <stdint.h>
#include <stdlib.h>
#include <string.h>

typedef uint64_t H;
typedef unsigned int mx_uint;

extern const char *MXGetLastError(void);
extern int MXGetVersion(int *);
extern int MXNDArrayCreate(const uint32_t *, uint32_t, int, int, int, H *);
extern int MXNDArraySyncCopyFromCPU(H, const void *, size_t);
extern int MXNDArraySyncCopyToCPU(H, void *, size_t);
extern int MXSymbolCreateVariable(const char *, H *);
extern int MXSymbolCreateAtomicSymbol(const char *, uint32_t, const char **,
                                      const char **, H *);
extern int MXSymbolCompose(H, const char *, uint32_t, const char **, H *);
extern int MXSymbolListArguments(H, uint32_t *, const char ***);
extern int MXSymbolListAtomicSymbolCreators(mx_uint *, H **);
extern int MXExecutorBind(H, int, int, uint32_t, H *, H *, uint32_t, H *,
                          H *);
extern int MXExecutorForward(H, int);
extern int MXExecutorOutputs(H, uint32_t *, H **);

#define PCHK(call)                                                       \
    do {                                                                 \
        if ((call) != 0) croak("mxnet_tpu: %s", MXGetLastError());       \
    } while (0)

/* parse "a,b,c" into uint32 array; returns count */
static uint32_t parse_csv_u32(const char *s, uint32_t *out, uint32_t cap) {
    uint32_t n = 0;
    while (s && *s && n < cap) {
        out[n++] = (uint32_t)strtoul(s, (char **)&s, 10);
        if (*s == ',') s++;
    }
    return n;
}

static uint64_t parse_csv_u64(const char *s, H *out, uint32_t cap) {
    uint32_t n = 0;
    while (s && *s && n < cap) {
        out[n++] = (H)strtoull(s, (char **)&s, 10);
        if (*s == ',') s++;
    }
    return n;
}

MODULE = MXNetTPU  PACKAGE = MXNetTPU

PROTOTYPES: DISABLE

int
version()
    CODE:
        int v = 0;
        PCHK(MXGetVersion(&v));
        RETVAL = v;
    OUTPUT:
        RETVAL

unsigned int
op_count()
    CODE:
        mx_uint n = 0;
        H *arr = NULL;
        PCHK(MXSymbolListAtomicSymbolCreators(&n, &arr));
        RETVAL = n;
    OUTPUT:
        RETVAL

UV
nd_create(shape_csv)
        const char *shape_csv
    CODE:
        uint32_t shape[8];
        uint32_t nd = parse_csv_u32(shape_csv, shape, 8);
        H h = 0;
        PCHK(MXNDArrayCreate(shape, nd, 1, 0, 0, &h));
        RETVAL = (UV)h;
    OUTPUT:
        RETVAL

void
nd_set(h, packed)
        UV h
        SV *packed
    CODE:
        STRLEN len;
        const char *buf = SvPV(packed, len);
        PCHK(MXNDArraySyncCopyFromCPU((H)h, buf, len / sizeof(float)));

SV *
nd_get(h, nfloat)
        UV h
        UV nfloat
    CODE:
        float *buf = (float *)malloc(nfloat * sizeof(float));
        int rc = MXNDArraySyncCopyToCPU((H)h, buf, nfloat);
        if (rc != 0) {
            free(buf);
            croak("mxnet_tpu: %s", MXGetLastError());
        }
        RETVAL = newSVpvn((const char *)buf, nfloat * sizeof(float));
        free(buf);
    OUTPUT:
        RETVAL

UV
sym_variable(name)
        const char *name
    CODE:
        H h = 0;
        PCHK(MXSymbolCreateVariable(name, &h));
        RETVAL = (UV)h;
    OUTPUT:
        RETVAL

UV
sym_create(op, keys_csv, vals_csv, name, in_csv)
        const char *op
        const char *keys_csv
        const char *vals_csv
        const char *name
        const char *in_csv
    CODE:
        /* keys/vals as ';'-separated (attr values may contain commas) */
        const char *keys[16], *vals[16];
        char kbuf[512], vbuf[512];
        uint32_t nk = 0;
        if (keys_csv && *keys_csv) {
            strncpy(kbuf, keys_csv, sizeof(kbuf) - 1);
            kbuf[sizeof(kbuf) - 1] = 0;
            strncpy(vbuf, vals_csv, sizeof(vbuf) - 1);
            vbuf[sizeof(vbuf) - 1] = 0;
            char *kp = kbuf, *vp = vbuf;
            while (kp && vp && nk < 16) {
                keys[nk] = kp;
                vals[nk] = vp;
                nk++;
                kp = strchr(kp, ';');
                if (kp) *kp++ = 0;
                vp = strchr(vp, ';');
                if (vp) *vp++ = 0;
            }
        }
        H h = 0;
        PCHK(MXSymbolCreateAtomicSymbol(op, nk, keys, vals, &h));
        H ins[16];
        uint32_t ni = (uint32_t)parse_csv_u64(in_csv, ins, 16);
        PCHK(MXSymbolCompose(h, name, ni, NULL, ins));
        RETVAL = (UV)h;
    OUTPUT:
        RETVAL

SV *
sym_arguments(h)
        UV h
    CODE:
        uint32_t n = 0;
        const char **names = NULL;
        PCHK(MXSymbolListArguments((H)h, &n, &names));
        SV *joined = newSVpvn("", 0);
        for (uint32_t i = 0; i < n; i++) {
            if (i) sv_catpvn(joined, ",", 1);
            sv_catpv(joined, names[i]);
        }
        RETVAL = joined;
    OUTPUT:
        RETVAL

UV
exec_bind(sym, args_csv)
        UV sym
        const char *args_csv
    CODE:
        H args[64];
        uint32_t n = (uint32_t)parse_csv_u64(args_csv, args, 64);
        H ex = 0;
        PCHK(MXExecutorBind((H)sym, 1, 0, n, args, NULL, 0, NULL, &ex));
        RETVAL = (UV)ex;
    OUTPUT:
        RETVAL

void
exec_forward(ex)
        UV ex
    CODE:
        PCHK(MXExecutorForward((H)ex, 0));

UV
exec_out0(ex)
        UV ex
    CODE:
        uint32_t n = 0;
        H *outs = NULL;
        PCHK(MXExecutorOutputs((H)ex, &n, &outs));
        if (n < 1) croak("mxnet_tpu: executor has no outputs");
        RETVAL = (UV)outs[0];
    OUTPUT:
        RETVAL
