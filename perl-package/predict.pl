#!/usr/bin/perl
# predict.pl — Perl consumer of the compiled C ABI through the MXNetTPU XS
# binding (ref role: perl-package/ AI::MXNet inference;
# VERDICT r4 item 10: prove the ABI from one non-C language).
#
# Builds softmax(fc(data)) symbolically, loads known weights, runs a
# forward pass, and checks the probabilities against a pure-Perl
# reference computation.
use strict;
use warnings;
use FindBin;
use lib "$FindBin::Bin/blib";
use MXNetTPU;

printf "mxnet_tpu version %d (via Perl XS)\n", MXNetTPU::version();
my $nops = MXNetTPU::op_count();
die "too few ops: $nops" unless $nops > 200;
print "ops visible through ABI: $nops\n";

# --- net: SoftmaxOutput(FullyConnected(data, num_hidden=3)) ---
my ( $batch, $feat, $classes ) = ( 2, 4, 3 );
my $data  = MXNetTPU::sym_variable("data");
my $label = MXNetTPU::sym_variable("softmax_label");
my $fc    = MXNetTPU::sym_create( "FullyConnected", "num_hidden", "3",
    "fc", "$data" );
my $net = MXNetTPU::sym_create( "SoftmaxOutput", "", "", "softmax",
    "$fc,$label" );
my $args = MXNetTPU::sym_arguments($net);
die "unexpected args: $args"
  unless $args eq "data,fc_weight,fc_bias,softmax_label";

# --- arrays with known contents ---
my @x = map { 0.1 * $_ } 1 .. $batch * $feat;
my @w = map { 0.05 * ( $_ % 7 - 3 ) } 1 .. $classes * $feat;
my @b = ( 0.1, -0.2, 0.3 );
my @l = (0) x $batch;

my $a_x = MXNetTPU::nd_create("$batch,$feat");
my $a_w = MXNetTPU::nd_create("$classes,$feat");
my $a_b = MXNetTPU::nd_create("$classes");
my $a_l = MXNetTPU::nd_create("$batch");
MXNetTPU::nd_set( $a_x, pack( "f*", @x ) );
MXNetTPU::nd_set( $a_w, pack( "f*", @w ) );
MXNetTPU::nd_set( $a_b, pack( "f*", @b ) );
MXNetTPU::nd_set( $a_l, pack( "f*", @l ) );

my $exec = MXNetTPU::exec_bind( $net, "$a_x,$a_w,$a_b,$a_l" );
MXNetTPU::exec_forward($exec);
my @probs = unpack( "f*",
    MXNetTPU::nd_get( MXNetTPU::exec_out0($exec), $batch * $classes ) );

# --- pure-Perl reference: softmax(x @ w' + b) ---
for my $i ( 0 .. $batch - 1 ) {
    my @logits;
    for my $c ( 0 .. $classes - 1 ) {
        my $s = $b[$c];
        $s += $x[ $i * $feat + $_ ] * $w[ $c * $feat + $_ ]
          for 0 .. $feat - 1;
        push @logits, $s;
    }
    my $max = ( sort { $b <=> $a } @logits )[0];
    my @e   = map { exp( $_ - $max ) } @logits;
    my $z   = 0;
    $z += $_ for @e;
    for my $c ( 0 .. $classes - 1 ) {
        my $ref = $e[$c] / $z;
        my $got = $probs[ $i * $classes + $c ];
        # tolerance covers TPU execution (bf16 MXU matmuls): the axon
        # sitecustomize pins the platform, so this may run on-chip
        die sprintf( "mismatch row %d class %d: %g vs %g",
            $i, $c, $got, $ref )
          if abs( $got - $ref ) > 2e-3;
    }
}
print "softmax probabilities match pure-Perl reference\n";
print "PERL PASS\n";
