#!/usr/bin/env python
"""Benchmark: ResNet-50 training throughput and MFU on one chip.

Mirrors the reference's headline number (BASELINE.md: ResNet-50 train,
batch 32 — 45.52 img/s K80 / 90.74 M40 / 181.53 P100, from
docs/how_to/perf.md:159-190; script behavior ref:
example/image-classification/benchmark_score.py + train_imagenet.py).

vs_baseline is measured against the strongest single-GPU reference number
(P100, 181.53 img/s). Prints ONE JSON line.

Measurement notes (docs/perf.md has the full story):
- On the tunneled single-chip host, ``block_until_ready`` does not reliably
  block, so timing forces a tiny host readback of a scalar.
- Fixed per-readback tunnel latency is removed by differencing a 20-step and
  a 120-step run; the best of BENCH_ROUNDS rounds is reported.
- FLOPs come from XLA's own cost analysis of the compiled train step
  (~24.0 GFLOP/image for ResNet-50 fwd+bwd, i.e. 3x the 8.2 GFLOP forward),
  so MFU = achieved FLOP/s over the chip's peak bf16 FLOP/s.

Env knobs: BENCH_BATCH (default 128; 32 is the reference-parity config),
BENCH_ROUNDS (default 3), BENCH_DTYPE (float32|bfloat16 compute, default
bfloat16), BENCH_DEPTH (default 50), BENCH_IMAGE (default 224),
BENCH_STEPS_PER_DISPATCH (default 1; >=2 enables the steady-state bulked
mode: K steps per lax.scan dispatch over a device-resident superbatch with
metrics read back once per K — docs/perf.md "Dispatch bulking").

BENCH_DP_DEVICES=N adds a data-parallel scaling row to the JSON line
(docs/perf.md "Data-parallel scaling"): the same train-step config is
measured twice through the fused K-step scan — single device, and sharded
over an N-way 'data' mesh at the SAME global batch (params replicated,
batch axis split, gradient psum inside the donated body) — and the line
gains ``dp: {n_devices, img_per_sec, img_per_sec_1chip,
scaling_efficiency, collective_count, collective_bytes,
predicted_efficiency}`` (the last three from the commscheck static
inventory + roofline — docs/static_analysis.md "Communication lints";
the headline line carries the same three fields for the measured
program, zero collectives / efficiency 1.0 single-device). Needs N
visible devices (on CPU:
``XLA_FLAGS=--xla_force_host_platform_device_count=N``).

BENCH_LM=1 switches to the flagship-LM training bench (docs/perf.md
"Flagship LM"): the transformer LM through the SAME fused K-step scan
harness as the headline number, reporting steady-state tokens/sec + MFU
(XLA cost-model FLOPs over the commscheck peak-FLOPs table; on CPU /
unknown devices the roofline's nominal fallback, labeled
peak_source=nominal-fallback), then one row per mesh spec in
BENCH_LM_MESHES (";"-separated — default "data=2;seq=2;data=2,seq=2":
data-parallel, ring-attention sequence-parallel, and the composed
dp x sp mesh) at the SAME global batch, each with measured scaling
efficiency plus the commscheck collective inventory and predicted
efficiency. Knobs: BENCH_LM_BATCH (32), BENCH_LM_SEQ (128),
BENCH_LM_VOCAB (1024), BENCH_LM_EMBED (256), BENCH_LM_LAYERS (4),
BENCH_LM_HEADS (8), BENCH_LM_DTYPE (bfloat16), BENCH_LM_MESHES,
BENCH_STEPS_PER_DISPATCH (default 4 in this mode; env > tuning DB >
default), BENCH_ROUNDS. Multi-axis rows need the devices visible (on
CPU: XLA_FLAGS=--xla_force_host_platform_device_count=N).

BENCH_SERVE=1 switches to the serving latency bench (docs/serving.md):
drive the dynamic batcher over the AOT shape-bucketed engine at a target
QPS with open-loop arrivals and report request latency p50/p99 plus
achieved throughput as one JSON line (the BENCH_serve_rNN.json number).
Knobs: BENCH_SERVE_MODEL (mlp|lenet, default mlp), BENCH_SERVE_QPS
(default 200), BENCH_SERVE_REQS (default 400), BENCH_SERVE_CLIENTS
(default 4), plus the MXTPU_SERVE_* batcher knobs (docs/env_var.md).

BENCH_DECODE=1 switches to the production-decode-path bench
(docs/serving.md "Production decode path"): per-leg A/B tokens/sec for
in-graph sampling, int8 weights (HBM reduction + quality gate), the
prefix cache and speculative decoding, each against the same greedy-f32
DecodeLoop baseline — the BENCH_decode_rNN.json number. Knobs:
BENCH_DECODE_REQS (8), BENCH_DECODE_NEW (24), BENCH_DECODE_SLOTS (4),
BENCH_DECODE_VOCAB (64), BENCH_DECODE_EMBED (32), BENCH_DECODE_LAYERS
(2), BENCH_DECODE_HEADS (2), BENCH_DECODE_LEN (64), BENCH_DECODE_SPEC_K
(2). Honest expectations on CPU: prefix reuse wins outright; speculation
is dispatch-bound (the draft chain adds K+1 host round-trips per round)
and ships default-off; int8 trades dequant compute for the recorded ~4x
weight-HBM win.

BENCH_FLEET=1 switches to the fleet latency bench (docs/serving.md "Fleet
tier"): N replicas (each its own AOT engine + Batcher) behind a
FleetRouter, open-loop arrivals at a QPS one replica cannot hold, a mixed
interactive/batch class workload, and a MID-RUN drain + rejoin of one
replica — reporting per-class p50/p99, achieved rps for the fleet AND for
a single replica measured by the same harness (their ratio is the
scaling number the BENCH_fleet_rNN.json gate pins), per-replica
utilization, and requeued/shed/failed counts (drain+death must shed
nothing). On hosts without a real accelerator the per-dispatch device
time is EMULATED by a labeled GIL-free sleep (BENCH_FLEET_DEVICE_MS,
default 40 — the emulation is printed in the JSON as emulated_device_ms;
set 0 on real hardware): one CPU core cannot demonstrate replica
parallelism, but the router/queue/drain path under test is fully real.
Knobs: BENCH_FLEET_REPLICAS (2), BENCH_FLEET_QPS (500),
BENCH_FLEET_REQS (600), BENCH_FLEET_SINGLE_REQS (200),
BENCH_FLEET_MAX_BATCH (8 — with the emulated device time this pins one
replica's capacity at max_batch/cycle, so both phases measure capacity),
BENCH_FLEET_MODEL (mlp|lenet), BENCH_FLEET_BATCH_FRAC (0.25),
BENCH_FLEET_DRAIN (1), BENCH_FLEET_DEADLINE_MS (20000), plus
MXTPU_FLEET_* / MXTPU_SERVE_*.

BENCH_ZOO_DISPATCH=1 switches to the zoo-dispatch mode (docs/perf.md
"Packed accumulators"): the models whose metric class used to silently
force k=1 — SSD's multi-head loc+cls under MultiBoxMetric and the
transformer LM under Perplexity — run Module.fit(steps_per_dispatch=K)
on the fused K-step scan at BENCH_ZD_DEVICES forced-host devices,
measured k=1 vs k=K through the SAME fit loop plus a 1-device run for a
dp-efficiency row; fails if any model falls back to k=1 or any
tracecheck/memcheck finding appears over the new program set (the
sharded programs are comms-audited at dispatch via MXTPU_COMMSCHECK=
error). Knobs: BENCH_ZD_MODELS (ssd,transformer), BENCH_ZD_DEVICES (8),
BENCH_ZD_BATCH (8*devices), BENCH_ZD_DISPATCHES (6), BENCH_ZD_IMAGE
(64), BENCH_ZD_SEQ (32), BENCH_STEPS_PER_DISPATCH (4). NOTE on reading
CPU numbers: XLA:CPU runs convolutions inside While/scan bodies ~3x
slower than outside (matmuls unaffected), so conv models can read <1x
on CPU hosts; the committed number's gate is engagement + parity +
zero findings, the speedup story is the TPU round-6 table.

BENCH_REAL_DATA=1 switches to the real-data input-tier gate (docs/perf.md
"Device-fed input pipeline"): generate a real-JPEG RecordIO set, run an
epoch of the SAME model/batch/K through the full
``mxnet_tpu.data`` tier — ImageRecordIter(num_workers=N) decode pool ->
DevicePrefetcher superbatch H2D -> fused K-step scan — and assert the
real-data img/s reaches ``MXTPU_REALDATA_MIN_RATIO`` (default 0.9) of the
synthetic device-resident number. One JSON line with both rates, the
ratio, per-stage PipelineStats, DataHealth and the tracecheck audit —
the BENCH_realdata_rNN.json number. Knobs: BENCH_RD_BATCH (128),
BENCH_RD_IMAGE (224), BENCH_RD_IMAGES (batch*k*8), BENCH_DEPTH (50),
BENCH_STEPS_PER_DISPATCH (4), MXTPU_DATA_WORKERS (min(4, cores)),
BENCH_RD_QUALITY (90), BENCH_RD_MODEL (resnet | lenet — the latter for
1-core CI hosts where resnet's XLA compile dominates),
BENCH_RD_MEASURE ("short,long" synthetic differencing steps).

BENCH_HOST_OVERHEAD=1 switches to the host-overhead mode (docs/perf.md
"Host off the critical path"): a full Module.fit loop with checkpointing
enabled, swept over BENCH_CKPT_CADENCES (default "8,16"), measuring
steady-state img/s and host_stall_frac — the fraction of wall time the
loop spent blocked on the host (packed-metric readbacks + checkpoint
serialization) — for the sync/eager baseline vs async checkpointing +
pipelined dispatch. Extra knobs: BENCH_HO_BATCHES (batches/epoch, default
32), BENCH_HO_IMAGE (default 112), BENCH_HO_BATCH (default 64),
BENCH_STEPS_PER_DISPATCH (default 4 in this mode),
MXTPU_DISPATCH_PIPELINE (depth for the pipelined config, default 1).
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np

# every BENCH_* knob is declared ONCE in the BenchConfig table
# (mxnet_tpu/autotune/benchcfg.py) and read through benv — integers and
# floats route through base.env_int/env_float, so a junk spelling raises
# MXNetError naming the variable instead of a raw ValueError / silent
# truncation. The autotuner's programmatic path reads the same table.
from mxnet_tpu.autotune.benchcfg import benv, env_set
# ONE measurement harness shared with the autotuner and the multichip CI
# gate (docs/perf.md "Autotuning"): bench re-exports it so existing
# `from bench import measure_scan_ips` callers keep working
from mxnet_tpu.autotune.harness import (measure_scan_ips,  # noqa: F401
                                        open_loop_run, serve_model)
from mxnet_tpu.base import env_float, env_int


def _peak_flops(device):
    """Peak dense bf16 FLOP/s by TPU generation — ONE table, owned by
    mxnet_tpu.devspec (commscheck's roofline, flopcheck's and this
    bench's MFU must agree on the same device). Unknown kinds return
    None here (MFU is omitted rather than guessed) instead of devspec's
    nominal CPU fallback."""
    from mxnet_tpu import devspec
    spec, source = devspec.lookup(device)
    kind = devspec.device_kind(device)
    if source == "spec":
        return spec.peak_flops_per_s, kind
    return None, kind


def _obs_block():
    """The unified-observability block every bench mode's JSON line
    carries (docs/observability.md): one metrics-registry snapshot — the
    five legacy health/stats objects ride it as views — plus host-tracer
    status and per-name span counts when MXTPU_TRACE=1."""
    from mxnet_tpu import obs
    snap = obs.REGISTRY.snapshot()
    block = {"trace_enabled": obs.enabled(),
             "counters": {k: v for k, v in sorted(snap.items())
                          if not k.endswith("last_error")}}
    if obs.enabled():
        by = {}
        for ev in obs.events():
            if ev.get("ph") in ("X", "i"):
                by[ev["name"]] = by.get(ev["name"], 0) + 1
        block["span_counts"] = by
        block["trace_path"] = obs.trace.trace_path()
    return block


def host_overhead_main():
    """Host-overhead mode: measure what checkpointing + metric readback
    COST the train loop, and how much of it the async writer + dispatch
    pipeline hide. One JSON line:

        {"metric": "...host_overhead...", "value": <best async img/s>,
         "host_stall_frac": <that same best-async config's frac>,
         "sweep": [{"cadence": N, "sync": {...}, "async": {...}}, ...]}

    Each config trains epoch 1 as compile/warmup and measures epoch 2's
    wall clock; host_stall_frac = (packed-readback stall + checkpoint
    save time on the loop thread) / epoch wall."""
    import tempfile
    import numpy as np
    import mxnet_tpu as mx
    from mxnet_tpu import models
    from mxnet_tpu.model import CheckpointManager

    batch = benv("BENCH_HO_BATCH")
    image = benv("BENCH_HO_IMAGE")
    depth = benv("BENCH_DEPTH")
    k = benv("BENCH_STEPS_PER_DISPATCH", 4)
    nbatches = benv("BENCH_HO_BATCHES")
    cadences = [int(c) for c in benv("BENCH_CKPT_CADENCES").split(",")
                if c.strip()]
    from mxnet_tpu import engine
    pl_depth = engine.dispatch_pipeline()

    sym = models.resnet(num_classes=1000, num_layers=depth,
                        image_shape="3,%d,%d" % (image, image))
    rng = np.random.default_rng(0)
    X = rng.normal(size=(nbatches * batch, 3, image, image)) \
        .astype(np.float32)
    y = rng.integers(0, 1000, nbatches * batch).astype(np.float32)

    def run(cadence, pipelined, async_ckpt, tmpdir, tag):
        mx.random.seed(0)
        it = mx.io.NDArrayIter(X, y, batch_size=batch)
        mod = mx.mod.Module(sym, context=mx.cpu()
                            if jax_platform() == "cpu" else None)
        mgr = CheckpointManager(os.path.join(tmpdir, tag, "ck"), keep=2)
        caps = {}

        def cb(p):
            caps["pipeline"] = p.locals.get("pipeline")

        marks = {}

        def epoch_cb(epoch, *_a):
            p = caps.get("pipeline")
            marks[epoch] = (time.perf_counter(),
                            getattr(p, "host_stall", 0.0), mgr.save_time)

        mod.fit(it, num_epoch=2, steps_per_dispatch=k,
                optimizer_params={"learning_rate": 0.1, "momentum": 0.9},
                checkpoint_prefix=mgr, checkpoint_every_n_batches=cadence,
                checkpoint_async=async_ckpt,
                dispatch_pipeline=pl_depth if pipelined else 0,
                batch_end_callback=cb, epoch_end_callback=epoch_cb)
        (t0, s0, c0), (t1, s1, c1) = marks[0], marks[1]
        wall = t1 - t0
        stall = (s1 - s0) + (c1 - c0)
        writer = mgr.async_writer or mgr.last_async_writer
        return {"images_per_sec": round(nbatches * batch / wall, 2),
                "host_stall_frac": round(max(0.0, stall) / wall, 4),
                "ckpt_skipped": writer.skipped if writer else 0}

    def jax_platform():
        import jax
        return jax.devices()[0].platform

    sweep = []
    best_async = None
    with tempfile.TemporaryDirectory() as tmpdir:
        for cadence in cadences:
            sync = run(cadence, False, False, tmpdir, "sync-%d" % cadence)
            asyn = run(cadence, True, True, tmpdir, "async-%d" % cadence)
            sweep.append({"cadence": cadence, "sync": sync, "async": asyn})
            if best_async is None or (asyn["images_per_sec"]
                                      > best_async["images_per_sec"]):
                best_async = asyn

    from mxnet_tpu import tracecheck
    out = {
        "metric": "resnet%d_host_overhead_b%d_k%d" % (depth, batch, k),
        "value": best_async["images_per_sec"],
        "unit": "images/sec",
        "steps_per_dispatch": k,
        "pipeline_depth": pl_depth,
        "host_stall_frac": best_async["host_stall_frac"],
        # unexpected jit-cache misses over the whole sweep: a nonzero count
        # means a config retraced a seen program (docs/static_analysis.md)
        "retraces": tracecheck.retrace_count(),
        "sweep": sweep,
    }
    out["obs"] = _obs_block()
    print(json.dumps(out))


def _zd_model(name, batch):
    """(symbol, data dict, label dict, data/label names, metric) for the
    zoo-dispatch bench — the models whose dispatch class used to force
    k=1: SSD's multi-head loc+cls and the transformer LM under
    Perplexity."""
    import mxnet_tpu as mx
    from mxnet_tpu import models
    rng = np.random.default_rng(0)
    if name == "ssd":
        image = benv("BENCH_ZD_IMAGE")
        sym = models.get_symbol("ssd", num_classes=3, width=16)
        X = rng.normal(size=(batch, 3, image, image)).astype(np.float32)
        lab = rng.random((batch, 4, 5)).astype(np.float32)
        lab[..., 0] = rng.integers(0, 3, (batch, 4))
        x1 = np.minimum(lab[..., 1], lab[..., 3])
        y1 = np.minimum(lab[..., 2], lab[..., 4])
        lab[..., 3] = np.maximum(lab[..., 1], lab[..., 3]) + 0.05
        lab[..., 4] = np.maximum(lab[..., 2], lab[..., 4]) + 0.05
        lab[..., 1], lab[..., 2] = x1, y1
        return (sym, {"data": X}, {"label": lab}, ("data",), ("label",),
                mx.metric.MultiBoxMetric())
    if name == "transformer":
        seq = benv("BENCH_ZD_SEQ")
        sym = models.get_symbol("transformer", vocab_size=64, embed=32,
                                num_heads=4, num_layers=2, seq_len=seq)
        X = rng.integers(0, 64, (batch, seq)).astype(np.float32)
        y = rng.integers(0, 64, (batch, seq)).astype(np.float32)
        return (sym, {"data": X}, {"softmax_label": y}, ("data",),
                ("softmax_label",), mx.metric.Perplexity(ignore_label=None))
    raise SystemExit("BENCH_ZD_MODELS entries must be ssd|transformer, "
                     "got %r" % name)


def zoo_dispatch_main():
    """BENCH_ZOO_DISPATCH=1 (docs/perf.md "Packed accumulators"): the
    scenario-diversity proof — the models whose metric class used to
    silently force steps_per_dispatch=1 (SSD multi-head, transformer-LM
    perplexity) run Module.fit on the fused K-step scan at
    BENCH_ZD_DEVICES forced-host devices, measured k=1 vs k=K through
    the SAME fit loop (epoch 1 compiles, epoch 2 is timed), plus the
    k=K run at 1 device for a dp scaling-efficiency row. One JSON line;
    fails if any model falls back to k=1 or any static finding appears
    across the new program set (the dispatch-time commscheck hook is
    armed in error mode for the sharded programs)."""
    import jax
    import mxnet_tpu as mx
    from mxnet_tpu import tracecheck, memcheck

    ndev = benv("BENCH_ZD_DEVICES")
    k = benv("BENCH_STEPS_PER_DISPATCH", 4)
    batch = benv("BENCH_ZD_BATCH") or 8 * max(1, ndev)
    dispatches = benv("BENCH_ZD_DISPATCHES")
    model_names = [m for m in benv("BENCH_ZD_MODELS").split(",")
                   if m.strip()]
    if len(jax.devices()) < ndev:
        raise SystemExit(
            "BENCH_ZD_DEVICES=%d but only %d device(s) visible — on CPU "
            "raise with XLA_FLAGS=--xla_force_host_platform_device_count"
            "=%d" % (ndev, len(jax.devices()), ndev))
    # the sharded scans get comms-audited at first dispatch; min_eff=0
    # because this gate checks the collective INVENTORY lints, not the
    # training-scale-out roofline (mirroring the serving-tier audits)
    os.environ.setdefault("MXTPU_COMMSCHECK", "error")
    os.environ.setdefault("MXTPU_COMMSCHECK_MIN_EFF", "0")

    def run_fit(name, spd, contexts, tag):
        sym, data, label, dnames, lnames, metric = _zd_model(name, batch)
        n = batch * spd * dispatches
        reps = (n + batch - 1) // batch
        Xr = {kk: np.concatenate([v] * reps)[:n] for kk, v in data.items()}
        yr = {kk: np.concatenate([v] * reps)[:n] for kk, v in label.items()}
        it = mx.io.NDArrayIter(Xr, yr, batch_size=batch)
        mod = mx.mod.Module(sym, data_names=dnames, label_names=lnames,
                            context=contexts)
        mx.random.seed(0)
        marks = {}

        def epoch_cb(epoch, *_a):
            marks[epoch] = time.perf_counter()

        mod.fit(it, num_epoch=2, steps_per_dispatch=spd,
                initializer=mx.initializer.Xavier(),
                eval_metric=metric,
                optimizer_params={"learning_rate": 0.01, "momentum": 0.9},
                epoch_end_callback=epoch_cb)
        wall = marks[1] - marks[0]
        scan_engaged = (mod._fused is not None
                        and any(key[1] == spd
                                for key in mod._fused._jit_scan))
        prefix = (mod._fused._watcher.name + "/"
                  if mod._fused is not None and mod._fused._watcher
                  else None)
        return n / wall, scan_engaged, prefix, metric

    ctx_n = [mx.Context("cpu" if jax.devices()[0].platform == "cpu"
                        else "tpu", i) for i in range(ndev)]
    ctx_1 = ctx_n[0]
    rows = {}
    prefixes = []
    failed = []
    for name in model_names:
        ips_k1, _, _, _ = run_fit(name, 1, ctx_n, "k1")
        ips_kk, engaged, prefix, metric = run_fit(name, k, ctx_n, "kk")
        ips_1dev, _, _, _ = run_fit(name, k, ctx_1, "kk1dev")
        if prefix:
            prefixes.append(prefix)
        if not engaged:
            failed.append(name)
        rows[name] = {
            "k": k,
            "img_per_sec_k1": round(ips_k1, 2),
            "img_per_sec_k%d" % k: round(ips_kk, 2),
            "dispatch_speedup": round(ips_kk / max(ips_k1, 1e-9), 3),
            "dp_devices": ndev,
            "img_per_sec_1dev": round(ips_1dev, 2),
            "dp_efficiency": round(ips_kk / max(ips_1dev, 1e-9), 3),
            "scan_engaged": engaged,
            "metric": type(metric).__name__,
        }
    # the new program set must be lint-clean as a unit: tracecheck full
    # lints + memcheck (incl. resident-set) over every program the fits
    # registered; commscheck already gated each sharded dispatch (error
    # mode raises inside fit)
    findings = []
    for p in prefixes:
        findings += tracecheck.unsuppressed(
            tracecheck.check_registered(match=p))
    mem_findings, _reports = memcheck.check_registered(
        match=tuple(prefixes), resident_name="zoo-dispatch")
    findings += [f for f in mem_findings if not f.suppressed]
    out = {
        "metric": "zoo_dispatch_b%d_k%d_dp%d" % (batch, k, ndev),
        "value": round(min(r["dispatch_speedup"] for r in rows.values()),
                       3),
        "unit": "min_dispatch_speedup_x",
        "models": rows,
        "findings": len(findings),
        "retraces": tracecheck.retrace_count(),
    }
    out["obs"] = _obs_block()
    print(json.dumps(out))
    if failed:
        raise SystemExit("BENCH_ZOO_DISPATCH gate: %s fell back to k=1 — "
                         "the packed-accumulator path did not engage"
                         % ", ".join(failed))
    if findings:
        for f in findings:
            print(f.format(), file=sys.stderr)
        raise SystemExit("BENCH_ZOO_DISPATCH gate: %d static finding(s) "
                         "across the new program set" % len(findings))


def _make_realdata_rec(path, n, size, quality, classes=8, seed=11):
    """Pack n real JPEGs (distinct per-class color/stripe textures, real
    libjpeg bytes) into an indexed .rec — the decode cost is the honest
    ImageNet-shaped cost, only the pixels are synthetic."""
    import io as _bio
    from PIL import Image
    from mxnet_tpu import recordio

    rng = np.random.default_rng(seed)
    ang = rng.uniform(0, np.pi, classes)
    freq = rng.uniform(3, 9, classes)
    base = rng.uniform(0.25, 0.75, (classes, 3))
    xs = np.linspace(0, 1, size)
    gx, gy = np.meshgrid(xs, xs, indexing="ij")
    idx_path = os.path.splitext(path)[0] + ".idx"
    rec = recordio.MXIndexedRecordIO(idx_path, path, "w")
    for i in range(n):
        c = i % classes
        wave = np.sin(2 * np.pi * freq[c]
                      * (gx * np.cos(ang[c]) + gy * np.sin(ang[c]))
                      + rng.uniform(0, 2 * np.pi))
        img = (base[c][:, None, None] + 0.22 * wave[None]
               + rng.normal(0, 0.05, (3, size, size)))
        arr = (np.clip(img, 0, 1) * 255).astype(np.uint8).transpose(1, 2, 0)
        buf = _bio.BytesIO()
        Image.fromarray(arr).save(buf, format="JPEG", quality=quality)
        rec.write_idx(i, recordio.pack(
            recordio.IRHeader(0, float(c), i, 0), buf.getvalue()))
    rec.close()
    return path


def realdata_main():
    """Real-data input-tier gate (docs/perf.md "Device-fed input
    pipeline"): the same fused K-step scan measured twice — superbatch
    device-resident (the synthetic headline methodology), and fed by the
    FULL data tier from real JPEG bytes (sharded reader -> decode worker
    pool -> superbatch stack -> prefetch-to-device). Asserts
    real/synthetic >= MXTPU_REALDATA_MIN_RATIO and prints one JSON line
    with per-stage PipelineStats — the number that says the input side no
    longer hides behind the synthetic bench."""
    import tempfile
    import jax
    import jax.numpy as jnp
    from mxnet_tpu import models, engine, tracecheck
    from mxnet_tpu import data as mdata
    from mxnet_tpu.train_step import TrainStep

    batch = benv("BENCH_RD_BATCH")
    image = benv("BENCH_RD_IMAGE")
    depth = benv("BENCH_DEPTH")
    k = max(2, benv("BENCH_STEPS_PER_DISPATCH", 4))
    nimg = benv("BENCH_RD_IMAGES") or batch * k * 8
    # whole superbatches only: one compiled program, no epoch tail
    nimg = max(batch * k, nimg - nimg % (batch * k))
    quality = benv("BENCH_RD_QUALITY")
    workers = env_int("MXTPU_DATA_WORKERS", 0) \
        or min(4, os.cpu_count() or 1)
    min_ratio = env_float("MXTPU_REALDATA_MIN_RATIO", 0.9)
    rounds = benv("BENCH_ROUNDS", 2)
    cdtype = benv("BENCH_DTYPE")
    if jax.devices()[0].platform == "cpu":
        cdtype = "float32"  # bf16 matmuls emulate slowly on CPU

    model = benv("BENCH_RD_MODEL")
    if model == "resnet":
        sym = models.resnet(num_classes=8, num_layers=depth,
                            image_shape="3,%d,%d" % (image, image))
        mname = "resnet%d" % depth
    elif model == "lenet":
        # the multichip gate's conv workload: seconds to compile on a
        # 1-core CI host where resnet's XLA compile alone runs minutes —
        # same pipeline, same gate semantics
        sym = models.lenet(num_classes=8)
        mname = "lenet"
    else:
        raise SystemExit("BENCH_RD_MODEL must be resnet|lenet, got %r"
                         % model)

    def make_step():
        return TrainStep(
            sym, optimizer="sgd", learning_rate=0.1, momentum=0.9, wd=1e-4,
            compute_dtype=None if cdtype == "float32" else cdtype)

    dshape = (batch, 3, image, image)
    # -- synthetic side: device-resident superbatch, the headline
    # methodology (short/long differencing, best of rounds)
    step = make_step()
    state = step.init({"data": dshape}, {"softmax_label": (batch,)})
    rng = np.random.default_rng(0)
    sb = {"data": jnp.stack(
              [jnp.asarray(rng.normal(size=dshape), np.float32)] * k),
          "softmax_label": jnp.stack(
              [jnp.asarray(rng.integers(0, 8, batch), np.float32)] * k)}
    # BENCH_RD_MEASURE="short,long" differencing steps for the synthetic
    # side (defaults sized for chip hosts; the CI smoke shrinks them — a
    # CPU dispatch takes seconds, so the fixed-latency term the
    # differencing cancels is proportionally tiny there)
    meas = benv("BENCH_RD_MEASURE").split(",")
    n_short = max(1, (int(meas[0]) + k - 1) // k)
    n_long = max(n_short + 2, (int(meas[1]) + k - 1) // k)
    synth_ips = measure_scan_ips(step, state, sb, batch, k, n_short,
                                 n_long, rounds=rounds)
    if synth_ips <= 0:
        raise RuntimeError("realdata bench: synthetic measurement failed")

    # -- real side: JPEG -> reader -> decode pool -> prefetch-to-device ->
    # the SAME compiled scan, timed over whole epochs
    import mxnet_tpu as mx
    with tempfile.TemporaryDirectory(prefix="bench_rd_") as tmp:
        gen0 = time.perf_counter()
        rec = _make_realdata_rec(os.path.join(tmp, "train.rec"), nimg,
                                 int(image * 1.15), quality)
        gen_s = time.perf_counter() - gen0
        it = mx.image.ImageRecordIter(
            path_imgrec=rec, data_shape=(3, image, image),
            batch_size=batch, shuffle=True, seed=1, rand_crop=True,
            rand_mirror=True, resize=int(image * 1.1),
            mean_r=123.68, mean_g=116.28, mean_b=103.53,
            std_r=58.4, std_g=57.1, std_b=57.4, num_workers=workers)
        pf = mdata.DevicePrefetcher(it, k, depth=engine.dispatch_pipeline(),
                                    last_group_handle="discard")
        step2 = make_step()
        state2 = step2.init({"data": dshape}, {"softmax_label": (batch,)})

        def epoch(st):
            seen = 0
            for sb in pf:
                feed = {"data": sb.data[0].data,
                        "softmax_label": sb.label[0].data}
                st, _m = step2.run_steps(st, feed)
                seen += batch * sb.num_steps
            np.asarray(st["step"])  # forced readback: epoch fully retired
            pf.reset()
            return st, seen

        state2, _ = epoch(state2)        # warmup: compile + file cache
        it.data_stats.reset()
        best_real = 0.0
        for _ in range(rounds):
            t0 = time.perf_counter()
            state2, seen = epoch(state2)
            best_real = max(best_real, seen / (time.perf_counter() - t0))
        pf.close()
        it.close()
        health = it.data_health.report()
        pipeline_rep = it.data_stats.report()

    ratio = best_real / synth_ips
    findings = tracecheck.unsuppressed(tracecheck.check_registered())
    out = {
        "metric": "%s_realdata_images_per_sec_b%d_%s_k%d"
                  % (mname, batch, cdtype, k),
        "value": round(best_real, 2),
        "unit": "images/sec",
        "synthetic_img_per_sec": round(synth_ips, 2),
        "ratio": round(ratio, 3),
        "min_ratio": min_ratio,
        "images": nimg,
        "image_px": image,
        "workers": workers,
        "steps_per_dispatch": k,
        "jpeg_gen_seconds": round(gen_s, 1),
        "pipeline": pipeline_rep,
        "data_health": health,
        "tracecheck_findings": len(findings),
        "retraces": tracecheck.retrace_count(),
    }
    out["obs"] = _obs_block()
    print(json.dumps(out))
    if ratio < min_ratio:
        raise SystemExit(
            "BENCH_REAL_DATA gate: real-data %.2f img/s is %.3f of the "
            "synthetic %.2f img/s — below MXTPU_REALDATA_MIN_RATIO=%.2f "
            "(the input tier is not feeding the chip; see 'pipeline' "
            "stage seconds in the JSON line above)"
            % (best_real, ratio, synth_ips, min_ratio))


def _serve_model(name=None):
    """Build (engine kwargs) for the serving/fleet benches — ONE recipe
    shared with the autotuner's serving harness
    (``autotune.harness.serve_model``). ``name`` defaults to the
    BENCH_SERVE_MODEL env knob."""
    from mxnet_tpu.base import MXNetError
    if name is None:
        name = benv("BENCH_SERVE_MODEL")
    try:
        return serve_model(name)
    except MXNetError as e:
        raise SystemExit("bench serve/fleet: %s" % (e,))


def serve_main():
    """Serving latency bench: open-loop arrivals at a target QPS through
    the dynamic batcher; one JSON line with p50/p99 latency and achieved
    throughput (docs/serving.md "Latency bench")."""
    from mxnet_tpu import serving, tracecheck

    qps = benv("BENCH_SERVE_QPS")
    nreq = benv("BENCH_SERVE_REQS")
    nclients = benv("BENCH_SERVE_CLIENTS")
    name, sym, params, shape = _serve_model()

    eng = serving.ServingEngine(sym, params, {"data": shape})
    batcher = serving.Batcher(eng)
    rs = np.random.default_rng(1)
    x1 = rs.normal(size=(1,) + shape).astype(np.float32)
    batcher.infer({"data": x1})           # warm the smallest bucket path

    # open-loop arrivals through the shared client harness (also drives
    # the autotuner's serving trials): request i is DUE at t0+i/qps, so
    # queueing delay lands in the measured latency, never in offered load
    latencies, errors, wall = open_loop_run(
        batcher.infer, {"data": x1}, qps, nreq, nclients=nclients)
    batcher.close()
    if not latencies:
        raise RuntimeError("serving bench completed no requests: %s"
                           % errors[:3])
    lat_ms = np.asarray(latencies) * 1e3
    findings = tracecheck.unsuppressed(
        tracecheck.check_registered(match=eng.name + "/"))
    # static memory profile of the bucket set (already compiled — free):
    # per-bucket peak plus the co-resident footprint the AOT cache retains
    mem_fields = {}
    try:
        from mxnet_tpu import memcheck
        reports = eng.memory_report()
        if reports:
            mem_fields = {
                "hbm_peak_bytes": max(r.peak_bytes
                                      for r in reports.values()),
                "temp_bytes": max(r.temp_bytes for r in reports.values()),
                "hbm_resident_bytes": memcheck.resident_bytes(
                    reports.values()),
            }
    except Exception as exc:
        print("WARNING: memcheck analysis failed, no HBM fields emitted: %r"
              % exc, file=sys.stderr)
    out = {
        "metric": "serve_%s_latency_qps%g" % (name, qps),
        "value": round(float(np.percentile(lat_ms, 99)), 3),
        "unit": "ms_p99",
        "p50_ms": round(float(np.percentile(lat_ms, 50)), 3),
        "p99_ms": round(float(np.percentile(lat_ms, 99)), 3),
        "mean_ms": round(float(lat_ms.mean()), 3),
        "throughput_rps": round(len(latencies) / wall, 2),
        "qps_target": qps,
        "completed": len(latencies),
        "failed": len(errors),
        "buckets": list(eng.buckets),
        "batches": eng.health.batches,
        "avg_batch": round(eng.health.examples
                           / max(1, eng.health.batches), 2),
        "padded_frac": round(eng.health.padded
                             / max(1, eng.health.examples
                                   + eng.health.padded), 4),
        # the serving program set must stay lint-clean while under load
        "tracecheck_findings": len(findings),
        "retraces": tracecheck.retrace_count(),
    }
    out.update(mem_fields)
    out["obs"] = _obs_block()
    print(json.dumps(out))


def _decode_lm_params(cfg, num_layers, seed):
    """Random f32 transformer-LM params for the decode bench (weights
    don't affect throughput; the int8 leg re-derives its own from these)."""
    from mxnet_tpu import models
    sym = models.transformer(vocab_size=cfg["vocab"], embed=cfg["embed"],
                             num_heads=cfg["heads"],
                             num_layers=num_layers, seq_len=cfg["len"])
    arg_shapes, _, _ = sym.infer_shape(data=(1, cfg["len"]),
                                       softmax_label=(1, cfg["len"]))
    rs = np.random.RandomState(seed)
    params = {n: (rs.randn(*s) * 0.3).astype(np.float32)
              for n, s in zip(sym.list_arguments(), arg_shapes)
              if n not in ("data", "softmax_label")}
    return sym, params


def decode_main():
    """Production-decode-path bench (docs/serving.md "Production decode
    path"): per-leg A/B tokens/sec for the four decode features —
    in-graph sampling, int8 weights (with the HBM win and the quality
    gate), prefix-cache reuse, speculative decoding (with the
    token-identity cross-check) — each against the same greedy-f32
    baseline loop. One JSON line (the BENCH_decode_rNN.json number)."""
    from mxnet_tpu import serving, tracecheck
    from mxnet_tpu.serving.quantize import check_quality

    nreq = benv("BENCH_DECODE_REQS")
    max_new = benv("BENCH_DECODE_NEW")
    slots = benv("BENCH_DECODE_SLOTS")
    spec_k = benv("BENCH_DECODE_SPEC_K")
    cfg = {"vocab": benv("BENCH_DECODE_VOCAB"),
           "embed": benv("BENCH_DECODE_EMBED"),
           "layers": benv("BENCH_DECODE_LAYERS"),
           "heads": benv("BENCH_DECODE_HEADS"),
           "len": benv("BENCH_DECODE_LEN")}
    sym, params = _decode_lm_params(cfg, cfg["layers"], seed=0)
    _dsym, draft = _decode_lm_params(cfg, 1, seed=1)

    rs = np.random.RandomState(2)
    shared = [int(t) for t in rs.randint(1, cfg["vocab"], 8)]
    tails = [[int(t) for t in rs.randint(1, cfg["vocab"], 2 + i % 3)]
             for i in range(nreq)]
    prompts = [shared + t for t in tails]
    seeds = [101 + i for i in range(nreq)]

    def run(loop, temp, plen=0):
        """One warmed A/B measurement: tokens/sec over the fixed request
        batch (and the emitted streams, for the identity cross-checks)."""
        def once():
            futs = [loop.generate(p, max_new, temperature=temp,
                                  seed=s, prefix_len=plen)
                    for p, s in zip(prompts, seeds)]
            return [f.result(timeout=300.0) for f in futs]
        once()                                    # warm (primes prefixes)
        t0 = time.perf_counter()
        outs = once()
        dt = time.perf_counter() - t0
        return sum(len(o) for o in outs) / dt, outs

    mk = lambda **kw: serving.DecodeLoop(
        params, num_layers=cfg["layers"], num_heads=cfg["heads"],
        max_len=cfg["len"], slots=slots, **kw)
    legs, findings = {}, 0

    base = mk(quantize="none", prefix_cache=False)
    base_tps, _ = run(base, temp=0.0)
    sampled_tps, sampled_outs = run(base, temp=0.8)
    findings += len(base.check(memory=True))
    base.close()
    legs["greedy_f32"] = {"tokens_per_sec": round(base_tps, 1)}
    legs["sampled"] = {"tokens_per_sec": round(sampled_tps, 1)}

    q = mk(quantize="int8", prefix_cache=False)
    int8_tps, _ = run(q, temp=0.8)
    findings += len(q.check(memory=True))
    int8_bytes = q.weight_bytes()
    q.close()
    # the quality gate runs through the engine pair — the documented
    # quant workflow (docs/serving.md "Quantized weights")
    ref_eng = serving.ServingEngine(sym, params, {"data": (cfg["len"],)},
                                    buckets=(4,))
    q_eng = serving.ServingEngine(sym, params, {"data": (cfg["len"],)},
                                  buckets=(4,), quantize="int8")
    probe = np.zeros((4, cfg["len"]), np.float32)
    probe[:, :8] = np.asarray([shared] * 4, np.float32)
    quality = q_eng.quality_report(ref_eng, {"data": probe})
    check_quality(quality, who="bench-decode int8")
    f32_bytes = ref_eng.weight_bytes()
    legs["int8"] = {
        "tokens_per_sec": round(int8_tps, 1),
        "weight_bytes_f32": f32_bytes,
        "weight_bytes_int8": int8_bytes,
        "weight_hbm_reduction": round(1.0 - int8_bytes / f32_bytes, 4),
        "top1_agreement": round(quality["top1_agreement"], 4),
    }

    pre = mk(quantize="none", prefix_cache=True)
    prefix_tps, _ = run(pre, temp=0.8, plen=len(shared))
    findings += len(pre.check(memory=True))
    legs["prefix"] = {"tokens_per_sec": round(prefix_tps, 1),
                      "prefix_hits": pre.health.prefix_hits,
                      "prefix_prefills": pre.health.prefix_prefills}
    pre.close()

    spec = mk(quantize="none", prefix_cache=False, spec_k=spec_k,
              draft_params=draft, draft_num_layers=1)
    spec_tps, spec_outs = run(spec, temp=0.8)
    findings += len(spec.check(memory=True))
    h = spec.health
    legs["spec_k%d" % spec_k] = {
        "tokens_per_sec": round(spec_tps, 1),
        "accept_rate": round(h.spec_accepted / max(1, h.spec_drafted), 4),
        # the correctness contract, measured, not assumed: speculative
        # output is token-identical to target-only under the same seeds
        "token_identical": spec_outs == sampled_outs,
    }
    spec.close()
    if spec_outs != sampled_outs:
        raise RuntimeError("speculative decode diverged from target-only "
                           "sampling under identical seeds")

    for leg in legs.values():
        leg["x_vs_greedy_f32"] = round(
            leg["tokens_per_sec"] / max(base_tps, 1e-9), 3)
    out = {
        "metric": "decode_path_l%d_e%d_v%d" % (cfg["layers"],
                                               cfg["embed"], cfg["vocab"]),
        "value": round(base_tps, 1),
        "unit": "tokens_per_sec_greedy_f32",
        "requests": nreq,
        "max_new": max_new,
        "slots": slots,
        "legs": legs,
        "tracecheck_findings": findings,
        "retraces": tracecheck.retrace_count(),
        "obs": _obs_block(),
    }
    print(json.dumps(out))


class _PacedEngine(object):
    """Bench-local engine proxy emulating device dispatch latency with a
    GIL-free sleep: on a host without a real accelerator, one core cannot
    demonstrate replica parallelism — the sleep stands in for the
    accelerator's execution time (overlapping across replicas exactly like
    real devices would) while the batcher/router/queue path under test
    stays fully real. The emulation is labeled in the bench JSON
    (``emulated_device_ms``); 0 disables it for real-hardware runs."""

    def __init__(self, engine, device_ms):
        self._engine = engine
        self._device_s = device_ms / 1e3

    def infer(self, inputs):
        if self._device_s > 0:
            time.sleep(self._device_s)
        return self._engine.infer(inputs)

    def __getattr__(self, name):
        return getattr(self._engine, name)


def _percentiles_ms(latencies):
    lat = np.asarray(latencies) * 1e3
    return {"p50_ms": round(float(np.percentile(lat, 50)), 3),
            "p99_ms": round(float(np.percentile(lat, 99)), 3),
            "mean_ms": round(float(lat.mean()), 3)}


def _fleet_open_loop(router, inputs, nreq, qps, classes, deadline_ms):
    """TRUE open-loop arrival harness for the fleet phases: one pacer
    thread issues NON-BLOCKING submissions (request i DUE at t0 + i/qps —
    queueing delay lands in measured latency, never caps the offered
    load the way a pool of blocking clients would), completions are
    timestamped by the router's settle callback. Returns (per-class
    latency lists, errors, wall seconds from first due to last
    completion)."""
    import threading
    lat = {c: [] for c in set(classes)}
    errors = []
    lock = threading.Lock()
    interval = 1.0 / qps
    done_ts = [0.0]

    def make_cb(cls, t_start):
        def cb(freq):
            now = time.perf_counter()
            with lock:
                if freq.error is None:
                    lat[cls].append(now - t_start)
                else:
                    errors.append(repr(freq.error))
                done_ts[0] = max(done_ts[0], now)
        return cb

    futs = []
    t0 = time.perf_counter() + 0.05
    for i in range(nreq):
        due = t0 + i * interval
        delay = due - time.perf_counter()
        if delay > 0:
            time.sleep(delay)
        # latency counts from the DUE time, not the actual submit
        # instant: a pacer running late must charge its lag to the
        # measured latency, not silently exclude it (coordinated
        # omission)
        try:
            futs.append(router.submit(inputs, priority=classes[i],
                                      deadline_ms=deadline_ms,
                                      on_done=make_cb(classes[i], due)))
        except Exception as e:
            with lock:
                errors.append(repr(e))
    for f in futs:
        f.event.wait(timeout=deadline_ms / 1e3 + 5.0)
    return lat, errors, max(done_ts[0], t0) - t0


def fleet_main():
    """Fleet latency bench (docs/serving.md "Fleet tier"): N replicas
    behind a FleetRouter at a QPS one replica cannot hold, with a mid-run
    drain + rejoin; one JSON line with per-class latency, fleet-vs-single
    achieved rps, per-replica utilization, and the static audit."""
    import threading
    from mxnet_tpu import serving, tracecheck

    nrep = benv("BENCH_FLEET_REPLICAS")
    qps = benv("BENCH_FLEET_QPS")
    nreq = benv("BENCH_FLEET_REQS")
    nreq_single = benv("BENCH_FLEET_SINGLE_REQS")
    batch_frac = benv("BENCH_FLEET_BATCH_FRAC")
    device_ms = benv("BENCH_FLEET_DEVICE_MS")
    deadline_ms = benv("BENCH_FLEET_DEADLINE_MS")
    # one dispatch serves at most this many co-riders: with the emulated
    # device time this pins a replica's capacity (max_batch/cycle) well
    # below the offered QPS, so BOTH phases measure capacity, not load
    max_batch = benv("BENCH_FLEET_MAX_BATCH")
    do_drain = benv("BENCH_FLEET_DRAIN")
    name, sym, params, shape = _serve_model(benv("BENCH_FLEET_MODEL"))
    rs = np.random.default_rng(1)
    x1 = rs.normal(size=(1,) + shape).astype(np.float32)

    def mk_replica():
        eng = serving.ServingEngine(sym, params, {"data": shape})
        return serving.Batcher(_PacedEngine(eng, device_ms),
                               max_batch=max_batch)

    # ---- phase A: ONE replica's achieved rps under the same open loop —
    # the capacity the fleet must beat (completions per wall second at an
    # offered load above what one replica can hold)
    single = serving.FleetRouter([mk_replica()], name="fleet-single")
    single.infer({"data": x1}, deadline_ms=deadline_ms)   # warm path
    cls_single = ["interactive"] * nreq_single
    lat1, err1, wall1 = _fleet_open_loop(single, {"data": x1},
                                         nreq_single, qps, cls_single,
                                         deadline_ms)
    single.close()
    done1 = sum(len(v) for v in lat1.values())
    rps_single = done1 / wall1

    # ---- phase B: the fleet, same open loop, mixed classes, and (by
    # default) a mid-run drain of r0 + a warm rejoin while serving
    replicas = {"r%d" % i: mk_replica() for i in range(nrep)}
    r0_engine = replicas["r0"].engine
    router = serving.FleetRouter(replicas, name="fleet-bench")
    router.infer({"data": x1}, deadline_ms=deadline_ms)
    stride = max(2, int(round(1.0 / batch_frac))) if batch_frac > 0 else 0
    cls = ["batch" if (stride and i % stride == 0) else "interactive"
           for i in range(nreq)]
    drain_state = {"event": None}

    def coordinator():
        # fire the membership event once ~35% of the run has been issued
        time.sleep(0.05 + (0.35 * nreq) / qps)
        try:
            router.drain("r0", timeout=60.0)
            # warm rejoin: same engine (already compiled), fresh batcher —
            # join() re-warms every bucket off the serving path
            router.join("r0b",
                        lambda: serving.Batcher(r0_engine,
                                                max_batch=max_batch),
                        warmup=True)
            drain_state["event"] = "drain+join ok"
        except Exception as e:
            drain_state["event"] = "FAILED: %r" % (e,)

    coord = None
    if do_drain:
        coord = threading.Thread(target=coordinator, daemon=True)
        coord.start()
    lat, errors, wall = _fleet_open_loop(router, {"data": x1}, nreq, qps,
                                         cls, deadline_ms)
    if coord is not None:
        coord.join(timeout=90.0)
    done = sum(len(v) for v in lat.values())
    rps_fleet = done / wall
    report = router.report()
    # static audit across EVERY replica's program set (tracecheck +
    # memory + comms lints; r0 and r0b share one engine/program set)
    findings = [f for f in router.check(memory=True, comms=True)
                if not f.suppressed]
    # utilization per DISTINCT engine: a warm rejoin (r0b) shares r0's
    # engine, so its counters must be attributed once, under a combined
    # key, not double-counted per replica name
    by_engine = {}
    for rname, r in sorted(report["replicas"].items()):
        key = r["engine"]
        names, _ = by_engine.get(key, ([], 0))
        by_engine[key] = (names + [rname], r["engine_health"]["examples"])
    total_examples = sum(ex for _, ex in by_engine.values()) or 1
    util = {"+".join(names): round(ex / total_examples, 3)
            for names, ex in by_engine.values()}
    router.close()
    if not done:
        raise RuntimeError("fleet bench completed no requests: %s"
                           % errors[:3])
    out = {
        "metric": "fleet_%s_r%d_qps%g" % (name, nrep, qps),
        "value": round(rps_fleet / max(rps_single, 1e-9), 3),
        "unit": "x_single_replica_rps",
        "replicas": nrep,
        "qps_target": qps,
        "rps_fleet": round(rps_fleet, 2),
        "rps_single": round(rps_single, 2),
        "scaling": round(rps_fleet / max(rps_single, 1e-9), 3),
        "completed": done,
        "failed": len(errors),
        "single_phase_failed": len(err1),
        "emulated_device_ms": device_ms,
        "drain_event": drain_state["event"] if do_drain else "disabled",
        "requeued": report["fleet"]["requeued"],
        "shed": report["fleet"]["shed"],
        "expired": report["fleet"]["expired"],
        "dropped": report["fleet"]["dropped"],
        "utilization": util,
        "tracecheck_findings": len(findings),
        "retraces": tracecheck.retrace_count(),
    }
    for c in serving.FLEET_CLASSES:
        if lat.get(c):
            out[c] = dict(_percentiles_ms(lat[c]),
                          completed=len(lat[c]))
    out["single"] = dict(_percentiles_ms(sum(lat1.values(), [])),
                         completed=done1)
    out["obs"] = _obs_block()
    print(json.dumps(out))


def _dp_scaling_row(sym, dshape, batch, sdtype, cdtype, remat, spd, rounds):
    """BENCH_DP_DEVICES=N: measure the fused K-step scan single-device and
    sharded over an N-way 'data' mesh at the SAME global batch (docs/perf.md
    "Data-parallel scaling"). Both sides run the identical run_steps harness
    so the efficiency ratio compares like with like; the superbatch is
    device-resident (landed sharded once), so this is pure step scaling,
    not input scaling."""
    import jax.numpy as jnp
    from mxnet_tpu.train_step import TrainStep
    from mxnet_tpu.parallel.mesh import data_parallel_mesh

    n = benv("BENCH_DP_DEVICES")
    k = max(1, spd)
    sharded = {}  # the n-device side's program + struct args for commscheck

    def measure(mesh):
        step = TrainStep(
            sym, optimizer="sgd", learning_rate=0.1, momentum=0.9, wd=1e-4,
            dtype=sdtype, mesh=mesh,
            remat={"conv": "conv", "full": True}.get(remat, False),
            compute_dtype=None if cdtype == "float32" else cdtype)
        state = step.init({"data": dshape}, {"softmax_label": (batch,)})
        rng = np.random.default_rng(0)
        sb = step.shard_superbatch({
            "data": np.stack([rng.normal(size=dshape).astype(np.float32)]
                             * k),
            "softmax_label": np.stack(
                [rng.integers(0, 1000, batch).astype(np.float32)] * k)})
        if mesh is not None:
            # struct capture BEFORE measuring: the scan donates the state
            # buffers, and the comms analyzer needs only shardings/shapes
            from mxnet_tpu import commscheck
            sharded["args"] = commscheck.struct_args(
                (state, sb, step._dispatch_key(),
                 jnp.zeros((k,), jnp.float32)))
            sharded["step"] = step
            sharded["mesh"] = mesh
        # keep measured *steps* roughly constant as K grows (as main does)
        n_short = max(2, (20 + k - 1) // k)
        n_long = max(n_short + 5, (120 + k - 1) // k)
        return measure_scan_ips(step, state, sb, batch, k, n_short, n_long,
                                rounds=rounds)

    ips1 = measure(None)
    ipsn = measure(data_parallel_mesh(n))
    row = {
        "n_devices": n,
        "img_per_sec": round(ipsn, 2),
        "img_per_sec_1chip": round(ips1, 2),
        "scaling_efficiency": (round(ipsn / ips1, 3) if ips1 > 0 else None),
    }
    # static comms profile of the measured sharded scan (one extra compile;
    # docs/static_analysis.md "Communication lints"): the roofline's
    # prediction rides next to the measured efficiency, so the gap between
    # model and machine is visible in every BENCH_DP_DEVICES line
    try:
        from mxnet_tpu import commscheck
        rep = commscheck.analyze(
            sharded["step"]._jit_scan[(batch, k)], sharded["args"],
            name="bench-dp-scan", mesh=sharded["mesh"], loop_trips=k)
        row["collective_count"] = rep.collective_count
        row["collective_bytes"] = rep.collective_bytes
        row["predicted_efficiency"] = (
            None if rep.predicted_efficiency is None
            else round(rep.predicted_efficiency, 3))
    except Exception as exc:
        print("WARNING: commscheck analysis failed, no dp comms fields "
              "emitted: %r" % exc, file=sys.stderr)
    return row


def lm_main():
    """BENCH_LM=1: flagship transformer-LM training bench (docs/perf.md
    "Flagship LM"): steady-state tokens/sec + MFU through the SAME fused
    K-step scan harness as the ResNet headline (measure_scan_ips — one
    methodology, so the LM and vision lines compare like with like),
    then one row per mesh spec in BENCH_LM_MESHES — dp, sp (ring
    attention over the 'seq' axis) and the composed dp x sp mesh — at
    the SAME global batch, each with measured scaling efficiency AND the
    commscheck roofline's prediction riding next to it, so the gap
    between model and machine is visible per mesh."""
    import jax
    import jax.numpy as jnp
    from mxnet_tpu import models, tracecheck
    from mxnet_tpu.train_step import TrainStep
    from mxnet_tpu.parallel.mesh import mesh_from_spec

    batch = benv("BENCH_LM_BATCH")
    seq = benv("BENCH_LM_SEQ")
    vocab = benv("BENCH_LM_VOCAB")
    embed = benv("BENCH_LM_EMBED")
    layers = benv("BENCH_LM_LAYERS")
    heads = benv("BENCH_LM_HEADS")
    cdtype = benv("BENCH_LM_DTYPE")
    rounds = benv("BENCH_ROUNDS")
    mesh_specs = [m.strip() for m in benv("BENCH_LM_MESHES").split(";")
                  if m.strip()]

    sym = models.transformer(vocab_size=vocab, embed=embed,
                             num_heads=heads, num_layers=layers,
                             seq_len=seq)
    # meshes carrying a 'seq' axis run RING attention (the flagship
    # sequence-parallel mode: K/V rotate over the axis via ppermute)
    # with the rank-3 preserve_shape head, instead of leaving the
    # seq-sharded tensors to GSPMD's generic resharding — same math,
    # but the measured collectives are the ring's, and the head never
    # merges the sharded batch x seq dims (no per-trip all-gather)
    sym_ring = models.transformer(vocab_size=vocab, embed=embed,
                                  num_heads=heads, num_layers=layers,
                                  seq_len=seq, seq_parallel="ring",
                                  preserve_shape=True)

    # BENCH_STEPS_PER_DISPATCH resolution: env > tuning DB > mode default
    # (4 — the LM bench IS the steady-state story), the same precedence
    # chain as the headline bench, and the JSON line says which source won
    from mxnet_tpu import autotune as _autotune
    spd = benv("BENCH_STEPS_PER_DISPATCH", 4)
    at_block = {"steps_per_dispatch": {
        "value": spd,
        "source": "env" if env_set("BENCH_STEPS_PER_DISPATCH")
        else "default"}}
    if at_block["steps_per_dispatch"]["source"] == "default":
        db_key, db_knobs = _autotune.resolve_train_knobs(sym, batch)
        if db_knobs and "steps_per_dispatch" in db_knobs:
            spd = max(1, int(db_knobs["steps_per_dispatch"]))
            at_block = {"steps_per_dispatch": {"value": spd,
                                               "source": "db"},
                        "db_entry": db_key,
                        "db": _autotune.default_db_path()}
            _autotune.note_db_resolution(None, "bench.py", db_key,
                                         {"steps_per_dispatch": spd})
    k = max(1, spd)

    # every mesh spec is validated BEFORE the headline measurement
    # (mesh_from_spec fails with the XLA_FLAGS recipe on a device
    # shortfall; shard_superbatch names the failing axis + dimension on
    # a divisibility miss at each row's build) — a misconfigured env
    # must not discard minutes of already-measured throughput
    meshes = [(spec, mesh_from_spec(spec)) for spec in mesh_specs]

    rng = np.random.default_rng(0)
    data_h = rng.integers(0, vocab, (batch, seq)).astype(np.float32)
    label_h = rng.integers(0, vocab, (batch, seq)).astype(np.float32)
    # keep measured *steps* roughly constant as K grows (as the headline
    # bench does; the LM is heavier per step so the counts start lower)
    n_short = max(2, (12 + k - 1) // k)
    n_long = max(n_short + 3, (48 + k - 1) // k)

    def measure(mesh):
        """(samples/sec, TrainStep, scan struct-args) for one mesh. The
        struct capture happens BEFORE measuring: the scan donates the
        state buffers, and the analyzers need only shapes + shardings."""
        from mxnet_tpu import commscheck
        from mxnet_tpu.parallel.mesh import AXIS_SEQ
        seq_mesh = mesh is not None and AXIS_SEQ in mesh.axis_names
        s = sym_ring if seq_mesh else sym
        # pos_embed rows live with their 'seq' shard (replicated, the
        # naturally seq-sharded grad pays an all-gather every trip)
        shardings = ({"pos_embed_weight":
                      jax.sharding.PartitionSpec(AXIS_SEQ, None)}
                     if seq_mesh else None)
        step = TrainStep(
            s, optimizer="sgd", learning_rate=0.1, momentum=0.9,
            wd=1e-4, mesh=mesh, param_shardings=shardings,
            compute_dtype=None if cdtype == "float32" else cdtype)
        state = step.init({"data": (batch, seq)},
                          {"softmax_label": (batch, seq)})
        sb = step.shard_superbatch({
            "data": np.stack([data_h] * k),
            "softmax_label": np.stack([label_h] * k)})
        args = commscheck.struct_args(
            (state, sb, step._dispatch_key(),
             jnp.zeros((k,), jnp.float32)))
        ips = measure_scan_ips(step, state, sb, batch, k, n_short,
                               n_long, rounds=rounds)
        return ips, step, args

    ips1, step1, args1 = measure(None)
    if ips1 <= 0.0:
        raise RuntimeError(
            "LM benchmark produced no valid measurement (rounds=%d)"
            % rounds)

    # exact FLOPs from XLA's cost model on the SINGLE LM step (lowered
    # from the captured structs — the live state is already donated; the
    # scan lowers to a While whose body the cost model counts once, so
    # the per-token figure must come from the per-step computation)
    flops_per_sample = None
    try:
        state_s, sb_s, key_s, _lrs = args1
        if batch not in step1._jit:
            step1._jit[batch] = step1._build(batch)
        step_args = (state_s,
                     {n: jax.ShapeDtypeStruct(v.shape[1:], v.dtype)
                      for n, v in sb_s.items()},
                     key_s, jax.ShapeDtypeStruct((), np.float32))
        lowered = step1._jit[batch].lower(*step_args)
        try:
            ca = lowered.cost_analysis()
        except Exception:
            ca = None
        if ca is None:  # pre-compile analysis unsupported on this backend
            ca = lowered.compile().cost_analysis()
        if isinstance(ca, list):
            ca = ca[0]
        flops_per_sample = float(ca["flops"]) / batch
    except Exception as exc:  # MFU is a headline metric: never drop silently
        print("WARNING: cost analysis failed, no MFU emitted: %r" % exc,
              file=sys.stderr)

    # static memory + comms profile of the measured single-device scan
    # (ONE extra compile shared by both analyzers, exactly as the
    # headline bench does for its measured program)
    mem = None
    comms = None
    compiled1 = None
    try:
        from mxnet_tpu import memcheck
        compiled1 = step1._jit_scan[(batch, k)].lower(*args1).compile()
        mem = memcheck.analyze_compiled(
            compiled1, "bench-lm-scan", args=args1, donate_argnums=(0,))
    except Exception as exc:  # the bench number must survive an analyzer bug
        print("WARNING: memcheck analysis failed, no HBM fields emitted: "
              "%r" % exc, file=sys.stderr)
    try:
        from mxnet_tpu import commscheck
        if compiled1 is not None:
            comms = commscheck.analyze_compiled(
                compiled1, "bench-lm-scan", loop_trips=k)
    except Exception as exc:
        print("WARNING: commscheck analysis failed, no comms fields "
              "emitted: %r" % exc, file=sys.stderr)
    roof = None
    try:
        from mxnet_tpu import flopcheck
        if compiled1 is not None:
            roof = flopcheck.analyze_compiled(
                compiled1, "bench-lm-scan", loop_trips=k)
    except Exception as exc:
        print("WARNING: flopcheck analysis failed, no roofline fields "
              "emitted: %r" % exc, file=sys.stderr)

    # per-mesh rows: SAME global batch, SAME harness; the sharded scan's
    # comms audit (commscheck.analyze compiles from the captured sharded
    # structs) puts the roofline prediction next to the measured ratio
    rows = []
    for spec, mesh in meshes:
        ipsn, stepn, argsn = measure(mesh)
        row = {
            "mesh": spec,
            "n_devices": int(np.prod(list(mesh.shape.values()))),
            "tokens_per_sec": round(ipsn * seq, 1),
            "samples_per_sec": round(ipsn, 2),
            "scaling_efficiency": (round(ipsn / ips1, 3)
                                   if ips1 > 0 else None),
        }
        try:
            from mxnet_tpu import commscheck
            rep = commscheck.analyze(
                stepn._jit_scan[(batch, k)], argsn,
                name="bench-lm-scan[%s]" % spec, mesh=mesh, loop_trips=k)
            row["collective_count"] = rep.collective_count
            row["collective_bytes"] = rep.collective_bytes
            row["predicted_efficiency"] = (
                None if rep.predicted_efficiency is None
                else round(rep.predicted_efficiency, 3))
        except Exception as exc:
            print("WARNING: commscheck analysis failed for mesh %s, no "
                  "comms fields emitted: %r" % (spec, exc),
                  file=sys.stderr)
        rows.append(row)

    peak, kind = _peak_flops(jax.devices()[0])
    peak_source = "spec"
    if peak is None:
        # CPU / unknown device: devspec's documented nominal fallback,
        # clearly labeled — an MFU against a guessed spec-sheet number
        # would be misinformation, but the forced-host CI line still
        # needs a deterministic utilization figure
        from mxnet_tpu.devspec import DEFAULT_SPEC
        peak, peak_source = DEFAULT_SPEC.peak_flops_per_s, "nominal-fallback"
    out = {
        "metric": "lm_train_tokens_per_sec_b%d_s%d_%s_k%d"
                  % (batch, seq, cdtype, k),
        "value": round(ips1 * seq, 1),
        "unit": "tokens/sec",
        "samples_per_sec": round(ips1, 2),
        "tokens_per_sample": seq,
        "model": {"vocab_size": vocab, "embed": embed,
                  "num_layers": layers, "num_heads": heads,
                  "seq_len": seq, "batch": batch},
        "steps_per_dispatch": k,
        # unexpected jit-cache misses during the measured run — a retrace
        # storm invalidates the steady-state number
        "retraces": tracecheck.retrace_count(),
    }
    if mem is not None:
        out["hbm_peak_bytes"] = mem.peak_bytes
        out["temp_bytes"] = mem.temp_bytes
        out["alias_bytes"] = mem.alias_bytes
    if comms is not None:
        out["collective_count"] = comms.collective_count
        out["collective_bytes"] = comms.collective_bytes
        out["predicted_efficiency"] = (
            None if comms.predicted_efficiency is None
            else round(comms.predicted_efficiency, 3))
    if roof is not None and not roof.hlo_unavailable:
        # the flopcheck roofline's forecast rides next to the measured
        # number: a widening measured-vs-predicted MFU gap means either
        # the wire model drifted or the schedule did
        out["predicted_step_ms"] = round(roof.predicted_step_ms, 4)
        if roof.predicted_mfu is not None:
            out["predicted_mfu"] = round(roof.predicted_mfu, 6)
    if flops_per_sample:
        out["gflop_per_token_xla"] = round(flops_per_sample / seq / 1e9, 4)
        out["achieved_tflops"] = round(ips1 * flops_per_sample / 1e12, 4)
        # MFU only for bf16 compute: the peak table is the bf16 peak,
        # and fp32 runs against it would understate utilization
        if peak and cdtype == "bfloat16":
            out["mfu"] = round(ips1 * flops_per_sample / peak, 6)
            out["device_kind"] = kind
            out["peak_tflops_bf16"] = peak / 1e12
            out["peak_source"] = peak_source
    out["meshes"] = rows
    out["autotune"] = at_block
    out["obs"] = _obs_block()
    print(json.dumps(out))


def main():
    import jax
    import jax.numpy as jnp
    from mxnet_tpu import models
    from mxnet_tpu.train_step import TrainStep

    batch = benv("BENCH_BATCH")
    rounds = benv("BENCH_ROUNDS")
    depth = benv("BENCH_DEPTH")
    image = benv("BENCH_IMAGE")
    cdtype = benv("BENCH_DTYPE")
    dp_n = benv("BENCH_DP_DEVICES")
    if dp_n > 1:
        # validate BEFORE the headline measurement: a misconfigured env
        # must not discard minutes of already-measured throughput
        if len(jax.devices()) < dp_n:
            raise SystemExit(
                "BENCH_DP_DEVICES=%d but only %d device(s) are visible — "
                "on CPU raise the count with XLA_FLAGS="
                "--xla_force_host_platform_device_count=%d"
                % (dp_n, len(jax.devices()), dp_n))
        if batch % dp_n:
            raise SystemExit(
                "BENCH_DP_DEVICES=%d does not divide BENCH_BATCH=%d — the "
                "sharded scan needs equal per-chip shards"
                % (dp_n, batch))
    baseline = 181.53  # P100, ResNet-50 train b32 (docs/how_to/perf.md:183-190)

    # measured r4: remat=conv loses ~17% on v5e (recompute re-reads conv
    # outputs; chip is HBM-bound) — remat stays a memory knob, not a default
    remat = benv("BENCH_REMAT")  # conv|full|off
    # measured r4: NHWC+Pallas conv+BN-stats fusion is 2x SLOWER than
    # letting XLA fuse (docs/perf.md r4 section) — NCHW/XLA stays default
    layout = benv("BENCH_LAYOUT")
    dshape = ((batch, image, image, 3) if layout == "NHWC"
              else (batch, 3, image, image))
    # BENCH_STORAGE_DTYPE=bfloat16 stores params+optimizer state in bf16
    # (no f32 masters) — measured r5, see docs/perf.md
    sdtype = benv("BENCH_STORAGE_DTYPE")
    sym = models.resnet(num_classes=1000, num_layers=depth,
                        image_shape="3,%d,%d" % (image, image),
                        layout=layout)
    step = TrainStep(sym, optimizer="sgd", learning_rate=0.1, momentum=0.9,
                     wd=1e-4, dtype=sdtype,
                     remat={"conv": "conv", "full": True}.get(remat, False),
                     compute_dtype=None if cdtype == "float32" else cdtype)
    # storage dtype != f32 forces compute to the storage dtype inside
    # TrainStep; label the run by what actually executed
    if step.compute_dtype is not None:
        cdtype = np.dtype(step.compute_dtype).name
    state = step.init({"data": dshape}, {"softmax_label": (batch,)})

    rng = np.random.default_rng(0)
    data = {"data": jnp.asarray(rng.normal(size=dshape), np.float32),
            "softmax_label": jnp.asarray(rng.integers(0, 1000, batch),
                                         np.float32)}

    # steady-state bulked mode: K steps per dispatch via TrainStep.run_steps
    # (lax.scan). The superbatch is built ON DEVICE once — input cost is out
    # of the loop, so this measures the pure dispatch-amortization win the
    # per-step mode leaves on the table.
    # BENCH_STEPS_PER_DISPATCH resolution (docs/perf.md "Autotuning"):
    # env > tuning DB > default — and the JSON line SAYS which source won,
    # so a bench number is always attributable to its configuration
    from mxnet_tpu import autotune as _autotune
    spd = benv("BENCH_STEPS_PER_DISPATCH")
    at_block = {"steps_per_dispatch": {
        "value": spd,
        "source": "env" if env_set("BENCH_STEPS_PER_DISPATCH")
        else "default"}}
    if at_block["steps_per_dispatch"]["source"] == "default":
        db_key, db_knobs = _autotune.resolve_train_knobs(sym, batch)
        if db_knobs and "steps_per_dispatch" in db_knobs:
            spd = max(1, int(db_knobs["steps_per_dispatch"]))
            at_block = {"steps_per_dispatch": {"value": spd,
                                               "source": "db"},
                        "db_entry": db_key,
                        "db": _autotune.default_db_path()}
            _autotune.note_db_resolution(None, "bench.py", db_key,
                                         {"steps_per_dispatch": spd})
    if spd > 1:
        sbatch = {n: jnp.stack([v] * spd) for n, v in data.items()}

        def run(state, dispatches):
            t0 = time.perf_counter()
            for _ in range(dispatches):
                state, _metrics = step.run_steps(state, sbatch)
            np.asarray(state["step"])  # forced readback: tunnel-honored sync
            return time.perf_counter() - t0, state

        # keep measured *steps* roughly constant as K grows
        n_short = max(2, (20 + spd - 1) // spd)
        n_long = max(n_short + 5, (120 + spd - 1) // spd)
        imgs_per_dispatch = batch * spd
    else:
        def run(state, steps):
            t0 = time.perf_counter()
            for _ in range(steps):
                state, _outs = step.step(state, data)
            np.asarray(state["step"])  # forced readback: sync point the tunnel honors
            return time.perf_counter() - t0, state

        n_short, n_long = 20, 120
        imgs_per_dispatch = batch

    # warmup / compile (retry: remote_compile over the tunnel can flake).
    # A failed attempt may have executed a step and donated the state
    # buffers, so each retry starts from freshly initialized state.
    for attempt in range(4):
        try:
            _, state = run(state, 3)
            break
        except Exception:
            if attempt == 3:
                raise
            time.sleep(3)
            state = step.init({"data": dshape}, {"softmax_label": (batch,)})

    best_ips = 0.0
    for _ in range(rounds):
        t_short, state = run(state, n_short)
        t_long, state = run(state, n_long)
        if t_long > t_short:
            best_ips = max(best_ips, imgs_per_dispatch * (n_long - n_short)
                           / (t_long - t_short))
    if best_ips <= 0.0:
        raise RuntimeError(
            "benchmark produced no valid measurement (rounds=%d)" % rounds)
    ips = best_ips

    # exact FLOPs from XLA's cost model on the SINGLE step (lowered, not
    # recompiled) in both modes: the scan lowers to a While whose body the
    # cost model counts once, not trip-count times, so the per-image figure
    # must come from the per-step computation
    flops_per_img = None
    step_compiled = None  # shared with the memory profile below
    step_args = None
    try:
        key = jax.random.key(0)
        lr_base = jnp.asarray(0.1, jnp.float32)
        if batch not in step._jit:
            step._jit[batch] = step._build(batch)
        step_args = (state, data, key, lr_base)
        lowered = step._jit[batch].lower(*step_args)
        try:
            ca = lowered.cost_analysis()
        except Exception:
            ca = None
        if ca is None:  # pre-compile analysis unsupported on this backend
            step_compiled = lowered.compile()
            ca = step_compiled.cost_analysis()
        if isinstance(ca, list):
            ca = ca[0]
        flops_per_img = float(ca["flops"]) / batch
    except Exception as exc:  # MFU is a headline metric: never drop silently
        print("WARNING: cost analysis failed, no MFU emitted: %r" % exc,
              file=sys.stderr)
        lowered = None

    # static memory profile of the program that actually ran (docs/
    # static_analysis.md "Memory lints"): peak HBM + temp bytes ride next
    # to img/s, so a fusion/remat regression that doubles temps is visible
    # in the same JSON line that would show the throughput cost. The
    # single-step mode reuses the cost-analysis lowering (at most ONE
    # extra compile); the scan mode pays one compile of the scan — the
    # measured program — since jit exposes no handle to its executable.
    mem = None
    comms = None
    measured_compiled = None  # ONE compile shared by both analyzers
    try:
        from mxnet_tpu import memcheck
        if spd > 1:
            scan_args = (state, sbatch, step._dispatch_key(),
                         jnp.zeros((spd,), jnp.float32))
            measured_compiled = step._jit_scan[(batch, spd)] \
                .lower(*scan_args).compile()
            mem = memcheck.analyze_compiled(
                measured_compiled, "bench-scan", args=scan_args,
                donate_argnums=(0,))
        elif lowered is not None:
            if step_compiled is None:
                step_compiled = lowered.compile()
            measured_compiled = step_compiled
            mem = memcheck.analyze_compiled(
                step_compiled, "bench-step", args=step_args,
                donate_argnums=(0,))
    except Exception as exc:  # the bench number must survive an analyzer bug
        print("WARNING: memcheck analysis failed, no HBM fields emitted: %r"
              % exc, file=sys.stderr)
    # static comms profile of the same executable (docs/static_analysis.md
    # "Communication lints"): collective count/bytes + the roofline's
    # predicted scaling efficiency ride next to img/s and hbm_peak_bytes —
    # zero collectives and efficiency 1.0 on a single-device run, so a
    # sharding change that makes the headline program communicate shows in
    # the same JSON line as its throughput cost
    try:
        from mxnet_tpu import commscheck
        if measured_compiled is not None:
            comms = commscheck.analyze_compiled(
                measured_compiled,
                "bench-scan" if spd > 1 else "bench-step",
                mesh=step.mesh, loop_trips=max(1, spd))
    except Exception as exc:
        print("WARNING: commscheck analysis failed, no comms fields "
              "emitted: %r" % exc, file=sys.stderr)
    # static roofline forecast of the same executable (docs/
    # static_analysis.md "Roofline lints"): predicted step time + MFU
    # ride next to the measured img/s so the forecast-vs-measured gap is
    # one JSON line — the third analyzer sharing measured_compiled's
    # single compile
    roof = None
    try:
        from mxnet_tpu import flopcheck
        if measured_compiled is not None:
            roof = flopcheck.analyze_compiled(
                measured_compiled,
                "bench-scan" if spd > 1 else "bench-step",
                mesh=step.mesh, loop_trips=max(1, spd))
    except Exception as exc:
        print("WARNING: flopcheck analysis failed, no roofline fields "
              "emitted: %r" % exc, file=sys.stderr)

    peak, kind = _peak_flops(jax.devices()[0])
    metric = "resnet%d_train_images_per_sec_b%d_%s" % (depth, batch, cdtype)
    if sdtype != "float32":
        metric += "_store_%s" % sdtype
    if spd > 1:
        metric += "_k%d" % spd
    from mxnet_tpu import tracecheck
    out = {
        "metric": metric,
        "value": round(ips, 2),
        "unit": "images/sec",
        "vs_baseline": round(ips / baseline, 3),
        # unexpected jit-cache misses during the measured run — a retrace
        # storm invalidates the steady-state number (docs/static_analysis.md)
        "retraces": tracecheck.retrace_count(),
    }
    if spd > 1:
        out["steps_per_dispatch"] = spd
    if mem is not None:
        out["hbm_peak_bytes"] = mem.peak_bytes
        out["temp_bytes"] = mem.temp_bytes
        out["alias_bytes"] = mem.alias_bytes
    if comms is not None:
        out["collective_count"] = comms.collective_count
        out["collective_bytes"] = comms.collective_bytes
        out["predicted_efficiency"] = (
            None if comms.predicted_efficiency is None
            else round(comms.predicted_efficiency, 3))
    if roof is not None and not roof.hlo_unavailable:
        out["predicted_step_ms"] = round(roof.predicted_step_ms, 4)
        if roof.predicted_mfu is not None:
            out["predicted_mfu"] = round(roof.predicted_mfu, 6)
    if flops_per_img:
        out["gflop_per_image_xla"] = round(flops_per_img / 1e9, 2)
        out["achieved_tflops"] = round(ips * flops_per_img / 1e12, 1)
        # MFU only for bf16 compute: the peak table is the bf16 peak, and
        # fp32 runs against it would understate utilization several-fold
        if peak and cdtype == "bfloat16":
            out["mfu"] = round(ips * flops_per_img / peak, 4)
            out["device_kind"] = kind
            out["peak_tflops_bf16"] = peak / 1e12
    if dp_n > 1:
        out["dp"] = _dp_scaling_row(sym, dshape, batch, sdtype, cdtype,
                                    remat, spd, rounds)
    out["autotune"] = at_block
    out["obs"] = _obs_block()
    print(json.dumps(out))


if __name__ == "__main__":
    if benv("BENCH_ZOO_DISPATCH"):
        zoo_dispatch_main()
    elif benv("BENCH_REAL_DATA"):
        realdata_main()
    elif benv("BENCH_LM"):
        lm_main()
    elif benv("BENCH_FLEET"):
        fleet_main()
    elif benv("BENCH_DECODE"):
        decode_main()
    elif benv("BENCH_SERVE"):
        serve_main()
    elif benv("BENCH_HOST_OVERHEAD"):
        host_overhead_main()
    else:
        main()
