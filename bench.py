#!/usr/bin/env python
"""Benchmark: ResNet-50 training throughput (images/sec) on one chip.

Mirrors the reference's headline number (BASELINE.md: ResNet-50 train,
batch 32 — 45.52 img/s K80 / 90.74 M40 / 181.53 P100, from
docs/how_to/perf.md:159-190; script behavior ref:
example/image-classification/benchmark_score.py + train_imagenet.py).

vs_baseline is measured against the strongest single-GPU reference number
(P100, 181.53 img/s). Prints ONE JSON line.

Env knobs: BENCH_BATCH (default 32), BENCH_STEPS (default 20),
BENCH_DTYPE (float32|bfloat16 compute, default bfloat16),
BENCH_DEPTH (default 50), BENCH_IMAGE (default 224).
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np


def main():
    import jax
    from mxnet_tpu import models
    from mxnet_tpu.train_step import TrainStep

    batch = int(os.environ.get("BENCH_BATCH", "32"))
    steps = int(os.environ.get("BENCH_STEPS", "20"))
    depth = int(os.environ.get("BENCH_DEPTH", "50"))
    image = int(os.environ.get("BENCH_IMAGE", "224"))
    cdtype = os.environ.get("BENCH_DTYPE", "bfloat16")
    baseline = 181.53  # P100, ResNet-50 train b32 (docs/how_to/perf.md:183-190)

    sym = models.resnet(num_classes=1000, num_layers=depth,
                        image_shape="3,%d,%d" % (image, image))
    step = TrainStep(sym, optimizer="sgd", learning_rate=0.1, momentum=0.9,
                     wd=1e-4,
                     compute_dtype=None if cdtype == "float32" else cdtype)
    state = step.init({"data": (batch, 3, image, image)},
                      {"softmax_label": (batch,)})

    rng = np.random.default_rng(0)
    data = {"data": np.asarray(rng.normal(size=(batch, 3, image, image)),
                               np.float32),
            "softmax_label": np.asarray(rng.integers(0, 1000, batch),
                                        np.float32)}
    import jax.numpy as jnp
    data = {k: jnp.asarray(v) for k, v in data.items()}

    # warmup / compile
    for _ in range(3):
        state, outs = step.step(state, data)
    jax.block_until_ready(state["params"]["fc1_weight"])

    t0 = time.perf_counter()
    for _ in range(steps):
        state, outs = step.step(state, data)
    jax.block_until_ready(state["params"]["fc1_weight"])
    dt = time.perf_counter() - t0

    ips = batch * steps / dt
    print(json.dumps({
        "metric": "resnet%d_train_images_per_sec_b%d_%s" % (depth, batch,
                                                            cdtype),
        "value": round(ips, 2),
        "unit": "images/sec",
        "vs_baseline": round(ips / baseline, 3),
    }))


if __name__ == "__main__":
    main()
