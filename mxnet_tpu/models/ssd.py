"""SSD: Single-Shot MultiBox Detector (ref config 4).

Faithful re-build of the reference's SSD wiring
(ref: example/ssd/symbol/common.py:110-190 multibox_layer,
example/ssd/symbol/symbol_vgg16_ssd_300.py:124-155 train/eval heads) on a
compact conv backbone: per-feature-map loc/cls conv heads + MultiBoxPrior
anchors, MultiBoxTarget matching + hard-negative mining for training
(SoftmaxOutput with ignore + smooth_l1 MakeLoss), MultiBoxDetection NMS for
eval. The MultiBox ops are the dense-masked XLA reformulations in
ops/contrib.py.
"""
from .. import symbol as sym


def _conv_act(data, num_filter, kernel, stride, pad, name):
    c = sym.Convolution(data=data, num_filter=num_filter, kernel=kernel,
                        stride=stride, pad=pad, name=name)
    return sym.Activation(data=c, act_type="relu")


def _backbone(data, width=32):
    """Small VGG-style feature extractor returning taps at strides 8/16/32."""
    x = _conv_act(data, width, (3, 3), (1, 1), (1, 1), "conv1_1")
    x = sym.Pooling(data=x, kernel=(2, 2), stride=(2, 2), pool_type="max")
    x = _conv_act(x, width * 2, (3, 3), (1, 1), (1, 1), "conv2_1")
    x = sym.Pooling(data=x, kernel=(2, 2), stride=(2, 2), pool_type="max")
    x = _conv_act(x, width * 4, (3, 3), (1, 1), (1, 1), "conv3_1")
    tap1 = _conv_act(x, width * 4, (3, 3), (1, 1), (1, 1), "conv3_2")
    x = sym.Pooling(data=tap1, kernel=(2, 2), stride=(2, 2),
                    pool_type="max")
    tap2 = _conv_act(x, width * 8, (3, 3), (1, 1), (1, 1), "conv4_1")
    x = sym.Pooling(data=tap2, kernel=(2, 2), stride=(2, 2),
                    pool_type="max")
    tap3 = _conv_act(x, width * 8, (3, 3), (1, 1), (1, 1), "conv5_1")
    return [tap1, tap2, tap3]


def multibox_layer(from_layers, num_classes, sizes, ratios, clip=False,
                   normalization=-1):
    """Per-feature-map loc/cls heads + anchors
    (ref: example/ssd/symbol/common.py:110-190)."""
    loc_layers, cls_layers, anchor_layers = [], [], []
    num_classes += 1                     # + background class
    for k, from_layer in enumerate(from_layers):
        name = "mb%d" % k
        norm = (normalization[k] if isinstance(normalization, (list, tuple))
                else normalization)
        if norm > 0:
            # channel L2-norm with fixed scale (ref uses a learnable scale
            # initialized to `norm`; the constant matches its init state)
            from_layer = sym.L2Normalization(data=from_layer,
                                             mode="channel",
                                             name=name + "_norm") * norm
        size, ratio = sizes[k], ratios[k]
        na = len(size) + len(ratio) - 1
        loc = sym.Convolution(data=from_layer, num_filter=na * 4,
                              kernel=(3, 3), pad=(1, 1),
                              name=name + "_loc_pred_conv")
        loc = sym.transpose(data=loc, axes=(0, 2, 3, 1))
        loc_layers.append(sym.Flatten(data=loc))
        cls = sym.Convolution(data=from_layer, num_filter=na * num_classes,
                              kernel=(3, 3), pad=(1, 1),
                              name=name + "_cls_pred_conv")
        cls = sym.transpose(data=cls, axes=(0, 2, 3, 1))
        cls_layers.append(sym.Flatten(data=cls))
        anchors = sym.MultiBoxPrior(from_layer,
                                    sizes=",".join(str(s) for s in size),
                                    ratios=",".join(str(r) for r in ratio),
                                    clip=clip, name=name + "_anchors")
        anchor_layers.append(sym.Flatten(data=anchors))
    loc_preds = sym.Concat(*loc_layers, dim=1, name="multibox_loc_pred")
    cls_preds = sym.Concat(*cls_layers, dim=1)
    cls_preds = sym.Reshape(data=cls_preds, shape=(0, -1, num_classes))
    cls_preds = sym.transpose(data=cls_preds, axes=(0, 2, 1),
                              name="multibox_cls_pred")
    anchors = sym.Concat(*anchor_layers, dim=1)
    anchors = sym.Reshape(data=anchors, shape=(0, -1, 4),
                          name="multibox_anchors")
    return loc_preds, cls_preds, anchors


_DEFAULT_SIZES = [[0.2, 0.27], [0.37, 0.44], [0.54, 0.62]]
_DEFAULT_RATIOS = [[1.0, 2.0, 0.5]] * 3


def _heads(num_classes, width, sizes, ratios):
    data = sym.Variable("data")
    taps = _backbone(data, width)
    sizes = sizes or _DEFAULT_SIZES
    ratios = ratios or _DEFAULT_RATIOS
    return multibox_layer(taps, num_classes, sizes, ratios, clip=True)


def get_symbol_train(num_classes=4, width=32, sizes=None, ratios=None,
                     nms_thresh=0.5, nms_topk=400, **kwargs):
    """Training net: losses wired exactly like the reference head
    (symbol_vgg16_ssd_300.py:129-155)."""
    loc_preds, cls_preds, anchors = _heads(num_classes, width, sizes, ratios)
    label = sym.Variable("label")
    tmp = sym.MultiBoxTarget(anchors, label, cls_preds,
                             overlap_threshold=0.5, ignore_label=-1,
                             negative_mining_ratio=3,
                             negative_mining_thresh=0.5,
                             variances="0.1,0.1,0.2,0.2",
                             name="multibox_target")
    loc_target, loc_target_mask, cls_target = tmp[0], tmp[1], tmp[2]
    cls_prob = sym.SoftmaxOutput(data=cls_preds, label=cls_target,
                                 ignore_label=-1, use_ignore=True,
                                 multi_output=True, normalization="valid",
                                 name="cls_prob")
    loc_loss_ = sym.smooth_l1(data=loc_target_mask * (loc_preds - loc_target),
                              scalar=1.0, name="loc_loss_")
    loc_loss = sym.MakeLoss(loc_loss_, normalization="valid",
                            name="loc_loss")
    cls_label = sym.MakeLoss(data=cls_target, grad_scale=0.0,
                             name="cls_label")
    det = sym.MultiBoxDetection(cls_prob, loc_preds, anchors,
                                nms_threshold=nms_thresh,
                                variances="0.1,0.1,0.2,0.2",
                                nms_topk=nms_topk, name="detection")
    det = sym.MakeLoss(data=det, grad_scale=0.0, name="det_out")
    return sym.Group([cls_prob, loc_loss, cls_label, det])


def get_symbol(num_classes=4, width=32, sizes=None, ratios=None,
               nms_thresh=0.5, nms_topk=400, **kwargs):
    """Inference net: softmax + decode + NMS
    (ref: symbol_vgg16_ssd_300.py:157-190)."""
    loc_preds, cls_preds, anchors = _heads(num_classes, width, sizes, ratios)
    cls_prob = sym.SoftmaxActivation(data=cls_preds, mode="channel",
                                     name="cls_prob")
    return sym.MultiBoxDetection(cls_prob, loc_preds, anchors,
                                 nms_threshold=nms_thresh,
                                 variances="0.1,0.1,0.2,0.2",
                                 nms_topk=nms_topk, name="detection")
