"""ResNet v1.5/v2 (He et al. 2015/2016) — the north-star benchmark model
(ref: example/image-classification/symbols/resnet.py behavior; BASELINE.md
ResNet-50/152 rows).

Standard depth configs: 18/34 (basic block), 50/101/152 (bottleneck).
``image_shape`` picks the ImageNet stem (7x7/s2 + maxpool) or the CIFAR stem
(3x3/s1). BatchNorm everywhere, no bias on convs feeding BN — XLA fuses the
BN+ReLU chains into the conv epilogues on TPU.
"""
from .. import symbol as sym

_DEPTH_CONFIGS = {
    18: ("basic", (2, 2, 2, 2)),
    34: ("basic", (3, 4, 6, 3)),
    50: ("bottleneck", (3, 4, 6, 3)),
    101: ("bottleneck", (3, 4, 23, 3)),
    152: ("bottleneck", (3, 8, 36, 3)),
}

_BN_ARGS = dict(fix_gamma=False, eps=2e-5, momentum=0.9)


def _conv_bn(data, num_filter, kernel, stride, pad, name, act=True,
             layout="NCHW"):
    c = sym.Convolution(data=data, num_filter=num_filter, kernel=kernel,
                        stride=stride, pad=pad, no_bias=True,
                        layout=layout, name=name + "_conv")
    bn = sym.BatchNorm(data=c, name=name + "_bn",
                       axis=3 if layout == "NHWC" else 1, **_BN_ARGS)
    if act:
        return sym.Activation(data=bn, act_type="relu")
    return bn


def _basic_block(data, num_filter, stride, dim_match, name, layout="NCHW"):
    body = _conv_bn(data, num_filter, (3, 3), stride, (1, 1), name + "_1",
                    layout=layout)
    body = _conv_bn(body, num_filter, (3, 3), (1, 1), (1, 1), name + "_2",
                    act=False, layout=layout)
    if dim_match:
        shortcut = data
    else:
        shortcut = _conv_bn(data, num_filter, (1, 1), stride, (0, 0),
                            name + "_sc", act=False, layout=layout)
    return sym.Activation(data=body + shortcut, act_type="relu")


def _bottleneck_block(data, num_filter, stride, dim_match, name,
                      layout="NCHW"):
    body = _conv_bn(data, num_filter // 4, (1, 1), (1, 1), (0, 0),
                    name + "_1", layout=layout)
    body = _conv_bn(body, num_filter // 4, (3, 3), stride, (1, 1),
                    name + "_2", layout=layout)
    body = _conv_bn(body, num_filter, (1, 1), (1, 1), (0, 0), name + "_3",
                    act=False, layout=layout)
    if dim_match:
        shortcut = data
    else:
        shortcut = _conv_bn(data, num_filter, (1, 1), stride, (0, 0),
                            name + "_sc", act=False, layout=layout)
    return sym.Activation(data=body + shortcut, act_type="relu")


def get_symbol(num_classes=1000, num_layers=50, image_shape="3,224,224",
               layout="NCHW", **kwargs):
    """layout="NHWC" builds the channels-last variant (data fed as NHWC):
    the TPU-preferred layout that enables the Pallas conv+BN-stats fusion
    (ops/pallas_fused.py). Weights are OIHW in both layouts, so checkpoints
    transfer."""
    if num_layers not in _DEPTH_CONFIGS:
        raise ValueError("resnet depth must be one of %s"
                         % sorted(_DEPTH_CONFIGS))
    block_type, units = _DEPTH_CONFIGS[num_layers]
    block = _basic_block if block_type == "basic" else _bottleneck_block
    widths = ([64, 128, 256, 512] if block_type == "basic"
              else [256, 512, 1024, 2048])
    if isinstance(image_shape, str):
        image_shape = tuple(int(x) for x in image_shape.split(","))
    small_input = image_shape[-1] <= 64

    data = sym.Variable("data")
    if small_input:  # CIFAR stem
        body = _conv_bn(data, 64, (3, 3), (1, 1), (1, 1), "stem",
                        layout=layout)
    else:            # ImageNet stem
        body = _conv_bn(data, 64, (7, 7), (2, 2), (3, 3), "stem",
                        layout=layout)
        body = sym.Pooling(data=body, kernel=(3, 3), stride=(2, 2),
                           pad=(1, 1), pool_type="max", layout=layout)

    for stage, (n_units, width) in enumerate(zip(units, widths)):
        for unit in range(n_units):
            stride = (1, 1) if (stage == 0 or unit > 0) else (2, 2)
            dim_match = unit > 0
            body = block(body, width, stride, dim_match,
                         "stage%d_unit%d" % (stage + 1, unit + 1),
                         layout=layout)

    pool = sym.Pooling(data=body, global_pool=True, kernel=(7, 7),
                       pool_type="avg", layout=layout, name="global_pool")
    flat = sym.Flatten(data=pool)
    fc = sym.FullyConnected(data=flat, num_hidden=num_classes, name="fc1")
    return sym.SoftmaxOutput(data=fc, name="softmax")
