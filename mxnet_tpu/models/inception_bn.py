"""Inception with BatchNorm (Ioffe & Szegedy 2015; ref: symbols/
inception-bn.py behavior — the reference's ImageNet workhorse)."""
from .. import symbol as sym


def _conv_factory(data, num_filter, kernel, stride=(1, 1), pad=(0, 0),
                  name=None):
    conv = sym.Convolution(data=data, num_filter=num_filter, kernel=kernel,
                           stride=stride, pad=pad, no_bias=True,
                           name="conv_%s" % name)
    bn = sym.BatchNorm(data=conv, fix_gamma=False, name="bn_%s" % name)
    return sym.Activation(data=bn, act_type="relu")


def _inception_a(data, n1, n3r, n3, d3r, d3, pool_type, np_, name):
    c1 = _conv_factory(data, n1, (1, 1), name="%s_1x1" % name)
    c3 = _conv_factory(data, n3r, (1, 1), name="%s_3x3r" % name)
    c3 = _conv_factory(c3, n3, (3, 3), pad=(1, 1), name="%s_3x3" % name)
    cd = _conv_factory(data, d3r, (1, 1), name="%s_d3x3r" % name)
    cd = _conv_factory(cd, d3, (3, 3), pad=(1, 1), name="%s_d3x3a" % name)
    cd = _conv_factory(cd, d3, (3, 3), pad=(1, 1), name="%s_d3x3b" % name)
    pool = sym.Pooling(data=data, kernel=(3, 3), stride=(1, 1), pad=(1, 1),
                       pool_type=pool_type)
    cp = _conv_factory(pool, np_, (1, 1), name="%s_proj" % name)
    return sym.Concat(c1, c3, cd, cp, name="ch_concat_%s" % name)


def _inception_b(data, n3r, n3, d3r, d3, name):
    c3 = _conv_factory(data, n3r, (1, 1), name="%s_3x3r" % name)
    c3 = _conv_factory(c3, n3, (3, 3), stride=(2, 2), pad=(1, 1),
                       name="%s_3x3" % name)
    cd = _conv_factory(data, d3r, (1, 1), name="%s_d3x3r" % name)
    cd = _conv_factory(cd, d3, (3, 3), pad=(1, 1), name="%s_d3x3a" % name)
    cd = _conv_factory(cd, d3, (3, 3), stride=(2, 2), pad=(1, 1),
                       name="%s_d3x3b" % name)
    pool = sym.Pooling(data=data, kernel=(3, 3), stride=(2, 2), pad=(1, 1),
                       pool_type="max")
    return sym.Concat(c3, cd, pool, name="ch_concat_%s" % name)


def get_symbol(num_classes=1000, **kwargs):
    data = sym.Variable("data")
    body = _conv_factory(data, 64, (7, 7), stride=(2, 2), pad=(3, 3),
                         name="1")
    body = sym.Pooling(data=body, kernel=(3, 3), stride=(2, 2), pad=(1, 1),
                       pool_type="max")
    body = _conv_factory(body, 64, (1, 1), name="2r")
    body = _conv_factory(body, 192, (3, 3), pad=(1, 1), name="2")
    body = sym.Pooling(data=body, kernel=(3, 3), stride=(2, 2), pad=(1, 1),
                       pool_type="max")
    body = _inception_a(body, 64, 64, 64, 64, 96, "avg", 32, "3a")
    body = _inception_a(body, 64, 64, 96, 64, 96, "avg", 64, "3b")
    body = _inception_b(body, 128, 160, 64, 96, "3c")
    body = _inception_a(body, 224, 64, 96, 96, 128, "avg", 128, "4a")
    body = _inception_a(body, 192, 96, 128, 96, 128, "avg", 128, "4b")
    body = _inception_a(body, 160, 128, 160, 128, 160, "avg", 128, "4c")
    body = _inception_a(body, 96, 128, 192, 160, 192, "avg", 128, "4d")
    body = _inception_b(body, 128, 192, 192, 256, "4e")
    body = _inception_a(body, 352, 192, 320, 160, 224, "avg", 128, "5a")
    body = _inception_a(body, 352, 192, 320, 192, 224, "max", 128, "5b")
    body = sym.Pooling(data=body, kernel=(7, 7), global_pool=True,
                       pool_type="avg")
    flat = sym.Flatten(data=body)
    fc = sym.FullyConnected(data=flat, num_hidden=num_classes, name="fc1")
    return sym.SoftmaxOutput(data=fc, name="softmax")
