"""Model zoo: symbol builders for the reference's flagship configs
(ref: example/image-classification/symbols/*.py, example/rnn).

Each ``get_symbol``-style factory returns a Symbol ready for Module; the
architectures are the standard published ones (LeCun'98 LeNet, He'15 ResNet,
Krizhevsky'12 AlexNet, Simonyan'14 VGG, Ioffe'15 Inception-BN), built
TPU-first: plain graph ops that XLA fuses, bfloat16-ready, no hand layout.
"""
from .lenet import get_symbol as lenet
from .mlp import get_symbol as mlp
from .resnet import get_symbol as resnet
from .alexnet import get_symbol as alexnet
from .vgg import get_symbol as vgg
from .inception_bn import get_symbol as inception_bn
from .transformer import get_symbol as transformer
from .ssd import get_symbol_train as ssd_train

_FACTORIES = {
    "transformer": transformer,
    "lenet": lenet,
    "mlp": mlp,
    "resnet": resnet,
    "alexnet": alexnet,
    "vgg": vgg,
    "inception-bn": inception_bn,
    # the TRAIN symbol (MultiBoxTarget matching + loss heads): the zoo
    # audits and the dispatch gate exercise training programs
    "ssd": ssd_train,
}


def get_symbol(network, **kwargs):
    """Factory by name (ref: example/image-classification/train_*.py
    --network flag)."""
    if network not in _FACTORIES:
        raise ValueError("unknown network %r; have %s"
                         % (network, sorted(_FACTORIES)))
    return _FACTORIES[network](**kwargs)
