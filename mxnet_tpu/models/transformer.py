"""Decoder-only transformer LM — the long-context flagship model.

Supersedes the reference's model-parallel LSTM as the long-sequence story
(ref pattern being replaced: example/model-parallel-lstm/lstm.py:48-112;
SURVEY.md §5): blockwise attention on one chip, ring or Ulysses sequence
parallelism over the mesh 'seq' axis (``seq_parallel`` attr on
MultiHeadAttention), data/tensor parallelism via the ambient mesh.

Pre-LN blocks: x + MHA(LN(x)); x + FFN(LN(x)); loss is per-position
softmax cross-entropy over the vocabulary.
"""
from .. import symbol as sym


def _ffn(x, embed, hidden, name):
    h = sym.Reshape(data=x, shape=(-1, embed))
    h = sym.FullyConnected(data=h, num_hidden=hidden, name=name + "_fc1")
    h = sym.Activation(data=h, act_type="relu")
    h = sym.FullyConnected(data=h, num_hidden=embed, name=name + "_fc2")
    return h


def get_symbol(vocab_size=256, embed=128, num_heads=4, num_layers=2,
               seq_len=128, ffn_hidden=None, causal=True, seq_parallel="",
               block_size=0, dropout=0.0, **kwargs):
    """Returns the LM symbol; data (batch, seq) int tokens, label
    (batch, seq) next-token ids."""
    ffn_hidden = ffn_hidden or 4 * embed
    data = sym.Variable("data")
    pos = sym.Variable("pos_embed_weight", shape=(seq_len, embed))
    tok = sym.Embedding(data=data, input_dim=vocab_size, output_dim=embed,
                        name="tok_embed")
    x = sym.broadcast_add(tok, sym.expand_dims(pos, axis=0))
    for i in range(num_layers):
        name = "layer%d" % i
        a = sym.LayerNorm(data=x, name=name + "_ln1")
        a = sym.MultiHeadAttention(data=a, num_heads=num_heads,
                                   causal=causal, seq_parallel=seq_parallel,
                                   block_size=block_size,
                                   name=name + "_attn")
        if dropout > 0:
            a = sym.Dropout(data=a, p=dropout)
        x = x + a
        f = sym.LayerNorm(data=x, name=name + "_ln2")
        f = _ffn(f, embed, ffn_hidden, name + "_ffn")
        f = sym.Reshape(data=f, shape=(-1, seq_len, embed))
        if dropout > 0:
            f = sym.Dropout(data=f, p=dropout)
        x = x + f
    x = sym.LayerNorm(data=x, name="final_ln")
    x = sym.Reshape(data=x, shape=(-1, embed))
    logits = sym.FullyConnected(data=x, num_hidden=vocab_size, name="lm_head")
    label = sym.Variable("softmax_label")
    label = sym.Reshape(data=label, shape=(-1,))
    return sym.SoftmaxOutput(data=logits, label=label, name="softmax")
