"""Decoder-only transformer LM — the long-context flagship model.

Supersedes the reference's model-parallel LSTM as the long-sequence story
(ref pattern being replaced: example/model-parallel-lstm/lstm.py:48-112;
SURVEY.md §5): blockwise attention on one chip, ring or Ulysses sequence
parallelism over the mesh 'seq' axis (``seq_parallel`` attr on
MultiHeadAttention), data/tensor parallelism via the ambient mesh, and —
with ``stack_layers=True`` — pipeline parallelism over the 'pipe' axis
(the TransformerStack op stacks per-layer weights along a leading stage
dimension for the GPipe schedule in parallel/pipeline.py).

Pre-LN blocks: x + MHA(LN(x)); x + FFN(LN(x)); loss is per-position
softmax cross-entropy over the vocabulary.
"""
from .. import symbol as sym
from ..base import MXNetError


def _ffn(x, embed, hidden, name):
    # flatten=False keeps (b, s, e) through both projections: the old
    # Reshape pair merged the batch and seq dims, which forces an
    # all-gather over 'seq' every scan trip on a composed data x seq mesh
    h = sym.FullyConnected(data=x, num_hidden=hidden, flatten=False,
                           name=name + "_fc1")
    h = sym.Activation(data=h, act_type="relu")
    h = sym.FullyConnected(data=h, num_hidden=embed, flatten=False,
                           name=name + "_fc2")
    return h


def _validate(vocab_size, embed, num_heads, num_layers, seq_len,
              ffn_hidden, max_seq_len, seq_parallel, block_size, dropout,
              stack_layers):
    """Build-time configuration validation with actionable errors — the
    training-side twin of DecodeLoop's serve-time rejections (a config
    that would silently clamp positions or gather garbage embeddings must
    fail HERE, not as a partitioner shape complaint three layers down)."""
    if vocab_size < 2:
        raise MXNetError(
            "transformer: vocab_size must be >= 2, got %d — the LM head "
            "and embedding table need a real vocabulary" % vocab_size)
    if seq_len < 1:
        raise MXNetError("transformer: seq_len must be >= 1, got %d"
                         % seq_len)
    if num_layers < 1:
        raise MXNetError("transformer: num_layers must be >= 1, got %d"
                         % num_layers)
    if embed % num_heads:
        raise MXNetError(
            "transformer: embed %d %% num_heads %d != 0 — the head dim "
            "must be integral (pick embed a multiple of num_heads)"
            % (embed, num_heads))
    if ffn_hidden < 1:
        raise MXNetError("transformer: ffn_hidden must be >= 1, got %d"
                         % ffn_hidden)
    if max_seq_len is not None and seq_len > max_seq_len:
        raise MXNetError(
            "transformer: seq_len %d exceeds the positional embedding "
            "table (%d rows) — positions past it would be silently "
            "clamped at serve time; raise max_seq_len or shorten seq_len"
            % (seq_len, max_seq_len))
    if block_size < 0 or block_size > seq_len:
        raise MXNetError(
            "transformer: block_size %d is outside [0, seq_len=%d] — 0 "
            "disables blocking, otherwise blocks must fit the sequence"
            % (block_size, seq_len))
    if block_size and seq_len % block_size:
        raise MXNetError(
            "transformer: seq_len %d %% block_size %d != 0 — blockwise "
            "attention needs equal blocks" % (seq_len, block_size))
    if not 0.0 <= dropout < 1.0:
        raise MXNetError("transformer: dropout must be in [0, 1), got %g"
                         % dropout)
    if stack_layers and seq_parallel:
        raise MXNetError(
            "transformer: stack_layers=True cannot combine with "
            "seq_parallel=%r — a pipeline stage body already runs inside "
            "shard_map, where the nested seq-parallel shard_map cannot "
            "be formed; pick 'pipe' OR 'seq' for the layer stack"
            % seq_parallel)
    if stack_layers and dropout > 0:
        raise MXNetError(
            "transformer: stack_layers=True does not support dropout — "
            "the stacked stage body is shared across layers; train the "
            "per-layer build or drop dropout")


def get_symbol(vocab_size=256, embed=128, num_heads=4, num_layers=2,
               seq_len=128, ffn_hidden=None, causal=True, seq_parallel="",
               block_size=0, dropout=0.0, max_seq_len=None,
               stack_layers=False, num_microbatches=0,
               preserve_shape=False, **kwargs):
    """Returns the LM symbol; data (batch, seq) int tokens, label
    (batch, seq) next-token ids.

    ``preserve_shape=True`` keeps the head rank-3 — (batch, seq, vocab)
    probabilities, label consumed as (batch, seq) — instead of the
    historical flattened (batch*seq, vocab) output: on a composed
    data x seq mesh the flatten merges two sharded dims, which costs an
    all-gather over 'seq' EVERY scan trip; the rank-3 head is
    gather-free. Metrics handle both layouts.

    ``max_seq_len`` decouples the positional-embedding table from the
    training window (the table gets ``max_seq_len`` rows; serve-time
    decode may then run past ``seq_len`` up to the table, mirroring
    DecodeLoop's max_len bound). ``stack_layers=True`` builds the layer
    stack as ONE TransformerStack op over (num_layers, ...) stacked
    weights — under an ambient mesh with a 'pipe' axis the stack runs
    the GPipe schedule (``num_microbatches`` 0 = one per stage).
    Token ids must lie in [0, vocab_size): out-of-range ids gather
    garbage embeddings silently on TPU — validate the tokenizer output
    (DecodeLoop.generate rejects them at serve time)."""
    ffn_hidden = ffn_hidden or 4 * embed
    _validate(vocab_size, embed, num_heads, num_layers, seq_len,
              ffn_hidden, max_seq_len, seq_parallel, block_size, dropout,
              stack_layers)
    table_rows = max_seq_len if max_seq_len is not None else seq_len
    data = sym.Variable("data")
    pos = sym.Variable("pos_embed_weight", shape=(table_rows, embed))
    if table_rows != seq_len:
        pos = sym.slice_axis(pos, axis=0, begin=0, end=seq_len)
    tok = sym.Embedding(data=data, input_dim=vocab_size, output_dim=embed,
                        name="tok_embed")
    x = sym.broadcast_add(tok, sym.expand_dims(pos, axis=0))
    if stack_layers:
        x = sym.TransformerStack(
            data=x, num_layers=num_layers, num_heads=num_heads,
            ffn_hidden=ffn_hidden, causal=causal, block_size=block_size,
            num_microbatches=num_microbatches, name="stack")
    else:
        for i in range(num_layers):
            name = "layer%d" % i
            a = sym.LayerNorm(data=x, name=name + "_ln1")
            a = sym.MultiHeadAttention(data=a, num_heads=num_heads,
                                       causal=causal,
                                       seq_parallel=seq_parallel,
                                       block_size=block_size,
                                       name=name + "_attn")
            if dropout > 0:
                a = sym.Dropout(data=a, p=dropout)
            x = x + a
            f = sym.LayerNorm(data=x, name=name + "_ln2")
            f = _ffn(f, embed, ffn_hidden, name + "_ffn")
            if dropout > 0:
                f = sym.Dropout(data=f, p=dropout)
            x = x + f
    x = sym.LayerNorm(data=x, name="final_ln")
    label = sym.Variable("softmax_label")
    if preserve_shape:
        # rank-3 head: (b, s, vocab) probabilities over the last dim with
        # the (b, s) label consumed directly — no batch x seq dim merge
        # anywhere, so the composed data x seq program carries no
        # resharding gather in its compiled loop (the flat default below
        # keeps the historical (b*s, vocab) output for existing callers)
        logits = sym.FullyConnected(data=x, num_hidden=vocab_size,
                                    flatten=False, name="lm_head")
        return sym.SoftmaxOutput(data=logits, label=label,
                                 preserve_shape=True, name="softmax")
    x = sym.Reshape(data=x, shape=(-1, embed))
    logits = sym.FullyConnected(data=x, num_hidden=vocab_size, name="lm_head")
    label = sym.Reshape(data=label, shape=(-1,))
    return sym.SoftmaxOutput(data=logits, label=label, name="softmax")
