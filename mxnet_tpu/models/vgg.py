"""VGG-11/13/16/19 (Simonyan & Zisserman 2014; ref: symbols/vgg.py behavior)."""
from .. import symbol as sym

_CONFIGS = {
    11: ((1, 64), (1, 128), (2, 256), (2, 512), (2, 512)),
    13: ((2, 64), (2, 128), (2, 256), (2, 512), (2, 512)),
    16: ((2, 64), (2, 128), (3, 256), (3, 512), (3, 512)),
    19: ((2, 64), (2, 128), (4, 256), (4, 512), (4, 512)),
}


def get_symbol(num_classes=1000, num_layers=16, batch_norm=False, **kwargs):
    if num_layers not in _CONFIGS:
        raise ValueError("vgg depth must be one of %s" % sorted(_CONFIGS))
    data = sym.Variable("data")
    net = data
    for stage, (n_convs, width) in enumerate(_CONFIGS[num_layers]):
        for i in range(n_convs):
            net = sym.Convolution(data=net, kernel=(3, 3), pad=(1, 1),
                                  num_filter=width,
                                  name="conv%d_%d" % (stage + 1, i + 1))
            if batch_norm:
                net = sym.BatchNorm(data=net, fix_gamma=False,
                                    name="bn%d_%d" % (stage + 1, i + 1))
            net = sym.Activation(data=net, act_type="relu")
        net = sym.Pooling(data=net, kernel=(2, 2), stride=(2, 2),
                          pool_type="max")
    net = sym.Flatten(data=net)
    net = sym.FullyConnected(data=net, num_hidden=4096, name="fc6")
    net = sym.Activation(data=net, act_type="relu")
    net = sym.Dropout(data=net, p=0.5)
    net = sym.FullyConnected(data=net, num_hidden=4096, name="fc7")
    net = sym.Activation(data=net, act_type="relu")
    net = sym.Dropout(data=net, p=0.5)
    net = sym.FullyConnected(data=net, num_hidden=num_classes, name="fc8")
    return sym.SoftmaxOutput(data=net, name="softmax")
