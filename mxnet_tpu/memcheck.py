"""memcheck: a static HBM analyzer for compiled step/serving programs.

The reference MXNet plans memory ahead of execution — NNVM's ``PlanMemory``
pass is a first-class pillar of the design and the paper credits it for
fitting larger models per device (arXiv:1512.01274, PAPER.md layer map #1);
TensorFlow makes the same argument for ahead-of-time buffer analysis
(arXiv:1605.08695). On the XLA substrate that plan exists too — the buffer
assignment of every compiled executable — but nothing in this stack audited
it: peak HBM was invisible until an OOM at full batch, and a regression
that silently doubles temp buffers passed every gate (tracecheck, PR 5,
audits the *semantics* of the program set; this module is its memory-side
complement and shares its :class:`~mxnet_tpu.tracecheck.Finding` framework,
suppressions and CLI shape).

``memcheck`` lowers AND compiles a program WITHOUT executing it — arguments
can be ``ShapeDtypeStruct``s, no buffer is ever allocated — and derives a
:class:`MemoryReport` from ``compiled.memory_analysis()`` plus the
scheduled-HLO view: peak HBM, argument/output/temp/alias bytes, and a
breakdown attributing the largest buffers to op paths and source provenance
(the same ``op_name``/``source_file`` metadata tracecheck's collective audit
reads).

Memory lint catalog (docs/static_analysis.md "Memory lints"):

==================  =====================================================
lint id             fires when
==================  =====================================================
``hbm-budget``      a program's peak HBM exceeds ``MXTPU_MEMCHECK_BUDGET``
                    (default derived from the device's ``bytes_limit``,
                    16 GiB when the backend reports none)
``donation-waste``  a donated input's bytes are NOT realized as alias
                    savings — the buffer is copied, so donation bought
                    nothing (the memory-side complement of tracecheck's
                    ``donation`` lint: that one says "not aliased", this
                    one accounts the wasted bytes per argument)
``temp-blowup``     temp bytes exceed ``MXTPU_MEMCHECK_TEMP_MULT`` (4.0)
                    times the argument+output estimate — the signature of
                    a rematerialization/fusion regression
``resident-set``    the co-resident footprint of a program SET — all
                    serving buckets of one engine, or the guard-on +
                    guard-off train programs — exceeds the budget. jit
                    caches keep every executable reachable, so their
                    temps are all retained: resident =
                    max(arg+out-alias) (state/params are shared, donated
                    buffers counted once) + sum(temp)
==================  =====================================================

CLI::

    python -m mxnet_tpu.memcheck --zoo                    # audit the zoo
    python -m mxnet_tpu.memcheck --models mlp,lenet --json
    python -m mxnet_tpu.memcheck --zoo --write-baseline MEMCHECK_baseline.json
    python -m mxnet_tpu.memcheck --zoo --baseline MEMCHECK_baseline.json

The ``--baseline`` mode is the CI regression gate (``ci/memcheck.sh``):
every zoo program's peak/temp bytes are compared against the committed
baseline with a tolerance band (``MXTPU_MEMCHECK_TOL``, default 10%) — any
program growing past tolerance fails with the buffer breakdown in the
message. Exit status is non-zero iff any unsuppressed finding or baseline
regression remains.
"""
from __future__ import annotations

import json
import re

import numpy as np

from .base import MXNetError, env_str
from .tracecheck import (Finding, MEM_LINTS, _is_suppressed,
                         unsuppressed, ZOO)

__all__ = [
    "MemoryReport", "analyze", "analyze_compiled", "lint_report",
    "lint_resident_set", "resident_bytes", "check_program",
    "check_registered", "check_train_step", "check_zoo",
    "compare_baseline", "write_baseline",
    "device_budget", "budget_bytes", "temp_multiple", "tolerance", "main",
    "MEM_LINTS",
]

#: fallback budget when the backend reports no ``bytes_limit`` (CPU): the
#: v5e HBM size — the chip this stack's perf story is written against
_DEFAULT_BUDGET = 16 << 30

#: ignore donation waste below this (a stray unaliased scalar — e.g. a
#: step counter returned transformed — is not worth a red gate)
_WASTE_FLOOR = 1024


def _parse_bytes(v, name):
    """Parse a byte count: plain number (int/float/scientific) or a
    K/M/G/T binary suffix (``MXTPU_MEMCHECK_BUDGET=12G``)."""
    v = str(v).strip()
    if not v:
        return None
    m = re.match(r"^([0-9.eE+\-]+)\s*([kKmMgGtT]?)i?[bB]?$", v)
    try:
        num = float(m.group(1)) if m else None
    except ValueError:
        num = None
    if num is None or num < 0:
        raise MXNetError("%s must be a byte count (optionally suffixed "
                         "K/M/G/T), got %r" % (name, v))
    scale = {"": 1, "k": 1 << 10, "m": 1 << 20,
             "g": 1 << 30, "t": 1 << 40}[m.group(2).lower()]
    return int(num * scale)


def _env_bytes(name):
    return _parse_bytes(env_str(name), name)


def device_budget(device=None):
    """Per-device HBM budget derivation (docs/static_analysis.md "Memory
    lints"): the backend's reported ``bytes_limit`` when it has one (TPU),
    else 16 GiB."""
    import jax
    device = device or jax.devices()[0]
    try:
        stats = device.memory_stats() or {}
    except Exception:
        stats = {}
    limit = stats.get("bytes_limit")
    return int(limit) if limit else _DEFAULT_BUDGET


def budget_bytes(device=None):
    """Effective peak-HBM budget: ``MXTPU_MEMCHECK_BUDGET`` (bytes, K/M/G/T
    suffixes accepted) or :func:`device_budget`."""
    env = _env_bytes("MXTPU_MEMCHECK_BUDGET")
    return env if env is not None else device_budget(device)


def temp_multiple():
    """``temp-blowup`` threshold: temps may be at most this multiple of the
    argument+output bytes (``MXTPU_MEMCHECK_TEMP_MULT``, default 4.0)."""
    from .base import env_float
    return env_float("MXTPU_MEMCHECK_TEMP_MULT", 4.0)


def tolerance():
    """Baseline-gate tolerance band (``MXTPU_MEMCHECK_TOL``, default 0.1 =
    10% growth allowed per program per metric)."""
    from .base import env_float
    return env_float("MXTPU_MEMCHECK_TOL", 0.1)


# ---------------------------------------------------------------------------
# scheduled-HLO parsing: shapes, aliasing, buffer attribution
# ---------------------------------------------------------------------------

#: bit widths of HLO element types (pred buffers are byte-addressed)
_DTYPE_BITS = {
    "pred": 8, "s4": 4, "u4": 4, "s8": 8, "u8": 8,
    "f8e4m3fn": 8, "f8e5m2": 8, "f8e4m3b11fnuz": 8, "f8e4m3fnuz": 8,
    "f8e5m2fnuz": 8,
    "s16": 16, "u16": 16, "f16": 16, "bf16": 16,
    "s32": 32, "u32": 32, "f32": 32,
    "s64": 64, "u64": 64, "f64": 64, "c64": 64, "c128": 128,
}

# one instruction: `%name = f32[8,64]{1,0} opcode(...)`
_INSTR_RE = re.compile(
    r"^\s*(?:ROOT\s+)?%(?P<instr>[\w.\-]+)\s*=\s*"
    r"(?P<dtype>[a-z][a-z0-9]*)\[(?P<dims>[0-9,]*)\](?:\{[^}]*\})?\s+"
    r"(?P<opcode>[\w\-]+)\(")
# computation headers: `%fused_computation (...) -> ... {` / `ENTRY %main ...`
_COMP_RE = re.compile(r"^(?P<entry>ENTRY\s+)?%(?P<name>[\w.\-]+)\s*\(.*\{\s*$")
# op_name may contain escaped quotes: op_name="state[\'p\']"
_OPNAME_RE = re.compile(r'op_name="((?:[^"\\]|\\.)*)"')
_SOURCE_RE = re.compile(r'source_file="([^"]+)"\s+source_line=(\d+)')
# input_output_alias={ {0}: (0, {}, may-alias), {1}: (1, {}, may-alias) }
_ALIAS_MAP_RE = re.compile(r"input_output_alias=\{(?P<body>.*?)\}\s*,?\s*"
                           r"entry_computation_layout", re.S)
_ALIAS_ENTRY_RE = re.compile(r"\{[0-9,\s]*\}:\s*\((\d+),")
_PARAM_RE = re.compile(r"parameter\((\d+)\)")

#: opcodes whose "output" is a view of an existing buffer, not a new one —
#: attributing bytes to them would double-count the real producer
_VIEW_OPCODES = frozenset({"get-tuple-element", "bitcast", "tuple"})


def _shape_bytes(dtype, dims):
    bits = _DTYPE_BITS.get(dtype)
    if bits is None:
        return 0
    n = 1
    for d in dims.split(","):
        d = d.strip()
        if d:
            n *= int(d)
    return (n * bits) // 8


def _unescape(s):
    return s.replace("\\'", "'").replace('\\"', '"')


def parse_hlo_buffers(hlo_text):
    """Walk the scheduled HLO text of a compiled program and return
    ``(buffers, entry_params, aliased_params)``:

    * ``buffers`` — one dict per buffer-producing instruction (fusion
      internals and pure views skipped) with ``bytes``, ``opcode``,
      ``instruction``, ``op_path`` (the op_name metadata — nesting through
      ``while`` bodies visible, same convention as tracecheck) and
      ``provenance`` (``file:line``), sorted largest first;
    * ``entry_params`` — ``{param_number: (label, bytes)}`` for the entry
      computation's parameters (jax labels them with the argument path,
      e.g. ``state['p']``);
    * ``aliased_params`` — parameter numbers the lowering aliased to an
      output (successful donation), from the ``input_output_alias`` header.
    """
    buffers, entry_params, aliased = [], {}, set()
    m = _ALIAS_MAP_RE.search(hlo_text)
    if m:
        for e in _ALIAS_ENTRY_RE.finditer(m.group("body")):
            aliased.add(int(e.group(1)))
    in_entry = False
    in_fusion = False
    for line in hlo_text.splitlines():
        cm = _COMP_RE.match(line)
        if cm:
            in_entry = bool(cm.group("entry"))
            in_fusion = cm.group("name").startswith("fused_computation")
            continue
        im = _INSTR_RE.match(line)
        if not im:
            continue
        nbytes = _shape_bytes(im.group("dtype"), im.group("dims"))
        opcode = im.group("opcode")
        if opcode == "parameter" and in_entry:
            pm = _PARAM_RE.search(line)
            if pm:
                op = _OPNAME_RE.search(line)
                label = _unescape(op.group(1)) if op else None
                entry_params[int(pm.group(1))] = (label, nbytes)
        if in_fusion or opcode in _VIEW_OPCODES or not nbytes:
            continue
        if opcode == "parameter" and not in_entry:
            continue  # sub-computation params alias their call operands
        op = _OPNAME_RE.search(line)
        src = _SOURCE_RE.search(line)
        buffers.append({
            "bytes": nbytes,
            "opcode": opcode,
            "instruction": im.group("instr"),
            "op_path": _unescape(op.group(1)) if op else None,
            "provenance": ("%s:%s" % (src.group(1), src.group(2))
                           if src else None),
        })
    buffers.sort(key=lambda b: b["bytes"], reverse=True)
    return buffers, entry_params, aliased


# ---------------------------------------------------------------------------
# the report
# ---------------------------------------------------------------------------

def _fmt_bytes(n):
    if n is None:
        return "?"
    for unit, div in (("GiB", 1 << 30), ("MiB", 1 << 20), ("KiB", 1 << 10)):
        if n >= div:
            return "%.2f %s" % (n / div, unit)
    return "%d B" % n


class MemoryReport(object):
    """Static memory profile of ONE compiled program.

    ``peak_bytes`` is the program's high-water HBM estimate:
    ``argument + output + temp - alias`` (an aliased/donated buffer is
    counted once, not as both input and output — XLA's own accounting).
    ``top_buffers`` attributes the largest individual buffers to op paths
    and source provenance."""

    __slots__ = ("program", "platform", "argument_bytes", "output_bytes",
                 "temp_bytes", "alias_bytes", "generated_code_bytes",
                 "top_buffers", "donated", "unaliased_donated")

    def __init__(self, program, platform, argument_bytes, output_bytes,
                 temp_bytes, alias_bytes, generated_code_bytes=0,
                 top_buffers=(), donated=(), unaliased_donated=()):
        self.program = program
        self.platform = platform
        self.argument_bytes = int(argument_bytes)
        self.output_bytes = int(output_bytes)
        self.temp_bytes = int(temp_bytes)
        self.alias_bytes = int(alias_bytes)
        self.generated_code_bytes = int(generated_code_bytes)
        self.top_buffers = list(top_buffers)
        #: [(label, bytes)] of donated argument leaves
        self.donated = list(donated)
        #: [(label, bytes)] donated leaves the lowering did NOT alias
        self.unaliased_donated = list(unaliased_donated)

    @property
    def peak_bytes(self):
        return (self.argument_bytes + self.output_bytes + self.temp_bytes
                - self.alias_bytes)

    @property
    def donated_bytes(self):
        return sum(b for _, b in self.donated)

    @property
    def wasted_donation_bytes(self):
        return sum(b for _, b in self.unaliased_donated)

    def as_dict(self):
        return {
            "program": self.program,
            "platform": self.platform,
            "peak_bytes": self.peak_bytes,
            "argument_bytes": self.argument_bytes,
            "output_bytes": self.output_bytes,
            "temp_bytes": self.temp_bytes,
            "alias_bytes": self.alias_bytes,
            "generated_code_bytes": self.generated_code_bytes,
            "donated_bytes": self.donated_bytes,
            "wasted_donation_bytes": self.wasted_donation_bytes,
            "top_buffers": self.top_buffers,
        }

    def breakdown(self, top=5):
        """Human-readable largest-buffer attribution, one line each."""
        lines = []
        for b in self.top_buffers[:top]:
            where = b["op_path"] or b["instruction"]
            if b["provenance"]:
                where += " @ " + b["provenance"]
            lines.append("%10s  %-16s %s"
                         % (_fmt_bytes(b["bytes"]), b["opcode"], where))
        return lines

    def format(self):
        return ("%s: peak %s (args %s + out %s + temp %s - alias %s)"
                % (self.program, _fmt_bytes(self.peak_bytes),
                   _fmt_bytes(self.argument_bytes),
                   _fmt_bytes(self.output_bytes),
                   _fmt_bytes(self.temp_bytes),
                   _fmt_bytes(self.alias_bytes)))

    def __repr__(self):
        return "MemoryReport(%s)" % self.format()


def _donated_leaves(args, kwargs, donate_argnums):
    """Flat-leaf index -> (label, bytes, keystr) bookkeeping for the
    donated positional args. The flat order matches the entry parameter
    numbering UNLESS the lowering pruned an unused argument (e.g. the RNG
    key of an rng-free step) — so :func:`analyze_compiled` aligns by the
    HLO's own parameter labels first and falls back to position."""
    import jax
    donated = {}
    offset = 0
    for i, a in enumerate(args):
        leaves = jax.tree_util.tree_flatten_with_path(a)[0]
        for j, (path, leaf) in enumerate(leaves):
            if i in (donate_argnums or ()):
                nbytes = int(np.prod(getattr(leaf, "shape", ()) or (1,))
                             * np.dtype(leaf.dtype).itemsize) \
                    if hasattr(leaf, "dtype") else 0
                ks = jax.tree_util.keystr(path)
                donated[offset + j] = (
                    "args[%d]%s" % (i, ks), nbytes, ks)
        offset += len(leaves)
    offset += len(jax.tree_util.tree_leaves(dict(kwargs or {})))
    return donated, offset


_IDENT_RE = re.compile(r"^[A-Za-z_][A-Za-z0-9_]*")


def _label_keystr(label):
    """The pytree-path part of an HLO entry-parameter label: jax labels
    parameters ``<argname><keystr>`` (``state['opt']['fc1_weight']``) —
    strip the leading identifier so donated leaves can be matched by
    keystr regardless of the function's parameter name."""
    if not label:
        return None
    m = _IDENT_RE.match(label)
    return label[m.end():] if m else None


def analyze_compiled(compiled, name, args=(), kwargs=None,
                     donate_argnums=(), top=8):
    """Build a :class:`MemoryReport` from an ALREADY-compiled program
    (``jax.stages.Compiled`` — e.g. a serving bucket executable). Never
    executes anything."""
    import jax
    ma = compiled.memory_analysis()
    try:
        hlo_text = compiled.as_text()
    except Exception:
        hlo_text = ""
    buffers, entry_params, aliased = parse_hlo_buffers(hlo_text or "")
    donated, total = _donated_leaves(args, kwargs, donate_argnums)
    # map each donated leaf to its HLO parameter number by LABEL keystr
    # first (robust to the lowering pruning an unused argument, which
    # shifts every later position), positionally only when labels cannot
    # disambiguate AND nothing was pruned
    by_keystr = {}
    for pnum, (plabel, _pb) in entry_params.items():
        ks = _label_keystr(plabel)
        if ks is not None:
            by_keystr.setdefault(ks, []).append(pnum)
    pruned = bool(entry_params) and len(entry_params) != total
    # a waste claim needs parseable aliasing EVIDENCE: if the HLO text was
    # unavailable/unparseable (no alias entries found even though the
    # compiler reports alias savings), claiming every donated leaf wasted
    # would fail healthy deploys under MXTPU_MEMCHECK=error
    evidence = bool(hlo_text) and (bool(aliased)
                                   or ma.alias_size_in_bytes == 0)
    donated_sizes, unaliased = [], []
    for idx, (label, nbytes, ks) in sorted(donated.items()):
        cands = by_keystr.get(ks, ())
        if len(cands) == 1:
            pnum = cands[0]
        elif pruned:
            continue  # cannot align this leaf — claim nothing about it
        else:
            pnum = idx
        if pnum in entry_params:
            plabel, pbytes = entry_params[pnum]
            label = plabel or label
            nbytes = pbytes or nbytes
        donated_sizes.append((label, nbytes))
        if evidence and pnum not in aliased:
            unaliased.append((label, nbytes))
    return MemoryReport(
        name, jax.devices()[0].platform,
        argument_bytes=ma.argument_size_in_bytes,
        output_bytes=ma.output_size_in_bytes,
        temp_bytes=ma.temp_size_in_bytes,
        alias_bytes=ma.alias_size_in_bytes,
        generated_code_bytes=ma.generated_code_size_in_bytes,
        top_buffers=buffers[:top],
        donated=donated_sizes,
        unaliased_donated=unaliased)


def analyze(fn, args=(), kwargs=None, donate_argnums=(), name=None, top=8):
    """Lower AND compile ``fn`` (never executed — args may be
    ``ShapeDtypeStruct``s) and return its :class:`MemoryReport`.

    ``fn`` may be a jitted function (its own donation settings are kept —
    pass ``donate_argnums`` anyway so the per-argument waste accounting
    knows which leaves were meant to alias) or a plain callable (wrapped in
    ``jax.jit(fn, donate_argnums=...)``)."""
    import jax
    kwargs = dict(kwargs or {})
    if name is None:
        name = getattr(fn, "__name__", None) or repr(fn)
    jitted = fn if hasattr(fn, "lower") \
        else jax.jit(fn, donate_argnums=donate_argnums or ())
    compiled = jitted.lower(*args, **kwargs).compile()
    return analyze_compiled(compiled, name, args=args, kwargs=kwargs,
                            donate_argnums=donate_argnums, top=top)


# ---------------------------------------------------------------------------
# lints
# ---------------------------------------------------------------------------

def _top_attr(report, skip_params=False):
    """(op_path, provenance) of the report's largest attributable buffer —
    the thing a budget/temp finding should point at."""
    for b in report.top_buffers:
        if skip_params and b["opcode"] == "parameter":
            continue
        return b["op_path"] or b["instruction"], b["provenance"]
    return None, None


def lint_report(report, budget=None, temp_mult=None, waste_floor=None):
    """Per-program memory lints over one :class:`MemoryReport`:
    ``hbm-budget``, ``donation-waste``, ``temp-blowup``. Returns findings
    with suppressions applied (like ``tracecheck.check_program``)."""
    findings = []
    budget = budget_bytes() if budget is None else int(budget)
    temp_mult = temp_multiple() if temp_mult is None else float(temp_mult)
    waste_floor = _WASTE_FLOOR if waste_floor is None else int(waste_floor)
    name = report.program

    if report.peak_bytes > budget:
        op_path, prov = _top_attr(report)
        findings.append(Finding(
            "hbm-budget", name,
            "peak HBM %s exceeds the budget %s (args %s + out %s + temp %s"
            " - alias %s; MXTPU_MEMCHECK_BUDGET). Largest buffers:\n  %s"
            % (_fmt_bytes(report.peak_bytes), _fmt_bytes(budget),
               _fmt_bytes(report.argument_bytes),
               _fmt_bytes(report.output_bytes),
               _fmt_bytes(report.temp_bytes),
               _fmt_bytes(report.alias_bytes),
               "\n  ".join(report.breakdown())),
            op_path=op_path, provenance=prov))

    for label, nbytes in report.unaliased_donated:
        if nbytes < waste_floor:
            continue
        findings.append(Finding(
            "donation-waste", name,
            "donated argument %s (%s) is NOT aliased to any output — its "
            "bytes are copied, not saved; the program's working set carries "
            "both the old and the new buffer (alias savings realized: %s of "
            "%s donated)"
            % (label, _fmt_bytes(nbytes), _fmt_bytes(report.alias_bytes),
               _fmt_bytes(report.donated_bytes)),
            op_path=label))

    estimate = report.argument_bytes + report.output_bytes
    if estimate > 0 and report.temp_bytes > temp_mult * estimate:
        op_path, prov = _top_attr(report, skip_params=True)
        findings.append(Finding(
            "temp-blowup", name,
            "temp buffers %s are %.1fx the param+activation estimate %s "
            "(threshold %.1fx, MXTPU_MEMCHECK_TEMP_MULT) — a "
            "rematerialization/fusion regression. Largest buffers:\n  %s"
            % (_fmt_bytes(report.temp_bytes),
               report.temp_bytes / estimate, _fmt_bytes(estimate),
               temp_mult, "\n  ".join(report.breakdown())),
            op_path=op_path, provenance=prov))

    for f in findings:
        f.suppressed = _is_suppressed(f)
    return findings


def resident_bytes(reports):
    """Co-resident footprint of a program set: arguments/outputs are shared
    state (the same params/batch buffers feed every variant — take the
    max), but every executable's temp allocation stays reachable through
    the jit cache — sum them."""
    reports = list(reports)
    if not reports:
        return 0
    return (max(r.argument_bytes + r.output_bytes - r.alias_bytes
                for r in reports)
            + sum(r.temp_bytes for r in reports))


def lint_resident_set(reports, set_name, budget=None):
    """``resident-set``: the summed footprint of co-resident programs (all
    serving buckets of one engine; guard-on + guard-off train programs)
    against the budget."""
    reports = list(reports)
    budget = budget_bytes() if budget is None else int(budget)
    total = resident_bytes(reports)
    findings = []
    if reports and total > budget:
        biggest = max(reports, key=lambda r: r.temp_bytes)
        members = ", ".join(
            "%s (temp %s)" % (r.program, _fmt_bytes(r.temp_bytes))
            for r in reports)
        findings.append(Finding(
            "resident-set", set_name,
            "co-resident program set needs %s (> budget %s): jit caches "
            "keep every executable's buffers reachable — "
            "max(args+out-alias) + sum(temps) over [%s]. Largest temp "
            "holder: %s\n  %s"
            % (_fmt_bytes(total), _fmt_bytes(budget), members,
               biggest.program, "\n  ".join(biggest.breakdown())),
            op_path=biggest.program))
    for f in findings:
        f.suppressed = _is_suppressed(f)
    return findings


def check_program(fn, args=(), kwargs=None, donate_argnums=(), name=None,
                  budget=None, temp_mult=None):
    """Analyze + lint ONE program; returns ``(findings, report)``."""
    report = analyze(fn, args, kwargs, donate_argnums=donate_argnums,
                     name=name)
    return lint_report(report, budget=budget, temp_mult=temp_mult), report


def check_registered(match=None, budget=None, temp_mult=None,
                     resident_name=None):
    """Memory-audit live programs from the tracecheck registry whose name
    contains ``match`` (a string, or a tuple — contains ANY): per-program
    lints plus ONE ``resident-set`` lint over the whole matched set. This
    is the bucketed-cache audit (``BucketingModule.check(memory=True)``,
    docs/perf.md "Packed accumulators"): every bucket shape's compiled
    scan stays reachable in its jit cache, so the set's co-resident
    footprint — max(args+out) + sum(temps) — is what the budget must
    cover. Returns ``(findings, reports)``."""
    from .tracecheck import registered_programs
    if match is None:
        matches = None                  # audit EVERY registered program
    else:
        matches = (match,) if isinstance(match, str) else tuple(match)
        if not matches:
            # an explicitly EMPTY prefix set audits nothing: a
            # BucketingModule that never dispatched must not sweep (and
            # attribute a resident-set over) unrelated programs
            return [], {}
    findings = []
    reports = {}
    for rec in registered_programs():
        if matches is not None and not any(m in rec.name
                                           for m in matches):
            continue
        fn = rec.fn_ref()
        if fn is None:
            continue
        fs, rep = check_program(fn, rec.arg_structs,
                                donate_argnums=rec.donate_argnums,
                                name=rec.name, budget=budget,
                                temp_mult=temp_mult)
        findings += fs
        reports[rec.name] = rep
    findings += lint_resident_set(
        reports.values(),
        "%s/resident-set" % (resident_name or "registered"),
        budget=budget)
    return findings, reports


# ---------------------------------------------------------------------------
# TrainStep / zoo auditing (mirrors tracecheck.check_train_step)
# ---------------------------------------------------------------------------

def check_train_step(ts, data_shapes, label_shapes, k=2, guard=True,
                     name=None, budget=None, temp_mult=None):
    """Memory-audit a :class:`~mxnet_tpu.train_step.TrainStep`'s full
    program set — unguarded step, guarded step, K-step scan, guarded K-step
    scan (``tracecheck.train_step_programs``, THE shared recipe for what
    training dispatches) — plus the ``resident-set`` lint over the whole
    set (the guard-on and guard-off executables are co-resident in the jit
    caches). No step program ever executes. Returns ``(findings,
    reports)`` where ``reports`` maps program name ->
    :class:`MemoryReport`."""
    from .tracecheck import train_step_programs
    name = name or "TrainStep(%s)" % ts.symbol.name
    findings = []
    reports = {}
    for pname, jitfn, pargs in train_step_programs(
            ts, data_shapes, label_shapes, k=k, guard=guard, name=name):
        fs, rep = check_program(jitfn, pargs, donate_argnums=(0,),
                                name=pname, budget=budget,
                                temp_mult=temp_mult)
        findings += fs
        reports[pname] = rep
    findings += lint_resident_set(reports.values(),
                                  "%s/resident-set" % name, budget=budget)
    return findings, reports


def check_zoo(names=None, k=2, guard=True, budget=None, temp_mult=None,
              log=None):
    """Memory-audit the model zoo's step programs (same configs as
    ``tracecheck.ZOO``); returns ``(findings, reports)``."""
    from .tracecheck import zoo_train_step
    names = list(names) if names else sorted(ZOO)
    findings = []
    reports = {}
    for mname in names:
        if mname not in ZOO:
            raise MXNetError("memcheck: unknown zoo model %r (have %s)"
                             % (mname, ", ".join(sorted(ZOO))))
        if log:
            log("memcheck: analyzing %s ..." % mname)
        ts, data_shapes, label_shapes = zoo_train_step(mname)
        fs, reps = check_train_step(
            ts, data_shapes, label_shapes,
            k=k, guard=guard, name=mname, budget=budget,
            temp_mult=temp_mult)
        findings += fs
        reports.update(reps)
    return findings, reports


# ---------------------------------------------------------------------------
# the baseline regression gate (ci/memcheck.sh)
# ---------------------------------------------------------------------------

#: metrics the baseline pins per program
_BASELINE_METRICS = ("peak_bytes", "temp_bytes")

#: absolute slack added to the tolerance band — the zoo programs are tiny
#: on purpose, and a 10% band around a 40 KiB program is measurement noise
_BASELINE_SLACK = 64 << 10


def write_baseline(reports, path, tol=None):
    """Write the committed baseline: per-program peak/temp bytes, keyed by
    platform (a CPU baseline must not gate a TPU run)."""
    import jax
    from .model import atomic_write_bytes
    data = {
        "platform": jax.devices()[0].platform,
        "tolerance": tolerance() if tol is None else float(tol),
        "programs": {
            name: {m: getattr(rep, m) for m in _BASELINE_METRICS}
            for name, rep in sorted(reports.items())},
    }
    atomic_write_bytes(path, (json.dumps(data, indent=2, sort_keys=True)
                              + "\n").encode())
    return data


def compare_baseline(reports, baseline, tol=None):
    """The regression gate: compare every report against the committed
    baseline. Returns ``(failures, notes)`` — ``failures`` are gate-red
    strings (program grew past the tolerance band, or is missing from the
    baseline), ``notes`` informational (program shrank well below
    baseline: refresh it; stale baseline entries). A platform-mismatched
    baseline produces one note and no failures — a CPU baseline cannot
    judge TPU numbers."""
    import jax
    if isinstance(baseline, str):
        with open(baseline) as f:
            baseline = json.load(f)
    if tol is None:
        # precedence: explicit arg > MXTPU_MEMCHECK_TOL env (the operator
        # loosening a gate run) > the baseline's stored band > 0.1
        from .base import env_float
        tol = env_float("MXTPU_MEMCHECK_TOL",
                        float(baseline.get("tolerance", 0.1)))
    else:
        tol = float(tol)
    platform = jax.devices()[0].platform
    failures, notes = [], []
    if baseline.get("platform") != platform:
        notes.append(
            "memcheck baseline was written on platform %r but this run is "
            "%r — skipping the regression gate (re-run --write-baseline on "
            "this platform to arm it)"
            % (baseline.get("platform"), platform))
        return failures, notes
    base_progs = dict(baseline.get("programs") or {})
    for name, rep in sorted(reports.items()):
        base = base_progs.pop(name, None)
        if base is None:
            failures.append(
                "%s: not in the baseline — a new program must be added "
                "deliberately (run `python -m mxnet_tpu.memcheck --zoo "
                "--write-baseline MEMCHECK_baseline.json` and commit the "
                "diff)" % name)
            continue
        for metric in _BASELINE_METRICS:
            b = int(base.get(metric, 0))
            cur = int(getattr(rep, metric))
            allowed = b + max(int(b * tol), _BASELINE_SLACK)
            if cur > allowed:
                failures.append(
                    "%s: %s grew %s -> %s (+%.1f%%, tolerance %.0f%% + "
                    "%s slack, MXTPU_MEMCHECK_TOL). Largest buffers:\n  %s"
                    % (name, metric, _fmt_bytes(b), _fmt_bytes(cur),
                       100.0 * (cur - b) / max(1, b), 100.0 * tol,
                       _fmt_bytes(_BASELINE_SLACK),
                       "\n  ".join(rep.breakdown())))
            elif b > _BASELINE_SLACK and cur < b - max(int(b * tol),
                                                       _BASELINE_SLACK):
                notes.append(
                    "%s: %s shrank %s -> %s — nice; refresh the baseline "
                    "to lock the win in"
                    % (name, metric, _fmt_bytes(b), _fmt_bytes(cur)))
    for name in sorted(base_progs):
        notes.append("baseline entry %r matches no audited program "
                     "(stale — refresh the baseline)" % name)
    return failures, notes


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------

def report_table(reports, out=None):
    import sys
    out = out or sys.stdout
    w = max([len(n) for n in reports] + [8])
    out.write("%-*s  %10s %10s %10s %10s %10s\n"
              % (w, "program", "peak", "args", "out", "temp", "alias"))
    for name in sorted(reports):
        r = reports[name]
        out.write("%-*s  %10s %10s %10s %10s %10s\n"
                  % (w, name, _fmt_bytes(r.peak_bytes),
                     _fmt_bytes(r.argument_bytes),
                     _fmt_bytes(r.output_bytes), _fmt_bytes(r.temp_bytes),
                     _fmt_bytes(r.alias_bytes)))


def main(argv=None):
    import argparse
    import sys
    from . import tracecheck as _tc
    p = argparse.ArgumentParser(
        prog="python -m mxnet_tpu.memcheck",
        description="Static HBM analyzer for compiled step programs: "
                    "peak/argument/temp/alias accounting, donation-waste "
                    "and budget lints, and the baseline regression gate "
                    "(docs/static_analysis.md \"Memory lints\").")
    p.add_argument("--zoo", action="store_true",
                   help="analyze every shipped model's step/scan programs")
    p.add_argument("--models", default=None,
                   help="comma-separated zoo subset (implies --zoo)")
    p.add_argument("--k", type=int, default=2,
                   help="scan depth for the K-step programs (default 2)")
    p.add_argument("--no-guard", action="store_true",
                   help="skip the guarded program variants")
    p.add_argument("--budget", default=None,
                   help="peak-HBM budget in bytes (K/M/G/T suffixes ok; "
                        "default MXTPU_MEMCHECK_BUDGET or the device)")
    p.add_argument("--temp-mult", type=float, default=None,
                   help="temp-blowup multiple (default "
                        "MXTPU_MEMCHECK_TEMP_MULT or 4.0)")
    p.add_argument("--baseline", default=None, metavar="FILE",
                   help="compare against a committed baseline (the CI "
                        "regression gate); exit non-zero on growth past "
                        "tolerance")
    p.add_argument("--write-baseline", default=None, metavar="FILE",
                   help="write the per-program baseline JSON and exit 0 "
                        "(skips the findings/baseline gate — refreshing "
                        "the baseline is a deliberate act)")
    p.add_argument("--tol", type=float, default=None,
                   help="baseline tolerance band (default "
                        "MXTPU_MEMCHECK_TOL, the baseline's own, or 0.1)")
    p.add_argument("--json", action="store_true", help="JSON output")
    p.add_argument("--list", action="store_true",
                   help="list zoo models and exit")
    p.add_argument("--quiet", action="store_true",
                   help="suppress progress lines")
    args = p.parse_args(argv)
    if args.list:
        for n in sorted(ZOO):
            print(n)
        return 0
    if not (args.zoo or args.models):
        p.error("nothing to check: pass --zoo or --models")
    names = ([s.strip() for s in args.models.split(",") if s.strip()]
             if args.models else None)
    log = (lambda m: None) if (args.quiet or args.json) \
        else (lambda m: print(m, file=sys.stderr))
    budget = (None if args.budget is None
              else _parse_bytes(args.budget, "--budget"))
    findings, reports = check_zoo(names=names, k=args.k,
                                  guard=not args.no_guard, budget=budget,
                                  temp_mult=args.temp_mult, log=log)
    if args.write_baseline:
        write_baseline(reports, args.write_baseline, tol=args.tol)
        log("memcheck: baseline written to %s (%d programs)"
            % (args.write_baseline, len(reports)))
        return 0
    failures, notes = [], []
    if args.baseline:
        failures, notes = compare_baseline(reports, args.baseline,
                                           tol=args.tol)
    bad = unsuppressed(findings)
    if args.json:
        import jax
        print(json.dumps({
            "platform": jax.devices()[0].platform,
            "budget_bytes": budget if budget is not None else budget_bytes(),
            "programs": {n: r.as_dict() for n, r in sorted(reports.items())},
            "findings": [f.as_dict() for f in findings],
            "suppressed": len(findings) - len(bad),
            "baseline_failures": failures,
            "baseline_notes": notes,
        }, indent=2))
    else:
        report_table(reports)
        _tc.report(findings)
        for n in notes:
            print("note: %s" % n)
        for f in failures:
            print("BASELINE REGRESSION: %s" % f)
        print("memcheck: %d finding(s) (%d suppressed), %d baseline "
              "regression(s) over %d program(s)"
              % (len(findings), len(findings) - len(bad), len(failures),
                 len(reports)))
    return 1 if (bad or failures) else 0


if __name__ == "__main__":
    import sys
    sys.exit(main())
