"""Standalone predictor (ref: include/mxnet/c_predict_api.h +
src/c_api/c_predict_api.cc, 334 LoC; amalgamation's MXNET_PREDICT_ONLY build).

Inference-only API over a saved checkpoint: load symbol JSON + params, bind
once, ``forward`` repeatedly. The reference ships this as a separate minimal
C API for mobile/embedded; here it is a thin class whose jitted forward is
the deployable artifact. The production serving tier —  AOT-compiled shape
buckets, dynamic batching, continuous decode — builds on the same helpers
and lives in :mod:`mxnet_tpu.serving` (docs/serving.md).
"""
from __future__ import annotations

from collections import OrderedDict

import numpy as np

from .base import MXNetError
from . import ndarray as nd
from . import symbol as sym
from .context import current_context

# loss heads → inference-time equivalent op on the head's data input
# (ref: c_predict_api binds the net for prediction; the loss ops' forward is
# label-independent, so stripping the head drops the label argument entirely)
_LOSS_HEADS = {
    "SoftmaxOutput": "SoftmaxActivation",
    "LogisticRegressionOutput": "sigmoid",
    "LinearRegressionOutput": "identity",
    "MAERegressionOutput": "identity",
    "SVMOutput": "identity",
    "MakeLoss": "identity",
    "IdentityAttachKLSparseReg": "identity",
}


def _strip_loss_heads(symbol):
    """Rewrite loss-head outputs to their inference transform so binding
    needs no label arrays (labels vanish from list_arguments)."""
    from .symbol import Symbol, _Node
    from .ops import registry as _reg
    new_outputs = []
    changed = False
    for node, idx in symbol._outputs:
        if (not node.is_variable) and node.op.name in _LOSS_HEADS:
            repl = _LOSS_HEADS[node.op.name]
            attrs = {}
            if repl == "SoftmaxActivation":
                from .base import attr_bool
                mo = attr_bool(node.attrs.get("multi_output", False), False)
                attrs["mode"] = "channel" if mo else "instance"
            new = _Node(_reg.get(repl), node.name, attrs,
                        [node.inputs[0]], node._user_attr)
            new_outputs.append((new, 0))
            changed = True
        else:
            new_outputs.append((node, idx))
    return Symbol(new_outputs) if changed else symbol


def load_symbol(symbol_json_or_file):
    """Accept a Symbol, a JSON string, or a path to a -symbol.json file
    (shared by Predictor and serving.ServingEngine)."""
    if isinstance(symbol_json_or_file, str):
        if symbol_json_or_file.lstrip().startswith("{"):
            return sym.load_json(symbol_json_or_file)
        return sym.load(symbol_json_or_file)
    return symbol_json_or_file


def load_param_dict(param_file_or_dict):
    """Split a saved-params file (or an already-loaded dict, with or without
    ``arg:``/``aux:`` prefixes) into (arg_params, aux_params)."""
    if isinstance(param_file_or_dict, str):
        loaded = nd.load(param_file_or_dict)
    else:
        loaded = param_file_or_dict
    arg_params = {}
    aux_params = {}
    for k, v in loaded.items():
        if k.startswith("arg:"):
            arg_params[k[4:]] = v
        elif k.startswith("aux:"):
            aux_params[k[4:]] = v
        else:
            arg_params[k] = v
    return arg_params, aux_params


def pick_partial_outputs(symbol, output_names):
    """Partial-output binding: group only the requested internal heads
    (ref: MXPredCreatePartialOut, c_predict_api.h:92-102)."""
    internals = symbol.get_internals()
    avail = internals.list_outputs()
    picked = []
    for key in output_names:
        cand = key if key in avail else key + "_output"
        if cand not in avail:
            raise MXNetError(
                "partial output %r not found (have e.g. %s)"
                % (key, avail[-5:]))
        picked.append(internals[avail.index(cand)])
    return sym.Group(picked)


def check_missing_params(symbol, input_names, arg_params, aux_params,
                         who="Predictor"):
    """Raise an MXNetError naming every parameter/auxiliary state the
    loaded dict does NOT cover. A typo'd or truncated key must fail loudly:
    silently zero-filling a weight serves garbage predictions."""
    missing = [n for n in symbol.list_arguments()
               if n not in input_names and n not in arg_params
               # a loss head outside _LOSS_HEADS keeps its label variable
               # in list_arguments(); labels are inputs, not checkpoint
               # parameters (the "<name>_label" default-naming convention)
               and not n.endswith("_label")]
    missing += ["aux:" + n for n in symbol.list_auxiliary_states()
                if n not in aux_params]
    if missing:
        raise MXNetError(
            "%s: checkpoint is missing parameter(s) %s — a stale or "
            "mismatched params file would serve garbage predictions "
            "(pass allow_missing=True to zero-fill deliberately)"
            % (who, sorted(missing)))


class Predictor(object):
    def __init__(self, symbol_json_or_file, param_file_or_dict, input_shapes,
                 ctx=None, output_names=None, allow_missing=False):
        ctx = ctx or current_context()
        self._symbol = _strip_loss_heads(load_symbol(symbol_json_or_file))
        if output_names:
            self._symbol = pick_partial_outputs(self._symbol, output_names)
        arg_params, aux_params = load_param_dict(param_file_or_dict)
        if not allow_missing:
            check_missing_params(self._symbol, set(input_shapes),
                                 arg_params, aux_params)
        self._input_names = list(input_shapes.keys())
        self._ctx = ctx
        self._arg_params = arg_params
        self._aux_params = aux_params
        # executors cached by the full input-shape tuple: alternating batch
        # sizes through reshape() reuse their executor instead of rebinding
        # (and re-jitting) on every flip — the serving batcher depends on
        # it. LRU-bounded: unquantized request sizes must not pin one
        # compiled program per distinct batch size forever.
        self._exec_cache = OrderedDict()
        self._executor = self._bind(
            {k: tuple(v) for k, v in input_shapes.items()})

    def _shape_key(self, input_shapes):
        return tuple(sorted((n, tuple(s)) for n, s in input_shapes.items()))

    #: executor-cache LRU bound (distinct input-shape tuples kept alive)
    _EXEC_CACHE_CAP = 16

    def _bind(self, input_shapes):
        key = self._shape_key(input_shapes)
        cached = self._exec_cache.get(key)
        if cached is not None:
            self._exec_cache.move_to_end(key)
            return cached
        arg_shapes, _, aux_shapes = self._symbol.infer_shape(**input_shapes)
        args = {}
        for name, shape in zip(self._symbol.list_arguments(), arg_shapes):
            if name in self._arg_params:
                p = self._arg_params[name]
                if tuple(p.shape) != tuple(shape):
                    raise MXNetError(
                        "bind changes parameter %s: %s -> %s (only input "
                        "shapes may change)" % (name, tuple(p.shape),
                                                tuple(shape)))
                args[name] = p
            else:
                args[name] = nd.zeros(shape)
        aux = {}
        for name, shape in zip(self._symbol.list_auxiliary_states(),
                               aux_shapes):
            if name in self._aux_params:
                a = self._aux_params[name]
                if tuple(a.shape) != tuple(shape):
                    raise MXNetError(
                        "bind changes auxiliary state %s: %s -> %s (only "
                        "input shapes may change)" % (name, tuple(a.shape),
                                                      tuple(shape)))
                aux[name] = a
            else:
                aux[name] = nd.zeros(shape)
        executor = self._symbol.bind(self._ctx, args, aux_states=aux)
        self._exec_cache[key] = executor
        while len(self._exec_cache) > self._EXEC_CACHE_CAP:
            self._exec_cache.popitem(last=False)
        return executor

    def reshape(self, input_shapes):
        """Rebind for new input shapes, keeping the loaded parameters —
        the MXPredReshape capability (a predictor serving variable batch
        sizes without reloading weights). Inputs not named keep their
        current shapes (the reference allows partial reshape). Executors
        are cached by the full input-shape tuple, so flipping between a
        set of batch sizes binds (and compiles) each shape once. Returns
        self."""
        full = {n: tuple(self._executor.arg_dict[n].shape)
                for n in self._input_names}
        unknown = set(input_shapes) - set(full)
        if unknown:
            raise MXNetError("reshape: unknown inputs %s (have %s)"
                             % (sorted(unknown), self._input_names))
        full.update({k: tuple(v) for k, v in input_shapes.items()})
        try:
            self._executor = self._bind(full)
        except MXNetError as e:
            # keep the historical reshape error contract
            raise MXNetError(str(e).replace("bind changes",
                                            "reshape changes"))
        self._input_names = list(full.keys())
        return self

    def forward(self, **inputs):
        feed = {}
        for k, v in inputs.items():
            if k not in self._input_names:
                raise MXNetError("unknown input %r (have %s)"
                                 % (k, self._input_names))
            feed[k] = (v if isinstance(v, nd.NDArray)
                       else nd.array(np.asarray(v)))
        self._executor.forward(is_train=False, **feed)
        return self

    def get_output(self, index=0):
        return self._executor.outputs[index]

    @property
    def outputs(self):
        return self._executor.outputs
