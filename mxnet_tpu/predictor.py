"""Standalone predictor (ref: include/mxnet/c_predict_api.h +
src/c_api/c_predict_api.cc, 334 LoC; amalgamation's MXNET_PREDICT_ONLY build).

Inference-only API over a saved checkpoint: load symbol JSON + params, bind
once, ``forward`` repeatedly. The reference ships this as a separate minimal
C API for mobile/embedded; here it is a thin class whose jitted forward is
the deployable artifact (export via jax.jit / AOT lowering).
"""
from __future__ import annotations

import numpy as np

from .base import MXNetError
from . import ndarray as nd
from . import symbol as sym
from .context import current_context


class Predictor(object):
    def __init__(self, symbol_json_or_file, param_file_or_dict, input_shapes,
                 ctx=None):
        ctx = ctx or current_context()
        if isinstance(symbol_json_or_file, str):
            if symbol_json_or_file.lstrip().startswith("{"):
                self._symbol = sym.load_json(symbol_json_or_file)
            else:
                self._symbol = sym.load(symbol_json_or_file)
        else:
            self._symbol = symbol_json_or_file
        # strip loss heads for inference when present (ref: c_predict picks
        # the network output)
        if isinstance(param_file_or_dict, str):
            loaded = nd.load(param_file_or_dict)
        else:
            loaded = param_file_or_dict
        arg_params = {}
        aux_params = {}
        for k, v in loaded.items():
            if k.startswith("arg:"):
                arg_params[k[4:]] = v
            elif k.startswith("aux:"):
                aux_params[k[4:]] = v
            else:
                arg_params[k] = v

        arg_shapes, _, aux_shapes = self._symbol.infer_shape(**input_shapes)
        arg_names = self._symbol.list_arguments()
        args = {}
        for name, shape in zip(arg_names, arg_shapes):
            if name in arg_params:
                args[name] = arg_params[name]
            else:
                args[name] = nd.zeros(shape)
        aux = {}
        for name, shape in zip(self._symbol.list_auxiliary_states(),
                               aux_shapes):
            aux[name] = aux_params.get(name, nd.zeros(shape))
        self._input_names = list(input_shapes.keys())
        self._executor = self._symbol.bind(ctx, args, aux_states=aux)

    def forward(self, **inputs):
        feed = {}
        for k, v in inputs.items():
            if k not in self._input_names:
                raise MXNetError("unknown input %r (have %s)"
                                 % (k, self._input_names))
            feed[k] = (v if isinstance(v, nd.NDArray)
                       else nd.array(np.asarray(v)))
        self._executor.forward(is_train=False, **feed)
        return self

    def get_output(self, index=0):
        return self._executor.outputs[index]

    @property
    def outputs(self):
        return self._executor.outputs
