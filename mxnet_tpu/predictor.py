"""Standalone predictor (ref: include/mxnet/c_predict_api.h +
src/c_api/c_predict_api.cc, 334 LoC; amalgamation's MXNET_PREDICT_ONLY build).

Inference-only API over a saved checkpoint: load symbol JSON + params, bind
once, ``forward`` repeatedly. The reference ships this as a separate minimal
C API for mobile/embedded; here it is a thin class whose jitted forward is
the deployable artifact (export via jax.jit / AOT lowering).
"""
from __future__ import annotations

import numpy as np

from .base import MXNetError
from . import ndarray as nd
from . import symbol as sym
from .context import current_context

# loss heads → inference-time equivalent op on the head's data input
# (ref: c_predict_api binds the net for prediction; the loss ops' forward is
# label-independent, so stripping the head drops the label argument entirely)
_LOSS_HEADS = {
    "SoftmaxOutput": "SoftmaxActivation",
    "LogisticRegressionOutput": "sigmoid",
    "LinearRegressionOutput": "identity",
    "MAERegressionOutput": "identity",
    "SVMOutput": "identity",
    "MakeLoss": "identity",
    "IdentityAttachKLSparseReg": "identity",
}


def _strip_loss_heads(symbol):
    """Rewrite loss-head outputs to their inference transform so binding
    needs no label arrays (labels vanish from list_arguments)."""
    from .symbol import Symbol, _Node
    from .ops import registry as _reg
    new_outputs = []
    changed = False
    for node, idx in symbol._outputs:
        if (not node.is_variable) and node.op.name in _LOSS_HEADS:
            repl = _LOSS_HEADS[node.op.name]
            attrs = {}
            if repl == "SoftmaxActivation":
                from .base import attr_bool
                mo = attr_bool(node.attrs.get("multi_output", False), False)
                attrs["mode"] = "channel" if mo else "instance"
            new = _Node(_reg.get(repl), node.name, attrs,
                        [node.inputs[0]], node._user_attr)
            new_outputs.append((new, 0))
            changed = True
        else:
            new_outputs.append((node, idx))
    return Symbol(new_outputs) if changed else symbol


class Predictor(object):
    def __init__(self, symbol_json_or_file, param_file_or_dict, input_shapes,
                 ctx=None, output_names=None):
        ctx = ctx or current_context()
        if isinstance(symbol_json_or_file, str):
            if symbol_json_or_file.lstrip().startswith("{"):
                self._symbol = sym.load_json(symbol_json_or_file)
            else:
                self._symbol = sym.load(symbol_json_or_file)
        else:
            self._symbol = symbol_json_or_file
        self._symbol = _strip_loss_heads(self._symbol)
        if output_names:
            # partial-output predictor: bind only the requested heads
            # (ref: MXPredCreatePartialOut, c_predict_api.h:92-102)
            internals = self._symbol.get_internals()
            avail = internals.list_outputs()
            picked = []
            for key in output_names:
                cand = key if key in avail else key + "_output"
                if cand not in avail:
                    raise MXNetError(
                        "partial output %r not found (have e.g. %s)"
                        % (key, avail[-5:]))
                picked.append(internals[avail.index(cand)])
            self._symbol = sym.Group(picked)
        if isinstance(param_file_or_dict, str):
            loaded = nd.load(param_file_or_dict)
        else:
            loaded = param_file_or_dict
        arg_params = {}
        aux_params = {}
        for k, v in loaded.items():
            if k.startswith("arg:"):
                arg_params[k[4:]] = v
            elif k.startswith("aux:"):
                aux_params[k[4:]] = v
            else:
                arg_params[k] = v

        arg_shapes, _, aux_shapes = self._symbol.infer_shape(**input_shapes)
        arg_names = self._symbol.list_arguments()
        args = {}
        for name, shape in zip(arg_names, arg_shapes):
            if name in arg_params:
                args[name] = arg_params[name]
            else:
                args[name] = nd.zeros(shape)
        aux = {}
        for name, shape in zip(self._symbol.list_auxiliary_states(),
                               aux_shapes):
            aux[name] = aux_params.get(name, nd.zeros(shape))
        self._input_names = list(input_shapes.keys())
        self._ctx = ctx
        self._arg_params = arg_params
        self._aux_params = aux_params
        self._executor = self._symbol.bind(ctx, args, aux_states=aux)

    def reshape(self, input_shapes):
        """Rebind for new input shapes, keeping the loaded parameters —
        the MXPredReshape capability (a predictor serving variable batch
        sizes without reloading weights). Inputs not named keep their
        current shapes (the reference allows partial reshape). Returns
        self."""
        full = {n: tuple(self._executor.arg_dict[n].shape)
                for n in self._input_names}
        unknown = set(input_shapes) - set(full)
        if unknown:
            raise MXNetError("reshape: unknown inputs %s (have %s)"
                             % (sorted(unknown), self._input_names))
        full.update({k: tuple(v) for k, v in input_shapes.items()})
        input_shapes = full
        arg_shapes, _, aux_shapes = self._symbol.infer_shape(**input_shapes)
        args = {}
        for name, shape in zip(self._symbol.list_arguments(), arg_shapes):
            if name in self._arg_params:
                p = self._arg_params[name]
                if tuple(p.shape) != tuple(shape):
                    raise MXNetError(
                        "reshape changes parameter %s: %s -> %s (only input "
                        "shapes may change)" % (name, p.shape, shape))
                args[name] = p
            else:
                args[name] = nd.zeros(shape)
        aux = {}
        for name, shape in zip(self._symbol.list_auxiliary_states(),
                               aux_shapes):
            if name in self._aux_params:
                a = self._aux_params[name]
                if tuple(a.shape) != tuple(shape):
                    raise MXNetError(
                        "reshape changes auxiliary state %s: %s -> %s (only "
                        "input shapes may change)" % (name, a.shape, shape))
                aux[name] = a
            else:
                aux[name] = nd.zeros(shape)
        self._input_names = list(input_shapes.keys())
        self._executor = self._symbol.bind(self._ctx, args, aux_states=aux)
        return self

    def forward(self, **inputs):
        feed = {}
        for k, v in inputs.items():
            if k not in self._input_names:
                raise MXNetError("unknown input %r (have %s)"
                                 % (k, self._input_names))
            feed[k] = (v if isinstance(v, nd.NDArray)
                       else nd.array(np.asarray(v)))
        self._executor.forward(is_train=False, **feed)
        return self

    def get_output(self, index=0):
        return self._executor.outputs[index]

    @property
    def outputs(self):
        return self._executor.outputs
