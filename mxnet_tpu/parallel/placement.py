"""group2ctx model parallelism lowered to mesh shardings.

The reference implements model parallelism by *placement*: the PlaceDevice
pass colors each node with the device of its ``ctx_group`` and inserts
``_CrossDeviceCopy`` nodes at color boundaries
(ref: src/executor/graph_executor.cc:244-334, example/model-parallel-lstm/
lstm.py:48-112). That is an MPMD design for GPUs + NCCL.

XLA on TPU is SPMD: one program runs on every device and tensors are
*sharded*, not placed. The idiomatic lowering of ``group2ctx`` is therefore:

- each ctx_group maps to a **sharding spec** over the ambient device mesh;
- the graph runner applies ``jax.lax.with_sharding_constraint`` to every
  node output in the group — the exact analog of ``_CrossDeviceCopy``: XLA
  inserts the resharding collectives at group boundaries, riding ICI;
- parameters consumed by a group are allocated sharded with a matching spec,
  so each group's weight memory lives distributed across the mesh — the
  memory-capacity win that motivated layer-per-GPU placement.

Sharding constraints never change values (collectives are inserted to
preserve semantics), so a group2ctx-annotated model is numerically identical
to its single-device run — a property the reference could only approximate.

``group2ctx`` values accepted:

- mesh axis name (str), e.g. ``{'decode': 'model'}`` — outputs and params
  of the group are sharded over that axis on their last (outputs) / first
  (params) dimension divisible by the axis size;
- ``jax.sharding.PartitionSpec`` — applied verbatim to every output whose
  rank/shape admits it (non-divisible or rank-short outputs stay
  unconstrained);
- ``jax.sharding.NamedSharding`` — spec + explicit mesh;
- ``Context`` (legacy API, e.g. ``mx.gpu(1)``) — accepted for source
  compatibility; physical placement is XLA's job under SPMD, so this is
  recorded but lowers to no constraint.
"""
from __future__ import annotations

import jax
import numpy as np

from ..context import Context

P = jax.sharding.PartitionSpec


def _axis_size(mesh, names):
    """Total number of shards for one PartitionSpec entry (str or tuple)."""
    if isinstance(names, str):
        names = (names,)
    return int(np.prod([dict(zip(mesh.axis_names, mesh.devices.shape))[n]
                        for n in names]))


def _fit_spec(spec, shape, mesh):
    """Clip a PartitionSpec to a concrete shape: entries that don't divide
    their dimension (or exceed the rank) become None. Returns None if no
    dimension ends up sharded."""
    out = []
    any_sharded = False
    for d in range(len(shape)):
        e = spec[d] if d < len(spec) else None
        if e is None:
            out.append(None)
            continue
        if shape[d] % _axis_size(mesh, e) == 0:
            out.append(e)
            any_sharded = True
        else:
            out.append(None)
    return P(*out) if any_sharded else None


def _auto_spec(axis, shape, mesh, prefer_first=False):
    """Pick one dimension to shard over ``axis``: the last (or first, for
    parameters) dimension divisible by the axis size."""
    n = _axis_size(mesh, axis)
    dims = range(len(shape)) if prefer_first else reversed(range(len(shape)))
    for d in dims:
        if shape[d] > 1 and shape[d] % n == 0:
            return P(*([None] * d + [axis]))
    return None


def auto_spec(axis, shape, mesh, prefer_first=False):
    """Public spelling of the group2ctx auto-sharding rule (one dimension
    sharded over ``axis``; ``prefer_first=True`` is the parameter rule —
    first divisible dim, i.e. the OUTPUT dim of a (out, in) weight, so
    matmul contraction dims never split and sharded forwards stay bitwise
    with their single-chip runs). ``None`` when no dim divides. The
    serving tier shards checkpoints with this exact rule
    (docs/serving.md "Model-parallel replicas")."""
    return _auto_spec(axis, shape, mesh, prefer_first=prefer_first)


def _spec_axes(rule):
    """All mesh axis names a rule refers to."""
    if isinstance(rule, str):
        return [rule]
    out = []
    for e in rule:
        if e is None:
            continue
        out.extend([e] if isinstance(e, str) else list(e))
    return out


class GroupPlacement(object):
    """Resolved group2ctx: callable constraint per group + param specs."""

    def __init__(self, group2ctx, mesh):
        from ..base import MXNetError
        self.mesh = mesh
        self.raw = dict(group2ctx or {})   # as the user wrote it
        self.groups = {}        # name -> (rule, mesh) ; rule None = legacy
        for g, v in (group2ctx or {}).items():
            if isinstance(v, Context):
                # legacy device placement: under SPMD, XLA owns physical
                # placement; record the group so attrs round-trip
                self.groups[g] = (None, None)
                continue
            if isinstance(v, jax.sharding.NamedSharding):
                rule, gmesh = v.spec, v.mesh
            elif isinstance(v, (P, str)):
                rule, gmesh = v, mesh
            else:
                raise TypeError(
                    "group2ctx[%r]: expected Context, mesh axis name, "
                    "PartitionSpec or NamedSharding, got %r" % (g, v))
            if gmesh is None:
                raise MXNetError(
                    "group2ctx[%r] = %r needs a device mesh: pass mesh= or "
                    "bind inside `with MeshScope(mesh):`" % (g, v))
            bad = [a for a in _spec_axes(rule) if a not in gmesh.axis_names]
            if bad:
                raise MXNetError(
                    "group2ctx[%r]: axis %r not in mesh axes %r"
                    % (g, bad[0], tuple(gmesh.axis_names)))
            if self.mesh is None:
                self.mesh = gmesh
            elif gmesh is not self.mesh and (
                    tuple(gmesh.axis_names) != tuple(self.mesh.axis_names)
                    or gmesh.devices.shape != self.mesh.devices.shape):
                # one jit = one mesh: XLA cannot mix meshes in a computation
                raise MXNetError(
                    "group2ctx[%r]: NamedSharding mesh %r conflicts with the "
                    "binding mesh %r — all groups must share one mesh"
                    % (g, tuple(gmesh.axis_names),
                       tuple(self.mesh.axis_names)))
            self.groups[g] = (rule, gmesh)

    def _resolve_spec(self, group, shape, prefer_first=False):
        if group not in self.groups:
            return None, None
        rule, mesh = self.groups[group]
        if rule is None or mesh is None or len(shape) == 0:
            return None, None
        if isinstance(rule, str):
            return _auto_spec(rule, shape, mesh, prefer_first), mesh
        if prefer_first:
            # params: reuse the rule's first named axis, first divisible dim
            for e in rule:
                if e is not None:
                    ax = e if isinstance(e, str) else e[0]
                    return _auto_spec(ax, shape, mesh, True), mesh
            return None, None
        return _fit_spec(rule, shape, mesh), mesh

    def constrain(self, group, value, is_param=False):
        """with_sharding_constraint for one node value (trace-time).
        Parameters use the same first-dim rule as their allocation so the
        constraint confirms the resident layout instead of forcing a
        reshard every step."""
        spec, mesh = self._resolve_spec(group, getattr(value, "shape", ()),
                                        prefer_first=is_param)
        if spec is None:
            return value
        return jax.lax.with_sharding_constraint(
            value, jax.sharding.NamedSharding(mesh, spec))

    def param_spec(self, group, shape):
        """Sharding spec for a parameter consumed by ``group`` (first
        divisible dim — e.g. the (4H, D) LSTM i2h weight splits its gate
        dim across the axis, Megatron-style)."""
        spec, _ = self._resolve_spec(group, shape, prefer_first=True)
        return spec


def node_group(node):
    """The ctx_group annotation of a graph node (AttrScope(ctx_group=...))."""
    return node._user_attr.get("ctx_group")


def param_groups(nodes):
    """Map variable name -> ctx_group, from the variable's own annotation or
    (fallback) the single group of its consumers — mirrors how PlaceDevice
    propagates colors to inputs (ref: graph_executor.cc:244-334)."""
    out = {}
    consumers = {}
    for node in nodes:
        if node.is_variable:
            g = node_group(node)
            if g is not None:
                out[node.name] = g
            continue
        g = node_group(node)
        if g is None:
            continue
        for inp, _ in node.inputs:
            if inp.is_variable:
                consumers.setdefault(inp.name, set()).add(g)
    for name, gs in consumers.items():
        if name not in out and len(gs) == 1:
            out[name] = next(iter(gs))
    return out


def resolve(group2ctx, mesh=None):
    """Build a GroupPlacement (or None if there is nothing to do)."""
    if not group2ctx:
        return None
    if mesh is None:
        from .mesh import current_mesh
        mesh = current_mesh()
    gp = GroupPlacement(group2ctx, mesh)
    if gp.mesh is None:
        return None
    return gp
