"""SPMD parallelism over the TPU mesh.

This package is the TPU-native replacement for the reference's entire
distributed stack (ref: src/kvstore/, ps-lite, tools/launch.py — SURVEY.md
§2.4/§5): parameter-server push/pull becomes in-step XLA collectives over a
``jax.sharding.Mesh`` (psum for dist_sync gradient aggregation), launchers
become ``jax.distributed.initialize``, and model-parallel ``group2ctx``
placement becomes sharding annotations. Long-context parallelism (ring
attention / sequence parallel) lives in mxnet_tpu.parallel.ring.
"""
from .mesh import (make_mesh, data_parallel_mesh, current_mesh, MeshScope,
                   replicate, shard_batch, grad_sync, data_axis_size,
                   superbatch_sharding, parse_mesh_axes, mesh_from_spec,
                   check_axis_divides)
from . import ring  # noqa: F401
from . import placement  # noqa: F401
from .pipeline import pipeline_apply, pipeline_spmd  # noqa: F401
