"""Device-mesh helpers.

The reference enumerates devices by (device_type, dev_id) and hand-routes
communication (CommDevice GPU reduce, comm.h:211-373; ps-lite across hosts).
Here placement is declarative: build a Mesh with named axes — 'data' (dp),
'model' (tp), 'pipe' (pp), 'seq' (sp), 'expert' (ep) — annotate shardings,
and XLA inserts the collectives that ride ICI within a slice and DCN across
slices (the "How to Scale Your Model" recipe).
"""
from __future__ import annotations

import threading

import jax
import numpy as np

from ..base import MXNetError

P = jax.sharding.PartitionSpec

_scope = threading.local()

AXIS_DATA = "data"
AXIS_MODEL = "model"
AXIS_PIPE = "pipe"
AXIS_SEQ = "seq"
AXIS_EXPERT = "expert"


def make_mesh(axis_shapes, devices=None):
    """Create a Mesh from {'data': 4, 'model': 2, ...}.

    Axis order follows insertion order; total size must equal device count.
    """
    if devices is None:
        devices = jax.devices()
    names = tuple(axis_shapes.keys())
    shape = tuple(int(axis_shapes[n]) for n in names)
    n = int(np.prod(shape))
    if n != len(devices):
        if n < len(devices):
            devices = devices[:n]
        else:
            raise MXNetError("mesh needs %d devices, have %d"
                             % (n, len(devices)))
    arr = np.array(devices).reshape(shape)
    return jax.sharding.Mesh(arr, names)


def data_parallel_mesh(num=None, devices=None):
    if devices is None:
        devices = jax.devices()
    if num is not None:
        devices = devices[:num]
    return make_mesh({AXIS_DATA: len(devices)}, devices)


class MeshScope(object):
    """with MeshScope(mesh): — sets the ambient mesh for Module/KVStore."""

    def __init__(self, mesh):
        self.mesh = mesh

    def __enter__(self):
        self._old = getattr(_scope, "mesh", None)
        _scope.mesh = self.mesh
        return self.mesh

    def __exit__(self, *a):
        _scope.mesh = self._old


def current_mesh():
    return getattr(_scope, "mesh", None)


def replicate(tree, mesh):
    """device_put a pytree replicated over the mesh."""
    s = jax.sharding.NamedSharding(mesh, P())
    return jax.tree_util.tree_map(lambda x: jax.device_put(x, s), tree)


def shard_batch(tree, mesh, axis=AXIS_DATA):
    """device_put a pytree with dim-0 sharded along the given mesh axis."""
    def put(x):
        spec = P(axis) if getattr(x, "ndim", 0) >= 1 else P()
        return jax.device_put(x, jax.sharding.NamedSharding(mesh, spec))
    return jax.tree_util.tree_map(put, tree)


def grad_sync(grads, axis_name=AXIS_DATA):
    """Explicit gradient all-reduce for shard_map-style training steps —
    the dist_sync kv.push+pull semantics as one psum over ICI
    (ref: kvstore_dist.h sync mode; SURVEY.md §2.4)."""
    return jax.tree_util.tree_map(
        lambda g: jax.lax.psum(g, axis_name), grads)
