"""Device-mesh helpers.

The reference enumerates devices by (device_type, dev_id) and hand-routes
communication (CommDevice GPU reduce, comm.h:211-373; ps-lite across hosts).
Here placement is declarative: build a Mesh with named axes — 'data' (dp),
'model' (tp), 'pipe' (pp), 'seq' (sp), 'expert' (ep) — annotate shardings,
and XLA inserts the collectives that ride ICI within a slice and DCN across
slices (the "How to Scale Your Model" recipe).
"""
from __future__ import annotations

import threading

import jax
import numpy as np

from ..base import MXNetError

P = jax.sharding.PartitionSpec

_scope = threading.local()

AXIS_DATA = "data"
AXIS_MODEL = "model"
AXIS_PIPE = "pipe"
AXIS_SEQ = "seq"
AXIS_EXPERT = "expert"

#: every axis name a multi-axis training mesh may carry, in canonical
#: order (the order ``parse_mesh_axes`` normalizes specs into)
AXIS_NAMES = (AXIS_DATA, AXIS_MODEL, AXIS_PIPE, AXIS_SEQ, AXIS_EXPERT)


def make_mesh(axis_shapes, devices=None):
    """Create a Mesh from {'data': 4, 'model': 2, ...}.

    Axis order follows insertion order; total size must equal device count.
    """
    if devices is None:
        devices = jax.devices()
    names = tuple(axis_shapes.keys())
    shape = tuple(int(axis_shapes[n]) for n in names)
    n = int(np.prod(shape))
    if n != len(devices):
        if n < len(devices):
            devices = devices[:n]
        else:
            raise MXNetError("mesh needs %d devices, have %d"
                             % (n, len(devices)))
    arr = np.array(devices).reshape(shape)
    return jax.sharding.Mesh(arr, names)


def data_parallel_mesh(num=None, devices=None):
    if devices is None:
        devices = jax.devices()
    if num is not None:
        devices = devices[:num]
    return make_mesh({AXIS_DATA: len(devices)}, devices)


def model_parallel_mesh(num=None, devices=None):
    """One-axis 'model' mesh — the serving tier's bigger-than-one-chip
    substrate: a ServingEngine/DecodeLoop built over N contexts compiles
    each program with params sharded over this axis
    (docs/serving.md "Model-parallel replicas")."""
    if devices is None:
        devices = jax.devices()
    if num is not None:
        if num > len(devices):
            raise MXNetError(
                "model_parallel_mesh: %d devices requested, %d visible "
                "(on CPU, raise XLA_FLAGS=--xla_force_host_platform_"
                "device_count)" % (num, len(devices)))
        devices = devices[:num]
    return make_mesh({AXIS_MODEL: len(devices)}, devices)


def parse_mesh_axes(spec):
    """Parse a mesh-axes spec — ``"data=2,seq=4"`` or a ``{"data": 2,
    "seq": 4}`` dict — into an ordered ``{axis: size}`` dict (insertion
    order preserved; that order becomes the mesh axis order). Axis names
    must come from :data:`AXIS_NAMES`; sizes must be positive integers.
    Raises :class:`MXNetError` naming the offending token."""
    if isinstance(spec, dict):
        items = list(spec.items())
    else:
        items = []
        for tok in str(spec).split(","):
            tok = tok.strip()
            if not tok:
                continue
            if "=" not in tok:
                raise MXNetError(
                    "mesh axes spec %r: token %r is not 'axis=N' "
                    "(e.g. 'data=2,seq=4')" % (spec, tok))
            name, _, num = tok.partition("=")
            items.append((name.strip(), num.strip()))
    axes = {}
    for name, num in items:
        if name not in AXIS_NAMES:
            raise MXNetError(
                "mesh axes spec %r: unknown axis %r (valid: %s)"
                % (spec, name, ", ".join(AXIS_NAMES)))
        try:
            n = int(num)
        except (TypeError, ValueError):
            raise MXNetError("mesh axes spec %r: axis %r size %r is not "
                             "an integer" % (spec, name, num))
        if n < 1:
            raise MXNetError("mesh axes spec %r: axis %r size must be "
                             ">= 1, got %d" % (spec, name, n))
        if name in axes:
            raise MXNetError("mesh axes spec %r: axis %r given twice"
                             % (spec, name))
        axes[name] = n
    if not axes:
        raise MXNetError("mesh axes spec %r names no axes" % (spec,))
    return axes


def mesh_from_spec(spec, devices=None):
    """Build a multi-axis Mesh from a spec (:func:`parse_mesh_axes`
    accepts strings and dicts) over the first ``prod(sizes)`` visible
    devices. A device shortfall fails actionably with the
    ``XLA_FLAGS`` recipe instead of :func:`make_mesh`'s bare count."""
    axes = parse_mesh_axes(spec)
    if devices is None:
        devices = jax.devices()
    need = int(np.prod(list(axes.values())))
    if need > len(devices):
        raise MXNetError(
            "mesh %s needs %d devices but only %d are visible — on CPU "
            "raise the count with XLA_FLAGS=--xla_force_host_platform_"
            "device_count=%d"
            % ("x".join("%s=%d" % kv for kv in axes.items()), need,
               len(devices), need))
    return make_mesh(axes, list(devices)[:need])


def check_axis_divides(mesh, axis, value, what):
    """Divisibility precheck for one mesh axis: ``value`` (the dimension
    the axis will shard) must divide evenly over the axis. Raises
    :class:`MXNetError` NAMING the failing axis and the offending
    dimension — the error a user can act on, instead of the XLA
    partitioner's shape complaint three layers down. No-op when the mesh
    lacks the axis (size 1 divides everything)."""
    n = data_axis_size(mesh, axis)
    if n > 1 and int(value) % n:
        raise MXNetError(
            "%s %d does not divide the %d-way %r mesh axis — every shard "
            "must be equal (pad %s or pick a size divisible by %d)"
            % (what, int(value), n, axis, what, n))


class MeshScope(object):
    """with MeshScope(mesh): — sets the ambient mesh for Module/KVStore."""

    def __init__(self, mesh):
        self.mesh = mesh

    def __enter__(self):
        self._old = getattr(_scope, "mesh", None)
        _scope.mesh = self.mesh
        return self.mesh

    def __exit__(self, *a):
        _scope.mesh = self._old


def current_mesh():
    return getattr(_scope, "mesh", None)


def replicate(tree, mesh):
    """device_put a pytree replicated over the mesh."""
    s = jax.sharding.NamedSharding(mesh, P())
    return jax.tree_util.tree_map(lambda x: jax.device_put(x, s), tree)


def shard_batch(tree, mesh, axis=AXIS_DATA):
    """device_put a pytree with dim-0 sharded along the given mesh axis."""
    def put(x):
        spec = P(axis) if getattr(x, "ndim", 0) >= 1 else P()
        return jax.device_put(x, jax.sharding.NamedSharding(mesh, spec))
    return jax.tree_util.tree_map(put, tree)


def shard_map_compat(f, mesh=None, in_specs=None, out_specs=None,
                     check_vma=None, **kw):
    """``jax.shard_map`` across jax versions: new jax exposes it at the top
    level with a ``check_vma`` kwarg; this build (0.4.x) only has
    ``jax.experimental.shard_map.shard_map`` with ``check_rep``. One shim so
    the ring/Ulysses/pipeline code runs on both instead of failing on the
    rename."""
    if hasattr(jax, "shard_map"):
        if check_vma is not None:
            kw["check_vma"] = check_vma
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, **kw)
    from jax.experimental.shard_map import shard_map as _sm
    if check_vma is not None:
        kw["check_rep"] = check_vma
    return _sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kw)


def axis_size(axis_name):
    """Static size of a mapped mesh axis from inside a shard_map body —
    ``jax.lax.axis_size`` where it exists (newer jax), else ``psum(1)``,
    which folds to a concrete int at trace time on 0.4.x."""
    if hasattr(jax.lax, "axis_size"):
        return jax.lax.axis_size(axis_name)
    return int(jax.lax.psum(1, axis_name))


def data_axis_size(mesh, axis=AXIS_DATA):
    """Number of shards along the mesh's data axis (1 when absent) — the
    divisor every global batch dimension must honor."""
    if mesh is None or axis not in mesh.axis_names:
        return 1
    return int(mesh.shape[axis])


def superbatch_sharding(mesh, axis=AXIS_DATA, seq=False):
    """NamedSharding for stacked (k, batch, ...) superbatch arrays: the
    step axis replicated, the batch axis sharded along ``axis``. This is
    the sharding ``SuperBatchIter`` lands its H2D with, so each chip
    receives only its own batch shard and the dispatch loop never pays a
    resharding copy (the dist_sync data partition, one level up: the unit
    is a whole K-step dispatch).

    ``seq=True`` additionally splits dim 2 (the token dim of a stacked
    (k, batch, seq) LM batch) over the 'seq' axis when the mesh carries
    one — the multi-axis variant; only valid when EVERY array the
    sharding will land is rank >= 3 stacked (SuperBatchIter applies one
    sharding to all slots)."""
    if mesh is None:
        return None
    if seq and AXIS_SEQ in mesh.axis_names:
        bax = axis if axis in mesh.axis_names else None
        return jax.sharding.NamedSharding(mesh, P(None, bax, AXIS_SEQ))
    if axis not in mesh.axis_names:
        return None
    return jax.sharding.NamedSharding(mesh, P(None, axis))


def is_multiprocess(mesh):
    """True when the mesh spans more than one jax process (multi-host)."""
    if mesh is None:
        return False
    return len({d.process_index for d in mesh.devices.flat}) > 1


def global_data_mesh(axis_name=AXIS_DATA, local_devices=None):
    """Mesh over devices of ALL processes along one data axis — the
    dist_sync substrate: batch shards ride 'data' across hosts and XLA's
    gradient psum rides DCN/ICI (the ps-lite replacement, SURVEY §2.4).

    ``local_devices`` restricts the mesh to the given devices of THIS
    process plus the same positions on every other process (workers are
    assumed symmetric — the reference's assumption too: every worker runs
    the same script with the same device list)."""
    devices = jax.devices()  # global list, all processes
    if local_devices is not None:
        mine = jax.local_devices()
        keep = sorted({mine.index(d) for d in local_devices})
        by_proc = {}
        for d in devices:
            by_proc.setdefault(d.process_index, []).append(d)
        devices = [p_devs[i] for _, p_devs in sorted(by_proc.items())
                   for i in keep if i < len(p_devs)]
    return jax.sharding.Mesh(np.array(devices), (axis_name,))


def host_to_global(mesh, spec, local_value):
    """Build a global jax.Array from per-process host data.

    For dims sharded across processes ``local_value`` is THIS process's
    portion (e.g. its batch shard); for replicated specs every process
    passes the same full value.
    """
    s = jax.sharding.NamedSharding(mesh, spec)
    return jax.make_array_from_process_local_data(s, np.asarray(local_value))


def host_broadcast0(mesh, value):
    """Broadcast rank-0's host value to every process (returns a host
    array): the dist kvstore init semantics — one authoritative copy, like
    the reference server's single stored weight (ref: kvstore_dist_server.h).
    Implemented as a masked global sum so it rides the same collective path
    as everything else."""
    import jax.numpy as jnp
    me = jax.process_index()
    n_local = sum(1 for d in mesh.devices.flat if d.process_index == me)
    local = np.asarray(value)
    # only rank 0's FIRST device slot contributes the value — no division,
    # so integer dtypes survive and every rank builds the same-typed array
    zero = np.zeros_like(local)
    tile = np.stack([local if (me == 0 and j == 0) else zero
                     for j in range(n_local)])
    axis = mesh.axis_names[0]
    sharded = jax.sharding.NamedSharding(mesh, P(axis))
    repl = jax.sharding.NamedSharding(mesh, P())
    garr = jax.make_array_from_process_local_data(sharded, tile)
    out = jax.jit(lambda a: jnp.sum(a, axis=0), out_shardings=repl)(garr)
    return np.asarray(out)


def local_view(arr):
    """This process's slice of a global array, as one host-order array
    (the per-worker view of batch-sharded outputs: each worker computes
    metrics on its own shard, like the reference's per-worker eval)."""
    import jax.numpy as jnp
    if getattr(arr, "is_fully_addressable", True):
        return arr
    if arr.is_fully_replicated:
        return jnp.asarray(np.asarray(arr))
    shards = sorted(arr.addressable_shards,
                    key=lambda s: [sl.start or 0 for sl in s.index])
    return jnp.concatenate([s.data for s in shards], axis=0)


def grad_sync(grads, axis_name=AXIS_DATA):
    """Explicit gradient all-reduce for shard_map-style training steps —
    the dist_sync kv.push+pull semantics as one psum over ICI
    (ref: kvstore_dist.h sync mode; SURVEY.md §2.4)."""
    return jax.tree_util.tree_map(
        lambda g: jax.lax.psum(g, axis_name), grads)
