"""SPMD pipeline parallelism over the 'pipe' mesh axis.

The reference pipelines an LSTM across GPUs by *placing* each layer on its
own device and letting the dependency engine overlap timesteps
(ref: example/model-parallel-lstm/lstm.py:48-112,
docs/how_to/model_parallel_lstm.md). The TPU/SPMD formulation: stack the
per-stage parameters along a leading stage dimension sharded over the
'pipe' axis (one stage per device), split the batch into microbatches, and
run the classic GPipe schedule as a single ``lax.scan`` — on every tick all
stages compute in parallel on their in-flight microbatch, then activations
hop to the next stage via ``ppermute`` over neighbor ICI links. The bubble
is (S-1)/(S-1+M) and shrinks with more microbatches.

Requires all stages to share one structure (true for stacked LSTM/transformer
layers). Works inside jit/shard_map; differentiable, so the same schedule
serves training.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from jax.sharding import NamedSharding, PartitionSpec as P


def pipeline_spmd(stage_fn, stacked_params, microbatches, axis_name="pipe"):
    """Run microbatches through a pipeline of stages — call INSIDE shard_map.

    stage_fn(params, x) -> y        one stage's computation; y.shape == x.shape
    stacked_params: pytree whose leaves have leading dim 1 (this device's
        stage, i.e. the global (S, ...) stack sharded over ``axis_name``)
    microbatches: (M, ...) array, identical on every device (replicated)

    Returns (M, ...) outputs of the LAST stage, identical on every device.
    """
    from .mesh import axis_size
    S = axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    M = microbatches.shape[0]
    local_params = jax.tree_util.tree_map(lambda p: p[0], stacked_params)
    fwd = [(j, (j + 1) % S) for j in range(S)]
    zero = jnp.zeros_like(microbatches[0])

    def tick(carry, t):
        state, out_buf = carry
        # stage 0 ingests microbatch t (zeros once the feed is exhausted —
        # bubble ticks compute on garbage that is never read)
        feed = jax.lax.dynamic_index_in_dim(
            microbatches, jnp.minimum(t, M - 1), axis=0, keepdims=False)
        x = jnp.where(idx == 0, feed, state)
        y = stage_fn(local_params, x)
        # last stage banks its result at output slot t-(S-1)
        slot = jnp.clip(t - (S - 1), 0, M - 1)
        bank = jnp.logical_and(idx == S - 1, t >= S - 1)
        cur = jax.lax.dynamic_index_in_dim(out_buf, slot, 0, keepdims=False)
        out_buf = jax.lax.dynamic_update_index_in_dim(
            out_buf, jnp.where(bank, y, cur), slot, 0)
        # activations hop one stage forward around the ring
        state = jax.lax.ppermute(y, axis_name, fwd)
        return (state, out_buf), None

    out0 = jnp.zeros_like(microbatches)
    (_, out_buf), _ = jax.lax.scan(
        tick, (zero, out0), jnp.arange(S + M - 1))
    # only the last stage holds real outputs; share them with every stage
    mask = (idx == S - 1).astype(out_buf.dtype)
    return jax.lax.psum(out_buf * mask, axis_name)


def pipeline_apply(stage_fn, stacked_params, batch, mesh, axis_name="pipe",
                   num_microbatches=None, batch_axis=None):
    """jit-able wrapper: shard stacked params over ``axis_name``, split the
    batch into microbatches, run the GPipe schedule, and re-assemble.

    stacked_params leaves have leading dim S == mesh.shape[axis_name];
    batch is (B, ...) with B divisible by num_microbatches (default S).

    ``batch_axis`` composes pipeline with data parallelism on one mesh:
    when set (normally 'data'), each microbatch's batch dimension stays
    sharded over that axis inside the schedule — the pipe ring hops and
    the final psum ride ``axis_name`` only, so a data x pipe mesh runs
    dp shards of the same pipeline side by side.
    """
    from ..base import MXNetError
    S = dict(zip(mesh.axis_names, mesh.devices.shape))[axis_name]
    M = num_microbatches or S
    B = batch.shape[0]
    if B % M:
        raise MXNetError(
            "pipeline_apply: batch dim %d does not divide into %d "
            "microbatches over the %d-way %r mesh axis — pad the batch "
            "or pass a num_microbatches that divides it" % (B, M, S,
                                                            axis_name))
    for leaf in jax.tree_util.tree_leaves(stacked_params):
        if leaf.shape[0] != S:
            raise MXNetError(
                "pipeline_apply: stacked-parameter stage dim %d does not "
                "match the %d-way %r mesh axis — stack one stage per "
                "device (or reshape a layer stack to (stages, "
                "layers_per_stage, ...) before the call)"
                % (leaf.shape[0], S, axis_name))
    micro = batch.reshape((M, B // M) + batch.shape[1:])
    if batch_axis is not None:
        from .mesh import data_axis_size
        dp = data_axis_size(mesh, batch_axis)
        if (B // M) % dp:
            raise MXNetError(
                "pipeline_apply: microbatch dim %d does not divide the "
                "%d-way %r mesh axis — every shard must be equal"
                % (B // M, dp, batch_axis))
    bspec = P() if batch_axis is None else P(None, batch_axis)

    pspec = jax.tree_util.tree_map(lambda _: P(axis_name), stacked_params)
    from .mesh import shard_map_compat
    fn = shard_map_compat(
        functools.partial(pipeline_spmd, stage_fn, axis_name=axis_name),
        mesh=mesh,
        in_specs=(pspec, bspec),
        out_specs=bspec,
        check_vma=False)
    out = fn(stacked_params, micro)
    return out.reshape((B,) + out.shape[2:])
