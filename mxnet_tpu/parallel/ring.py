"""Ring attention / sequence-context parallelism over the ICI mesh.

The reference's only long-context stories are bucketing, fused RNN kernels and
layer-per-device model parallelism (SURVEY.md §5). This module supplies the
genuinely-new TPU pieces: blockwise ring attention (K/V rotate around the
'seq' mesh axis via ppermute while queries stay resident) and Ulysses-style
head-sharded attention (all-to-all). Round-1 scope: numerically-stable
blockwise attention core + single-host ring step; full multichip wiring lands
with the transformer/LSTM flagship.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


def _block_attn(q, k, v, m_prev, l_prev, acc, scale):
    """One blockwise-softmax accumulation step (log-sum-exp streaming)."""
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    m_cur = jnp.max(s, axis=-1)
    m_new = jnp.maximum(m_prev, m_cur)
    p = jnp.exp(s - m_new[..., None])
    l_corr = l_prev * jnp.exp(m_prev - m_new)
    l_new = l_corr + jnp.sum(p, axis=-1)
    acc = acc * jnp.exp(m_prev - m_new)[..., None] + jnp.einsum(
        "bhqk,bhkd->bhqd", p, v)
    return m_new, l_new, acc


def blockwise_attention(q, k, v, block_size=None, causal=False):
    """Memory-efficient attention via streaming softmax over K/V blocks.

    q,k,v: (batch, heads, seq, dim). Equivalent to softmax(qk^T/sqrt(d))v but
    never materializes the full (seq, seq) matrix — the single-chip half of
    ring attention.
    """
    b, h, sq, d = q.shape
    sk = k.shape[2]
    scale = 1.0 / (d ** 0.5)
    if block_size is None:
        block_size = min(512, sk)
    nblocks = (sk + block_size - 1) // block_size
    pad = nblocks * block_size - sk
    if pad:
        k = jnp.pad(k, ((0, 0), (0, 0), (0, pad), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, 0), (0, pad), (0, 0)))
    kb = k.reshape(b, h, nblocks, block_size, d)
    vb = v.reshape(b, h, nblocks, block_size, d)

    def body(carry, inputs):
        m, l, acc = carry
        (kblk, vblk, blk_idx) = inputs
        s = jnp.einsum("bhqd,bhkd->bhqk", q, kblk) * scale
        # mask padding and causal positions
        kpos = blk_idx * block_size + jnp.arange(block_size)
        pad_mask = kpos < sk
        mask = pad_mask[None, None, None, :]
        if causal:
            qpos = jnp.arange(sq)
            mask = mask & (kpos[None, :] <= qpos[:, None])[None, None]
        s = jnp.where(mask, s, -jnp.inf)
        m_cur = jnp.max(s, axis=-1)
        m_new = jnp.maximum(m, m_cur)
        m_safe = jnp.where(jnp.isneginf(m_new), 0.0, m_new)
        p = jnp.where(mask, jnp.exp(s - m_safe[..., None]), 0.0)
        corr = jnp.where(jnp.isneginf(m), 0.0, jnp.exp(m - m_safe))
        l_new = l * corr + jnp.sum(p, axis=-1)
        acc_new = acc * corr[..., None] + jnp.einsum("bhqk,bhkd->bhqd", p, vblk)
        return (m_new, l_new, acc_new), None

    # carries derived from q keep any shard_map varying manual axes
    m0 = jnp.full_like(q[..., 0], -jnp.inf)
    l0 = jnp.zeros_like(q[..., 0])
    acc0 = jnp.zeros_like(q)
    (m, l, acc), _ = jax.lax.scan(
        body, (m0, l0, acc0),
        (jnp.moveaxis(kb, 2, 0), jnp.moveaxis(vb, 2, 0), jnp.arange(nblocks)))
    return acc / jnp.maximum(l, 1e-20)[..., None]


def ring_attention(q, k, v, axis_name="seq", causal=False):
    """Ring attention inside shard_map over the 'seq' mesh axis: each device
    holds a sequence shard of q/k/v; K/V shards rotate via ppermute while the
    local q accumulates blockwise-softmax statistics. Communication rides ICI
    neighbor links — bandwidth-optimal for long context.
    """
    from .mesh import axis_size
    n = axis_size(axis_name)
    my = jax.lax.axis_index(axis_name)
    b, h, sq, d = q.shape
    scale = 1.0 / (d ** 0.5)
    sk = k.shape[2]

    def step(carry, i):
        m, l, acc, kr, vr = carry
        src_idx = (my - i) % n  # which shard we currently hold
        s = jnp.einsum("bhqd,bhkd->bhqk", q, kr) * scale
        if causal:
            qpos = my * sq + jnp.arange(sq)
            kpos = src_idx * sk + jnp.arange(sk)
            mask = (kpos[None, :] <= qpos[:, None])[None, None]
            s = jnp.where(mask, s, -jnp.inf)
        m_cur = jnp.max(s, axis=-1)
        m_new = jnp.maximum(m, m_cur)
        m_safe = jnp.where(jnp.isneginf(m_new), 0.0, m_new)
        p = jnp.exp(s - m_safe[..., None])
        if causal:
            p = jnp.where(mask, p, 0.0)
        corr = jnp.where(jnp.isneginf(m), 0.0, jnp.exp(m - m_safe))
        l_new = l * corr + jnp.sum(p, axis=-1)
        acc_new = acc * corr[..., None] + jnp.einsum("bhqk,bhkd->bhqd", p, vr)
        # rotate K/V to the next device around the ring
        perm = [(j, (j + 1) % n) for j in range(n)]
        kr = jax.lax.ppermute(kr, axis_name, perm)
        vr = jax.lax.ppermute(vr, axis_name, perm)
        return (m_new, l_new, acc_new, kr, vr), None

    # derive carries from q so they inherit the 'seq' varying manual axis
    # (shard_map requires scan carry in/out types to match)
    m0 = jnp.full_like(q[..., 0], -jnp.inf)
    l0 = jnp.zeros_like(q[..., 0])
    acc0 = jnp.zeros_like(q)
    (m, l, acc, _, _), _ = jax.lax.scan(
        step, (m0, l0, acc0, k, v), jnp.arange(n))
    return acc / jnp.maximum(l, 1e-20)[..., None]


def ulysses_attention(q, k, v, axis_name="seq", attn_fn=None):
    """Ulysses-style sequence parallelism: all-to-all converts sequence
    sharding into head sharding, full-sequence attention runs locally per
    head group, then the layout is restored."""
    from .mesh import axis_size
    n = axis_size(axis_name)

    def a2a(x, split_axis, concat_axis):
        return jax.lax.all_to_all(x, axis_name, split_axis=split_axis,
                                  concat_axis=concat_axis, tiled=True)

    # (b, h, s/n, d) -> (b, h/n, s, d)
    qh = a2a(q, 1, 2)
    kh = a2a(k, 1, 2)
    vh = a2a(v, 1, 2)
    if attn_fn is None:
        attn_fn = functools.partial(blockwise_attention)
    out = attn_fn(qh, kh, vh)
    # back to sequence sharding
    return a2a(out, 2, 1)
