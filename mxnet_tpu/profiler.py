"""Profiler (ref: python/mxnet/profiler.py; C++ engine profiler at
src/engine/profiler.{h,cc} emitting Chrome trace-event JSON).

TPU-native substrate: jax.profiler captures XLA device traces (XPlane /
TensorBoard format, which also opens in chrome://tracing-compatible viewers
via Perfetto). The reference API shape — set_config, set_state, dump — is
preserved; op names flow into the trace through jit scopes automatically.
MXNET_PROFILER_AUTOSTART honored (ref: src/initialize.cc).
"""
from __future__ import annotations

import os

import jax

from .base import MXNetError

_state = {"running": False, "dir": "profile_output", "mode": "symbolic"}


def profiler_set_config(mode="symbolic", filename="profile.json"):
    """Configure output location (ref: MXSetProfilerConfig). ``filename``'s
    directory becomes the trace dir (XPlane traces are directories)."""
    _state["mode"] = mode
    d = os.path.dirname(filename) or "."
    base = os.path.basename(filename)
    _state["dir"] = os.path.join(d, base.replace(".json", "_trace"))


def profiler_set_state(state="stop"):
    """'run' starts the jax trace; 'stop' ends and writes it
    (ref: MXSetProfilerState)."""
    if state == "run" and not _state["running"]:
        jax.profiler.start_trace(_state["dir"])
        _state["running"] = True
    elif state == "stop" and _state["running"]:
        jax.profiler.stop_trace()
        _state["running"] = False
    elif state not in ("run", "stop"):
        raise MXNetError("profiler state must be 'run' or 'stop'")


def dump_profile():
    """Finish the trace (ref: MXDumpProfile). XPlane output is written on
    stop; this stops a running trace."""
    if _state["running"]:
        profiler_set_state("stop")


class Scope(object):
    """Named trace annotation for user code regions."""

    def __init__(self, name):
        self._t = jax.profiler.TraceAnnotation(name)

    def __enter__(self):
        self._t.__enter__()
        return self

    def __exit__(self, *a):
        self._t.__exit__(*a)


if os.environ.get("MXNET_PROFILER_AUTOSTART", "0") == "1":
    profiler_set_state("run")
