"""Profiler (ref: python/mxnet/profiler.py; C++ engine profiler at
src/engine/profiler.{h,cc} emitting Chrome trace-event JSON).

TPU-native substrate: jax.profiler captures XLA device traces (XPlane /
TensorBoard format, which also opens in chrome://tracing-compatible viewers
via Perfetto). The reference API shape — set_config, set_state, dump — is
preserved; op names flow into the trace through jit scopes automatically.

``MXNET_PROFILER_AUTOSTART=1`` is honored (ref: src/initialize.cc) but
DEFERRED to the first dispatch: starting the device trace at import time
would race ``profiler_set_config`` — the trace would land in the default
directory before the program ever had a chance to point it elsewhere.
:func:`maybe_autostart` is called from the executor/fused-dispatch hot
paths (one boolean check once armed-or-done).

The HOST half of the timeline lives in :mod:`mxnet_tpu.obs`:
:class:`Scope` enters a ``jax.profiler.TraceAnnotation`` (device trace)
AND an ``obs.span`` (host trace) together, so one ``with`` covers both
sides of the Perfetto view (docs/observability.md).
"""
from __future__ import annotations

import os

import jax

from .base import MXNetError
from .obs import trace as _obs_trace

_state = {"running": False, "dir": "profile_output", "mode": "symbolic"}

#: MXNET_PROFILER_AUTOSTART seen at import: the trace starts at the FIRST
#: DISPATCH, after any profiler_set_config has run — never at import
_autostart_pending = (
    os.environ.get("MXNET_PROFILER_AUTOSTART", "0") == "1")


def profiler_set_config(mode="symbolic", filename="profile.json"):
    """Configure output location (ref: MXSetProfilerConfig). ``filename``'s
    directory becomes the trace dir (XPlane traces are directories)."""
    _state["mode"] = mode
    d = os.path.dirname(filename) or "."
    base = os.path.basename(filename)
    _state["dir"] = os.path.join(d, base.replace(".json", "_trace"))


def profiler_set_state(state="stop"):
    """'run' starts the jax trace; 'stop' ends and writes it
    (ref: MXSetProfilerState)."""
    global _autostart_pending
    if state == "run" and not _state["running"]:
        _autostart_pending = False  # an explicit start supersedes it
        jax.profiler.start_trace(_state["dir"])
        _state["running"] = True
    elif state == "stop" and _state["running"]:
        jax.profiler.stop_trace()
        _state["running"] = False
    elif state not in ("run", "stop"):
        raise MXNetError("profiler state must be 'run' or 'stop'")


def maybe_autostart():
    """First-dispatch hook: start the deferred MXNET_PROFILER_AUTOSTART
    trace, AFTER any profiler_set_config has had its say. Near-zero cost
    once resolved (one module-global boolean check)."""
    global _autostart_pending
    if _autostart_pending:
        _autostart_pending = False
        profiler_set_state("run")


def dump_profile():
    """Finish the trace (ref: MXDumpProfile). XPlane output is written on
    stop; this stops a running trace."""
    if _state["running"]:
        profiler_set_state("stop")


class Scope(object):
    """Named trace annotation for user code regions — on BOTH timelines:
    the device trace (``jax.profiler.TraceAnnotation`` threads the name
    into the XPlane track) and the host trace (an ``obs.span`` complete
    event), so one ``with profiler.Scope("epoch3")`` brackets the same
    region in Perfetto's device and host views side by side."""

    def __init__(self, name, **args):
        self._t = jax.profiler.TraceAnnotation(name)
        self._name = name
        self._args = args
        self._span = None

    def __enter__(self):
        self._span = _obs_trace.span(self._name, **self._args)
        self._span.__enter__()
        self._t.__enter__()
        return self

    def __exit__(self, *a):
        try:
            self._t.__exit__(*a)
        finally:
            self._span.__exit__(*a)
            self._span = None
