"""Plugins (ref: plugin/{torch,caffe,warpctc,opencv,sframe} — SURVEY.md §2.7).

- torch: embed PyTorch modules as operators (ref: plugin/torch TorchModule) —
  see mxnet_tpu.plugin.torch_module.
- warpctc: the CTC loss is first-class contrib here (mx.sym.CTCLoss).
- opencv: image ops live in mxnet_tpu.image (Pillow-backed).
- caffe/sframe: not reproduced — Caffe-era interop with no TPU users;
  documented gap rather than a stub that pretends to work.
"""
from . import torch_module  # noqa: F401
