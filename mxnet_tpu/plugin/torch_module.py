"""Torch plugin: run PyTorch modules as operators
(ref: plugin/torch/torch_module.cc TorchModule/TorchCriterion, which embedded
Lua Torch layers; here the embed target is PyTorch-CPU via the CustomOp
host-callback path).

Example::

    import torch.nn as tnn
    op = TorchModule(tnn.Linear(4, 3))
    y = op(mx.nd.ones((2, 4)))          # imperative
    s = op.get_symbol(mx.sym.Variable("data"))   # symbolic, differentiable
"""
from __future__ import annotations

import numpy as np

from ..base import MXNetError
from .. import operator as mxop

_TORCH_COUNTER = [0]


class TorchModule(object):
    """Wrap a torch.nn.Module as an operator. Parameters live inside the
    torch module (host-side); gradients flow through to the mxnet graph
    inputs via torch autograd inside the callback."""

    def __init__(self, module):
        try:
            import torch  # noqa: F401
        except ImportError as e:
            raise MXNetError("TorchModule requires torch: %s" % e)
        self.module = module
        _TORCH_COUNTER[0] += 1
        self._reg_name = "_torch_module_%d" % _TORCH_COUNTER[0]
        self._register()

    def _register(self):
        import torch
        mod = self.module

        class _TorchOp(mxop.CustomOp):
            def forward(self, is_train, req, in_data, out_data, aux):
                x = torch.from_numpy(np.ascontiguousarray(
                    in_data[0].asnumpy()))
                with torch.no_grad():
                    y = mod(x)
                self.assign(out_data[0], req[0], y.numpy())

            def backward(self, req, out_grad, in_data, out_data, in_grad,
                         aux):
                x = torch.from_numpy(np.ascontiguousarray(
                    in_data[0].asnumpy())).requires_grad_(True)
                y = mod(x)
                g = torch.from_numpy(np.ascontiguousarray(
                    out_grad[0].asnumpy()))
                y.backward(g)
                self.assign(in_grad[0], req[0], x.grad.numpy())

        class _TorchProp(mxop.CustomOpProp):
            def __init__(self):
                super().__init__(need_top_grad=True)

            def list_arguments(self):
                return ["data"]

            def list_outputs(self):
                return ["output"]

            def infer_shape(self, in_shape):
                x = torch.zeros(*in_shape[0])
                with torch.no_grad():
                    y = mod(x)
                return in_shape, [list(y.shape)], []

            def create_operator(self, ctx, shapes, dtypes):
                return _TorchOp()

        mxop.register(self._reg_name)(lambda **kw: _TorchProp())

    def __call__(self, x):
        from .. import ndarray as nd
        return nd.Custom(x, op_type=self._reg_name)

    def get_symbol(self, data, name=None):
        from .. import symbol as sym
        return sym.Custom(data=data, op_type=self._reg_name, name=name)


class TorchCriterion(object):
    """Wrap a torch loss (ref: TorchCriterion): forward computes the loss,
    backward emits d(loss)/d(input) like the reference loss layers."""

    def __init__(self, criterion):
        try:
            import torch  # noqa: F401
        except ImportError as e:
            raise MXNetError("TorchCriterion requires torch: %s" % e)
        self.criterion = criterion
        _TORCH_COUNTER[0] += 1
        self._reg_name = "_torch_criterion_%d" % _TORCH_COUNTER[0]
        self._register()

    def _register(self):
        import torch
        crit = self.criterion

        class _CritOp(mxop.CustomOp):
            def forward(self, is_train, req, in_data, out_data, aux):
                x = torch.from_numpy(np.ascontiguousarray(
                    in_data[0].asnumpy()))
                t = torch.from_numpy(np.ascontiguousarray(
                    in_data[1].asnumpy()))
                with torch.no_grad():
                    loss = crit(x, t)
                self.assign(out_data[0], req[0],
                            np.asarray([float(loss)], np.float32))

            def backward(self, req, out_grad, in_data, out_data, in_grad,
                         aux):
                x = torch.from_numpy(np.ascontiguousarray(
                    in_data[0].asnumpy())).requires_grad_(True)
                t = torch.from_numpy(np.ascontiguousarray(
                    in_data[1].asnumpy()))
                loss = crit(x, t)
                loss.backward()
                self.assign(in_grad[0], req[0], x.grad.numpy())
                self.assign(in_grad[1], req[1],
                            np.zeros_like(in_data[1].asnumpy()))

        class _CritProp(mxop.CustomOpProp):
            def __init__(self):
                super().__init__(need_top_grad=False)

            def list_arguments(self):
                return ["data", "label"]

            def list_outputs(self):
                return ["loss"]

            def infer_shape(self, in_shape):
                return in_shape, [[1]], []

            def create_operator(self, ctx, shapes, dtypes):
                return _CritOp()

        mxop.register(self._reg_name)(lambda **kw: _CritProp())

    def __call__(self, data, label):
        from .. import ndarray as nd
        return nd.Custom(data, label, op_type=self._reg_name)
