"""Module: the standard computation module over one symbol
(ref: python/mxnet/module/module.py, 708 LoC — bind :323, init_optimizer
:432-510, update :553-569, checkpoint :97-156/:674-704).

Data parallelism over multiple contexts is SPMD: the executor group builds a
jax Mesh and shards the batch axis (see executor_group.py); kvstore semantics
(update_on_kvstore vs local updater) follow model.py:40-117.
"""
from __future__ import annotations

import logging

from ..base import MXNetError
from ..context import Context, cpu
from ..initializer import Uniform, InitDesc
from .. import optimizer as opt
from ..optimizer import Optimizer
from .. import kvstore as _kvstore
from ..model import (_create_kvstore, _initialize_kvstore,
                     _update_params, _update_params_on_kvstore,
                     load_checkpoint)
from ..tracecheck import RetraceError
from .base_module import BaseModule
from .executor_group import DataParallelExecutorGroup


def _seed_opt_state(ts, params, optimizer, updater, exec_param_names):
    """Optimizer state for a fused state tree, seeded from preloaded
    updater states when present (load_optimizer_states round-trip) —
    ONE recipe shared by Module._fused_opt_state and
    BucketingModule._seed_fused_state, so the two fused paths can never
    drift on how moments are imported or bf16-cast."""
    states = dict(getattr(updater, "states", None) or {})
    idx_of = {n: i for i, n in enumerate(exec_param_names)}

    def to_jnp(x):
        if x is None:
            return None
        if isinstance(x, tuple):
            return tuple(to_jnp(i) for i in x)
        return x.data if hasattr(x, "data") else x

    out = {}
    for n, v in params.items():
        if n in ts.frozen_param_names:
            continue
        idx = idx_of.get(n)
        if idx is not None and idx in states:
            out[n] = to_jnp(states[idx])
        else:
            out[n] = optimizer.create_fused_state(v)
    return ts.cast_opt_state(out)


class Module(BaseModule):
    def __init__(self, symbol, data_names=("data",),
                 label_names=("softmax_label",), logger=logging,
                 context=None, work_load_list=None, fixed_param_names=None,
                 state_names=None, mesh_axes=None, param_shardings=None):
        super().__init__(logger=logger)
        # multi-axis mesh training (docs/perf.md "Flagship LM"):
        # ``mesh_axes`` — "data=2,seq=2" / {"data": 2, "pipe": 2} — makes
        # the fused step run over a named multi-axis mesh instead of the
        # contexts-derived 1-axis 'data' mesh; MXTPU_LM_MESH supplies the
        # same spec from the environment (explicit arg wins).
        # ``param_shardings`` maps parameter names to PartitionSpecs
        # (e.g. the stack_* stacked weights onto P('pipe')).
        if mesh_axes is None:
            from ..base import env_str
            mesh_axes = env_str("MXTPU_LM_MESH") or None
        if mesh_axes is not None:
            from ..parallel.mesh import parse_mesh_axes
            mesh_axes = parse_mesh_axes(mesh_axes)
        self._mesh_axes = mesh_axes
        self._param_shardings = dict(param_shardings or {})
        self._override_mesh_cache = None
        if context is None:
            from ..context import current_context
            from .. import engine as _engine
            context = current_context()
            n_dp = _engine.dp_devices()
            if n_dp > 1:
                # MXTPU_DP_DEVICES=N: spread over the first N local devices
                # (docs/perf.md "Data-parallel scaling"). Distinctness is
                # what makes the executor group build a 'data' mesh, so an
                # over-ask fails actionably instead of silently collapsing
                # onto one device
                import jax
                avail = len(jax.local_devices())
                if n_dp > avail:
                    raise MXNetError(
                        "MXTPU_DP_DEVICES=%d but only %d local device(s) "
                        "are visible — on CPU, raise the count with "
                        "XLA_FLAGS=--xla_force_host_platform_device_count"
                        "=%d" % (n_dp, avail, n_dp))
                context = [Context(context.device_type, i)
                           for i in range(n_dp)]
        if isinstance(context, Context):
            context = [context]
        self._context = context
        if work_load_list is None:
            work_load_list = [1] * len(self._context)
        self._work_load_list = work_load_list

        self._symbol = symbol
        data_names = list(data_names) if data_names is not None else []
        label_names = list(label_names) if label_names is not None else []
        state_names = list(state_names) if state_names is not None else []
        fixed_param_names = (list(fixed_param_names)
                             if fixed_param_names is not None else [])
        arg_names = symbol.list_arguments()
        input_names = data_names + label_names + state_names
        self._param_names = [x for x in arg_names if x not in input_names]
        self._fixed_param_names = fixed_param_names
        self._aux_names = symbol.list_auxiliary_states()
        self._data_names = data_names
        self._label_names = label_names
        self._state_names = state_names
        self._output_names = symbol.list_outputs()

        self._arg_params = None
        self._aux_params = None
        self._params_dirty = False

        self._optimizer = None
        self._kvstore = None
        self._update_on_kvstore = None
        self._updater = None
        self._preload_opt_states = None
        self._exec_group = None
        self._data_shapes = None
        self._label_shapes = None

        # fused fast path (forward+backward+update in ONE donated jit; the
        # reference's API and its speed were the same thing — model.py:88-117
        # update_on_kvstore was its fast path, this is ours)
        self._fused = None
        self._fused_state = None
        self._fused_outputs = None
        self._fused_ok = True
        self._fused_dirty = False
        self._fused_params_stale = False
        self._fused_metrics_ok = False
        # the eval metric's resolved packed-accumulator spec
        # (docs/perf.md "Packed accumulators"), stashed by
        # _can_bulk_dispatch(eval_metric) and consumed per dispatch
        self._fused_metric_spec = None
        self._monitor_installed = False
        # checkpoint resume: the update-count the fused step clock (and lr
        # schedule) continues from (set via _restore_trainer_clock)
        self._resume_step = 0
        # host-side mirror of the fused device step counter, advanced
        # arithmetically per dispatch so progress queries (checkpoint
        # manifests, _fused_step_count) never sync the device
        self._fused_host_step = 0

    # -- checkpointing (ref: module.py:97-156, :674-704) ----------------
    @staticmethod
    def load(prefix, epoch, load_optimizer_states=False, **kwargs):
        sym, args, auxs = load_checkpoint(prefix, epoch)
        mod = Module(symbol=sym, **kwargs)
        mod._arg_params = args
        mod._aux_params = auxs
        mod.params_initialized = True
        if load_optimizer_states:
            mod._preload_opt_states = "%s-%04d.states" % (prefix, epoch)
        return mod

    def save_checkpoint(self, prefix, epoch, save_optimizer_states=False):
        from ..model import atomic_write_bytes
        atomic_write_bytes("%s-symbol.json" % prefix,
                           self._symbol.tojson().encode())
        param_name = "%s-%04d.params" % (prefix, epoch)
        self.save_params(param_name)
        logging.info("Saved checkpoint to \"%s\"", param_name)
        if save_optimizer_states:
            state_name = "%s-%04d.states" % (prefix, epoch)
            self.save_optimizer_states(state_name)
            logging.info("Saved optimizer state to \"%s\"", state_name)

    def save_optimizer_states(self, fname):
        """Returns the serialized bytes so callers (CheckpointManager) can
        checksum the INTENDED payload rather than re-read the file — a torn
        write then fails manifest validation instead of sealing as valid."""
        assert self.optimizer_initialized
        self._sync_fused_opt_states()
        if self._update_on_kvstore:
            return self._kvstore.save_optimizer_states(fname)
        from ..model import atomic_write_bytes
        data = self._updater.get_states()
        atomic_write_bytes(fname, data)
        return data

    def load_optimizer_states(self, fname):
        assert self.optimizer_initialized
        if self._update_on_kvstore:
            self._kvstore.load_optimizer_states(fname)
        else:
            from ..model import apply_optimizer_states
            apply_optimizer_states(self._updater.set_states, fname)

    # -- properties -----------------------------------------------------
    @property
    def data_names(self):
        return self._data_names

    @property
    def label_names(self):
        return self._label_names

    @property
    def output_names(self):
        return self._output_names

    @property
    def data_shapes(self):
        assert self.binded
        return self._data_shapes

    @property
    def label_shapes(self):
        assert self.binded
        return self._label_shapes

    @property
    def output_shapes(self):
        assert self.binded
        outs = [o.shape for o in self._exec_group.executor.outputs]
        return list(zip(self._output_names, outs))

    # -- params ---------------------------------------------------------
    def get_params(self):
        assert self.binded and self.params_initialized
        if self._params_dirty:
            self._sync_params_from_devices()
        return (self._arg_params, self._aux_params)

    def _sync_params_from_devices(self):
        self._sync_fused_to_executor()
        self._exec_group.get_params(self._arg_params, self._aux_params)
        self._params_dirty = False

    def init_params(self, initializer=None, arg_params=None, aux_params=None,
                    allow_missing=False, force_init=False):
        if self.params_initialized and not force_init:
            return
        assert self.binded, "call bind before initializing the parameters"
        if initializer is None and not (arg_params or aux_params):
            initializer = Uniform(0.01)

        if self._arg_params is None:
            self._arg_params = {
                n: self._exec_group.executor.arg_dict[n]
                for n in self._param_names}
        if self._aux_params is None:
            self._aux_params = {
                n: self._exec_group.executor.aux_dict[n]
                for n in self._aux_names}

        attrs = self._symbol.attr_dict()

        def _impl(name, arr, cache):
            if cache is not None and name in cache:
                cache_arr = cache[name]
                if cache_arr is not arr:
                    cache_arr.copyto(arr)
            else:
                if not allow_missing and initializer is None:
                    raise MXNetError("%s is not presented" % name)
                if initializer is not None:
                    initializer(InitDesc(name, attrs.get(name, {})), arr)

        for name in self._param_names:
            _impl(name, self._arg_params[name], arg_params)
        for name in self._aux_names:
            _impl(name, self._aux_params[name], aux_params)

        self.params_initialized = True
        self._params_dirty = False
        self._exec_group._replicate_params()
        # executor arrays are now authoritative: the fused copy must be
        # re-seeded from them, never written back over them
        self._fused_params_stale = True
        self._fused_dirty = False

    def set_params(self, arg_params, aux_params, allow_missing=False,
                   force_init=True):
        if not allow_missing:
            self.init_params(initializer=None, arg_params=arg_params,
                             aux_params=aux_params, allow_missing=allow_missing,
                             force_init=force_init)
            return
        if self.params_initialized and not force_init:
            return
        self._exec_group.set_params(arg_params, aux_params)
        self._params_dirty = True
        self.params_initialized = True
        self._fused_params_stale = True
        self._fused_dirty = False

    # -- bind -----------------------------------------------------------
    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, shared_module=None,
             grad_req="write"):
        if force_rebind:
            self._reset_bind()
        if self.binded:
            self.logger.warning("Already binded, ignoring bind()")
            return
        self.for_training = for_training
        self.inputs_need_grad = inputs_need_grad
        self.binded = True
        if not for_training:
            assert not inputs_need_grad

        self._data_shapes = data_shapes
        self._label_shapes = label_shapes

        shared_group = None
        if shared_module is not None:
            assert shared_module.binded and shared_module.params_initialized
            shared_group = shared_module._exec_group

        self._exec_group = DataParallelExecutorGroup(
            self._symbol, self._context, self._work_load_list, data_shapes,
            label_shapes, self._param_names, for_training, inputs_need_grad,
            shared_group, logger=self.logger,
            fixed_param_names=self._fixed_param_names, grad_req=grad_req,
            state_names=self._state_names)
        self._total_exec_bytes = 0
        if shared_module is not None:
            self.params_initialized = True
            self._arg_params = shared_module._arg_params
            self._aux_params = shared_module._aux_params
            # parameter buffers are aliased by simple_bind's shared pool —
            # do NOT set_params here: _arg_params may hold stale host
            # snapshots from a get_params() sync and would revert training
            # (ref: module.py shared bind skips parameter copy)
        elif self.params_initialized:
            self._exec_group.set_params(self._arg_params, self._aux_params)

        if shared_module is not None and shared_module.optimizer_initialized:
            self.borrow_optimizer(shared_module)

    def _reset_bind(self):
        self.binded = False
        self._exec_group = None
        self._data_shapes = None
        self._label_shapes = None

    def reshape(self, data_shapes, label_shapes=None):
        assert self.binded
        # flush + drop the fused state: its jit cache and param tree are
        # keyed to the old shapes and would silently train on stale data
        self._sync_fused_to_executor()
        self._fused = None
        self._fused_state = None
        self._fused_outputs = None
        self._data_shapes = data_shapes
        self._label_shapes = label_shapes
        shapes = {}
        for d in data_shapes:
            name, shape = (d.name, d.shape) if hasattr(d, "name") else (d[0], d[1])
            shapes[name] = shape
        for l in (label_shapes or []):
            name, shape = (l.name, l.shape) if hasattr(l, "name") else (l[0], l[1])
            shapes[name] = shape
        # allow_up_sizing: Module.reshape serves batch-size changes in both
        # directions (ref executor_group passes it on this path)
        self._exec_group.executor = self._exec_group.executor.reshape(
            allow_up_sizing=True, **shapes)

    # -- optimizer ------------------------------------------------------
    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.01),),
                       force_init=False):
        assert self.binded and self.params_initialized
        if self.optimizer_initialized and not force_init:
            self.logger.warning("optimizer already initialized, "
                                "ignoring init_optimizer")
            return
        # a dirty fused state holds the latest trained weights; flush it
        # before the reset below discards it (e.g. re-init to change lr)
        self._sync_fused_to_executor()

        (kvstore, update_on_kvstore) = _create_kvstore(
            kvstore, len(self._context), self._arg_params)

        batch_size = self._exec_group.batch_size
        if kvstore and "dist" in kvstore.type:
            batch_size *= kvstore.num_workers
        rescale_grad = 1.0 / batch_size

        if isinstance(optimizer, str):
            idx2name = dict(enumerate(self._exec_group.param_names))
            optimizer_params = dict(optimizer_params)
            if "rescale_grad" not in optimizer_params:
                optimizer_params["rescale_grad"] = rescale_grad
            optimizer = opt.create(optimizer, sym=self.symbol,
                                   param_idx2name=idx2name, **optimizer_params)
        else:
            assert isinstance(optimizer, Optimizer)
            if optimizer.rescale_grad != rescale_grad:
                self.logger.warning(
                    "Optimizer created manually outside Module but rescale_grad "
                    "is not normalized to 1.0/batch_size/num_workers (%s vs. %s).",
                    optimizer.rescale_grad, rescale_grad)

        self._optimizer = optimizer
        self._kvstore = kvstore
        self._update_on_kvstore = update_on_kvstore
        self._updater = None

        if kvstore:
            _initialize_kvstore(kvstore=kvstore,
                                param_arrays=[self._arg_params[n] for n in
                                              self._exec_group.param_names],
                                arg_params=self._arg_params,
                                param_names=self._exec_group.param_names,
                                update_on_kvstore=update_on_kvstore)
        if update_on_kvstore:
            kvstore.set_optimizer(self._optimizer)
        else:
            self._updater = opt.get_updater(optimizer)

        self.optimizer_initialized = True
        self._fused = None
        self._fused_state = None
        self._fused_ok = True
        if self._preload_opt_states is not None:
            self.load_optimizer_states(self._preload_opt_states)
            self._preload_opt_states = None

    def _resolve_updater(self):
        """The updater whose optimizer copy actually applies updates: the
        kvstore's pickled updater under update_on_kvstore, else the local
        one (shared by the rollback lr-reduction, the resume clock-wind and
        fused-state seeding — one routing rule, three consumers)."""
        if self._update_on_kvstore and self._kvstore is not None:
            return getattr(self._kvstore, "_updater", None)
        return self._updater

    def _drop_fused_state(self):
        """Divergence-rollback hook: discard the fused state tree WITHOUT
        flushing it (it holds the diverged/poisoned params). The next fused
        dispatch reseeds from the executor arrays + updater states the
        rollback just restored; the TrainStep and its jit caches survive, so
        a rollback never recompiles."""
        self._fused_state = None
        self._fused_outputs = None
        self._fused_dirty = False
        self._fused_params_stale = False

    def _refresh_dist_scale(self):
        """Post-re-form hook (docs/robustness.md "Elastic distributed
        training"): the live worker count changed, so the global-batch
        denominator behind ``rescale_grad`` changed with it. Re-derive
        the scale into every optimizer copy (the kvstore's pickled one
        included) and drop the fused TrainStep — its trace captured the
        old scale. MUST run BEFORE checkpoint states are re-applied:
        ``set_optimizer`` builds a fresh (empty) kvstore updater."""
        kv = self._kvstore
        if kv is None or "dist" not in kv.type:
            return
        bs = self._exec_group.batch_size * max(1, kv.num_workers)
        rescale = 1.0 / bs
        if self._optimizer is not None:
            self._optimizer.rescale_grad = rescale
        upd_opt = getattr(self._resolve_updater(), "optimizer", None)
        if upd_opt is not None and upd_opt is not self._optimizer:
            upd_opt.rescale_grad = rescale
        if self._update_on_kvstore and self._optimizer is not None:
            kv.set_optimizer(self._optimizer)
        self._fused = None
        self._drop_fused_state()

    def _scale_lr(self, factor):
        """Divergence-rollback hook: reduce the learning rate by ``factor``
        everywhere the next step might read it — the optimizer, its
        scheduler's base_lr, and the kvstore updater's pickled optimizer
        copy (the same set _restore_trainer_clock winds)."""
        def scale(opt_):
            opt_.lr *= factor
            if opt_.lr_scheduler is not None:
                opt_.lr_scheduler.base_lr *= factor

        if self._optimizer is not None:
            scale(self._optimizer)
        upd_opt = getattr(self._resolve_updater(), "optimizer", None)
        if upd_opt is not None and upd_opt is not self._optimizer:
            scale(upd_opt)

    def _fused_step_count(self):
        """The fused step counter, for checkpoint manifests: trails
        ``num_update`` by the number of guard-skipped steps, and is the
        clock the dropout/SGLD noise streams and Adam's t actually follow.
        None when no fused state is live.

        Reads the HOST-side mirror (advanced arithmetically per dispatch;
        guarded dispatches advance it at sentinel retirement, which always
        precedes a checkpoint snapshot) — never ``np.asarray`` on the
        device counter, so progress queries cost no device sync and cannot
        stall the dispatch pipeline."""
        if self._fused_state is None:
            return None
        return int(self._fused_host_step)

    def _restore_trainer_clock(self, num_update, fused_step=None):
        """Resume hook: continue the optimizer's update clock (lr schedule,
        per-index counts) from ``num_update`` and the fused step counter —
        the noise/Adam-t clock — from ``fused_step`` (they differ by the
        number of guard-skipped steps; pre-guard checkpoints carry only
        ``num_update``)."""
        n = int(num_update or 0)
        self._resume_step = n if fused_step is None else int(fused_step)

        def wind(opt):
            opt.num_update = n
            opt.begin_num_update = n
            opt._index_update_count = {}

        if self._optimizer is not None:
            wind(self._optimizer)
        # the update_on_kvstore path updates through the kvstore updater's
        # PICKLED optimizer copy (set_optimizer round-trip) — wind that
        # clock too or its lr schedule restarts from 0 after resume
        updater = self._resolve_updater()
        if updater is not None and getattr(updater, "optimizer",
                                           None) is not None:
            wind(updater.optimizer)
        if self._fused_state is not None:
            import jax.numpy as jnp
            self._fused_state["step"] = jnp.full((), self._resume_step,
                                                 jnp.int32)
            self._fused_host_step = self._resume_step

    def borrow_optimizer(self, shared_module):
        assert shared_module.optimizer_initialized
        self._optimizer = shared_module._optimizer
        self._kvstore = shared_module._kvstore
        self._update_on_kvstore = shared_module._update_on_kvstore
        self._updater = shared_module._updater
        self.optimizer_initialized = True
        # shared/bucketing modules alias parameter storage across executors;
        # the fused state would break that aliasing — keep the executor path
        self._fused_ok = False
        shared_module._fused_ok = False
        shared_module._sync_fused_to_executor()

    # -- fused fast path ------------------------------------------------
    def _fused_eligible(self):
        if not self._fused_ok or self._monitor_installed:
            return False
        if self.inputs_need_grad or self._state_names:
            return False
        if not getattr(self._optimizer, "fused_supported", False):
            return False
        eg = self._exec_group
        for n in eg.param_names:
            if eg.grad_req.get(n, "null") not in ("write", "null"):
                return False
        return True

    def _is_dist_kvstore(self):
        return (self._kvstore is not None and "dist" in self._kvstore.type
                and getattr(self._kvstore, "num_workers", 1) > 1)

    def _override_mesh(self):
        """The multi-axis mesh requested via ``mesh_axes`` /
        ``MXTPU_LM_MESH``, built lazily over the process's devices (None
        when no spec was given). Replaces the contexts-derived 1-axis
        'data' mesh for the fused step."""
        if self._mesh_axes is None:
            return None
        if self._override_mesh_cache is None:
            from ..parallel.mesh import mesh_from_spec
            self._override_mesh_cache = mesh_from_spec(self._mesh_axes)
        return self._override_mesh_cache

    def _fused_mesh(self):
        """The mesh the fused step will (or does) run over: the explicit
        multi-axis override when given, else the executor group's
        contexts-derived 'data' mesh."""
        om = self._override_mesh()
        if om is not None:
            return om
        return (self._exec_group._mesh
                if self._exec_group is not None else None)

    def _build_fused(self):
        from ..train_step import TrainStep
        eg = self._exec_group
        frozen = [n for n in eg.param_names
                  if eg.grad_req.get(n, "null") == "null"]
        mesh = eg._mesh
        om = self._override_mesh()
        if om is not None:
            if self._is_dist_kvstore():
                raise MXNetError(
                    "mesh_axes/MXTPU_LM_MESH cannot combine with a dist "
                    "kvstore — the multi-axis mesh is single-controller; "
                    "use the global 'data' mesh for dist workers")
            mesh = om
        elif (self._is_dist_kvstore()
              and getattr(self._kvstore, "_ring", None) is None):
            # LEGACY mesh transport (MXTPU_DIST_TRANSPORT=mesh): the batch
            # shards over a global mesh spanning every worker process and
            # XLA places the gradient psum over DCN/ICI exactly where the
            # reference ran ps-lite push/pull (ref: kvstore_dist.h sync
            # mode). Not elastic — the default ring transport keeps the
            # mesh LOCAL and sums gradients through the control plane.
            from ..parallel.mesh import global_data_mesh
            mesh = global_data_mesh(
                local_devices=[c.to_device() for c in self._context])
        self._fused = TrainStep(
            self._symbol, data_names=eg.data_names,
            label_names=eg.label_names, optimizer=self._optimizer,
            mesh=mesh, param_shardings=self._param_shardings or None,
            frozen_param_names=frozen)
        if (self._is_dist_kvstore()
                and getattr(self._kvstore, "_ring", None) is not None):
            # ring transport: each process runs the LOCAL program; the
            # cross-process gradient sum is the in-scan host callback.
            # Donation off — a dispatch killed by WorkerLostError must
            # leave the pre-step state buffers valid for the re-form.
            self._fused.dist_reduce = self._kvstore.grad_reduce
            self._fused.donate = False
        self._fused_state = self._seed_fused_state()
        self._fused_params_stale = False
        self._fused_metrics_ok = self._infer_fused_metrics_ok()

    def _bound_shapes(self):
        """(input-shape dict, label shapes, output shapes) from the bound
        data/label descriptors — what the packed-accumulator protocol
        resolves metric specs against."""
        shapes = {}
        for d in (self._data_shapes or []):
            name, shape = ((d.name, d.shape) if hasattr(d, "name")
                           else (d[0], d[1]))
            shapes[name] = shape
        lshapes = []
        for l in (self._label_shapes or []):
            name, shape = ((l.name, l.shape) if hasattr(l, "name")
                           else (l[0], l[1]))
            shapes[name] = shape
            lshapes.append(shape)
        _, out_shapes, _ = self._symbol.infer_shape(**shapes)
        return shapes, lshapes, out_shapes

    def _infer_fused_metrics_ok(self):
        """Whether the DEFAULT packed layout (in-scan CE loss + top-1
        correct) is well-defined for this module: a single (rank-2 output,
        rank-1 label) classification head. The guard's loss observation
        and spec-less ``run_steps`` callers rely on it; metric-declared
        layouts (:meth:`_device_sum_spec`) cover everything else."""
        try:
            _, lshapes, out_shapes = self._bound_shapes()
            return (len(out_shapes) == 1 and len(lshapes) == 1
                    and len(out_shapes[0]) == 2 and len(lshapes[0]) == 1
                    and out_shapes[0][0] == lshapes[0][0])
        except Exception:
            return False

    def _device_sum_spec(self, metric):
        """Resolve ``metric``'s packed-accumulator layout
        (:func:`mxnet_tpu.metric.device_sum_spec`) against this module's
        bound output/label shapes; None when the metric declares none for
        these shapes."""
        from .. import metric as _metric
        try:
            _, lshapes, out_shapes = self._bound_shapes()
            return _metric.device_sum_spec(metric, out_shapes, lshapes)
        except Exception:
            return None

    def _can_bulk_dispatch(self, eval_metric=None):
        """fit()'s precheck half of :meth:`_dispatch_fused_steps`: called
        after init_optimizer so steps_per_dispatch>1 warns and skips the
        superbatch wrapper up front instead of silently paying K-batch
        stacking for dispatches the per-step path ends up training.

        With ``eval_metric`` the metric's packed-accumulator spec is
        resolved against the bound shapes and STASHED on the module
        (``_fused_metric_spec``) for :meth:`_dispatch_fused_steps`;
        without one (the guard precheck) the DEFAULT layout's
        single-head shape requirement applies."""
        if not self._fused_eligible():
            return (False, "module configuration needs the per-step "
                    "executor path (monitor/grad_req/unfused optimizer/"
                    "shared module)")
        if (self._is_dist_kvstore()
                and getattr(self._kvstore, "_ring", None) is None):
            return (False, "dist kvstore ('mesh' transport) keeps per-step "
                    "dispatch (per-step push/pull sync is the contract); "
                    "the default ring transport bulk-dispatches")
        if eval_metric is None:
            if not self._infer_fused_metrics_ok():
                return (False, "the default device metric sums need a "
                        "single (rank-2 output, rank-1 label) head")
        else:
            spec = self._device_sum_spec(eval_metric)
            if spec is None:
                try:
                    _, lshapes, out_shapes = self._bound_shapes()
                    shapes = (" for outputs %s / labels %s"
                              % ([tuple(s) for s in out_shapes],
                                 [tuple(s) for s in lshapes]))
                except Exception:
                    shapes = ""
                return (False, "metric %r declares no device-sum layout%s "
                        "— it updates per-step on host"
                        % (getattr(eval_metric, "name", eval_metric),
                           shapes))
            self._fused_metric_spec = spec
        mesh = self._fused_mesh()
        if mesh is not None:
            from ..parallel.mesh import data_axis_size, AXIS_SEQ
            explicit = self._override_mesh() is not None
            n = data_axis_size(mesh)
            if self._exec_group.batch_size % n:
                why = ("global batch %d does not divide the %d-way "
                       "'data' mesh axis — the sharded scan needs equal "
                       "per-chip shards" % (self._exec_group.batch_size, n))
                if explicit:
                    # the user ASKED for this mesh: a silent fall-back to
                    # per-step single-device training would train the
                    # wrong program — fail with the axis named
                    raise MXNetError("Module(mesh_axes=...): " + why)
                return (False, why)
            sp = data_axis_size(mesh, AXIS_SEQ)
            if sp > 1:
                for name, shape in self._bound_shapes()[0].items():
                    if len(shape) >= 2 and shape[1] % sp:
                        raise MXNetError(
                            "Module(mesh_axes=...): bound input %r "
                            "sequence dim %d does not divide the %d-way "
                            "'seq' mesh axis — pad the sequence or pick a "
                            "divisible seq_len" % (name, shape[1], sp))
        return True, None

    def _superbatch_sharding(self):
        """The NamedSharding ``fit`` hands to :class:`~mxnet_tpu.io.\
SuperBatchIter` so stacked superbatches LAND per-chip sharded (step axis
        replicated, batch axis split over 'data') — one sharded H2D on the
        producer thread, zero resharding in the dispatch loop (docs/perf.md
        "Data-parallel scaling"). None when the fused path runs without a
        single-process mesh (single device, dist workers, per-step
        configs)."""
        mesh = self._fused_mesh()
        if mesh is None or self._is_dist_kvstore():
            return None
        from ..parallel.mesh import (is_multiprocess, superbatch_sharding,
                                     AXIS_SEQ)
        if is_multiprocess(mesh):
            return None
        if AXIS_SEQ in mesh.axis_names:
            # the seq-aware sharding splits dim 2 of every stacked slot, so
            # it is only safe when every bound array is rank >= 2 (LM data
            # AND label are (batch, seq))
            shapes = list(self._bound_shapes()[0].values())
            if shapes and all(len(s) >= 2 for s in shapes):
                return superbatch_sharding(mesh, seq=True)
            return superbatch_sharding(mesh)
        return superbatch_sharding(mesh)

    def _global_batch_scale(self):
        """Factor turning this process's per-iterator img/s into GLOBAL
        img/s: >1 only in multi-process data parallelism, where each
        worker's iterator yields its local shard of the global batch
        (per-chip local batch x axis size = global batch). Speedometer
        reads it through ``param.locals['self']``."""
        if self._is_dist_kvstore():
            return int(self._kvstore.num_workers)
        if self._fused is not None:
            from ..parallel.mesh import is_multiprocess
            if is_multiprocess(self._fused.mesh):
                import jax
                return int(jax.process_count())
        return 1

    def _speed_tokens_per_sample(self):
        """Tokens per sample for throughput reporting: the product of the
        bound label's non-batch dims (an LM label is (batch, seq) next-token
        ids, so seq tokens land per sample). 1 for rank-1 labels —
        Speedometer only appends a tokens/sec figure when this exceeds 1,
        so classification runs keep their samples/sec-only line."""
        try:
            _, lshapes, _ = self._bound_shapes()
            if len(lshapes) == 1 and len(lshapes[0]) > 1:
                import numpy as _np
                return int(_np.prod(lshapes[0][1:]))
        except Exception:
            pass
        return 1

    def _can_guard(self):
        """fit()'s precheck for ``guard=``: the TrainingGuard's device
        sentinels (and its in-graph loss observation) need the fused step
        and a single classification head — the same eligibility set as
        dispatch bulking."""
        return self._can_bulk_dispatch()

    def _jnp_copy(self, x):
        import jax.numpy as jnp
        if not getattr(x, "is_fully_addressable", True):
            # multi-host global array -> process-local copy (params/aux are
            # replicated in dist DP, so the local copy is the full value and
            # the executor's single-device jit can consume it)
            from ..parallel.mesh import local_view
            return jnp.copy(local_view(x))
        return jnp.copy(x)

    def _seed_fused_state(self, prev=None):
        """Build the fused state tree from the executor's current arrays
        (copies: the first step donates the state buffers). ``prev`` keeps
        optimizer state and step count across a parameter re-seed."""
        import jax.numpy as jnp
        ex = self._exec_group.executor
        params = {n: self._jnp_copy(ex.arg_dict[n].data)
                  for n in self._fused.param_names}
        # MXTPU_BF16_STATS: moving stats store bf16 inside the fused state
        # (executor arrays and checkpoints stay f32 — the cast back on
        # sync is exact, so resume stays bitwise)
        aux = self._fused.cast_stats(
            {n: self._jnp_copy(ex.aux_dict[n].data)
             for n in self._fused.aux_names})
        if prev is not None:
            opt_state = prev["opt"]
            step = prev["step"]  # host mirror already tracks it
        else:
            opt_state = self._fused_opt_state(params)
            # a resumed run continues the step clock (noise streams /
            # schedules) where the killed run stopped, not at 0
            step = jnp.full((), self._resume_step, jnp.int32)
            self._fused_host_step = self._resume_step
        state = {"params": params, "aux": aux, "opt": opt_state,
                 "step": step}
        if self._fused.mesh is not None:
            state = self._fused._shard_state(state)
        return state

    def _fused_opt_state(self, params):
        """Optimizer state for the fused tree, seeded from preloaded updater
        states when present (load_optimizer_states round-trip)."""
        return _seed_opt_state(self._fused, params, self._optimizer,
                               self._resolve_updater(),
                               self._exec_group.param_names)

    def _try_fused_fit_step(self, data_batch, guard=None):
        """fit()'s fast path: one donated jit for fwd+bwd+update. Returns
        False when the configuration needs the general executor path.

        With a :class:`~mxnet_tpu.guard.TrainingGuard`, the guarded step
        runs instead: device sentinels make a non-finite step a no-op, the
        sentinel packet feeds ``guard.on_dispatch`` and
        ``guard.last_step_skipped`` tells fit to keep the skipped batch out
        of the host-side metric."""
        if not (self.binded and self.params_initialized
                and self.optimizer_initialized):
            return False
        from .. import profiler as _profiler
        _profiler.maybe_autostart()
        if self._fused is None:
            if not self._fused_eligible():
                return False
            self._build_fused()
        if self._fused_state is None:
            # dropped by a divergence rollback: reseed from the restored
            # executor params + updater states (NOT prev — the diverged
            # optimizer state must not survive the rollback)
            self._fused_state = self._seed_fused_state()
            self._fused_params_stale = False
        elif self._fused_params_stale:
            self._fused_state = self._seed_fused_state(prev=self._fused_state)
            self._fused_params_stale = False
        eg = self._exec_group
        from ..parallel.mesh import is_multiprocess, local_view
        multiproc = is_multiprocess(self._fused.mesh)
        # the multi-axis override mesh is NOT the executor group's mesh:
        # eg._shard_batch would land dim-0-only shards on the wrong mesh,
        # so route through TrainStep.shard_batch (which also splits the
        # token dim over 'seq')
        route = multiproc or self._override_mesh() is not None
        batch = {}
        for name, value in zip(eg.data_names, data_batch.data):
            batch[name] = value if route else eg._shard_batch(value)
        if eg.label_names and data_batch.label:
            for name, value in zip(eg.label_names, data_batch.label):
                batch[name] = value if route else eg._shard_batch(value)
        if route:
            # each worker contributes its local shard of the global batch
            import numpy as _np
            batch = self._fused.shard_batch(
                {k: _np.asarray(v.asnumpy() if hasattr(v, "asnumpy") else v)
                 for k, v in batch.items()})
        from ..ndarray import NDArray
        # retrace events attribute to THIS run's health when guarded (the
        # process-global TRAINING_HEALTH mirror always counts them)
        self._fused.health = guard.health if guard is not None else None
        if guard is not None:
            guard.last_step_skipped = False
            try:
                self._fused_state, outs, packed = self._fused.step(
                    self._fused_state, batch, guard=True)
            except RetraceError as e:
                self._adopt_retrace_result(e, 1, guard)
                raise
            self._fused_outputs = [NDArray(local_view(o)) for o in outs]
            self._fused_dirty = True
            self._params_dirty = True
            # the per-step path reads outputs for the metric anyway, so the
            # sentinel readback costs no extra sync point
            import numpy as _np
            self._feed_guard_sentinels(guard, _np.asarray(packed))
            return True
        try:
            self._fused_state, outs = self._fused.step(
                self._fused_state, batch)
        except RetraceError as e:
            self._adopt_retrace_result(e, 1, None)
            raise
        self._fused_host_step += 1
        # per-worker view of batch-sharded outputs (each worker's metric
        # covers its own shard, matching reference per-worker eval)
        self._fused_outputs = [NDArray(local_view(o)) for o in outs]
        self._fused_dirty = True
        self._params_dirty = True
        return True

    def _dispatch_fused_steps(self, super_batch, guard=None):
        """fit()'s K-step fast path, dispatch half: enqueue one donated
        ``lax.scan`` over a stacked superbatch (``TrainStep.run_steps``)
        and return the device-resident :class:`~mxnet_tpu.train_step.\
StepMetrics` WITHOUT reading it back — the packed metric/sentinel array is
        a future, and deferring its ``np.asarray`` is what lets ``fit``'s
        dispatch pipeline enqueue dispatch N+1 before dispatch N's readback
        (docs/perf.md "Host off the critical path"). Returns None when the
        configuration needs the general per-step path.

        The caller MUST retire the result (fold it into the metric, feed
        the guard, call :meth:`_note_dispatch_retired`) in dispatch order —
        ``fit``'s ``_consume`` owns that retirement sequence."""
        if not (self.binded and self.params_initialized
                and self.optimizer_initialized):
            return None
        from .. import profiler as _profiler
        _profiler.maybe_autostart()
        if self._fused is None:
            if not self._fused_eligible():
                return None
            self._build_fused()
        from ..parallel.mesh import is_multiprocess
        if is_multiprocess(self._fused.mesh):
            # dist workers keep per-step dispatch: the per-step kvstore sync
            # semantics (and per-worker metric shards) are the contract
            return None
        spec = self._fused_metric_spec
        if spec is None and not getattr(self, "_fused_metrics_ok", False):
            # no metric-declared packed layout AND the default layout's
            # single-head shape requirement fails: per-step host metrics
            return None
        if self._fused_state is None:
            # dropped by a divergence rollback: reseed from the restored
            # executor params + updater states
            self._fused_state = self._seed_fused_state()
            self._fused_params_stale = False
        elif self._fused_params_stale:
            self._fused_state = self._seed_fused_state(prev=self._fused_state)
            self._fused_params_stale = False
        eg = self._exec_group
        batch = {}
        for name, value in zip(eg.data_names, super_batch.data):
            batch[name] = value
        if eg.label_names and super_batch.label:
            for name, value in zip(eg.label_names, super_batch.label):
                batch[name] = value
        batch = self._fused.shard_superbatch(batch)
        self._fused.health = guard.health if guard is not None else None
        try:
            self._fused_state, sums = self._fused.run_steps(
                self._fused_state, batch, guard=guard is not None,
                metric_spec=spec)
        except RetraceError as e:
            self._adopt_retrace_result(e, super_batch.num_steps, guard)
            raise
        if guard is None:
            # unguarded: every step lands, the mirror advances at dispatch;
            # guarded dispatches advance at retirement (skip count is in
            # the sentinel readback)
            self._fused_host_step += super_batch.num_steps
        self._fused_outputs = None  # outputs stay on device, un-materialized
        self._fused_dirty = True
        self._params_dirty = True
        return sums

    # _adopt_retrace_result / _note_dispatch_retired live on BaseModule —
    # shared verbatim with BucketingModule so the sentinel/step-clock
    # protocol can never drift between the two fused paths

    def _sync_fused_to_executor(self):
        """Write fused params/aux back into the executor arrays (copies —
        the next fused step donates the state)."""
        if not self._fused_dirty or self._fused_state is None:
            return
        ex = self._exec_group.executor
        for n in self._fused.param_names:
            ex.arg_dict[n]._set_data(
                self._jnp_copy(self._fused_state["params"][n]))
        for n in self._fused.aux_names:
            v = self._jnp_copy(self._fused_state["aux"][n])
            tgt = ex.aux_dict[n].data.dtype
            if v.dtype != tgt:
                # bf16 moving stats (MXTPU_BF16_STATS) widen back to the
                # executor's f32 — exact, so checkpoints/score() see the
                # same values the fused state trains with
                v = v.astype(tgt)
            ex.aux_dict[n]._set_data(v)
        self._fused_dirty = False

    def _sync_fused_opt_states(self):
        """Mirror fused optimizer state into the updater's index-keyed dict
        so save_optimizer_states round-trips."""
        if self._fused_state is None:
            return
        updater = self._resolve_updater()
        if updater is None:
            return
        from ..ndarray import NDArray

        def to_nd(x):
            if x is None:
                return None
            if isinstance(x, tuple):
                return tuple(to_nd(i) for i in x)
            v = self._jnp_copy(x)
            if str(v.dtype) == "bfloat16":
                # bf16 optimizer state (MXTPU_BF16_STATS=opt) serializes
                # f32: save formats stay unchanged and the bf16->f32->bf16
                # round trip is exact, so resume stays bitwise
                import jax.numpy as jnp
                v = v.astype(jnp.float32)
            return NDArray(v)

        idx_of = {n: i for i, n in enumerate(self._exec_group.param_names)}
        for n, st in self._fused_state["opt"].items():
            if n in idx_of:
                updater.states[idx_of[n]] = to_nd(st)

    def _snapshot_opt_states(self):
        """Decoupled optimizer-state snapshot for the async checkpoint
        writer (model.AsyncCheckpointWriter): only device-side copies
        happen here; the returned callable does the D2H + pickle on the
        writer thread, byte-identical to ``save_optimizer_states`` over the
        same state. The copies matter: the imperative updater mutates its
        state arrays in place per step, so an un-decoupled snapshot would
        race later training. None when this module cannot snapshot (e.g. a
        dist kvstore owns the states) — the manager then saves
        synchronously."""
        if not self.optimizer_initialized:
            return None
        self._sync_fused_opt_states()
        updater = self._resolve_updater()
        if updater is None or not hasattr(updater, "states"):
            return None
        from ..ndarray import NDArray
        from ..optimizer import Updater

        def cp(x):
            if x is None:
                return None
            if isinstance(x, tuple):
                return tuple(cp(i) for i in x)
            if isinstance(x, NDArray):
                return NDArray(self._jnp_copy(x.data))
            return x

        states = {k: cp(v) for k, v in updater.states.items()}
        return lambda: Updater.serialize_states(states)

    # -- computation ----------------------------------------------------
    def forward(self, data_batch, is_train=None):
        assert self.binded and self.params_initialized
        self._sync_fused_to_executor()
        self._fused_outputs = None
        self._exec_group.forward(data_batch, is_train)

    def backward(self, out_grads=None):
        assert self.binded and self.params_initialized
        self._exec_group.backward(out_grads=out_grads)

    def update(self):
        """Apply gradients (ref: module.py:553-569 + model.py:88-117)."""
        assert self.binded and self.params_initialized \
            and self.optimizer_initialized
        self._params_dirty = True
        param_names = self._exec_group.param_names
        exec_ = self._exec_group.executor
        param_arrays = [exec_.arg_dict[n] for n in param_names]
        grad_arrays = [exec_.grad_dict.get(n) for n in param_names]
        if self._update_on_kvstore:
            _update_params_on_kvstore(param_arrays, grad_arrays, self._kvstore)
        else:
            _update_params(param_arrays, grad_arrays, updater=self._updater,
                           num_device=len(self._context),
                           kvstore=self._kvstore)

    def get_outputs(self, merge_multi_context=True):
        assert self.binded and self.params_initialized
        if self._fused_outputs is not None:
            return list(self._fused_outputs)
        return self._exec_group.get_outputs(merge_multi_context)

    def get_input_grads(self, merge_multi_context=True):
        assert self.binded and self.params_initialized \
            and self.inputs_need_grad
        return self._exec_group.get_input_grads(merge_multi_context)

    def update_metric(self, eval_metric, labels):
        if self._fused_outputs is not None:
            eval_metric.update(labels, self._fused_outputs)
        else:
            self._exec_group.update_metric(eval_metric, labels)

    def install_monitor(self, mon):
        assert self.binded
        # monitor needs the per-node executor path
        self._sync_fused_to_executor()
        self._monitor_installed = True
        self._fused_ok = False
        mon.install(self._exec_group.executor)
