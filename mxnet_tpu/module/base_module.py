"""BaseModule: the high-level training interface
(ref: python/mxnet/module/base_module.py, 952 LoC — fit at :368-519).
"""
from __future__ import annotations

import logging
import os
import time
from collections import deque, namedtuple

import numpy as np

from ..base import (MXNetError, TrainingPreemptedError, env_bool,
                    env_float)
from .. import metric as _metric
from .. import ndarray as nd
from ..ndarray import NDArray
from ..obs import trace as _obs_trace

BatchEndParam = namedtuple("BatchEndParams",
                           ["epoch", "nbatch", "eval_metric", "locals"])


def _as_list(obj):
    if isinstance(obj, list):
        return obj
    return [obj]


class _DispatchPipeline(object):
    """Deferred-readback window for K-step fused dispatches (docs/perf.md
    "Host off the critical path").

    ``run_steps`` returns a device-resident packed metric/sentinel array —
    a future; the ONLY host block in the steady-state train loop is its
    ``np.asarray`` readback. With depth D, ``fit`` enqueues dispatch N+D
    before fetching dispatch N's array, so the device always has the next
    scan queued while the host blocks — Speedometer, batch callbacks and
    the TrainingGuard consume D-dispatch-lagged sums in strict dispatch
    order (FIFO: the metric/guard fold sequence is bitwise identical to
    eager, only later in wall-clock). Depth 0 is eager mode.

    ``host_stall`` accumulates the seconds actually spent blocked in
    readbacks — the Speedometer pipeline suffix and bench.py's
    ``host_stall_frac`` read it.
    """

    # __weakref__: the Speedometer's windowed-suffix store holds its
    # sources weakly (callback.py _window_for) — a slots class without it
    # cannot be weak-referenced
    __slots__ = ("depth", "_pending", "dispatches", "retired",
                 "host_stall", "__weakref__")

    def __init__(self, depth):
        self.depth = max(0, int(depth))
        self._pending = deque()
        self.dispatches = 0
        self.retired = 0
        self.host_stall = 0.0

    def __len__(self):
        return len(self._pending)

    def push(self, sums, nsteps, nbatch, disp=None):
        """Enqueue one dispatch's device-resident sums; returns the list of
        ``(sums, nsteps, nbatch)`` entries that fell out of the window
        (fetched, ready to fold into metric/guard). ``disp`` is the
        dispatch correlation index the readback span reports
        (docs/observability.md); defaults to the push ordinal."""
        if disp is None:
            disp = self.dispatches
        self.dispatches += 1
        self._pending.append((sums, nsteps, nbatch, disp))
        out = []
        while len(self._pending) > self.depth:
            out.append(self._fetch_one())
        return out

    def drain(self):
        """Fetch everything still in flight (checkpoint sealing, epoch
        ends, per-step fallbacks: consumers need ALL sentinels covering the
        current state before acting on it)."""
        out = []
        while self._pending:
            out.append(self._fetch_one())
        return out

    def discard(self):
        """Divergence rollback: pending dispatches cover post-divergence
        state — their sums must never reach the metric or the guard. The
        device work is abandoned, not awaited."""
        self._pending.clear()

    def _fetch_one(self):
        from ..obs import trace as _obs
        sums, nsteps, nbatch, disp = self._pending.popleft()
        t0 = time.perf_counter()
        sums.fetch()
        dt = time.perf_counter() - t0
        _obs.complete("readback_stall", dt, dispatch=disp)
        self.host_stall += dt
        self.retired += 1
        return sums, nsteps, nbatch, disp


class BaseModule(object):
    """Abstract module: computation machine with forward/backward/update
    plus the high-level fit/predict/score drivers."""

    def __init__(self, logger=logging):
        self.logger = logger
        self.binded = False
        self.for_training = False
        self.inputs_need_grad = False
        self.params_initialized = False
        self.optimizer_initialized = False
        self._symbol = None
        self._total_exec_bytes = 0

    # -- high-level drivers --------------------------------------------
    def forward_backward(self, data_batch):
        self.forward(data_batch, is_train=True)
        self.backward()

    def score(self, eval_data, eval_metric, num_batch=None,
              batch_end_callback=None, score_end_callback=None, reset=True,
              epoch=0):
        """Evaluate over eval_data (ref: base_module.py score)."""
        assert self.binded and self.params_initialized
        if reset:
            eval_data.reset()
        if not isinstance(eval_metric, _metric.EvalMetric):
            eval_metric = _metric.create(eval_metric)
        eval_metric.reset()
        actual_num_batch = 0
        for nbatch, eval_batch in enumerate(eval_data):
            if num_batch is not None and nbatch == num_batch:
                break
            self.forward(eval_batch, is_train=False)
            self.update_metric(eval_metric, eval_batch.label)
            if batch_end_callback is not None:
                params = BatchEndParam(epoch=epoch, nbatch=nbatch,
                                       eval_metric=eval_metric, locals=locals())
                for callback in _as_list(batch_end_callback):
                    callback(params)
            actual_num_batch += 1
        if score_end_callback:
            params = BatchEndParam(epoch=epoch, nbatch=actual_num_batch,
                                   eval_metric=eval_metric, locals=locals())
            for callback in _as_list(score_end_callback):
                callback(params)
        return eval_metric.get_name_value()

    def iter_predict(self, eval_data, num_batch=None, reset=True):
        assert self.binded and self.params_initialized
        if reset:
            eval_data.reset()
        for nbatch, eval_batch in enumerate(eval_data):
            if num_batch is not None and nbatch == num_batch:
                break
            self.forward(eval_batch, is_train=False)
            pad = eval_batch.pad
            outputs = [out[0:out.shape[0] - pad] for out in self.get_outputs()]
            yield (outputs, nbatch, eval_batch)

    def predict(self, eval_data, num_batch=None, merge_batches=True,
                reset=True, always_output_list=False):
        """Run prediction, collecting outputs (ref: base_module.py predict)."""
        assert self.binded and self.params_initialized
        if reset:
            eval_data.reset()
        output_list = []
        for nbatch, eval_batch in enumerate(eval_data):
            if num_batch is not None and nbatch == num_batch:
                break
            self.forward(eval_batch, is_train=False)
            pad = eval_batch.pad
            outputs = [out[0:out.shape[0] - pad].copy()
                       for out in self.get_outputs()]
            output_list.append(outputs)
        if len(output_list) == 0:
            return output_list
        if merge_batches:
            num_outputs = len(output_list[0])
            for out in output_list:
                assert len(out) == num_outputs, \
                    "Cannot merge batches, as num of outputs is not the same " \
                    "in mini-batches. Maybe bucketing is used?"
            output_list2 = [nd.concatenate([out[i] for out in output_list])
                            for i in range(num_outputs)]
            if num_outputs == 1 and not always_output_list:
                return output_list2[0]
            return output_list2
        return output_list

    def fit(self, train_data, eval_data=None, eval_metric="acc",
            epoch_end_callback=None, batch_end_callback=None, kvstore="local",
            optimizer="sgd", optimizer_params=(("learning_rate", 0.01),),
            eval_end_callback=None, eval_batch_end_callback=None,
            initializer=None, arg_params=None, aux_params=None,
            allow_missing=False, force_rebind=False, force_init=False,
            begin_epoch=0, num_epoch=None, validation_metric=None,
            monitor=None, steps_per_dispatch=None, resume=None,
            checkpoint_prefix=None, checkpoint_every_n_batches=None,
            checkpoint_keep=3, checkpoint_async=None, guard=None,
            dispatch_pipeline=None):
        """The training loop (ref: base_module.py:368-519).

        Data-parallel scaling (docs/perf.md "Data-parallel scaling"): a
        Module built over multiple contexts (or ``MXTPU_DP_DEVICES=N``)
        trains the SAME fused K-step scan over a 'data' mesh — superbatches
        land per-chip sharded straight off the producer thread, params and
        optimizer state are replicated, the gradient all-reduce runs inside
        the donated compiled body, and the packed metric/sentinel array
        comes back globally reduced so the per-K readback stays one small
        host transfer. The guard and checkpoint/resume stack below compose
        unchanged: a chip-count-N run checkpoints and resumes exactly like
        N=1.

        ``steps_per_dispatch=k`` (default: ``engine.bulk_size()``, normally
        1) bulks K train steps into ONE compiled dispatch over a stacked
        superbatch: Python dispatch overhead and the per-step host metric
        readback amortize over K (docs/perf.md "Dispatch bulking"). Metric,
        callback and lr-scheduler plumbing run at K-step granularity —
        ``nbatch`` still counts single batches, but batch_end_callback fires
        once per dispatch. Requires the fused fast path and an acc/ce-style
        metric; configurations that cannot bulk fall back to k=1 with a
        warning.

        Fault tolerance (docs/robustness.md): ``checkpoint_prefix`` turns
        on atomic checksummed checkpoints — every epoch end, plus every
        ``checkpoint_every_n_batches`` completed batches (rounded to a
        dispatch boundary under ``steps_per_dispatch``). ``resume='auto'``
        restores the newest *valid* checkpoint (params, optimizer state,
        lr/update clock, RNG stream, metric partial sums) and fast-forwards
        the train iterator past the already-trained batches, so a killed
        run re-launched with the same script continues bit-for-bit. The
        last ``checkpoint_keep`` checkpoints are retained.
        ``checkpoint_async=True`` (env default ``MXTPU_ASYNC_CKPT``) moves
        the D2H + serialize + hash + fsync work to a background writer
        thread (docs/robustness.md "Asynchronous checkpointing"): the loop
        pays only for an on-device snapshot, blocks on the writer only at
        epoch ends / rollback / teardown, and sheds (counts) a cadence
        save whose predecessor is still in flight. Checkpoint bytes and
        crash-consistency invariants are identical to the sync path.

        Host off the critical path (docs/perf.md): under
        ``steps_per_dispatch=k`` the dispatch loop is PIPELINED —
        ``dispatch_pipeline=d`` (env default ``MXTPU_DISPATCH_PIPELINE``,
        1) defers each dispatch's packed metric/sentinel readback until
        ``d`` further dispatches are enqueued, so the device never idles
        on the host between scans. Metric, Speedometer, batch callbacks
        and the guard consume d-dispatch-lagged sums in strict dispatch
        order (bitwise-identical fold sequence; divergence detection gains
        a bounded staleness of d dispatches); checkpoint sealing always
        drains the pipeline first, so a diverged state can never be sealed
        known-good. ``dispatch_pipeline=0`` — and any per-step
        configuration (k=1, monitors, epoch tails) — is the eager mode.

        Numerical guardrails (docs/robustness.md "Numerical guardrails"):
        ``guard=True`` (or a configured
        :class:`~mxnet_tpu.guard.TrainingGuard`; ``MXTPU_GUARD=1`` turns it
        on by default) makes non-finite steps device-side no-ops counted in
        ``guard.health``, watches a rolling loss window, and on divergence
        rolls back to the newest *known-good* checkpoint with the lr
        reduced by ``guard.lr_factor`` — raising
        :class:`~mxnet_tpu.guard.TrainingDivergedError` once
        ``guard.max_rollbacks`` is exhausted (or immediately when no
        ``checkpoint_prefix``/known-good checkpoint exists to roll back
        to). Requires the fused fast path; ineligible configurations warn
        and train unguarded.
        """
        assert num_epoch is not None, "please specify number of epochs"
        from ..initializer import Uniform
        ckpt_mgr = None
        resume_state = None
        if checkpoint_prefix is not None:
            from ..model import CheckpointManager
            if isinstance(checkpoint_prefix, CheckpointManager):
                # callers (bench.py host-overhead mode, tests) may pass a
                # preconfigured manager to read its counters afterwards
                ckpt_mgr = checkpoint_prefix
            else:
                ckpt_mgr = CheckpointManager(checkpoint_prefix,
                                             keep=checkpoint_keep,
                                             logger=self.logger)
        if resume in ("auto", True):
            if ckpt_mgr is None:
                raise MXNetError("fit(resume=%r) requires checkpoint_prefix"
                                 % (resume,))
            resume_state = ckpt_mgr.load_latest()
            if resume_state is None:
                self.logger.info("resume='auto': no valid checkpoint under "
                                 "%r, starting fresh", checkpoint_prefix)
            else:
                self.logger.info(
                    "resuming from checkpoint %s (epoch %d, %d batches "
                    "done)", resume_state.tag, resume_state.epoch,
                    resume_state.batches_done)
                arg_params = resume_state.arg_params
                aux_params = resume_state.aux_params
                force_init = True
                begin_epoch = resume_state.epoch
        elif resume not in (None, False):
            raise MXNetError("resume must be 'auto' or None, got %r"
                             % (resume,))
        if initializer is None:
            initializer = Uniform(0.01)
        self.bind(data_shapes=train_data.provide_data,
                  label_shapes=train_data.provide_label, for_training=True,
                  force_rebind=force_rebind)
        if monitor is not None:
            self.install_monitor(monitor)
        self.init_params(initializer=initializer, arg_params=arg_params,
                         aux_params=aux_params, allow_missing=allow_missing,
                         force_init=force_init)
        self.init_optimizer(kvstore=kvstore, optimizer=optimizer,
                            optimizer_params=optimizer_params)
        if resume_state is not None:
            self._apply_resume_state(resume_state)
        if validation_metric is None:
            validation_metric = eval_metric
        if not isinstance(eval_metric, _metric.EvalMetric):
            eval_metric = _metric.create(eval_metric)

        # numerical guardrails (docs/robustness.md "Numerical guardrails")
        from ..guard import TrainingGuard, _DivergenceRollback
        if guard is None and env_bool("MXTPU_GUARD"):
            guard = True
        if guard in (None, False):
            guard = None
        else:
            if not isinstance(guard, TrainingGuard):
                guard = TrainingGuard(logger=self.logger)
            can = getattr(self, "_can_guard", None)
            ok, why = (can() if can is not None
                       else (False, "this module has no fused guard "
                             "support"))
            if not ok:
                self.logger.warning(
                    "guard: training-health guardrails unavailable (%s); "
                    "training UNGUARDED", why)
                guard = None
            elif ckpt_mgr is None:
                self.logger.warning(
                    "guard: no checkpoint_prefix — divergence cannot roll "
                    "back and will raise TrainingDivergedError")

        # asynchronous checkpointing (docs/robustness.md): attach a
        # background writer so cadence saves cost the loop only a device
        # snapshot; created here (after guard resolution) so back-pressure
        # skips count into THIS run's health object
        writer_owned = False
        if ckpt_mgr is not None:
            if checkpoint_async is None:
                checkpoint_async = env_bool("MXTPU_ASYNC_CKPT")
            if checkpoint_async and ckpt_mgr.async_writer is None:
                from ..model import AsyncCheckpointWriter
                from .. import guard as _guard_mod
                ckpt_mgr.async_writer = AsyncCheckpointWriter(
                    logger=self.logger,
                    health=(guard.health if guard is not None
                            else _guard_mod.TRAINING_HEALTH))
                writer_owned = True

        fused_step = getattr(self, "_try_fused_fit_step", None)
        fused_dispatch = getattr(self, "_dispatch_fused_steps", None)
        # knob resolution (docs/perf.md "Autotuning"): explicit arg > env
        # > tuning DB > built-in default, per knob — a DB hit is logged
        # once per run via the obs registry, so the training log always
        # says where the configuration came from
        from .. import autotune as _autotune
        k, pl_depth, _knob_src = _autotune.resolve_fit_knobs(
            self, train_data, steps_per_dispatch, dispatch_pipeline,
            logger=self.logger)
        if k > 1:
            reason = None
            if monitor is not None:
                reason = "a monitor needs per-step executor access"
            elif fused_dispatch is None:
                reason = "this module has no fused multi-step path"
            elif not hasattr(train_data, "superbatch"):
                reason = "train_data is not a DataIter (no superbatch mode)"
            else:
                # module-level eligibility (optimizer/grad_req/dist) AND
                # the metric's packed-accumulator layout (docs/perf.md
                # "Packed accumulators") are knowable NOW — checking here
                # instead of per dispatch avoids silently paying
                # superbatch stacking for an epoch the per-step path ends
                # up training anyway, and guarantees every fallback warns
                # with a reason that names WHY (metric, shapes, config)
                can = getattr(self, "_can_bulk_dispatch", None)
                if can is not None:
                    ok, why = can(eval_metric)
                    if not ok:
                        reason = why
            if reason is not None:
                self.logger.warning(
                    "steps_per_dispatch=%d unavailable (%s); training "
                    "with 1", k, reason)
                k = 1

        # pipelined dispatch (docs/perf.md "Host off the critical path"):
        # eager mode is auto-selected for per-step configurations — k=1
        # trains through per-step host metrics, whose output readback is
        # the sync point the pipeline would otherwise defer
        pl_depth = max(0, int(pl_depth))
        if k <= 1 or fused_dispatch is None:
            pl_depth = 0
        if getattr(self, "_is_dist_kvstore", lambda: False)():
            # elastic dist training (docs/robustness.md): every dispatch
            # already blocks on the cross-process reduction so a peer
            # failure surfaces AT its dispatch — a deferred-readback
            # window would only widen the state a WorkerLostError has to
            # discard at re-form time
            pl_depth = 0
        pipeline = _DispatchPipeline(pl_depth)
        if k > 1:
            # device-fed input tier (docs/perf.md "Device-fed input
            # pipeline"): the prefetcher stacks K host batches per dispatch
            # and lands them D+1 deep ahead of the depth-D dispatch
            # pipeline, charging stack/H2D/stall to the pipeline's
            # PipelineStats. A data-parallel mesh hands it the batch-axis
            # sharding so every stacked array LANDS per-chip sharded — the
            # one H2D is the scatter, and the dispatch loop never pays a
            # resharding copy (docs/perf.md "Data-parallel scaling")
            sb_sharding = getattr(self, "_superbatch_sharding", None)
            from .. import data as _data
            train_iter = _data.DevicePrefetcher(
                train_data, k, depth=pl_depth,
                sharding=sb_sharding() if sb_sharding is not None else None)
        else:
            train_iter = train_data
        # deterministic resume through shuffling iterators: pin the data
        # order to the ABSOLUTE epoch — a fresh process resuming at epoch E
        # must re-derive epoch E's shuffle, not epoch 0's (iterators
        # without epoch-addressable order ignore this)
        iter_set_epoch = getattr(train_iter, "set_epoch", None)
        if iter_set_epoch is not None:
            iter_set_epoch(begin_epoch)
        data_stats = (getattr(train_iter, "stats", None)
                      or getattr(train_iter, "data_stats", None))

        note_retired = getattr(self, "_note_dispatch_retired", None)

        def _consume(entries, epoch):
            """Retire dispatches in dispatch order: fold each one's sums
            into the metric and the guard, then fire ITS batch callback
            before folding the next — so every callback observes the
            metric exactly as the eager mode would have at the same
            nbatch (the fold+fire sequence is what the bitwise
            pipelined-vs-eager parity contract pins)."""
            from .. import obs as _obs
            for sums, nsteps, nb, disp in entries:
                _metric.update_from_device_sums(eval_metric, sums)
                if guard is not None:
                    guard.on_dispatch(loss_sum=sums.loss_sum,
                                      nsamp=sums.num_samples,
                                      skipped=sums.skipped,
                                      grad_norm=sums.last_grad_norm,
                                      nsteps=nsteps)
                if note_retired is not None:
                    note_retired(sums, nsteps)
                # flight recorder: the per-dispatch counter delta rides
                # the marks ring so a post-mortem shows what each of the
                # last K dispatches changed (docs/observability.md)
                _obs.flight.note("dispatch_retired", dispatch=disp,
                                 nbatch=nb, nsteps=nsteps)
                if batch_end_callback is not None:
                    cb_params = BatchEndParam(
                        epoch=epoch, nbatch=nb, eval_metric=eval_metric,
                        locals={"guard": guard, "pipeline": pipeline,
                                "eval_metric": eval_metric, "self": self,
                                "data_stats": data_stats})
                    for callback in _as_list(batch_end_callback):
                        callback(cb_params)

        # flight-recorder baseline (docs/observability.md): mark the run
        # start so the FIRST retired dispatch's counter delta covers that
        # dispatch, not "everything since the process began"
        from ..obs import flight as _obs_flight
        from ..kvstore import WorkerLostError as _WorkerLost
        _obs_flight.note("fit_start", epoch=begin_epoch)

        # graceful preemption (docs/robustness.md "Graceful preemption"):
        # SIGTERM is the TPU-preemption shape — the scheduler gives the VM
        # a grace window, then pulls the plug. Install a handler that only
        # SETS A FLAG (checked once per loop iteration, so the signal never
        # interrupts a dispatch mid-flight) and starts a hard wall-clock
        # deadline: a graceful exit that cannot finish in time degrades to
        # an abrupt one, which the SIGKILL resume contract already covers.
        # Installed only when there is a checkpoint manager to seal an
        # emergency save into, and only on the main thread (signal() is
        # main-thread-only; nested/threaded fits keep default delivery).
        import signal as _signal
        import threading as _threading
        preempt = None
        prev_sigterm = None
        sigterm_installed = False
        if (ckpt_mgr is not None
                and not env_bool("MXTPU_SIGTERM_GRACEFUL_OFF")
                and _threading.current_thread() is _threading.main_thread()):
            preempt = {"flag": False, "timer": None}
            _deadline_s = env_float("MXTPU_SIGTERM_DEADLINE", 30.0)

            def _on_sigterm(signum, frame, _p=preempt, _d=_deadline_s):
                if _p["flag"]:
                    return
                _p["flag"] = True
                t = _threading.Timer(_d, os._exit, args=(124,))
                t.daemon = True
                t.start()
                _p["timer"] = t
            prev_sigterm = _signal.getsignal(_signal.SIGTERM)
            _signal.signal(_signal.SIGTERM, _on_sigterm)
            sigterm_installed = True
        try:
            epoch = begin_epoch
            while epoch < num_epoch:
                tic = time.time()
                eval_metric.reset()
                nbatch = -1
                since_ckpt = 0
                resume_skip = 0
                if (resume_state is not None
                        and epoch == resume_state.epoch
                        and resume_state.batches_done > 0):
                    # mid-epoch resume (or divergence rollback): replay the
                    # metric's partial sums and fast-forward past the
                    # already-trained batches (the iterator is consumed but
                    # nothing is computed)
                    resume_skip = resume_state.batches_done
                    self._restore_metric_state(eval_metric,
                                               resume_state.metric_state)
                    self.logger.info("resume: fast-forwarding %d batches "
                                     "of epoch %d", resume_skip, epoch)
                try:
                    for data_batch in train_iter:
                        tail_batches = None
                        stepped_eager = False
                        if resume_skip > 0:
                            n = getattr(data_batch, "num_steps", 1)
                            if n <= resume_skip:
                                resume_skip -= n
                                nbatch += n
                                continue
                            # checkpoint cut through a superbatch (k changed
                            # between runs): train only the un-skipped tail,
                            # per-step
                            tail_batches = data_batch.unstack()[resume_skip:]
                            nbatch += resume_skip
                            resume_skip = 0
                        if monitor is not None:
                            monitor.tic()
                        # fast path: K fused steps in one donated lax.scan
                        # dispatch; the packed metric/sentinel readback is
                        # DEFERRED through the pipeline so dispatch N+1 is
                        # enqueued before dispatch N's np.asarray
                        sums = None
                        disp_id = getattr(data_batch, "sb_seq",
                                          pipeline.dispatches)
                        if (tail_batches is None and k > 1
                                and getattr(data_batch, "num_steps", 0) == k
                                and fused_dispatch is not None):
                            # the "dispatch" span is the ENQUEUE — the
                            # device-side scan runs async; its readback is
                            # the correlated readback_stall span
                            with _obs_trace.span("dispatch",
                                                 dispatch=disp_id,
                                                 k=data_batch.num_steps,
                                                 epoch=epoch):
                                sums = fused_dispatch(data_batch, guard)
                        if sums is not None:
                            nbatch += data_batch.num_steps
                            since_ckpt += data_batch.num_steps
                            _consume(pipeline.push(
                                sums, data_batch.num_steps, nbatch,
                                disp=disp_id), epoch)
                        else:
                            # per-step path: the general executor loop, also
                            # the epoch tail (num_steps < k) without a
                            # K'-recompile. Eager by contract — per-step
                            # host metrics must fold in dispatch order, so
                            # everything still in flight retires first.
                            _consume(pipeline.drain(), epoch)
                            if tail_batches is None:
                                tail_batches = (
                                    data_batch.unstack()
                                    if hasattr(data_batch, "unstack")
                                    else [data_batch])
                            for batch in tail_batches:
                                nbatch += 1
                                since_ckpt += 1
                                if guard is not None:
                                    guard.last_step_skipped = False
                                # fused single step (falls back to the
                                # executor path when the module configuration
                                # needs it — monitor, dist kvstore, grad_req,
                                # unfused optimizer, bucketing/shared
                                # modules)
                                if monitor is not None or fused_step is None \
                                        or not fused_step(batch, guard):
                                    self.forward_backward(batch)
                                    self.update()
                                # a device-side skipped (non-finite) step
                                # contributes nothing to the metric
                                if guard is None \
                                        or not guard.last_step_skipped:
                                    self.update_metric(eval_metric,
                                                       batch.label)
                            stepped_eager = True
                        if monitor is not None:
                            monitor.toc_print()
                        if guard is not None and guard.diverged:
                            # unwind to the rollback handler BEFORE the
                            # checkpoint block: a diverged state must never
                            # be sealed into a checkpoint
                            raise _DivergenceRollback()
                        if (ckpt_mgr is not None
                                and checkpoint_every_n_batches
                                and since_ckpt >= checkpoint_every_n_batches):
                            # checkpoint sealing needs EVERY sentinel
                            # covering the state it is about to seal: drain
                            # the pipeline, re-check divergence, then gate
                            # on the (now fully informed) guard
                            _consume(pipeline.drain(), epoch)
                            if guard is not None and guard.diverged:
                                raise _DivergenceRollback()
                            if guard is None or guard.ok_to_checkpoint():
                                # a mid-spike state is suspect: deferring the
                                # save keeps the newest known-good checkpoint
                                # PRE-spike, so a rollback escapes the
                                # divergence instead of re-entering it
                                with _obs_trace.span("checkpoint",
                                                     dispatch=disp_id,
                                                     epoch=epoch,
                                                     nbatch=nbatch + 1):
                                    ckpt_mgr.save(self, epoch, nbatch + 1,
                                                  metric=eval_metric)
                                since_ckpt = 0
                        self._check_worker_health(
                            ckpt_mgr, eval_metric, epoch, nbatch,
                            drain_pipeline=lambda e=epoch: _consume(
                                pipeline.drain(), e),
                            guard=guard)
                        if preempt is not None and preempt["flag"]:
                            # SIGTERM landed: retire everything in flight
                            # (an emergency checkpoint must never seal a
                            # state its sentinels haven't cleared), then
                            # seal + raise — all inside the deadline timer
                            _consume(pipeline.drain(), epoch)
                            self._graceful_preempt(preempt, ckpt_mgr,
                                                   guard, eval_metric,
                                                   epoch, nbatch)
                        if stepped_eager and batch_end_callback is not None:
                            # eagerly-trained batches (per-step path): one
                            # callback at the current nbatch, exactly as
                            # before
                            batch_end_params = BatchEndParam(
                                epoch=epoch, nbatch=nbatch,
                                eval_metric=eval_metric, locals=locals())
                            for callback in _as_list(batch_end_callback):
                                callback(batch_end_params)
                    # epoch end: everything still in flight retires (folds
                    # + fires its callbacks) before the epoch is sealed
                    # (train metric logged, epoch-end checkpoint written) —
                    # and a divergence surfacing in those last sentinels
                    # still rolls back, never seals
                    _consume(pipeline.drain(), epoch)
                    if guard is not None and guard.diverged:
                        raise _DivergenceRollback()
                except _DivergenceRollback:
                    # divergence: restore the newest known-good checkpoint,
                    # rewind the trainer clock, reduce lr, and re-enter the
                    # epoch loop at the restored cursor (the iterator is
                    # reset and re-fast-forwarded like a resume). Dispatches
                    # still in the pipeline cover post-divergence state:
                    # their sums must never reach the metric or the guard
                    _obs_trace.instant("divergence", epoch=epoch,
                                       nbatch=nbatch,
                                       reason=guard.diverged_reason)
                    pipeline.discard()
                    resume_state = self._guard_rollback(guard, ckpt_mgr)
                    epoch = resume_state.epoch
                    train_iter.reset()
                    if iter_set_epoch is not None:
                        # the rollback rewinds the epoch cursor: re-pin the
                        # data order (reset() alone advances it by one)
                        iter_set_epoch(epoch)
                    continue
                except _WorkerLost as wle:
                    # elastic membership (docs/robustness.md "Elastic
                    # distributed training"): a peer died mid-epoch —
                    # discard in-flight dispatches (their cross-worker
                    # reductions never completed), seal an emergency
                    # checkpoint, re-form the ring at N-1, adopt the
                    # leader's state, and re-enter the epoch loop exactly
                    # like a resume
                    _obs_trace.instant("worker_lost", epoch=epoch,
                                       nbatch=nbatch)
                    pipeline.discard()
                    resume_state = self._elastic_reform(
                        wle, ckpt_mgr, guard, eval_metric, epoch, nbatch,
                        train_data)
                    epoch = resume_state.epoch
                    train_iter.reset()
                    if iter_set_epoch is not None:
                        iter_set_epoch(epoch)
                    continue

                for name, val in eval_metric.get_name_value():
                    self.logger.info("Epoch[%d] Train-%s=%f", epoch, name, val)
                toc = time.time()
                self.logger.info("Epoch[%d] Time cost=%.3f", epoch,
                                 (toc - tic))
                if guard is not None:
                    h = guard.health.report()
                    if h["skipped"] or h["rollbacks"] or h["retraces"]:
                        self.logger.info(
                            "Epoch[%d] TrainingHealth: skipped=%d "
                            "rollbacks=%d divergences=%d retraces=%d "
                            "last_grad_norm=%s",
                            epoch, h["skipped"], h["rollbacks"],
                            h["divergences"], h["retraces"],
                            h["last_grad_norm"])

                arg_params, aux_params = self.get_params()
                self.set_params(arg_params, aux_params)
                if epoch_end_callback is not None:
                    for callback in _as_list(epoch_end_callback):
                        callback(epoch, self.symbol, arg_params, aux_params)

                if eval_data:
                    res = self.score(
                        eval_data, validation_metric,
                        score_end_callback=eval_end_callback,
                        batch_end_callback=eval_batch_end_callback,
                        epoch=epoch)
                    for name, val in res:
                        self.logger.info("Epoch[%d] Validation-%s=%f", epoch,
                                         name, val)
                if ckpt_mgr is not None and (guard is None
                                             or guard.ok_to_checkpoint()):
                    # epoch boundary checkpoint: cursor points at the clean
                    # start of the next epoch (deferred while the loss
                    # watcher is mid-spike, same as cadence saves). The
                    # epoch end is a BARRIER for async saves: an in-flight
                    # cadence save lands first (so the epoch-end save is
                    # never shed by back-pressure), then fit blocks until
                    # the epoch's state is durably on disk
                    ckpt_mgr.drain()
                    with _obs_trace.span("checkpoint", epoch=epoch + 1,
                                         nbatch=0):
                        ckpt_mgr.save(self, epoch + 1, 0)
                    ckpt_mgr.drain()
                # epoch boundary is the ONLY admission point for late
                # joiners: a mid-epoch join would change the gradient
                # denominator between checkpoints
                self._admit_dist_joiners(ckpt_mgr, train_data)
                if train_iter is train_data or epoch < num_epoch - 1:
                    train_iter.reset()
                else:
                    # final epoch of a superbatch wrapper: stop its producer
                    # thread (reset() would spawn one that pre-pulls batches
                    # from — and pins device buffers for — an epoch nobody
                    # consumes) and hand the user back a reset base iterator
                    train_iter.close()
                    train_data.reset()
                epoch += 1
        finally:
            if sigterm_installed:
                _signal.signal(_signal.SIGTERM, prev_sigterm)
                # a SIGTERM that arrived too late to be honored (epoch tail,
                # teardown) must not leave a live os._exit timer behind
                if preempt["timer"] is not None:
                    preempt["timer"].cancel()
            if ckpt_mgr is not None and ckpt_mgr.async_writer is not None:
                # teardown barrier: the in-flight save lands (or is reaped)
                # before fit returns; a writer fit created is shut down AND
                # detached so the manager stays usable (a later fit makes a
                # fresh writer, a manual save falls back to sync) — its
                # counters stay readable via last_async_writer. A
                # caller-attached writer is only drained.
                if writer_owned:
                    w = ckpt_mgr.async_writer
                    w.close()
                    ckpt_mgr.async_writer = None
                    ckpt_mgr.last_async_writer = w
                else:
                    ckpt_mgr.async_writer.drain()
            if train_iter is not train_data:
                # exception paths included: never leave a producer thread
                # consuming the user's iterator (close() is idempotent)
                train_iter.close()

    # -- fused-dispatch hooks shared by Module and BucketingModule ------
    def _note_dispatch_retired(self, sums, nsteps):
        """Retirement hook for the dispatch pipeline: advance the
        host-side step-clock mirror for a GUARDED dispatch once its
        sentinels (the device-side skip count) have been fetched —
        skipped steps are full no-ops, the clock must not count them.
        Unguarded dispatches advanced at dispatch time."""
        if getattr(sums, "guarded", False):
            self._fused_host_step += int(nsteps) - sums.skipped

    def _feed_guard_sentinels(self, guard, sent):
        """Host side of one GUARDED single-step dispatch: advance the
        step-clock mirror skip-aware and feed the packed ``[loss,
        correct, nsamp, skipped, grad_norm]`` sentinel array to the
        guard (``last_step_skipped`` tells fit to keep the skipped batch
        out of host-side metrics). ONE definition — the sentinel packet
        layout must never drift between the Module and BucketingModule
        paths."""
        self._fused_host_step += 1 - int(sent[3] > 0)
        guard.on_dispatch(loss_sum=float(sent[0]), nsamp=float(sent[2]),
                          skipped=float(sent[3]),
                          grad_norm=float(sent[4]), nsteps=1)
        guard.last_step_skipped = bool(sent[3] > 0)

    def _adopt_retrace_result(self, e, nsteps, guard):
        """``MXTPU_TRACECHECK=error`` raised mid-dispatch
        (tracecheck.RetraceError): the dispatch already ran and DONATED
        the previous fused state, and the new state rides in
        ``e.result`` — adopt it so ``_fused_state`` never dangles on
        deleted buffers (``get_params`` / emergency checkpoints after
        catching the error keep working). The step-clock mirror advances
        as on the success path; the run is aborting, so the guarded
        paths' sentinel readback costs nothing that matters."""
        if e.result is None:
            return
        self._fused_state = e.result[0]
        self._fused_outputs = None
        self._fused_dirty = True
        self._params_dirty = True
        if guard is None:
            self._fused_host_step += nsteps
            return
        tail = e.result[-1]
        if hasattr(tail, "skipped"):   # StepMetrics (run_steps path)
            skipped = int(tail.skipped)
        else:                          # packed sentinel array (step path)
            skipped = int(np.asarray(tail)[3] > 0)
        self._fused_host_step += nsteps - skipped

    # -- fault tolerance hooks (docs/robustness.md) ---------------------
    def _graceful_preempt(self, preempt, ckpt_mgr, guard, eval_metric,
                          epoch, nbatch):
        """Honor a SIGTERM (docs/robustness.md "Graceful preemption"): the
        dispatch pipeline is already drained by the caller — seal an
        emergency checkpoint with the async writer drained on both sides
        (so the save is never shed by back-pressure and is durably on disk
        before we exit), dump the flight recorder, cancel the hard-deadline
        timer and raise :class:`TrainingPreemptedError`. The checkpoint
        cursor is ``nbatch + 1`` mid-epoch — strictly newer than the last
        cadence save a SIGKILL at the same moment would resume from."""
        tag = None
        if ckpt_mgr is not None and (guard is None
                                     or guard.ok_to_checkpoint()):
            ckpt_mgr.drain()
            with _obs_trace.span("checkpoint", epoch=epoch,
                                 nbatch=nbatch + 1, preempt=True):
                ckpt_mgr.save(self, epoch, nbatch + 1, metric=eval_metric)
            ckpt_mgr.drain()
            tag = "e%04d-b%08d" % (epoch, nbatch + 1)
        self.logger.warning(
            "SIGTERM: graceful preemption — emergency checkpoint %s sealed "
            "at epoch %d batch %d; re-launch with resume='auto' to "
            "continue", tag or "(none: guard mid-spike or no manager)",
            epoch, nbatch + 1)
        from ..obs import flight as _flight
        _flight.dump("TrainingPreemptedError: SIGTERM preemption",
                     extra={"epoch": epoch, "nbatch": nbatch, "tag": tag})
        if preempt["timer"] is not None:
            preempt["timer"].cancel()
        raise TrainingPreemptedError(
            "training preempted by SIGTERM at epoch %d batch %d "
            "(emergency checkpoint: %s) — resume='auto' continues from it"
            % (epoch, nbatch + 1, tag), epoch=epoch,
            batches_done=nbatch + 1, tag=tag)

    def _guard_rollback(self, guard, ckpt_mgr):
        """Divergence recovery (docs/robustness.md "Numerical guardrails"):
        restore the newest known-good checkpoint, rewind the trainer clock
        and RNG stream, reduce the lr by ``guard.lr_factor``, and hand the
        restored cursor back to ``fit``'s epoch loop (which resets and
        re-fast-forwards the iterator). Raises
        :class:`~mxnet_tpu.guard.TrainingDivergedError` when the rollback
        budget is exhausted or there is nothing safe to roll back to."""
        from ..guard import TrainingDivergedError
        from ..obs import flight as _flight

        def _diverged(msg):
            # the post-mortem (docs/observability.md): the last K
            # dispatches' spans + counter deltas land on disk BEFORE the
            # error unwinds — dump() never raises into this failure path
            _flight.dump("TrainingDivergedError: %s" % msg,
                         extra={"health": guard.health.report()})
            return TrainingDivergedError(msg, health=guard.health)

        if guard.health.rollbacks >= guard.max_rollbacks:
            raise _diverged(
                "training diverged again after %d rollback(s) "
                "(max_rollbacks=%d): %s"
                % (guard.health.rollbacks, guard.max_rollbacks,
                   guard.diverged_reason))
        if ckpt_mgr is None:
            raise _diverged(
                "training diverged (%s) and fit() has no checkpoint_prefix "
                "to roll back to — configure checkpoints or lower the lr"
                % (guard.diverged_reason,))
        # async saves: the rollback target search must see the newest save
        # fully on disk (manifest + latest), not race a half-written one
        ckpt_mgr.drain()
        st = ckpt_mgr.load_latest()
        if st is None:
            raise _diverged(
                "training diverged (%s) and no known-good checkpoint "
                "exists under %r" % (guard.diverged_reason,
                                     ckpt_mgr.prefix))
        self.logger.warning(
            "TrainingGuard: rolling back to known-good checkpoint %s "
            "(epoch %d, %d batches done), reducing lr by x%g",
            st.tag, st.epoch, st.batches_done, guard.lr_factor)
        self.init_params(initializer=None, arg_params=st.arg_params,
                         aux_params=st.aux_params, allow_missing=False,
                         force_init=True)
        # the diverged fused state must NOT survive (its optimizer state is
        # poisoned); drop it BEFORE restoring the checkpointed one
        self._drop_fused_state()
        self._apply_resume_state(st)
        self._scale_lr(guard.lr_factor)
        # a SURVIVED divergence still leaves a post-mortem: the timeline
        # that led into the rollback is exactly what the next tuning pass
        # needs, and a rerun would not reproduce it (captured BEFORE
        # note_rollback clears diverged_reason)
        _flight.dump("guard rollback to %s (%s)"
                     % (st.tag, guard.diverged_reason),
                     extra={"health": guard.health.report(),
                            "rollback_tag": st.tag,
                            "rollback_epoch": st.epoch})
        guard.note_rollback(st.tag)
        return st

    def _elastic_reform(self, err, ckpt_mgr, guard, eval_metric, epoch,
                        nbatch, train_data=None):
        """Worker-loss recovery (docs/robustness.md "Elastic distributed
        training"): survivors seal a durable emergency checkpoint, re-form
        the control-plane ring at N-1, adopt ONE authoritative state (the
        leader's newest checkpoint — survivors can legitimately be one
        step apart at the failure point), re-derive the gradient rescale
        and this worker's data shard for the shrunken world, and hand
        ``fit`` a resume cursor. Raises :class:`WorkerLostError` (with a
        flight dump) when the re-form budget (``MXTPU_KV_MAX_REFORMS``)
        is exhausted or the store has no elastic transport."""
        from ..kvstore import WorkerLostError
        from ..obs import flight as _flight
        kv = getattr(self, "_kvstore", None)
        if kv is None or getattr(kv, "reform", None) is None \
                or ckpt_mgr is None:
            why = ("fit() has no checkpoint_prefix to recover through"
                   if kv is not None and ckpt_mgr is None
                   else "kvstore has no elastic re-form support")
            _flight.dump("WorkerLostError: %s" % err, extra={"elastic": why})
            raise err
        max_reforms = int(getattr(kv, "max_reforms", 0))
        if int(getattr(kv, "reforms", 0)) >= max_reforms:
            _flight.dump("WorkerLostError: re-form budget exhausted",
                         extra={"reforms": int(kv.reforms),
                                "max_reforms": max_reforms,
                                "liveness": kv.liveness_table()})
            raise WorkerLostError(
                "worker lost and the re-form budget is exhausted (%d "
                "re-form(s) this run, MXTPU_KV_MAX_REFORMS=%d): %s"
                % (kv.reforms, max_reforms, err)) from err
        self.logger.warning(
            "worker lost (%s): re-forming the ring (re-form %d/%d)",
            err, int(kv.reforms) + 1, max_reforms)
        # 1. seal this survivor's own durable emergency checkpoint BEFORE
        # any further ring traffic: if the re-form itself fails, the run
        # stays resumable from here (drain twice — an in-flight cadence
        # save lands first, then the emergency save must be on disk)
        ckpt_mgr.drain()
        if guard is None or guard.ok_to_checkpoint():
            ckpt_mgr.save(self, epoch, nbatch + 1, metric=eval_metric)
        ckpt_mgr.drain()
        # 2. re-form at N-1 (plus any joiners already waiting)
        kv.reform()
        # 3. one authoritative state for the new ring
        st = self._adopt_leader_checkpoint(kv, ckpt_mgr)
        self.init_params(initializer=None, arg_params=st.arg_params,
                         aux_params=st.aux_params, allow_missing=False,
                         force_init=True)
        self._drop_fused_state()
        # rescale/batch-size re-derivation MUST precede the optimizer
        # state restore: set_optimizer builds a fresh (empty) kvstore
        # updater, which _apply_resume_state then re-fills
        self._refresh_dist_scale()
        self._apply_resume_state(st)
        self._reshard_train_data(kv, train_data)
        _flight.dump(
            "ring re-formed at %d worker(s), resuming from %s"
            % (kv.num_workers, st.tag),
            extra={"liveness": kv.liveness_table(),
                   "reforms": int(kv.reforms), "resume_tag": st.tag,
                   "resume_epoch": st.epoch,
                   "batches_done": st.batches_done})
        self.logger.warning(
            "ring re-formed: %d worker(s) (this rank now index %d), "
            "resuming from %s (epoch %d, %d batches done)",
            kv.num_workers, kv.worker_index, st.tag, st.epoch,
            st.batches_done)
        return st

    def _adopt_leader_checkpoint(self, kv, ckpt_mgr):
        """Broadcast the leader's newest checkpoint BYTES over the ring
        and install + load it on every member. Survivors may be one step
        apart at the failure point; adopting one authoritative state is
        what makes the re-formed replicas bitwise-identical — and a fresh
        resume from the same prefix then reproduces exactly this state
        (the invariant the elastic test pins)."""
        payload = b""
        if kv.worker_index == 0:
            payload = ckpt_mgr.export_latest()
        blob = kv.broadcast_bytes(payload)
        if kv.worker_index != 0 and blob:
            ckpt_mgr.import_blob(blob)
        st = ckpt_mgr.load_latest()
        if st is None:
            raise MXNetError(
                "ring re-form: no loadable checkpoint after the leader "
                "broadcast (prefix %r)" % (ckpt_mgr.prefix,))
        return st

    def _reshard_train_data(self, kv, train_data):
        """Re-derive this worker's data shard from its new (index, size)
        after a membership change. Iterators expose ``reshard_workers``;
        anything else keeps its original shard — correct but overlapping,
        so the run says so."""
        if train_data is None:
            return
        reshard = getattr(train_data, "reshard_workers", None)
        if reshard is not None:
            reshard(kv.worker_index, kv.num_workers)
        else:
            self.logger.warning(
                "train_data has no reshard_workers(index, size): keeping "
                "the pre-reform shard (the dead worker's shard is not "
                "redistributed this run)")

    def _admit_dist_joiners(self, ckpt_mgr, train_data):
        """Epoch-boundary admission (docs/robustness.md "Elastic
        distributed training"): when a late worker has published a join
        request, re-form the ring to include it and broadcast the
        leader's epoch-boundary checkpoint as its warm start; incumbents
        re-derive shards and rescale exactly like a loss re-form. The
        decision itself rides a leader broadcast so every incumbent
        reaches the SAME verdict — per-member polling could split on a
        request that lands mid-poll."""
        kv = getattr(self, "_kvstore", None)
        if kv is None or "dist" not in getattr(kv, "type", ""):
            return
        poll = getattr(kv, "pending_joiners", None)
        bcast = getattr(kv, "broadcast_bytes", None)
        if poll is None or bcast is None or ckpt_mgr is None \
                or kv.num_workers <= 0:
            return
        import pickle
        payload = b""
        if kv.worker_index == 0:
            payload = pickle.dumps(sorted(poll()))
        blob = bcast(payload)
        if not blob:
            return  # no elastic transport: broadcast_bytes is identity
        pending = pickle.loads(blob)
        if not pending:
            return
        self.logger.info("admitting joining worker(s) %s at the epoch "
                         "boundary", list(pending))
        kv.reform()
        self._adopt_leader_checkpoint(kv, ckpt_mgr)
        self._drop_fused_state()
        self._refresh_dist_scale()
        self._reshard_train_data(kv, train_data)

    def _refresh_dist_scale(self):
        """Hook: re-derive the gradient rescale (1 / global batch) after
        a dist membership change. Subclasses with an optimizer
        override."""

    def _drop_fused_state(self):
        """Hook: discard (not flush) any fused device state so the next
        dispatch reseeds from the just-restored params. Subclasses with a
        fused path override."""

    def _scale_lr(self, factor):
        """Hook: reduce the learning rate everywhere the next step reads it
        (rollback policy). Subclasses with an optimizer override."""

    def _apply_resume_state(self, st):
        """Restore optimizer state, update clock and RNG stream from a
        validated checkpoint (params/aux already rode ``init_params``).
        Called by ``fit`` right after ``init_optimizer``."""
        if st.opt_states_file and hasattr(self, "load_optimizer_states"):
            self.load_optimizer_states(st.opt_states_file)
        self._restore_trainer_clock(st.num_update,
                                    getattr(st, "fused_step", None))
        st.restore_rng()

    def _restore_trainer_clock(self, num_update, fused_step=None):
        """Hook: carry the optimizer update count across a resume so lr
        schedules and per-step noise streams continue where the killed run
        stopped. ``fused_step`` is the device step counter, which trails
        ``num_update`` by the number of guard-skipped steps (a skip is a
        full no-op). Subclasses with an optimizer override."""

    @staticmethod
    def _restore_metric_state(eval_metric, state):
        """Replay a checkpointed metric's partial sums into a freshly reset
        metric (scalar or per-output list state; composites skip)."""
        if not state or not hasattr(eval_metric, "sum_metric"):
            return
        try:
            s, n = state
        except (TypeError, ValueError):
            return
        eval_metric.sum_metric = s
        eval_metric.num_inst = n

    def _check_worker_health(self, ckpt_mgr, eval_metric, epoch, nbatch,
                             drain_pipeline=None, guard=None):
        """Dist kvstore degradation policy: feed ``num_dead_node`` into
        warn -> emergency checkpoint -> ``WorkerLostError`` escalation
        (KVStore.check_health throttles the underlying heartbeat scan).
        No-op for local stores."""
        kv = getattr(self, "_kvstore", None)
        if kv is None or "dist" not in getattr(kv, "type", ""):
            return
        on_degraded = None
        if ckpt_mgr is not None:
            def on_degraded():
                # checkpoint sealing needs every in-flight dispatch retired
                # first (metric folds + guard sentinels + step mirror must
                # cover the state being saved) — same invariant as the
                # cadence/epoch-end sites, and a diverged state still must
                # never seal known-good
                if drain_pipeline is not None:
                    drain_pipeline()
                if guard is not None and not guard.ok_to_checkpoint():
                    self.logger.warning(
                        "worker-loss emergency checkpoint skipped: the "
                        "guard reports the current state unsafe to seal")
                    return
                # emergency checkpoint must never be shed by async
                # back-pressure (a cadence save in flight) and must be
                # durable BEFORE check_health escalates to WorkerLostError
                ckpt_mgr.drain()
                ckpt_mgr.save(self, epoch, nbatch + 1, metric=eval_metric)
                ckpt_mgr.drain()
        kv.check_health(on_degraded=on_degraded)

    # -- symbol / params accessors -------------------------------------
    @property
    def symbol(self):
        return self._symbol

    def get_params(self):
        raise NotImplementedError()

    def init_params(self, initializer=None, arg_params=None, aux_params=None,
                    allow_missing=False, force_init=False):
        raise NotImplementedError()

    def set_params(self, arg_params, aux_params, allow_missing=False,
                   force_init=True):
        self.init_params(initializer=None, arg_params=arg_params,
                         aux_params=aux_params, allow_missing=allow_missing,
                         force_init=force_init)

    def save_params(self, fname):
        from ..model import atomic_write_bytes, _param_save_bytes
        arg_params, aux_params = self.get_params()
        atomic_write_bytes(fname, _param_save_bytes(arg_params, aux_params))

    def load_params(self, fname):
        from ..model import _split_param_dict
        save_dict = nd.load(fname)
        arg_params, aux_params = _split_param_dict(save_dict, fname)
        self.set_params(arg_params, aux_params)

    # -- computation API (implemented by subclasses) --------------------
    def forward(self, data_batch, is_train=None):
        raise NotImplementedError()

    def backward(self, out_grads=None):
        raise NotImplementedError()

    def update(self):
        raise NotImplementedError()

    def get_outputs(self, merge_multi_context=True):
        raise NotImplementedError()

    def get_input_grads(self, merge_multi_context=True):
        raise NotImplementedError()

    def update_metric(self, eval_metric, labels):
        raise NotImplementedError()

    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, shared_module=None,
             grad_req="write"):
        raise NotImplementedError()

    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.01),),
                       force_init=False):
        raise NotImplementedError()

    def install_monitor(self, mon):
        raise NotImplementedError()

    @property
    def data_names(self):
        raise NotImplementedError()

    @property
    def output_names(self):
        raise NotImplementedError()

    @property
    def data_shapes(self):
        raise NotImplementedError()

    @property
    def label_shapes(self):
        raise NotImplementedError()

    @property
    def output_shapes(self):
        raise NotImplementedError()
