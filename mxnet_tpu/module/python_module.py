"""Pure-Python modules: stateless computation steps in a Module pipeline.

API parity with the reference (ref: python/mxnet/module/python_module.py:338;
PythonModule base + PythonLossModule). These carry no parameters and no
executor — they exist so users can interleave host-side computation (custom
losses, constraint projections) with SequentialModule stages.
"""
from __future__ import annotations

import logging

from .. import ndarray as nd
from ..initializer import Uniform
from .base_module import BaseModule


class PythonModule(BaseModule):
    """A module whose computation is defined in Python rather than by a
    Symbol. Parameter/optimizer APIs default to no-ops; subclasses override
    ``forward``/``backward`` and ``_compute_output_shapes``."""

    def __init__(self, data_names, label_names, output_names, logger=logging):
        super().__init__(logger=logger)
        self._data_names = list(data_names)
        self._label_names = list(label_names) if label_names is not None else None
        self._output_names = list(output_names)
        self._data_shapes = None
        self._label_shapes = None
        self._output_shapes = None
        self.params_initialized = True      # no params to initialize

    # -- symbol information --------------------------------------------
    @property
    def data_names(self):
        return self._data_names

    @property
    def output_names(self):
        return self._output_names

    # -- shapes --------------------------------------------------------
    @property
    def data_shapes(self):
        return self._data_shapes

    @property
    def label_shapes(self):
        return self._label_shapes

    @property
    def output_shapes(self):
        return self._output_shapes

    # -- parameters (none by default) ----------------------------------
    def get_params(self):
        return {}, {}

    def init_params(self, initializer=Uniform(0.01), arg_params=None,
                    aux_params=None, allow_missing=False, force_init=False):
        pass

    def update(self):
        pass

    def update_metric(self, eval_metric, labels):
        if self._label_shapes is None:
            return
        eval_metric.update(labels, self.get_outputs())

    # -- setup ---------------------------------------------------------
    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, shared_module=None,
             grad_req="write"):
        if self.binded and not force_rebind:
            self.logger.warning("Already bound, ignoring bind()")
            return
        if grad_req != "write":
            raise ValueError("PythonModule only supports grad_req='write'")
        if [x[0] for x in data_shapes] != self._data_names:
            raise ValueError("data_shapes names %r != %r"
                             % ([x[0] for x in data_shapes], self._data_names))
        if (label_shapes is not None and self._label_names is not None
                and [x[0] for x in label_shapes] != self._label_names):
            raise ValueError("label_shapes names mismatch")
        self.for_training = for_training
        self.inputs_need_grad = inputs_need_grad
        self._data_shapes = data_shapes
        self._label_shapes = label_shapes
        self._output_shapes = self._compute_output_shapes()
        self.binded = True

    def _compute_output_shapes(self):
        raise NotImplementedError()

    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.01),),
                       force_init=False):
        pass


class PythonLossModule(PythonModule):
    """A loss head defined by a Python gradient function: forward passes
    scores through; backward calls ``grad_func(scores, labels)`` to produce
    the gradient w.r.t. the scores (ref: python_module.py PythonLossModule)."""

    def __init__(self, name="pyloss", data_names=("data",),
                 label_names=("softmax_label",), logger=logging,
                 grad_func=None):
        super().__init__(data_names, label_names,
                         [name + "_output"], logger=logger)
        if len(self._data_names) != 1:
            raise ValueError("PythonLossModule takes exactly one data")
        if self._label_names is not None and len(self._label_names) != 1:
            raise ValueError("PythonLossModule takes at most one label")
        self._name = name
        self._scores = None
        self._labels = None
        self._scores_grad = None
        if grad_func is not None and not callable(grad_func):
            raise TypeError("grad_func must be callable")
        self._grad_func = grad_func

    def _compute_output_shapes(self):
        return [(self._name + "_output", self._data_shapes[0][1])]

    def forward(self, data_batch, is_train=None):
        self._scores = data_batch.data[0]
        if is_train is None:
            is_train = self.for_training
        if is_train:
            self._labels = data_batch.label[0]

    def get_outputs(self, merge_multi_context=True):
        assert merge_multi_context is True
        return [self._scores]

    def backward(self, out_grads=None):
        assert out_grads is None, "loss module: out_grads must be None"
        assert self.for_training
        self._backward_impl()

    def _backward_impl(self):
        if self._grad_func is None:
            raise NotImplementedError(
                "pass grad_func or override _backward_impl")
        grad = self._grad_func(self._scores, self._labels)
        if not isinstance(grad, nd.NDArray):
            grad = nd.array(grad)
        self._scores_grad = grad

    def get_input_grads(self, merge_multi_context=True):
        assert merge_multi_context is True
        return [self._scores_grad]

    def install_monitor(self, mon):
        raise NotImplementedError()
