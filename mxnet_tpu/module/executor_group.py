"""DataParallelExecutorGroup (ref: python/mxnet/module/executor_group.py).

The reference creates one executor per device, scatters batch slices
(`decide_slices`, executor_group.py:207-231; `_load_general` :14-41) and
gathers outputs (`_merge_multi_context` :53). On the SPMD substrate the same
data parallelism is ONE executor whose jit runs over a ``jax.sharding.Mesh``
of the given contexts: inputs are device_put with the batch axis sharded
('data' mesh axis), parameters replicated, and XLA/GSPMD inserts the gradient
all-reduce (psum over ICI) that the reference implemented as CommDevice
copy+sum (comm.h:211-373). The class keeps the reference's API so Module and
user code are unchanged.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..base import MXNetError
from ..context import Context
from ..ndarray import NDArray
from ..executor import simple_bind


def _create_mesh(contexts):
    devices = [c.to_device() for c in contexts]
    if len(set(devices)) != len(devices):
        # duplicate physical devices (cpu(0), cpu(1) on one host): no mesh
        return None
    return jax.sharding.Mesh(np.array(devices), ("data",))


class DataParallelExecutorGroup(object):
    def __init__(self, symbol, contexts, workload, data_shapes, label_shapes,
                 param_names, for_training, inputs_need_grad, shared_group=None,
                 logger=None, fixed_param_names=None, grad_req="write",
                 state_names=None):
        self.symbol = symbol
        self.contexts = [c if isinstance(c, Context) else Context(c)
                         for c in contexts]
        self.workload = workload
        self.param_names = list(param_names)
        self.for_training = for_training
        self.inputs_need_grad = inputs_need_grad
        self.fixed_param_names = set(fixed_param_names or [])
        self.state_names = list(state_names or [])
        self.arg_names = symbol.list_arguments()
        self.aux_names = symbol.list_auxiliary_states()
        self.data_names = [x.name if hasattr(x, "name") else x[0]
                           for x in data_shapes]
        self.label_names = [x.name if hasattr(x, "name") else x[0]
                            for x in (label_shapes or [])]
        self.batch_size = (data_shapes[0].shape if hasattr(data_shapes[0], "shape")
                           else data_shapes[0][1])[0]

        self._mesh = (_create_mesh(self.contexts)
                      if len(self.contexts) > 1 else None)
        self._data_sharding = None
        self._repl_sharding = None
        if self._mesh is not None:
            P = jax.sharding.PartitionSpec
            self._data_sharding = jax.sharding.NamedSharding(self._mesh, P("data"))
            self._repl_sharding = jax.sharding.NamedSharding(self._mesh, P())

        # grad_req per arg (ref: executor_group.py grad_req dict build)
        if self.for_training:
            req = {}
            for name in self.arg_names:
                if name in self.param_names:
                    req[name] = ("null" if name in self.fixed_param_names
                                 else (grad_req if isinstance(grad_req, str)
                                       else grad_req.get(name, "write")))
                elif name in self.data_names:
                    req[name] = "write" if inputs_need_grad else "null"
                else:
                    req[name] = "null"
            self.grad_req = req
        else:
            self.grad_req = {name: "null" for name in self.arg_names}

        shapes = {}
        for d in data_shapes:
            name, shape = (d.name, d.shape) if hasattr(d, "name") else (d[0], d[1])
            shapes[name] = shape
        for l in (label_shapes or []):
            name, shape = (l.name, l.shape) if hasattr(l, "name") else (l[0], l[1])
            shapes[name] = shape

        ctx0 = self.contexts[0]
        shared = shared_group.executor if shared_group is not None else None
        self.executor = simple_bind(symbol, ctx0, grad_req=self.grad_req,
                                    shared_exec=shared, **shapes)
        self.data_shapes = data_shapes
        self.label_shapes = label_shapes
        self._replicate_params()

    # ------------------------------------------------------------------
    def _replicate_params(self):
        if self._mesh is None:
            return
        for n in self.param_names:
            arr = self.executor.arg_dict[n]
            arr._set_data(jax.device_put(arr.data, self._repl_sharding))
        for n in self.aux_names:
            arr = self.executor.aux_dict[n]
            arr._set_data(jax.device_put(arr.data, self._repl_sharding))

    def _shard_batch(self, value):
        v = value.data if isinstance(value, NDArray) else jnp.asarray(np.asarray(value))
        if self._mesh is not None:
            v = jax.device_put(v, self._data_sharding)
        return v

    # ------------------------------------------------------------------
    def forward(self, data_batch, is_train=None):
        if is_train is None:
            is_train = self.for_training
        feed = {}
        for name, value in zip(self.data_names, data_batch.data):
            feed[name] = NDArray(self._shard_batch(value))
        if self.label_names and data_batch.label:
            for name, value in zip(self.label_names, data_batch.label):
                feed[name] = NDArray(self._shard_batch(value))
        self.executor.forward(is_train=is_train, **feed)

    def backward(self, out_grads=None):
        self.executor.backward(out_grads)

    def get_outputs(self, merge_multi_context=True):
        return list(self.executor.outputs)

    def get_input_grads(self, merge_multi_context=True):
        if not self.inputs_need_grad:
            raise MXNetError("bind with inputs_need_grad=True to get input grads")
        return [self.executor.grad_dict[n] for n in self.data_names]

    def get_grads(self):
        return [self.executor.grad_dict[n] for n in self.param_names
                if n in self.executor.grad_dict]

    # ------------------------------------------------------------------
    def set_params(self, arg_params, aux_params):
        self.executor.copy_params_from(arg_params, aux_params,
                                       allow_extra_params=True)
        self._replicate_params()

    def get_params(self, arg_params, aux_params):
        """Copy current params into the given dicts (host-side)."""
        for name in self.param_names:
            arg_params[name] = self.executor.arg_dict[name].copy()
        for name in self.aux_names:
            aux_params[name] = self.executor.aux_dict[name].copy()

    def update_metric(self, eval_metric, labels):
        eval_metric.update(labels, self.get_outputs())
