"""BucketingModule (ref: python/mxnet/module/bucketing_module.py, 467 LoC;
re-bind flow SURVEY.md §3.5).

``sym_gen(bucket_key) -> (symbol, data_names, label_names)``; a Module is
bound per bucket, sharing parameter arrays with the default-bucket module
(ref: shared_module => shared memory pool, graph_executor.cc:352-355). On the
XLA substrate per-bucket executors are per-shape jit entries over the SAME
parameter buffers — the jit cache plays the role of the reference's shared
storage re-bind.
"""
from __future__ import annotations

import logging

from ..base import MXNetError
from .base_module import BaseModule
from .module import Module


class BucketingModule(BaseModule):
    def __init__(self, sym_gen, default_bucket_key=None, logger=logging,
                 context=None, work_load_list=None, fixed_param_names=None,
                 state_names=None):
        super().__init__(logger=logger)
        assert default_bucket_key is not None
        self._default_bucket_key = default_bucket_key
        self._sym_gen = sym_gen
        self._context = context
        self._work_load_list = work_load_list
        self._fixed_param_names = fixed_param_names
        self._state_names = state_names
        self._buckets = {}
        self._curr_module = None
        self._curr_bucket_key = None
        self._params_dirty = False
        # bucketed fused fast path (docs/perf.md "Packed accumulators"):
        # ONE donated state tree shared by per-bucket compiled K-step
        # scans — every bucket shape gets its own TrainStep (and jit
        # cache entry) over the SAME parameters, so variable-length
        # training rides the fused dispatch instead of falling back to
        # per-step executors
        self._bucket_fused = {}      # bucket_key -> TrainStep
        self._bucket_specs = {}      # bucket_key -> DeviceSumSpec | None
        self._bucket_warned = set()  # bucket_key fallbacks already named
        self._fused_state = None
        self._fused_outputs = None
        self._fused_dirty = False
        self._fused_params_stale = False
        self._fused_metric = None    # metric fit() resolved specs for
        self._fused_host_step = 0

    def _reset_bind(self):
        self.binded = False
        self._buckets = {}
        self._curr_module = None
        self._curr_bucket_key = None
        self._bucket_fused = {}
        self._bucket_specs = {}
        self._bucket_warned = set()
        self._fused_state = None
        self._fused_outputs = None
        self._fused_dirty = False
        self._fused_params_stale = False

    @property
    def data_names(self):
        if self.binded:
            return self._curr_module.data_names
        _, data_names, _ = self._call_sym_gen(self._default_bucket_key)
        return data_names

    @property
    def output_names(self):
        if self.binded:
            return self._curr_module.output_names
        symbol, _, _ = self._call_sym_gen(self._default_bucket_key)
        return symbol.list_outputs()

    @property
    def data_shapes(self):
        assert self.binded
        return self._curr_module.data_shapes

    @property
    def label_shapes(self):
        assert self.binded
        return self._curr_module.label_shapes

    @property
    def output_shapes(self):
        assert self.binded
        return self._curr_module.output_shapes

    @property
    def symbol(self):
        assert self.binded
        return self._curr_module.symbol

    def _call_sym_gen(self, bucket_key):
        res = self._sym_gen(bucket_key)
        if isinstance(res, tuple):
            return res
        return (res, ("data",), ("softmax_label",))

    def get_params(self):
        assert self.params_initialized
        self._sync_fused_to_executor()
        self._curr_module._params_dirty = self._params_dirty
        params = self._curr_module.get_params()
        self._params_dirty = False
        return params

    def init_params(self, initializer=None, arg_params=None, aux_params=None,
                    allow_missing=False, force_init=False):
        if self.params_initialized and not force_init:
            return
        assert self.binded, "call bind before initializing the parameters"
        self._curr_module.init_params(initializer=initializer,
                                      arg_params=arg_params,
                                      aux_params=aux_params,
                                      allow_missing=allow_missing,
                                      force_init=force_init)
        self._params_dirty = False
        self.params_initialized = True
        # executor arrays are now authoritative: the shared fused state
        # must re-seed from them, never write back over them
        self._fused_params_stale = True
        self._fused_dirty = False

    def set_params(self, arg_params, aux_params, allow_missing=False,
                   force_init=True):
        self.init_params(initializer=None, arg_params=arg_params,
                         aux_params=aux_params, allow_missing=allow_missing,
                         force_init=force_init)

    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, shared_module=None,
             grad_req="write"):
        if force_rebind:
            self._reset_bind()
        if self.binded:
            self.logger.warning("Already binded, ignoring bind()")
            return
        assert shared_module is None, \
            "shared_module for BucketingModule is not supported"
        self.for_training = for_training
        self.inputs_need_grad = inputs_need_grad
        self.binded = True

        symbol, data_names, label_names = self._call_sym_gen(
            self._default_bucket_key)
        module = Module(symbol, data_names, label_names, logger=self.logger,
                        context=self._context,
                        work_load_list=self._work_load_list,
                        fixed_param_names=self._fixed_param_names,
                        state_names=self._state_names)
        module.bind(data_shapes, label_shapes, for_training,
                    inputs_need_grad, force_rebind=False, shared_module=None,
                    grad_req=grad_req)
        self._curr_module = module
        self._curr_bucket_key = self._default_bucket_key
        self._buckets[self._default_bucket_key] = module

    def switch_bucket(self, bucket_key, data_shapes, label_shapes=None):
        """Switch to a bucket, binding a shared-parameter module if unseen
        (ref: bucketing_module.py switch_bucket)."""
        assert self.binded, "call bind before switching bucket"
        if bucket_key not in self._buckets:
            symbol, data_names, label_names = self._call_sym_gen(bucket_key)
            module = Module(symbol, data_names, label_names,
                            logger=self.logger, context=self._context,
                            work_load_list=self._work_load_list,
                            fixed_param_names=self._fixed_param_names,
                            state_names=self._state_names)
            module.bind(data_shapes, label_shapes, self.for_training,
                        self.inputs_need_grad, force_rebind=False,
                        shared_module=self._buckets[self._default_bucket_key],
                        grad_req="write")
            self._buckets[bucket_key] = module
        self._curr_module = self._buckets[bucket_key]
        self._curr_bucket_key = bucket_key

    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.01),),
                       force_init=False):
        assert self.binded and self.params_initialized
        if self.optimizer_initialized and not force_init:
            self.logger.warning("optimizer already initialized, "
                                "ignoring.")
            return
        self._curr_module.init_optimizer(kvstore, optimizer, optimizer_params,
                                         force_init=force_init)
        for mod in self._buckets.values():
            if mod is not self._curr_module:
                mod.borrow_optimizer(self._curr_module)
        self.optimizer_initialized = True

    # -- bucketed fused K-step dispatch (docs/perf.md "Packed
    # -- accumulators": bucketed-shape jit-cache handling) ---------------
    @property
    def _base_module(self):
        return self._buckets[self._default_bucket_key]

    def _can_bulk_dispatch(self, eval_metric=None):
        """fit()'s precheck: whether this bucketed module can ride the
        fused K-step scan — one compiled program per bucket shape over ONE
        shared donated state tree. With ``eval_metric`` the metric's
        packed-accumulator spec must resolve for the DEFAULT bucket's
        shapes (per-bucket specs resolve lazily at first dispatch of each
        bucket); the metric is stashed so dispatches can resolve them."""
        base = self._base_module
        opt = base._optimizer
        if not getattr(opt, "fused_supported", False):
            return (False, "optimizer %r has no fused update"
                    % type(opt).__name__)
        if base._is_dist_kvstore():
            return (False, "dist kvstore keeps per-step dispatch "
                    "(per-step push/pull sync is the contract)")
        if base._monitor_installed:
            return (False, "a monitor needs per-step executor access")
        if self.inputs_need_grad or self._state_names:
            return (False, "inputs_need_grad/state_names need the "
                    "per-step executor path")
        eg = base._exec_group
        if eg._mesh is not None:
            return (False, "bucketed dispatch is single-device (one "
                    "fused program per bucket shape; no data mesh yet)")
        for n in eg.param_names:
            if eg.grad_req.get(n, "null") not in ("write", "null"):
                return (False, "grad_req %r needs the per-step executor "
                        "path" % eg.grad_req.get(n))
        if eval_metric is not None:
            spec = base._device_sum_spec(eval_metric)
            if spec is None:
                return (False, "metric %r declares no device-sum layout "
                        "for the default bucket's shapes — it updates "
                        "per-step on host"
                        % getattr(eval_metric, "name", eval_metric))
            self._fused_metric = eval_metric
            self._bucket_specs = {}
        return True, None

    def _can_guard(self):
        """Guard eligibility (docs/robustness.md "Numerical guardrails"):
        the per-bucket fused scans carry the same device sentinels as the
        single-symbol path — grad-norm + all-finite computed inside each
        bucket's compiled body, skipped steps excluded from every
        accumulator slot — so a bucketed model no longer trains UNGUARDED
        under ``MXTPU_GUARD=1``. Same eligibility set as dispatch
        bulking (the sentinels ride the fused programs)."""
        return self._can_bulk_dispatch()

    def _get_bucket_step(self, bucket_key):
        """The bucket's compiled TrainStep, built lazily from its symbol —
        NO executor is bound for buckets that only ever train fused. All
        bucket TrainSteps share the module's ONE optimizer instance, so
        the lr-schedule clock advances once across every bucket."""
        ts = self._bucket_fused.get(bucket_key)
        if ts is not None:
            return ts
        from ..train_step import TrainStep
        base = self._base_module
        symbol, data_names, label_names = self._call_sym_gen(bucket_key)
        eg = base._exec_group
        frozen = [n for n in eg.param_names
                  if eg.grad_req.get(n, "null") == "null"]
        ts = TrainStep(symbol, data_names=list(data_names),
                       label_names=list(label_names),
                       optimizer=base._optimizer,
                       frozen_param_names=frozen)
        self._bucket_fused[bucket_key] = ts
        return ts

    def _get_bucket_spec(self, bucket_key, ts, super_batch):
        """The stashed metric's packed-accumulator spec resolved against
        THIS bucket's shapes (cached per bucket — the slot layout is
        metric-determined and identical across buckets, only the traced
        shapes differ)."""
        if bucket_key in self._bucket_specs:
            return self._bucket_specs[bucket_key]
        from .. import metric as _metric
        spec = None
        if self._fused_metric is not None:
            shapes = {}
            lshapes = []
            pd = super_batch.step_provide_data
            pl = super_batch.step_provide_label
            if pd is None:
                # no per-bucket descriptors: derive from the stacked arrays
                pd = list(zip(ts.data_names,
                              [tuple(v.shape[1:])
                               for v in super_batch.data]))
                pl = list(zip(ts.label_names,
                              [tuple(v.shape[1:])
                               for v in (super_batch.label or [])]))
            for d in pd:
                name, shape = ((d.name, d.shape) if hasattr(d, "name")
                               else (d[0], d[1]))
                shapes[name] = tuple(shape)
            for l in (pl or []):
                name, shape = ((l.name, l.shape) if hasattr(l, "name")
                               else (l[0], l[1]))
                shapes[name] = tuple(shape)
                lshapes.append(tuple(shape))
            try:
                _, out_shapes, _ = ts.symbol.infer_shape(**shapes)
                spec = _metric.device_sum_spec(self._fused_metric,
                                               out_shapes, lshapes)
            except Exception:
                spec = None
        self._bucket_specs[bucket_key] = spec
        return spec

    def _warn_bucket_fallback(self, bucket_key, why):
        if bucket_key in self._bucket_warned:
            return
        self._bucket_warned.add(bucket_key)
        self.logger.warning(
            "bucketed dispatch: bucket %r falls back to per-step "
            "training (%s)", bucket_key, why)

    def _seed_fused_state(self, ts):
        """The ONE shared state tree, seeded from the default bucket's
        executor arrays + updater states (copies — the first dispatch
        donates the buffers). The step clock continues from the host-side
        mirror so noise streams survive a re-seed."""
        import jax.numpy as jnp
        from .module import _seed_opt_state
        base = self._base_module
        ex = base._exec_group.executor
        params = {n: base._jnp_copy(ex.arg_dict[n].data)
                  for n in ts.param_names}
        aux = ts.cast_stats({n: base._jnp_copy(ex.aux_dict[n].data)
                             for n in ts.aux_names})
        opt = _seed_opt_state(ts, params, base._optimizer,
                              base._resolve_updater(),
                              base._exec_group.param_names)
        state = {"params": params, "aux": aux, "opt": opt,
                 "step": jnp.full((), self._fused_host_step, jnp.int32)}
        # COMMIT every leaf to the BOUND context's device: the per-bucket
        # scan outputs are committed arrays, and an uncommitted seed
        # state would give the first dispatch after every (re-)seed a
        # different jit cache key than steady state — one spurious
        # compile per bucket per seed (measured; the bucketed-cache
        # assert_no_retrace pin catches it). The bound device, not
        # devices()[0]: a module bound on a non-zero device must not
        # migrate its training onto device 0
        import jax
        ctx = (base._context[0] if getattr(base, "_context", None)
               else None)
        dev = ctx.to_device() if ctx is not None else jax.devices()[0]
        return jax.tree_util.tree_map(
            lambda v: jax.device_put(v, dev), state)

    def _ensure_fused_state(self, ts):
        """Param-set compatibility BEFORE seeding, and the seed always
        from the DEFAULT bucket's TrainStep: a bucket symbol with an
        extra/missing parameter must warn-and-fall-back (the caller's
        contract), never KeyError mid-seed or skew the shared tree onto
        its own param set."""
        base_ts = self._get_bucket_step(self._default_bucket_key)
        if set(ts.param_names) != set(base_ts.param_names):
            return False
        if self._fused_state is None or self._fused_params_stale:
            self._fused_state = self._seed_fused_state(base_ts)
            self._fused_params_stale = False
        return True

    def _dispatch_fused_steps(self, super_batch, guard=None):
        """fit()'s bucketed K-step fast path: one donated ``lax.scan``
        through THIS bucket's compiled program over the shared state tree
        (the jit cache plays the reference's shared-storage re-bind role
        one level up — per bucket SHAPE, not per bucket executor).
        Returns None when this superbatch must train per-step.

        With a :class:`~mxnet_tpu.guard.TrainingGuard` the bucket's
        GUARDED scan runs instead (separate jit cache per bucket, same
        shared state): grad-norm/all-finite sentinels inside the compiled
        body, non-finite steps are device-side no-ops excluded from every
        accumulator slot, and the sentinels ride back with the metric
        sums in the one per-K readback (docs/robustness.md "Numerical
        guardrails")."""
        if not (self.binded and self.params_initialized
                and self.optimizer_initialized):
            return None
        ok, _why = self._can_bulk_dispatch()
        if not ok:
            return None
        key = super_batch.bucket_key
        if key is None:
            key = self._default_bucket_key
        ts = self._get_bucket_step(key)
        if not self._ensure_fused_state(ts):
            self._warn_bucket_fallback(
                key, "its symbol's parameter set differs from the shared "
                "state tree")
            return None
        spec = self._get_bucket_spec(key, ts, super_batch)
        if self._fused_metric is not None and spec is None:
            self._warn_bucket_fallback(
                key, "metric %r declares no device-sum layout for this "
                "bucket's shapes"
                % getattr(self._fused_metric, "name", self._fused_metric))
            return None
        feed = {}
        for name, v in zip(ts.data_names, super_batch.data):
            feed[name] = v
        for name, v in zip(ts.label_names, super_batch.label or []):
            feed[name] = v
        feed = ts.shard_superbatch(feed)
        # retrace events attribute to THIS run's health when guarded
        ts.health = guard.health if guard is not None else None
        from ..tracecheck import RetraceError
        try:
            self._fused_state, sums = ts.run_steps(
                self._fused_state, feed, guard=guard is not None,
                metric_spec=spec)
        except RetraceError as e:
            # the dispatch already ran and donated the shared state:
            # adopt the new tree (BaseModule hook) before re-raising so
            # get_params/emergency checkpoints never dangle
            self._adopt_retrace_result(e, super_batch.num_steps, guard)
            raise
        self._fused_outputs = None
        self._fused_dirty = True
        self._params_dirty = True
        if guard is None:
            # unguarded: every step lands, the mirror advances at
            # dispatch; guarded dispatches advance at retirement (the
            # skip count rides the sentinel readback —
            # ``BaseModule._note_dispatch_retired``)
            self._fused_host_step += super_batch.num_steps
        return sums

    def _try_fused_fit_step(self, data_batch, guard=None):
        """fit()'s per-step path for bucket-run tails: the bucket's fused
        single-step program over the SAME shared state — so a superbatch
        cut short by a bucket switch never detours through the executor
        (whose optimizer state would then diverge from the donated
        tree). With a guard, the bucket's GUARDED single step runs (same
        sentinel packet as the single-symbol path) and a skipped step is
        kept out of the host-side metric via ``guard.last_step_skipped``."""
        if not (self.binded and self.params_initialized
                and self.optimizer_initialized):
            return False
        ok, _why = self._can_bulk_dispatch()
        if not ok:
            return False
        key = getattr(data_batch, "bucket_key", None)
        if key is None:
            key = self._default_bucket_key
        ts = self._get_bucket_step(key)
        if not self._ensure_fused_state(ts):
            return False
        import jax.numpy as jnp
        import numpy as _np
        from ..ndarray import NDArray

        def to_jnp(v):
            return v.data if isinstance(v, NDArray) else jnp.asarray(v)

        feed = {}
        for name, v in zip(ts.data_names, data_batch.data):
            feed[name] = to_jnp(v)
        for name, v in zip(ts.label_names, data_batch.label or []):
            feed[name] = to_jnp(v)
        ts.health = guard.health if guard is not None else None
        from ..tracecheck import RetraceError
        if guard is not None:
            guard.last_step_skipped = False
            try:
                self._fused_state, outs, packed = ts.step(
                    self._fused_state, feed, guard=True)
            except RetraceError as e:
                self._adopt_retrace_result(e, 1, guard)
                raise
            self._fused_outputs = [NDArray(o) for o in outs]
            self._fused_dirty = True
            self._params_dirty = True
            self._feed_guard_sentinels(guard, _np.asarray(packed))
            return True
        try:
            self._fused_state, outs = ts.step(self._fused_state, feed)
        except RetraceError as e:
            self._adopt_retrace_result(e, 1, None)
            raise
        self._fused_outputs = [NDArray(o) for o in outs]
        self._fused_dirty = True
        self._params_dirty = True
        self._fused_host_step += 1
        return True

    # -- divergence rollback / resume hooks (docs/robustness.md) ---------
    def _drop_fused_state(self):
        """Divergence-rollback hook: discard the shared state tree WITHOUT
        flushing it (it holds the diverged params/moments). The next
        dispatch reseeds from the default bucket's executor arrays +
        updater states the rollback just restored; the per-bucket
        TrainSteps and their jit caches survive — a rollback never
        recompiles."""
        self._fused_state = None
        self._fused_outputs = None
        self._fused_dirty = False
        self._fused_params_stale = False

    def _scale_lr(self, factor):
        """Divergence-rollback hook: one optimizer instance is shared by
        every bucket TrainStep, so the base module's reduction covers the
        whole module."""
        self._base_module._scale_lr(factor)

    def _restore_trainer_clock(self, num_update, fused_step=None):
        """Resume/rollback hook: wind the (shared) optimizer clocks
        through the base module, then pin the bucketed host-side step
        mirror — and the live shared state's device counter, if any — to
        the checkpointed fused step (trails ``num_update`` by the guard's
        skip count)."""
        base = self._base_module
        base._restore_trainer_clock(num_update, fused_step)
        self._fused_host_step = base._resume_step
        if self._fused_state is not None:
            import jax.numpy as jnp
            self._fused_state["step"] = jnp.full(
                (), self._fused_host_step, jnp.int32)

    def load_optimizer_states(self, fname):
        """Restore updater states through the current module; the shared
        fused tree reseeds from them at the next dispatch."""
        self._curr_module.load_optimizer_states(fname)
        self._fused_params_stale = True

    def _fused_step_count(self):
        """Checkpoint-manifest hook: the bucketed host-side mirror of the
        shared device step counter (never a device sync)."""
        if self._fused_state is None:
            return None
        return int(self._fused_host_step)

    def _sync_fused_to_executor(self):
        """Write the shared fused params/aux back into the default
        bucket's executor arrays — which every bucket executor ALIASES
        (the shared-pool bind), so one write covers the whole module."""
        if not self._fused_dirty or self._fused_state is None:
            return
        base = self._base_module
        ex = base._exec_group.executor
        for n, v in self._fused_state["params"].items():
            ex.arg_dict[n]._set_data(base._jnp_copy(v))
        for n, v in self._fused_state["aux"].items():
            v = base._jnp_copy(v)
            tgt = ex.aux_dict[n].data.dtype
            if v.dtype != tgt:
                v = v.astype(tgt)
            ex.aux_dict[n]._set_data(v)
        self._fused_dirty = False

    def _sync_fused_opt_states(self):
        """Mirror the shared fused optimizer state into the updater's
        index-keyed dict so ``save_optimizer_states`` (and an imperative
        ``update()`` after fused training) see the trained moments."""
        if self._fused_state is None:
            return
        base = self._base_module
        updater = base._resolve_updater()
        if updater is None:
            return
        from ..ndarray import NDArray

        def to_nd(x):
            if x is None:
                return None
            if isinstance(x, tuple):
                return tuple(to_nd(i) for i in x)
            v = base._jnp_copy(x)
            if str(v.dtype) == "bfloat16":
                import jax.numpy as jnp
                v = v.astype(jnp.float32)
            return NDArray(v)

        idx_of = {n: i for i, n in enumerate(base._exec_group.param_names)}
        for n, st in self._fused_state["opt"].items():
            if n in idx_of:
                updater.states[idx_of[n]] = to_nd(st)

    def save_optimizer_states(self, fname):
        self._sync_fused_opt_states()
        return self._curr_module.save_optimizer_states(fname)

    def check(self, memory=False, budget=None, temp_mult=None):
        """Static audit of the fused bucket-program cache AS A UNIT
        (docs/static_analysis.md): tracecheck lints per registered bucket
        program, plus (``memory=True``) the memcheck per-program lints
        and ONE ``resident-set`` finding over every bucket's compiled
        scan — the jit caches keep all of them reachable at once, so the
        cache's co-resident footprint is what the budget must cover."""
        from .. import tracecheck as _tc
        prefixes = [ts._watcher.name + "/"
                    for ts in self._bucket_fused.values()
                    if ts._watcher is not None]
        findings = []
        for p in prefixes:
            findings += _tc.check_registered(match=p)
        if memory:
            from .. import memcheck as _mc
            fs, _reports = _mc.check_registered(
                match=tuple(prefixes), budget=budget, temp_mult=temp_mult,
                resident_name="BucketingModule(%s)"
                % self._default_bucket_key)
            findings += fs
        return findings

    def forward(self, data_batch, is_train=None):
        assert self.binded and self.params_initialized
        # an executor forward must see the fused-trained params (shared
        # arrays across buckets — one sync covers all executors)
        self._sync_fused_to_executor()
        self._fused_outputs = None
        self.switch_bucket(data_batch.bucket_key,
                           data_batch.provide_data,
                           data_batch.provide_label)
        self._curr_module.forward(data_batch, is_train=is_train)

    def backward(self, out_grads=None):
        assert self.binded and self.params_initialized
        self._curr_module.backward(out_grads=out_grads)

    def update(self):
        assert self.binded and self.params_initialized \
            and self.optimizer_initialized
        self._params_dirty = True
        if self._fused_state is not None and not self._fused_params_stale:
            # imperative updates land in the executor arrays + updater
            # states: hand them the fused moments first, and re-seed the
            # shared state tree before the next fused dispatch
            self._sync_fused_opt_states()
            self._fused_params_stale = True
        self._curr_module.update()

    def get_outputs(self, merge_multi_context=True):
        assert self.binded and self.params_initialized
        if self._fused_outputs is not None:
            return list(self._fused_outputs)
        return self._curr_module.get_outputs(merge_multi_context)

    def get_input_grads(self, merge_multi_context=True):
        assert self.binded and self.params_initialized \
            and self.inputs_need_grad
        return self._curr_module.get_input_grads(merge_multi_context)

    def update_metric(self, eval_metric, labels):
        assert self.binded and self.params_initialized
        if self._fused_outputs is not None:
            eval_metric.update(labels, self._fused_outputs)
            return
        self._curr_module.update_metric(eval_metric, labels)

    def install_monitor(self, mon):
        assert self.binded
        for mod in self._buckets.values():
            mod.install_monitor(mon)
