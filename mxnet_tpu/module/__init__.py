"""Module API (ref: python/mxnet/module/ — the training API contract,
SURVEY.md §2.6)."""
from .base_module import BaseModule, BatchEndParam
from .module import Module
from .executor_group import DataParallelExecutorGroup
from .bucketing_module import BucketingModule
from .sequential_module import SequentialModule
from .python_module import PythonModule, PythonLossModule
