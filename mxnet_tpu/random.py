"""Random state for mxnet_tpu.

The reference seeds per-device mshadow PRNGs through the ResourceManager
(ref: src/resource.cc:127-135, C API MXRandomSeed). Here randomness is JAX
functional PRNG: a module-level root key that is split on every imperative
draw, and *threaded explicitly* through traced executor code (ops that
declare ``needs_rng`` receive a fresh subkey derived from the executor's
step counter, keeping jit-traced code deterministic and replayable).
"""
from __future__ import annotations

import threading

import jax
import numpy as _np

# process-global (NOT thread-local: seed() must reach PrefetchingIter
# producer threads too, or every worker thread re-seeds itself to the
# default and draws identical streams); a lock serializes split()
_lock = threading.Lock()
_key = None

_DEFAULT_SEED = 0


def _get():
    global _key
    if _key is None:
        _key = jax.random.key(_DEFAULT_SEED)
    return _key


def seed(seed_state):
    """Seed the global random number generator (parity: mx.random.seed)."""
    global _key
    with _lock:
        _key = jax.random.key(int(seed_state))


def split():
    """Return a fresh PRNG subkey, advancing the global state."""
    global _key
    with _lock:
        key, sub = jax.random.split(_get())
        _key = key
    return sub


def get_state():
    """Snapshot the global PRNG key (for scoped seeding)."""
    return _get()


def set_state(key):
    """Restore a key captured by get_state()."""
    global _key
    with _lock:
        _key = key


_tls = threading.local()


def np_rng():
    """A numpy Generator seeded from the functional stream (host-side uses:
    data shuffling, initializers that want numpy).

    Inside a :func:`scoped_np_rng` block the scoped Generator is returned
    instead — the device-fed input tier's decode workers pin each batch's
    augmentation draws to a Generator derived from (seed, epoch, batch
    index), so worker parallelism and completion order never perturb the
    augmentation stream (docs/perf.md "Device-fed input pipeline")."""
    ov = getattr(_tls, "np_rng", None)
    if ov is not None:
        return ov
    sub = split()
    return _np.random.default_rng(_np.asarray(jax.random.key_data(sub))[-1])


class scoped_np_rng(object):
    """Thread-local override of :func:`np_rng` for the calling thread:

        with random.scoped_np_rng(np.random.default_rng(s)):
            ...   # every np_rng() here returns that Generator

    Scopes nest; the override never leaks to other threads (each decode
    worker scopes its own batch) nor past the block."""

    def __init__(self, rng):
        self._rng = rng

    def __enter__(self):
        self._prev = getattr(_tls, "np_rng", None)
        _tls.np_rng = self._rng
        return self._rng

    def __exit__(self, *exc):
        _tls.np_rng = self._prev
        return False


# ---------------------------------------------------------------------------
# sampling API (ref: python/mxnet/random.py uniform/normal/...; scalar ops
# are _random_* in ops/tensor.py; the tensor-parameter _sample_* multisample
# family (ref multisample_op.cc) is exposed via nd._sample_*)
# ---------------------------------------------------------------------------

def _sample(op_name, out=None, **attrs):
    from . import ndarray as nd
    from .ops import registry as _reg
    return nd.invoke(_reg.get(op_name), [], attrs, out=out)


def uniform(low=0, high=1, shape=None, ctx=None, out=None):
    return _sample("_random_uniform", out=out, low=low, high=high,
                   shape=shape or (1,))


def normal(loc=0, scale=1, shape=None, ctx=None, out=None):
    return _sample("_random_normal", out=out, loc=loc, scale=scale,
                   shape=shape or (1,))


def gamma(alpha=1, beta=1, shape=None, ctx=None, out=None):
    return _sample("_random_gamma", out=out, alpha=alpha, beta=beta,
                   shape=shape or (1,))


def exponential(lam=1, shape=None, ctx=None, out=None):
    return _sample("_random_exponential", out=out, lam=lam, shape=shape or (1,))


def poisson(lam=1, shape=None, ctx=None, out=None):
    return _sample("_random_poisson", out=out, lam=lam, shape=shape or (1,))


def negative_binomial(k=1, p=1, shape=None, ctx=None, out=None):
    return _sample("_random_negative_binomial", out=out, k=k, p=p,
                   shape=shape or (1,))
