"""Fused training step: forward + backward + optimizer update in ONE jit.

This is the TPU-native analog of everything the reference engine pipeline did
per batch — RunOps over bulked segments, gradient reduce, updater
(ref: call stack SURVEY.md §3.1) — collapsed into a single donated XLA
computation. Module uses the lazy executor path for API fidelity; this module
is the performance path used by bench.py, the multichip dry-run, and any
training loop that wants max throughput.

Sharding: pass a Mesh plus optional per-parameter PartitionSpecs. Batch
arrays are sharded along ``data``; parameters default to replicated
(pure DP — XLA inserts the gradient psum exactly where the reference ran its
CommDevice reduce) and any parameter given a spec with a ``model`` axis is
tensor-parallel sharded.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .base import MXNetError, env_str
from .executor import _build_graph_runner
from .initializer import Xavier, InitDesc
from .ndarray import NDArray
from .ops import registry as _reg
from . import optimizer as _opt
from .optimizer import Optimizer
from . import random as _random

P = jax.sharding.PartitionSpec

# rng stream offset so optimizer noise keys (SGLD) never collide with the
# graph runner's per-node fold_in(key, node_index) streams
_OPT_KEY_OFFSET = 1 << 20


class StepMetrics(object):
    """Device-resident metric accumulators for one K-step dispatch.

    Holds the packed accumulator array produced on device by
    ``TrainStep.run_steps``; the first property access performs the ONE
    host readback for the whole dispatch (and doubles as the sync point
    per-step training got from reading outputs every batch).

    Without a ``spec`` the layout is the legacy default
    ``[loss_sum, top1_correct, num_samples]``. With a
    :class:`~mxnet_tpu.metric.DeviceSumSpec` (the packed-accumulator
    protocol, docs/perf.md "Packed accumulators") the layout is the
    spec's declared slots — read them by name via :meth:`values`; the
    ``loss_sum``/``num_samples`` properties then read the spec's
    ``loss_slots`` pair (NaN / 0 when the spec declares none, which
    makes the TrainingGuard skip its loss watch rather than observe
    garbage).

    A GUARDED dispatch (``run_steps(..., guard=True)``) extends the packed
    array to ``[..., skipped, last_grad_norm]`` — the training-health
    sentinels ride back with the metric sums in the same single readback,
    and skipped (non-finite) steps are already excluded from every
    declared accumulator.
    """

    __slots__ = ("device", "guarded", "spec", "_host")

    def __init__(self, device_array, guarded=False, spec=None):
        self.device = device_array
        self.guarded = guarded
        self.spec = spec
        self._host = None

    def _vals(self):
        if self._host is None:
            self._host = np.asarray(self.device)
        return self._host

    def fetch(self):
        """Perform the dispatch's one host readback NOW (idempotent) and
        return self. The packed device array is a future: ``fit``'s
        dispatch pipeline (docs/perf.md "Host off the critical path")
        defers this call until the NEXT dispatch has been enqueued, so the
        readback stall overlaps device compute instead of serializing it."""
        self._vals()
        return self

    @property
    def fetched(self):
        """True once the host readback has happened (property access or
        :meth:`fetch`) — reading it never syncs the device."""
        return self._host is not None

    @property
    def _n_slots(self):
        return 3 if self.spec is None else len(self.spec.slots)

    def values(self):
        """Slot-name -> float dict of the dispatch's accumulated sums
        (spec layout; the legacy layout maps to loss_sum/top1_correct/
        num_samples)."""
        v = self._vals()
        if self.spec is None:
            return {"loss_sum": float(v[0]), "top1_correct": float(v[1]),
                    "num_samples": float(v[2])}
        return {s: float(v[i]) for i, s in enumerate(self.spec.slots)}

    def _loss_pair(self):
        v = self._vals()
        if self.spec is None:
            return float(v[0]), float(v[2])
        if self.spec.loss_slots is None:
            return float("nan"), 0.0
        idx = {s: i for i, s in enumerate(self.spec.slots)}
        ls, ns = self.spec.loss_slots
        return float(v[idx[ls]]), float(v[idx[ns]])

    @property
    def loss_sum(self):
        """Summed watchable loss over every sample in the dispatch (the
        spec's declared loss pair; in-scan CE on the legacy layout)."""
        return self._loss_pair()[0]

    @property
    def top1_correct(self):
        """Count of top-1 correct predictions (legacy layout only; NaN
        under a spec — read :meth:`values` by slot name instead)."""
        if self.spec is not None:
            return float("nan")
        return float(self._vals()[1])

    @property
    def num_samples(self):
        return int(round(self._loss_pair()[1]))

    @property
    def accuracy(self):
        n = self.num_samples
        return self.top1_correct / n if n else float("nan")

    @property
    def loss_avg(self):
        n = self.num_samples
        return self.loss_sum / n if n else float("nan")

    @property
    def skipped(self):
        """Count of device-side no-op (non-finite) steps in the dispatch;
        0 for an unguarded dispatch."""
        return int(self._vals()[self._n_slots]) if self.guarded else 0

    @property
    def last_grad_norm(self):
        """Global gradient norm of the dispatch's LAST step (guarded only;
        NaN/Inf when that step was the poisoned one — informative)."""
        if not self.guarded:
            return None
        return float(self._vals()[self._n_slots + 1])

    def __repr__(self):
        if self.spec is None:
            s = ("StepMetrics(loss_sum=%.6g, top1_correct=%g, "
                 "num_samples=%d"
                 % (self.loss_sum, self.top1_correct, self.num_samples))
        else:
            s = "StepMetrics(%s" % ", ".join(
                "%s=%.6g" % kv for kv in sorted(self.values().items()))
        if self.guarded:
            s += ", skipped=%d, last_grad_norm=%g" % (self.skipped,
                                                      self.last_grad_norm)
        return s + ")"


def _metric_step_sums(outs, labels, zero):
    """One step's device metric sums (CE loss, top-1 correct) over every
    (rank-2 output, rank-1 label) pair, positionally. ONE definition shared
    by the unguarded scan, the guarded scan and the guarded single step —
    they are parity-tested against each other and against host
    metric.CrossEntropy (eps 1e-8) / metric.Accuracy (argmax axis=1), so
    the accumulation must never drift between paths. ``labels`` pairs with
    ``outs`` positionally (None entries skip)."""
    loss = zero
    correct = zero
    for o, lbl in zip(outs, labels):
        if (lbl is not None and getattr(o, "ndim", 0) == 2
                and lbl.ndim == 1 and o.shape[0] == lbl.shape[0]):
            li = lbl.astype(jnp.int32)
            # take_along_axis, NOT o[arange(bs), li]: the batch dim of both
            # operand and indices stays aligned, so under a data-parallel
            # mesh GSPMD keeps the gather fully per-shard. The arange
            # fancy-index looks identical but loses that alignment and
            # lowers to THREE all-gathers inside the scan body (the
            # collective-in-scan lint pins this); on one device both forms
            # gather the same elements and are bitwise identical
            p = jnp.take_along_axis(o, li[:, None], axis=1)[:, 0] \
                .astype(jnp.float32)
            # eps pinned f32: a bare Python 1e-8 is weak-typed and would
            # promote to f64 under jax_enable_x64 (tracecheck dtype lint);
            # on the default config the pin is bitwise-identical
            loss = loss + jnp.sum(-jnp.log(p + jnp.float32(1e-8)))
            correct = correct + jnp.sum(
                (jnp.argmax(o, axis=1).astype(jnp.int32) == li)
                .astype(jnp.float32))
    return loss, correct


def _stable_sig(sig):
    """Project a spec signature onto run-to-run-stable atoms for program
    NAMING (the jit cache itself keys on the raw signature): function
    objects — a CustomMetric's step_sums — repr with their memory
    address, so they collapse to their qualname here."""
    if isinstance(sig, tuple):
        return tuple(_stable_sig(s) for s in sig)
    if isinstance(sig, (str, int, float, bool)) or sig is None:
        return sig
    return getattr(sig, "__qualname__", type(sig).__name__)


def _default_slot_sums(outs, labels, batch_size):
    """The legacy packed layout ``(ce_loss, top1_correct, num_samples)``
    as a slot tuple — what ``run_steps`` accumulates when no
    packed-accumulator spec is passed (TrainStep API users, bench.py, the
    multichip gate). Bit-for-bit the pre-protocol scan accumulation."""
    zero = jnp.zeros((), jnp.float32)
    loss, correct = _metric_step_sums(outs, labels, zero)
    return (loss, correct, jnp.float32(batch_size))


def _with_guard_loss(spec, batch_size):
    """Augment a packed-accumulator spec that declares NO watchable loss
    pair with two hidden slots — the in-scan CE loss and sample count the
    TrainingGuard's divergence EMA has always observed. The metric's own
    fold never sees the hidden slots; ``StepMetrics.loss_sum`` and the
    guard do."""
    from .metric import DeviceSumSpec
    if spec is None or spec.loss_slots is not None:
        return spec
    base_slots = spec.slots
    base_step = spec.step_sums
    base_fold = spec.fold

    def step_sums(outs, labels):
        vals = tuple(base_step(outs, labels))
        zero = jnp.zeros((), jnp.float32)
        loss, _ = _metric_step_sums(outs, labels, zero)
        return vals + (loss, jnp.float32(batch_size))

    def fold(metric, values):
        base_fold(metric, {s: values[s] for s in base_slots})

    return DeviceSumSpec(
        base_slots + ("__guard_loss", "__guard_n"), step_sums, fold,
        ("guardloss",) + (spec.signature if isinstance(spec.signature,
                                                       tuple)
         else (spec.signature,)),
        loss_slots=("__guard_loss", "__guard_n"), tag=spec.tag)


class TrainStep(object):
    """Compiled train step over a symbol.

    state = {params, aux, opt, step}; ``step(state, batch)`` returns
    (new_state, outputs) and donates the old state buffers.

    ``optimizer`` may be a registry name (created with learning_rate /
    momentum / wd) or an Optimizer instance — any optimizer in the zoo with
    ``fused_supported`` works, including lr_mult/wd_mult from symbol attrs
    and an lr_scheduler (evaluated host-side per step, fed in as a traced
    scalar so schedules never retrace).
    """

    def __init__(self, symbol, data_names=("data",),
                 label_names=("softmax_label",), optimizer="sgd",
                 learning_rate=0.01, momentum=0.9, wd=0.0, rescale_grad=None,
                 mesh=None, param_shardings=None, dtype=np.float32,
                 compute_dtype=None, remat=False, frozen_param_names=None,
                 group2ctx=None):
        self.symbol = symbol
        self.data_names = list(data_names)
        self.label_names = list(label_names)
        self.arg_names = symbol.list_arguments()
        self.aux_names = symbol.list_auxiliary_states()
        self.param_names = [n for n in self.arg_names
                            if n not in self.data_names + self.label_names]
        self.frozen_param_names = set(frozen_param_names or ())
        if isinstance(optimizer, Optimizer):
            self._opt = optimizer
            # an instance's rescale_grad is authoritative (even 1.0): the
            # imperative updater applies it verbatim, so the fused path must
            # too; the 1/batch_size default exists only for the
            # string-optimizer convenience constructor. A left-at-default
            # 1.0 almost always means batch-SUMMED gradients at full lr —
            # warn like Module.init_optimizer does (ref: module.py:460-463)
            if rescale_grad is None:
                rescale_grad = optimizer.rescale_grad
                if rescale_grad == 1.0:
                    import logging
                    logging.warning(
                        "TrainStep: optimizer instance has rescale_grad=1.0 "
                        "(gradients are batch sums); pass "
                        "rescale_grad=1/batch_size to the optimizer or to "
                        "TrainStep if per-example scaling is intended")
        else:
            kwargs = {"learning_rate": learning_rate, "wd": wd,
                      "sym": symbol}
            if optimizer.lower() in ("sgd", "nag", "ccsgd", "dcasgd"):
                kwargs["momentum"] = momentum
            self._opt = _opt.create(optimizer, **kwargs)
        if not self._opt.fused_supported:
            raise MXNetError("fused step: optimizer %r has no fused update"
                             % type(self._opt).__name__)
        self.optimizer = optimizer
        self.rescale_grad = rescale_grad
        self.mesh = mesh
        self.param_shardings = dict(param_shardings or {})
        self.dtype = np.dtype(dtype)
        # MXTPU_BF16_STATS (docs/perf.md next-steps item 2): store the
        # NON-parameter state in bf16 — any truthy value keeps BatchNorm
        # moving stats (aux states) in bf16; "opt"/"all" additionally
        # keeps optimizer state (momentum/Adam moments) in bf16. Halves
        # the non-param state traffic on a bandwidth-bound chip; params
        # keep f32 masters (bf16 params measured -12%, docs/perf.md r5).
        # Checkpoints still serialize f32 (bf16->f32->bf16 is exact), so
        # resume stays bitwise and save formats are unchanged.
        _bf16 = env_str("MXTPU_BF16_STATS").lower()
        self.bf16_stats = _bf16 not in ("", "0", "false", "off", "no")
        self.bf16_opt = _bf16 in ("opt", "all", "full")
        if compute_dtype is not None:
            self.compute_dtype = np.dtype(compute_dtype)
        elif self.dtype != np.dtype(np.float32):
            # params stored in a non-f32 dtype: batch inputs must be cast to
            # match (lax.conv requires equal dtypes), so the storage dtype IS
            # the compute dtype
            self.compute_dtype = self.dtype
        else:
            self.compute_dtype = None
        # ctx_group model parallelism: lower group annotations to sharding
        # constraints inside the step, and default each grouped parameter's
        # sharding from its group spec (explicit param_shardings win)
        from .parallel import placement as _placement
        self._placement = _placement.resolve(group2ctx, mesh)
        self._run, self._nodes = _build_graph_runner(symbol, self._placement)
        if self._placement is not None:
            if self.mesh is None:
                self.mesh = self._placement.mesh
            pgroups = _placement.param_groups(self._nodes)
            self._auto_group_params = {
                n: g for n, g in pgroups.items() if n in self.param_names
                and n not in self.param_shardings}
        else:
            self._auto_group_params = {}
        self._needs_rng = any((not n.is_variable) and n.op.needs_rng
                              for n in self._nodes)
        self.remat = remat
        if remat:
            self._run = self._wrap_remat(self._run)
        self._jit = {}  # keyed by batch size (rescale_grad depends on it)
        self._jit_scan = {}  # keyed by (batch_size, k) — see run_steps
        # guarded variants live in SEPARATE caches: enabling the guard must
        # never retrace (or change the jaxpr of) the unguarded fast path
        self._jit_g = {}
        self._jit_scan_g = {}
        self._base_key = None  # drawn lazily from the global seeded stream
        self._static_key = None  # cached no-rng key (one H2D, not per-step)
        # tracecheck runtime hooks (docs/static_analysis.md): every jit
        # cache entry registers with the program registry so the guard-on /
        # guard-off / scan program set is auditable as a unit, and every
        # dispatch records its call signature so an unexpected cache miss
        # logs (or raises, MXTPU_TRACECHECK=error) the cache-key diff
        self._watcher = None
        self.health = None  # per-run TrainingHealth (Module attaches it)
        # elastic dist training (docs/robustness.md): Module attaches the
        # kvstore's ring reducer here; the step then sums gradients across
        # worker processes through an ordered host callback INSIDE the
        # compiled program (so the K-step scan keeps its bulk dispatch).
        # Donation is disabled in dist mode: a dispatch that dies in the
        # ring must leave the input state buffers valid for the re-form.
        self.dist_reduce = None
        self.dist_error = None
        self.donate = True

    # ------------------------------------------------------------------
    def _ambient(self):
        """Ambient-mesh scope for jit trace/dispatch. Ops that dispatch on
        ``parallel.mesh.current_mesh()`` (MultiHeadAttention's 'seq' modes,
        TransformerStack's 'pipe' schedule) must see THIS TrainStep's mesh
        while the program traces; entering the scope on every dispatch
        keeps the first (tracing) call and steady-state calls identical,
        so the multi-axis program never depends on the caller remembering
        a ``with MeshScope(...)`` around ``fit``."""
        if self.mesh is None:
            import contextlib
            return contextlib.nullcontext()
        from .parallel.mesh import MeshScope
        return MeshScope(self.mesh)

    # ------------------------------------------------------------------
    def _wrap_remat(self, run):
        """Memory mirroring: recompute activations in backward
        (ref: MXNET_BACKWARD_DO_MIRROR, graph_executor.cc:213-226).

        remat=True: a single jax.checkpoint over the whole forward (minimum
        memory, full recompute). remat="conv": save only Convolution /
        FullyConnected outputs (the ``conv_out``/``fc_out`` checkpoint_name
        anchors in ops/nn.py) and recompute the elementwise chain between
        them (BN normalize, ReLU, pad/pool) in backward — on a
        bandwidth-bound chip this trades cheap VPU FLOPs for one fewer
        HBM round-trip per saved activation."""
        if self.remat == "conv":
            policy = jax.checkpoint_policies.save_only_these_names(
                "conv_out", "fc_out")
        else:
            policy = None

        def wrapped(arg_vals, aux_vals, key, is_train):
            def inner(arg_vals):
                return run(arg_vals, aux_vals, key, is_train)
            return jax.checkpoint(inner, policy=policy)(arg_vals)
        return wrapped

    # ------------------------------------------------------------------
    def init(self, data_shapes, label_shapes=None, initializer=None, seed=0):
        """Allocate and initialize state from inferred shapes.

        Runs under ``jax.transfer_guard("allow")``: init is setup, not the
        dispatch hot loop — host-to-device transfers are its job. The
        tracecheck runtime contract (``tracecheck``-marked tests under
        ``transfer_guard("disallow")``, docs/static_analysis.md) polices
        the per-dispatch path only."""
        with jax.transfer_guard("allow"):
            return self._init(data_shapes, label_shapes, initializer, seed)

    def _init(self, data_shapes, label_shapes, initializer, seed):
        shapes = dict(data_shapes)
        shapes.update(label_shapes or {})
        arg_shapes, _, aux_shapes = self.symbol.infer_shape(**shapes)
        shape_of = dict(zip(self.arg_names, arg_shapes))
        aux_shape_of = dict(zip(self.aux_names, aux_shapes))
        initializer = initializer or Xavier()
        attrs = self.symbol.attr_dict()
        # scoped seeding: deterministic init draws WITHOUT clobbering the
        # process-global stream (mx.random.seed set by the user must keep
        # governing dropout/SGLD keys drawn later in step())
        saved = _random.get_state()
        _random.seed(seed)
        try:
            params = {}
            for n in self.param_names:
                arr = NDArray(jnp.zeros(shape_of[n], self.dtype))
                initializer(InitDesc(n, attrs.get(n, {})), arr)
                params[n] = arr.data
            aux = {}
            for n in self.aux_names:
                arr = NDArray(jnp.zeros(aux_shape_of[n], self.dtype))
                initializer(InitDesc(n, attrs.get(n, {})), arr)
                aux[n] = arr.data
        finally:
            _random.set_state(saved)
        opt = self._init_opt_state(params)
        state = {"params": params, "aux": self.cast_stats(aux), "opt": opt,
                 "step": jnp.zeros((), jnp.int32)}
        if self.mesh is not None:
            state = self._shard_state(state)
        return state

    def cast_stats(self, aux):
        """MXTPU_BF16_STATS: aux (BatchNorm moving stats) storage cast —
        identity when the knob is off."""
        if not self.bf16_stats:
            return aux
        return {n: (v.astype(jnp.bfloat16)
                    if jnp.issubdtype(jnp.asarray(v).dtype, jnp.floating)
                    else v)
                for n, v in aux.items()}

    def cast_opt_state(self, opt):
        """MXTPU_BF16_STATS=opt|all: optimizer-state storage cast —
        identity when the knob is off."""
        if not self.bf16_opt:
            return opt
        return jax.tree_util.tree_map(
            lambda v: (v.astype(jnp.bfloat16)
                       if jnp.issubdtype(jnp.asarray(v).dtype, jnp.floating)
                       else v), opt)

    def _init_opt_state(self, params):
        return self.cast_opt_state(
            {n: self._opt.create_fused_state(v)
             for n, v in params.items()
             if n not in self.frozen_param_names})

    # ------------------------------------------------------------------
    def _param_spec(self, name, shape=None):
        if name in self.param_shardings:
            return self.param_shardings[name]
        g = self._auto_group_params.get(name)
        if g is not None and shape is not None:
            spec = self._placement.param_spec(g, tuple(shape))
            if spec is not None:
                return spec
        return P()

    def _shard_state(self, state):
        mesh = self.mesh
        # multi-host mesh: device_put cannot target non-addressable devices;
        # assemble global arrays from (identical) per-process host copies
        from .parallel.mesh import (is_multiprocess, host_to_global,
                                    host_broadcast0)
        if is_multiprocess(mesh):
            def put(v, spec):
                if spec == P():
                    # replicated state must be CONSISTENT across workers
                    # even if their host copies diverged (e.g. per-rank
                    # seeding): rank 0's copy is authoritative, like the
                    # reference server's single stored weight
                    v = host_broadcast0(mesh, v)
                return host_to_global(mesh, spec, v)
        else:
            def put(v, spec):
                return jax.device_put(
                    v, jax.sharding.NamedSharding(mesh, spec))

        def put_params(tree):
            return {n: put(v, self._param_spec(n, v.shape))
                    for n, v in tree.items()}

        out = dict(state)
        out["params"] = put_params(state["params"])
        # optimizer state pytrees shard exactly like their weight
        out["opt"] = {
            n: jax.tree_util.tree_map(
                lambda v, _n=n: put(v, self._param_spec(_n, v.shape)), st)
            for n, st in state["opt"].items()}
        out["aux"] = {n: put(v, P()) for n, v in state["aux"].items()}
        out["step"] = put(state["step"], P())
        return out

    def shard_batch(self, batch):
        """Place batch arrays with dim-0 sharded along the data axis; when
        the mesh also has a 'seq' axis, dim-1 of rank>=2 arrays is sharded
        along it (sequence/context parallelism — the token dim feeds the
        ring/Ulysses attention shards).

        On a multi-host mesh each process passes its LOCAL batch shard and
        the global batch is their concatenation — the dist_sync data
        partition (ref: kvstore num_workers/rank feeding ImageRecordIter
        part_index/num_parts)."""
        if self.mesh is None:
            return batch
        from .parallel.mesh import (is_multiprocess, host_to_global,
                                    data_axis_size, AXIS_SEQ)
        has_seq = AXIS_SEQ in self.mesh.axis_names
        bax = "data" if "data" in self.mesh.axis_names else None
        if bax is not None:
            n = data_axis_size(self.mesh)
            for k, v in batch.items():
                b = (v.shape if hasattr(v, "shape")
                     else np.asarray(v).shape)[0]
                if b % n:
                    raise MXNetError(
                        "shard_batch: %r batch dim %d does not divide the "
                        "%d-way 'data' mesh axis — pad the batch or pick a "
                        "divisible batch size" % (k, b, n))
        if has_seq:
            sp = data_axis_size(self.mesh, AXIS_SEQ)
            for k, v in batch.items():
                shp = (v.shape if hasattr(v, "shape")
                       else np.asarray(v).shape)
                if len(shp) >= 2 and shp[1] % sp:
                    raise MXNetError(
                        "shard_batch: %r sequence dim %d does not divide "
                        "the %d-way 'seq' mesh axis — pad the sequence or "
                        "pick a divisible seq_len" % (k, shp[1], sp))

        def spec_for(v):
            nd = getattr(v, "ndim", None)
            if nd is None:
                nd = np.asarray(v).ndim
            if has_seq and nd >= 2:
                return P(bax, AXIS_SEQ)
            return P(bax)

        if is_multiprocess(self.mesh):
            return {k: host_to_global(self.mesh, spec_for(v), v)
                    for k, v in batch.items()}
        return {k: jax.device_put(
            jnp.asarray(v),
            jax.sharding.NamedSharding(self.mesh, spec_for(v)))
            for k, v in batch.items()}

    # ------------------------------------------------------------------
    def _make_step_fn(self, batch_size, guard=False):
        """The fused fwd+bwd+update body, shared verbatim by the single-step
        jit (``step``) and the K-step ``lax.scan`` dispatch (``run_steps``)
        so both paths compute identical numbers.

        ``guard=True`` (docs/robustness.md "Numerical guardrails") adds
        on-device training-health sentinels: a global gradient norm and an
        all-finite flag over loss+grads (``jnp.isfinite`` reductions), and
        makes the update GUARDED — when the flag is false every
        param/opt/aux/step write ``jnp.where``-selects the old value, so the
        poisoned step is a device-side no-op (no ``lax.cond`` host
        round-trip). The guarded step_fn takes an extra traced ``poison``
        scalar (0.0 normally; NaN when the ``guard.grad_nan`` fault site
        fires) and returns ``(new_state, outs, (ok, grad_norm))``. With
        ``guard=False`` the trace is byte-for-byte the unguarded body — no
        sentinel ops, no retrace, jaxpr unchanged.

        An optimizer ``clip_global_norm`` is applied here across ALL
        parameter gradients at once (after rescale, before the per-optimizer
        elementwise ``clip_gradient``), reusing the same norm reduction as
        the sentinel."""
        run = self._run
        optzr = self._opt
        param_names = list(self.param_names)
        updated = [n for n in param_names if n not in self.frozen_param_names]
        rescale = (self.rescale_grad if self.rescale_grad is not None
                   else 1.0 / batch_size)
        compute_dtype = self.compute_dtype
        needs_key = getattr(optzr, "fused_needs_key", False)
        # per-parameter lr/wd multipliers resolved by name, matching
        # Optimizer._get_lr/_get_wd (ref: python/mxnet/optimizer.py)
        lr_mult = {n: optzr.lr_mult.get(n, 1.0) for n in updated}
        wd_mult = {n: optzr.wd_mult.get(n, 1.0) for n in updated}
        wd = optzr.wd
        clip_norm = getattr(optzr, "clip_global_norm", None)
        bf16_opt = self.bf16_opt

        def step_fn(state, batch, key, lr_base, poison=None):
            params, aux, opt = state["params"], state["aux"], state["opt"]
            # fold the state's OWN step counter into the key (traced, so no
            # host sync): restoring a checkpointed state reproduces the
            # dropout/SGLD noise stream implied by its step count, and two
            # states interleaved through one TrainStep never share noise
            key = jax.random.fold_in(key, state["step"].astype(jnp.uint32))

            def f(p):
                arg_vals = dict(batch)
                if compute_dtype is not None:
                    arg_vals = {
                        k: (v.astype(compute_dtype)
                            if jnp.issubdtype(v.dtype, jnp.floating) else v)
                        for k, v in arg_vals.items()}
                    p = {k: v.astype(compute_dtype) for k, v in p.items()}
                arg_vals.update(p)
                outs, aux_up = run(arg_vals, aux, key, True)
                return outs, aux_up

            (outs, aux_up), vjp_fn = jax.vjp(f, params)
            cots = [jnp.ones_like(o) for o in outs]
            cots_aux = jax.tree_util.tree_map(jnp.zeros_like, aux_up)
            (grads,) = vjp_fn((cots, cots_aux))

            t = state["step"].astype(jnp.float32) + jnp.float32(1.0)
            gs = {n: grads[n].astype(params[n].dtype) * rescale
                  for n in updated}
            if poison is not None:
                # guard.grad_nan fault site: poison is 0.0 on clean steps
                # (identity) and NaN on the injected one — always threaded
                # through the guarded trace so faulted and unfaulted guarded
                # runs share ONE compiled program
                gs = {n: g + poison.astype(g.dtype) for n, g in gs.items()}
            if self.dist_reduce is not None:
                # cross-process sum AFTER the local poison (a poisoned
                # worker poisons every replica, so guarded skips stay
                # bitwise-identical) and BEFORE gnorm/clip/guard, which
                # must see the GLOBAL gradient
                gs = self._cross_grad_reduce(gs, updated)
            gnorm = None
            if guard or clip_norm is not None:
                gnorm = jnp.sqrt(sum(
                    jnp.sum(jnp.square(g.astype(jnp.float32)))
                    for g in gs.values()))
            if clip_norm is not None:
                scale = jnp.minimum(
                    jnp.float32(1.0),
                    jnp.float32(clip_norm)
                    / jnp.maximum(gnorm, jnp.float32(1e-12)))
                gs = {n: g * scale.astype(g.dtype) for n, g in gs.items()}
            ok = None
            if guard:
                # all-finite over loss+grads: outputs feed the in-scan loss,
                # and any non-finite forward poisons the grads anyway
                flags = [jnp.all(jnp.isfinite(g)) for g in gs.values()]
                flags += [jnp.all(jnp.isfinite(o)) for o in outs]
                ok = flags[0]
                for fl in flags[1:]:
                    ok = jnp.logical_and(ok, fl)
            new_params = dict(params)
            new_opt = {}
            for i, n in enumerate(updated):
                w = params[n]
                g = gs[n]
                subkey = (jax.random.fold_in(key, _OPT_KEY_OFFSET + i)
                          if needs_key else None)
                new_w, new_s = optzr.fused_update(
                    n, w, g, opt[n], lr_base * lr_mult[n], wd * wd_mult[n],
                    t, key=subkey)
                if bf16_opt:
                    # bf16 optimizer state: the update computes in the
                    # promoted dtype, storage goes back to bf16 — BEFORE
                    # the guard select (the scan carry dtype must not
                    # change step-to-step)
                    new_s = jax.tree_util.tree_map(
                        lambda a, b: a.astype(b.dtype), new_s, opt[n])
                if guard:
                    new_w = jnp.where(ok, new_w, w)
                    new_s = jax.tree_util.tree_map(
                        lambda a, b: jnp.where(ok, a, b), new_s, opt[n])
                new_params[n] = new_w
                new_opt[n] = new_s
            new_aux = dict(aux)
            for k, v in aux_up.items():
                nv = v.astype(aux[k].dtype)
                if guard:
                    nv = jnp.where(ok, nv, aux[k])
                new_aux[k] = nv
            # a skipped step is a FULL no-op: the step counter (and with it
            # the dropout/SGLD noise stream) does not advance either
            step_inc = ok.astype(jnp.int32) if guard else 1
            new_state = {"params": new_params, "aux": new_aux,
                         "opt": new_opt, "step": state["step"] + step_inc}
            new_state = self._pin_state_sharding(new_state)
            if guard:
                return new_state, outs, (ok, gnorm)
            return new_state, outs

        return step_fn

    def _cross_grad_reduce(self, gs, updated):
        """Sum the update set's gradients across worker processes inside
        the traced step: flatten to ONE f32 vector, hop to the host
        through an ordered ``io_callback`` for the control-plane ring
        allreduce, unflatten. One callback per step regardless of
        parameter count, and it composes with the K-step ``lax.scan`` —
        the bulked dispatch makes K ring exchanges without returning to
        Python. A lost worker cannot raise through XLA: the callback
        stashes the error on the TrainStep, returns NaN (a guarded step
        no-ops on it), and :meth:`_dist_sync_result` re-raises after the
        dispatch."""
        from jax.experimental import io_callback
        names = list(updated)
        if not names:
            return gs
        flat = jnp.concatenate([gs[n].astype(jnp.float32).reshape(-1)
                                for n in names])

        def host_sum(v):
            try:
                out = self.dist_reduce(np.asarray(v, np.float32))
                return np.asarray(out, np.float32).reshape(v.shape)
            except Exception as e:
                self.dist_error = e
                return np.full(v.shape, np.nan, np.float32)

        sds = jax.ShapeDtypeStruct(flat.shape, jnp.float32)
        kwargs = {}
        if self.mesh is not None and self.mesh.devices.size > 1:
            # pin the callback to one device so a multi-device local mesh
            # performs ONE ring exchange per step, not one per device
            kwargs["sharding"] = jax.sharding.SingleDeviceSharding(
                self.mesh.devices.ravel()[0])
        try:
            red = io_callback(host_sum, sds, flat, ordered=True, **kwargs)
        except TypeError:           # older jax: no sharding kwarg
            red = io_callback(host_sum, sds, flat, ordered=True)
        out = {}
        off = 0
        for n in names:
            size = int(np.prod(gs[n].shape)) if gs[n].shape else 1
            out[n] = (red[off:off + size].reshape(gs[n].shape)
                      .astype(gs[n].dtype))
            off += size
        return out

    def _dist_sync_result(self, out):
        """Dist-mode dispatch epilogue: block on the results and re-raise
        any error the ring callback stashed (WorkerLostError surfaces
        HERE, with the pre-dispatch state still intact — donation is off
        in dist mode). Single-process: identity, no block."""
        if self.dist_reduce is None:
            return out
        jax.block_until_ready(out)
        err, self.dist_error = self.dist_error, None
        if err is not None:
            raise err
        return out

    def _pin_state_sharding(self, state):
        """Constrain the OUTPUT state to the same shardings ``_shard_state``
        placed the input with. Without the pin, GSPMD is free to return the
        state under whatever sharding its solver picked for a multi-axis
        mesh — then dispatch 2's argument shardings differ from dispatch
        1's and the jit cache misses once (a retrace tracecheck rightly
        flags). Pinning closes the loop: state out == state in, every
        dispatch hits the first compile."""
        if self.mesh is None:
            return state
        from jax.sharding import NamedSharding, PartitionSpec as P

        def con(v, spec):
            return jax.lax.with_sharding_constraint(
                v, NamedSharding(self.mesh, spec))

        out = dict(state)
        out["params"] = {n: con(v, self._param_spec(n, v.shape))
                         for n, v in state["params"].items()}
        out["opt"] = {
            n: jax.tree_util.tree_map(
                lambda v, _n=n: con(v, self._param_spec(_n, v.shape)), st)
            for n, st in state["opt"].items()}
        out["aux"] = {n: con(v, P()) for n, v in state["aux"].items()}
        out["step"] = con(state["step"], P())
        return out

    def _state_out_shardings(self, state):
        """Prefix pytree of jit ``out_shardings`` for the state: params and
        optimizer state pinned to their placement spec (one spec per param
        covers its whole opt-state subtree), aux/step replicated — exactly
        what ``_shard_state`` placed the inputs with. The in-body
        ``_pin_state_sharding`` constraint alone does not survive the
        scan-carry unification on every backend (jax 0.4.x may hand back
        solver-chosen shardings from the While root), and an unpinned
        output misses the jit cache on the next dispatch. ``None`` when no
        mesh (and for the non-state outputs: propagation decides)."""
        if self.mesh is None:
            return None
        from jax.sharding import NamedSharding

        def ns(spec):
            return NamedSharding(self.mesh, spec)

        return {
            "params": {n: ns(self._param_spec(n, v.shape))
                       for n, v in state["params"].items()},
            "opt": {n: ns(self._param_spec(n, state["params"][n].shape))
                    for n in state["opt"]},
            "aux": {n: ns(P()) for n in state["aux"]},
            "step": ns(P()),
        }

    def _build(self, batch_size, state=None):
        outs = None
        if state is not None and self.mesh is not None:
            outs = (self._state_out_shardings(state), None)
        return jax.jit(self._make_step_fn(batch_size),
                       donate_argnums=(0,) if self.donate else (),
                       out_shardings=outs)

    def _build_guard_step(self, batch_size, state=None):
        """Guarded single-step jit: the fused body plus device sentinels,
        returning ``(new_state, outs, packed)`` where ``packed`` is the same
        ``[loss, correct, nsamp, skipped, grad_norm]`` layout the guarded
        scan accumulates (zeros for a skipped step, so metric consumers
        exclude it without a second readback)."""
        step_fn = self._make_step_fn(batch_size, guard=True)
        label_names = list(self.label_names)

        def fn(state, batch, key, lr, poison):
            new_st, outs, (ok, gnorm) = step_fn(state, batch, key, lr,
                                                poison)
            zero = jnp.zeros((), jnp.float32)
            loss, correct = _metric_step_sums(
                outs, [batch.get(n) for n in label_names], zero)
            okf = ok.astype(jnp.float32)
            packed = jnp.stack([
                jnp.where(ok, loss, zero), jnp.where(ok, correct, zero),
                okf * jnp.float32(batch_size), jnp.float32(1.0) - okf,
                gnorm.astype(jnp.float32)])
            return new_st, outs, packed

        outs_sh = None
        if state is not None and self.mesh is not None:
            outs_sh = (self._state_out_shardings(state), None, None)
        return jax.jit(fn, donate_argnums=(0,) if self.donate else (),
                       out_shardings=outs_sh)

    def _build_scan(self, batch_size, k, guard=False, metric_spec=None,
                    state=None):
        """K steps in ONE compiled dispatch: lax.scan of the fused step body
        over a stacked (k, batch, ...) superbatch, state donated across the
        whole scan. This is the reference engine's bulking — whole graph
        segments per engine dispatch (SURVEY.md §3.1) — applied to the train
        loop itself: Python dispatch and host readback amortize over K steps.

        Metric accumulators are carried through the scan so metrics cross
        the host boundary once per K steps. Without ``metric_spec`` the
        legacy layout (CE loss sum, top-1 correct count, sample count)
        pairs each rank-2 output with its label by position, matching
        metric.CrossEntropy (eps 1e-8) / metric.Accuracy (argmax axis=1)
        bit-for-bit over the same outputs. With a
        :class:`~mxnet_tpu.metric.DeviceSumSpec` (packed-accumulator
        protocol, docs/perf.md "Packed accumulators") the carry holds the
        spec's declared slots instead — any metric that declares a layout
        rides the same one-readback-per-K contract.

        ``guard=True`` threads the training-health sentinels through the
        scan: a per-step NaN poison vector rides in next to ``lrs``, skipped
        (non-finite) steps are excluded from every accumulator slot, and
        the packed result grows to ``[slots..., skipped, last_grad_norm]``
        — sentinels ride back with the metric sums in the SAME single
        readback. The ``guard=False`` trace is unchanged.
        """
        step_fn = self._make_step_fn(batch_size, guard=guard)
        label_names = list(self.label_names)
        spec = metric_spec
        if spec is not None:
            nslots = len(spec.slots)

            def slot_sums(outs, labels):
                return tuple(spec.step_sums(outs, labels))
        else:
            nslots = 3

            def slot_sums(outs, labels):
                return _default_slot_sums(outs, labels, batch_size)

        def scan_fn(state, superbatch, key, lrs, poisons=None):
            zero = jnp.zeros((), jnp.float32)

            def body(carry, xs):
                if guard:
                    st, accs = carry
                    slots, skipped, gnorm = \
                        accs[:nslots], accs[nslots], accs[nslots + 1]
                    batch, lr, poison = xs
                    new_st, outs, (ok, g_norm) = step_fn(st, batch, key, lr,
                                                         poison)
                else:
                    st, slots = carry
                    batch, lr = xs
                    new_st, outs = step_fn(st, batch, key, lr)
                step_vals = slot_sums(
                    outs, [batch.get(n) for n in label_names])
                if guard:
                    # skipped steps drop out of every accumulator: the
                    # metric denominators never see the poisoned batch
                    slots = tuple(a + jnp.where(ok, v, zero)
                                  for a, v in zip(slots, step_vals))
                    skipped = skipped + jnp.where(ok, zero, jnp.float32(1))
                    return (new_st, slots + (skipped,
                                             g_norm.astype(jnp.float32))), \
                        None
                slots = tuple(a + v for a, v in zip(slots, step_vals))
                return (new_st, slots), None

            if guard:
                zeros = tuple(zero for _ in range(nslots + 2))
                (state, accs), _ = jax.lax.scan(
                    body, (state, zeros), (superbatch, lrs, poisons))
                return state, jnp.stack(list(accs))
            zeros = tuple(zero for _ in range(nslots))
            (state, slots), _ = jax.lax.scan(
                body, (state, zeros), (superbatch, lrs))
            # one packed array => one host transfer for all K-step metrics
            return state, jnp.stack(list(slots))

        outs_sh = None
        if state is not None and self.mesh is not None:
            outs_sh = (self._state_out_shardings(state), None)
        return jax.jit(scan_fn, donate_argnums=(0,) if self.donate else (),
                       out_shardings=outs_sh)

    def _dispatch_key(self):
        if self._needs_rng or getattr(self._opt, "fused_needs_key", False):
            # base key rides the global seeded stream (mx.random.seed), so
            # dropout/SGLD respond to seeding and two TrainSteps never share
            # noise; per-step keys fold in the step counter
            if self._base_key is None:
                with jax.transfer_guard("allow"):  # one-time key creation
                    self._base_key = _random.split()
            return self._base_key  # per-step variation folds in state["step"]
        if self._static_key is None:
            # cached: creating a fresh key would cost an (implicit) H2D
            # per dispatch — the transfer-guard runtime lint flags exactly
            # this pattern inside the hot loop
            with jax.transfer_guard("allow"):
                self._static_key = jax.random.key(0)
        return self._static_key  # static; unused ops ignore it

    def _next_lr(self):
        # scheduler clock advances host-side; lr rides in as a traced scalar
        self._opt.num_update += 1
        if self._opt.lr_scheduler is not None:
            return self._opt.lr_scheduler(self._opt.num_update)
        return self._opt.lr

    def _poison_scalars(self, k):
        """Host-side ``guard.grad_nan`` firing, one shot per TRAINING step:
        a (k,) float32 of 0.0 (clean) / NaN (poisoned) that rides into the
        guarded trace (docs/robustness.md "Numerical guardrails")."""
        from . import faults as _faults
        return np.asarray(
            [float("nan") if _faults.fire_flag("guard.grad_nan") else 0.0
             for _ in range(k)], np.float32)

    def _tc_after(self, kind, cache_key, jitfn, call_args, result=None,
                  spec=None):
        """tracecheck runtime hook (docs/static_analysis.md), called right
        after a watched jit call: registers the program with the analyzer's
        registry (first call per cache entry — the guard-on / guard-off /
        scan program set is auditable as a unit via
        ``tracecheck.check_registered``) and feeds the call signature to the
        per-TrainStep retrace watcher, so an unexpected jit-cache miss logs
        — or raises under ``MXTPU_TRACECHECK=error`` — a diff naming the
        offending argument. Signature/struct capture is metadata-only
        (shape/dtype/weak-type), so the donated state buffers are safe to
        sign post-call; the dispatch is already enqueued, so this host work
        overlaps device compute."""
        from . import tracecheck as _tc
        if not _tc.enabled():
            return
        if self._watcher is None:
            # names are process-unique (tracecheck.make_watcher): two
            # TrainSteps over same-named symbols must not collide in the
            # program registry, or the second instance's programs would
            # never register and check_registered would silently audit the
            # wrong instance's program set
            self._watcher = _tc.make_watcher(
                "TrainStep(%s)" % (self.symbol.name,))
        if isinstance(cache_key, tuple):
            key = "%s[bs=%d,k=%d]" % (kind, cache_key[0], cache_key[1])
            if len(cache_key) > 2:
                # spec-keyed scan (packed-accumulator protocol): the
                # metric tag (+ signature digest — two eps variants of
                # one metric are distinct programs) keeps same-shape
                # programs with different packed layouts distinct in the
                # registry. crc32 over a STABILIZED repr, NOT hash():
                # tuple hashes are PYTHONHASHSEED-salted, and a raw repr
                # of a CustomMetric signature would embed its function
                # object's memory address — either way a run-to-run-
                # unstable program name silently unpins name-matched
                # suppressions and drifts committed baselines
                import zlib
                tag = spec.tag if spec is not None else "spec"
                key = "%s[bs=%d,k=%d,m=%s.%04x]" % (
                    kind, cache_key[0], cache_key[1], tag,
                    zlib.crc32(repr(_stable_sig(cache_key[2]))
                               .encode()) & 0xffff)
        else:
            key = "%s[bs=%d]" % (kind, cache_key)
        name = "%s/%s" % (self._watcher.name, key)
        if name not in _tc.PROGRAMS:
            _tc.register_program(name, jitfn, call_args,
                                 donate_argnums=(0,))
            if self.mesh is not None:
                # MXTPU_COMMSCHECK (docs/static_analysis.md
                # "Communication lints"): one-time collective audit of a
                # freshly compiled SHARDED program — off by default; warn/
                # error pay one extra compile at the first dispatch. The
                # call args are reduced to sharded structs inside, so the
                # just-donated state buffers are never read.
                from . import commscheck as _cc
                trips = (cache_key[1] if isinstance(cache_key, tuple)
                         else 1)
                _cc.maybe_audit_dispatch(name, jitfn, call_args,
                                         loop_trips=trips, mesh=self.mesh)
            # MXTPU_FLOPCHECK (docs/static_analysis.md "Roofline
            # lints"): one-time roofline audit of every freshly compiled
            # program (single-device too — a fusion regression needs no
            # mesh to hurt); same struct-args discipline as above.
            from . import flopcheck as _fc
            _fc.maybe_audit_dispatch(
                name, jitfn, call_args,
                loop_trips=(cache_key[1] if isinstance(cache_key, tuple)
                            else 1),
                mesh=self.mesh)
        try:
            self._watcher.after_call(key, jitfn, _tc.signature(call_args),
                                     health=self.health)
        except _tc.RetraceError as e:
            # the dispatch already ran and donated the old state: hand the
            # new state to the caller through the exception so it never
            # holds a reference to deleted buffers
            e.result = result
            raise

    def step(self, state, batch, guard=False):
        """One fused train step. ``batch``: dict name -> array.

        ``guard=True`` runs the guarded body (non-finite steps become
        device-side no-ops) and returns ``(new_state, outputs, packed)``
        where ``packed`` is the ``[loss, correct, nsamp, skipped,
        grad_norm]`` sentinel array (see :class:`StepMetrics`)."""
        bs = next(iter(batch.values())).shape[0]
        if guard:
            if bs not in self._jit_g:
                self._jit_g[bs] = self._build_guard_step(bs, state=state)
            fn = self._jit_g[bs]
            # 0-d np.asarray pins (see run_steps): explicit dtype + explicit
            # device transfer for the per-step lr/poison scalars (a bare
            # numpy SCALAR still rides the implicit-transfer path)
            call_args = (state, batch, self._dispatch_key(),
                         jnp.asarray(np.asarray(self._next_lr(),
                                                np.float32)),
                         jnp.asarray(np.asarray(
                             self._poison_scalars(1)[0], np.float32)))
            with self._ambient():
                out = self._dist_sync_result(fn(*call_args))
                self._tc_after("guard-step", bs, fn, call_args, result=out)
            return out
        if bs not in self._jit:
            self._jit[bs] = self._build(bs, state=state)
        fn = self._jit[bs]
        call_args = (state, batch, self._dispatch_key(),
                     jnp.asarray(np.asarray(self._next_lr(), np.float32)))
        with self._ambient():
            out = self._dist_sync_result(fn(*call_args))
            self._tc_after("step", bs, fn, call_args, result=out)
        return out

    def run_steps(self, state, superbatch, k=None, guard=False,
                  metric_spec=None):
        """Run K fused train steps in ONE compiled dispatch.

        ``superbatch``: dict name -> stacked array of shape (k, batch, ...)
        (build one with ``io.SuperBatchIter`` / ``DataIter.superbatch(k)``,
        or stack K batches yourself). The scheduler clock advances K host
        updates and the per-step lr schedule rides in as a traced (k,)
        vector, so schedules never retrace; the jit cache is keyed on
        (batch_size, k) — plus the metric spec's signature when one is
        passed — so a fixed K never recompiles across epochs.

        Returns ``(new_state, metrics)`` where ``metrics`` is a
        :class:`StepMetrics` holding the device-resident K-step
        accumulators — reading any of its properties performs the single
        host readback for the dispatch. Without ``metric_spec`` the
        accumulators are the legacy (loss sum, top-1 correct count, sample
        count); with a :class:`~mxnet_tpu.metric.DeviceSumSpec` they are
        the spec's declared slots (read by name via ``metrics.values()``,
        folded by ``metric.update_from_device_sums``).

        ``guard=True`` compiles the GUARDED scan (separate jit cache; the
        unguarded program is untouched): non-finite steps become device-side
        no-ops, are excluded from the metric accumulators, and the returned
        :class:`StepMetrics` additionally carries ``skipped`` and
        ``last_grad_norm`` in the same single readback. A spec with no
        watchable loss pair is augmented with the in-scan CE loss so the
        guard's divergence EMA keeps its observation.
        """
        vals = list(superbatch.values())
        if not vals:
            raise MXNetError("run_steps: empty superbatch")
        lead = vals[0].shape[0]
        if k is not None and k != lead:
            raise MXNetError("run_steps: k=%d but superbatch is stacked %d "
                             "deep" % (k, lead))
        k = lead
        if any(v.shape[0] != k or v.ndim < 2 for v in vals):
            raise MXNetError("run_steps: superbatch arrays must share a "
                             "(k, batch, ...) leading shape, got %r"
                             % {n: tuple(v.shape)
                                for n, v in superbatch.items()})
        bs = vals[0].shape[1]
        if guard and metric_spec is not None:
            metric_spec = _with_guard_loss(metric_spec, bs)
        cache = self._jit_scan_g if guard else self._jit_scan
        ckey = ((bs, k) if metric_spec is None
                else (bs, k, metric_spec.signature))
        if ckey not in cache:
            cache[ckey] = self._build_scan(bs, k, guard=guard,
                                           metric_spec=metric_spec,
                                           state=state)
        fn = cache[ckey]
        # lr vector pinned through np.float32 BEFORE the device transfer:
        # the explicit f32 pin keeps the trace weak-type-free under any
        # jax config (tracecheck dtype lint), and jnp.asarray of a host
        # numpy array is an EXPLICIT transfer — a bare Python list would
        # ride an implicit one, which the transfer-guard runtime lint
        # rejects in the dispatch hot loop
        lrs = jnp.asarray(np.asarray([self._next_lr() for _ in range(k)],
                                     np.float32))
        if guard:
            call_args = (state, superbatch, self._dispatch_key(), lrs,
                         jnp.asarray(self._poison_scalars(k)))
            with self._ambient():
                new_state, packed = self._dist_sync_result(fn(*call_args))
                sums = StepMetrics(packed, guarded=True, spec=metric_spec)
                self._tc_after("guard-scan", ckey, fn, call_args,
                               result=(new_state, sums), spec=metric_spec)
            return new_state, sums
        call_args = (state, superbatch, self._dispatch_key(), lrs)
        with self._ambient():
            new_state, packed = self._dist_sync_result(fn(*call_args))
            sums = StepMetrics(packed, spec=metric_spec)
            self._tc_after("scan", ckey, fn, call_args,
                           result=(new_state, sums), spec=metric_spec)
        return new_state, sums

    def shard_superbatch(self, superbatch):
        """Place stacked (k, batch, ...) arrays for the scan dispatch: dim 0
        is the step axis (never sharded), dim 1 is the batch axis sharded
        along 'data' — the superbatch analog of :meth:`shard_batch`.

        Arrays already carrying the right NamedSharding (a
        ``SuperBatchIter`` given ``sharding=`` lands them per-chip on the
        producer thread) pass through ``jax.device_put`` as a no-op — the
        dispatch hot loop then performs zero resharding copies."""
        def to_jnp(v):
            return v.data if isinstance(v, NDArray) else jnp.asarray(v)
        if self.mesh is None:
            return {n: to_jnp(v) for n, v in superbatch.items()}
        from .parallel.mesh import (is_multiprocess, data_axis_size,
                                    AXIS_SEQ)
        if is_multiprocess(self.mesh):
            raise MXNetError("shard_superbatch: multi-process meshes keep "
                             "per-step dispatch (use step())")
        has_seq = AXIS_SEQ in self.mesh.axis_names
        bax = "data" if "data" in self.mesh.axis_names else None
        if bax is not None:
            n = data_axis_size(self.mesh)
            for name, v in superbatch.items():
                b = getattr(v, "shape", (0, 0))[1]
                if b % n:
                    raise MXNetError(
                        "shard_superbatch: %r batch dim %d does not divide "
                        "the %d-way 'data' mesh axis" % (name, b, n))
        if has_seq:
            sp = data_axis_size(self.mesh, AXIS_SEQ)
            for name, v in superbatch.items():
                shp = getattr(v, "shape", ())
                if len(shp) >= 3 and shp[2] % sp:
                    raise MXNetError(
                        "shard_superbatch: %r sequence dim %d does not "
                        "divide the %d-way 'seq' mesh axis — pad the "
                        "sequence or pick a divisible seq_len"
                        % (name, shp[2], sp))

        def spec_for(v):
            if has_seq and v.ndim >= 3:
                return P(None, bax, AXIS_SEQ)
            return P(None, bax)

        return {n: jax.device_put(
            to_jnp(v), jax.sharding.NamedSharding(self.mesh, spec_for(v)))
            for n, v in superbatch.items()}


def data_parallel_spec(mesh_shape, n_devices=None, devices=None):
    """Helper: build a mesh dict for make-style calls."""
    from .parallel.mesh import make_mesh
    return make_mesh(mesh_shape, devices)
