"""Fused training step: forward + backward + optimizer update in ONE jit.

This is the TPU-native analog of everything the reference engine pipeline did
per batch — RunOps over bulked segments, gradient reduce, updater
(ref: call stack SURVEY.md §3.1) — collapsed into a single donated XLA
computation. Module uses the lazy executor path for API fidelity; this module
is the performance path used by bench.py, the multichip dry-run, and any
training loop that wants max throughput.

Sharding: pass a Mesh plus optional per-parameter PartitionSpecs. Batch
arrays are sharded along ``data``; parameters default to replicated
(pure DP — XLA inserts the gradient psum exactly where the reference ran its
CommDevice reduce) and any parameter given a spec with a ``model`` axis is
tensor-parallel sharded.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .base import MXNetError
from .executor import _build_graph_runner
from .initializer import Xavier, InitDesc
from .ndarray import NDArray
from .ops import registry as _reg
from . import random as _random

P = jax.sharding.PartitionSpec


def _sgd_mom_init(shape, dtype):
    return jnp.zeros(shape, dtype)


class TrainStep(object):
    """Compiled train step over a symbol.

    state = {params, aux, opt, step}; ``step(state, batch)`` returns
    (new_state, outputs) and donates the old state buffers.
    """

    def __init__(self, symbol, data_names=("data",),
                 label_names=("softmax_label",), optimizer="sgd",
                 learning_rate=0.01, momentum=0.9, wd=0.0, rescale_grad=None,
                 mesh=None, param_shardings=None, dtype=np.float32,
                 compute_dtype=None, remat=False):
        self.symbol = symbol
        self.data_names = list(data_names)
        self.label_names = list(label_names)
        self.arg_names = symbol.list_arguments()
        self.aux_names = symbol.list_auxiliary_states()
        self.param_names = [n for n in self.arg_names
                            if n not in self.data_names + self.label_names]
        self.optimizer = optimizer
        self.lr = learning_rate
        self.momentum = momentum
        self.wd = wd
        self.rescale_grad = rescale_grad
        self.mesh = mesh
        self.param_shardings = dict(param_shardings or {})
        self.dtype = np.dtype(dtype)
        if compute_dtype is not None:
            self.compute_dtype = np.dtype(compute_dtype)
        elif self.dtype != np.dtype(np.float32):
            # params stored in a non-f32 dtype: batch inputs must be cast to
            # match (lax.conv requires equal dtypes), so the storage dtype IS
            # the compute dtype
            self.compute_dtype = self.dtype
        else:
            self.compute_dtype = None
        self._run, self._nodes = _build_graph_runner(symbol)
        self._needs_rng = any((not n.is_variable) and n.op.needs_rng
                              for n in self._nodes)
        if remat:
            self._run = self._wrap_remat(self._run)
        self._jit = {}  # keyed by batch size (rescale_grad depends on it)

    # ------------------------------------------------------------------
    def _wrap_remat(self, run):
        """Memory mirroring: recompute activations in backward
        (ref: MXNET_BACKWARD_DO_MIRROR, graph_executor.cc:213-226 — here a
        single jax.checkpoint over the whole forward)."""
        def wrapped(arg_vals, aux_vals, key, is_train):
            def inner(arg_vals):
                return run(arg_vals, aux_vals, key, is_train)
            return jax.checkpoint(inner)(arg_vals)
        return wrapped

    # ------------------------------------------------------------------
    def init(self, data_shapes, label_shapes=None, initializer=None, seed=0):
        """Allocate and initialize state from inferred shapes."""
        shapes = dict(data_shapes)
        shapes.update(label_shapes or {})
        arg_shapes, _, aux_shapes = self.symbol.infer_shape(**shapes)
        shape_of = dict(zip(self.arg_names, arg_shapes))
        aux_shape_of = dict(zip(self.aux_names, aux_shapes))
        initializer = initializer or Xavier()
        _random.seed(seed)
        attrs = self.symbol.attr_dict()
        params = {}
        for n in self.param_names:
            arr = NDArray(jnp.zeros(shape_of[n], self.dtype))
            initializer(InitDesc(n, attrs.get(n, {})), arr)
            params[n] = arr.data
        aux = {}
        for n in self.aux_names:
            arr = NDArray(jnp.zeros(aux_shape_of[n], self.dtype))
            initializer(InitDesc(n, attrs.get(n, {})), arr)
            aux[n] = arr.data
        opt = self._init_opt_state(params)
        state = {"params": params, "aux": aux, "opt": opt,
                 "step": jnp.zeros((), jnp.int32)}
        if self.mesh is not None:
            state = self._shard_state(state)
        return state

    def _init_opt_state(self, params):
        if self.optimizer == "sgd" and self.momentum:
            return {"mom": {n: jnp.zeros_like(v) for n, v in params.items()}}
        if self.optimizer == "adam":
            return {"mean": {n: jnp.zeros_like(v) for n, v in params.items()},
                    "var": {n: jnp.zeros_like(v) for n, v in params.items()}}
        return {}

    # ------------------------------------------------------------------
    def _param_spec(self, name):
        return self.param_shardings.get(name, P())

    def _shard_state(self, state):
        mesh = self.mesh

        def put_params(tree):
            return {n: jax.device_put(
                v, jax.sharding.NamedSharding(mesh, self._param_spec(n)))
                for n, v in tree.items()}

        out = dict(state)
        out["params"] = put_params(state["params"])
        out["opt"] = {k: put_params(v) for k, v in state["opt"].items()}
        repl = jax.sharding.NamedSharding(mesh, P())
        out["aux"] = {n: jax.device_put(v, repl)
                      for n, v in state["aux"].items()}
        out["step"] = jax.device_put(state["step"], repl)
        return out

    def shard_batch(self, batch):
        """device_put batch arrays with dim-0 sharded along the data axis."""
        if self.mesh is None:
            return batch
        s = jax.sharding.NamedSharding(self.mesh, P("data"))
        return {k: jax.device_put(jnp.asarray(v), s) for k, v in batch.items()}

    # ------------------------------------------------------------------
    def _build(self, batch_size):
        run = self._run
        param_names = list(self.param_names)
        lr, momentum, wd = self.lr, self.momentum, self.wd
        rescale = (self.rescale_grad if self.rescale_grad is not None
                   else 1.0 / batch_size)
        optimizer = self.optimizer
        compute_dtype = self.compute_dtype

        def step_fn(state, batch, key):
            params, aux, opt = state["params"], state["aux"], state["opt"]

            def f(p):
                arg_vals = dict(batch)
                if compute_dtype is not None:
                    arg_vals = {
                        k: (v.astype(compute_dtype)
                            if jnp.issubdtype(v.dtype, jnp.floating) else v)
                        for k, v in arg_vals.items()}
                    p = {k: v.astype(compute_dtype) for k, v in p.items()}
                arg_vals.update(p)
                outs, aux_up = run(arg_vals, aux, key, True)
                return outs, aux_up

            (outs, aux_up), vjp_fn = jax.vjp(f, params)
            cots = [jnp.ones_like(o) for o in outs]
            cots_aux = jax.tree_util.tree_map(jnp.zeros_like, aux_up)
            (grads,) = vjp_fn((cots, cots_aux))
            grads = {n: grads[n].astype(state["params"][n].dtype)
                     for n in param_names}

            new_params = {}
            new_opt = {k: dict(v) for k, v in opt.items()}
            for n in param_names:
                w, g = params[n], grads[n]
                g = g * rescale
                if optimizer == "sgd" and momentum:
                    m = momentum * opt["mom"][n] - lr * (g + wd * w)
                    new_params[n] = w + m
                    new_opt["mom"][n] = m
                elif optimizer == "sgd":
                    new_params[n] = w - lr * (g + wd * w)
                elif optimizer == "adam":
                    t = state["step"].astype(jnp.float32) + 1.0
                    b1, b2, eps = 0.9, 0.999, 1e-8
                    g = g + wd * w  # ref: python Adam applies wd to the grad
                    mean = b1 * opt["mean"][n] + (1 - b1) * g
                    var = b2 * opt["var"][n] + (1 - b2) * g * g
                    lr_t = lr * jnp.sqrt(1 - b2 ** t) / (1 - b1 ** t)
                    new_params[n] = w - lr_t * mean / (jnp.sqrt(var) + eps)
                    new_opt["mean"][n] = mean
                    new_opt["var"][n] = var
                else:
                    raise MXNetError("fused step: optimizer %r unsupported"
                                     % optimizer)
            new_aux = dict(aux)
            for k, v in aux_up.items():
                new_aux[k] = v.astype(aux[k].dtype)
            new_state = {"params": new_params, "aux": new_aux,
                         "opt": new_opt, "step": state["step"] + 1}
            return new_state, outs

        return jax.jit(step_fn, donate_argnums=(0,))

    def step(self, state, batch):
        """One fused train step. ``batch``: dict name -> array."""
        bs = next(iter(batch.values())).shape[0]
        if bs not in self._jit:
            self._jit[bs] = self._build(bs)
        if self._needs_rng:
            key = jax.random.fold_in(jax.random.key(0), state["step"])
        else:
            key = jax.random.key(0)  # static; unused ops ignore it
        return self._jit[bs](state, batch, key)


def data_parallel_spec(mesh_shape, n_devices=None, devices=None):
    """Helper: build a mesh dict for make-style calls."""
    from .parallel.mesh import make_mesh
    return make_mesh(mesh_shape, devices)
