"""Flat C-API-shaped surface for language bindings.

The reference exposes 114 ``extern "C" MX*`` functions
(ref: include/mxnet/c_api.h, src/c_api/*.cc) that every binding (R/Scala/
Perl/C++/Matlab — SURVEY.md §2.7) consumes: opaque handles + flat functions
returning an int status, with ``MXGetLastError`` for messages.

This module reproduces that contract over the Python substrate: integer
handles into a registry, the same function names/argument orders, status-code
returns. It is directly usable via cffi's ``embedding`` or any FFI that can
call into CPython; a compiled ``libmxnet_tpu`` shim that exports these as
real C symbols (CPython C API) is the bindings-stage follow-up.

Only the error contract differs internally: exceptions are caught and stored
for MXGetLastError, exactly like c_api_common.h's error ring.
"""
from __future__ import annotations

import json
import threading

import numpy as np

from . import ndarray as nd
from . import symbol as sym
from . import kvstore as kvs
from . import random as _random
from .base import MXNetError
from .executor import Executor
from .ndarray import NDArray

_state = threading.local()
_handles = {}
_next_handle = [1]
_lock = threading.Lock()


def _new_handle(obj):
    with _lock:
        h = _next_handle[0]
        _next_handle[0] += 1
        _handles[h] = obj
    return h


def _get(h):
    return _handles[h]


def _free(h):
    _handles.pop(h, None)


def _capi(fn):
    """Wrap: return 0 on success, -1 + stored error on exception
    (ref: API_BEGIN/API_END macros, c_api_common.h)."""
    def wrapped(*args, **kwargs):
        try:
            return 0, fn(*args, **kwargs)
        except Exception as e:  # noqa: BLE001 - the C API flattens all errors
            _state.error = "%s: %s" % (type(e).__name__, e)
            return -1, None
    wrapped.__name__ = fn.__name__
    return wrapped


def MXGetLastError():
    return getattr(_state, "error", "")


@_capi
def MXGetVersion():
    from .base import (MXNET_TPU_MAJOR, MXNET_TPU_MINOR, MXNET_TPU_PATCH)
    return MXNET_TPU_MAJOR * 10000 + MXNET_TPU_MINOR * 100 + MXNET_TPU_PATCH


@_capi
def MXRandomSeed(seed):
    _random.seed(seed)


@_capi
def MXNotifyShutdown():
    from . import engine
    engine.wait_all()


# -- NDArray ---------------------------------------------------------------

@_capi
def MXNDArrayCreate(shape, dev_type, dev_id, delay_alloc=0, dtype="float32"):
    from .context import Context
    ctx = Context(Context.devtype2str[dev_type], dev_id)
    return _new_handle(nd.zeros(tuple(shape), ctx=ctx, dtype=dtype))


@_capi
def MXNDArrayCreateFromNumpy(arr):
    return _new_handle(nd.array(np.asarray(arr)))


@_capi
def MXNDArrayFree(handle):
    _free(handle)


@_capi
def MXNDArrayGetShape(handle):
    return _get(handle).shape


@_capi
def MXNDArrayGetDType(handle):
    return str(_get(handle).dtype)


@_capi
def MXNDArrayGetContext(handle):
    ctx = _get(handle).context
    return (ctx.device_typeid, ctx.device_id)


@_capi
def MXNDArraySyncCopyToCPU(handle):
    return _get(handle).asnumpy()


@_capi
def MXNDArraySyncCopyFromCPU(handle, arr):
    _get(handle)[:] = np.asarray(arr)


@_capi
def MXNDArrayWaitToRead(handle):
    _get(handle).wait_to_read()


@_capi
def MXNDArrayWaitAll():
    nd.waitall()


@_capi
def MXNDArraySlice(handle, begin, end):
    return _new_handle(_get(handle)[begin:end])


@_capi
def MXNDArrayReshape(handle, shape):
    return _new_handle(_get(handle).reshape(tuple(shape)))


@_capi
def MXNDArraySave(fname, handles, keys=None):
    arrays = [_get(h) for h in handles]
    if keys:
        nd.save(fname, dict(zip(keys, arrays)))
    else:
        nd.save(fname, arrays)


@_capi
def MXNDArrayLoad(fname):
    data = nd.load(fname)
    if isinstance(data, dict):
        keys = list(data.keys())
        return [_new_handle(data[k]) for k in keys], keys
    return [_new_handle(a) for a in data], []


# -- operator invocation ----------------------------------------------------

@_capi
def MXListAllOpNames():
    from .ops import list_ops
    return list_ops()


@_capi
def MXImperativeInvoke(op_name, input_handles, attrs):
    from .ops import get as get_op
    from .ndarray import invoke
    opdef = get_op(op_name)
    inputs = [_get(h) for h in input_handles]
    out = invoke(opdef, inputs, dict(attrs or {}))
    outs = out if isinstance(out, list) else [out]
    return [_new_handle(o) for o in outs]


@_capi
def MXImperativeInvokeInPlace(op_name, input_handles, attrs,
                              output_handles):
    """The ``*outputs != NULL`` half of the reference MXImperativeInvoke
    contract (ref: src/c_api/c_api_ndarray.cc:322): results are written IN
    PLACE into the caller's existing NDArray handles (``out=`` semantics)
    — the handles keep identifying the same NDArrays, whose storage is
    updated. A count mismatch fails loudly instead of truncating."""
    from .ops import get as get_op
    from .ndarray import invoke
    opdef = get_op(op_name)
    inputs = [_get(h) for h in input_handles]
    targets = [_get(h) for h in output_handles]
    # invoke()'s out= path validates count/shape/dtype BEFORE any write
    # (fails loudly instead of reshaping/casting the caller's buffers) and
    # records the targets themselves with autograd — a manual copy of the
    # results here would leave the out handles off the recorded graph
    invoke(opdef, inputs, dict(attrs or {}), out=targets)
    return len(targets)


# -- Symbol ----------------------------------------------------------------

@_capi
def MXSymbolCreateVariable(name):
    return _new_handle(sym.Variable(name))


@_capi
def MXSymbolCreateAtomicSymbol(op_name, keys, vals):
    attrs = dict(zip(keys, vals))
    name = attrs.pop("name", None)
    return _new_handle((op_name, attrs, name))  # composed at MXSymbolCompose


@_capi
def MXSymbolCompose(handle, name, arg_handles, arg_keys=None):
    spec = _get(handle)
    if isinstance(spec, tuple):
        op_name, attrs, aname = spec
        args = [_get(h) for h in arg_handles]
        if arg_keys:
            kwargs = dict(zip(arg_keys, args))
            kwargs.update(attrs)
            result = getattr(sym, op_name)(name=name or aname, **kwargs)
        else:
            result = getattr(sym, op_name)(*args, name=name or aname, **attrs)
        _handles[handle] = result
        return handle
    raise MXNetError("MXSymbolCompose: handle is already composed")


@_capi
def MXSymbolCreateFromJSON(json_str):
    return _new_handle(sym.load_json(json_str))


@_capi
def MXSymbolSaveToJSON(handle):
    return _get(handle).tojson()


@_capi
def MXSymbolListArguments(handle):
    return _get(handle).list_arguments()


@_capi
def MXSymbolListOutputs(handle):
    return _get(handle).list_outputs()


@_capi
def MXSymbolListAuxiliaryStates(handle):
    return _get(handle).list_auxiliary_states()


@_capi
def MXSymbolInferShape(handle, keys, shapes):
    s = _get(handle)
    arg_shapes, out_shapes, aux_shapes = s.infer_shape(
        **dict(zip(keys, shapes)))
    return arg_shapes, out_shapes, aux_shapes


@_capi
def MXSymbolGetInternals(handle):
    return _new_handle(_get(handle).get_internals())


@_capi
def MXSymbolFree(handle):
    _free(handle)


# -- Executor --------------------------------------------------------------

@_capi
def MXExecutorBind(sym_handle, dev_type, dev_id, arg_handles,
                   grad_handles=None, grad_reqs="write", aux_handles=None):
    from .context import Context
    ctx = Context(Context.devtype2str[dev_type], dev_id)
    s = _get(sym_handle)
    args = [_get(h) for h in arg_handles]
    grads = [_get(h) if h else None for h in (grad_handles or [])] or None
    auxs = [_get(h) for h in (aux_handles or [])] or None
    ex = s.bind(ctx, args, grads, grad_reqs, auxs)
    return _new_handle(ex)


@_capi
def MXExecutorForward(handle, is_train):
    _get(handle).forward(is_train=bool(is_train))


@_capi
def MXExecutorBackward(handle, out_grad_handles=None):
    grads = ([_get(h) for h in out_grad_handles]
             if out_grad_handles else None)
    _get(handle).backward(grads)


@_capi
def MXExecutorOutputs(handle):
    return [_new_handle(o) for o in _get(handle).outputs]


@_capi
def MXExecutorFree(handle):
    _free(handle)


# -- KVStore ---------------------------------------------------------------

@_capi
def MXKVStoreCreate(kv_type):
    return _new_handle(kvs.create(kv_type))


@_capi
def MXKVStoreInit(handle, keys, value_handles):
    _get(handle).init(list(keys), [_get(h) for h in value_handles])


@_capi
def MXKVStorePush(handle, keys, value_handles, priority=0):
    _get(handle).push(list(keys), [_get(h) for h in value_handles],
                      priority=priority)


@_capi
def MXKVStorePull(handle, keys, out_handles, priority=0):
    _get(handle).pull(list(keys), out=[_get(h) for h in out_handles],
                      priority=priority)


@_capi
def MXKVStoreGetRank(handle):
    return _get(handle).rank


@_capi
def MXKVStoreGetGroupSize(handle):
    return _get(handle).num_workers


@_capi
def MXKVStoreBarrier(handle):
    _get(handle).barrier()


@_capi
def MXKVStoreFree(handle):
    _free(handle)


@_capi
def MXKVStoreGetNumDeadNode(handle, node_id, timeout_sec=60):
    return _get(handle).num_dead_node(node_id, timeout_sec)


# ---------------------------------------------------------------------------
# byte-level marshalling helpers for the compiled shim (src/capi/): the C
# side traffics raw buffers; dtype framing happens here
# ---------------------------------------------------------------------------
@_capi
def MXNDArraySyncCopyFromBytes(handle, buf, dtype="float32"):
    a = _get(handle)
    a[:] = np.frombuffer(buf, np.dtype(dtype)).reshape(a.shape)


@_capi
def MXNDArraySyncCopyToBytes(handle):
    return np.ascontiguousarray(_get(handle).asnumpy()).tobytes()


@_capi
def MXNDArraySize(handle):
    return int(_get(handle).size)


# ---------------------------------------------------------------------------
# C predict API (ref: include/mxnet/c_predict_api.h, src/c_api/
# c_predict_api.cc — the deploy/amalgamation surface) over Predictor
# ---------------------------------------------------------------------------
def _pred_create(symbol_json, param_bytes, dev_type, dev_id, input_keys,
                 input_shapes, output_names=None):
    from . import dmlc_serial
    from .predictor import Predictor
    from .context import Context
    ctx = Context(Context.devtype2str[dev_type], dev_id)
    if param_bytes:
        arrs, names = dmlc_serial.loads(bytes(param_bytes))
        params = {n: NDArray(np.asarray(a)) for n, a in zip(names, arrs)}
    else:
        params = {}
    shapes = {k: tuple(int(d) for d in s)
              for k, s in zip(input_keys, input_shapes)}
    # legacy contract: a NULL/empty param blob means "uninitialized
    # predictor" (zero weights) — keep it; a NON-empty blob with missing
    # keys is a broken deploy and raises (predictor.check_missing_params)
    pred = Predictor(symbol_json, params, shapes, ctx=ctx,
                     output_names=output_names,
                     allow_missing=not param_bytes)
    pred._pending = {}
    return _new_handle(pred)


@_capi
def MXPredCreate(symbol_json, param_bytes, dev_type, dev_id,
                 input_keys, input_shapes):
    return _pred_create(symbol_json, param_bytes, dev_type, dev_id,
                        input_keys, input_shapes)


@_capi
def MXPredCreatePartialOut(symbol_json, param_bytes, dev_type, dev_id,
                           input_keys, input_shapes, output_keys):
    """Predictor over selected output heads (ref: MXPredCreatePartialOut,
    c_predict_api.h:92-102)."""
    return _pred_create(symbol_json, param_bytes, dev_type, dev_id,
                        input_keys, input_shapes,
                        output_names=list(output_keys))


@_capi
def MXPredReshape(handle, input_keys, input_shapes):
    """Rebind an existing predictor for new input shapes; returns a NEW
    predictor handle sharing the loaded weights (the reference's
    MXPredReshape contract: old handle stays valid)."""
    import copy as _copy
    pred = _get(handle)
    new = _copy.copy(pred)     # shares symbol/params; gets its own executor
    from collections import OrderedDict as _OD
    new._exec_cache = _OD()    # executors are NOT shared across handles:
    #                            two handles at one shape must keep their
    #                            own input placeholders (set-input isolation)
    shapes = {k: tuple(int(d) for d in s)
              for k, s in zip(input_keys, input_shapes)}
    new.reshape(shapes)
    new._pending = {}
    return _new_handle(new)


@_capi
def MXPredSetInput(handle, key, buf, dtype="float32"):
    pred = _get(handle)
    shape = None
    for name in pred._input_names:
        if name == key:
            shape = pred._executor.arg_dict[name].shape
    if shape is None:
        raise MXNetError("MXPredSetInput: unknown input %r" % key)
    pred._pending[key] = np.frombuffer(buf, np.dtype(dtype)).reshape(shape)


@_capi
def MXPredForward(handle):
    pred = _get(handle)
    pred.forward(**pred._pending)


@_capi
def MXPredGetOutputShape(handle, index):
    return tuple(int(d) for d in _get(handle).outputs[index].shape)


@_capi
def MXPredGetOutput(handle, index):
    out = _get(handle).outputs[index]
    return np.ascontiguousarray(out.asnumpy(), np.float32).tobytes()


@_capi
def MXPredFree(handle):
    _free(handle)


# ---------------------------------------------------------------------------
# r5 completion: the remaining c_api.h families so the ABI reaches binding-
# generation completeness (ref: include/mxnet/c_api.h; VERDICT r4 item 2)
# ---------------------------------------------------------------------------

# -- NDArray (remaining) ----------------------------------------------------

@_capi
def MXNDArrayCreateNone():
    """Placeholder array (ref: MXNDArrayCreateNone, c_api.cc) — delayed
    alloc collapses on this substrate; an empty f32 scalar stands in."""
    return _new_handle(nd.zeros((1,)))


@_capi
def MXNDArrayCreateEx(shape, dev_type, dev_id, delay_alloc, dtype_id):
    from .context import Context
    ctx = Context(Context.devtype2str[dev_type], dev_id)
    return _new_handle(nd.zeros(tuple(shape), ctx=ctx,
                                dtype=_DTYPE_ID2NAME[int(dtype_id)]))


@_capi
def MXNDArrayAt(handle, idx):
    return _new_handle(_get(handle)[int(idx)])


@_capi
def MXNDArrayGetData(handle):
    """Raw bytes of the array (the compiled shim hands out a pointer into
    its per-call buffer; true zero-copy device pointers have no meaning
    through the tunnel)."""
    return np.ascontiguousarray(_get(handle).asnumpy()).tobytes()


@_capi
def MXNDArraySaveRawBytes(handle):
    from . import dmlc_serial
    a = _get(handle)
    return dmlc_serial.dumps([a.asnumpy()], [""])


@_capi
def MXNDArrayLoadFromRawBytes(buf):
    from . import dmlc_serial
    arrs, _names = dmlc_serial.loads(bytes(buf))
    return _new_handle(NDArray(np.asarray(arrs[0])))


@_capi
def MXNDArrayWaitToWrite(handle):
    _get(handle).wait_to_read()  # functional arrays: read-ready == write-ready


_DTYPE_ID2NAME = {0: "float32", 1: "float64", 2: "float16", 3: "uint8",
                  4: "int32", 5: "int8", 6: "int64", 12: "bfloat16"}


# -- Function registry (legacy imperative surface; ref: c_api.cc:396-422,
#    NDArrayFunctionReg). Functions ARE ops here; a function handle is an
#    index into the sorted op list. ----------------------------------------

def _op_names_sorted():
    from .ops import list_ops
    return list_ops()


@_capi
def MXListFunctions():
    return list(range(len(_op_names_sorted())))


@_capi
def MXGetFunction(name):
    names = _op_names_sorted()
    try:
        return names.index(name)
    except ValueError:
        raise MXNetError("function %r not found" % name)


def _op_by_index(fh):
    from .ops import get as get_op
    names = _op_names_sorted()
    if not 0 <= int(fh) < len(names):
        raise MXNetError("invalid function handle %r" % fh)
    return get_op(names[int(fh)])


def _safe_arity(op):
    try:
        return op.list_inputs({}), op.num_outputs({})
    except MXNetError:  # arity depends on attrs (e.g. Custom)
        return ["data"], 1


@_capi
def MXFuncGetInfo(fh):
    op = _op_by_index(int(fh))
    ins, _ = _safe_arity(op)
    return (op.name, op.description or op.name, len(ins), list(ins),
            ["NDArray"] * len(ins), [""] * len(ins))


@_capi
def MXFuncDescribe(fh):
    op = _op_by_index(int(fh))
    ins, n_out = _safe_arity(op)
    # the *_scalar op family consumes one float via the 'scalar' attr
    # (ref: elemwise_binary_scalar_op.h); everything else takes attrs only
    n_scalar = 1 if op.name.endswith("_scalar") else 0
    return (len(ins), n_scalar, n_out, 0)  # use, scalars, mutate, type_mask


@_capi
def MXFuncInvoke(fh, use_var_handles, scalars, mutate_var_handles):
    return _func_invoke(int(fh), use_var_handles, scalars,
                        mutate_var_handles, {})


@_capi
def MXFuncInvokeEx(fh, use_var_handles, scalars, mutate_var_handles,
                   keys, vals):
    return _func_invoke(int(fh), use_var_handles, scalars,
                        mutate_var_handles, dict(zip(keys, vals)))


def _func_invoke(fh, use_vars, scalars, mutate_vars, attrs):
    from .ndarray import invoke
    op = _op_by_index(fh)
    inputs = [_get(h) for h in use_vars]
    if scalars:  # scalar args ride the attr dict (ops parse strings)
        attrs = dict(attrs)
        attrs.setdefault("scalar", str(scalars[0]))
    out = invoke(op, inputs, attrs)
    outs = out if isinstance(out, list) else [out]
    for h, o in zip(mutate_vars, outs):
        _get(h)[:] = o.asnumpy()


# -- Symbol (remaining) -----------------------------------------------------

@_capi
def MXSymbolCopy(handle):
    import copy as _copy
    return _new_handle(_copy.deepcopy(_get(handle)))


@_capi
def MXSymbolCreateFromFile(fname):
    return _new_handle(sym.load(fname))


@_capi
def MXSymbolCreateGroup(handles):
    return _new_handle(sym.Group([_get(h) for h in handles]))


@_capi
def MXSymbolGetName(handle):
    return _get(handle).name or ""


@_capi
def MXSymbolGetAttr(handle, key):
    v = _get(handle).attr(key)
    return ("", 0) if v is None else (str(v), 1)


@_capi
def MXSymbolSetAttr(handle, key, value):
    _get(handle)._set_attr(**{key: value})


@_capi
def MXSymbolListAttr(handle):
    """Recursive attr list as flat [k0, v0, k1, v1, ...] with
    ``node_name$key`` keys (ref: MXSymbolListAttr, c_api_symbolic.cc)."""
    flat = []
    for node_name, attrs in _get(handle).attr_dict().items():
        for k, v in attrs.items():
            flat += ["%s$%s" % (node_name, k), str(v)]
    return flat


@_capi
def MXSymbolListAttrShallow(handle):
    flat = []
    for k, v in (_get(handle).list_attr() or {}).items():
        flat += [str(k), str(v)]
    return flat


@_capi
def MXSymbolGetChildren(handle):
    return _new_handle(_get(handle).get_children())


@_capi
def MXSymbolGetOutput(handle, index):
    return _new_handle(_get(handle)[int(index)])


@_capi
def MXSymbolGrad(handle, wrt):
    # reference parity: v0.9.5's own MXSymbolGrad is LOG(FATAL)
    # "not implemented" (src/c_api/c_api_symbolic.cc:545-549)
    raise MXNetError("MXSymbolGrad is not implemented (matches reference "
                     "v0.9.5); bind with args_grad instead")


@_capi
def MXSymbolInferShapePartial(handle, keys, shapes):
    return _get(handle).infer_shape_partial(**dict(zip(keys, shapes)))


@_capi
def MXSymbolInferType(handle, keys, dtypes):
    arg_t, out_t, aux_t = _get(handle).infer_type(**dict(zip(keys, dtypes)))
    tostr = lambda ts: [None if t is None else np.dtype(t).name for t in ts]
    return tostr(arg_t), tostr(out_t), tostr(aux_t)


@_capi
def MXSymbolPrint(handle):
    s = _get(handle)
    lines = ["Symbol Outputs:"]
    for o in s.list_outputs():
        lines.append("\toutput[%d]=%s" % (len(lines) - 1, o))
    for a in s.list_arguments():
        lines.append("Variable:%s" % a)
    return "\n".join(lines)


@_capi
def MXSymbolSaveToFile(handle, fname):
    _get(handle).save(fname)


# -- Op introspection: what every reference binding autogenerates its
#    wrappers from (ref: MXSymbolListAtomicSymbolCreators +
#    MXSymbolGetAtomicSymbolInfo, consumed by OpWrapperGenerator.py) -------

@_capi
def MXSymbolListAtomicSymbolCreators():
    return list(range(len(_op_names_sorted())))


@_capi
def MXSymbolGetAtomicSymbolName(creator):
    return _op_names_sorted()[int(creator)]


@_capi
def MXSymbolGetAtomicSymbolInfo(creator):
    """(name, description, num_args, arg_names, arg_types, arg_descriptions,
    key_var_num_args, return_type). Tensor inputs are typed
    'NDArray-or-Symbol' exactly as the reference documents them; free-form
    attr params carry type 'string (optional)'."""
    op = _op_by_index(int(creator))
    # a creator handle names the REGISTERED entry (alias or canonical),
    # exactly like nnvm's per-alias Op entries
    reg_name = _op_names_sorted()[int(creator)]
    try:
        ins = op.list_inputs({})
    except MXNetError:
        # arity depends on attrs (e.g. Custom needs op_type): variadic
        ins = ["data"]
    names = list(ins)
    types = ["NDArray-or-Symbol"] * len(ins)
    descs = ["input: %s" % n for n in ins]
    kv = op.var_inputs_attr or ""
    return (reg_name, op.description or op.name, len(names), names, types,
            descs, kv, "NDArray-or-Symbol")


# -- Autograd (ref: MXAutograd*, c_api_ndarray.cc; python
#    contrib/autograd.py) ---------------------------------------------------

@_capi
def MXAutogradSetIsTraining(is_training):
    from . import autograd as ag
    prev = ag.is_recording()
    st = ag._st()
    st.recording = bool(is_training)
    st.training = bool(is_training)
    return 1 if prev else 0


@_capi
def MXAutogradMarkVariables(var_handles, grad_handles, grad_reqs=None):
    from . import autograd as ag
    ag.mark_variables([_get(h) for h in var_handles],
                      [_get(h) for h in grad_handles],
                      grad_reqs or "write")


@_capi
def MXAutogradComputeGradient(output_handles):
    from . import autograd as ag
    ag.compute_gradient([_get(h) for h in output_handles])


# -- DataIter (ref: MXDataIter family, c_api.cc ~708-788; creators
#    registered via MXNET_REGISTER_IO_ITER) --------------------------------

def _iter_creators():
    from . import io as mxio
    from . import image as mximg
    # the reference registers exactly the file-fed iterators at C level
    # (MXNET_REGISTER_IO_ITER in src/io/*.cc); NDArrayIter is python-only
    # there too
    return [
        ("MNISTIter", mxio.MNISTIter, "MNIST data iterator"),
        ("CSVIter", mxio.CSVIter, "CSV file iterator"),
        ("ImageRecordIter", mximg.ImageRecordIter,
         "RecordIO image iterator with decode+augment pipeline"),
        ("ImageDetIter", mximg.ImageDetIter,
         "RecordIO detection iterator (object-detection labels)"),
    ]


@_capi
def MXListDataIters():
    return list(range(len(_iter_creators())))


@_capi
def MXDataIterGetIterInfo(creator):
    import inspect
    name, cls, desc = _iter_creators()[int(creator)]
    try:
        params = [p for p in inspect.signature(cls).parameters
                  if p not in ("self", "kwargs")]
    except (TypeError, ValueError):
        params = []
    return (name, desc, len(params), params,
            ["string (optional)"] * len(params), [""] * len(params))


def _parse_param(v):
    """Iterator params arrive as strings over the C ABI; recover python
    values ('32'->int, '(3,28,28)'->tuple, 'True'->bool, paths stay str)."""
    import ast
    s = str(v)
    try:
        return ast.literal_eval(s)
    except (ValueError, SyntaxError):
        return s


class _CIter(object):
    __slots__ = ("it", "batch")

    def __init__(self, it):
        self.it = it
        self.batch = None


@_capi
def MXDataIterCreateIter(creator, keys, vals):
    _name, cls, _desc = _iter_creators()[int(creator)]
    kwargs = {k: _parse_param(v) for k, v in zip(keys, vals)}
    return _new_handle(_CIter(cls(**kwargs)))


@_capi
def MXDataIterFree(handle):
    _free(handle)


@_capi
def MXDataIterNext(handle):
    ci = _get(handle)
    try:
        ci.batch = next(ci.it)
        return 1
    except StopIteration:
        ci.batch = None
        return 0


@_capi
def MXDataIterBeforeFirst(handle):
    ci = _get(handle)
    ci.it.reset()
    ci.batch = None


def _cur_batch(handle):
    ci = _get(handle)
    if ci.batch is None:
        raise MXNetError("DataIter: no current batch (call MXDataIterNext)")
    return ci.batch


@_capi
def MXDataIterGetData(handle):
    return _new_handle(_cur_batch(handle).data[0])


@_capi
def MXDataIterGetLabel(handle):
    return _new_handle(_cur_batch(handle).label[0])


@_capi
def MXDataIterGetIndex(handle):
    idx = getattr(_cur_batch(handle), "index", None)
    return [] if idx is None else [int(i) for i in idx]


@_capi
def MXDataIterGetPadNum(handle):
    return int(getattr(_cur_batch(handle), "pad", 0) or 0)


# -- RecordIO (ref: MXRecordIO* in c_api.cc over dmlc recordio) ------------

@_capi
def MXRecordIOWriterCreate(uri):
    from .recordio import MXRecordIO
    return _new_handle(MXRecordIO(uri, "w"))


@_capi
def MXRecordIOWriterFree(handle):
    _get(handle).close()
    _free(handle)


@_capi
def MXRecordIOWriterWriteRecord(handle, buf):
    _get(handle).write(bytes(buf))


@_capi
def MXRecordIOWriterTell(handle):
    return int(_get(handle).tell())


@_capi
def MXRecordIOReaderCreate(uri):
    from .recordio import MXRecordIO
    return _new_handle(MXRecordIO(uri, "r"))


@_capi
def MXRecordIOReaderFree(handle):
    _get(handle).close()
    _free(handle)


@_capi
def MXRecordIOReaderReadRecord(handle):
    rec = _get(handle).read()
    return b"" if rec is None else bytes(rec)


@_capi
def MXRecordIOReaderSeek(handle, pos):
    r = _get(handle)
    r.handle.seek(int(pos))


# -- Rtc: runtime user kernels. The reference JIT-compiles CUDA source via
#    NVRTC (src/common/mxrtc.cc); the TPU-native analog JIT-traces a
#    user-supplied Pallas/JAX kernel body supplied as source text. ---------

@_capi
def MXRtcCreate(name, input_names, output_names, input_handles,
                output_handles, kernel_src):
    from .rtc import PallasKernel
    ns = {}
    exec(compile(kernel_src, "<mxrtc:%s>" % name, "exec"), ns)  # noqa: S102
    if name not in ns or not callable(ns[name]):
        raise MXNetError("MXRtcCreate: kernel source must define a callable "
                         "named %r" % name)
    kern = PallasKernel(ns[name], out_like=0)
    return _new_handle({"kernel": kern, "inputs": list(input_names),
                        "outputs": list(output_names)})


@_capi
def MXRtcPush(handle, input_handles, output_handles,
              gridx=1, gridy=1, gridz=1, blockx=1, blocky=1, blockz=1):
    ent = _get(handle)
    outs = ent["kernel"](*[_get(h) for h in input_handles])
    if not isinstance(outs, (list, tuple)):
        outs = [outs]
    for h, o in zip(output_handles, outs):
        _get(h)[:] = o.asnumpy()


@_capi
def MXRtcFree(handle):
    _free(handle)


# -- Profiler (ref: MXSetProfilerConfig/State, MXDumpProfile) --------------

@_capi
def MXSetProfilerConfig(mode, filename):
    from . import profiler
    profiler.profiler_set_config(
        mode if isinstance(mode, str) else ("all" if mode else "symbolic"),
        filename)


@_capi
def MXSetProfilerState(state):
    from . import profiler
    profiler.profiler_set_state(
        state if isinstance(state, str) else ("run" if state else "stop"))


@_capi
def MXDumpProfile():
    from . import profiler
    profiler.dump_profile()


# -- Executor (remaining) ---------------------------------------------------

def _bind_with(sym_handle, dev_type, dev_id, g2c_keys, g2c_dev_types,
               g2c_dev_ids, arg_handles, grad_handles, grad_reqs,
               aux_handles, shared_exec_handle=None):
    from .context import Context
    ctx = Context(Context.devtype2str[dev_type], dev_id)
    s = _get(sym_handle)
    group2ctx = {k: Context(Context.devtype2str[t], i)
                 for k, t, i in zip(g2c_keys or [], g2c_dev_types or [],
                                    g2c_dev_ids or [])} or None
    args = [_get(h) for h in arg_handles]
    grads = [_get(h) if h else None for h in (grad_handles or [])] or None
    auxs = [_get(h) for h in (aux_handles or [])] or None
    reqs = grad_reqs if isinstance(grad_reqs, str) else list(grad_reqs)
    shared = _get(shared_exec_handle) if shared_exec_handle else None
    ex = Executor(s, ctx, args, grads, reqs, auxs, group2ctx=group2ctx,
                  shared_exec=shared)
    return _new_handle(ex)


@_capi
def MXExecutorBindX(sym_handle, dev_type, dev_id, g2c_keys, g2c_dev_types,
                    g2c_dev_ids, arg_handles, grad_handles=None,
                    grad_reqs="write", aux_handles=None):
    return _bind_with(sym_handle, dev_type, dev_id, g2c_keys, g2c_dev_types,
                      g2c_dev_ids, arg_handles, grad_handles, grad_reqs,
                      aux_handles)


@_capi
def MXExecutorBindEX(sym_handle, dev_type, dev_id, g2c_keys, g2c_dev_types,
                     g2c_dev_ids, arg_handles, grad_handles=None,
                     grad_reqs="write", aux_handles=None,
                     shared_exec_handle=None):
    return _bind_with(sym_handle, dev_type, dev_id, g2c_keys, g2c_dev_types,
                      g2c_dev_ids, arg_handles, grad_handles, grad_reqs,
                      aux_handles, shared_exec_handle)


@_capi
def MXExecutorPrint(handle):
    ex = _get(handle)
    lines = ["Executor over symbol %r" % (ex._symbol.name,)]
    for n, a in ex.arg_dict.items():
        lines.append("arg %s: shape %s dtype %s" % (n, a.shape, a.dtype))
    return "\n".join(lines)


def _wrap_c_callback(addr, argspec):
    """Wrap a raw C function pointer (passed as an integer address by the
    compiled shim) into a python callable via ctypes."""
    import ctypes
    return ctypes.CFUNCTYPE(None, *argspec)(addr)


@_capi
def MXExecutorSetMonitorCallback(handle, callback_addr, closure_addr=0):
    """callback: void (*)(const char* name, NDArrayHandle out, void*).
    Called with every op output during monitored forwards (ref:
    ExecutorMonitorCallback, c_api.h:68-70;
    GraphExecutor::SetMonitorCallback, graph_executor.cc:72)."""
    import ctypes
    cfn = _wrap_c_callback(int(callback_addr),
                           (ctypes.c_char_p, ctypes.c_uint64,
                            ctypes.c_void_p))
    closure = int(closure_addr or 0)

    def py_cb(name, arr):
        # handle valid for the duration of the callback only (the reference
        # engine owns its NDArrays across the callback the same way)
        h = _new_handle(arr if isinstance(arr, NDArray) else NDArray(arr))
        try:
            cfn(str(name).encode(), h, closure)
        finally:
            _free(h)
    _get(handle).set_monitor_callback(py_cb)


# -- KVStore (remaining) ----------------------------------------------------

@_capi
def MXKVStoreGetType(handle):
    return _get(handle).type


@_capi
def MXKVStoreIsWorkerNode():
    import os
    return 1 if os.environ.get("DMLC_ROLE", "worker") == "worker" else 0


@_capi
def MXKVStoreIsServerNode():
    import os
    return 1 if os.environ.get("DMLC_ROLE", "worker") == "server" else 0


@_capi
def MXKVStoreIsSchedulerNode():
    import os
    return 1 if os.environ.get("DMLC_ROLE", "worker") == "scheduler" else 0


@_capi
def MXKVStoreRunServer(handle, controller_addr=None):
    """Server role collapses on this substrate (SURVEY §2.4: psum replaces
    ps-lite); the entry blocks until the worker group's rendezvous ends —
    here that is a no-op returning immediately, matching kvstore_server's
    thin-by-design role."""
    from . import kvstore_server
    kvstore_server._init_distributed()


@_capi
def MXKVStoreSendCommmandToServers(handle, cmd_id, cmd_body):
    kv = _get(handle)
    if int(cmd_id) == 0:  # kController optimizer install (ref: kvstore.py:226)
        import pickle
        body = bytes(cmd_body) if not isinstance(cmd_body, str) \
            else cmd_body.encode("latin-1")
        try:
            optzr = pickle.loads(body)
        except Exception as e:
            # a body that fails to unpickle means the server would train
            # with the WRONG optimizer — surface it, never swallow it
            # (the truncation bug this catches: NUL-terminated marshalling
            # of a binary pickle; use MXKVStoreSendCommmandToServersEx)
            raise MXNetError(
                "kvstore command 0 (set optimizer): body of %d bytes "
                "failed to unpickle (%s: %s); binary bodies must be sent "
                "length-explicit" % (len(body), type(e).__name__, e))
        kv.set_optimizer(optzr)
    # other commands (kSetMultiPrecision etc.) have no role here


@_capi
def MXKVStoreSetBarrierBeforeExit(handle, do_barrier):
    setattr(_get(handle), "_barrier_before_exit", bool(do_barrier))


@_capi
def MXKVStoreSetUpdater(handle, updater_addr, closure_addr=0):
    """updater: void (*)(int key, NDArrayHandle recv, NDArrayHandle local,
    void*). The C callback is invoked with handles; mutations it makes to
    ``local`` through the ABI are the update (ref: MXKVStoreUpdater,
    c_api.h:1264-1277)."""
    import ctypes
    cfn = _wrap_c_callback(int(updater_addr),
                           (ctypes.c_int, ctypes.c_uint64, ctypes.c_uint64,
                            ctypes.c_void_p))
    closure = int(closure_addr or 0)

    def py_updater(key, recv, local):
        # handles are valid for the duration of the callback only
        hr, hl = _new_handle(recv), _new_handle(local)
        try:
            cfn(int(key), hr, hl, closure)
        finally:
            _free(hr)
            _free(hl)
    _get(handle)._set_updater(py_updater)


@_capi
def MXInitPSEnv(keys, vals):
    import os
    for k, v in zip(keys, vals):
        os.environ[str(k)] = str(v)


# -- CustomOp registration through the ABI (ref: MXCustomOpRegister,
#    src/operator/custom/custom.cc). The compiled shim passes the creator
#    as a raw fn pointer; python-side registrations use operator.register.

@_capi
def MXCustomOpRegister(op_type, creator_addr=None):
    if creator_addr is None:
        raise MXNetError(
            "MXCustomOpRegister from C requires a creator callback; "
            "python CustomOpProp classes register via "
            "mxnet_tpu.operator.register(%r)" % op_type)
    raise MXNetError(
        "C-struct CustomOp creators are not supported on this substrate; "
        "register a python CustomOpProp (mxnet_tpu.operator.register) — "
        "the compiled ABI can drive it via MXImperativeInvoke('Custom')")
