"""Flat C-API-shaped surface for language bindings.

The reference exposes 114 ``extern "C" MX*`` functions
(ref: include/mxnet/c_api.h, src/c_api/*.cc) that every binding (R/Scala/
Perl/C++/Matlab — SURVEY.md §2.7) consumes: opaque handles + flat functions
returning an int status, with ``MXGetLastError`` for messages.

This module reproduces that contract over the Python substrate: integer
handles into a registry, the same function names/argument orders, status-code
returns. It is directly usable via cffi's ``embedding`` or any FFI that can
call into CPython; a compiled ``libmxnet_tpu`` shim that exports these as
real C symbols (CPython C API) is the bindings-stage follow-up.

Only the error contract differs internally: exceptions are caught and stored
for MXGetLastError, exactly like c_api_common.h's error ring.
"""
from __future__ import annotations

import json
import threading

import numpy as np

from . import ndarray as nd
from . import symbol as sym
from . import kvstore as kvs
from . import random as _random
from .base import MXNetError
from .executor import Executor
from .ndarray import NDArray

_state = threading.local()
_handles = {}
_next_handle = [1]
_lock = threading.Lock()


def _new_handle(obj):
    with _lock:
        h = _next_handle[0]
        _next_handle[0] += 1
        _handles[h] = obj
    return h


def _get(h):
    return _handles[h]


def _free(h):
    _handles.pop(h, None)


def _capi(fn):
    """Wrap: return 0 on success, -1 + stored error on exception
    (ref: API_BEGIN/API_END macros, c_api_common.h)."""
    def wrapped(*args, **kwargs):
        try:
            return 0, fn(*args, **kwargs)
        except Exception as e:  # noqa: BLE001 - the C API flattens all errors
            _state.error = "%s: %s" % (type(e).__name__, e)
            return -1, None
    wrapped.__name__ = fn.__name__
    return wrapped


def MXGetLastError():
    return getattr(_state, "error", "")


@_capi
def MXGetVersion():
    from .base import (MXNET_TPU_MAJOR, MXNET_TPU_MINOR, MXNET_TPU_PATCH)
    return MXNET_TPU_MAJOR * 10000 + MXNET_TPU_MINOR * 100 + MXNET_TPU_PATCH


@_capi
def MXRandomSeed(seed):
    _random.seed(seed)


@_capi
def MXNotifyShutdown():
    from . import engine
    engine.wait_all()


# -- NDArray ---------------------------------------------------------------

@_capi
def MXNDArrayCreate(shape, dev_type, dev_id, delay_alloc=0, dtype="float32"):
    from .context import Context
    ctx = Context(Context.devtype2str[dev_type], dev_id)
    return _new_handle(nd.zeros(tuple(shape), ctx=ctx, dtype=dtype))


@_capi
def MXNDArrayCreateFromNumpy(arr):
    return _new_handle(nd.array(np.asarray(arr)))


@_capi
def MXNDArrayFree(handle):
    _free(handle)


@_capi
def MXNDArrayGetShape(handle):
    return _get(handle).shape


@_capi
def MXNDArrayGetDType(handle):
    return str(_get(handle).dtype)


@_capi
def MXNDArrayGetContext(handle):
    ctx = _get(handle).context
    return (ctx.device_typeid, ctx.device_id)


@_capi
def MXNDArraySyncCopyToCPU(handle):
    return _get(handle).asnumpy()


@_capi
def MXNDArraySyncCopyFromCPU(handle, arr):
    _get(handle)[:] = np.asarray(arr)


@_capi
def MXNDArrayWaitToRead(handle):
    _get(handle).wait_to_read()


@_capi
def MXNDArrayWaitAll():
    nd.waitall()


@_capi
def MXNDArraySlice(handle, begin, end):
    return _new_handle(_get(handle)[begin:end])


@_capi
def MXNDArrayReshape(handle, shape):
    return _new_handle(_get(handle).reshape(tuple(shape)))


@_capi
def MXNDArraySave(fname, handles, keys=None):
    arrays = [_get(h) for h in handles]
    if keys:
        nd.save(fname, dict(zip(keys, arrays)))
    else:
        nd.save(fname, arrays)


@_capi
def MXNDArrayLoad(fname):
    data = nd.load(fname)
    if isinstance(data, dict):
        keys = list(data.keys())
        return [_new_handle(data[k]) for k in keys], keys
    return [_new_handle(a) for a in data], []


# -- operator invocation ----------------------------------------------------

@_capi
def MXListAllOpNames():
    from .ops import list_ops
    return list_ops()


@_capi
def MXImperativeInvoke(op_name, input_handles, attrs):
    from .ops import get as get_op
    from .ndarray import invoke
    opdef = get_op(op_name)
    inputs = [_get(h) for h in input_handles]
    out = invoke(opdef, inputs, dict(attrs or {}))
    outs = out if isinstance(out, list) else [out]
    return [_new_handle(o) for o in outs]


# -- Symbol ----------------------------------------------------------------

@_capi
def MXSymbolCreateVariable(name):
    return _new_handle(sym.Variable(name))


@_capi
def MXSymbolCreateAtomicSymbol(op_name, keys, vals):
    attrs = dict(zip(keys, vals))
    name = attrs.pop("name", None)
    return _new_handle((op_name, attrs, name))  # composed at MXSymbolCompose


@_capi
def MXSymbolCompose(handle, name, arg_handles, arg_keys=None):
    spec = _get(handle)
    if isinstance(spec, tuple):
        op_name, attrs, aname = spec
        args = [_get(h) for h in arg_handles]
        if arg_keys:
            kwargs = dict(zip(arg_keys, args))
            kwargs.update(attrs)
            result = getattr(sym, op_name)(name=name or aname, **kwargs)
        else:
            result = getattr(sym, op_name)(*args, name=name or aname, **attrs)
        _handles[handle] = result
        return handle
    raise MXNetError("MXSymbolCompose: handle is already composed")


@_capi
def MXSymbolCreateFromJSON(json_str):
    return _new_handle(sym.load_json(json_str))


@_capi
def MXSymbolSaveToJSON(handle):
    return _get(handle).tojson()


@_capi
def MXSymbolListArguments(handle):
    return _get(handle).list_arguments()


@_capi
def MXSymbolListOutputs(handle):
    return _get(handle).list_outputs()


@_capi
def MXSymbolListAuxiliaryStates(handle):
    return _get(handle).list_auxiliary_states()


@_capi
def MXSymbolInferShape(handle, keys, shapes):
    s = _get(handle)
    arg_shapes, out_shapes, aux_shapes = s.infer_shape(
        **dict(zip(keys, shapes)))
    return arg_shapes, out_shapes, aux_shapes


@_capi
def MXSymbolGetInternals(handle):
    return _new_handle(_get(handle).get_internals())


@_capi
def MXSymbolFree(handle):
    _free(handle)


# -- Executor --------------------------------------------------------------

@_capi
def MXExecutorBind(sym_handle, dev_type, dev_id, arg_handles,
                   grad_handles=None, grad_reqs="write", aux_handles=None):
    from .context import Context
    ctx = Context(Context.devtype2str[dev_type], dev_id)
    s = _get(sym_handle)
    args = [_get(h) for h in arg_handles]
    grads = [_get(h) if h else None for h in (grad_handles or [])] or None
    auxs = [_get(h) for h in (aux_handles or [])] or None
    ex = s.bind(ctx, args, grads, grad_reqs, auxs)
    return _new_handle(ex)


@_capi
def MXExecutorForward(handle, is_train):
    _get(handle).forward(is_train=bool(is_train))


@_capi
def MXExecutorBackward(handle, out_grad_handles=None):
    grads = ([_get(h) for h in out_grad_handles]
             if out_grad_handles else None)
    _get(handle).backward(grads)


@_capi
def MXExecutorOutputs(handle):
    return [_new_handle(o) for o in _get(handle).outputs]


@_capi
def MXExecutorFree(handle):
    _free(handle)


# -- KVStore ---------------------------------------------------------------

@_capi
def MXKVStoreCreate(kv_type):
    return _new_handle(kvs.create(kv_type))


@_capi
def MXKVStoreInit(handle, keys, value_handles):
    _get(handle).init(list(keys), [_get(h) for h in value_handles])


@_capi
def MXKVStorePush(handle, keys, value_handles, priority=0):
    _get(handle).push(list(keys), [_get(h) for h in value_handles],
                      priority=priority)


@_capi
def MXKVStorePull(handle, keys, out_handles, priority=0):
    _get(handle).pull(list(keys), out=[_get(h) for h in out_handles],
                      priority=priority)


@_capi
def MXKVStoreGetRank(handle):
    return _get(handle).rank


@_capi
def MXKVStoreGetGroupSize(handle):
    return _get(handle).num_workers


@_capi
def MXKVStoreBarrier(handle):
    _get(handle).barrier()


@_capi
def MXKVStoreFree(handle):
    _free(handle)


@_capi
def MXKVStoreGetNumDeadNode(handle, node_id, timeout_sec=60):
    return _get(handle).num_dead_node(node_id, timeout_sec)


# ---------------------------------------------------------------------------
# byte-level marshalling helpers for the compiled shim (src/capi/): the C
# side traffics raw buffers; dtype framing happens here
# ---------------------------------------------------------------------------
@_capi
def MXNDArraySyncCopyFromBytes(handle, buf, dtype="float32"):
    a = _get(handle)
    a[:] = np.frombuffer(buf, np.dtype(dtype)).reshape(a.shape)


@_capi
def MXNDArraySyncCopyToBytes(handle):
    return np.ascontiguousarray(_get(handle).asnumpy()).tobytes()


@_capi
def MXNDArraySize(handle):
    return int(_get(handle).size)


# ---------------------------------------------------------------------------
# C predict API (ref: include/mxnet/c_predict_api.h, src/c_api/
# c_predict_api.cc — the deploy/amalgamation surface) over Predictor
# ---------------------------------------------------------------------------
@_capi
def MXPredCreate(symbol_json, param_bytes, dev_type, dev_id,
                 input_keys, input_shapes):
    from . import dmlc_serial
    from .predictor import Predictor
    from .context import Context
    ctx = Context(Context.devtype2str[dev_type], dev_id)
    if param_bytes:
        arrs, names = dmlc_serial.loads(bytes(param_bytes))
        params = {n: NDArray(np.asarray(a)) for n, a in zip(names, arrs)}
    else:
        params = {}
    shapes = {k: tuple(int(d) for d in s)
              for k, s in zip(input_keys, input_shapes)}
    pred = Predictor(symbol_json, params, shapes, ctx=ctx)
    pred._pending = {}
    return _new_handle(pred)


@_capi
def MXPredSetInput(handle, key, buf, dtype="float32"):
    pred = _get(handle)
    shape = None
    for name in pred._input_names:
        if name == key:
            shape = pred._executor.arg_dict[name].shape
    if shape is None:
        raise MXNetError("MXPredSetInput: unknown input %r" % key)
    pred._pending[key] = np.frombuffer(buf, np.dtype(dtype)).reshape(shape)


@_capi
def MXPredForward(handle):
    pred = _get(handle)
    pred.forward(**pred._pending)


@_capi
def MXPredGetOutputShape(handle, index):
    return tuple(int(d) for d in _get(handle).outputs[index].shape)


@_capi
def MXPredGetOutput(handle, index):
    out = _get(handle).outputs[index]
    return np.ascontiguousarray(out.asnumpy(), np.float32).tobytes()


@_capi
def MXPredFree(handle):
    _free(handle)
