"""flopcheck: a static per-kernel compute/memory roofline analyzer for
compiled programs.

The analyzer trilogy audits retraces (tracecheck), HBM footprint
(memcheck) and collective traffic (commscheck); this module completes
the suite with the resource none of them price: the compute itself.
ROADMAP item 3 wants a Pallas kernel tier "searched by the autotuner",
but a search loop needs a per-kernel cost signal before it measures
anything — TVM's whole premise (arXiv:1802.04799) — and MXNet's
original design treats the graph cost model as the substrate every
optimization pass stands on (arXiv:1512.01274). ``flopcheck`` is that
signal: it names WHICH fusions are worth a hand kernel, before any
profiler runs.

Like its siblings it compiles a program WITHOUT executing it (arguments
may be ``ShapeDtypeStruct``s) and walks the scheduled HLO — here
fusion-by-fusion into a per-program **kernel inventory**
(:class:`KernelEntry`): per-fusion FLOPs (structural estimates
normalized against ``compiled.cost_analysis()`` — "cost-analysis
apportioned", so the sum matches XLA's own count), HBM bytes moved
(operand + result shapes x memcheck's dtype-width table, alias-aware),
arithmetic intensity against the device ridge point
(``peak_flops / hbm_bandwidth`` from :mod:`mxnet_tpu.devspec`),
compute-bound/memory-bound classification, in-loop multipliers for
scan/while bodies, op path and source provenance. From the inventory:

* **predicted step time** — per kernel ``max(flops/peak, bytes/bw)``,
  summed with the in-loop multipliers and composed with commscheck's
  collective wire-time model (collective opcodes are EXCLUDED from the
  kernel inventory so their time is never double-counted);
* **predicted MFU** — dispatch FLOPs over ``predicted_time x peak``;
* a ranked **hotspot table** (``--hotspots``) — the Pallas tier's
  shopping list: the biggest memory-bound fusions are exactly the
  flash-attention/fused-optimizer candidates.

Four lints ride tracecheck's :class:`~mxnet_tpu.tracecheck.Finding`
framework and shared suppression registry
(``tracecheck.ROOFLINE_LINTS``):

====================  ====================================================
lint id               fires when
====================  ====================================================
``memory-bound-hot``  one fusion holds >= ``MXTPU_FLOPCHECK_HOT_FRAC``
                      of the predicted step time with arithmetic
                      intensity below the device ridge point (and moves
                      >= ``MXTPU_FLOPCHECK_HOT_BYTES``) — the
                      flash-attention / fused-optimizer signature: the
                      step is waiting on HBM, a hand kernel that keeps
                      the working set in VMEM wins
``layout-copy``       a transpose/copy/bitcast kernel (or a fusion of
                      nothing else) moves more than
                      ``MXTPU_FLOPCHECK_LAYOUT_BYTES`` per dispatch —
                      pure data motion, zero FLOPs: fix the layout that
                      forced it
``tiny-dispatch``     more than ``MXTPU_FLOPCHECK_TINY_COUNT`` kernel
                      executions per dispatch each predicted under
                      ``MXTPU_FLOPCHECK_TINY_US`` — the fusion-
                      regression signature: dispatch overhead dominates
                      compute
``predicted-mfu``     the program's predicted MFU is below
                      ``MXTPU_FLOPCHECK_MIN_MFU`` (default 0 =
                      disabled; arm it per-deploy for the flagship LM)
====================  ====================================================

The roofline is a MODEL, not a measurement: structural FLOP counts,
spec-sheet peak/bandwidth rows (:mod:`mxnet_tpu.devspec` — the SAME
table bench.py's MFU and commscheck's wire model read), zero overlap
assumed. bench.py emits ``predicted_mfu`` next to measured MFU and the
multichip gate records the prediction gap — a big gap is a note, never
a failure.

CLI::

    python -m mxnet_tpu.flopcheck --zoo                   # 32 programs
    python -m mxnet_tpu.flopcheck --zoo --sharded         # all 36
    python -m mxnet_tpu.flopcheck --models transformer --hotspots 10
    python -m mxnet_tpu.flopcheck --zoo --sharded \\
        --write-baseline FLOPCHECK_baseline.json

``--baseline`` is the CI drift gate (``ci/flopcheck.sh``): per-program
kernel count, predicted step time, predicted MFU and top-hotspot
identity against the committed ``FLOPCHECK_baseline.json`` with a
tolerance band (``MXTPU_FLOPCHECK_TOL``, default 10%) — a refactor that
shatters a fusion or bloats the predicted step time fails CI with the
kernel breakdown and source provenance, before any profiler runs. The
same absence-of-evidence discipline as commscheck: an unreadable HLO
fails the gate (and ``--write-baseline`` refuses it), never reads as an
improvement.

``--memcheck-baseline`` / ``--commscheck-baseline`` turn the run into
the COMBINED compile-once gate: one compile per program feeds all three
static analyzers (memcheck + commscheck + flopcheck), cutting CI
wall-clock by ~3x over three separate sweeps (the gate logs the compile
phase it shared). ``MXTPU_FLOPCHECK=warn|error`` arms a one-time
first-dispatch audit through the TrainStep registration hook.
"""
from __future__ import annotations

import json
import re

import numpy as np

from .base import MXNetError, env_float, env_int, env_str
from .tracecheck import (Finding, ROOFLINE_LINTS, _is_suppressed,
                         unsuppressed, ZOO)
# ONE HLO-metadata parser set across the analyzer suite: byte/shape
# helpers, the computation-header regex and the op_name/source
# provenance regexes all live in memcheck
from .memcheck import (_parse_bytes, _shape_bytes, _fmt_bytes, _unescape,
                       _COMP_RE, _OPNAME_RE, _SOURCE_RE, _VIEW_OPCODES)
# the collective inventory + wire-time model live in commscheck; the
# tuple-capable type pattern is shared so fusion results parse
from .commscheck import (COLLECTIVE_KINDS, CommsReport, _TYPE_PAT,
                         _infer_mesh, parse_collectives, struct_args)
from . import devspec

__all__ = [
    "KernelEntry", "RooflineReport", "parse_kernels", "analyze",
    "analyze_compiled", "lint_report", "check_program", "check_train_step",
    "check_zoo", "check_sharded", "compiled_zoo_programs",
    "compiled_sharded_programs", "hotspot_report", "write_baseline",
    "compare_baseline", "hot_frac", "hot_bytes", "layout_bytes",
    "layout_frac", "tiny_us",
    "tiny_count", "min_mfu", "tolerance", "maybe_audit_dispatch", "main",
    "ROOFLINE_LINTS",
]


# ---------------------------------------------------------------------------
# knobs
# ---------------------------------------------------------------------------

def hot_frac():
    """``memory-bound-hot`` step-time share threshold
    (``MXTPU_FLOPCHECK_HOT_FRAC``, default 0.6)."""
    return env_float("MXTPU_FLOPCHECK_HOT_FRAC", 0.6)


def hot_bytes():
    """``memory-bound-hot`` absolute traffic floor — a kernel must move
    this much per dispatch before its step-time share matters
    (``MXTPU_FLOPCHECK_HOT_BYTES``, K/M/G/T binary suffixes; default
    4 MiB — the zoo's deliberately tiny programs all have SOME dominant
    kernel, and flagging a 50 KiB matvec as a Pallas candidate would be
    noise)."""
    env = _parse_bytes(env_str("MXTPU_FLOPCHECK_HOT_BYTES"),
                       "MXTPU_FLOPCHECK_HOT_BYTES")
    return env if env is not None else (4 << 20)


def layout_bytes():
    """``layout-copy`` absolute per-dispatch traffic floor
    (``MXTPU_FLOPCHECK_LAYOUT_BYTES``, default 4 MiB) — a copy must move
    at least this much before its traffic SHARE (:func:`layout_frac`)
    matters; keeps KiB-scale relayouts in toy programs quiet."""
    env = _parse_bytes(env_str("MXTPU_FLOPCHECK_LAYOUT_BYTES"),
                       "MXTPU_FLOPCHECK_LAYOUT_BYTES")
    return env if env is not None else (4 << 20)


def layout_frac():
    """``layout-copy`` share-of-total-traffic threshold
    (``MXTPU_FLOPCHECK_LAYOUT_FRAC``, default 0.25): a pure-data-motion
    kernel only fires when it carries at least this fraction of the
    program's HBM bytes per dispatch. An absolute threshold alone cannot
    work — vgg legitimately re-lays-out ~1.5 GiB of stacked conv
    activations, a rounding error next to its conv traffic, while a
    transpose chain moving 10 MiB of a 12 MiB program IS the problem."""
    return env_float("MXTPU_FLOPCHECK_LAYOUT_FRAC", 0.25)


def tiny_us():
    """``tiny-dispatch`` per-kernel predicted-time floor in microseconds
    (``MXTPU_FLOPCHECK_TINY_US``, default 1.0)."""
    return env_float("MXTPU_FLOPCHECK_TINY_US", 1.0)


def tiny_count():
    """``tiny-dispatch`` kernel-execution count threshold per dispatch
    (``MXTPU_FLOPCHECK_TINY_COUNT``, default 4096 — above every zoo
    program including inception-bn's guarded K-step scan (~3.2k genuine
    small executions) and the nested ring-attention scans; a fusion
    regression that shatters the step blows past it)."""
    return env_int("MXTPU_FLOPCHECK_TINY_COUNT", 4096)


def min_mfu():
    """``predicted-mfu`` floor (``MXTPU_FLOPCHECK_MIN_MFU``, default 0.0
    = disabled — the zoo's tiny programs are memory-bound by
    construction; arm per-deploy for the flagship LM)."""
    return env_float("MXTPU_FLOPCHECK_MIN_MFU", 0.0)


def tolerance():
    """Baseline drift band (``MXTPU_FLOPCHECK_TOL``, default 0.1)."""
    return env_float("MXTPU_FLOPCHECK_TOL", 0.1)


# ---------------------------------------------------------------------------
# the scheduled-HLO kernel parser
# ---------------------------------------------------------------------------

# one instruction, tuple-typed results included (fusions returning
# several buffers, while carries) — commscheck's _TYPE_PAT
_KINSTR_RE = re.compile(
    r"^\s*(?:ROOT\s+)?%(?P<instr>[\w.\-]+)\s*=\s*"
    r"(?P<type>" + _TYPE_PAT + r")\s+"
    r"(?P<opcode>[\w\-]+)\((?P<rest>.*)$")
_SHAPE_RE = re.compile(r"([a-z][a-z0-9]*)\[([0-9,]*)\]")
# an operand's inline type: `f32[8,64]{1,0} %name` — anchored on the
# following %ref so shape-shaped noise elsewhere on the line never counts
_OPERAND_TYPE_RE = re.compile(
    r"([a-z][a-z0-9]*)\[([0-9,]*)\](?:\{[^}]*\})?\s+%")
_CALLS_RE = re.compile(r"calls=%(?P<callee>[\w.\-]+)")
_TO_APPLY_RE = re.compile(r"to_apply=%(?P<callee>[\w.\-]+)")
_BODY_RE = re.compile(r"body=%(?P<body>[\w.\-]+)")
_BRANCHES_RE = re.compile(
    r"(?:true_computation=%([\w.\-]+)|false_computation=%([\w.\-]+)"
    r"|branch_computations=\{([^}]*)\})")
_BRANCH_NAME_RE = re.compile(r"%([\w.\-]+)")
_CONTRACT_RE = re.compile(r"lhs_contracting_dims=\{([0-9,\s]*)\}")
_DIM_LABELS_RE = re.compile(r"dim_labels=([a-z0-9?]+)_([a-z0-9?]+)->")
_TRIP_RE = re.compile(r'"known_trip_count":\{"n":"(\d+)"\}')

#: opcodes that never become kernels: control flow (their bodies are
#: inventoried as their own execution contexts), data views, and the
#: collectives (priced by commscheck's wire model — counting them here
#: would double-bill the step time)
_NONKERNEL_OPCODES = frozenset(
    {"parameter", "constant", "while", "conditional", "call",
     "after-all", "add-dependency", "copy-start", "copy-done"}
    | set(_VIEW_OPCODES)
    | set(COLLECTIVE_KINDS)
    | {k + "-start" for k in COLLECTIVE_KINDS}
    | {k + "-done" for k in COLLECTIVE_KINDS})

#: pure data-motion opcodes: a kernel (or a fusion of nothing else) made
#: of these computes nothing — the ``layout-copy`` signature
_LAYOUT_OPCODES = frozenset({"copy", "transpose", "bitcast", "reshape"})

#: a while loop with more trips than this is an EXPANSION loop (the CPU
#: backend lowers select-and-scatter / pool backprop as scalar loops
#: with one trip per output element) — not a dispatch-per-trip scan
#: body. It is collapsed into ONE merged kernel (body totals x trips)
#: instead of multiplying the inventory into millions of "executions";
#: real K-step scans and ring schedules sit far below this
_EXPANSION_TRIPS = 64


def _dims(dims_str):
    return [int(d) for d in dims_str.split(",") if d.strip()]


def _type_elems(type_str):
    """Total element count of a (possibly tuple) HLO type string."""
    total = 0
    for _dt, dims in _SHAPE_RE.findall(type_str):
        n = 1
        for d in _dims(dims):
            n *= d
        total += n
    return total


def _type_bytes(type_str):
    return sum(_shape_bytes(dt, dims)
               for dt, dims in _SHAPE_RE.findall(type_str))


def _operand_head(rest):
    """The operand segment of an instruction's tail — everything before
    the metadata block, so source paths / op names can never be read as
    shapes."""
    idx = rest.find("metadata=")
    return rest if idx < 0 else rest[:idx]


def _parse_computations(hlo_text):
    """name -> [instr dict] for every computation, plus the entry name.
    An instr dict carries instruction/type/opcode/rest plus op path and
    source provenance pulled from its metadata."""
    comps, entry_name, cur = {}, None, None
    for line in hlo_text.splitlines():
        cm = _COMP_RE.match(line)
        if cm:
            cur = cm.group("name")
            comps[cur] = []
            if cm.group("entry"):
                entry_name = cur
            continue
        if line.startswith("}"):
            cur = None
            continue
        if cur is None:
            continue
        im = _KINSTR_RE.match(line)
        if not im:
            continue
        op = _OPNAME_RE.search(line)
        src = _SOURCE_RE.search(line)
        comps[cur].append({
            "instr": im.group("instr"),
            "type": im.group("type"),
            "opcode": im.group("opcode"),
            "rest": im.group("rest"),
            "op_path": _unescape(op.group(1)) if op else None,
            "provenance": ("%s:%s" % (src.group(1), src.group(2))
                           if src else None),
        })
    return comps, entry_name


def _estimate_flops(ins, comps, _depth=0):
    """Structural FLOP estimate for one instruction: dots and convs by
    their contraction algebra, fusions by their callee's sum, everything
    else one op per output element. These are RELATIVE weights — the
    report normalizes their sum against ``cost_analysis()['flops']``, so
    only the apportioning between kernels rides on this model."""
    opcode = ins["opcode"]
    if (opcode in ("parameter", "constant") or opcode in _VIEW_OPCODES
            or _depth > 8):
        return 0.0
    head = _operand_head(ins["rest"])
    if opcode in ("fusion", "call"):
        m = _CALLS_RE.search(ins["rest"]) or _TO_APPLY_RE.search(ins["rest"])
        if m:
            return sum(_estimate_flops(i, comps, _depth + 1)
                       for i in comps.get(m.group("callee"), ()))
        return float(_type_elems(ins["type"]))
    if opcode == "dot":
        out = _type_elems(ins["type"])
        ops = _OPERAND_TYPE_RE.findall(head)
        cm = _CONTRACT_RE.search(ins["rest"])
        contracted = 1
        if ops and cm:
            lhs_dims = _dims(ops[0][1])
            for idx in _dims(cm.group(1)):
                if idx < len(lhs_dims):
                    contracted *= lhs_dims[idx]
        return 2.0 * out * contracted
    if opcode == "convolution":
        out = _type_elems(ins["type"])
        ops = _OPERAND_TYPE_RE.findall(head)
        if len(ops) >= 2:
            rhs_dims = _dims(ops[1][1])
            rhs_elems = 1
            for d in rhs_dims:
                rhs_elems *= d
            out_ch = 1
            dl = _DIM_LABELS_RE.search(ins["rest"])
            if dl:
                o_idx = dl.group(2).find("o")
                if 0 <= o_idx < len(rhs_dims):
                    out_ch = rhs_dims[o_idx] or 1
            # 2 x output x (kernel-volume x in-channels-per-group): the
            # rhs carries exactly that product out_ch times, so /out_ch
            # absorbs feature groups too
            return 2.0 * out * rhs_elems / max(out_ch, 1)
        return 2.0 * out
    if opcode in ("reduce", "reduce-window", "sort", "scatter",
                  "select-and-scatter"):
        ops = _OPERAND_TYPE_RE.findall(head)
        if ops:
            n = 1
            for d in _dims(ops[0][1]):
                n *= d
            return float(max(n, _type_elems(ins["type"])))
    return float(_type_elems(ins["type"]))


def _estimate_bytes(ins):
    """HBM traffic estimate: operand bytes read + result bytes written
    (inline operand types x memcheck's dtype widths). Alias-aware: a
    dynamic-slice reads only the slice (not its operand), a
    dynamic-update-slice touches only the update window (the rest of its
    full-shaped "result" aliases the operand in place), and an explicit
    ``output_to_operand_aliasing`` counts the shared buffer once."""
    opcode = ins["opcode"]
    head = _operand_head(ins["rest"])
    result = _type_bytes(ins["type"])
    if opcode in ("dynamic-slice", "gather"):
        return 2 * result
    if opcode == "dynamic-update-slice":
        ops = _OPERAND_TYPE_RE.findall(head)
        upd = _shape_bytes(*ops[1]) if len(ops) >= 2 else result
        return 2 * upd
    operand = sum(_shape_bytes(dt, dims)
                  for dt, dims in _OPERAND_TYPE_RE.findall(head))
    if "output_to_operand_aliasing=" in ins["rest"]:
        return max(operand, result)
    return operand + result


def _comp_totals(cname, comps, _depth=0):
    """(flops, bytes) of ONE sequential execution of a computation,
    nested control flow included (inner whiles multiply by their known
    trips) — the merged-kernel cost of a collapsed expansion loop."""
    flops = nbytes = 0.0
    if _depth > 8:
        return flops, nbytes
    for ins in comps.get(cname, ()):
        opcode = ins["opcode"]
        if opcode == "while":
            bm = _BODY_RE.search(ins["rest"])
            tm = _TRIP_RE.search(ins["rest"])
            trips = int(tm.group(1)) if tm else 1
            if bm:
                f, b = _comp_totals(bm.group("body"), comps, _depth + 1)
                flops += f * trips
                nbytes += b * trips
            continue
        if opcode in ("conditional", "call"):
            for m in (_CALLS_RE.search(ins["rest"]),
                      _TO_APPLY_RE.search(ins["rest"])):
                if m:
                    f, b = _comp_totals(m.group("callee"), comps,
                                        _depth + 1)
                    flops += f
                    nbytes += b
            for groups in _BRANCHES_RE.findall(ins["rest"]):
                for g in groups:
                    if not g:
                        continue
                    for bname in (_BRANCH_NAME_RE.findall(g) or [g]):
                        f, b = _comp_totals(bname, comps, _depth + 1)
                        flops += f
                        nbytes += b
            continue
        if opcode in _NONKERNEL_OPCODES:
            continue
        flops += _estimate_flops(ins, comps)
        nbytes += _estimate_bytes(ins)
    return flops, nbytes


def _is_layout(ins, comps):
    """Pure data motion? True for copy/transpose kernels and for fusions
    whose callee computes nothing but layout ops."""
    opcode = ins["opcode"]
    if opcode in ("copy", "transpose"):
        return True
    if opcode == "fusion":
        m = _CALLS_RE.search(ins["rest"])
        body = comps.get(m.group("callee"), ()) if m else ()
        real = [i for i in body
                if i["opcode"] not in ("parameter", "constant")
                and i["opcode"] not in _VIEW_OPCODES]
        return bool(real) and all(i["opcode"] in _LAYOUT_OPCODES
                                  for i in real)
    return False


class KernelEntry(object):
    """One kernel launch in the compiled program's schedule: a fusion,
    dot, convolution, reduce, copy ... with its apportioned FLOPs, HBM
    traffic, roofline classification and provenance. ``multiplier`` is
    the per-dispatch execution count (a while-body kernel runs K times);
    ``seconds`` is the roofline time for ONE execution —
    ``max(flops/peak, bytes/bw)``."""

    __slots__ = ("instruction", "opcode", "flops", "bytes", "in_loop",
                 "multiplier", "is_layout", "op_path", "provenance",
                 "seconds", "intensity", "bound", "norm_flops")

    def __init__(self, instruction, opcode, flops, bytes_, in_loop=False,
                 multiplier=1, is_layout=False, op_path=None,
                 provenance=None, norm_flops=None):
        self.instruction = instruction
        self.opcode = opcode
        self.flops = float(flops)
        self.bytes = int(bytes_)
        #: the weight this kernel contributes to the cost-analysis
        #: normalization basis. Defaults to ``flops``; a collapsed
        #: expansion loop passes its ONE-trip body estimate instead —
        #: the XLA cost model counts a while body once, so normalizing
        #: on the trip-multiplied figure would let one scalar loop steal
        #: the whole program's FLOP budget
        self.norm_flops = (self.flops if norm_flops is None
                           else float(norm_flops))
        self.in_loop = bool(in_loop)
        self.multiplier = max(1, int(multiplier))
        self.is_layout = bool(is_layout)
        self.op_path = op_path
        self.provenance = provenance
        # roofline fields, priced by the report against its device spec
        self.seconds = 0.0
        self.intensity = 0.0
        self.bound = "memory"

    def price(self, peak_flops_per_s, hbm_bytes_per_s):
        self.intensity = (self.flops / self.bytes) if self.bytes else 0.0
        ridge = peak_flops_per_s / hbm_bytes_per_s
        self.bound = "compute" if self.intensity >= ridge else "memory"
        self.seconds = max(self.flops / peak_flops_per_s,
                           self.bytes / hbm_bytes_per_s)

    @property
    def total_seconds(self):
        return self.seconds * self.multiplier

    def as_dict(self):
        return {
            "instruction": self.instruction,
            "opcode": self.opcode,
            "flops": self.flops,
            "bytes": self.bytes,
            "intensity": self.intensity,
            "bound": self.bound,
            "in_loop": self.in_loop,
            "multiplier": self.multiplier,
            "is_layout": self.is_layout,
            "predicted_us": self.seconds * 1e6,
            "op_path": self.op_path,
            "provenance": self.provenance,
        }

    def format(self):
        where = self.op_path or self.instruction
        if self.provenance:
            where += " @ " + self.provenance
        mult = " x%d" % self.multiplier if self.multiplier > 1 else ""
        return ("%-7s %8.2fus %10s %8.1f FLOP/B %-14s%s %s"
                % (self.bound, self.seconds * 1e6, _fmt_bytes(self.bytes),
                   self.intensity, self.opcode, mult, where))

    def __repr__(self):
        return "KernelEntry(%s)" % self.format()


def parse_kernels(hlo_text, loop_trips=1):
    """Walk the scheduled HLO into the kernel inventory: the entry
    computation's top-level instructions plus every while body (in-loop,
    multiplied by its known trip count or ``loop_trips``) and every
    conditional branch. Parameters, constants, views, control flow and
    collectives are not kernels. FLOPs here are the RAW structural
    estimates — :func:`analyze_compiled` apportions them against the XLA
    cost model."""
    comps, entry = _parse_computations(hlo_text)
    if entry is None:
        return []
    kernels = []
    seen = set()
    # (computation, in_loop, multiplier) execution contexts, discovered
    # by walking control flow from the entry
    work = [(entry, False, 1)]
    while work:
        cname, in_loop, mult = work.pop(0)
        if cname in seen:
            continue
        seen.add(cname)
        for ins in comps.get(cname, ()):
            opcode = ins["opcode"]
            if opcode == "while":
                bm = _BODY_RE.search(ins["rest"])
                if bm:
                    trips = loop_trips
                    tm = _TRIP_RE.search(ins["rest"])
                    if tm:
                        trips = int(tm.group(1))
                    trips = max(1, trips)
                    if trips > _EXPANSION_TRIPS:
                        # a scalar expansion loop (CPU pool backprop),
                        # not a per-trip dispatch schedule: ONE merged
                        # kernel. FLOPs are the body total x trips, but
                        # bytes are ONE streaming pass over the
                        # loop-carried state (read + write the tuple):
                        # each scalar iteration's body references the
                        # full arrays it slices from, so body-bytes x
                        # trips would bill the whole array once per
                        # element — petabytes of fiction
                        f, _ = _comp_totals(bm.group("body"), comps)
                        b = 2 * _type_bytes(ins["type"])
                        kernels.append(KernelEntry(
                            ins["instr"], "while", f * trips, b,
                            in_loop=in_loop, multiplier=mult,
                            op_path=ins["op_path"],
                            provenance=ins["provenance"],
                            norm_flops=f))
                    else:
                        work.append((bm.group("body"), True,
                                     mult * trips))
                continue
            if opcode == "conditional":
                for groups in _BRANCHES_RE.findall(ins["rest"]):
                    for g in groups:
                        if not g:
                            continue
                        # group 3 is a brace list of %names; 1/2 are bare
                        for bname in (_BRANCH_NAME_RE.findall(g) or [g]):
                            work.append((bname, in_loop, mult))
                continue
            if opcode == "call":
                tm = _TO_APPLY_RE.search(ins["rest"])
                if tm:
                    work.append((tm.group("callee"), in_loop, mult))
                continue
            if opcode in _NONKERNEL_OPCODES:
                continue
            kernels.append(KernelEntry(
                ins["instr"], opcode,
                _estimate_flops(ins, comps),
                _estimate_bytes(ins),
                in_loop=in_loop, multiplier=mult,
                is_layout=_is_layout(ins, comps),
                op_path=ins["op_path"], provenance=ins["provenance"]))
    return kernels


# ---------------------------------------------------------------------------
# the report + roofline
# ---------------------------------------------------------------------------

class RooflineReport(object):
    """Static compute/memory profile of ONE compiled program.

    ``kernel_count`` is the PER-DISPATCH kernel execution count (in-loop
    kernels multiplied by their trips — the same semantics as
    commscheck's ``collective_count``); ``predicted_step_seconds`` is
    the zero-overlap roofline bound for one dispatch: every kernel's
    ``max(flops/peak, bytes/bw)`` plus the collective wire time from the
    embedded :class:`~mxnet_tpu.commscheck.CommsReport`. The baseline
    gate pins kernel count / predicted step ms / predicted MFU /
    top-hotspot identity."""

    __slots__ = ("program", "platform", "kernels", "loop_trips", "flops",
                 "comms", "peak_flops_per_s", "hbm_bytes_per_s",
                 "peak_source", "hlo_unavailable")

    def __init__(self, program, platform, kernels, loop_trips=1,
                 flops=None, comms=None, peak_flops_per_s=None,
                 hbm_bytes_per_s=None, peak_source=None,
                 hlo_unavailable=False):
        self.program = program
        self.platform = platform
        self.kernels = list(kernels)
        self.loop_trips = max(1, int(loop_trips))
        self.flops = None if flops is None else float(flops)
        self.comms = comms
        if peak_flops_per_s is None or hbm_bytes_per_s is None:
            spec, source = devspec.lookup()
            peak_flops_per_s = (spec.peak_flops_per_s
                                if peak_flops_per_s is None
                                else peak_flops_per_s)
            hbm_bytes_per_s = (spec.hbm_bytes_per_s
                               if hbm_bytes_per_s is None
                               else hbm_bytes_per_s)
            peak_source = source if peak_source is None else peak_source
        self.peak_flops_per_s = float(peak_flops_per_s)
        self.hbm_bytes_per_s = float(hbm_bytes_per_s)
        self.peak_source = peak_source or "spec"
        #: the executable's HLO text could not be read: the (empty)
        #: inventory is ABSENCE OF EVIDENCE, not a cheap program — the
        #: drift gate fails such programs and the roofline claims nothing
        self.hlo_unavailable = bool(hlo_unavailable)
        # apportion the structural estimates against the XLA cost model
        # (which counts a while body ONCE — so normalize on the
        # once-each sum, then let the multipliers scale per-dispatch)
        raw = sum(k.norm_flops for k in self.kernels)
        if self.flops and raw > 0:
            scale = self.flops / raw
            for k in self.kernels:
                k.flops *= scale
        for k in self.kernels:
            k.price(self.peak_flops_per_s, self.hbm_bytes_per_s)
        self.kernels.sort(key=lambda k: k.total_seconds, reverse=True)

    @property
    def ridge_intensity(self):
        return self.peak_flops_per_s / self.hbm_bytes_per_s

    @property
    def kernel_count(self):
        return sum(k.multiplier for k in self.kernels)

    @property
    def flops_per_dispatch(self):
        return sum(k.flops * k.multiplier for k in self.kernels)

    @property
    def bytes_per_dispatch(self):
        return sum(k.bytes * k.multiplier for k in self.kernels)

    @property
    def kernel_seconds(self):
        return sum(k.total_seconds for k in self.kernels)

    @property
    def comm_seconds(self):
        """Per-dispatch collective wire time (commscheck's per-iteration
        model x the trip count); 0 for an unsharded program."""
        if self.comms is None:
            return 0.0
        return self.comms.comm_seconds * self.loop_trips

    @property
    def predicted_step_seconds(self):
        return self.kernel_seconds + self.comm_seconds

    @property
    def predicted_step_ms(self):
        return self.predicted_step_seconds * 1e3

    @property
    def predicted_mfu(self):
        """Dispatch FLOPs over predicted time x peak — what the roofline
        says utilization CAN be; None without evidence."""
        if self.hlo_unavailable or not self.kernels:
            return None
        t = self.predicted_step_seconds
        if t <= 0:
            return None
        return self.flops_per_dispatch / (t * self.peak_flops_per_s)

    @property
    def top_hotspot(self):
        """op path (or instruction name) of the kernel holding the most
        predicted step time — the identity the baseline pins."""
        if not self.kernels:
            return None
        k = self.kernels[0]
        return k.op_path or k.instruction

    def hotspots(self, top=10, memory_only=False):
        """The Pallas shopping list: kernels ranked by held step time
        (``memory_only`` keeps just the below-ridge ones — the hand-
        kernel candidates)."""
        ks = [k for k in self.kernels
              if not memory_only or k.bound == "memory"]
        return ks[:top]

    def breakdown(self, top=6):
        return [k.format() for k in self.kernels[:top]]

    def as_dict(self):
        mfu = self.predicted_mfu
        return {
            "program": self.program,
            "platform": self.platform,
            "hlo_unavailable": self.hlo_unavailable,
            "kernel_count": self.kernel_count,
            "flops_per_dispatch": self.flops_per_dispatch,
            "bytes_per_dispatch": self.bytes_per_dispatch,
            "ridge_intensity": self.ridge_intensity,
            "peak_source": self.peak_source,
            "loop_trips": self.loop_trips,
            "kernel_seconds": self.kernel_seconds,
            "comm_seconds": self.comm_seconds,
            "predicted_step_ms": self.predicted_step_ms,
            "predicted_mfu": None if mfu is None else round(mfu, 6),
            "top_hotspot": self.top_hotspot,
            "kernels": [k.as_dict() for k in self.kernels],
        }

    def format(self):
        mfu = self.predicted_mfu
        return ("%s: %d kernel(s)/dispatch, predicted %.3f ms, MFU %s"
                % (self.program, self.kernel_count, self.predicted_step_ms,
                   "?" if mfu is None else "%.4f" % mfu))

    def __repr__(self):
        return "RooflineReport(%s)" % self.format()


def analyze_compiled(compiled, name, mesh=None, loop_trips=1):
    """Build a :class:`RooflineReport` from an ALREADY-compiled program
    (``jax.stages.Compiled``). Never executes anything; ONE HLO text
    read feeds both the kernel walk and the embedded collective
    inventory."""
    import jax
    text_ok = True
    try:
        hlo_text = compiled.as_text()
        if not hlo_text:
            text_ok = False
    except Exception as exc:
        import logging
        logging.warning("flopcheck: %s: compiled HLO text unavailable "
                        "(%r) — the inventory is empty for lack of "
                        "EVIDENCE, not because the program is free",
                        name, exc)
        hlo_text = ""
        text_ok = False
    flops = None
    try:
        ca = compiled.cost_analysis()
        if isinstance(ca, list):
            ca = ca[0]
        if ca:
            flops = float(ca.get("flops", 0.0)) or None
    except Exception:
        flops = None
    platform = jax.devices()[0].platform
    kernels = parse_kernels(hlo_text, loop_trips=loop_trips)
    comms = None
    entries = parse_collectives(hlo_text, mesh=mesh, loop_trips=loop_trips)
    if entries:
        n_dev = int(mesh.devices.size) if mesh is not None else 1
        comms = CommsReport(name, platform, n_dev, entries,
                            loop_trips=loop_trips, flops=flops,
                            hlo_unavailable=not text_ok)
    return RooflineReport(name, platform, kernels,
                          loop_trips=loop_trips, flops=flops, comms=comms,
                          hlo_unavailable=not text_ok)


def analyze(fn, args=(), kwargs=None, name=None, mesh=None, loop_trips=1):
    """Compile ``fn`` (never executed — args may be
    ``ShapeDtypeStruct``s; sharded programs must carry real shardings)
    and return its :class:`RooflineReport`."""
    import jax
    kwargs = dict(kwargs or {})
    if name is None:
        name = getattr(fn, "__name__", None) or repr(fn)
    if mesh is None:
        mesh = _infer_mesh(args, kwargs)
    jitted = fn if hasattr(fn, "lower") else jax.jit(fn)
    compiled = jitted.lower(*args, **kwargs).compile()
    return analyze_compiled(compiled, name, mesh=mesh,
                            loop_trips=loop_trips)


# ---------------------------------------------------------------------------
# lints
# ---------------------------------------------------------------------------

def lint_report(report, hot_threshold=None, hot_floor=None,
                layout_threshold=None, layout_share=None,
                tiny_floor_us=None, tiny_threshold=None, mfu_floor=None):
    """The four roofline lints over one :class:`RooflineReport`:
    ``memory-bound-hot``, ``layout-copy``, ``tiny-dispatch``,
    ``predicted-mfu``. Returns findings with suppressions applied (like
    ``tracecheck.check_program``)."""
    hot_threshold = hot_frac() if hot_threshold is None \
        else float(hot_threshold)
    hot_floor = hot_bytes() if hot_floor is None else int(hot_floor)
    layout_threshold = layout_bytes() if layout_threshold is None \
        else int(layout_threshold)
    layout_share = layout_frac() if layout_share is None \
        else float(layout_share)
    tiny_floor_us = tiny_us() if tiny_floor_us is None \
        else float(tiny_floor_us)
    tiny_threshold = tiny_count() if tiny_threshold is None \
        else int(tiny_threshold)
    mfu_floor = min_mfu() if mfu_floor is None else float(mfu_floor)
    name = report.program
    findings = []
    step = report.predicted_step_seconds
    total_bytes = report.bytes_per_dispatch

    for k in report.kernels:
        frac = (k.total_seconds / step) if step > 0 else 0.0
        if (k.bound == "memory" and not k.is_layout
                and frac >= hot_threshold
                and k.bytes * k.multiplier >= hot_floor):
            findings.append(Finding(
                "memory-bound-hot", name,
                "kernel %r holds %.0f%% of the predicted step time "
                "(%.2fus of %.2fus) at intensity %.1f FLOP/B — below "
                "the ridge %.1f, so it is waiting on HBM (%s moved per "
                "dispatch); this is the Pallas-candidate signature: a "
                "hand kernel that keeps the working set in VMEM wins "
                "(threshold MXTPU_FLOPCHECK_HOT_FRAC=%.2f)"
                % (k.instruction, 100.0 * frac, k.total_seconds * 1e6,
                   step * 1e6, k.intensity, report.ridge_intensity,
                   _fmt_bytes(k.bytes * k.multiplier), hot_threshold),
                op_path=k.op_path or k.instruction,
                provenance=k.provenance))
        kb = k.bytes * k.multiplier
        byte_share = (kb / float(total_bytes)) if total_bytes > 0 else 0.0
        if (k.is_layout and kb > layout_threshold
                and byte_share >= layout_share):
            findings.append(Finding(
                "layout-copy", name,
                "kernel %r is pure data motion (%s) moving %s per "
                "dispatch — %.0f%% of the program's HBM traffic "
                "(> %.0f%%, MXTPU_FLOPCHECK_LAYOUT_FRAC) spent "
                "re-laying-out memory, zero FLOPs; fix the layout that "
                "forced the %s"
                % (k.instruction, k.opcode, _fmt_bytes(kb),
                   100.0 * byte_share, 100.0 * layout_share, k.opcode),
                op_path=k.op_path or k.instruction,
                provenance=k.provenance))

    tiny = [k for k in report.kernels
            if k.seconds * 1e6 < tiny_floor_us]
    tiny_execs = sum(k.multiplier for k in tiny)
    if tiny_execs > tiny_threshold:
        worst = tiny[0] if tiny else report.kernels[0]
        findings.append(Finding(
            "tiny-dispatch", name,
            "%d kernel execution(s) per dispatch each predicted under "
            "%.1fus (> %d, MXTPU_FLOPCHECK_TINY_COUNT) — dispatch "
            "overhead dominates compute: a fusion regression shattered "
            "the step (or the program genuinely needs fusing)"
            % (tiny_execs, tiny_floor_us, tiny_threshold),
            op_path=worst.op_path or worst.instruction,
            provenance=worst.provenance))

    mfu = report.predicted_mfu
    if mfu_floor > 0 and mfu is not None and mfu < mfu_floor:
        k = report.kernels[0]
        findings.append(Finding(
            "predicted-mfu", name,
            "predicted MFU %.4f is below the floor %.2f "
            "(MXTPU_FLOPCHECK_MIN_MFU): the roofline says the program "
            "CANNOT reach the target utilization — %.3f ms predicted "
            "step time at %s peak (%s). Inventory:\n  %s"
            % (mfu, mfu_floor, report.predicted_step_ms,
               "%.1f TFLOP/s" % (report.peak_flops_per_s / 1e12),
               report.peak_source, "\n  ".join(report.breakdown())),
            op_path=k.op_path or k.instruction, provenance=k.provenance))

    for f in findings:
        f.suppressed = _is_suppressed(f)
    return findings


def check_program(fn, args=(), kwargs=None, name=None, mesh=None,
                  loop_trips=1, **lint_kw):
    """Analyze + lint ONE program; returns ``(findings, report)``."""
    report = analyze(fn, args, kwargs=kwargs, name=name, mesh=mesh,
                     loop_trips=loop_trips)
    return lint_report(report, **lint_kw), report


def hotspot_report(fn, args=(), kwargs=None, name=None, mesh=None,
                   loop_trips=1, top=10, memory_only=True):
    """The Pallas tier's shopping list for ONE program: analyze and
    return the ranked hotspot entries as dicts (exposed to the autotune
    search driver as ``mxnet_tpu.autotune.hotspot_report``)."""
    report = analyze(fn, args, kwargs=kwargs, name=name, mesh=mesh,
                     loop_trips=loop_trips)
    step = report.predicted_step_seconds
    out = []
    for k in report.hotspots(top=top, memory_only=memory_only):
        d = k.as_dict()
        d["step_time_frac"] = ((k.total_seconds / step)
                               if step > 0 else 0.0)
        out.append(d)
    return out


# ---------------------------------------------------------------------------
# runtime hook (MXTPU_FLOPCHECK / engine.flopcheck_mode)
# ---------------------------------------------------------------------------

#: program names already audited by the dispatch hook — the audit pays
#: one extra compile, so it runs once per compiled program per process
_AUDITED = set()


def maybe_audit_dispatch(name, jitfn, call_args, loop_trips=1, mesh=None):
    """One-time roofline audit of a freshly-compiled dispatch program
    (``TrainStep`` calls this at first registration — single-device
    programs too, a fusion regression needs no mesh to hurt): under
    ``MXTPU_FLOPCHECK=warn`` unsuppressed findings are logged, under
    ``error`` they raise. Costs one extra compile; ``off`` (the default)
    skips entirely. Call arguments are reduced to ``ShapeDtypeStruct``s
    first, so already-donated buffers are never touched."""
    from . import engine
    mode = engine.flopcheck_mode()
    if mode == "off" or name in _AUDITED:
        return None
    _AUDITED.add(name)
    # knobs resolve BEFORE the analyzer guard: a malformed env var must
    # propagate as MXNetError instead of silently disarming the gate the
    # operator just configured (memcheck's load-audit hardening)
    kw = dict(hot_threshold=hot_frac(), hot_floor=hot_bytes(),
              layout_threshold=layout_bytes(), layout_share=layout_frac(),
              tiny_floor_us=tiny_us(), tiny_threshold=tiny_count(),
              mfu_floor=min_mfu())
    try:
        findings, report = check_program(
            jitfn, struct_args(tuple(call_args)), name=name, mesh=mesh,
            loop_trips=loop_trips, **kw)
    except Exception as exc:
        import logging
        logging.warning("flopcheck: dispatch audit of %s failed (%r) — "
                        "skipping", name, exc)
        return None
    if report.hlo_unavailable:
        # the armed gate must not pass vacuously: no HLO text means NO
        # audit ran (same contract as the CLI / baseline consumers)
        msg = ("flopcheck: compiled HLO text unavailable for %s — the "
               "MXTPU_FLOPCHECK audit could not run" % name)
        if mode == "error":
            raise MXNetError(msg)
        import logging
        logging.warning(msg)
        return report
    bad = unsuppressed(findings)
    if bad:
        msg = ("flopcheck: %d finding(s) on program %s "
               "(MXTPU_FLOPCHECK):\n%s"
               % (len(bad), name, "\n".join(f.format() for f in bad)))
        if mode == "error":
            raise MXNetError(msg)
        import logging
        logging.warning(msg)
    return report


# ---------------------------------------------------------------------------
# compile-once program sets (zoo + sharded) — ONE compile feeds all
# three analyzers (memcheck + commscheck + flopcheck)
# ---------------------------------------------------------------------------

def compiled_zoo_programs(names=None, k=2, guard=True, log=None):
    """Compile every zoo step program ONCE and yield
    ``(name, compiled, args, loop_trips, mesh)`` — the shared substrate
    of the combined CI gate (one compile per program instead of one per
    analyzer). Program names and shapes come from
    ``tracecheck.train_step_programs``, THE shared recipe, so the
    analyzers can never drift apart on what training dispatches."""
    from .tracecheck import train_step_programs, zoo_train_step
    names = list(names) if names else sorted(ZOO)
    for mname in names:
        if mname not in ZOO:
            raise MXNetError("flopcheck: unknown zoo model %r (have %s)"
                             % (mname, ", ".join(sorted(ZOO))))
        if log:
            log("flopcheck: compiling %s ..." % mname)
        ts, data_shapes, label_shapes = zoo_train_step(mname)
        for pname, jitfn, pargs in train_step_programs(
                ts, data_shapes, label_shapes, k=k, guard=guard,
                name=mname):
            trips = k if "/scan[" in pname or "-scan[" in pname else 1
            compiled = jitfn.lower(*pargs).compile()
            yield pname, compiled, pargs, trips, ts.mesh


def compiled_sharded_programs(n_devices=8, k=2, log=None):
    """Compile the sharded gate set (``commscheck.sharded_programs``)
    ONCE each; yields ``(name, compiled, args, loop_trips, mesh)``."""
    import contextlib
    from .commscheck import sharded_programs
    from .parallel.mesh import MeshScope
    for name, jitfn, args, trips, mesh, scope in sharded_programs(
            n_devices=n_devices, k=k):
        if log:
            log("flopcheck: compiling %s ..." % name)
        ambient = (MeshScope(scope) if scope is not None
                   else contextlib.nullcontext())
        with ambient:
            compiled = jitfn.lower(*args).compile()
        yield name, compiled, args, trips, mesh


def check_train_step(ts, data_shapes, label_shapes, k=2, guard=True,
                     name=None, **lint_kw):
    """Roofline-audit a :class:`~mxnet_tpu.train_step.TrainStep`'s full
    program set (``tracecheck.train_step_programs``). Returns
    ``(findings, reports)``."""
    from .tracecheck import train_step_programs
    name = name or "TrainStep(%s)" % ts.symbol.name
    findings, reports = [], {}
    for pname, jitfn, pargs in train_step_programs(
            ts, data_shapes, label_shapes, k=k, guard=guard, name=name):
        trips = k if "/scan[" in pname or "-scan[" in pname else 1
        fs, rep = check_program(jitfn, pargs, name=pname, mesh=ts.mesh,
                                loop_trips=trips, **lint_kw)
        findings += fs
        reports[pname] = rep
    return findings, reports


def check_zoo(names=None, k=2, guard=True, log=None, programs=None,
              **lint_kw):
    """Roofline-audit the model zoo's step programs (same configs as
    ``tracecheck.ZOO``); returns ``(findings, reports)``. Pass
    ``programs`` (an iterable from :func:`compiled_zoo_programs`) to
    reuse already-compiled executables — the combined gate path."""
    findings, reports = [], {}
    progs = programs if programs is not None else compiled_zoo_programs(
        names=names, k=k, guard=guard, log=log)
    for pname, compiled, _pargs, trips, mesh in progs:
        rep = analyze_compiled(compiled, pname, mesh=mesh,
                               loop_trips=trips)
        findings += lint_report(rep, **lint_kw)
        reports[pname] = rep
    return findings, reports


def check_sharded(n_devices=8, k=2, log=None, programs=None, **lint_kw):
    """Roofline-audit the sharded gate program set; returns
    ``(findings, reports)``."""
    findings, reports = [], {}
    progs = programs if programs is not None else \
        compiled_sharded_programs(n_devices=n_devices, k=k, log=log)
    for pname, compiled, _pargs, trips, mesh in progs:
        rep = analyze_compiled(compiled, pname, mesh=mesh,
                               loop_trips=trips)
        findings += lint_report(rep, **lint_kw)
        reports[pname] = rep
    return findings, reports


# ---------------------------------------------------------------------------
# the baseline drift gate (ci/flopcheck.sh)
# ---------------------------------------------------------------------------

#: metrics the baseline pins per program: kernel count (growth = a
#: fusion shattered), predicted step ms (growth = the roofline got
#: worse), predicted MFU (drop = ditto) and the top-hotspot identity
#: (change = the optimization target moved — a note, not a failure)
_BASELINE_METRICS = ("kernel_count", "predicted_step_ms", "predicted_mfu")


def write_baseline(reports, path, tol=None):
    """Write the committed baseline, keyed by platform (a CPU baseline
    must not gate a TPU run). Refuses evidence-free reports — committing
    a fabricated zero for a program whose HLO text could not be read
    would pin the drift gate on nothing."""
    import jax
    from .model import atomic_write_bytes
    blind = sorted(n for n, r in reports.items()
                   if getattr(r, "hlo_unavailable", False))
    if blind:
        raise MXNetError(
            "write_baseline: compiled HLO text was unavailable for %s — "
            "their inventories are absence of evidence, not zeros; "
            "refusing to commit a fabricated baseline" % ", ".join(blind))
    data = {
        "platform": jax.devices()[0].platform,
        "tolerance": tolerance() if tol is None else float(tol),
        "programs": {
            name: {
                "kernel_count": int(rep.kernel_count),
                "predicted_step_ms": round(rep.predicted_step_ms, 6),
                "predicted_mfu": (None if rep.predicted_mfu is None
                                  else round(rep.predicted_mfu, 6)),
                "top_hotspot": rep.top_hotspot,
            }
            for name, rep in sorted(reports.items())},
    }
    atomic_write_bytes(path, (json.dumps(data, indent=2, sort_keys=True)
                              + "\n").encode())
    return data


def compare_baseline(reports, baseline, tol=None):
    """The drift gate: kernel count or predicted step time growing past
    the tolerance band fails WITH the kernel breakdown (op paths +
    source provenance); predicted MFU dropping past the band fails too.
    A program missing from the baseline fails (new programs are added
    deliberately), and a nonzero-pinned kernel count collapsing to zero
    fails — a parser gone blind must not read as a win. Shrinks, MFU
    gains, hotspot moves and stale entries are notes; a platform-
    mismatched baseline skips the gate with one note. Returns
    ``(failures, notes)``."""
    import jax
    if isinstance(baseline, str):
        with open(baseline) as f:
            baseline = json.load(f)
    if tol is None:
        # precedence: explicit arg > MXTPU_FLOPCHECK_TOL env > the
        # baseline's stored band > 0.1 (memcheck's hardened ordering)
        tol = env_float("MXTPU_FLOPCHECK_TOL",
                        float(baseline.get("tolerance", 0.1)))
    else:
        tol = float(tol)
    platform = jax.devices()[0].platform
    failures, notes = [], []
    if baseline.get("platform") != platform:
        notes.append(
            "flopcheck baseline was written on platform %r but this run "
            "is %r — skipping the drift gate (re-run --write-baseline on "
            "this platform to arm it)"
            % (baseline.get("platform"), platform))
        return failures, notes
    base_progs = dict(baseline.get("programs") or {})
    for name, rep in sorted(reports.items()):
        base = base_progs.pop(name, None)
        if getattr(rep, "hlo_unavailable", False):
            failures.append(
                "%s: compiled HLO text unavailable on this backend — the "
                "kernel inventory could not be audited; the drift gate "
                "refuses to pass on absence of evidence" % name)
            continue
        if base is None:
            failures.append(
                "%s: not in the baseline — a new program must be added "
                "deliberately (run `python -m mxnet_tpu.flopcheck --zoo "
                "--sharded --write-baseline FLOPCHECK_baseline.json` and "
                "commit the diff)" % name)
            continue
        breakdown = "\n  ".join(rep.breakdown()) or "(empty)"
        # kernel count: integer growth past the band = fusion regression
        b_count = int(base.get("kernel_count", 0))
        count = int(rep.kernel_count)
        if count > b_count + int(b_count * tol):
            failures.append(
                "%s: kernel_count grew %d -> %d (tolerance %.0f%%, "
                "MXTPU_FLOPCHECK_TOL) — a fusion shattered or new "
                "kernels appeared. Inventory:\n  %s"
                % (name, b_count, count, 100.0 * tol, breakdown))
        elif count == 0 and b_count > 0:
            failures.append(
                "%s: kernel_count collapsed %d -> 0 — either the program "
                "genuinely vanished (refresh the baseline deliberately) "
                "or the HLO parser went blind (an XLA text-format "
                "drift); the gate refuses to treat a total collapse as "
                "a win" % (name, b_count))
        elif count < b_count - int(b_count * tol) and b_count > 0:
            notes.append("%s: kernel_count shrank %d -> %d — nice; "
                         "refresh the baseline to lock the win in"
                         % (name, b_count, count))
        # predicted step time: float growth past the band
        b_ms = float(base.get("predicted_step_ms", 0.0))
        ms = rep.predicted_step_ms
        if b_ms > 0 and ms > b_ms * (1.0 + tol):
            failures.append(
                "%s: predicted_step_ms grew %.4f -> %.4f (tolerance "
                "%.0f%%, MXTPU_FLOPCHECK_TOL) — the roofline says this "
                "dispatch got slower. Inventory:\n  %s"
                % (name, b_ms, ms, 100.0 * tol, breakdown))
        elif b_ms > 0 and ms < b_ms * (1.0 - tol):
            notes.append("%s: predicted_step_ms shrank %.4f -> %.4f — "
                         "nice; refresh the baseline to lock the win in"
                         % (name, b_ms, ms))
        # predicted MFU: a drop past the band fails
        b_mfu = base.get("predicted_mfu")
        mfu = rep.predicted_mfu
        if b_mfu and mfu is not None:
            if mfu < float(b_mfu) * (1.0 - tol):
                failures.append(
                    "%s: predicted_mfu dropped %.4f -> %.4f (tolerance "
                    "%.0f%%, MXTPU_FLOPCHECK_TOL). Inventory:\n  %s"
                    % (name, float(b_mfu), mfu, 100.0 * tol, breakdown))
            elif mfu > float(b_mfu) * (1.0 + tol):
                notes.append("%s: predicted_mfu rose %.4f -> %.4f — "
                             "refresh the baseline to lock the win in"
                             % (name, float(b_mfu), mfu))
        b_hot = base.get("top_hotspot")
        if b_hot and rep.top_hotspot and b_hot != rep.top_hotspot:
            notes.append(
                "%s: top hotspot moved %r -> %r — the Pallas shopping "
                "list reordered; refresh the baseline if intended"
                % (name, b_hot, rep.top_hotspot))
    for name in sorted(base_progs):
        notes.append("baseline entry %r matches no audited program "
                     "(stale — refresh the baseline)" % name)
    return failures, notes


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------

def report_table(reports, out=None):
    import sys
    out = out or sys.stdout
    w = max([len(n) for n in reports] + [8])
    out.write("%-*s  %7s %10s %10s %8s %8s\n"
              % (w, "program", "kernels", "flops", "bytes", "pred-ms",
                 "mfu"))
    for name in sorted(reports):
        r = reports[name]
        mfu = r.predicted_mfu
        out.write("%-*s  %7d %10.3g %10s %8.4f %8s\n"
                  % (w, name, r.kernel_count, r.flops_per_dispatch,
                     _fmt_bytes(r.bytes_per_dispatch),
                     r.predicted_step_ms,
                     "?" if mfu is None else "%.4f" % mfu))


def hotspot_table(reports, top=10, memory_only=False, out=None):
    """Print the ranked hotspot table — the Pallas shopping list."""
    import sys
    out = out or sys.stdout
    for name in sorted(reports):
        r = reports[name]
        ks = r.hotspots(top=top, memory_only=memory_only)
        if not ks:
            continue
        step = r.predicted_step_seconds
        out.write("%s (predicted %.4f ms, ridge %.1f FLOP/B, %s):\n"
                  % (name, r.predicted_step_ms, r.ridge_intensity,
                     r.peak_source))
        for k in ks:
            frac = (k.total_seconds / step) if step > 0 else 0.0
            out.write("  %5.1f%%  %s\n" % (100.0 * frac, k.format()))


def _combined_memcheck(programs_by_model, baseline, tol):
    """The memcheck leg of the combined compile-once gate: reuse each
    zoo program's compiled executable for the HBM lints + per-model
    resident-set + baseline drift, exactly as ci/memcheck.sh runs them
    (the sharded set is NOT in MEMCHECK_baseline.json, so only zoo
    programs feed this leg)."""
    from . import memcheck
    findings, reports = [], {}
    for model, progs in sorted(programs_by_model.items()):
        model_reports = {}
        for pname, compiled, pargs, _trips, _mesh in progs:
            rep = memcheck.analyze_compiled(compiled, pname, args=pargs,
                                            donate_argnums=(0,))
            findings += memcheck.lint_report(rep)
            model_reports[pname] = rep
        findings += memcheck.lint_resident_set(
            model_reports.values(), "%s/resident-set" % model)
        reports.update(model_reports)
    failures, notes = memcheck.compare_baseline(reports, baseline, tol=tol)
    return findings, failures, notes


def _combined_commscheck(all_programs, baseline, tol):
    """The commscheck leg of the combined gate: collective lints +
    baseline drift from the SAME compiled executables."""
    from . import commscheck
    findings, reports = [], {}
    for pname, compiled, _pargs, trips, mesh in all_programs:
        rep = commscheck.analyze_compiled(compiled, pname, mesh=mesh,
                                          loop_trips=trips)
        findings += commscheck.lint_report(rep)
        reports[pname] = rep
    failures, notes = commscheck.compare_baseline(reports, baseline,
                                                  tol=tol)
    return findings, failures, notes


def main(argv=None):
    import argparse
    import sys
    import time
    from . import tracecheck as _tc
    p = argparse.ArgumentParser(
        prog="python -m mxnet_tpu.flopcheck",
        description="Static per-kernel compute/memory roofline analyzer:"
                    " kernel inventory (FLOPs/bytes/intensity/bound),"
                    " predicted step time + MFU, hotspot ranking for the"
                    " Pallas tier, roofline lints, and the baseline drift"
                    " gate (docs/static_analysis.md \"Roofline lints\").")
    p.add_argument("--zoo", action="store_true",
                   help="analyze every shipped model's step/scan programs")
    p.add_argument("--models", default=None,
                   help="comma-separated zoo subset (implies --zoo)")
    p.add_argument("--sharded", action="store_true",
                   help="also analyze the sharded gate set (needs 8 "
                        "visible devices)")
    p.add_argument("--devices", type=int, default=8,
                   help="device count for --sharded (default 8)")
    p.add_argument("--k", type=int, default=2,
                   help="scan depth for the K-step programs (default 2)")
    p.add_argument("--no-guard", action="store_true",
                   help="skip the guarded program variants")
    p.add_argument("--hotspots", type=int, default=None, metavar="N",
                   help="print the top-N hotspot kernels per program "
                        "(the Pallas shopping list)")
    p.add_argument("--memory-bound", action="store_true",
                   help="restrict --hotspots to memory-bound kernels")
    p.add_argument("--hot-frac", type=float, default=None,
                   help="memory-bound-hot step-share threshold (default "
                        "MXTPU_FLOPCHECK_HOT_FRAC or 0.6)")
    p.add_argument("--min-mfu", type=float, default=None,
                   help="predicted-mfu floor (default "
                        "MXTPU_FLOPCHECK_MIN_MFU or 0 = disabled)")
    p.add_argument("--baseline", default=None, metavar="FILE",
                   help="compare against a committed baseline (the CI "
                        "drift gate); exit non-zero on kernel-count / "
                        "step-time / MFU drift past tolerance")
    p.add_argument("--write-baseline", default=None, metavar="FILE",
                   help="write the per-program baseline JSON and exit 0 "
                        "(refreshing the baseline is a deliberate act)")
    p.add_argument("--tol", type=float, default=None,
                   help="baseline tolerance band (default "
                        "MXTPU_FLOPCHECK_TOL, the baseline's own, or 0.1)")
    p.add_argument("--memcheck-baseline", default=None, metavar="FILE",
                   help="ALSO run the memcheck gate from the same "
                        "compiled programs (the combined compile-once CI "
                        "gate; zoo programs only)")
    p.add_argument("--commscheck-baseline", default=None, metavar="FILE",
                   help="ALSO run the commscheck gate from the same "
                        "compiled programs")
    p.add_argument("--json", action="store_true", help="JSON output")
    p.add_argument("--list", action="store_true",
                   help="list zoo models and exit")
    p.add_argument("--quiet", action="store_true",
                   help="suppress progress lines")
    args = p.parse_args(argv)
    if args.list:
        for n in sorted(ZOO):
            print(n)
        return 0
    if not (args.zoo or args.models or args.sharded):
        p.error("nothing to check: pass --zoo, --models or --sharded")
    names = ([s.strip() for s in args.models.split(",") if s.strip()]
             if args.models else None)
    log = (lambda m: None) if (args.quiet or args.json) \
        else (lambda m: print(m, file=sys.stderr))
    combined = bool(args.memcheck_baseline or args.commscheck_baseline)

    # compile phase: ONE compile per program; when the combined gate is
    # on, the executables are kept and fed to all three analyzers
    t0 = time.time()
    zoo_progs, sharded_progs = [], []
    if args.zoo or args.models:
        zoo_progs = list(compiled_zoo_programs(
            names=names, k=args.k, guard=not args.no_guard, log=log))
    if args.sharded:
        sharded_progs = list(compiled_sharded_programs(
            n_devices=args.devices, k=args.k, log=log))
    compile_s = time.time() - t0
    n_progs = len(zoo_progs) + len(sharded_progs)
    n_analyzers = 1 + (1 if args.memcheck_baseline else 0) \
        + (1 if args.commscheck_baseline else 0)
    log("flopcheck: compiled %d program(s) once in %.1fs — %d analyzer(s)"
        " share them (a per-analyzer sweep would have paid ~%.1fs)"
        % (n_progs, compile_s, n_analyzers, n_analyzers * compile_s))

    lint_kw = {}
    if args.hot_frac is not None:
        lint_kw["hot_threshold"] = args.hot_frac
    if args.min_mfu is not None:
        lint_kw["mfu_floor"] = args.min_mfu
    findings, reports = [], {}
    fs, reps = check_zoo(programs=zoo_progs, **lint_kw)
    findings += fs
    reports.update(reps)
    fs, reps = check_sharded(programs=sharded_progs, **lint_kw)
    findings += fs
    reports.update(reps)

    if args.write_baseline:
        write_baseline(reports, args.write_baseline, tol=args.tol)
        log("flopcheck: baseline written to %s (%d programs)"
            % (args.write_baseline, len(reports)))
        return 0
    failures, notes = [], []
    if args.baseline:
        # compare_baseline already fails hlo_unavailable reports
        failures, notes = compare_baseline(reports, args.baseline,
                                           tol=args.tol)
    else:
        # no baseline gate running: the absence-of-evidence contract
        # still holds — an audit that never saw any HLO must not pass
        for n in sorted(reports):
            if reports[n].hlo_unavailable:
                failures.append(
                    "%s: compiled HLO text unavailable on this backend — "
                    "nothing was audited; refusing to pass on absence of "
                    "evidence" % n)

    if args.memcheck_baseline:
        by_model = {}
        for rec in zoo_progs:
            by_model.setdefault(rec[0].split("/")[0], []).append(rec)
        mfs, mfail, mnotes = _combined_memcheck(
            by_model, args.memcheck_baseline, args.tol)
        findings += mfs
        failures += ["[memcheck] " + f for f in mfail]
        notes += ["[memcheck] " + n for n in mnotes]
    if args.commscheck_baseline:
        cfs, cfail, cnotes = _combined_commscheck(
            zoo_progs + sharded_progs, args.commscheck_baseline, args.tol)
        findings += cfs
        failures += ["[commscheck] " + f for f in cfail]
        notes += ["[commscheck] " + n for n in cnotes]

    bad = unsuppressed(findings)
    if args.json:
        import jax
        print(json.dumps({
            "platform": jax.devices()[0].platform,
            "compile_seconds": round(compile_s, 2),
            "analyzers_sharing_compile": n_analyzers,
            "programs": {n: r.as_dict() for n, r in sorted(reports.items())},
            "findings": [f.as_dict() for f in findings],
            "suppressed": len(findings) - len(bad),
            "baseline_failures": failures,
            "baseline_notes": notes,
        }, indent=2))
    else:
        report_table(reports)
        if args.hotspots:
            hotspot_table(reports, top=args.hotspots,
                          memory_only=args.memory_bound)
        _tc.report(findings)
        for n in notes:
            print("note: %s" % n)
        for f in failures:
            print("BASELINE REGRESSION: %s" % f)
        print("flopcheck: %d finding(s) (%d suppressed), %d baseline "
              "regression(s) over %d program(s)%s"
              % (len(findings), len(findings) - len(bad), len(failures),
                 len(reports),
                 " [combined gate: %d analyzers, one compile]"
                 % n_analyzers if combined else ""))
    return 1 if (bad or failures) else 0


if __name__ == "__main__":
    import sys
    sys.exit(main())
