"""KVStore: key-value store for data-parallel gradient aggregation.

Re-design of the reference KVStore stack (ref: include/mxnet/kvstore.h,
src/kvstore/kvstore_local.h, comm.h, kvstore_dist.h — SURVEY.md section 2.4).
The single-process semantics are identical: ``push`` groups values by key,
reduces (sums) across the device list, applies the updater (or accumulates),
``pull`` broadcasts the stored value to each output. What changes is the
substrate:

- 'local'/'device': the reference hand-rolls copy+sum across GPUs
  (CommCPU/CommDevice, comm.h:62-373). Here values live as jax.Arrays; the
  reduce is one fused XLA sum — and in the Module fast path gradients never
  pass through host memory at all.
- 'dist_sync'/'dist_device_sync': the reference's ps-lite parameter server
  (ZMQ push/pull to sharded servers) is replaced by the control-plane ring
  (:mod:`mxnet_tpu.dist_ring`): cross-process aggregation is a
  deterministic KV-plane allreduce whose every wait aborts when a peer's
  heartbeat goes stale — a lost worker surfaces as
  :class:`WorkerLostError` in bounded time and the survivors can re-form
  at N-1 (docs/robustness.md "Elastic distributed training"). The legacy
  global-mesh psum transport survives behind
  ``MXTPU_DIST_TRANSPORT=mesh``.
- 'dist_async': bounded-staleness (SSP) push/pull — each worker's pushes
  carry a version; pull blocks ONLY while this worker is more than
  ``MXTPU_KV_STALENESS`` versions ahead of the slowest live peer (the
  reference's fully-async PS, made convergence-safe the Stale Synchronous
  Parallel way).
"""
from __future__ import annotations

import logging
import os
import pickle
import threading
import time

from .base import MXNetError
from .ndarray import NDArray, zeros
from . import optimizer as opt


class KVStoreTimeoutError(MXNetError):
    """A kvstore operation blew its configured deadline (or an injected
    message drop). ``started`` records whether the underlying op had begun:
    pre-op failures (drops) are retried against the configured budget;
    a started-but-stuck op escalates immediately — its abandoned watchdog
    thread may still be participating in a collective, and re-entering the
    same barrier would corrupt the rendezvous."""

    def __init__(self, msg, started=False):
        super().__init__(msg)
        self.started = started


class WorkerLostError(MXNetError):
    """Raised by the degradation policy when peers stay dead across
    consecutive health checks: BSP training cannot make progress, so the
    run should checkpoint (already done at strike 2) and surface."""


from .base import env_float as _env_float


def _run_with_timeout(fn, timeout, site):
    """Run an IDEMPOTENT op under a watchdog: if it makes no progress
    within ``timeout`` seconds, raise KVStoreTimeoutError (the worker
    thread is abandoned — safe only because the op is idempotent and the
    caller retries or escalates)."""
    result = {}
    done = threading.Event()

    def runner():
        try:
            result["v"] = fn()
        except BaseException as e:  # surfaced to the caller below
            result["e"] = e
        finally:
            done.set()

    t = threading.Thread(target=runner, name="mxtpu-kv-watchdog",
                         daemon=True)
    t.start()
    if not done.wait(timeout):
        raise KVStoreTimeoutError(
            "%s: no progress after %.1fs deadline; a peer may be dead or "
            "partitioned — check num_dead_node() and resume from the last "
            "checkpoint" % (site, timeout), started=True)
    if "e" in result:
        raise result["e"]
    return result.get("v")


class KVStore(object):
    """Single-process KVStore (types 'local', 'device')."""

    def __init__(self, kv_type="local"):
        self._type = kv_type
        self._store = {}
        self._updater = None
        # fault policy (docs/robustness.md): env-seeded, overridable via
        # set_fault_policy. timeout=None disables deadlines.
        self._timeout = _env_float("MXTPU_KV_TIMEOUT", None)
        self._retries = int(_env_float("MXTPU_KV_RETRIES", 2))
        self._backoff = _env_float("MXTPU_KV_BACKOFF", 0.02)
        self._backoff_max = _env_float("MXTPU_KV_BACKOFF_MAX", 0.5)
        self._health_interval = _env_float("MXTPU_KV_HEALTH_INTERVAL", 10.0)
        self._dead_timeout = _env_float("MXTPU_KV_DEAD_TIMEOUT", 60.0)
        self._dead_strikes = 0
        self._last_health = None

    def set_fault_policy(self, timeout="unset", retries=None, backoff=None,
                         backoff_max=None, health_interval=None,
                         dead_timeout=None):
        """Configure op deadlines, retry budget, backoff and health-check
        cadence (env defaults: MXTPU_KV_TIMEOUT / _RETRIES / _BACKOFF /
        _BACKOFF_MAX / _HEALTH_INTERVAL / _DEAD_TIMEOUT)."""
        if timeout != "unset":
            self._timeout = timeout
        if retries is not None:
            self._retries = int(retries)
        if backoff is not None:
            self._backoff = float(backoff)
        if backoff_max is not None:
            self._backoff_max = float(backoff_max)
        if health_interval is not None:
            self._health_interval = float(health_interval)
        if dead_timeout is not None:
            self._dead_timeout = float(dead_timeout)

    def _robust(self, op, fn, idempotent=False):
        """Run a kvstore op with the configured retry/backoff and (for
        idempotent ops) watchdog deadline. Only PRE-OP failures are
        retried — injected transients and drops, which fire before the op
        runs; budget exhaustion raises MXNetError naming the op and
        attempt count. A started-but-stuck op (watchdog timeout) escalates
        immediately: its abandoned thread may still be inside a
        distributed barrier, and re-entering the collective would corrupt
        the rendezvous. Non-idempotent ops (push/pull) that complete but
        exceed the deadline only warn: retrying a completed push would
        double-apply the gradient."""
        from . import faults as _faults
        site = "kvstore.%s" % op
        attempt = 0
        while True:
            attempt += 1
            try:
                act = _faults.fire(site)
                if act == "drop":
                    raise KVStoreTimeoutError(
                        "%s: message dropped (injected)" % site)
                if idempotent and self._timeout:
                    return _run_with_timeout(fn, self._timeout, site)
                t0 = time.monotonic()
                out = fn()
                elapsed = time.monotonic() - t0
                if self._timeout and elapsed > self._timeout:
                    logging.warning(
                        "%s completed but took %.2fs (deadline %.2fs) — "
                        "peers may be degrading; check num_dead_node()",
                        site, elapsed, self._timeout)
                return out
            except (KVStoreTimeoutError,
                    _faults.InjectedTransientFault) as e:
                if getattr(e, "started", False):
                    raise MXNetError(
                        "%s timed out after it started (attempt %d): %s"
                        % (site, attempt, e)) from e
                if attempt > self._retries:
                    raise MXNetError(
                        "%s failed after %d attempts (retry budget %d "
                        "exhausted): %s" % (site, attempt, self._retries,
                                            e)) from e
                delay = min(self._backoff * (2.0 ** (attempt - 1)),
                            self._backoff_max)
                logging.warning("%s: transient failure (attempt %d/%d), "
                                "retrying in %.3fs: %s", site, attempt,
                                self._retries + 1, delay, e)
                if delay > 0:
                    time.sleep(delay)

    def check_health(self, on_degraded=None, force=False):
        """The dead-node degradation policy: feed ``num_dead_node`` into a
        strike counter — strike 1 warns, strike 2 warns and runs
        ``on_degraded`` (fit passes an emergency-checkpoint closure),
        strike 3+ raises :class:`WorkerLostError`. A healthy scan resets
        the strikes. Scans are throttled to one per
        ``MXTPU_KV_HEALTH_INTERVAL`` seconds unless ``force``."""
        from . import faults as _faults
        now = time.monotonic()
        if (not force and self._last_health is not None
                and now - self._last_health < self._health_interval):
            return 0
        self._last_health = now
        dead = self.num_dead_node(0, timeout_sec=self._dead_timeout)
        act = _faults.fire("kvstore.dead_node")
        if act and isinstance(act, str) and act.startswith("dead:"):
            dead = max(dead, int(act.split(":", 1)[1]))
        if not dead:
            self._dead_strikes = 0
            return 0
        self._dead_strikes += 1
        if self._dead_strikes == 1:
            logging.warning(
                "kvstore: %d dead worker(s) detected (strike 1/3: warn)",
                dead)
        elif self._dead_strikes == 2:
            logging.warning(
                "kvstore: %d worker(s) still dead (strike 2/3: emergency "
                "checkpoint)", dead)
            if on_degraded is not None:
                on_degraded()
        else:
            msg = ("%d dead worker(s) across %d consecutive health checks; "
                   "BSP training cannot progress — restart from the last "
                   "checkpoint (resume='auto') with a healthy worker set"
                   % (dead, self._dead_strikes))
            # post-mortem before the escalation unwinds: the flight
            # recorder's dump never raises (docs/observability.md)
            from .obs import flight as _flight
            _flight.dump("WorkerLostError: %s" % msg,
                         extra={"dead_workers": dead,
                                "strikes": self._dead_strikes,
                                "rank": self.rank})
            raise WorkerLostError(msg)
        return dead

    @property
    def type(self):
        return self._type

    @property
    def rank(self):
        return 0

    @property
    def num_workers(self):
        return 1

    # ------------------------------------------------------------------
    def init(self, key, value):
        keys, values = _key_value(key, value)
        for k, vlist in zip(keys, values):
            if k in self._store:
                raise MXNetError("init: key %r already initialized" % (k,))
            self._store[k] = self._init_value(vlist[0].copy())

    def _init_value(self, value):
        """Hook: dist stores broadcast rank 0's copy so every worker starts
        from ONE authoritative value (ref: the server's single stored
        weight, kvstore_dist_server.h)."""
        return value

    def _cross_reduce(self, merged):
        """Hook: dist stores sum the locally-reduced value across workers."""
        return merged

    def push(self, key, value, priority=0):
        self._robust("push", lambda: self._do_push(key, value, priority))

    def _do_push(self, key, value, priority=0):
        keys, values = _key_value(key, value)
        for k, vlist in zip(keys, values):
            if k not in self._store:
                raise MXNetError("push: key %r not initialized" % (k,))
            # reduce across the device list (ref: comm_->Reduce,
            # kvstore_local.h:95-113) — one fused XLA sum
            merged = vlist[0].data
            for v in vlist[1:]:
                merged = merged + v.data
            merged = self._cross_reduce(merged)
            merged_nd = NDArray(merged)
            if self._updater is not None:
                self._updater(k, merged_nd, self._store[k])
            else:
                # no updater: stored <- merged (ref: kvstore_local.h Push
                # CopyFromTo path — push replaces with the reduced value)
                self._store[k]._set_data(merged)

    def pull(self, key, out=None, priority=0):
        assert out is not None
        self._robust("pull", lambda: self._do_pull(key, out, priority))

    def _do_pull(self, key, out, priority=0):
        keys, outs = _key_value(key, out)
        for k, olist in zip(keys, outs):
            if k not in self._store:
                raise MXNetError("pull: key %r not initialized" % (k,))
            src = self._store[k]
            for o in olist:
                src.copyto(o)

    # ------------------------------------------------------------------
    def set_optimizer(self, optimizer):
        """Use this optimizer as the updater (serialized round-trip kept for
        parity with the controller-command path, kvstore.py:226)."""
        optim_str = pickle.dumps(optimizer)
        self._set_updater(opt.get_updater(pickle.loads(optim_str)))

    def _set_updater(self, updater):
        self._updater = updater

    def _barrier(self):
        pass

    def barrier(self):
        """Block until every worker arrives (no-op single-process).
        Idempotent, so it runs under the watchdog deadline and retry
        budget when MXTPU_KV_TIMEOUT is set."""
        self._robust("barrier", self._barrier, idempotent=True)

    def save_optimizer_states(self, fname):
        """Returns the serialized bytes (see Module.save_optimizer_states:
        checkpoint manifests checksum the intended payload)."""
        if self._updater is None:
            raise MXNetError("Cannot save states for distributed training")
        from .model import atomic_write_bytes
        data = self._updater.get_states()
        atomic_write_bytes(fname, data)
        return data

    def load_optimizer_states(self, fname):
        if self._updater is None:
            raise MXNetError("Cannot load states for distributed training")
        from .model import apply_optimizer_states
        apply_optimizer_states(self._updater.set_states, fname)

    def num_dead_node(self, node_id, timeout_sec=60):
        """ref: kvstore_dist.h:159-168 — dead-node count surfaced to user
        scripts. Single-process stores have no peers, so report 0; the
        dist_sync store overrides this with a coordination-service
        heartbeat scan."""
        return 0


class _Heartbeat(object):
    """Worker liveness over the jax.distributed coordination service —
    the ps-lite heartbeat analog (ref: ps::Postoffice::GetDeadNodes used at
    kvstore_dist.h:159-168). Each worker's daemon thread stamps
    ``mxtpu_hb/<rank>`` every ``interval`` seconds; peers count ranks whose
    stamp is stale. Publishing piggybacks the already-running rendezvous
    server: no extra sockets, no extra ports."""

    KEY = "mxtpu_hb/%d"

    def __init__(self, rank, interval=2.0, startup_grace=None):
        self.rank = rank
        self.interval = interval
        self.startup_grace = startup_grace
        self._started = time.time()
        self._seen = set()  # ranks whose beat we have read at least once
        self._stop = None
        client = self._client()
        if client is None:
            return
        import threading
        self._stop = threading.Event()

        def beat():
            while not self._stop.wait(self.interval):
                self._publish(client)
        self._publish(client)
        t = threading.Thread(target=beat, name="mxtpu-heartbeat", daemon=True)
        t.start()

    @staticmethod
    def _client():
        try:
            from jax._src import distributed
            return distributed.global_state.client
        except Exception:
            return None

    def _publish(self, client):
        import time
        key = self.KEY % self.rank
        stamp = repr(time.time())
        try:
            from .dist_ring import DIST_HEALTH
            DIST_HEALTH.heartbeats += 1
        except Exception:
            pass
        try:
            client.key_value_set(key, stamp, allow_overwrite=True)
        except TypeError:            # older jaxlib: no overwrite kwarg
            try:
                client.key_value_delete(key)
            except Exception:
                pass
            try:
                client.key_value_set(key, stamp)
            except Exception:
                pass
        except Exception:
            pass

    def dead_nodes(self, size, timeout_sec):
        client = self._client()
        if client is None or size <= 1:
            return 0
        now = time.time()
        # a peer that has never published is "not up yet", not dead: during
        # rendezvous the slower ranks haven't stamped their first beat, and
        # counting them dead made every startup look like an outage. Only
        # after the startup grace (default: the staleness timeout itself)
        # does silence-from-birth count as death.
        grace = (self.startup_grace if self.startup_grace is not None
                 else timeout_sec)
        # ONE dir scan returns every published beat (this jaxlib has no
        # key_value_try_get; per-key blocking reads would serialize N
        # timeouts)
        stamps = {}
        try:
            got = client.key_value_dir_get(self.KEY.rsplit("%", 1)[0])
            items = got.items() if hasattr(got, "items") else got
            for k, v in items:
                try:
                    stamps[int(str(k).rsplit("/", 1)[1])] = float(v)
                except (ValueError, IndexError):
                    pass
        except Exception:
            return 0                 # plane unreadable: cannot judge peers
        dead = 0
        for r in range(size):
            if r == self.rank:
                continue
            if r in stamps:
                self._seen.add(r)
                if now - stamps[r] > timeout_sec:
                    dead += 1
            elif r in self._seen or now - self._started > grace:
                dead += 1
        return dead

    def stop(self):
        if self._stop is not None:
            self._stop.set()


_HB = None


def _shared_heartbeat(rank):
    """One heartbeat thread per process, stopped at exit — repeated
    KVStore creation must not accumulate beat threads."""
    global _HB
    if _HB is None:
        import atexit
        _HB = _Heartbeat(rank)
        atexit.register(_HB.stop)
    return _HB


class KVStoreDistSync(KVStore):
    """BSP data-parallel store over the jax.distributed control plane.

    Within one process this behaves exactly like 'local'; across processes
    the locally-reduced value is summed over the control-plane ring
    (:mod:`mxnet_tpu.dist_ring`) — deterministic member-order sum, so
    every worker computes the bitwise-identical aggregate (ref semantics:
    kvstore_dist.h sync mode, kvstore_dist_server.h:164-198). The ring is
    also what makes the store ELASTIC: any wait on a dead peer raises
    :class:`WorkerLostError` in bounded time, and :meth:`reform` rebuilds
    the membership at N-1 so fit can continue (docs/robustness.md
    "Elastic distributed training"). ``MXTPU_DIST_TRANSPORT=mesh``
    selects the legacy global-device-mesh psum transport instead (needs
    Gloo on CPU; NOT elastic — a dead peer wedges the collective).
    """

    def __init__(self, kv_type="dist_sync"):
        super().__init__(kv_type)
        self._rank, self._size = _dist_rank_size()
        self._gmesh = None
        self._sum_fn = None
        self._transport = os.environ.get("MXTPU_DIST_TRANSPORT", "ring")
        self._ring = None
        if self._size > 1 and self._transport != "mesh":
            from .dist_ring import shared_ring
            self._ring = shared_ring()
        self._heartbeat = (_shared_heartbeat(self._rank)
                           if self._size > 1 else None)
        self.max_reforms = int(_env_float("MXTPU_KV_MAX_REFORMS", 2))
        #: dist_sync is BSP: nobody is ever stale (Speedometer suffix)
        self.staleness_lag = 0

    def num_dead_node(self, node_id, timeout_sec=60):
        """Count workers whose coordination-service heartbeat is stale
        (ref contract: kvstore_dist.h:159-168 GetDeadNodes)."""
        if self._heartbeat is None:
            return 0
        return self._heartbeat.dead_nodes(self._size, timeout_sec)

    @property
    def rank(self):
        return self._rank

    @property
    def num_workers(self):
        """LIVE worker count: the ring membership size, which shrinks on
        re-form (rescale_grad and throughput scaling read this)."""
        if self._ring is not None:
            return len(self._ring.members)
        return self._size

    @property
    def worker_index(self):
        """This worker's logical position in the live membership — the
        data-shard index. ``rank`` stays the immutable process id;
        after a re-form the surviving ranks re-pack into 0..N-2 HERE."""
        if self._ring is not None:
            return self._ring.index
        return self._rank

    @property
    def reforms(self):
        """Ring re-forms survived so far (== ring generation)."""
        return self._ring.gen if self._ring is not None else 0

    def _barrier(self):
        if self._size == 1:
            return
        if self._ring is not None:
            self._ring.barrier()
            return
        from jax.experimental import multihost_utils
        multihost_utils.sync_global_devices("mxnet_tpu_kvstore_barrier")

    def _do_push(self, key, value, priority=0):
        from . import faults as _faults
        # "delay" rules sleep inside fire(): a slow network push
        _faults.fire("kv.push_delay")
        super()._do_push(key, value, priority)

    # ------------------------------------------------------------------
    def _cross_sum(self, value):
        """Sum a host value across all worker processes (the ps-lite server
        aggregation, ref kvstore_dist_server.h:164-198). BSP contract:
        every worker must call push with the same keys in the same
        order."""
        if self._size == 1:
            return value
        import jax.numpy as jnp
        import numpy as np
        if self._ring is not None:
            return jnp.asarray(self._ring.allreduce_sum(np.asarray(value)))
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P
        if self._gmesh is None:
            from .parallel.mesh import global_data_mesh
            self._gmesh = global_data_mesh("worker")
        if self._sum_fn is None:
            repl = NamedSharding(self._gmesh, P())
            self._sum_fn = jax.jit(lambda a: jnp.sum(a, axis=0),
                                   out_shardings=repl)
        sharded = NamedSharding(self._gmesh, P("worker"))
        local = np.asarray(value)
        n_local = jax.local_device_count()
        # the worker's value rides its FIRST device slot, zeros elsewhere —
        # the sum counts each worker exactly once with no dtype-changing
        # division (integer pushes stay integers)
        zero = np.zeros_like(local)
        tile = np.stack([local if j == 0 else zero for j in range(n_local)])
        garr = jax.make_array_from_process_local_data(sharded, tile)
        out = self._sum_fn(garr)
        return jnp.asarray(np.asarray(out))

    # the cross-worker aggregation slots into the base push/init via hooks:
    # every worker applies the identical updater to the identical aggregate
    # of one authoritative initial value, so replicas never diverge
    def _cross_reduce(self, merged):
        return self._cross_sum(merged)

    def _init_value(self, value):
        if self._size == 1:
            return value
        import jax.numpy as jnp
        if self._ring is not None:
            import numpy as np
            arr = self._ring.broadcast(np.asarray(value.data), root_index=0)
            value._set_data(jnp.asarray(arr))
            return value
        from .parallel.mesh import global_data_mesh, host_broadcast0
        if self._gmesh is None:
            self._gmesh = global_data_mesh("worker")
        value._set_data(jnp.asarray(host_broadcast0(self._gmesh,
                                                    value.data)))
        return value

    # ------------------------------------------------------------------
    # elastic membership (docs/robustness.md "Elastic distributed
    # training")
    def grad_reduce(self, vec):
        """Cross-worker sum of a flat host gradient vector — the fused
        TrainStep's in-scan host hook (ring transport only)."""
        if self._ring is None:
            return vec
        return self._ring.allreduce_sum(vec)

    def broadcast_bytes(self, payload, root_index=0):
        """Raw-bytes broadcast from the logical leader (checkpoint
        adoption after a re-form)."""
        if self._ring is None:
            return payload
        return self._ring.broadcast_bytes(payload, root_index=root_index)

    def reform(self):
        """Re-form the ring around the live members (plus any pending
        joiners); returns the new member list. Raises WorkerLostError
        when the store has no elastic transport, and surfaces (with a
        flight dump) once ``max_reforms`` (MXTPU_KV_MAX_REFORMS) is
        exhausted — callers check :attr:`reforms` BEFORE invoking."""
        if self._ring is None:
            raise WorkerLostError(
                "worker lost and no elastic transport: the '%s' transport "
                "cannot re-form (use MXTPU_DIST_TRANSPORT=ring)"
                % self._transport)
        return self._ring.reform()

    def pending_joiners(self):
        return self._ring.poll_joiners() if self._ring is not None else []

    def join(self, timeout=None):
        """Late-worker entry: request admission and block until the
        incumbents re-form us in at an epoch boundary; then warm-pull
        current params (kvstore broadcast) before the first step."""
        if self._ring is None:
            raise WorkerLostError("join requires the ring transport")
        return self._ring.request_join(timeout)

    def liveness_table(self):
        return (self._ring.liveness_table()
                if self._ring is not None else {})


class KVStoreDistAsync(KVStore):
    """Bounded-staleness (SSP) push/pull — the reference's fully-async
    parameter server (src/kvstore/kvstore_dist_server.h async mode) made
    convergence-safe the Stale Synchronous Parallel way.

    Every worker owns a per-key record on the control plane:
    ``(version, last_push, cumulative_sum)``, overwritten in place on
    each push (one key per worker per parameter — no unbounded queue).
    ``push`` never blocks. ``pull`` blocks ONLY while this worker is
    more than S = ``MXTPU_KV_STALENESS`` versions ahead of the slowest
    LIVE peer (dead laggards are dropped from the window — async
    training tolerates loss by design); a persistent stall ends in
    :class:`KVStoreTimeoutError`, never a hang.

    Aggregation at pull time: with an updater the store applies
    ``delta = sum_of_visible_cumulatives - already_applied`` (each
    worker's contribution lands exactly once, whatever interleaving);
    without one the store becomes the sum of each worker's latest
    visible push (the dist_sync closed form when everyone has pushed
    the same number of times).

    ``_plane=(client, rank, size)`` injects an in-memory control plane
    for tier-1 thread tests; real runs derive it from
    ``jax.distributed``.
    """

    def __init__(self, kv_type="dist_async", _plane=None, _ns="mxasync"):
        super().__init__(kv_type)
        self._ns = _ns
        if _plane is not None:
            self._client, self._rank, self._size = _plane
            self._heartbeat = None
        else:
            self._rank, self._size = _dist_rank_size()
            self._client = None
            self._heartbeat = None
            if self._size > 1:
                from .dist_ring import CoordClient
                from jax._src.distributed import global_state
                self._client = CoordClient(global_state.client)
                self._heartbeat = _shared_heartbeat(self._rank)
        self.staleness = int(_env_float("MXTPU_KV_STALENESS", 4))
        self._poll = _env_float("MXTPU_DIST_POLL", 0.005)
        self._pull_timeout = _env_float("MXTPU_DIST_OP_TIMEOUT", 120.0)
        self._ver = {}        # key -> this worker's push count
        self._last = {}       # key -> np array of the latest local push
        self._cum = {}        # key -> np cumulative sum of local pushes
        self._applied = {}    # key -> np total already folded into store
        self._dead_ranks = set()
        self.staleness_lag = 0

    @property
    def rank(self):
        return self._rank

    @property
    def num_workers(self):
        return self._size - len(self._dead_ranks)

    @property
    def worker_index(self):
        return self._rank

    def num_dead_node(self, node_id, timeout_sec=60):
        if self._heartbeat is not None:
            return self._heartbeat.dead_nodes(self._size, timeout_sec)
        return len(self._dead_ranks)

    # -- control-plane records --
    def _kpath(self, kind, k, rank=None):
        p = "%s/%s/%s" % (self._ns, kind, k)
        return p if rank is None else p + "/%d" % rank

    @staticmethod
    def _enc_state(ver, last, cum):
        import io as _io
        import struct
        import numpy as np
        bio = _io.BytesIO()
        bio.write(struct.pack("<q", int(ver)))
        np.lib.format.write_array(bio, np.ascontiguousarray(last),
                                  allow_pickle=False)
        np.lib.format.write_array(bio, np.ascontiguousarray(cum),
                                  allow_pickle=False)
        return bio.getvalue()

    @staticmethod
    def _dec_state(data):
        import io as _io
        import struct
        import numpy as np
        bio = _io.BytesIO(data)
        ver = struct.unpack("<q", bio.read(8))[0]
        last = np.lib.format.read_array(bio, allow_pickle=False)
        cum = np.lib.format.read_array(bio, allow_pickle=False)
        return ver, last, cum

    def _publish_state(self, k):
        if self._client is None:
            return
        self._client.set(self._kpath("v", k, self._rank),
                         self._enc_state(self._ver[k], self._last[k],
                                         self._cum[k]))

    def _peer_states(self, k):
        """Latest-visible (version, last, cum) per rank — DEAD ranks
        included: their landed contributions stay in the aggregate (only
        the staleness window stops gating on them). An unpublished rank
        reads as version 0 with zero contributions."""
        import numpy as np
        zero = np.zeros_like(self._cum[k])
        out = {r: (0, zero, zero) for r in range(self._size)}
        out[self._rank] = (self._ver[k], self._last[k], self._cum[k])
        if self._client is None:
            return out
        for key, data in self._client.dir(self._kpath("v", k) + "/").items():
            try:
                r = int(key.rsplit("/", 1)[1])
            except ValueError:
                continue
            if r == self._rank:
                continue
            out[r] = self._dec_state(data)
        return out

    # -- init/push/pull --
    def init(self, key, value):
        import numpy as np
        keys, values = _key_value(key, value)
        for k, vlist in zip(keys, values):
            if k in self._store:
                raise MXNetError("init: key %r already initialized" % (k,))
            v = vlist[0].copy()
            if self._client is not None and self._size > 1:
                # rank 0's copy is authoritative (the server's single
                # stored weight, ref kvstore_dist_server.h)
                ikey = self._kpath("init", k)
                if self._rank == 0:
                    from .dist_ring import _encode_array
                    self._client.set(ikey,
                                     _encode_array(np.asarray(v.data)))
                else:
                    import jax.numpy as jnp
                    from .dist_ring import _decode_array
                    data = self._blocking_get(ikey)
                    v._set_data(jnp.asarray(_decode_array(data)))
            arr = np.asarray(v.data)
            self._store[k] = v
            self._ver[k] = 0
            self._last[k] = np.zeros_like(arr)
            self._cum[k] = np.zeros_like(arr)
            self._applied[k] = np.zeros_like(arr)
            self._publish_state(k)

    def _blocking_get(self, key):
        deadline = time.monotonic() + self._pull_timeout
        while True:
            v = self._client.get(key)
            if v is not None:
                return v
            if time.monotonic() >= deadline:
                raise KVStoreTimeoutError(
                    "dist_async: %s not published within %.0fs (is rank 0 "
                    "up?)" % (key, self._pull_timeout), started=True)
            if self._poll:
                time.sleep(self._poll)

    def _do_push(self, key, value, priority=0):
        from . import faults as _faults
        import numpy as np
        _faults.fire("kv.push_delay")
        keys, values = _key_value(key, value)
        for k, vlist in zip(keys, values):
            if k not in self._store:
                raise MXNetError("push: key %r not initialized" % (k,))
            merged = vlist[0].data
            for v in vlist[1:]:
                merged = merged + v.data
            m = np.asarray(merged)
            self._ver[k] += 1
            self._last[k] = m
            self._cum[k] = self._cum[k] + m
            self._publish_state(k)       # overwrite in place; NON-blocking

    def _do_pull(self, key, out, priority=0):
        keys, outs = _key_value(key, out)
        for k, olist in zip(keys, outs):
            if k not in self._store:
                raise MXNetError("pull: key %r not initialized" % (k,))
            self._refresh(k)
            src = self._store[k]
            for o in olist:
                src.copyto(o)

    def _refresh(self, k):
        """Enforce the staleness window, then fold the visible state of
        every live peer into the stored value."""
        import numpy as np
        deadline = time.monotonic() + self._pull_timeout
        while True:
            states = self._peer_states(k)
            # the window gates on LIVE peers only; dead ranks' landed
            # contributions still aggregate below
            min_ver = min(v[0] for r, v in states.items()
                          if r not in self._dead_ranks)
            lag = self._ver[k] - min_ver
            self.staleness_lag = max(0, lag)
            try:
                from .dist_ring import DIST_HEALTH
                DIST_HEALTH.staleness_lag = self.staleness_lag
            except Exception:
                pass
            if lag <= self.staleness:
                break
            laggards = [r for r, v in states.items()
                        if self._ver[k] - v[0] > self.staleness
                        and r != self._rank and r not in self._dead_ranks]
            dead = [r for r in laggards
                    if self._client is not None
                    and not self._client.alive(r)]
            if dead:
                # async tolerates loss: a dead laggard stops gating the
                # window (its landed contributions remain in the sums)
                logging.warning(
                    "dist_async: dropping dead laggard worker(s) %s from "
                    "the staleness window for key %r", dead, k)
                self._dead_ranks.update(dead)
                continue
            if time.monotonic() >= deadline:
                raise KVStoreTimeoutError(
                    "dist_async pull: worker %d is %d versions ahead of "
                    "the slowest peer (window S=%d) and no progress for "
                    "%.0fs" % (self._rank, lag, self.staleness,
                               self._pull_timeout), started=True)
            if self._poll:
                time.sleep(self._poll)
        ranks = sorted(states)
        if self._updater is not None:
            total = None
            for r in ranks:
                c = states[r][2]
                total = c.copy() if total is None else total + c
            delta = total - self._applied[k]
            if np.any(delta != 0):
                import jax.numpy as jnp
                self._updater(k, NDArray(jnp.asarray(delta)),
                              self._store[k])
            self._applied[k] = total
        else:
            pushed = [states[r][1] for r in ranks if states[r][0] > 0]
            if pushed:
                import jax.numpy as jnp
                total = None
                for p in pushed:
                    total = p.copy() if total is None else total + p
                self._store[k]._set_data(jnp.asarray(total))

    def _barrier(self):
        """Best-effort KV barrier (async training rarely needs one; the
        dist launcher scripts use it around setup/teardown)."""
        if self._client is None or self._size <= 1:
            return
        self._bar_n = getattr(self, "_bar_n", 0) + 1
        prefix = "%s/bar/%d/" % (self._ns, self._bar_n)
        # "ok", not "1": sub-2-byte values segfault jaxlib's dir-get
        self._client.set(prefix + "%d" % self._rank, b"ok")
        deadline = time.monotonic() + self._pull_timeout
        while True:
            have = self._client.dir(prefix)
            missing = [r for r in range(self._size)
                       if r not in self._dead_ranks
                       and (prefix + "%d" % r) not in have]
            if not missing:
                return
            for r in list(missing):
                if not self._client.alive(r):
                    self._dead_ranks.add(r)
            if time.monotonic() >= deadline:
                raise KVStoreTimeoutError(
                    "dist_async barrier %d: missing ranks %s"
                    % (self._bar_n, missing), started=True)
            if self._poll:
                time.sleep(self._poll)


def _dist_rank_size():
    import jax
    try:
        return jax.process_index(), jax.process_count()
    except Exception:
        return 0, 1


def _key_value(key, value):
    """Normalize to (keys, list-of-value-lists) (ref: kvstore.py _ctype_key_value)."""
    if isinstance(key, (int, str)):
        keys = [key]
        values = [value if isinstance(value, (list, tuple)) else [value]]
        return keys, values
    assert len(key) == len(value)
    values = []
    for v in value:
        values.append(v if isinstance(v, (list, tuple)) else [v])
    return list(key), values


def create(name="local"):
    """Create a KVStore (ref: src/kvstore/kvstore.cc:17-45 factory).

    'local'/'device' — single-process multi-device (device-side reduce is
    automatic on the XLA substrate, so both names share one impl).
    'dist_sync'/'dist_device_sync' — BSP over jax.distributed + the
    control-plane ring (elastic; see KVStoreDistSync).
    'dist_async' — bounded-staleness SSP push/pull (see KVStoreDistAsync;
    window MXTPU_KV_STALENESS).
    """
    if not isinstance(name, str):
        raise TypeError("name must be a string")
    if "async" in name:
        return KVStoreDistAsync(name)
    if "dist" in name:
        return KVStoreDistSync(name)
    if name in ("local", "device", "local_allreduce_cpu",
                "local_allreduce_device"):
        return KVStore(name)
    raise MXNetError("unknown kvstore type %r" % name)
