"""KVStore: key-value store for data-parallel gradient aggregation.

Re-design of the reference KVStore stack (ref: include/mxnet/kvstore.h,
src/kvstore/kvstore_local.h, comm.h, kvstore_dist.h — SURVEY.md section 2.4).
The single-process semantics are identical: ``push`` groups values by key,
reduces (sums) across the device list, applies the updater (or accumulates),
``pull`` broadcasts the stored value to each output. What changes is the
substrate:

- 'local'/'device': the reference hand-rolls copy+sum across GPUs
  (CommCPU/CommDevice, comm.h:62-373). Here values live as jax.Arrays; the
  reduce is one fused XLA sum — and in the Module fast path gradients never
  pass through host memory at all.
- 'dist_sync'/'dist_device_sync': the reference's ps-lite parameter server
  (ZMQ push/pull to sharded servers) is replaced by SPMD collectives —
  ``jax.lax.psum`` over the ICI/DCN mesh inside the pjit-ed train step (see
  mxnet_tpu.parallel). This KVStore front-end keeps rank/num_workers/barrier
  semantics over ``jax.distributed`` for the host-side control plane.
- 'dist_async': intentionally NOT supported — fully-async parameter-server
  updates have no idiomatic TPU/SPMD analog (documented gap, SURVEY §5);
  a clear error explains the substitute.
"""
from __future__ import annotations

import logging
import os
import pickle
import threading
import time

from .base import MXNetError, NotImplementedForTPU
from .ndarray import NDArray, zeros
from . import optimizer as opt


class KVStoreTimeoutError(MXNetError):
    """A kvstore operation blew its configured deadline (or an injected
    message drop). ``started`` records whether the underlying op had begun:
    pre-op failures (drops) are retried against the configured budget;
    a started-but-stuck op escalates immediately — its abandoned watchdog
    thread may still be participating in a collective, and re-entering the
    same barrier would corrupt the rendezvous."""

    def __init__(self, msg, started=False):
        super().__init__(msg)
        self.started = started


class WorkerLostError(MXNetError):
    """Raised by the degradation policy when peers stay dead across
    consecutive health checks: BSP training cannot make progress, so the
    run should checkpoint (already done at strike 2) and surface."""


from .base import env_float as _env_float


def _run_with_timeout(fn, timeout, site):
    """Run an IDEMPOTENT op under a watchdog: if it makes no progress
    within ``timeout`` seconds, raise KVStoreTimeoutError (the worker
    thread is abandoned — safe only because the op is idempotent and the
    caller retries or escalates)."""
    result = {}
    done = threading.Event()

    def runner():
        try:
            result["v"] = fn()
        except BaseException as e:  # surfaced to the caller below
            result["e"] = e
        finally:
            done.set()

    t = threading.Thread(target=runner, name="mxtpu-kv-watchdog",
                         daemon=True)
    t.start()
    if not done.wait(timeout):
        raise KVStoreTimeoutError(
            "%s: no progress after %.1fs deadline; a peer may be dead or "
            "partitioned — check num_dead_node() and resume from the last "
            "checkpoint" % (site, timeout), started=True)
    if "e" in result:
        raise result["e"]
    return result.get("v")


class KVStore(object):
    """Single-process KVStore (types 'local', 'device')."""

    def __init__(self, kv_type="local"):
        self._type = kv_type
        self._store = {}
        self._updater = None
        # fault policy (docs/robustness.md): env-seeded, overridable via
        # set_fault_policy. timeout=None disables deadlines.
        self._timeout = _env_float("MXTPU_KV_TIMEOUT", None)
        self._retries = int(_env_float("MXTPU_KV_RETRIES", 2))
        self._backoff = _env_float("MXTPU_KV_BACKOFF", 0.02)
        self._backoff_max = _env_float("MXTPU_KV_BACKOFF_MAX", 0.5)
        self._health_interval = _env_float("MXTPU_KV_HEALTH_INTERVAL", 10.0)
        self._dead_timeout = _env_float("MXTPU_KV_DEAD_TIMEOUT", 60.0)
        self._dead_strikes = 0
        self._last_health = None

    def set_fault_policy(self, timeout="unset", retries=None, backoff=None,
                         backoff_max=None, health_interval=None,
                         dead_timeout=None):
        """Configure op deadlines, retry budget, backoff and health-check
        cadence (env defaults: MXTPU_KV_TIMEOUT / _RETRIES / _BACKOFF /
        _BACKOFF_MAX / _HEALTH_INTERVAL / _DEAD_TIMEOUT)."""
        if timeout != "unset":
            self._timeout = timeout
        if retries is not None:
            self._retries = int(retries)
        if backoff is not None:
            self._backoff = float(backoff)
        if backoff_max is not None:
            self._backoff_max = float(backoff_max)
        if health_interval is not None:
            self._health_interval = float(health_interval)
        if dead_timeout is not None:
            self._dead_timeout = float(dead_timeout)

    def _robust(self, op, fn, idempotent=False):
        """Run a kvstore op with the configured retry/backoff and (for
        idempotent ops) watchdog deadline. Only PRE-OP failures are
        retried — injected transients and drops, which fire before the op
        runs; budget exhaustion raises MXNetError naming the op and
        attempt count. A started-but-stuck op (watchdog timeout) escalates
        immediately: its abandoned thread may still be inside a
        distributed barrier, and re-entering the collective would corrupt
        the rendezvous. Non-idempotent ops (push/pull) that complete but
        exceed the deadline only warn: retrying a completed push would
        double-apply the gradient."""
        from . import faults as _faults
        site = "kvstore.%s" % op
        attempt = 0
        while True:
            attempt += 1
            try:
                act = _faults.fire(site)
                if act == "drop":
                    raise KVStoreTimeoutError(
                        "%s: message dropped (injected)" % site)
                if idempotent and self._timeout:
                    return _run_with_timeout(fn, self._timeout, site)
                t0 = time.monotonic()
                out = fn()
                elapsed = time.monotonic() - t0
                if self._timeout and elapsed > self._timeout:
                    logging.warning(
                        "%s completed but took %.2fs (deadline %.2fs) — "
                        "peers may be degrading; check num_dead_node()",
                        site, elapsed, self._timeout)
                return out
            except (KVStoreTimeoutError,
                    _faults.InjectedTransientFault) as e:
                if getattr(e, "started", False):
                    raise MXNetError(
                        "%s timed out after it started (attempt %d): %s"
                        % (site, attempt, e)) from e
                if attempt > self._retries:
                    raise MXNetError(
                        "%s failed after %d attempts (retry budget %d "
                        "exhausted): %s" % (site, attempt, self._retries,
                                            e)) from e
                delay = min(self._backoff * (2.0 ** (attempt - 1)),
                            self._backoff_max)
                logging.warning("%s: transient failure (attempt %d/%d), "
                                "retrying in %.3fs: %s", site, attempt,
                                self._retries + 1, delay, e)
                if delay > 0:
                    time.sleep(delay)

    def check_health(self, on_degraded=None, force=False):
        """The dead-node degradation policy: feed ``num_dead_node`` into a
        strike counter — strike 1 warns, strike 2 warns and runs
        ``on_degraded`` (fit passes an emergency-checkpoint closure),
        strike 3+ raises :class:`WorkerLostError`. A healthy scan resets
        the strikes. Scans are throttled to one per
        ``MXTPU_KV_HEALTH_INTERVAL`` seconds unless ``force``."""
        from . import faults as _faults
        now = time.monotonic()
        if (not force and self._last_health is not None
                and now - self._last_health < self._health_interval):
            return 0
        self._last_health = now
        dead = self.num_dead_node(0, timeout_sec=self._dead_timeout)
        act = _faults.fire("kvstore.dead_node")
        if act and isinstance(act, str) and act.startswith("dead:"):
            dead = max(dead, int(act.split(":", 1)[1]))
        if not dead:
            self._dead_strikes = 0
            return 0
        self._dead_strikes += 1
        if self._dead_strikes == 1:
            logging.warning(
                "kvstore: %d dead worker(s) detected (strike 1/3: warn)",
                dead)
        elif self._dead_strikes == 2:
            logging.warning(
                "kvstore: %d worker(s) still dead (strike 2/3: emergency "
                "checkpoint)", dead)
            if on_degraded is not None:
                on_degraded()
        else:
            msg = ("%d dead worker(s) across %d consecutive health checks; "
                   "BSP training cannot progress — restart from the last "
                   "checkpoint (resume='auto') with a healthy worker set"
                   % (dead, self._dead_strikes))
            # post-mortem before the escalation unwinds: the flight
            # recorder's dump never raises (docs/observability.md)
            from .obs import flight as _flight
            _flight.dump("WorkerLostError: %s" % msg,
                         extra={"dead_workers": dead,
                                "strikes": self._dead_strikes,
                                "rank": self.rank})
            raise WorkerLostError(msg)
        return dead

    @property
    def type(self):
        return self._type

    @property
    def rank(self):
        return 0

    @property
    def num_workers(self):
        return 1

    # ------------------------------------------------------------------
    def init(self, key, value):
        keys, values = _key_value(key, value)
        for k, vlist in zip(keys, values):
            if k in self._store:
                raise MXNetError("init: key %r already initialized" % (k,))
            self._store[k] = self._init_value(vlist[0].copy())

    def _init_value(self, value):
        """Hook: dist stores broadcast rank 0's copy so every worker starts
        from ONE authoritative value (ref: the server's single stored
        weight, kvstore_dist_server.h)."""
        return value

    def _cross_reduce(self, merged):
        """Hook: dist stores sum the locally-reduced value across workers."""
        return merged

    def push(self, key, value, priority=0):
        self._robust("push", lambda: self._do_push(key, value, priority))

    def _do_push(self, key, value, priority=0):
        keys, values = _key_value(key, value)
        for k, vlist in zip(keys, values):
            if k not in self._store:
                raise MXNetError("push: key %r not initialized" % (k,))
            # reduce across the device list (ref: comm_->Reduce,
            # kvstore_local.h:95-113) — one fused XLA sum
            merged = vlist[0].data
            for v in vlist[1:]:
                merged = merged + v.data
            merged = self._cross_reduce(merged)
            merged_nd = NDArray(merged)
            if self._updater is not None:
                self._updater(k, merged_nd, self._store[k])
            else:
                # no updater: stored <- merged (ref: kvstore_local.h Push
                # CopyFromTo path — push replaces with the reduced value)
                self._store[k]._set_data(merged)

    def pull(self, key, out=None, priority=0):
        assert out is not None
        self._robust("pull", lambda: self._do_pull(key, out, priority))

    def _do_pull(self, key, out, priority=0):
        keys, outs = _key_value(key, out)
        for k, olist in zip(keys, outs):
            if k not in self._store:
                raise MXNetError("pull: key %r not initialized" % (k,))
            src = self._store[k]
            for o in olist:
                src.copyto(o)

    # ------------------------------------------------------------------
    def set_optimizer(self, optimizer):
        """Use this optimizer as the updater (serialized round-trip kept for
        parity with the controller-command path, kvstore.py:226)."""
        optim_str = pickle.dumps(optimizer)
        self._set_updater(opt.get_updater(pickle.loads(optim_str)))

    def _set_updater(self, updater):
        self._updater = updater

    def _barrier(self):
        pass

    def barrier(self):
        """Block until every worker arrives (no-op single-process).
        Idempotent, so it runs under the watchdog deadline and retry
        budget when MXTPU_KV_TIMEOUT is set."""
        self._robust("barrier", self._barrier, idempotent=True)

    def save_optimizer_states(self, fname):
        """Returns the serialized bytes (see Module.save_optimizer_states:
        checkpoint manifests checksum the intended payload)."""
        if self._updater is None:
            raise MXNetError("Cannot save states for distributed training")
        from .model import atomic_write_bytes
        data = self._updater.get_states()
        atomic_write_bytes(fname, data)
        return data

    def load_optimizer_states(self, fname):
        if self._updater is None:
            raise MXNetError("Cannot load states for distributed training")
        from .model import apply_optimizer_states
        apply_optimizer_states(self._updater.set_states, fname)

    def num_dead_node(self, node_id, timeout_sec=60):
        """ref: kvstore_dist.h:159-168 — dead-node count surfaced to user
        scripts. Single-process stores have no peers, so report 0; the
        dist_sync store overrides this with a coordination-service
        heartbeat scan."""
        return 0


class _Heartbeat(object):
    """Worker liveness over the jax.distributed coordination service —
    the ps-lite heartbeat analog (ref: ps::Postoffice::GetDeadNodes used at
    kvstore_dist.h:159-168). Each worker's daemon thread stamps
    ``mxtpu_hb/<rank>`` every ``interval`` seconds; peers count ranks whose
    stamp is stale. Publishing piggybacks the already-running rendezvous
    server: no extra sockets, no extra ports."""

    KEY = "mxtpu_hb/%d"

    def __init__(self, rank, interval=2.0, startup_grace=None):
        self.rank = rank
        self.interval = interval
        self.startup_grace = startup_grace
        self._started = time.time()
        self._seen = set()  # ranks whose beat we have read at least once
        self._stop = None
        client = self._client()
        if client is None:
            return
        import threading
        self._stop = threading.Event()

        def beat():
            while not self._stop.wait(self.interval):
                self._publish(client)
        self._publish(client)
        t = threading.Thread(target=beat, name="mxtpu-heartbeat", daemon=True)
        t.start()

    @staticmethod
    def _client():
        try:
            from jax._src import distributed
            return distributed.global_state.client
        except Exception:
            return None

    def _publish(self, client):
        import time
        key = self.KEY % self.rank
        stamp = repr(time.time())
        try:
            client.key_value_set(key, stamp, allow_overwrite=True)
        except TypeError:            # older jaxlib: no overwrite kwarg
            try:
                client.key_value_delete(key)
            except Exception:
                pass
            try:
                client.key_value_set(key, stamp)
            except Exception:
                pass
        except Exception:
            pass

    def dead_nodes(self, size, timeout_sec):
        client = self._client()
        if client is None or size <= 1:
            return 0
        now = time.time()
        # a peer that has never published is "not up yet", not dead: during
        # rendezvous the slower ranks haven't stamped their first beat, and
        # counting them dead made every startup look like an outage. Only
        # after the startup grace (default: the staleness timeout itself)
        # does silence-from-birth count as death.
        grace = (self.startup_grace if self.startup_grace is not None
                 else timeout_sec)
        dead = 0
        for r in range(size):
            if r == self.rank:
                continue
            try:
                v = client.key_value_try_get(self.KEY % r)
                self._seen.add(r)
                if now - float(v) > timeout_sec:
                    dead += 1
            except Exception:        # no beat published for this rank
                if r in self._seen or now - self._started > grace:
                    dead += 1
        return dead

    def stop(self):
        if self._stop is not None:
            self._stop.set()


_HB = None


def _shared_heartbeat(rank):
    """One heartbeat thread per process, stopped at exit — repeated
    KVStore creation must not accumulate beat threads."""
    global _HB
    if _HB is None:
        import atexit
        _HB = _Heartbeat(rank)
        atexit.register(_HB.stop)
    return _HB


class KVStoreDistSync(KVStore):
    """BSP data-parallel store over the jax.distributed control plane.

    Within one process this behaves exactly like 'local'; across processes
    (multi-host pods) gradient aggregation itself rides the in-step psum
    (mxnet_tpu.parallel.grad_sync) — this object supplies rank/size/barrier
    (ref semantics: kvstore_dist.h sync mode, kvstore_dist_server.h:164-198).
    """

    def __init__(self, kv_type="dist_sync"):
        super().__init__(kv_type)
        self._rank, self._size = _dist_rank_size()
        self._gmesh = None
        self._sum_fn = None
        self._heartbeat = (_shared_heartbeat(self._rank)
                           if self._size > 1 else None)

    def num_dead_node(self, node_id, timeout_sec=60):
        """Count workers whose coordination-service heartbeat is stale
        (ref contract: kvstore_dist.h:159-168 GetDeadNodes)."""
        if self._heartbeat is None:
            return 0
        return self._heartbeat.dead_nodes(self._size, timeout_sec)

    @property
    def rank(self):
        return self._rank

    @property
    def num_workers(self):
        return self._size

    def _barrier(self):
        if self._size > 1:
            import jax
            from jax.experimental import multihost_utils
            multihost_utils.sync_global_devices("mxnet_tpu_kvstore_barrier")

    # ------------------------------------------------------------------
    def _cross_sum(self, value):
        """Sum a host value across all worker processes (the ps-lite server
        aggregation, ref kvstore_dist_server.h:164-198, as one XLA
        reduction over the global device mesh). BSP contract: every worker
        must call push with the same keys in the same order."""
        if self._size == 1:
            return value
        import jax
        import jax.numpy as jnp
        import numpy as np
        from jax.sharding import NamedSharding, PartitionSpec as P
        if self._gmesh is None:
            from .parallel.mesh import global_data_mesh
            self._gmesh = global_data_mesh("worker")
        if self._sum_fn is None:
            repl = NamedSharding(self._gmesh, P())
            self._sum_fn = jax.jit(lambda a: jnp.sum(a, axis=0),
                                   out_shardings=repl)
        sharded = NamedSharding(self._gmesh, P("worker"))
        local = np.asarray(value)
        n_local = jax.local_device_count()
        # the worker's value rides its FIRST device slot, zeros elsewhere —
        # the sum counts each worker exactly once with no dtype-changing
        # division (integer pushes stay integers)
        zero = np.zeros_like(local)
        tile = np.stack([local if j == 0 else zero for j in range(n_local)])
        garr = jax.make_array_from_process_local_data(sharded, tile)
        out = self._sum_fn(garr)
        return jnp.asarray(np.asarray(out))

    # the cross-worker aggregation slots into the base push/init via hooks:
    # every worker applies the identical updater to the identical aggregate
    # of one authoritative initial value, so replicas never diverge
    def _cross_reduce(self, merged):
        return self._cross_sum(merged)

    def _init_value(self, value):
        if self._size == 1:
            return value
        import jax.numpy as jnp
        from .parallel.mesh import global_data_mesh, host_broadcast0
        if self._gmesh is None:
            self._gmesh = global_data_mesh("worker")
        value._set_data(jnp.asarray(host_broadcast0(self._gmesh,
                                                    value.data)))
        return value


def _dist_rank_size():
    import jax
    try:
        return jax.process_index(), jax.process_count()
    except Exception:
        return 0, 1


def _key_value(key, value):
    """Normalize to (keys, list-of-value-lists) (ref: kvstore.py _ctype_key_value)."""
    if isinstance(key, (int, str)):
        keys = [key]
        values = [value if isinstance(value, (list, tuple)) else [value]]
        return keys, values
    assert len(key) == len(value)
    values = []
    for v in value:
        values.append(v if isinstance(v, (list, tuple)) else [v])
    return list(key), values


def create(name="local"):
    """Create a KVStore (ref: src/kvstore/kvstore.cc:17-45 factory).

    'local'/'device' — single-process multi-device (device-side reduce is
    automatic on the XLA substrate, so both names share one impl).
    'dist_sync'/'dist_device_sync' — BSP over jax.distributed + in-step psum.
    'dist_async' — unsupported on TPU (see module docstring).
    """
    if not isinstance(name, str):
        raise TypeError("name must be a string")
    if "async" in name:
        raise NotImplementedForTPU(
            "dist_async parameter-server semantics have no TPU/SPMD analog; "
            "use dist_sync (BSP via psum over ICI). See SURVEY.md section 5.")
    if "dist" in name:
        return KVStoreDistSync(name)
    if name in ("local", "device", "local_allreduce_cpu",
                "local_allreduce_device"):
        return KVStore(name)
    raise MXNetError("unknown kvstore type %r" % name)
