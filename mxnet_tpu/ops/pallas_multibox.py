"""Pallas TPU escape-hatch kernel: the MultiBox greedy-NMS suppression
sweep (docs/perf.md "Packed accumulators" — MultiBox A/B).

Why this op is the escape-hatch candidate (per the r4 fusion post-mortem
discipline: hand-fuse ONLY what XLA genuinely cannot): MultiBoxDetection's
suppression is a sequentially-dependent sweep — anchor i may only suppress
anchor j>i if i itself is still alive — which XLA lowers as a k-trip While
loop over HBM-resident (k, k) masks; every trip re-reads the suppression
matrix row and the alive vector. This kernel keeps the IOU matrix, the
class mask and the alive vector VMEM-RESIDENT for the whole sweep: one
pallas_call, one HBM read of the boxes/scores, one write of the final
mask (k = nms_topk ≤ 400 → the (k, k) f32 IOU is ≤ 640 KiB, well inside
the ~16 MiB VMEM envelope).

Gated OFF by default behind ``MXTPU_PALLAS_MULTIBOX`` ("1" on TPU,
"interpret" for CPU tests — the same spelling as MXTPU_FUSE_CONV_BN);
docs/perf.md records the measured A/B. Ship-only-if-it-wins: the knob
stays opt-in until a chip-host measurement shows a win.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


def _nms_kernel(boxes_ref, score_ref, cls_ref, alive_ref, *, nms_thresh,
                force):
    boxes = boxes_ref[...]                       # (k, 4) corners
    score = score_ref[...][:, 0]                 # (k,)
    cls = cls_ref[...][:, 0]                     # (k,)
    k = boxes.shape[0]
    x1, y1, x2, y2 = (boxes[:, i] for i in range(4))
    ix1 = jnp.maximum(x1[:, None], x1[None, :])
    iy1 = jnp.maximum(y1[:, None], y1[None, :])
    ix2 = jnp.minimum(x2[:, None], x2[None, :])
    iy2 = jnp.minimum(y2[:, None], y2[None, :])
    inter = (jnp.maximum(ix2 - ix1, 0.0) * jnp.maximum(iy2 - iy1, 0.0))
    area = jnp.maximum((x2 - x1) * (y2 - y1), 0.0)
    union = area[:, None] + area[None, :] - inter
    iou = jnp.where(union > 0, inter / union, 0.0)
    same = (cls[:, None] == cls[None, :]) | force
    sup = (iou > nms_thresh) & same              # (k, k), VMEM-resident
    later = jax.lax.broadcasted_iota(jnp.int32, (k,), 0)

    def body(i, alive):
        # row i suppresses strictly-later anchors, but only while i
        # itself is still alive — the sequential dependence that keeps
        # this a sweep rather than one reduction
        row = jax.lax.dynamic_slice_in_dim(sup, i, 1, axis=0)[0]
        ai = jax.lax.dynamic_slice_in_dim(alive, i, 1, axis=0)[0]
        return alive & ~(row & ai & (later > i))

    alive = jax.lax.fori_loop(0, k, body, score > 0)
    alive_ref[...] = alive.astype(jnp.float32)[:, None]


@functools.partial(jax.jit,
                   static_argnames=("nms_thresh", "force", "interpret"))
def nms_alive(sboxes, sscore, scls, nms_thresh, force=False,
              interpret=False):
    """Greedy class-aware NMS survival mask over score-sorted anchors:
    ``sboxes`` (k, 4) corners, ``sscore`` (k,), ``scls`` (k,) ->
    float32 (k,) 1.0/0.0 mask, semantics identical to the XLA
    fori_loop formulation in ops/contrib.py (parity-tested)."""
    k = sboxes.shape[0]
    kern = functools.partial(_nms_kernel, nms_thresh=float(nms_thresh),
                             force=bool(force))
    alive = pl.pallas_call(
        kern,
        out_shape=jax.ShapeDtypeStruct((k, 1), jnp.float32),
        interpret=interpret,
    )(sboxes.astype(jnp.float32), sscore.astype(jnp.float32)[:, None],
      scls.astype(jnp.float32)[:, None])
    return alive[:, 0]


def mode():
    """The MXTPU_PALLAS_MULTIBOX knob: '' (off, default), '1' (on-TPU
    compiled kernel), 'interpret' (interpreter — CPU tests/A-B)."""
    import os
    v = os.environ.get("MXTPU_PALLAS_MULTIBOX", "0").strip().lower()
    return "" if v in ("", "0", "false", "off", "no") else v


def enabled():
    return mode() != ""


def interpret_requested():
    return mode() == "interpret" or jax.default_backend() != "tpu"
