"""Spatial operators: GridGenerator, BilinearSampler, SpatialTransformer,
ROIPooling, Correlation.

TPU-native implementations of the reference's CUDA spatial ops
(ref: src/operator/grid_generator-inl.h:318, bilinear_sampler-inl.h:219,
spatial_transformer-inl.h:264, roi_pooling.cc:282, correlation-inl.h:236).
All are gather/segment formulations XLA vectorizes — no scalar loops. The
ROIPooling bins (dynamic per-roi extents) use a masked-max formulation
instead of the reference's pointer arithmetic, keeping shapes static for jit.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..base import attr_bool, attr_float, attr_int, attr_str, attr_tuple, MXNetError
from .registry import OpDef, register, register_def


# ---------------------------------------------------------------------------
# GridGenerator (ref: grid_generator-inl.h) — produces (N, 2, H, W) sampling
# grids with x,y in [-1, 1]
# ---------------------------------------------------------------------------

def _affine_grid(theta, h, w):
    n = theta.shape[0]
    theta = theta.reshape(n, 2, 3)
    ys = jnp.linspace(-1.0, 1.0, h)
    xs = jnp.linspace(-1.0, 1.0, w)
    gy, gx = jnp.meshgrid(ys, xs, indexing="ij")
    ones = jnp.ones_like(gx)
    base = jnp.stack([gx, gy, ones], axis=0).reshape(3, -1)  # (3, H*W)
    out = jnp.einsum("nij,jk->nik", theta, base)             # (N, 2, H*W)
    return out.reshape(n, 2, h, w)


def _grid_gen_infer(attrs, in_shapes):
    tt = attr_str(attrs.get("transform_type", "affine"), "affine")
    data = in_shapes[0]
    if tt == "affine":
        ts = attr_tuple(attrs["target_shape"])
        if data is None:
            raise MXNetError("GridGenerator: data shape required")
        return [(data[0], 6)], [(data[0], 2) + tuple(ts)], []
    if data is None:
        raise MXNetError("GridGenerator: data shape required")
    return [tuple(data)], [tuple(data)], []


@register("GridGenerator", inputs=("data",), infer_shape=_grid_gen_infer)
def _grid_generator(op_ctx, attrs, inputs, aux):
    tt = attr_str(attrs.get("transform_type", "affine"), "affine")
    data = inputs[0]
    if tt == "affine":
        h, w = attr_tuple(attrs["target_shape"])
        return (_affine_grid(data, h, w),)
    if tt == "warp":
        # data: flow (N, 2, H, W) added to the identity grid, normalized
        n, _, h, w = data.shape
        ys = jnp.arange(h, dtype=data.dtype)
        xs = jnp.arange(w, dtype=data.dtype)
        gy, gx = jnp.meshgrid(ys, xs, indexing="ij")
        x = (gx[None] + data[:, 0]) * 2.0 / max(w - 1, 1) - 1.0
        y = (gy[None] + data[:, 1]) * 2.0 / max(h - 1, 1) - 1.0
        return (jnp.stack([x, y], axis=1),)
    raise MXNetError("GridGenerator: unknown transform_type %r" % tt)


# ---------------------------------------------------------------------------
# BilinearSampler (ref: bilinear_sampler-inl.h) — sample data at grid coords,
# zero padding outside [-1, 1]
# ---------------------------------------------------------------------------

def _bilinear_sample(data, grid):
    n, c, h, w = data.shape
    gx = (grid[:, 0] + 1.0) * (w - 1) / 2.0   # (N, Ho, Wo)
    gy = (grid[:, 1] + 1.0) * (h - 1) / 2.0
    x0 = jnp.floor(gx)
    y0 = jnp.floor(gy)
    wx = gx - x0
    wy = gy - y0

    def gather(yi, xi):
        valid = ((xi >= 0) & (xi <= w - 1) & (yi >= 0)
                 & (yi <= h - 1)).astype(data.dtype)
        xc = jnp.clip(xi, 0, w - 1).astype(jnp.int32)
        yc = jnp.clip(yi, 0, h - 1).astype(jnp.int32)
        flat = data.reshape(n, c, h * w)
        idx = (yc * w + xc).reshape(n, 1, -1)
        vals = jnp.take_along_axis(flat, jnp.broadcast_to(
            idx, (n, c, idx.shape[-1])), axis=2)
        vals = vals.reshape(n, c, *xi.shape[1:])
        return vals * valid[:, None]

    v00 = gather(y0, x0)
    v01 = gather(y0, x0 + 1)
    v10 = gather(y0 + 1, x0)
    v11 = gather(y0 + 1, x0 + 1)
    wx_ = wx[:, None]
    wy_ = wy[:, None]
    return ((1 - wy_) * ((1 - wx_) * v00 + wx_ * v01)
            + wy_ * ((1 - wx_) * v10 + wx_ * v11))


def _bilinear_infer(attrs, in_shapes):
    data, grid = in_shapes
    if data is None or grid is None:
        raise MXNetError("BilinearSampler: both input shapes required")
    return [tuple(data), tuple(grid)], \
        [(data[0], data[1], grid[2], grid[3])], []


@register("BilinearSampler", inputs=("data", "grid"),
          infer_shape=_bilinear_infer)
def _bilinear_sampler(op_ctx, attrs, inputs, aux):
    return (_bilinear_sample(inputs[0], inputs[1]),)


# ---------------------------------------------------------------------------
# SpatialTransformer (ref: spatial_transformer-inl.h) — affine loc net output
# -> grid -> bilinear sample
# ---------------------------------------------------------------------------

def _st_infer(attrs, in_shapes):
    data = in_shapes[0]
    ts = attr_tuple(attrs["target_shape"])
    if data is None:
        raise MXNetError("SpatialTransformer: data shape required")
    return [tuple(data), (data[0], 6)], \
        [(data[0], data[1]) + tuple(ts)], []


@register("SpatialTransformer", inputs=("data", "loc"), infer_shape=_st_infer)
def _spatial_transformer(op_ctx, attrs, inputs, aux):
    data, loc = inputs
    h, w = attr_tuple(attrs["target_shape"])
    tt = attr_str(attrs.get("transform_type", "affine"), "affine")
    st = attr_str(attrs.get("sampler_type", "bilinear"), "bilinear")
    if tt != "affine" or st != "bilinear":
        raise MXNetError("SpatialTransformer supports affine+bilinear")
    grid = _affine_grid(loc, h, w)
    return (_bilinear_sample(data, grid),)


# ---------------------------------------------------------------------------
# ROIPooling (ref: roi_pooling.cc) — max pool over per-roi bins; masked-max
# formulation with static shapes
# ---------------------------------------------------------------------------

def _roi_infer(attrs, in_shapes):
    data, rois = in_shapes
    ph, pw = attr_tuple(attrs["pooled_size"])
    if data is None or rois is None:
        raise MXNetError("ROIPooling: both input shapes required")
    return [tuple(data), tuple(rois)], [(rois[0], data[1], ph, pw)], []


@register("ROIPooling", inputs=("data", "rois"), infer_shape=_roi_infer)
def _roi_pooling(op_ctx, attrs, inputs, aux):
    data, rois = inputs
    ph, pw = attr_tuple(attrs["pooled_size"])
    scale = attr_float(attrs.get("spatial_scale", 1.0), 1.0)
    n, c, h, w = data.shape

    def one_roi(roi):
        batch = roi[0].astype(jnp.int32)
        x1 = jnp.round(roi[1] * scale)
        y1 = jnp.round(roi[2] * scale)
        x2 = jnp.round(roi[3] * scale)
        y2 = jnp.round(roi[4] * scale)
        rh = jnp.maximum(y2 - y1 + 1.0, 1.0)
        rw = jnp.maximum(x2 - x1 + 1.0, 1.0)
        bin_h = rh / ph
        bin_w = rw / pw
        fmap = data[batch]                       # (C, H, W)
        iy = jnp.arange(ph)
        ix = jnp.arange(pw)
        hstart = jnp.clip(jnp.floor(iy * bin_h + y1), 0, h - 1)
        hend = jnp.clip(jnp.ceil((iy + 1) * bin_h + y1), 1, h)
        wstart = jnp.clip(jnp.floor(ix * bin_w + x1), 0, w - 1)
        wend = jnp.clip(jnp.ceil((ix + 1) * bin_w + x1), 1, w)
        hh = jnp.arange(h, dtype=jnp.float32)
        ww = jnp.arange(w, dtype=jnp.float32)
        hmask = ((hh[None] >= hstart[:, None])
                 & (hh[None] < hend[:, None]))    # (ph, H)
        wmask = ((ww[None] >= wstart[:, None])
                 & (ww[None] < wend[:, None]))    # (pw, W)
        neg = jnp.array(-jnp.inf, data.dtype)
        masked = jnp.where(hmask[None, :, None, :, None]
                           & wmask[None, None, :, None, :],
                           fmap[:, None, None], neg)  # (C, ph, pw, H, W)
        out = jnp.max(masked, axis=(3, 4))
        return jnp.where(jnp.isneginf(out), 0.0, out)

    return (jax.vmap(one_roi)(rois),)


# ---------------------------------------------------------------------------
# Correlation (ref: correlation-inl.h — FlowNet displacement correlation)
# ---------------------------------------------------------------------------

def _corr_attrs(attrs):
    k = attr_int(attrs.get("kernel_size", 1), 1)
    md = attr_int(attrs.get("max_displacement", 1), 1)
    s1 = attr_int(attrs.get("stride1", 1), 1)
    s2 = attr_int(attrs.get("stride2", 1), 1)
    pad = attr_int(attrs.get("pad_size", 0), 0)
    mult = attr_bool(attrs.get("is_multiply", True), True)
    return k, md, s1, s2, pad, mult


def _corr_infer(attrs, in_shapes):
    k, md, s1, s2, pad, mult = _corr_attrs(attrs)
    d1 = in_shapes[0]
    if d1 is None:
        raise MXNetError("Correlation: data1 shape required")
    n, c, h, w = d1
    ph, pw = h + 2 * pad, w + 2 * pad
    kr = k // 2
    br = md + kr  # border
    out_h = int(jnp.ceil((ph - br * 2) / s1))
    out_w = int(jnp.ceil((pw - br * 2) / s1))
    nbh = md // s2 * 2 + 1
    top_c = nbh * nbh
    return [tuple(d1), tuple(d1)], [(n, top_c, out_h, out_w)], []


@register("Correlation", inputs=("data1", "data2"), infer_shape=_corr_infer)
def _correlation(op_ctx, attrs, inputs, aux):
    k, md, s1, s2, pad, mult = _corr_attrs(attrs)
    d1, d2 = inputs
    n, c, h, w = d1.shape
    kr = k // 2
    br = md + kr
    p1 = jnp.pad(d1, ((0, 0), (0, 0), (pad, pad), (pad, pad)))
    p2 = jnp.pad(d2, ((0, 0), (0, 0), (pad, pad), (pad, pad)))
    ph, pw = h + 2 * pad, w + 2 * pad
    out_h = -((br * 2 - ph) // s1)
    out_w = -((br * 2 - pw) // s1)
    disp = range(-md, md + 1, s2)
    maps = []
    for dy in disp:
        for dx in disp:
            shifted = jnp.roll(p2, shift=(-dy, -dx), axis=(2, 3))
            if mult:
                prod = p1 * shifted
            else:
                prod = jnp.abs(p1 - shifted)
            # kernel window sum (k usually 1)
            if k > 1:
                prod = jax.lax.reduce_window(
                    prod, 0.0, jax.lax.add, (1, 1, k, k), (1, 1, 1, 1),
                    [(0, 0), (0, 0), (kr, kr), (kr, kr)])
            # ref normalizes by sumelems = k*k*channels (correlation-inl.h)
            m = jnp.mean(prod, axis=1) / (k * k)
            maps.append(m)
    out = jnp.stack(maps, axis=1)  # (N, D*D, ph, pw)
    # crop borders and stride
    out = out[:, :, br:br + out_h * s1:s1, br:br + out_w * s1:s1]
    return (out,)
