"""Fused optimizer update operators.

Re-design of the reference's in-graph update ops (ref:
src/operator/optimizer_op-inl.h:425 — sgd_update, sgd_mom_update, adam_update,
rmsprop_update, rmspropalex_update registered as NNVM ops so updates run
device-side). Here each is a single jnp expression XLA fuses into one kernel;
the Module fused train step inlines them into the same jit as fwd+bwd.

All follow the reference semantics. SGD clips the rescaled gradient before
adding weight decay (ref: sgd_update); Adam/RMSProp add wd*weight first and
clip the sum (ref: python optimizer.py Adam/RMSProp).
"""
from __future__ import annotations

import jax.numpy as jnp

from ..base import attr_float
from .registry import OpDef, register_def


def _common(attrs):
    lr = attr_float(attrs.get("lr"))
    wd = attr_float(attrs.get("wd", 0.0), 0.0)
    rescale = attr_float(attrs.get("rescale_grad", 1.0), 1.0)
    clip = attr_float(attrs.get("clip_gradient", -1.0), -1.0)
    return lr, wd, rescale, clip


def _prep_grad(grad, rescale, clip):
    g = grad * rescale
    if clip is not None and clip > 0:
        g = jnp.clip(g, -clip, clip)
    return g


def _sgd_update(op_ctx, attrs, inputs, aux):
    weight, grad = inputs
    lr, wd, rescale, clip = _common(attrs)
    g = _prep_grad(grad, rescale, clip)
    return (weight - lr * (g + wd * weight),)


def _sgd_mom_update(op_ctx, attrs, inputs, aux):
    weight, grad, mom = inputs
    lr, wd, rescale, clip = _common(attrs)
    momentum = attr_float(attrs.get("momentum", 0.0), 0.0)
    g = _prep_grad(grad, rescale, clip)
    new_mom = momentum * mom - lr * (g + wd * weight)
    return (weight + new_mom, new_mom)


def _adam_update(op_ctx, attrs, inputs, aux):
    weight, grad, mean, var = inputs
    lr, wd, rescale, clip = _common(attrs)
    beta1 = attr_float(attrs.get("beta1", 0.9), 0.9)
    beta2 = attr_float(attrs.get("beta2", 0.999), 0.999)
    eps = attr_float(attrs.get("epsilon", 1e-8), 1e-8)
    g = _prep_grad(grad * rescale + wd * weight, 1.0, clip)
    new_mean = beta1 * mean + (1 - beta1) * g
    new_var = beta2 * var + (1 - beta2) * jnp.square(g)
    new_weight = weight - lr * new_mean / (jnp.sqrt(new_var) + eps)
    return (new_weight, new_mean, new_var)


def _rmsprop_update(op_ctx, attrs, inputs, aux):
    weight, grad, n = inputs
    lr, wd, rescale, clip = _common(attrs)
    gamma1 = attr_float(attrs.get("gamma1", 0.95), 0.95)
    eps = attr_float(attrs.get("epsilon", 1e-8), 1e-8)
    clip_w = attr_float(attrs.get("clip_weights", -1.0), -1.0)
    g = _prep_grad(grad * rescale + wd * weight, 1.0, clip)
    new_n = (1 - gamma1) * jnp.square(g) + gamma1 * n
    new_weight = weight - lr * g / jnp.sqrt(new_n + eps)
    if clip_w is not None and clip_w > 0:
        new_weight = jnp.clip(new_weight, -clip_w, clip_w)
    return (new_weight, new_n)


def _rmspropalex_update(op_ctx, attrs, inputs, aux):
    # centered RMSProp (ref: rmspropalex_update, Graves 2013 variant)
    weight, grad, n, g_avg, delta = inputs
    lr, wd, rescale, clip = _common(attrs)
    gamma1 = attr_float(attrs.get("gamma1", 0.95), 0.95)
    gamma2 = attr_float(attrs.get("gamma2", 0.9), 0.9)
    eps = attr_float(attrs.get("epsilon", 1e-8), 1e-8)
    clip_w = attr_float(attrs.get("clip_weights", -1.0), -1.0)
    g = _prep_grad(grad * rescale + wd * weight, 1.0, clip)
    new_n = (1 - gamma1) * jnp.square(g) + gamma1 * n
    new_g = (1 - gamma1) * g + gamma1 * g_avg
    new_delta = gamma2 * delta - lr * g / jnp.sqrt(new_n - jnp.square(new_g) + eps)
    new_weight = weight + new_delta
    if clip_w is not None and clip_w > 0:
        new_weight = jnp.clip(new_weight, -clip_w, clip_w)
    return (new_weight, new_n, new_g, new_delta)


register_def(OpDef("sgd_update", _sgd_update, inputs=("weight", "grad")))
register_def(OpDef("sgd_mom_update", _sgd_mom_update,
                   inputs=("weight", "grad", "mom"),
                   outputs=("weight", "mom")))
register_def(OpDef("adam_update", _adam_update,
                   inputs=("weight", "grad", "mean", "var"),
                   outputs=("weight", "mean", "var")))
register_def(OpDef("rmsprop_update", _rmsprop_update,
                   inputs=("weight", "grad", "n"),
                   outputs=("weight", "n")))
register_def(OpDef("rmspropalex_update", _rmspropalex_update,
                   inputs=("weight", "grad", "n", "g", "delta"),
                   outputs=("weight", "n", "g", "delta")))
