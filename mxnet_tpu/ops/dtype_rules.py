"""Per-op dtype-inference rules (ref: the reference's per-op InferType
functions, src/operator/*-inl.h and nnvm ElemwiseType overrides).

Most ops follow the default "one dtype everywhere" rule in
OpDef.infer_type; this module attaches the exceptions after all ops have
registered (imported at the end of ops/__init__):

- Cast: output dtype is the attribute, input free.
- one_hot / sampling ops: output dtype from the ``dtype`` attr (default
  float32), indices keep their own (integer labels flow through).
- Embedding: lookup indices keep their own dtype (int32/float both legal,
  like the reference's float-id convention); weight/output share a float
  dtype.
- Loss heads (SoftmaxOutput family): the label input keeps its own dtype —
  int32 labels against bf16/f32 logits — outputs follow the data.
- where: the condition keeps its own dtype; x/y/output unify.
"""
from __future__ import annotations

import numpy as np

from . import registry as _reg

_F32 = np.dtype(np.float32)


def _set(name, fn):
    if _reg.exists(name):
        _reg.get(name)._infer_type = fn


def _cast_type(attrs, ins):
    return [ins[0]], [np.dtype(str(attrs.get("dtype", "float32")))], []


def _attr_dtype_out(attrs, ins):
    dt = np.dtype(str(attrs.get("dtype", "float32")))
    return list(ins), [dt], []


def _embedding_type(attrs, ins):
    data, weight = ins[0], ins[1]
    if weight is None:
        # indices may be integer; the table itself is float
        weight = data if (data is not None
                          and np.issubdtype(data, np.floating)) else _F32
    return [data, weight], [weight], []


def _label_free_loss(n_out=1):
    # a local closure is fine here: OpDef pickles by registry name
    # (OpDef.__reduce__), so installed rules never serialize
    def rule(attrs, ins):
        data = ins[0]
        full = [data] + [i if i is not None else data for i in ins[1:]]
        return full, [data] * n_out, []
    return rule


def _where_type(attrs, ins):
    cond = ins[0]
    known = [d for d in ins[1:] if d is not None]
    dt = known[0] if known else None
    return [cond, dt, dt], [dt], []


def install():
    _set("Cast", _cast_type)
    _set("one_hot", _attr_dtype_out)
    for s in ("_random_uniform", "_random_normal", "_random_gamma",
              "_random_exponential", "_random_poisson",
              "_random_negative_binomial",
              "_sample_uniform", "_sample_normal", "_sample_gamma",
              "_sample_exponential", "_sample_poisson",
              "_sample_negbinomial"):
        _set(s, _attr_dtype_out)
    _set("Embedding", _embedding_type)
    for loss in ("SoftmaxOutput", "LinearRegressionOutput",
                 "LogisticRegressionOutput", "MAERegressionOutput",
                 "SVMOutput"):
        _set(loss, _label_free_loss(1))
    _set("where", _where_type)


install()
