"""Operator library: single registry serving both imperative (ndarray) and
symbolic (symbol) namespaces (SURVEY.md section 2.3 inventory)."""
from . import registry
from . import tensor        # noqa: F401  (registers tensor ops)
from . import nn            # noqa: F401  (registers nn layer ops)
from . import optimizer_op  # noqa: F401  (registers fused update ops)
from . import rnn_op        # noqa: F401  (registers the fused RNN op)
from . import spatial       # noqa: F401  (registers spatial ops)
from . import contrib       # noqa: F401  (registers contrib/SSD/CTC ops)
from . import attention     # noqa: F401  (registers MultiHeadAttention/LayerNorm)
from . import transformer_stack  # noqa: F401  (registers TransformerStack)
from . import dtype_rules   # noqa: F401  (attaches per-op InferType rules)

get = registry.get
exists = registry.exists
list_ops = registry.list_ops
OpContext = registry.OpContext
OpDef = registry.OpDef
register = registry.register
register_def = registry.register_def
