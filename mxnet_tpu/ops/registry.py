"""Operator registry.

The reference has four registration systems (legacy OperatorProperty,
NNVM_REGISTER_OP, the elementwise macro family, and the simple-op registry —
SURVEY.md section 2.3). Here there is exactly ONE: an ``OpDef`` holding a pure
JAX function plus declarative metadata. From a single registration the
framework derives:

- the imperative NDArray wrapper  (ref: _init_ndarray_module autogen)
- the symbolic Symbol constructor (ref: _init_symbol_module autogen)
- shape/type inference            (ref: nnvm InferShape/InferType passes) —
  by default via ``jax.eval_shape`` abstract evaluation; layer ops with
  learnable inputs override ``infer_shape`` so parameter shapes can be
  *completed* from the data shape (what simple_bind relies on).

Gradients need no per-op registration at all: executors differentiate the
composed pure function with ``jax.vjp``. Ops whose reference backward is NOT
the mathematical vjp (loss layers like SoftmaxOutput, ref:
src/operator/softmax_output-inl.h) use ``jax.custom_vjp`` inside their fn.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as _np

from ..base import MXNetError


class OpContext(object):
    """Per-invocation context threaded into op kernels.

    Carries ``is_train`` (ref: OpContext.is_train, include/mxnet/operator.h)
    and a functional PRNG key for ops that declared ``needs_rng`` (ref:
    ResourceRequest::kRandom).
    """

    __slots__ = ("is_train", "rng", "fused_stats")

    def __init__(self, is_train=False, rng=None, fused_stats=None):
        self.is_train = is_train
        self.rng = rng
        # (s1, s2, count) batch statistics precomputed by a fused producer
        # (ops/pallas_fused.py); consumed by BatchNorm's fused_stats path
        self.fused_stats = fused_stats


class OpDef(object):
    """A registered operator."""

    def __init__(self, name, fn, inputs=("data",), aux=(), outputs=("output",),
                 infer_shape=None, infer_type=None, needs_rng=False,
                 var_inputs_attr=None, var_inputs_prefix="arg",
                 var_outputs=None, description=""):
        self.name = name
        self.fn = fn  # fn(op_ctx, attrs, inputs:list, aux:list) -> tuple | (tuple, aux_updates)
        self._inputs = tuple(inputs)
        self._aux = tuple(aux)
        self._outputs = tuple(outputs)
        self._infer_shape = infer_shape
        self._infer_type = infer_type
        self.needs_rng = needs_rng
        self.var_inputs_attr = var_inputs_attr   # e.g. "num_args" for Concat
        self.var_inputs_prefix = var_inputs_prefix
        self.var_outputs = var_outputs           # callable(attrs)->list[str] or None
        self.description = description

    # -- pickling -------------------------------------------------------
    def __reduce__(self):
        """Pickle by registry name: kernels capture local closures (the
        register_unary/binary helpers, dtype rules) that cannot serialize,
        and the live registry object is the authority anyway. Unpickling in
        another process resolves through ``get`` after import-time
        registration — exactly how the reference's creator handles travel
        across ps-lite (by name, ref: python/mxnet/kvstore.py:226 pickling
        only picklable optimizer state)."""
        return (get, (self.name,))

    # -- arity ----------------------------------------------------------
    def list_inputs(self, attrs):
        if self.var_inputs_attr is not None:
            n = int(attrs.get(self.var_inputs_attr, 1))
            return ["%s%d" % (self.var_inputs_prefix, i) for i in range(n)]
        return list(self._inputs)

    def list_aux(self, attrs):
        return list(self._aux)

    def list_outputs(self, attrs):
        if self.var_outputs is not None:
            return list(self.var_outputs(attrs))
        return list(self._outputs)

    def num_outputs(self, attrs):
        return len(self.list_outputs(attrs))

    # -- execution ------------------------------------------------------
    def apply(self, op_ctx, attrs, inputs, aux):
        """Run the kernel. Returns (outputs_tuple, aux_updates_tuple|None)."""
        out = self.fn(op_ctx, attrs, list(inputs), list(aux))
        if (isinstance(out, tuple) and len(out) == 2
                and isinstance(out[0], (tuple, list))
                and isinstance(out[1], (tuple, list))):
            return tuple(out[0]), tuple(out[1])
        if not isinstance(out, (tuple, list)):
            out = (out,)
        return tuple(out), None

    # -- inference ------------------------------------------------------
    def infer_shape(self, attrs, in_shapes, aux_shapes=None):
        """Complete shapes. ``in_shapes``: list of tuple|None per input.
        Returns (in_shapes, out_shapes, aux_shapes); raises if underdetermined.
        """
        if self._infer_shape is not None:
            return self._infer_shape(attrs, list(in_shapes))
        if any(s is None for s in in_shapes):
            missing = [self.list_inputs(attrs)[i]
                       for i, s in enumerate(in_shapes) if s is None]
            raise MXNetError(
                "op %s: cannot infer shapes of inputs %s (no custom infer_shape)"
                % (self.name, missing))
        outs = self._abstract_eval(attrs, in_shapes)
        return list(in_shapes), [tuple(o.shape) for o in outs], []

    def infer_type(self, attrs, in_dtypes):
        """Complete dtypes (ref: nnvm InferType; default = the reference's
        ElemwiseType rule: all inputs/outputs share one dtype). Unknown
        inputs inherit the first known dtype; already-known inputs are kept
        (a genuine conflict surfaces in the Symbol pass)."""
        if self._infer_type is not None:
            return self._infer_type(attrs, list(in_dtypes))
        known = [d for d in in_dtypes if d is not None]
        dt = known[0] if known else None
        full_in = [d if d is not None else dt for d in in_dtypes]
        return (full_in,
                [dt] * self.num_outputs(attrs),
                [dt] * len(self._aux))

    def _abstract_eval(self, attrs, in_shapes, in_dtypes=None):
        n = len(in_shapes)
        if in_dtypes is None:
            in_dtypes = [jnp.float32] * n
        args = [jax.ShapeDtypeStruct(tuple(s), d)
                for s, d in zip(in_shapes, in_dtypes)]
        aux_shapes = []  # abstract eval with no aux only valid for aux-free ops
        ctx = OpContext(is_train=False, rng=None)

        def run(*arrs):
            outs, _ = self.apply(ctx, attrs, list(arrs), [])
            return outs

        try:
            return jax.eval_shape(run, *args)
        except Exception as e:  # pragma: no cover - diagnostic path
            raise MXNetError("op %s: abstract shape eval failed for %s: %s"
                             % (self.name, in_shapes, e))


# ---------------------------------------------------------------------------
# registry
# ---------------------------------------------------------------------------
_REGISTRY = {}
_ALIASES = {}


def register(name, **kwargs):
    """Decorator: register ``fn(op_ctx, attrs, inputs, aux)`` as operator ``name``."""
    aliases = kwargs.pop("aliases", ())

    def deco(fn):
        opdef = OpDef(name, fn, **kwargs)
        _REGISTRY[name] = opdef
        for a in aliases:
            _ALIASES[a] = name
        return fn
    return deco


def register_def(opdef, aliases=()):
    _REGISTRY[opdef.name] = opdef
    for a in aliases:
        _ALIASES[a] = opdef.name
    return opdef


def get(name):
    if name in _REGISTRY:
        return _REGISTRY[name]
    if name in _ALIASES:
        return _REGISTRY[_ALIASES[name]]
    raise MXNetError("operator %r is not registered" % (name,))


def exists(name):
    return name in _REGISTRY or name in _ALIASES


def list_ops():
    return sorted(set(_REGISTRY) | set(_ALIASES))


# ---------------------------------------------------------------------------
# light-weight helpers for bulk registration of pure-jnp ops
# ---------------------------------------------------------------------------

def register_unary(name, jfn, aliases=()):
    """Elementwise unary op (ref: MXNET_OPERATOR_REGISTER_UNARY family)."""
    def fn(op_ctx, attrs, inputs, aux):
        return (jfn(inputs[0]),)
    register_def(OpDef(name, fn, inputs=("data",)), aliases=aliases)


def register_binary(name, jfn, aliases=()):
    """Elementwise binary op, same-shape (ref: elemwise_binary_op.h)."""
    def fn(op_ctx, attrs, inputs, aux):
        return (jfn(inputs[0], inputs[1]),)
    register_def(OpDef(name, fn, inputs=("lhs", "rhs")), aliases=aliases)


def register_binary_scalar(name, jfn, aliases=()):
    """lhs op scalar-attr (ref: elemwise_binary_scalar_op.h, attr 'scalar')."""
    def fn(op_ctx, attrs, inputs, aux):
        s = float(attrs.get("scalar", 0.0))
        return (jfn(inputs[0], s),)
    register_def(OpDef(name, fn, inputs=("data",)), aliases=aliases)
