"""Fused multi-layer RNN operator (RNN): vanilla/LSTM/GRU, bidirectional.

TPU-native replacement for the reference's cuDNN-only fused RNN
(ref: src/operator/rnn-inl.h:315 — CPU path is LOG(FATAL) "not implemented";
cudnn_rnn-inl.h:549). Here the recurrence is a ``lax.scan`` per layer and
direction — compiler-friendly control flow the MXU can pipeline — working on
every backend, with gradients from jax.vjp instead of cuDNN's backward.

Interface parity with the reference RNN op:
  inputs: data (T, N, C), parameters (flat vector), state (L*D, N, H)
          [, state_cell (L*D, N, H) for lstm]
  attrs:  state_size, num_layers, mode {rnn_relu, rnn_tanh, lstm, gru},
          bidirectional, p (inter-layer dropout), state_outputs
  outputs: output (T, N, H*D) [, state_out [, statecell_out]]

Packed parameter layout (cuDNN-compatible ordering, which FusedRNNCell's
unfuse()/unpack rely on): per layer, per direction: W_x (G*H, I) then
W_h (G*H, H); after ALL weights come the biases: per layer, per direction:
b_x (G*H,) then b_h (G*H,). Gate order: LSTM i,f,g,o; GRU r,z,n.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..base import attr_bool, attr_float, attr_int, attr_str, MXNetError
from .registry import OpDef, register_def

_GATES = {"rnn_relu": 1, "rnn_tanh": 1, "lstm": 4, "gru": 3}


def rnn_param_size(mode, input_size, state_size, num_layers, bidirectional):
    """Total packed parameter count (matches the layout above)."""
    g = _GATES[mode]
    d = 2 if bidirectional else 1
    h = state_size
    total = 0
    for layer in range(num_layers):
        i = input_size if layer == 0 else h * d
        total += d * (g * h * i + g * h * h)   # weights
    total += num_layers * d * 2 * g * h        # biases
    return total


def _param_slices(mode, input_size, state_size, num_layers, bidirectional):
    """Static offsets of each (layer, dir) -> (Wx, Wh, bx, bh) slice."""
    g = _GATES[mode]
    d = 2 if bidirectional else 1
    h = state_size
    slices = {}
    off = 0
    for layer in range(num_layers):
        i = input_size if layer == 0 else h * d
        for dr in range(d):
            wx = (off, g * h * i, (g * h, i)); off += g * h * i
            wh = (off, g * h * h, (g * h, h)); off += g * h * h
            slices[(layer, dr)] = [wx, wh, None, None]
    for layer in range(num_layers):
        for dr in range(d):
            bx = (off, g * h, (g * h,)); off += g * h
            bh = (off, g * h, (g * h,)); off += g * h
            slices[(layer, dr)][2] = bx
            slices[(layer, dr)][3] = bh
    return slices, off


def _take(params, spec):
    off, n, shape = spec
    return jax.lax.dynamic_slice(params, (off,), (n,)).reshape(shape)


def _cell_step(mode, x_proj, h_prev, c_prev, wh, bh):
    """One timestep given the precomputed input projection."""
    gates = x_proj + jnp.dot(h_prev, wh.T) + bh
    state_size = h_prev.shape[-1]
    if mode == "lstm":
        i, f, g, o = jnp.split(gates, 4, axis=-1)
        i = jax.nn.sigmoid(i)
        f = jax.nn.sigmoid(f)
        g = jnp.tanh(g)
        o = jax.nn.sigmoid(o)
        c = f * c_prev + i * g
        h = o * jnp.tanh(c)
        return h, c
    if mode == "gru":
        # GRU with cuDNN-style reset-after-projection on hidden candidate
        hr = jnp.dot(h_prev, wh.T) + bh
        xr = x_proj
        r = jax.nn.sigmoid(xr[..., :state_size] + hr[..., :state_size])
        z = jax.nn.sigmoid(xr[..., state_size:2 * state_size]
                           + hr[..., state_size:2 * state_size])
        n = jnp.tanh(xr[..., 2 * state_size:]
                     + r * hr[..., 2 * state_size:])
        hnew = (1 - z) * n + z * h_prev
        return hnew, c_prev
    act = jnp.tanh if mode == "rnn_tanh" else jax.nn.relu
    hnew = act(gates)
    return hnew, c_prev


def _run_direction(mode, xs, h0, c0, wx, wh, bx, bh, reverse):
    """Scan one layer in one direction. xs: (T, N, I)."""
    # hoist the input projection out of the scan: one big MXU matmul
    x_proj = jnp.einsum("tni,gi->tng", xs, wx) + bx

    def step(carry, xp):
        h_prev, c_prev = carry
        h, c = _cell_step(mode, xp, h_prev, c_prev, wh, bh)
        return (h, c), h

    (hT, cT), ys = jax.lax.scan(step, (h0, c0), x_proj, reverse=reverse)
    return ys, hT, cT


def _rnn_inputs(attrs):
    mode = attr_str(attrs.get("mode", "lstm"), "lstm")
    if mode == "lstm":
        return ["data", "parameters", "state", "state_cell"]
    return ["data", "parameters", "state"]


def _rnn_outputs(attrs):
    mode = attr_str(attrs.get("mode", "lstm"), "lstm")
    if attr_bool(attrs.get("state_outputs", False), False):
        return (["output", "state_out", "statecell_out"] if mode == "lstm"
                else ["output", "state_out"])
    return ["output"]


def _rnn_infer(attrs, in_shapes):
    mode = attr_str(attrs.get("mode", "lstm"), "lstm")
    h = attr_int(attrs["state_size"])
    L = attr_int(attrs.get("num_layers", 1), 1)
    bi = attr_bool(attrs.get("bidirectional", False), False)
    d = 2 if bi else 1
    data = in_shapes[0]
    if data is None:
        raise MXNetError("RNN: data shape required")
    t, n, c = data
    psize = rnn_param_size(mode, c, h, L, bi)
    shapes = [tuple(data), (psize,), (L * d, n, h)]
    if mode == "lstm":
        shapes.append((L * d, n, h))
    outs = [(t, n, h * d)]
    if attr_bool(attrs.get("state_outputs", False), False):
        outs.append((L * d, n, h))
        if mode == "lstm":
            outs.append((L * d, n, h))
    return shapes, outs, []


def _rnn(op_ctx, attrs, inputs, aux):
    mode = attr_str(attrs.get("mode", "lstm"), "lstm")
    h = attr_int(attrs["state_size"])
    L = attr_int(attrs.get("num_layers", 1), 1)
    bi = attr_bool(attrs.get("bidirectional", False), False)
    p = attr_float(attrs.get("p", 0.0), 0.0)
    state_outputs = attr_bool(attrs.get("state_outputs", False), False)
    d = 2 if bi else 1
    data, params = inputs[0], inputs[1]
    state = inputs[2]
    state_cell = inputs[3] if mode == "lstm" else jnp.zeros_like(state)
    t, n, c = data.shape
    slices, total = _param_slices(mode, c, h, L, bi)
    if params.shape[0] != total:
        raise MXNetError("RNN: parameters size %d != expected %d"
                         % (params.shape[0], total))

    xs = data
    h_outs = []
    c_outs = []
    for layer in range(L):
        ys_dirs = []
        for dr in range(d):
            wx = _take(params, slices[(layer, dr)][0])
            wh = _take(params, slices[(layer, dr)][1])
            bx = _take(params, slices[(layer, dr)][2])
            bh = _take(params, slices[(layer, dr)][3])
            idx = layer * d + dr
            ys, hT, cT = _run_direction(mode, xs, state[idx], state_cell[idx],
                                        wx, wh, bx, bh, reverse=(dr == 1))
            ys_dirs.append(ys)
            h_outs.append(hT)
            c_outs.append(cT)
        xs = (jnp.concatenate(ys_dirs, axis=-1) if d == 2 else ys_dirs[0])
        if p > 0 and layer < L - 1 and op_ctx.is_train and op_ctx.rng is not None:
            keep = 1.0 - p
            mask = jax.random.bernoulli(
                jax.random.fold_in(op_ctx.rng, layer), keep, xs.shape)
            xs = jnp.where(mask, xs / keep, 0.0).astype(xs.dtype)

    outs = [xs]
    if state_outputs:
        outs.append(jnp.stack(h_outs))
        if mode == "lstm":
            outs.append(jnp.stack(c_outs))
    return tuple(outs)


_RNN = register_def(OpDef("RNN", _rnn, inputs=("data", "parameters", "state"),
                          infer_shape=_rnn_infer, var_outputs=_rnn_outputs,
                          needs_rng=True))
_RNN.list_inputs = _rnn_inputs
