"""Neural-network layer operators.

TPU-native re-implementations of the reference layer ops
(ref: src/operator/*-inl.h — SURVEY.md section 2.3). Kernels are XLA
emissions (lax.conv_general_dilated for conv, lax.reduce_window for pooling)
in NCHW layout for API parity — XLA relayouts internally for the MXU, so no
NHWC is forced on the user. Loss layers reproduce the reference's
"backward-emits-the-gradient" contract via jax.custom_vjp
(ref: src/operator/softmax_output-inl.h, regression_output-inl.h,
make_loss-inl.h): their backward ignores the incoming out_grad exactly like
the reference.

Layer ops with learnable inputs provide custom infer_shape so simple_bind can
complete weight shapes from the data shape (ref: nnvm InferShape pass use in
src/executor/graph_executor.cc:428-445).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.ad_checkpoint import checkpoint_name as _ckpt_name

from ..base import attr_bool, attr_float, attr_int, attr_tuple, attr_str, MXNetError
from .registry import OpDef, register, register_def


# ---------------------------------------------------------------------------
# FullyConnected (ref: src/operator/fully_connected-inl.h:113-131)
# ---------------------------------------------------------------------------

def _fc_inputs(attrs):
    if attr_bool(attrs.get("no_bias", False), False):
        return ["data", "weight"]
    return ["data", "weight", "bias"]


def _fc_infer(attrs, in_shapes):
    num_hidden = attr_int(attrs["num_hidden"])
    no_bias = attr_bool(attrs.get("no_bias", False), False)
    flatten = attr_bool(attrs.get("flatten", True), True)
    data = in_shapes[0]
    if data is None:
        raise MXNetError("FullyConnected: data shape required")
    if flatten:
        in_units = 1
        for d in data[1:]:
            in_units *= d
        out = (data[0], num_hidden)
    else:
        # ref flatten=False: contract the LAST dim only, keep the leading
        # dims — (b, s, e) @ (h, e)^T -> (b, s, h). Under a composed
        # data x seq mesh this never merges two sharded dims, so no
        # resharding gather rides into the compiled loop
        in_units = data[-1]
        out = tuple(data[:-1]) + (num_hidden,)
    shapes = [tuple(data), (num_hidden, in_units)]
    if not no_bias:
        shapes.append((num_hidden,))
    return shapes, [out], []


def _fc(op_ctx, attrs, inputs, aux):
    num_hidden = attr_int(attrs["num_hidden"])
    no_bias = attr_bool(attrs.get("no_bias", False), False)
    flatten = attr_bool(attrs.get("flatten", True), True)
    data = inputs[0]
    x = data.reshape(data.shape[0], -1) if flatten else data
    w = inputs[1]
    y = jnp.dot(x, w.T)
    if not no_bias:
        y = y + inputs[2]
    # remat anchor (see Convolution): saved under TrainStep(remat="conv")
    return (_ckpt_name(y, "fc_out"),)


_FC = register_def(OpDef("FullyConnected", _fc, inputs=("data", "weight", "bias"),
                         infer_shape=_fc_infer))
_FC.list_inputs = _fc_inputs  # arity depends on no_bias


# ---------------------------------------------------------------------------
# Convolution / Deconvolution (ref: src/operator/convolution-inl.h:570,
# deconvolution-inl.h:669). CPU reference path is im2col+GEMM; here a single
# lax.conv_general_dilated call that XLA tiles onto the MXU.
# ---------------------------------------------------------------------------

def _conv_attrs(attrs):
    kernel = attr_tuple(attrs["kernel"])
    nd = len(kernel)
    stride = attr_tuple(attrs.get("stride", (1,) * nd), (1,) * nd)
    dilate = attr_tuple(attrs.get("dilate", (1,) * nd), (1,) * nd)
    pad = attr_tuple(attrs.get("pad", (0,) * nd), (0,) * nd)
    num_filter = attr_int(attrs["num_filter"])
    num_group = attr_int(attrs.get("num_group", 1), 1)
    no_bias = attr_bool(attrs.get("no_bias", False), False)
    return kernel, stride, dilate, pad, num_filter, num_group, no_bias


def _conv_inputs(attrs):
    if attr_bool(attrs.get("no_bias", False), False):
        return ["data", "weight"]
    return ["data", "weight", "bias"]


def _conv_layout(attrs, nd):
    """Activation layout. NHWC (2-d only) keeps the channel dim innermost —
    the TPU-preferred layout that also makes a 1x1 conv a free reshape to a
    matmul (the Pallas conv+BN-stats fusion requires it). Weights stay OIHW
    in every layout so checkpoints transfer."""
    default = "NCHW" if nd == 2 else ("NCW" if nd == 1 else "NCDHW")
    layout = attr_str(attrs.get("layout", ""), "")
    if not layout or layout == default:
        return default
    if nd != 2 or layout != "NHWC":
        raise MXNetError("Convolution: unsupported layout %r for %d-d"
                         % (layout, nd))
    return layout


def _conv_infer(attrs, in_shapes):
    kernel, stride, dilate, pad, nf, ng, no_bias = _conv_attrs(attrs)
    data = in_shapes[0]
    if data is None:
        raise MXNetError("Convolution: data shape required")
    nhwc = _conv_layout(attrs, len(kernel)) == "NHWC"
    c = data[-1] if nhwc else data[1]
    wshape = (nf, c // ng) + kernel
    out_sp = tuple(
        (data[(1 if nhwc else 2) + i] + 2 * pad[i]
         - dilate[i] * (kernel[i] - 1) - 1) // stride[i] + 1
        for i in range(len(kernel)))
    shapes = [tuple(data), wshape] + ([] if no_bias else [(nf,)])
    out = ((data[0],) + out_sp + (nf,)) if nhwc else ((data[0], nf) + out_sp)
    return shapes, [out], []


def _conv(op_ctx, attrs, inputs, aux):
    kernel, stride, dilate, pad, nf, ng, no_bias = _conv_attrs(attrs)
    x, w = inputs[0], inputs[1]
    nd = len(kernel)
    layout = _conv_layout(attrs, nd)
    dn = jax.lax.conv_dimension_numbers(
        x.shape, w.shape, (layout, "OIHW", layout) if nd == 2 else
        ("NCW", "OIW", "NCW") if nd == 1 else ("NCDHW", "OIDHW", "NCDHW"))
    # no preferred_element_type: the MXU accumulates bf16 matmuls in fp32
    # internally, and a widened output dtype breaks the conv transpose rule
    y = jax.lax.conv_general_dilated(
        x, w, window_strides=stride, padding=[(p, p) for p in pad],
        rhs_dilation=dilate, dimension_numbers=dn, feature_group_count=ng)
    if not no_bias:
        bshape = ((1,) * (nd + 1) + (nf,)) if layout == "NHWC" \
            else ((1, nf) + (1,) * nd)
        y = y + inputs[2].reshape(bshape)
    # remat anchor: under TrainStep(remat="conv") only these outputs are
    # saved for backward; BN/ReLU/pool between convs are recomputed, cutting
    # stored-activation HBM traffic (no-op outside jax.checkpoint)
    return (_ckpt_name(y, "conv_out"),)


_CONV = register_def(OpDef("Convolution", _conv, inputs=("data", "weight", "bias"),
                           infer_shape=_conv_infer))
_CONV.list_inputs = _conv_inputs


def _deconv_infer(attrs, in_shapes):
    kernel, stride, dilate, pad, nf, ng, no_bias = _conv_attrs(attrs)
    adj = attr_tuple(attrs.get("adj", (0,) * len(kernel)), (0,) * len(kernel))
    data = in_shapes[0]
    if data is None:
        raise MXNetError("Deconvolution: data shape required")
    c = data[1]
    wshape = (c, nf // ng) + kernel
    out_sp = tuple(
        (data[2 + i] - 1) * stride[i] - 2 * pad[i]
        + dilate[i] * (kernel[i] - 1) + 1 + adj[i]
        for i in range(len(kernel)))
    shapes = [tuple(data), wshape] + ([] if no_bias else [(nf,)])
    return shapes, [(data[0], nf) + out_sp], []


def _deconv(op_ctx, attrs, inputs, aux):
    kernel, stride, dilate, pad, nf, ng, no_bias = _conv_attrs(attrs)
    x, w = inputs[0], inputs[1]
    nd = len(kernel)
    # Deconvolution = gradient of convolution wrt data: lhs-dilated conv with
    # transposed kernel (ref: deconvolution-inl.h backward-as-forward trick).
    dn = jax.lax.conv_dimension_numbers(
        x.shape, (w.shape[1] * ng, w.shape[0] // ng) + kernel,
        ("NCHW", "OIHW", "NCHW") if nd == 2 else
        ("NCW", "OIW", "NCW") if nd == 1 else ("NCDHW", "OIDHW", "NCDHW"))
    # flip spatial dims, swap I/O
    wt = jnp.swapaxes(w, 0, 1)
    for i in range(nd):
        wt = jnp.flip(wt, axis=2 + i)
    if ng > 1:
        # regroup for grouped transpose conv
        ci, co = w.shape[0], w.shape[1]
        wt = wt.reshape(co, ng, ci // ng, *kernel)
        wt = wt.transpose(1, 0, 2, *range(3, 3 + nd))
        wt = wt.reshape(ng * co, ci // ng, *kernel)
    pads = [(dilate[i] * (kernel[i] - 1) - pad[i],
             dilate[i] * (kernel[i] - 1) - pad[i]
             + attr_tuple(attrs.get("adj", (0,) * nd), (0,) * nd)[i])
            for i in range(nd)]
    y = jax.lax.conv_general_dilated(
        x, wt, window_strides=(1,) * nd, padding=pads,
        lhs_dilation=stride, rhs_dilation=dilate,
        dimension_numbers=dn, feature_group_count=ng)
    if not no_bias:
        y = y + inputs[2].reshape((1, -1) + (1,) * nd)
    return (y,)


_DECONV = register_def(OpDef("Deconvolution", _deconv, inputs=("data", "weight", "bias"),
                             infer_shape=_deconv_infer))
_DECONV.list_inputs = _conv_inputs


# ---------------------------------------------------------------------------
# Activation / LeakyReLU / softmax (ref: activation-inl.h, leaky_relu-inl.h,
# softmax_activation-inl.h)
# ---------------------------------------------------------------------------

@register("Activation", inputs=("data",))
def _activation(op_ctx, attrs, inputs, aux):
    act = attr_str(attrs.get("act_type", "relu"), "relu")
    x = inputs[0]
    if act == "relu":
        return (jax.nn.relu(x),)
    if act == "sigmoid":
        return (jax.nn.sigmoid(x),)
    if act == "tanh":
        return (jnp.tanh(x),)
    if act == "softrelu":
        return (jax.nn.softplus(x),)
    raise MXNetError("Activation: unknown act_type %r" % act)


def _leaky_inputs(attrs):
    if attr_str(attrs.get("act_type", "leaky"), "leaky") == "prelu":
        return ["data", "gamma"]
    return ["data"]


def _leaky_infer(attrs, in_shapes):
    data = in_shapes[0]
    if data is None:
        raise MXNetError("LeakyReLU: data shape required")
    if attr_str(attrs.get("act_type", "leaky"), "leaky") == "prelu":
        return [tuple(data), (data[1],)], [tuple(data)], []
    return [tuple(data)], [tuple(data)], []


def _leaky(op_ctx, attrs, inputs, aux):
    act = attr_str(attrs.get("act_type", "leaky"), "leaky")
    x = inputs[0]
    slope = attr_float(attrs.get("slope", 0.25), 0.25)
    if act == "leaky":
        return (jnp.where(x > 0, x, slope * x),)
    if act == "elu":
        return (jnp.where(x > 0, x, slope * jnp.expm1(x)),)
    if act == "prelu":
        g = inputs[1].reshape((1, -1) + (1,) * (x.ndim - 2))
        return (jnp.where(x > 0, x, g * x),)
    if act == "rrelu":
        lo = attr_float(attrs.get("lower_bound", 0.125), 0.125)
        up = attr_float(attrs.get("upper_bound", 0.334), 0.334)
        if op_ctx.is_train and op_ctx.rng is not None:
            s = jax.random.uniform(op_ctx.rng, x.shape, minval=lo, maxval=up,
                                   dtype=x.dtype)
        else:
            s = (lo + up) / 2.0
        return (jnp.where(x > 0, x, s * x),)
    raise MXNetError("LeakyReLU: unknown act_type %r" % act)


_LRELU = register_def(OpDef("LeakyReLU", _leaky, inputs=("data",), needs_rng=True,
                            infer_shape=_leaky_infer))
_LRELU.list_inputs = _leaky_inputs


@register("SoftmaxActivation", inputs=("data",), aliases=("softmax",))
def _softmax_activation(op_ctx, attrs, inputs, aux):
    mode = attr_str(attrs.get("mode", "instance"), "instance")
    x = inputs[0]
    if mode == "channel":
        return (jax.nn.softmax(x, axis=1),)
    return (jax.nn.softmax(x.reshape(x.shape[0], -1), axis=-1).reshape(x.shape),)


@register("log_softmax", inputs=("data",))
def _log_softmax(op_ctx, attrs, inputs, aux):
    ax = attr_int(attrs.get("axis", -1), -1)
    return (jax.nn.log_softmax(inputs[0], axis=ax),)


# ---------------------------------------------------------------------------
# BatchNorm (ref: src/operator/batch_norm-inl.h:358; aux moving_mean/var via
# FMutateInputs). Functional form: returns aux *updates*, which the executor
# writes back on forward (mirrors the reference's in-place aux mutation).
# ---------------------------------------------------------------------------

def _bn_infer(attrs, in_shapes):
    data = in_shapes[0]
    if data is None:
        raise MXNetError("BatchNorm: data shape required")
    axis = attr_int(attrs.get("axis", 1), 1)
    c = data[axis] if len(data) > 1 else data[0]
    out_mv = attr_bool(attrs.get("output_mean_var", False), False)
    outs = [tuple(data)] + ([(c,), (c,)] if out_mv else [])
    return [tuple(data), (c,), (c,)], outs, [(c,), (c,)]


def _bn_outputs(attrs):
    if attr_bool(attrs.get("output_mean_var", False), False):
        return ["output", "mean", "var"]
    return ["output"]


def _batch_norm(op_ctx, attrs, inputs, aux):
    eps = attr_float(attrs.get("eps", 1e-3), 1e-3)
    momentum = attr_float(attrs.get("momentum", 0.9), 0.9)
    fix_gamma = attr_bool(attrs.get("fix_gamma", True), True)
    use_global = attr_bool(attrs.get("use_global_stats", False), False)
    out_mv = attr_bool(attrs.get("output_mean_var", False), False)
    x, gamma, beta = inputs
    moving_mean, moving_var = aux
    axis = attr_int(attrs.get("axis", 1), 1) % x.ndim
    red = tuple(i for i in range(x.ndim) if i != axis)
    bshape = tuple(-1 if i == axis else 1 for i in range(x.ndim))
    if fix_gamma:
        gamma = jax.lax.stop_gradient(jnp.ones_like(gamma))
    fused = getattr(op_ctx, "fused_stats", None)
    if op_ctx.is_train and not use_global and fused is not None:
        # batch statistics precomputed by a fused producer (the Pallas
        # conv+stats epilogue): sum and sum-of-squares over the reduce axes,
        # f32. Differentiable — cotangents flow to the producer's vjp.
        s1, s2, count = fused
        mean32 = s1 / count
        var32 = jnp.maximum(s2 / count - jnp.square(mean32), 0.0)
        mean = mean32.astype(x.dtype)
        var = var32.astype(x.dtype)
        new_mean = (momentum * moving_mean
                    + (1 - momentum) * jax.lax.stop_gradient(
                        mean32.astype(moving_mean.dtype)))
        new_var = (momentum * moving_var
                   + (1 - momentum) * jax.lax.stop_gradient(
                       var32.astype(moving_var.dtype)))
        aux_updates = (new_mean, new_var)
    elif op_ctx.is_train and not use_global:
        if x.dtype in (jnp.bfloat16, jnp.float16):
            # One-pass statistics: sum and sum-of-squares reduce in a SINGLE
            # fused read of x (f32 accumulation), vs the mean-then-var
            # two-pass whose second reduction re-reads the activation. BN
            # stats are the largest non-essential HBM traffic in ResNet
            # training (docs/perf.md: ~24% of step time). E[x^2]-E[x]^2 in
            # f32 carries ~16 more mantissa bits than the 16-bit data, so
            # cancellation cannot exceed the input's own rounding; wider
            # activations keep the two-pass form, where E[(x-m)^2] stays
            # exact for ill-conditioned (|mean| >> std) data.
            n = 1.0
            for i in red:
                n *= x.shape[i]
            x32 = x.astype(jnp.float32)
            mean32 = jnp.sum(x32, axis=red) / n
            var32 = jnp.maximum(
                jnp.sum(jnp.square(x32), axis=red) / n - jnp.square(mean32),
                0.0)
            mean = mean32.astype(x.dtype)
            var = var32.astype(x.dtype)
        else:
            mean = jnp.mean(x, axis=red)
            var = jnp.var(x, axis=red)
            mean32, var32 = mean, var
        new_mean = (momentum * moving_mean
                    + (1 - momentum) * jax.lax.stop_gradient(
                        mean32.astype(moving_mean.dtype)))
        new_var = (momentum * moving_var
                   + (1 - momentum) * jax.lax.stop_gradient(
                       var32.astype(moving_var.dtype)))
        aux_updates = (new_mean, new_var)
    else:
        mean, var = moving_mean, moving_var
        aux_updates = (moving_mean, moving_var)
    inv = jax.lax.rsqrt(var.reshape(bshape) + eps)
    y = (x - mean.reshape(bshape)) * inv * gamma.reshape(bshape) + beta.reshape(bshape)
    outs = (y, mean, var) if out_mv else (y,)
    return outs, aux_updates


register_def(OpDef("BatchNorm", _batch_norm, inputs=("data", "gamma", "beta"),
                   aux=("moving_mean", "moving_var"), infer_shape=_bn_infer,
                   var_outputs=_bn_outputs))


@register("InstanceNorm", inputs=("data", "gamma", "beta"))
def _instance_norm(op_ctx, attrs, inputs, aux):
    eps = attr_float(attrs.get("eps", 1e-3), 1e-3)
    x, gamma, beta = inputs
    red = tuple(range(2, x.ndim))
    mean = jnp.mean(x, axis=red, keepdims=True)
    var = jnp.var(x, axis=red, keepdims=True)
    bshape = (1, -1) + (1,) * (x.ndim - 2)
    y = (x - mean) * jax.lax.rsqrt(var + eps)
    return (y * gamma.reshape(bshape) + beta.reshape(bshape),)


@register("L2Normalization", inputs=("data",))
def _l2_normalization(op_ctx, attrs, inputs, aux):
    eps = attr_float(attrs.get("eps", 1e-10), 1e-10)
    mode = attr_str(attrs.get("mode", "instance"), "instance")
    x = inputs[0]
    if mode == "instance":
        red = tuple(range(1, x.ndim))
        n = jnp.sqrt(jnp.sum(jnp.square(x), axis=red, keepdims=True) + eps)
    elif mode == "channel":
        n = jnp.sqrt(jnp.sum(jnp.square(x), axis=1, keepdims=True) + eps)
    elif mode == "spatial":
        red = tuple(range(2, x.ndim))
        n = jnp.sqrt(jnp.sum(jnp.square(x), axis=red, keepdims=True) + eps)
    else:
        raise MXNetError("L2Normalization: unknown mode %r" % mode)
    return (x / n,)


@register("LRN", inputs=("data",))
def _lrn(op_ctx, attrs, inputs, aux):
    # ref: src/operator/lrn-inl.h — across-channel local response norm
    alpha = attr_float(attrs.get("alpha", 1e-4), 1e-4)
    beta = attr_float(attrs.get("beta", 0.75), 0.75)
    knorm = attr_float(attrs.get("knorm", 2.0), 2.0)
    nsize = attr_int(attrs.get("nsize", 5), 5)
    x = inputs[0]
    sq = jnp.square(x)
    half = nsize // 2
    win = jax.lax.reduce_window(
        sq, 0.0, jax.lax.add,
        window_dimensions=(1, nsize, 1, 1), window_strides=(1, 1, 1, 1),
        padding=((0, 0), (half, half), (0, 0), (0, 0)))
    return (x * jnp.power(knorm + (alpha / nsize) * win, -beta),)


# ---------------------------------------------------------------------------
# Pooling (ref: src/operator/pooling-inl.h:316, nn/pool.h). avg pooling
# divides by the constant kernel area (padding included), matching mshadow.
# ---------------------------------------------------------------------------

def _pool_out_dim(in_dim, k, s, p, convention):
    if convention == "full":
        import math
        return int(math.ceil((in_dim + 2 * p - k) / float(s))) + 1
    return (in_dim + 2 * p - k) // s + 1


def _pool_nhwc(attrs):
    layout = attr_str(attrs.get("layout", ""), "")
    if layout and layout not in ("NCHW", "NHWC", "NCW", "NCDHW"):
        raise MXNetError("Pooling: unsupported layout %r" % layout)
    return layout == "NHWC"


def _pool_infer(attrs, in_shapes):
    data = in_shapes[0]
    if data is None:
        raise MXNetError("Pooling: data shape required")
    nhwc = _pool_nhwc(attrs)
    if attr_bool(attrs.get("global_pool", False), False):
        if nhwc:
            return [tuple(data)], [(data[0],) + (1,) * (len(data) - 2)
                                   + (data[-1],)], []
        return [tuple(data)], [tuple(data[:2]) + (1,) * (len(data) - 2)], []
    kernel = attr_tuple(attrs["kernel"])
    nd = len(kernel)
    stride = attr_tuple(attrs.get("stride", (1,) * nd), (1,) * nd)
    pad = attr_tuple(attrs.get("pad", (0,) * nd), (0,) * nd)
    conv = attr_str(attrs.get("pooling_convention", "valid"), "valid")
    sp0 = 1 if nhwc else 2
    out_sp = tuple(_pool_out_dim(data[sp0 + i], kernel[i], stride[i], pad[i],
                                 conv)
                   for i in range(nd))
    if nhwc:
        return [tuple(data)], [(data[0],) + out_sp + (data[-1],)], []
    return [tuple(data)], [tuple(data[:2]) + out_sp], []


def _pooling(op_ctx, attrs, inputs, aux):
    x = inputs[0]
    ptype = attr_str(attrs.get("pool_type", "max"), "max")
    nhwc = _pool_nhwc(attrs)
    if attr_bool(attrs.get("global_pool", False), False):
        red = (tuple(range(1, x.ndim - 1)) if nhwc
               else tuple(range(2, x.ndim)))
        if ptype == "max":
            return (jnp.max(x, axis=red, keepdims=True),)
        if ptype == "sum":
            return (jnp.sum(x, axis=red, keepdims=True),)
        return (jnp.mean(x, axis=red, keepdims=True),)
    kernel = attr_tuple(attrs["kernel"])
    nd = len(kernel)
    stride = attr_tuple(attrs.get("stride", (1,) * nd), (1,) * nd)
    pad = attr_tuple(attrs.get("pad", (0,) * nd), (0,) * nd)
    conv = attr_str(attrs.get("pooling_convention", "valid"), "valid")
    # explicit padding incl. ceil-mode extra on the high side
    sp0 = 1 if nhwc else 2
    pads = [(0, 0)]
    for i in range(nd):
        out = _pool_out_dim(x.shape[sp0 + i], kernel[i], stride[i], pad[i],
                            conv)
        needed = (out - 1) * stride[i] + kernel[i] - x.shape[sp0 + i]
        pads.append((pad[i], max(pad[i], needed - pad[i])))
    if nhwc:
        pads = pads + [(0, 0)]
        wdims = (1,) + kernel + (1,)
        wstrides = (1,) + stride + (1,)
    else:
        pads = [pads[0]] + [(0, 0)] + pads[1:]
        wdims = (1, 1) + kernel
        wstrides = (1, 1) + stride
    if ptype == "max":
        # init must be a python literal, not a traced array — JAX's
        # reduce_window vjp rule only fires on the recognized monoid
        init = (-float("inf") if jnp.issubdtype(x.dtype, jnp.floating)
                else int(jnp.iinfo(x.dtype).min))
        y = jax.lax.reduce_window(x, init, jax.lax.max, wdims, wstrides, pads)
        return (y,)
    zero = 0.0 if jnp.issubdtype(x.dtype, jnp.floating) else 0
    y = jax.lax.reduce_window(x, zero, jax.lax.add, wdims, wstrides, pads)
    if ptype == "avg":
        area = 1
        for k in kernel:
            area *= k
        y = y / area
    return (y,)


register_def(OpDef("Pooling", _pooling, inputs=("data",), infer_shape=_pool_infer))


@register("Dropout", inputs=("data",), needs_rng=True)
def _dropout(op_ctx, attrs, inputs, aux):
    p = attr_float(attrs.get("p", 0.5), 0.5)
    x = inputs[0]
    if not op_ctx.is_train or p <= 0.0:
        return (x,)
    if op_ctx.rng is None:
        raise MXNetError("Dropout requires rng in training mode")
    keep = 1.0 - p
    mask = jax.random.bernoulli(op_ctx.rng, keep, x.shape)
    return (jnp.where(mask, x / keep, 0.0).astype(x.dtype),)


# ---------------------------------------------------------------------------
# Loss / output layers. Reference contract: forward transforms data; backward
# *produces* d(loss)/d(data) ignoring out_grad (loss layers are graph heads).
# ---------------------------------------------------------------------------

def _softmax_out_infer(attrs, in_shapes):
    data = in_shapes[0]
    if data is None:
        raise MXNetError("SoftmaxOutput: data shape required")
    multi = attr_bool(attrs.get("multi_output", False), False)
    preserve = attr_bool(attrs.get("preserve_shape", False), False)
    if preserve:
        label = tuple(data[:-1])
    elif multi:
        label = (data[0],) + tuple(data[2:])
    else:
        label = (data[0],)
    return [tuple(data), label], [tuple(data)], []


@functools.lru_cache(maxsize=None)
def _make_softmax_output(grad_scale, ignore_label, use_ignore, multi_output,
                         normalization, preserve_shape=False):
    """custom_vjp closure over the static attrs (jax.custom_vjp args must all
    be jax types)."""

    def _softmax_fwd(data):
        if preserve_shape:
            # ref preserve_shape: softmax over the LAST dim, shape kept —
            # (b, s, v) logits with (b, s) labels never flatten, so a
            # data x seq sharded LM head stays gather-free
            return jax.nn.softmax(data, axis=-1)
        if multi_output:
            return jax.nn.softmax(data, axis=1)
        return jax.nn.softmax(data.reshape(data.shape[0], -1),
                              axis=-1).reshape(data.shape)

    @jax.custom_vjp
    def softmax_output(data, label):
        return _softmax_fwd(data)

    def fwd(data, label):
        out = _softmax_fwd(data)
        return out, (out, label)

    def bwd(res, g):
        out, label = res
        if preserve_shape:
            lab = label.astype(jnp.int32)
            oh = jax.nn.one_hot(lab, out.shape[-1], dtype=out.dtype)
            grad = out - oh
            valid = jnp.ones(lab.shape, out.dtype)
            if use_ignore:
                valid = (lab != int(ignore_label)).astype(out.dtype)
                grad = grad * valid[..., None]
        elif multi_output:
            lab = label.astype(jnp.int32)
            oh = jax.nn.one_hot(lab, out.shape[1], axis=1, dtype=out.dtype)
            grad = out - oh
            valid = jnp.ones(lab.shape, out.dtype)
            if use_ignore:
                valid = (lab != int(ignore_label)).astype(out.dtype)
                grad = grad * valid[:, None]
        else:
            lab = label.reshape(label.shape[0]).astype(jnp.int32)
            oh = jax.nn.one_hot(lab, out.shape[1], dtype=out.dtype)
            grad = out - oh.reshape(out.shape)
            valid = jnp.ones(lab.shape, out.dtype)
            if use_ignore:
                valid = (lab != int(ignore_label)).astype(out.dtype)
                grad = grad * valid.reshape((-1,) + (1,) * (out.ndim - 1))
        if normalization == "batch":
            grad = grad / out.shape[0]
        elif normalization == "valid":
            grad = grad / jnp.maximum(jnp.sum(valid), 1.0)
        grad = grad * grad_scale
        return (grad, jnp.zeros_like(label))

    softmax_output.defvjp(fwd, bwd)
    return softmax_output


@register("SoftmaxOutput", inputs=("data", "label"),
          infer_shape=_softmax_out_infer, aliases=("Softmax",))
def _softmax_output(op_ctx, attrs, inputs, aux):
    gs = attr_float(attrs.get("grad_scale", 1.0), 1.0)
    il = attr_float(attrs.get("ignore_label", -1.0), -1.0)
    ui = attr_bool(attrs.get("use_ignore", False), False)
    mo = attr_bool(attrs.get("multi_output", False), False)
    ps = attr_bool(attrs.get("preserve_shape", False), False)
    norm = attr_str(attrs.get("normalization", "null"), "null")
    fn = _make_softmax_output(gs, il, ui, mo, norm, preserve_shape=ps)
    return (fn(inputs[0], inputs[1]),)


def _regression_infer(attrs, in_shapes):
    data = in_shapes[0]
    if data is None:
        raise MXNetError("regression output: data shape required")
    return [tuple(data), tuple(data)], [tuple(data)], []


@functools.lru_cache(maxsize=None)
def _make_regression(kind, grad_scale):
    transform = {"linear": lambda x: x, "logistic": jax.nn.sigmoid,
                 "mae": lambda x: x}[kind]
    grad_fn = {"linear": lambda o, l: (o - l),
               "logistic": lambda o, l: (o - l),
               "mae": lambda o, l: jnp.sign(o - l)}[kind]

    @jax.custom_vjp
    def reg(data, label):
        return transform(data)

    def fwd(data, label):
        out = transform(data)
        return out, (out, label)

    def bwd(res, g):
        out, label = res
        grad = grad_fn(out, label.reshape(out.shape)) * grad_scale
        return (grad, jnp.zeros_like(label))

    reg.defvjp(fwd, bwd)
    return reg


def _reg_op(kind):
    def op(op_ctx, attrs, inputs, aux):
        gs = attr_float(attrs.get("grad_scale", 1.0), 1.0)
        return (_make_regression(kind, gs)(inputs[0], inputs[1]),)
    return op


register_def(OpDef("LinearRegressionOutput", _reg_op("linear"),
                   inputs=("data", "label"), infer_shape=_regression_infer))
register_def(OpDef("LogisticRegressionOutput", _reg_op("logistic"),
                   inputs=("data", "label"), infer_shape=_regression_infer))
register_def(OpDef("MAERegressionOutput", _reg_op("mae"),
                   inputs=("data", "label"), infer_shape=_regression_infer))


@functools.lru_cache(maxsize=None)
def _make_loss_fn(grad_scale):
    @jax.custom_vjp
    def make_loss(data):
        return data

    def fwd(data):
        return data, None

    def bwd(res, g):
        # the cotangent carries shape/dtype; its value is ignored (ref:
        # make_loss backward emits grad_scale regardless of out_grad)
        return (jnp.full(g.shape, grad_scale, g.dtype),)

    make_loss.defvjp(fwd, bwd)
    return make_loss


@register("MakeLoss", inputs=("data",))
def _makeloss(op_ctx, attrs, inputs, aux):
    gs = attr_float(attrs.get("grad_scale", 1.0), 1.0)
    norm = attr_str(attrs.get("normalization", "null"), "null")
    x = inputs[0]
    if norm == "batch":
        gs = gs / x.shape[0]
    return (_make_loss_fn(gs)(x),)


@functools.lru_cache(maxsize=None)
def _make_svm(margin, reg_coef, use_linear):
    @jax.custom_vjp
    def svm(data, label):
        return data

    def fwd(data, label):
        return data, (data, label)

    def bwd(res, g):
        data, label = res
        lab = label.reshape(label.shape[0]).astype(jnp.int32)
        oh = jax.nn.one_hot(lab, data.shape[1], dtype=data.dtype)
        score_correct = jnp.take_along_axis(data, lab[:, None], axis=1)
        m = margin - (score_correct - data)
        if use_linear:  # L1-SVM hinge
            viol = (m > 0).astype(data.dtype) * (1 - oh)
            grad = reg_coef * (viol - oh * jnp.sum(viol, axis=1, keepdims=True))
        else:  # L2-SVM squared hinge
            viol = jnp.maximum(m, 0) * (1 - oh)
            grad = 2 * reg_coef * (viol - oh * jnp.sum(viol, axis=1,
                                                       keepdims=True))
        return (grad, jnp.zeros_like(label))

    svm.defvjp(fwd, bwd)
    return svm


@register("SVMOutput", inputs=("data", "label"), infer_shape=_softmax_out_infer)
def _svm_output(op_ctx, attrs, inputs, aux):
    margin = attr_float(attrs.get("margin", 1.0), 1.0)
    reg = attr_float(attrs.get("regularization_coefficient", 1.0), 1.0)
    lin = attr_bool(attrs.get("use_linear", False), False)
    return (_make_svm(margin, reg, lin)(inputs[0], inputs[1]),)


# ---------------------------------------------------------------------------
# Concat / SliceChannel (ref: concat-inl.h:244, slice_channel-inl.h:269)
# ---------------------------------------------------------------------------

def _concat_infer(attrs, in_shapes):
    dim = attr_int(attrs.get("dim", 1), 1)
    known = [s for s in in_shapes if s is not None]
    if not known:
        raise MXNetError("Concat: at least one input shape required")
    base = list(known[0])
    total = 0
    filled = []
    for s in in_shapes:
        if s is None:
            s = tuple(base)  # assume same as first (common weight-free case)
        total += s[dim]
        filled.append(tuple(s))
    out = list(filled[0])
    out[dim] = sum(s[dim] for s in filled)
    return filled, [tuple(out)], []


@register("Concat", var_inputs_attr="num_args", infer_shape=_concat_infer,
          aliases=("concat",))
def _concat(op_ctx, attrs, inputs, aux):
    dim = attr_int(attrs.get("dim", 1), 1)
    return (jnp.concatenate(inputs, axis=dim),)


def _slice_channel_outputs(attrs):
    n = attr_int(attrs.get("num_outputs", 1), 1)
    return ["output%d" % i for i in range(n)]


@register("SliceChannel", inputs=("data",), var_outputs=_slice_channel_outputs,
          aliases=("split",))
def _slice_channel(op_ctx, attrs, inputs, aux):
    n = attr_int(attrs.get("num_outputs", 1), 1)
    ax = attr_int(attrs.get("axis", 1), 1)
    squeeze = attr_bool(attrs.get("squeeze_axis", False), False)
    parts = jnp.split(inputs[0], n, axis=ax)
    if squeeze:
        parts = [p.squeeze(ax) for p in parts]
    return tuple(parts)


# ---------------------------------------------------------------------------
# Pad / UpSampling / Crop (ref: pad.cc:735, upsampling-inl.h:318, crop-inl.h)
# ---------------------------------------------------------------------------

@register("Pad", inputs=("data",), aliases=("pad",))
def _pad(op_ctx, attrs, inputs, aux):
    mode = attr_str(attrs.get("mode", "constant"), "constant")
    pw = attr_tuple(attrs["pad_width"])
    cv = attr_float(attrs.get("constant_value", 0.0), 0.0)
    x = inputs[0]
    pairs = [(pw[2 * i], pw[2 * i + 1]) for i in range(x.ndim)]
    if mode == "constant":
        return (jnp.pad(x, pairs, constant_values=cv),)
    if mode == "edge":
        return (jnp.pad(x, pairs, mode="edge"),)
    if mode == "reflect":
        return (jnp.pad(x, pairs, mode="reflect"),)
    raise MXNetError("Pad: unknown mode %r" % mode)


@register("UpSampling", var_inputs_attr="num_args", infer_shape=None)
def _upsampling(op_ctx, attrs, inputs, aux):
    scale = attr_int(attrs["scale"])
    stype = attr_str(attrs.get("sample_type", "nearest"), "nearest")
    if stype == "nearest":
        outs = []
        target = None
        for x in inputs:
            y = jnp.repeat(jnp.repeat(x, scale, axis=2), scale, axis=3)
            if target is None:
                target = y.shape[2:]
            outs.append(y[:, :, :target[0], :target[1]])
        return (jnp.concatenate(outs, axis=1) if len(outs) > 1 else outs[0],)
    raise MXNetError("UpSampling: sample_type %r not yet supported" % stype)


def _crop_inputs(attrs):
    n = attr_int(attrs.get("num_args", 1), 1)
    return ["data", "crop_like"] if n == 2 else ["data"]


def _crop(op_ctx, attrs, inputs, aux):
    x = inputs[0]
    if len(inputs) == 2:
        th, tw = inputs[1].shape[2], inputs[1].shape[3]
    else:
        hw = attr_tuple(attrs["h_w"])
        th, tw = hw[0], hw[1]
    if attr_bool(attrs.get("center_crop", False), False):
        oy = (x.shape[2] - th) // 2
        ox = (x.shape[3] - tw) // 2
    else:
        off = attr_tuple(attrs.get("offset", (0, 0)), (0, 0))
        oy, ox = off[0], off[1]
    return (x[:, :, oy:oy + th, ox:ox + tw],)


_CROP = register_def(OpDef("Crop", _crop, inputs=("data",)))
_CROP.list_inputs = _crop_inputs


# ---------------------------------------------------------------------------
# Sequence ops (ref: sequence_last/mask/reverse-inl.h). Sequence axis 0,
# batch axis 1 — matching the reference's (T, N, ...) layout.
# ---------------------------------------------------------------------------

def _seq_inputs(attrs):
    if attr_bool(attrs.get("use_sequence_length", False), False):
        return ["data", "sequence_length"]
    return ["data"]


def _seq_op(name, fn):
    od = register_def(OpDef(name, fn, inputs=("data",)))
    od.list_inputs = _seq_inputs


def _sequence_last(op_ctx, attrs, inputs, aux):
    x = inputs[0]
    if len(inputs) == 2:
        idx = (inputs[1].astype(jnp.int32) - 1).clip(0, x.shape[0] - 1)
        return (jnp.take_along_axis(
            x, idx.reshape((1, -1) + (1,) * (x.ndim - 2)), axis=0).squeeze(0),)
    return (x[-1],)


def _sequence_mask(op_ctx, attrs, inputs, aux):
    x = inputs[0]
    val = attr_float(attrs.get("value", 0.0), 0.0)
    if len(inputs) == 1:
        return (x,)
    t = jnp.arange(x.shape[0]).reshape((-1, 1) + (1,) * (x.ndim - 2))
    mask = t < inputs[1].astype(jnp.int32).reshape((1, -1) + (1,) * (x.ndim - 2))
    return (jnp.where(mask, x, val).astype(x.dtype),)


def _sequence_reverse(op_ctx, attrs, inputs, aux):
    x = inputs[0]
    if len(inputs) == 1:
        return (jnp.flip(x, axis=0),)
    seq_len = inputs[1].astype(jnp.int32)
    t = jnp.arange(x.shape[0])[:, None]
    rev_idx = jnp.where(t < seq_len[None, :], seq_len[None, :] - 1 - t, t)
    return (jnp.take_along_axis(
        x, rev_idx.reshape(rev_idx.shape + (1,) * (x.ndim - 2)), axis=0),)


_seq_op("SequenceLast", _sequence_last)
_seq_op("SequenceMask", _sequence_mask)
_seq_op("SequenceReverse", _sequence_reverse)


@register("IdentityAttachKLSparseReg", inputs=("data",))
def _id_kl_sparse(op_ctx, attrs, inputs, aux):
    return (inputs[0],)
