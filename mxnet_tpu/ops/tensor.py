"""Tensor operators: elementwise, broadcast, reduce, matrix, indexing,
ordering, init, sampling, control flow.

TPU-native coverage of the reference's tensor op menu
(ref: src/operator/tensor/elemwise_*_op*, broadcast_reduce_op.h,
matrix_op-inl.h, indexing_op.h, ordering_op-inl.h, init_op.h, sample_op.h,
control_flow_op.h; functor menu ref: src/operator/mshadow_op.h). Every kernel
is a pure jnp/lax emission — XLA fuses the elementwise chains that the
reference's engine bulked into segments, and gradients come from jax.vjp
instead of registered _backward_* ops.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.scipy.special import gammaln as _gammaln

from ..base import attr_bool, attr_float, attr_int, attr_tuple, MXNetError
from .registry import (OpDef, register, register_def, register_unary,
                       register_binary, register_binary_scalar)

# ---------------------------------------------------------------------------
# unary math menu (ref: mshadow_op.h:1-892)
# ---------------------------------------------------------------------------
_UNARY = {
    "abs": jnp.abs, "sign": jnp.sign, "rint": jnp.rint,
    "ceil": jnp.ceil, "floor": jnp.floor, "round": jnp.round,
    "fix": jnp.trunc, "square": jnp.square, "sqrt": jnp.sqrt,
    "rsqrt": lambda x: jax.lax.rsqrt(x), "exp": jnp.exp,
    "log": jnp.log, "log10": jnp.log10, "log2": jnp.log2,
    "log1p": jnp.log1p, "expm1": jnp.expm1,
    "sin": jnp.sin, "cos": jnp.cos, "tan": jnp.tan,
    "arcsin": jnp.arcsin, "arccos": jnp.arccos, "arctan": jnp.arctan,
    "sinh": jnp.sinh, "cosh": jnp.cosh, "tanh": jnp.tanh,
    "arcsinh": jnp.arcsinh, "arccosh": jnp.arccosh, "arctanh": jnp.arctanh,
    "degrees": jnp.degrees, "radians": jnp.radians,
    "gamma": lambda x: jnp.exp(_gammaln(x)), "gammaln": _gammaln,
    "negative": jnp.negative, "sigmoid": jax.nn.sigmoid,
    "relu": jax.nn.relu, "softsign": jax.nn.soft_sign,
    "erf": jax.lax.erf, "reciprocal": jnp.reciprocal,
}
for _n, _f in _UNARY.items():
    register_unary(_n, _f)

register_unary("identity", lambda x: x, aliases=("_copy",))


@register("BlockGrad", inputs=("data",), aliases=("stop_gradient",))
def _block_grad(op_ctx, attrs, inputs, aux):
    return (jax.lax.stop_gradient(inputs[0]),)


@register("Cast", inputs=("data",), aliases=("cast",))
def _cast(op_ctx, attrs, inputs, aux):
    return (inputs[0].astype(jnp.dtype(str(attrs["dtype"]))),)


@register("clip", inputs=("data",))
def _clip(op_ctx, attrs, inputs, aux):
    return (jnp.clip(inputs[0], attr_float(attrs.get("a_min")),
                     attr_float(attrs.get("a_max"))),)


@register("smooth_l1", inputs=("data",))
def _smooth_l1(op_ctx, attrs, inputs, aux):
    # ref: mshadow_op.h smooth_l1_loss — f(x)=0.5(sx)^2 if |x|<1/s^2 else |x|-0.5/s^2
    s = attr_float(attrs.get("scalar", 1.0), 1.0)
    x = inputs[0]
    s2 = s * s
    return (jnp.where(jnp.abs(x) < 1.0 / s2,
                      0.5 * s2 * x * x,
                      jnp.abs(x) - 0.5 / s2),)


# ---------------------------------------------------------------------------
# binary: same-shape elemwise (ref: elemwise_binary_op.h), broadcast
# (ref: elemwise_binary_broadcast_op.h), scalar (ref: *_scalar_op.h)
# ---------------------------------------------------------------------------
_BINARY = {
    "add": jnp.add, "sub": jnp.subtract, "mul": jnp.multiply,
    "div": jnp.divide, "power": jnp.power, "maximum": jnp.maximum,
    "minimum": jnp.minimum, "hypot": jnp.hypot, "mod": jnp.mod,
    "equal": lambda a, b: (a == b).astype(a.dtype),
    "not_equal": lambda a, b: (a != b).astype(a.dtype),
    "greater": lambda a, b: (a > b).astype(a.dtype),
    "greater_equal": lambda a, b: (a >= b).astype(a.dtype),
    "lesser": lambda a, b: (a < b).astype(a.dtype),
    "lesser_equal": lambda a, b: (a <= b).astype(a.dtype),
}
for _n, _f in _BINARY.items():
    register_binary("_" + _n, _f, aliases=("elemwise_" + _n,))
    register_binary("broadcast_" + _n, _f)

register_binary("_plus", jnp.add)
register_binary("_minus", jnp.subtract)
register_binary("broadcast_plus", jnp.add)
register_binary("broadcast_minus", jnp.subtract)
register_binary("_grad_add", jnp.add)
# public maximum/minimum: the reference exposes mx.nd.maximum(lhs, rhs)
# delegating to broadcast_maximum (ref: python/mxnet/ndarray.py:1497)
register_binary("maximum", jnp.maximum)
register_binary("minimum", jnp.minimum)

for _n, _f in _BINARY.items():
    register_binary_scalar("_%s_scalar" % _n, _f)
register_binary_scalar("_plus_scalar", jnp.add)
register_binary_scalar("_minus_scalar", jnp.subtract)
register_binary_scalar("_rminus_scalar", lambda x, s: s - x)
register_binary_scalar("_rdiv_scalar", lambda x, s: s / x)
register_binary_scalar("_rpower_scalar", lambda x, s: jnp.power(s, x))
register_binary_scalar("_rmod_scalar", lambda x, s: jnp.mod(s, x))
register_binary_scalar("_maximum_scalar", jnp.maximum)
register_binary_scalar("_minimum_scalar", jnp.minimum)
register_binary_scalar("_hypot_scalar", jnp.hypot)


# ---------------------------------------------------------------------------
# reductions (ref: tensor/broadcast_reduce_op.h)
# ---------------------------------------------------------------------------

def _parse_axis(attrs, ndim):
    ax = attrs.get("axis", None)
    if ax is None or ax == "":
        return None
    ax = attr_tuple(ax)
    return tuple(a % ndim for a in ax)


def _register_reduce(name, jfn, aliases=()):
    def fn(op_ctx, attrs, inputs, aux):
        x = inputs[0]
        axis = _parse_axis(attrs, x.ndim)
        keepdims = attr_bool(attrs.get("keepdims", False), False)
        return (jfn(x, axis=axis, keepdims=keepdims),)
    register_def(OpDef(name, fn, inputs=("data",)), aliases=aliases)


_register_reduce("sum", jnp.sum, aliases=("sum_axis",))
_register_reduce("mean", jnp.mean)
_register_reduce("prod", jnp.prod)
_register_reduce("nansum", jnp.nansum)
_register_reduce("nanprod", jnp.nanprod)
_register_reduce("max", jnp.max, aliases=("max_axis",))
_register_reduce("min", jnp.min, aliases=("min_axis",))


def _register_arg_reduce(name, jfn):
    def fn(op_ctx, attrs, inputs, aux):
        x = inputs[0]
        ax = attrs.get("axis", None)
        keepdims = attr_bool(attrs.get("keepdims", False), False)
        if ax is None or ax == "":
            # ref semantics: flatten, return float index
            r = jfn(x.reshape(-1))
            return (r.astype(x.dtype),)
        ax = attr_int(ax) % x.ndim
        r = jfn(x, axis=ax)
        if keepdims:
            r = jnp.expand_dims(r, ax)
        return (r.astype(x.dtype),)
    register_def(OpDef(name, fn, inputs=("data",)))


_register_arg_reduce("argmax", jnp.argmax)
_register_arg_reduce("argmin", jnp.argmin)


@register("argmax_channel", inputs=("data",))
def _argmax_channel(op_ctx, attrs, inputs, aux):
    x = inputs[0]
    return (jnp.argmax(x, axis=1).astype(x.dtype),)


@register("norm", inputs=("data",))
def _norm(op_ctx, attrs, inputs, aux):
    # ref: L2 norm of the whole array -> scalar shape (1,)
    x = inputs[0]
    return (jnp.sqrt(jnp.sum(jnp.square(x))).reshape(1),)


# ---------------------------------------------------------------------------
# broadcast shape ops
# ---------------------------------------------------------------------------

@register("broadcast_to", inputs=("data",))
def _broadcast_to(op_ctx, attrs, inputs, aux):
    x = inputs[0]
    shape = attr_tuple(attrs["shape"])
    # ref semantics: 0 in target shape means keep existing dim
    tgt = tuple(x.shape[i] if s == 0 else s for i, s in enumerate(shape))
    return (jnp.broadcast_to(x, tgt),)


@register("broadcast_axis", inputs=("data",), aliases=("broadcast_axes",))
def _broadcast_axis(op_ctx, attrs, inputs, aux):
    x = inputs[0]
    axes = attr_tuple(attrs["axis"])
    sizes = attr_tuple(attrs["size"])
    tgt = list(x.shape)
    for a, s in zip(axes, sizes):
        tgt[a % x.ndim] = s
    return (jnp.broadcast_to(x, tuple(tgt)),)


# ---------------------------------------------------------------------------
# matrix / shape manipulation (ref: tensor/matrix_op-inl.h)
# ---------------------------------------------------------------------------

def _reshape_target(shape_attr, src_shape):
    """Implements the reference Reshape's special codes 0, -1, -2, -3, -4
    (ref: matrix_op-inl.h ReshapeParam)."""
    target = list(shape_attr)
    src = list(src_shape)
    out = []
    src_idx = 0
    i = 0
    while i < len(target):
        s = target[i]
        if s == 0:
            out.append(src[src_idx]); src_idx += 1
        elif s == -1:
            out.append(-1); src_idx += 1
        elif s == -2:
            out.extend(src[src_idx:]); src_idx = len(src)
        elif s == -3:
            out.append(src[src_idx] * src[src_idx + 1]); src_idx += 2
        elif s == -4:
            d1, d2 = target[i + 1], target[i + 2]
            cur = src[src_idx]; src_idx += 1
            if d1 == -1:
                d1 = cur // d2
            if d2 == -1:
                d2 = cur // d1
            out.extend([d1, d2]); i += 2
        else:
            out.append(s); src_idx += 1
        i += 1
    return tuple(out)


@register("Reshape", inputs=("data",), aliases=("reshape",))
def _reshape(op_ctx, attrs, inputs, aux):
    x = inputs[0]
    if "shape" in attrs and attrs["shape"] not in (None, ""):
        tgt = _reshape_target(attr_tuple(attrs["shape"]), x.shape)
    elif attr_bool(attrs.get("reverse", False), False):
        raise MXNetError("Reshape: reverse without shape unsupported")
    else:
        raise MXNetError("Reshape requires shape attr")
    return (jnp.reshape(x, tgt),)


@register("Flatten", inputs=("data",), aliases=("flatten",))
def _flatten(op_ctx, attrs, inputs, aux):
    x = inputs[0]
    return (jnp.reshape(x, (x.shape[0], -1)),)


@register("transpose", inputs=("data",))
def _transpose(op_ctx, attrs, inputs, aux):
    x = inputs[0]
    axes = attrs.get("axes", None)
    axes = attr_tuple(axes) if axes not in (None, "", ()) else None
    return (jnp.transpose(x, axes),)


@register("expand_dims", inputs=("data",))
def _expand_dims(op_ctx, attrs, inputs, aux):
    return (jnp.expand_dims(inputs[0], attr_int(attrs["axis"])),)


@register("SwapAxis", inputs=("data",), aliases=("swapaxes",))
def _swapaxis(op_ctx, attrs, inputs, aux):
    return (jnp.swapaxes(inputs[0], attr_int(attrs.get("dim1", 0), 0),
                         attr_int(attrs.get("dim2", 0), 0)),)


@register("slice", inputs=("data",), aliases=("crop",))
def _slice(op_ctx, attrs, inputs, aux):
    x = inputs[0]
    begin = attr_tuple(attrs["begin"])
    end = attr_tuple(attrs["end"])
    idx = tuple(slice(b, e) for b, e in zip(begin, end))
    return (x[idx],)


@register("slice_axis", inputs=("data",))
def _slice_axis(op_ctx, attrs, inputs, aux):
    x = inputs[0]
    ax = attr_int(attrs["axis"]) % x.ndim
    b = attr_int(attrs["begin"], 0) or 0
    e = attrs.get("end", None)
    e = x.shape[ax] if e in (None, "None", "") else attr_int(e)
    if b < 0:
        b += x.shape[ax]
    if e < 0:
        e += x.shape[ax]
    idx = [slice(None)] * x.ndim
    idx[ax] = slice(b, e)
    return (x[tuple(idx)],)


@register("flip", inputs=("data",), aliases=("reverse",))
def _flip(op_ctx, attrs, inputs, aux):
    x = inputs[0]
    axes = attr_tuple(attrs["axis"])
    return (jnp.flip(x, axes),)


@register("repeat", inputs=("data",))
def _repeat(op_ctx, attrs, inputs, aux):
    x = inputs[0]
    reps = attr_int(attrs["repeats"])
    ax = attrs.get("axis", None)
    ax = attr_int(ax) if ax not in (None, "", "None") else None
    return (jnp.repeat(x, reps, axis=ax),)


@register("tile", inputs=("data",))
def _tile(op_ctx, attrs, inputs, aux):
    return (jnp.tile(inputs[0], attr_tuple(attrs["reps"])),)


def _dot_infer(attrs, in_shapes):
    a, b = in_shapes
    ta = attr_bool(attrs.get("transpose_a", False), False)
    tb = attr_bool(attrs.get("transpose_b", False), False)
    if a is None or b is None:
        raise MXNetError("dot: both input shapes required")
    ar = a[::-1] if ta else a
    br = b[::-1] if tb else b
    out = tuple(ar[:-1]) + tuple(br[1:])
    return [list(in_shapes)[0], list(in_shapes)[1]], [out], []


@register("dot", inputs=("lhs", "rhs"))
def _dot(op_ctx, attrs, inputs, aux):
    a, b = inputs
    if attr_bool(attrs.get("transpose_a", False), False):
        a = a.T
    if attr_bool(attrs.get("transpose_b", False), False):
        b = b.T
    return (jnp.dot(a, b),)


@register("batch_dot", inputs=("lhs", "rhs"))
def _batch_dot(op_ctx, attrs, inputs, aux):
    a, b = inputs
    if attr_bool(attrs.get("transpose_a", False), False):
        a = jnp.swapaxes(a, -1, -2)
    if attr_bool(attrs.get("transpose_b", False), False):
        b = jnp.swapaxes(b, -1, -2)
    return (jnp.matmul(a, b),)


# ---------------------------------------------------------------------------
# indexing & embedding (ref: tensor/indexing_op.h)
# ---------------------------------------------------------------------------

def _embedding_infer(attrs, in_shapes):
    data, weight = in_shapes
    in_dim = attr_int(attrs["input_dim"])
    out_dim = attr_int(attrs["output_dim"])
    weight = (in_dim, out_dim)
    if data is None:
        raise MXNetError("Embedding: data shape required")
    return [data, weight], [tuple(data) + (out_dim,)], []


@register("Embedding", inputs=("data", "weight"), infer_shape=_embedding_infer)
def _embedding(op_ctx, attrs, inputs, aux):
    data, weight = inputs
    idx = data.astype(jnp.int32)
    return (jnp.take(weight, idx, axis=0),)


@register("take", inputs=("a", "indices"))
def _take(op_ctx, attrs, inputs, aux):
    a, idx = inputs
    ax = attr_int(attrs.get("axis", 0), 0)
    mode = str(attrs.get("mode", "clip"))
    idx = idx.astype(jnp.int32)
    if mode == "clip":
        idx = jnp.clip(idx, 0, a.shape[ax] - 1)
    elif mode == "wrap":
        idx = jnp.mod(idx, a.shape[ax])
    return (jnp.take(a, idx, axis=ax),)


@register("batch_take", inputs=("a", "indices"))
def _batch_take(op_ctx, attrs, inputs, aux):
    a, idx = inputs
    return (jnp.take_along_axis(a, idx.astype(jnp.int32)[:, None],
                                axis=1).squeeze(1),)


@register("one_hot", inputs=("indices",))
def _one_hot(op_ctx, attrs, inputs, aux):
    depth = attr_int(attrs["depth"])
    on_v = attr_float(attrs.get("on_value", 1.0), 1.0)
    off_v = attr_float(attrs.get("off_value", 0.0), 0.0)
    dt = jnp.dtype(str(attrs.get("dtype", "float32")))
    idx = inputs[0].astype(jnp.int32)
    oh = jax.nn.one_hot(idx, depth, dtype=dt)
    return ((oh * (on_v - off_v) + off_v).astype(dt),)


@register("where", inputs=("condition", "x", "y"))
def _where(op_ctx, attrs, inputs, aux):
    cond, x, y = inputs
    if cond.ndim == 1 and x.ndim > 1:
        cond = cond.reshape((-1,) + (1,) * (x.ndim - 1))
    return (jnp.where(cond != 0, x, y),)


# ---------------------------------------------------------------------------
# ordering (ref: tensor/ordering_op-inl.h)
# ---------------------------------------------------------------------------

def _topk_outputs(attrs):
    rt = str(attrs.get("ret_typ", "indices"))
    return ["output0", "output1"] if rt == "both" else ["output"]


@register("topk", inputs=("data",), var_outputs=_topk_outputs)
def _topk(op_ctx, attrs, inputs, aux):
    x = inputs[0]
    ax = attr_int(attrs.get("axis", -1), -1)
    k = attr_int(attrs.get("k", 1), 1)
    rt = str(attrs.get("ret_typ", "indices"))
    is_ascend = attr_bool(attrs.get("is_ascend", False), False)
    ax = ax % x.ndim
    xm = jnp.moveaxis(x, ax, -1)
    vals, idxs = jax.lax.top_k(-xm if is_ascend else xm, k)
    if is_ascend:
        vals = -vals
    vals = jnp.moveaxis(vals, -1, ax)
    idxs = jnp.moveaxis(idxs, -1, ax).astype(x.dtype)
    if rt == "value":
        return (vals,)
    if rt == "both":
        return (vals, idxs)
    if rt == "mask":
        raise MXNetError("topk ret_typ=mask not yet supported")
    return (idxs,)


@register("sort", inputs=("data",))
def _sort(op_ctx, attrs, inputs, aux):
    x = inputs[0]
    ax = attr_int(attrs.get("axis", -1), -1)
    asc = attr_bool(attrs.get("is_ascend", True), True)
    r = jnp.sort(x, axis=ax)
    return (r if asc else jnp.flip(r, axis=ax),)


@register("argsort", inputs=("data",))
def _argsort(op_ctx, attrs, inputs, aux):
    x = inputs[0]
    ax = attr_int(attrs.get("axis", -1), -1)
    asc = attr_bool(attrs.get("is_ascend", True), True)
    r = jnp.argsort(x, axis=ax)
    if not asc:
        r = jnp.flip(r, axis=ax)
    return (r.astype(x.dtype),)


# ---------------------------------------------------------------------------
# init ops (ref: tensor/init_op.h) — imperative-only creators also route here
# ---------------------------------------------------------------------------

def _creation_shape_infer(attrs, in_shapes):
    shape = attr_tuple(attrs.get("shape", (1,)), (1,))
    return [], [shape], []


def _register_filler(name, fill):
    def fn(op_ctx, attrs, inputs, aux):
        shape = attr_tuple(attrs["shape"])
        dt = jnp.dtype(str(attrs.get("dtype", "float32")))
        return (jnp.full(shape, fill, dtype=dt),)
    register_def(OpDef(name, fn, inputs=(), infer_shape=_creation_shape_infer))


_register_filler("_zeros", 0)
_register_filler("_ones", 1)


@register("_full", inputs=(), infer_shape=_creation_shape_infer)
def _full(op_ctx, attrs, inputs, aux):
    shape = attr_tuple(attrs["shape"])
    dt = jnp.dtype(str(attrs.get("dtype", "float32")))
    return (jnp.full(shape, attr_float(attrs.get("value", 0.0), 0.0), dtype=dt),)


@register("zeros_like", inputs=("data",))
def _zeros_like(op_ctx, attrs, inputs, aux):
    return (jnp.zeros_like(inputs[0]),)


@register("ones_like", inputs=("data",))
def _ones_like(op_ctx, attrs, inputs, aux):
    return (jnp.ones_like(inputs[0]),)


def _arange_infer(attrs, in_shapes):
    start = attr_float(attrs.get("start", 0.0), 0.0)
    stop = attrs.get("stop", None)
    step = attr_float(attrs.get("step", 1.0), 1.0)
    rep = attr_int(attrs.get("repeat", 1), 1)
    if stop in (None, "None", ""):
        start, stop = 0.0, start
    else:
        stop = attr_float(stop)
    import math
    n = max(0, int(math.ceil((stop - start) / step)))
    return [], [(n * rep,)], []


@register("_arange", inputs=(), infer_shape=_arange_infer)
def _arange(op_ctx, attrs, inputs, aux):
    start = attr_float(attrs.get("start", 0.0), 0.0)
    stop = attrs.get("stop", None)
    step = attr_float(attrs.get("step", 1.0), 1.0)
    rep = attr_int(attrs.get("repeat", 1), 1)
    dt = jnp.dtype(str(attrs.get("dtype", "float32")))
    if stop in (None, "None", ""):
        start, stop = 0.0, start
    else:
        stop = attr_float(stop)
    r = jnp.arange(start, stop, step, dtype=dt)
    if rep > 1:
        r = jnp.repeat(r, rep)
    return (r,)


# ---------------------------------------------------------------------------
# random sampling (ref: tensor/sample_op.h) — functional PRNG, needs_rng
# ---------------------------------------------------------------------------

def _register_sample(name, draw, aliases=()):
    def fn(op_ctx, attrs, inputs, aux):
        if op_ctx.rng is None:
            raise MXNetError("op %s requires a PRNG key (rng resource)" % name)
        shape = attr_tuple(attrs.get("shape", (1,)), (1,))
        dt = jnp.dtype(str(attrs.get("dtype", "float32")))
        return (draw(op_ctx.rng, attrs, shape, dt),)
    register_def(OpDef(name, fn, inputs=(), needs_rng=True,
                       infer_shape=_creation_shape_infer), aliases=aliases)


_register_sample(
    "_random_uniform",
    lambda key, attrs, shape, dt: jax.random.uniform(
        key, shape, dtype=dt,
        minval=attr_float(attrs.get("low", 0.0), 0.0),
        maxval=attr_float(attrs.get("high", 1.0), 1.0)),
    aliases=("uniform", "random_uniform"))

_register_sample(
    "_random_normal",
    lambda key, attrs, shape, dt: (
        attr_float(attrs.get("loc", 0.0), 0.0)
        + attr_float(attrs.get("scale", 1.0), 1.0)
        * jax.random.normal(key, shape, dtype=dt)),
    aliases=("normal", "random_normal"))

_register_sample(
    "_random_gamma",
    lambda key, attrs, shape, dt: (
        jax.random.gamma(key, attr_float(attrs.get("alpha", 1.0), 1.0),
                         shape, dtype=dt)
        * attr_float(attrs.get("beta", 1.0), 1.0)),
    )

_register_sample(
    "_random_exponential",
    lambda key, attrs, shape, dt: (
        jax.random.exponential(key, shape, dtype=dt)
        / attr_float(attrs.get("lam", 1.0), 1.0)),
    )

_register_sample(
    "_random_poisson",
    lambda key, attrs, shape, dt: jax.random.poisson(
        key, attr_float(attrs.get("lam", 1.0), 1.0), shape).astype(dt),
    )

_register_sample(
    "_random_negative_binomial",
    lambda key, attrs, shape, dt: _neg_binomial(
        key, attr_int(attrs.get("k", 1), 1),
        attr_float(attrs.get("p", 1.0), 1.0), shape).astype(dt),
    )


def _neg_binomial(key, k, p, shape):
    # NB(k, p) = Poisson(Gamma(k, (1-p)/p))
    k1, k2 = jax.random.split(key)
    lam = jax.random.gamma(k1, k, shape) * ((1.0 - p) / p)
    return jax.random.poisson(k2, lam, shape)


# ---------------------------------------------------------------------------
# tensor-parameter multisampling (ref: tensor/multisample_op.cc): each entry
# of the parameter array(s) draws its own `shape`-shaped sample block;
# output shape = param.shape + shape
# ---------------------------------------------------------------------------

def _register_multisample(name, n_params, draw):
    def infer(attrs, in_shapes):
        p0 = in_shapes[0]
        if p0 is None:
            raise MXNetError("%s: parameter shape required" % name)
        tail = attr_tuple(attrs.get("shape", ()), ())
        return [tuple(p0)] * n_params, [tuple(p0) + tuple(tail)], []

    def fn(op_ctx, attrs, inputs, aux):
        if op_ctx.rng is None:
            raise MXNetError("op %s requires a PRNG key" % name)
        if len(inputs) != n_params:
            raise MXNetError("%s takes %d parameter array(s), got %d"
                             % (name, n_params, len(inputs)))
        tail = attr_tuple(attrs.get("shape", ()), ())
        dt = jnp.dtype(str(attrs.get("dtype", "float32")))
        pshape = inputs[0].shape
        flat = [jnp.ravel(p.astype(jnp.float32)) for p in inputs]
        n = flat[0].shape[0] if flat[0].ndim else 1
        keys = jax.random.split(op_ctx.rng, max(n, 1))
        out = jax.vmap(lambda k, *ps: draw(k, ps, tuple(tail)))(keys, *flat)
        return (out.reshape(tuple(pshape) + tuple(tail)).astype(dt),)

    inputs = ("low", "high")[:n_params] if "uniform" in name else \
        ("mu", "sigma")[:n_params] if "normal" in name else \
        ("alpha", "beta")[:n_params] if "gamma" in name else \
        ("k", "p")[:n_params] if "negbinomial" in name else ("lam",)
    register_def(OpDef(name, fn, inputs=inputs, needs_rng=True,
                       infer_shape=infer))


_register_multisample(
    "_sample_uniform", 2,
    lambda k, ps, sh: jax.random.uniform(k, sh) * (ps[1] - ps[0]) + ps[0])
_register_multisample(
    "_sample_normal", 2,
    lambda k, ps, sh: ps[0] + ps[1] * jax.random.normal(k, sh))
_register_multisample(
    "_sample_gamma", 2,
    lambda k, ps, sh: jax.random.gamma(k, ps[0], sh) * ps[1])
_register_multisample(
    "_sample_exponential", 1,
    lambda k, ps, sh: jax.random.exponential(k, sh) / ps[0])
_register_multisample(
    "_sample_poisson", 1,
    lambda k, ps, sh: jax.random.poisson(k, ps[0], sh).astype(jnp.float32))
_register_multisample(
    "_sample_negbinomial", 2,
    lambda k, ps, sh: _neg_binomial(k, ps[0], ps[1], sh).astype(jnp.float32))
