"""Symbol-level attention ops: the long-context flagship surface.

The reference's long-context stories are bucketing, fused RNN kernels and
layer-per-device model parallelism (SURVEY.md §5; the superseded pattern is
example/model-parallel-lstm/lstm.py:48-112). This module is the TPU-native
replacement: a MultiHeadAttention operator whose core is blockwise
(flash-style) attention, with optional sequence/context parallelism over
the mesh 'seq' axis — ring attention (K/V shards rotate over ICI neighbor
links via ppermute) or Ulysses (all-to-all head sharding). The parallel
modes activate under an ambient mesh (parallel.mesh.MeshScope / TrainStep
mesh) that has a 'seq' axis; single-chip execution uses the same blockwise
core, so numerics match across modes (tests/test_attention.py).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from ..base import attr_bool, attr_int, attr_float, attr_str, MXNetError
from .registry import OpDef, register_def


def _mha_attrs(attrs):
    num_heads = attr_int(attrs["num_heads"])
    causal = attr_bool(attrs.get("causal", False), False)
    no_bias = attr_bool(attrs.get("no_bias", False), False)
    seq_par = attr_str(attrs.get("seq_parallel", ""), "")
    block = attr_int(attrs.get("block_size", 0), 0)
    if seq_par not in ("", "ring", "ulysses"):
        raise MXNetError("MultiHeadAttention: seq_parallel must be "
                         "'', 'ring', or 'ulysses'")
    return num_heads, causal, no_bias, seq_par, block


def _mha_inputs(attrs):
    no_bias = attr_bool(attrs.get("no_bias", False), False)
    if no_bias:
        return ["data", "qkv_weight", "out_weight"]
    return ["data", "qkv_weight", "qkv_bias", "out_weight", "out_bias"]


def _mha_infer(attrs, in_shapes):
    num_heads, _, no_bias, _, _ = _mha_attrs(attrs)
    data = in_shapes[0]
    if data is None:
        raise MXNetError("MultiHeadAttention: data shape required")
    if len(data) != 3:
        raise MXNetError("MultiHeadAttention: data must be "
                         "(batch, seq, embed), got %s" % (data,))
    e = data[2]
    if e % num_heads:
        raise MXNetError("MultiHeadAttention: embed %d %% num_heads %d != 0"
                         % (e, num_heads))
    shapes = [tuple(data), (3 * e, e)]
    if not no_bias:
        shapes.append((3 * e,))
    shapes.append((e, e))
    if not no_bias:
        shapes.append((e,))
    return shapes, [tuple(data)], []


def _seq_mesh():
    """Ambient mesh carrying a 'seq' axis, if any."""
    from ..parallel import mesh as _mesh
    m = _mesh.current_mesh()
    if m is not None and _mesh.AXIS_SEQ in m.axis_names:
        return m
    return None


def _attend(q, k, v, causal, block, seq_par):
    """(b, h, s, d) -> (b, h, s, d); dispatches the parallel mode."""
    from ..parallel import ring as _ring
    block = block or None
    if seq_par:
        mesh = _seq_mesh()
        if mesh is None:
            raise MXNetError(
                "MultiHeadAttention(seq_parallel=%r) needs an ambient mesh "
                "with a 'seq' axis (parallel.mesh.MeshScope / TrainStep "
                "mesh)" % seq_par)
        from jax.sharding import PartitionSpec as P
        from ..parallel.mesh import check_axis_divides
        b, h, s, _ = q.shape
        # divisibility prechecks that NAME the failing axis (the shard_map
        # partitioner's complaint would not): seq dim over 'seq', batch
        # over 'data' when composed, heads over 'seq' for Ulysses' head
        # all-to-all
        check_axis_divides(mesh, "seq", s,
                           "MultiHeadAttention: sequence dim")
        check_axis_divides(mesh, "data", b, "MultiHeadAttention: batch dim")
        if seq_par == "ulysses":
            check_axis_divides(
                mesh, "seq", h,
                "MultiHeadAttention(seq_parallel='ulysses'): num_heads")
        # batch stays sharded over 'data' when the mesh carries both axes
        # (dp x sp); heads/dim replicated — ring/Ulysses communicate over
        # 'seq' only
        bax = "data" if "data" in mesh.axis_names else None
        spec = P(bax, None, "seq", None)
        if seq_par == "ring":
            if block:
                # ring shards K/V across devices; there is no intra-shard
                # blocking to honor — refuse rather than silently ignore
                # the user's memory bound
                raise MXNetError(
                    "MultiHeadAttention: block_size is not supported with "
                    "seq_parallel='ring' (K/V are already sharded per "
                    "device); unset block_size or use 'ulysses'")
            fn = functools.partial(_ring.ring_attention, axis_name="seq",
                                   causal=causal)
        else:
            fn = functools.partial(
                _ring.ulysses_attention, axis_name="seq",
                attn_fn=functools.partial(_ring.blockwise_attention,
                                          block_size=block, causal=causal))
        from ..parallel.mesh import shard_map_compat
        return shard_map_compat(fn, mesh=mesh, in_specs=(spec, spec, spec),
                                out_specs=spec)(q, k, v)
    return _ring.blockwise_attention(q, k, v, block_size=block,
                                     causal=causal)


def _mha(op_ctx, attrs, inputs, aux):
    num_heads, causal, no_bias, seq_par, block = _mha_attrs(attrs)
    if no_bias:
        x, wqkv, wout = inputs
        bqkv = bout = None
    else:
        x, wqkv, bqkv, wout, bout = inputs
    b, s, e = x.shape
    d = e // num_heads
    qkv = jnp.einsum("bse,fe->bsf", x, wqkv)
    if bqkv is not None:
        qkv = qkv + bqkv
    qkv = qkv.reshape(b, s, 3, num_heads, d)
    q, k, v = (jnp.transpose(qkv[:, :, i], (0, 2, 1, 3)) for i in range(3))
    out = _attend(q, k, v, causal, block, seq_par)
    out = jnp.transpose(out, (0, 2, 1, 3)).reshape(b, s, e)
    out = jnp.einsum("bse,fe->bsf", out, wout)
    if bout is not None:
        out = out + bout
    return (out,)


_MHA = register_def(OpDef(
    "MultiHeadAttention", _mha,
    inputs=("data", "qkv_weight", "qkv_bias", "out_weight", "out_bias"),
    infer_shape=_mha_infer))
_MHA.list_inputs = _mha_inputs


# ---------------------------------------------------------------------------
# LayerNorm (transformer building block; API matches the post-0.9 reference
# op of the same name)
# ---------------------------------------------------------------------------
def _ln_infer(attrs, in_shapes):
    data = in_shapes[0]
    if data is None:
        raise MXNetError("LayerNorm: data shape required")
    axis = attr_int(attrs.get("axis", -1), -1) % len(data)
    c = data[axis]
    return [tuple(data), (c,), (c,)], [tuple(data)], []


def _layer_norm(op_ctx, attrs, inputs, aux):
    eps = attr_float(attrs.get("eps", 1e-5), 1e-5)
    x, gamma, beta = inputs
    axis = attr_int(attrs.get("axis", -1), -1) % x.ndim
    mean = jnp.mean(x, axis=axis, keepdims=True)
    var = jnp.mean(jnp.square(x - mean), axis=axis, keepdims=True)
    xhat = (x - mean) * jax.lax.rsqrt(var + eps)
    bshape = tuple(-1 if i == axis else 1 for i in range(x.ndim))
    return (xhat * gamma.reshape(bshape) + beta.reshape(bshape),)


register_def(OpDef("LayerNorm", _layer_norm,
                   inputs=("data", "gamma", "beta"),
                   infer_shape=_ln_infer))
