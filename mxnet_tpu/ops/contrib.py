"""Contrib operators: SSD MultiBox family, CTCLoss, FFT, count_sketch,
quantization.

TPU-native re-implementations of the reference's CUDA contrib ops
(ref: src/operator/contrib/multibox_prior.{cc,cu}, multibox_target.*,
multibox_detection.* — SSD depends on these, example/ssd/symbol/common.py:175;
contrib/ctc_loss* with vendored warp-ctc kernels; contrib/fft*,
count_sketch*, quantize*). Design notes:

- MultiBox matching/NMS are reformulated as dense masked reductions with
  static shapes (anchors capped per class by ``nms_topk``) instead of the
  reference's atomics — XLA-friendly, no dynamic shapes.
- CTCLoss is the standard log-space alpha recursion under ``lax.scan``;
  the gradient comes from autodiff through the scan (no hand-written
  backward, unlike warp-ctc).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..base import attr_bool, attr_float, attr_int, attr_str, attr_tuple, MXNetError
from .registry import OpDef, register, register_def


# ---------------------------------------------------------------------------
# MultiBoxPrior (ref: contrib/multibox_prior.cc)
# ---------------------------------------------------------------------------

def _mbp_attrs(attrs):
    sizes = attr_tuple(attrs.get("sizes", (1.0,)), (1.0,), typ=float)
    ratios = attr_tuple(attrs.get("ratios", (1.0,)), (1.0,), typ=float)
    clip = attr_bool(attrs.get("clip", False), False)
    steps = attr_tuple(attrs.get("steps", (-1.0, -1.0)), (-1.0, -1.0),
                       typ=float)
    offsets = attr_tuple(attrs.get("offsets", (0.5, 0.5)), (0.5, 0.5),
                         typ=float)
    return sizes, ratios, clip, steps, offsets


def _mbp_infer(attrs, in_shapes):
    sizes, ratios, _, _, _ = _mbp_attrs(attrs)
    data = in_shapes[0]
    if data is None:
        raise MXNetError("MultiBoxPrior: data shape required")
    na = len(sizes) + len(ratios) - 1
    return [tuple(data)], [(1, data[2] * data[3] * na, 4)], []


@register("MultiBoxPrior", inputs=("data",), infer_shape=_mbp_infer,
          aliases=("_contrib_MultiBoxPrior",))
def _multibox_prior(op_ctx, attrs, inputs, aux):
    sizes, ratios, clip, steps, offsets = _mbp_attrs(attrs)
    h, w = inputs[0].shape[2], inputs[0].shape[3]
    step_y = steps[0] if steps[0] > 0 else 1.0 / h
    step_x = steps[1] if steps[1] > 0 else 1.0 / w
    cy = (jnp.arange(h) + offsets[0]) * step_y
    cx = (jnp.arange(w) + offsets[1]) * step_x
    # anchor list: (size_i, ratio_0) for all i, then (size_0, ratio_j) j>0
    whs = [(s * np.sqrt(ratios[0]), s / np.sqrt(ratios[0])) for s in sizes]
    whs += [(sizes[0] * np.sqrt(r), sizes[0] / np.sqrt(r))
            for r in ratios[1:]]
    ws = jnp.array([wh[0] for wh in whs]) / 2.0
    hs = jnp.array([wh[1] for wh in whs]) / 2.0
    gy, gx = jnp.meshgrid(cy, cx, indexing="ij")       # (H, W)
    gy = gy[..., None]
    gx = gx[..., None]
    boxes = jnp.stack([gx - ws, gy - hs, gx + ws, gy + hs], axis=-1)
    boxes = boxes.reshape(1, -1, 4)
    if clip:
        boxes = jnp.clip(boxes, 0.0, 1.0)
    return (boxes.astype(inputs[0].dtype),)


# ---------------------------------------------------------------------------
# box IOU helper
# ---------------------------------------------------------------------------

def _iou(a, b):
    """a: (..., A, 4), b: (..., B, 4) corners -> (..., A, B)."""
    ax1, ay1, ax2, ay2 = [a[..., i] for i in range(4)]
    bx1, by1, bx2, by2 = [b[..., i] for i in range(4)]
    ix1 = jnp.maximum(ax1[..., :, None], bx1[..., None, :])
    iy1 = jnp.maximum(ay1[..., :, None], by1[..., None, :])
    ix2 = jnp.minimum(ax2[..., :, None], bx2[..., None, :])
    iy2 = jnp.minimum(ay2[..., :, None], by2[..., None, :])
    iw = jnp.maximum(ix2 - ix1, 0.0)
    ih = jnp.maximum(iy2 - iy1, 0.0)
    inter = iw * ih
    area_a = jnp.maximum((ax2 - ax1) * (ay2 - ay1), 0.0)
    area_b = jnp.maximum((bx2 - bx1) * (by2 - by1), 0.0)
    union = area_a[..., :, None] + area_b[..., None, :] - inter
    return jnp.where(union > 0, inter / union, 0.0)


# ---------------------------------------------------------------------------
# MultiBoxTarget (ref: contrib/multibox_target.cc)
# ---------------------------------------------------------------------------

def _mbt_infer(attrs, in_shapes):
    anchors, labels, cls_preds = in_shapes
    if anchors is None or labels is None:
        raise MXNetError("MultiBoxTarget: anchor/label shapes required")
    a = anchors[1]
    n = labels[0]
    return [tuple(anchors), tuple(labels), tuple(cls_preds)], \
        [(n, a * 4), (n, a * 4), (n, a)], []


@register("MultiBoxTarget", inputs=("anchor", "label", "cls_pred"),
          infer_shape=_mbt_infer, aliases=("_contrib_MultiBoxTarget",))
def _multibox_target(op_ctx, attrs, inputs, aux):
    anchors, labels, cls_preds = inputs
    iou_thresh = attr_float(attrs.get("overlap_threshold", 0.5), 0.5)
    variances = attr_tuple(attrs.get("variances", (0.1, 0.1, 0.2, 0.2)),
                           (0.1, 0.1, 0.2, 0.2), typ=float)
    neg_ratio = attr_float(attrs.get("negative_mining_ratio", -1.0), -1.0)
    anc = anchors[0]                                  # (A, 4)
    A = anc.shape[0]

    def one_sample(lab, cls_pred):
        # lab: (O, 5) [cls, x1, y1, x2, y2], cls -1 padding
        valid = lab[:, 0] >= 0                        # (O,)
        gt = lab[:, 1:5]
        ious = _iou(anc, gt) * valid[None, :]         # (A, O)
        best_gt = jnp.argmax(ious, axis=1)            # per anchor
        best_iou = jnp.max(ious, axis=1)
        # anchors that are argmax for some gt are forced positive
        best_anchor_per_gt = jnp.argmax(ious, axis=0)  # (O,)
        # .max, not .set: padded labels all point at anchor 0 and a
        # duplicate-index .set could overwrite a real gt's forced match
        forced = jnp.zeros(A, bool).at[best_anchor_per_gt].max(valid)
        pos = (best_iou >= iou_thresh) | forced
        matched_gt = gt[best_gt]                      # (A, 4)
        matched_cls = lab[best_gt, 0]
        # encode offsets (center form, variance-scaled)
        aw = anc[:, 2] - anc[:, 0]
        ah = anc[:, 3] - anc[:, 1]
        acx = (anc[:, 0] + anc[:, 2]) / 2
        acy = (anc[:, 1] + anc[:, 3]) / 2
        gw = jnp.maximum(matched_gt[:, 2] - matched_gt[:, 0], 1e-8)
        gh = jnp.maximum(matched_gt[:, 3] - matched_gt[:, 1], 1e-8)
        gcx = (matched_gt[:, 0] + matched_gt[:, 2]) / 2
        gcy = (matched_gt[:, 1] + matched_gt[:, 3]) / 2
        tx = (gcx - acx) / jnp.maximum(aw, 1e-8) / variances[0]
        ty = (gcy - acy) / jnp.maximum(ah, 1e-8) / variances[1]
        tw = jnp.log(gw / jnp.maximum(aw, 1e-8)) / variances[2]
        th = jnp.log(gh / jnp.maximum(ah, 1e-8)) / variances[3]
        loc_t = jnp.stack([tx, ty, tw, th], axis=1) * pos[:, None]
        loc_m = jnp.tile(pos[:, None].astype(anc.dtype), (1, 4))
        cls_t = jnp.where(pos, matched_cls + 1.0, 0.0)  # 0 = background
        if neg_ratio > 0:
            # hard negative mining: keep top neg_ratio*npos negatives by
            # background-score deficiency, mark the rest ignore (-1)
            bg_scores = jax.nn.softmax(cls_pred, axis=0)[0]  # (A,)
            neg_cand = ~pos
            difficulty = jnp.where(neg_cand, 1.0 - bg_scores, -jnp.inf)
            order = jnp.argsort(-difficulty)
            rank = jnp.zeros(A, jnp.int32).at[order].set(jnp.arange(A))
            npos = jnp.sum(pos)
            keep_n = jnp.maximum((neg_ratio * npos).astype(jnp.int32), 1)
            keep_neg = neg_cand & (rank < keep_n)
            cls_t = jnp.where(pos, cls_t,
                              jnp.where(keep_neg, 0.0, -1.0))
        return loc_t.reshape(-1), loc_m.reshape(-1), cls_t

    loc_t, loc_m, cls_t = jax.vmap(one_sample)(labels, cls_preds)
    return (loc_t, loc_m, cls_t)


from .registry import get as _get  # noqa: E402

_get("MultiBoxTarget")._outputs = ("loc_target", "loc_mask", "cls_target")


# ---------------------------------------------------------------------------
# MultiBoxDetection (ref: contrib/multibox_detection.cc)
# ---------------------------------------------------------------------------

def _mbd_infer(attrs, in_shapes):
    cls_prob, loc_pred, anchor = in_shapes
    if cls_prob is None or anchor is None:
        raise MXNetError("MultiBoxDetection: shapes required")
    n = cls_prob[0]
    a = anchor[1]
    return [tuple(cls_prob), tuple(loc_pred), tuple(anchor)], \
        [(n, a, 6)], []


@register("MultiBoxDetection", inputs=("cls_prob", "loc_pred", "anchor"),
          infer_shape=_mbd_infer, aliases=("_contrib_MultiBoxDetection",))
def _multibox_detection(op_ctx, attrs, inputs, aux):
    cls_prob, loc_pred, anchors = inputs
    thresh = attr_float(attrs.get("threshold", 0.01), 0.01)
    nms_thresh = attr_float(attrs.get("nms_threshold", 0.5), 0.5)
    variances = attr_tuple(attrs.get("variances", (0.1, 0.1, 0.2, 0.2)),
                           (0.1, 0.1, 0.2, 0.2), typ=float)
    force = attr_bool(attrs.get("force_suppress", False), False)
    nms_topk = attr_int(attrs.get("nms_topk", -1), -1)
    anc = anchors[0]
    A = anc.shape[0]

    # decode
    aw = anc[:, 2] - anc[:, 0]
    ah = anc[:, 3] - anc[:, 1]
    acx = (anc[:, 0] + anc[:, 2]) / 2
    acy = (anc[:, 1] + anc[:, 3]) / 2

    def one_sample(cp, lp):
        lp = lp.reshape(A, 4)
        cx = lp[:, 0] * variances[0] * aw + acx
        cy = lp[:, 1] * variances[1] * ah + acy
        w = jnp.exp(lp[:, 2] * variances[2]) * aw / 2
        h = jnp.exp(lp[:, 3] * variances[3]) * ah / 2
        boxes = jnp.stack([cx - w, cy - h, cx + w, cy + h], axis=1)
        boxes = jnp.clip(boxes, 0.0, 1.0)
        # per anchor best non-background class
        scores = cp[1:]                       # (C-1, A)
        cls = jnp.argmax(scores, axis=0)      # (A,)
        score = jnp.max(scores, axis=0)
        keep = score > thresh
        score = jnp.where(keep, score, 0.0)
        # greedy NMS over anchors sorted by score
        k = A if nms_topk <= 0 else min(nms_topk, A)
        order = jnp.argsort(-score)[:k]
        sboxes = boxes[order]
        sscore = score[order]
        scls = cls[order]
        from . import pallas_multibox as _pmb
        if _pmb.enabled():
            # escape-hatch kernel (MXTPU_PALLAS_MULTIBOX, docs/perf.md):
            # the whole IOU + sequential suppression sweep VMEM-resident
            # in ONE pallas_call instead of a k-trip While over HBM masks
            alive = _pmb.nms_alive(
                sboxes, sscore, scls, nms_thresh, force=force,
                interpret=_pmb.interpret_requested()) > 0
        else:
            ious = _iou(sboxes, sboxes)           # (k, k)
            same_cls = (scls[:, None] == scls[None, :]) | force
            sup_matrix = (ious > nms_thresh) & same_cls

            def body(i, alive):
                sup = sup_matrix[i] & alive[i] & (jnp.arange(k) > i)
                return alive & ~sup

            alive = jax.lax.fori_loop(0, k, body, sscore > 0)
        out_cls = jnp.where(alive, scls.astype(cp.dtype), -1.0)
        out_score = jnp.where(alive, sscore, 0.0)
        det = jnp.concatenate([out_cls[:, None], out_score[:, None], sboxes],
                              axis=1)
        if k < A:
            pad = jnp.full((A - k, 6), -1.0, det.dtype)
            det = jnp.concatenate([det, pad], axis=0)
        return det

    return (jax.vmap(one_sample)(cls_prob, loc_pred),)


# ---------------------------------------------------------------------------
# CTCLoss (ref: contrib/ctc_loss*; warp-ctc semantics, blank = 0)
# ---------------------------------------------------------------------------

def _ctc_infer(attrs, in_shapes):
    data, label = in_shapes
    if data is None:
        raise MXNetError("CTCLoss: data shape required")
    return [tuple(data), tuple(label)], [(data[1],)], []


@register("CTCLoss", inputs=("data", "label"), infer_shape=_ctc_infer,
          aliases=("ctc_loss", "_contrib_CTCLoss"))
def _ctc_loss(op_ctx, attrs, inputs, aux):
    data, label = inputs     # data: (T, N, V) activations; label: (N, L)
    T, N, V = data.shape
    L = label.shape[1]
    logp = jax.nn.log_softmax(data, axis=-1)
    lab = label.astype(jnp.int32)
    # extended sequence: blank, l1, blank, l2, ... blank (blank = 0)
    S = 2 * L + 1
    ext = jnp.zeros((N, S), jnp.int32)
    ext = ext.at[:, 1::2].set(lab)
    lab_len = jnp.sum((lab > 0).astype(jnp.int32), axis=1)
    ext_len = 2 * lab_len + 1
    NEG = -1e9

    # can-skip mask: allowed to jump from s-2 to s when ext[s] != blank and
    # ext[s] != ext[s-2]
    ext_prev2 = jnp.pad(ext, ((0, 0), (2, 0)))[:, :S]
    can_skip = (ext != 0) & (ext != ext_prev2)

    def get_logp(t):
        # (N, S): log prob of emitting ext symbol s at time t
        return jnp.take_along_axis(logp[t], ext, axis=1)

    alpha0 = jnp.full((N, S), NEG)
    alpha0 = alpha0.at[:, 0].set(get_logp(0)[:, 0])
    alpha0 = alpha0.at[:, 1].set(jnp.where(lab_len > 0, get_logp(0)[:, 1],
                                           NEG))

    def lse(a, b):
        # NaN-safe log-add-exp: clamp the gap so neither branch of the
        # computation can produce inf/NaN in the vjp (the where-grad trap)
        m = jnp.maximum(a, b)
        d = jnp.clip(jnp.abs(a - b), 0.0, 60.0)
        return m + jnp.log1p(jnp.exp(-d))

    def step(alpha, t):
        a_prev1 = jnp.pad(alpha, ((0, 0), (1, 0)),
                          constant_values=NEG)[:, :S]
        a_prev2 = jnp.pad(alpha, ((0, 0), (2, 0)),
                          constant_values=NEG)[:, :S]
        a = lse(alpha, a_prev1)
        a = jnp.where(can_skip, lse(a, a_prev2), a)
        alpha_new = a + get_logp(t)
        return alpha_new, None

    alpha, _ = jax.lax.scan(step, alpha0, jnp.arange(1, T))
    # loss = -log(alpha[ext_len-1] + alpha[ext_len-2])
    idx_last = jnp.clip(ext_len - 1, 0, S - 1)
    idx_prev = jnp.clip(ext_len - 2, 0, S - 1)
    a_last = jnp.take_along_axis(alpha, idx_last[:, None], axis=1)[:, 0]
    a_prev = jnp.take_along_axis(alpha, idx_prev[:, None], axis=1)[:, 0]
    # empty-label rows (ext_len==1) have no second terminal state — using
    # lse(a, a) there would double-count the all-blank path
    total = jnp.where(ext_len >= 2, lse(a_last, a_prev), a_last)
    return (-total,)


# ---------------------------------------------------------------------------
# FFT / IFFT (ref: contrib/fft* — cuFFT there, jnp.fft here)
# ---------------------------------------------------------------------------

@register("fft", inputs=("data",), aliases=("_contrib_fft",))
def _fft(op_ctx, attrs, inputs, aux):
    x = inputs[0]
    y = jnp.fft.fft(x.astype(jnp.complex64), axis=-1)
    # reference packs complex interleaved [re, im] doubling the last dim
    out = jnp.stack([jnp.real(y), jnp.imag(y)], axis=-1)
    return (out.reshape(x.shape[:-1] + (2 * x.shape[-1],)).astype(x.dtype),)


@register("ifft", inputs=("data",), aliases=("_contrib_ifft",))
def _ifft(op_ctx, attrs, inputs, aux):
    x = inputs[0]
    n = x.shape[-1] // 2
    pairs = x.reshape(x.shape[:-1] + (n, 2))
    z = pairs[..., 0] + 1j * pairs[..., 1]
    y = jnp.fft.ifft(z, axis=-1) * n  # reference does unnormalized ifft
    return (jnp.real(y).astype(x.dtype),)


# ---------------------------------------------------------------------------
# count_sketch (ref: contrib/count_sketch* — compact bilinear pooling)
# ---------------------------------------------------------------------------

def _cs_infer(attrs, in_shapes):
    data, h, s = in_shapes
    out_dim = attr_int(attrs["out_dim"])
    if data is None:
        raise MXNetError("count_sketch: data shape required")
    return [tuple(data), (data[1],), (data[1],)], [(data[0], out_dim)], []


@register("count_sketch", inputs=("data", "h", "s"), infer_shape=_cs_infer,
          aliases=("_contrib_count_sketch",))
def _count_sketch(op_ctx, attrs, inputs, aux):
    data, h, s = inputs
    out_dim = attr_int(attrs["out_dim"])
    idx = h.astype(jnp.int32) % out_dim
    signed = data * s[None, :]
    out = jnp.zeros((data.shape[0], out_dim), data.dtype)
    return (out.at[:, idx].add(signed),)


# ---------------------------------------------------------------------------
# quantize / dequantize (ref: contrib/quantize*)
# ---------------------------------------------------------------------------

@register("quantize", inputs=("data", "min_range", "max_range"),
          aliases=("_contrib_quantize",))
def _quantize(op_ctx, attrs, inputs, aux):
    data, lo, hi = inputs
    scale = 255.0 / jnp.maximum(hi - lo, 1e-8)
    q = jnp.clip(jnp.round((data - lo) * scale), 0, 255).astype(jnp.uint8)
    return (q, lo, hi)


_get("quantize")._outputs = ("output", "min_range", "max_range")


@register("dequantize", inputs=("data", "min_range", "max_range"),
          aliases=("_contrib_dequantize",))
def _dequantize(op_ctx, attrs, inputs, aux):
    data, lo, hi = inputs
    scale = jnp.maximum(hi - lo, 1e-8) / 255.0
    return (data.astype(jnp.float32) * scale + lo,)
