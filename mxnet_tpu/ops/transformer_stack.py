"""TransformerStack: the whole pre-LN layer stack as ONE op over stacked
parameters — the pipeline-parallel flagship surface.

The per-layer symbol composition (models/transformer.py default) gives
every layer its own parameter Variables, which is the right shape for
data/tensor/sequence parallelism but cannot pipeline: GPipe needs every
stage to share one structure with parameters STACKED along a leading
stage dimension (parallel/pipeline.py). This op is that formulation —
each weight arrives as an (L, ...) stack, and the layer loop dispatches
on the ambient mesh:

* mesh with a 'pipe' axis: ``parallel.pipeline.pipeline_apply`` runs the
  GPipe schedule — layers fold onto stages ((L/S per stage), activations
  hop stages over ppermute, batch optionally stays sharded over 'data'
  (dp x pipe composition);
* otherwise: one ``lax.scan`` over the L layers (same numerics, single
  compiled layer body — also what keeps compile time flat as L grows).

Attention inside a stage is the single-chip blockwise core
(parallel/ring.py): a pipeline stage body already runs inside shard_map,
where a nested seq-parallel shard_map cannot be formed — get_symbol
refuses the stacked+seq_parallel combination up front.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..base import attr_bool, attr_int, MXNetError
from .registry import OpDef, register_def

#: stacked-parameter input order (leading dim L on every non-data input)
STACK_INPUTS = ("data", "ln1_gamma", "ln1_beta", "qkv_weight", "qkv_bias",
                "out_weight", "out_bias", "ln2_gamma", "ln2_beta",
                "fc1_weight", "fc1_bias", "fc2_weight", "fc2_bias")


def _stack_attrs(attrs):
    num_layers = attr_int(attrs["num_layers"])
    num_heads = attr_int(attrs["num_heads"])
    ffn_hidden = attr_int(attrs["ffn_hidden"])
    causal = attr_bool(attrs.get("causal", True), True)
    block = attr_int(attrs.get("block_size", 0), 0)
    micro = attr_int(attrs.get("num_microbatches", 0), 0)
    return num_layers, num_heads, ffn_hidden, causal, block, micro


def _stack_infer(attrs, in_shapes):
    L, num_heads, H, _, _, _ = _stack_attrs(attrs)
    data = in_shapes[0]
    if data is None:
        raise MXNetError("TransformerStack: data shape required")
    if len(data) != 3:
        raise MXNetError("TransformerStack: data must be "
                         "(batch, seq, embed), got %s" % (data,))
    e = data[2]
    if e % num_heads:
        raise MXNetError("TransformerStack: embed %d %% num_heads %d != 0"
                         % (e, num_heads))
    shapes = [tuple(data),
              (L, e), (L, e),               # ln1 gamma/beta
              (L, 3 * e, e), (L, 3 * e),    # qkv
              (L, e, e), (L, e),            # out proj
              (L, e), (L, e),               # ln2 gamma/beta
              (L, H, e), (L, H),            # ffn fc1
              (L, e, H), (L, e)]            # ffn fc2
    return shapes, [tuple(data)], []


def _layer_norm(x, gamma, beta, eps=1e-5):
    mean = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(x - mean), axis=-1, keepdims=True)
    return (x - mean) * jax.lax.rsqrt(var + eps) * gamma + beta


def _one_layer(p, x, num_heads, causal, block):
    """One pre-LN block: x + MHA(LN(x)); x + FFN(LN(x)) — the same math
    as the per-layer symbol composition (LayerNorm + MultiHeadAttention +
    FC), so stacked and unstacked builds agree for equal weights
    (tests/test_lm_flagship.py pins the parity)."""
    from ..parallel import ring as _ring
    (ln1_g, ln1_b, wqkv, bqkv, wout, bout,
     ln2_g, ln2_b, w1, b1, w2, b2) = p
    b, s, e = x.shape
    d = e // num_heads
    a = _layer_norm(x, ln1_g, ln1_b)
    qkv = jnp.einsum("bse,fe->bsf", a, wqkv) + bqkv
    qkv = qkv.reshape(b, s, 3, num_heads, d)
    q, k, v = (jnp.transpose(qkv[:, :, i], (0, 2, 1, 3)) for i in range(3))
    o = _ring.blockwise_attention(q, k, v, block_size=block or None,
                                  causal=causal)
    o = jnp.transpose(o, (0, 2, 1, 3)).reshape(b, s, e)
    x = x + jnp.einsum("bse,fe->bsf", o, wout) + bout
    f = _layer_norm(x, ln2_g, ln2_b)
    f = jax.nn.relu(jnp.einsum("bse,he->bsh", f, w1) + b1)
    f = jnp.einsum("bsh,eh->bse", f, w2) + b2
    return x + f


def _pipe_mesh():
    """Ambient mesh carrying a 'pipe' axis, if any."""
    from ..parallel import mesh as _mesh
    m = _mesh.current_mesh()
    if m is not None and _mesh.AXIS_PIPE in m.axis_names:
        return m
    return None


def _transformer_stack(op_ctx, attrs, inputs, aux):
    L, num_heads, H, causal, block, micro = _stack_attrs(attrs)
    x, params = inputs[0], tuple(inputs[1:])

    def run_layers(stack, xin):
        def body(carry, p):
            return _one_layer(p, carry, num_heads, causal, block), None
        out, _ = jax.lax.scan(body, xin, stack)
        return out

    mesh = _pipe_mesh()
    if mesh is None:
        return (run_layers(params, x),)

    from ..parallel.mesh import (AXIS_DATA, AXIS_PIPE, check_axis_divides,
                                 data_axis_size)
    from ..parallel.pipeline import pipeline_apply
    S = data_axis_size(mesh, AXIS_PIPE)
    check_axis_divides(mesh, AXIS_PIPE, L, "TransformerStack: num_layers")
    check_axis_divides(mesh, AXIS_DATA, x.shape[0],
                       "TransformerStack: batch dim")
    # fold the (L, ...) stacks onto stages: (S, L/S, ...) — one stage per
    # 'pipe' device, L/S layers scanned inside each stage body
    staged = tuple(p.reshape((S, L // S) + p.shape[1:]) for p in params)
    bax = AXIS_DATA if AXIS_DATA in mesh.axis_names else None
    out = pipeline_apply(run_layers, staged, x, mesh, axis_name=AXIS_PIPE,
                         num_microbatches=micro or None, batch_axis=bax)
    return (out,)


register_def(OpDef("TransformerStack", _transformer_stack,
                   inputs=STACK_INPUTS, infer_shape=_stack_infer))
