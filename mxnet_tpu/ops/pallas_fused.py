"""Pallas TPU kernel: matmul with BatchNorm-statistics epilogue.

The perf story (docs/perf.md): ResNet training on v5e is HBM-bound, and the
BN batch-statistics pass is the largest non-essential traffic source — the
stats reduction re-reads the full conv output that the conv just wrote. A
1x1 convolution in NHWC is exactly a matmul, so this kernel computes

    y = x @ w        (MXU, f32 accumulation)
    s1 = sum(y)      per output channel   (VPU, from the f32 accumulator)
    s2 = sum(y*y)    per output channel

in ONE pass: the stats come for free out of VMEM while the tile is still
resident, eliminating the separate full-tensor read. The executor's fusion
pass (executor.py) rewrites Convolution(1x1)->BatchNorm pairs onto this
kernel at trace time; BatchNorm then consumes (s1, s2, count) directly
(ops/nn.py fused_stats path).

Replaces the role of the reference's cuDNN fused conv+BN epilogues
(ref: src/operator/cudnn_batch_norm-inl.h + convolution autotuning); the
backward is plain XLA matmuls with the stats cotangents folded into the
output cotangent (dy_eff = dy + ds1 + 2*y*ds2), which XLA fuses into the
matmul operand reads.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl


def _tile_m(m, cap=1024):
    """Largest divisor of m that is <= cap and sublane-aligned (mult of 16).
    Returns None when m has no aligned divisor (caller skips fusion)."""
    best = None
    for t in range(16, min(m, cap) + 1, 16):
        if m % t == 0:
            best = t
    return best


def _acc_dtype(dt):
    """Stats/accumulator dtype: f32 except for f64 inputs (numeric tests)."""
    return jnp.float64 if dt == jnp.float64 else jnp.float32


def _kernel(x_ref, w_ref, y_ref, ps_ref):
    acc = jnp.dot(x_ref[...], w_ref[...],
                  preferred_element_type=ps_ref.dtype)
    y_ref[...] = acc.astype(y_ref.dtype)
    ps_ref[0, 0, :] = jnp.sum(acc, axis=0)
    ps_ref[0, 1, :] = jnp.sum(acc * acc, axis=0)


@functools.partial(jax.jit, static_argnames=("interpret",))
def _matmul_stats_raw(x, w, interpret=False):
    """x (M, K) @ w (K, N) -> y (M, N), s1 (N,), s2 (N,) f32."""
    m, k = x.shape
    n = w.shape[1]
    acc_dt = _acc_dtype(x.dtype)
    tm = _tile_m(m)
    tn = n if n <= 256 else 256
    if tm is None or n % tn or n % 128:
        # shape outside the kernel's envelope: plain XLA fallback
        yacc = jnp.dot(x, w, preferred_element_type=acc_dt)
        return (yacc.astype(x.dtype), jnp.sum(yacc, axis=0),
                jnp.sum(yacc * yacc, axis=0))
    grid = (m // tm, n // tn)
    y, ps = pl.pallas_call(
        _kernel,
        grid=grid,
        in_specs=[pl.BlockSpec((tm, k), lambda i, j: (i, 0)),
                  pl.BlockSpec((k, tn), lambda i, j: (0, j))],
        out_specs=[pl.BlockSpec((tm, tn), lambda i, j: (i, j)),
                   pl.BlockSpec((1, 2, tn), lambda i, j: (i, 0, j))],
        out_shape=[jax.ShapeDtypeStruct((m, n), x.dtype),
                   jax.ShapeDtypeStruct((grid[0], 2, n), acc_dt)],
        interpret=interpret,
    )(x, w)
    return y, ps[:, 0, :].sum(axis=0), ps[:, 1, :].sum(axis=0)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def matmul_stats(x, w, interpret=False):
    """Differentiable fused matmul+stats; cotangents on the stats flow back
    into x and w (the BN batch statistics are functions of the data)."""
    return _matmul_stats_raw(x, w, interpret)


def _mm_fwd(x, w, interpret):
    out = _matmul_stats_raw(x, w, interpret)
    return out, (x, w, out[0])


def _mm_bwd(interpret, res, cots):
    x, w, y = res
    dy, ds1, ds2 = cots
    # d/dy [ <dy,y> + <ds1, sum(y)> + <ds2, sum(y^2)> ]
    acc_dt = ds1.dtype
    dy_eff = (dy.astype(acc_dt) + ds1[None, :]
              + 2.0 * y.astype(acc_dt) * ds2[None, :]).astype(x.dtype)
    dx = jnp.dot(dy_eff, w.T)
    dw = jnp.dot(x.T, dy_eff)
    return dx, dw


matmul_stats.defvjp(_mm_fwd, _mm_bwd)


# ---------------------------------------------------------------------------
# fusion-pass predicates and driver (used by executor._build_graph_runner)
# ---------------------------------------------------------------------------
def conv1x1_fusable(conv_attrs):
    """True when a Convolution node is a pure NHWC 1x1 matmul this kernel
    covers: kernel (1,1), stride 1, no pad/dilation/groups/bias."""
    from ..base import attr_bool, attr_int, attr_tuple, attr_str
    try:
        if attr_str(conv_attrs.get("layout", ""), "") != "NHWC":
            return False
        if attr_tuple(conv_attrs["kernel"]) != (1, 1):
            return False
        if attr_tuple(conv_attrs.get("stride", (1, 1)), (1, 1)) != (1, 1):
            return False
        if attr_tuple(conv_attrs.get("pad", (0, 0)), (0, 0)) != (0, 0):
            return False
        if attr_tuple(conv_attrs.get("dilate", (1, 1)), (1, 1)) != (1, 1):
            return False
        if attr_int(conv_attrs.get("num_group", 1), 1) != 1:
            return False
        if not attr_bool(conv_attrs.get("no_bias", False), False):
            return False
    except Exception:
        return False
    return True


def bn_fusable(bn_attrs):
    """BN can consume producer stats: channel-last axis, batch stats."""
    from ..base import attr_bool, attr_int
    if attr_bool(bn_attrs.get("use_global_stats", False), False):
        return False
    return attr_int(bn_attrs.get("axis", 1), 1) in (-1, 3)


def apply_conv1x1_stats(x, w, interpret=False):
    """NHWC activation x (..., C), OIHW weight w (F, C, 1, 1) ->
    (y (..., F), (s1, s2, count))."""
    k = x.shape[-1]
    f = w.shape[0]
    x2 = x.reshape(-1, k)
    w2 = w.reshape(f, k).T
    y2, s1, s2 = matmul_stats(x2, w2, interpret)
    return y2.reshape(x.shape[:-1] + (f,)), (s1, s2, float(x2.shape[0]))
