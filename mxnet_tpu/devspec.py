"""devspec: ONE per-device-kind capability table for every roofline.

Three analyzers and a bench used to carry their own copies of the TPU
spec sheet: bench.py's MFU peak table, commscheck's ``PEAK_FLOPS_PER_S``
and ICI ``LINK_BYTES_PER_S``, and (new) flopcheck's HBM-bandwidth
column. A spec number that lives in two places drifts — one table gets a
new chip generation, the other silently keeps pricing it as unknown —
so the three columns live HERE and everybody reads them through the same
prefix-matched lookup:

==============  ===========  ===========  ===========
device kind     peak bf16    HBM          ICI link
                FLOP/s       bytes/s      bytes/s
==============  ===========  ===========  ===========
TPU v2          46e12        7.0e11       6.2e10
TPU v3          123e12       9.0e11      8.1e10
TPU v4          275e12       1.2e12       1.2e11
TPU v5e/lite    197e12       8.1e11       4.5e10
TPU v5p         459e12       2.765e12     9.0e10
TPU v6e/lite    918e12       1.64e12      9.0e10
==============  ===========  ===========  ===========

(public spec-sheet figures, order-of-magnitude — every consumer's
roofline is a MODEL and the multichip gate cross-checks predictions
against measurement). CPU / unknown kinds fall back to nominal figures
so the forced-host CI mesh stays finite and deterministic; the
``peak_source`` field says which case you got (``"spec"`` vs
``"nominal-fallback"``) so an MFU/roofline number is never silently a
guess.
"""
from __future__ import annotations

from collections import namedtuple

__all__ = [
    "DeviceSpec", "DEVICE_SPECS", "DEFAULT_SPEC", "device_kind", "lookup",
    "peak_flops", "hbm_bandwidth", "link_bandwidth", "ridge_intensity",
    "peak_source",
]

#: one device kind's capability row (all rates are per-chip):
#: ``peak_flops_per_s`` dense bf16, ``hbm_bytes_per_s`` main-memory
#: bandwidth, ``link_bytes_per_s`` one-directional inter-chip ICI
DeviceSpec = namedtuple("DeviceSpec", ["peak_flops_per_s",
                                       "hbm_bytes_per_s",
                                       "link_bytes_per_s"])

#: per-device-kind table, matched by ``device_kind`` PREFIX (a v5e
#: reports "TPU v5 lite" or "TPU v5e" depending on runtime version)
DEVICE_SPECS = {
    "TPU v2": DeviceSpec(46e12, 7.0e11, 6.2e10),
    "TPU v3": DeviceSpec(123e12, 9.0e11, 8.1e10),
    "TPU v4": DeviceSpec(275e12, 1.2e12, 1.2e11),
    "TPU v5 lite": DeviceSpec(197e12, 8.1e11, 4.5e10),
    "TPU v5e": DeviceSpec(197e12, 8.1e11, 4.5e10),
    "TPU v5p": DeviceSpec(459e12, 2.765e12, 9.0e10),
    "TPU v6 lite": DeviceSpec(918e12, 1.64e12, 9.0e10),
    "TPU v6e": DeviceSpec(918e12, 1.64e12, 9.0e10),
}

#: CPU / unknown backends: nominal few-core figures. The ratio matters
#: as much as the magnitudes — peak/hbm here puts the ridge point at 10
#: FLOP/byte, so low-intensity kernels (attention score x V, optimizer
#: sweeps) classify memory-bound on the CI host the way they do on real
#: chips, instead of everything degenerating to one side of the ridge.
DEFAULT_SPEC = DeviceSpec(5.0e10, 5.0e9, 1.0e10)


def device_kind(device=None):
    """The backend's device-kind string ("" when it reports none)."""
    import jax
    device = device or jax.devices()[0]
    return getattr(device, "device_kind", "")


def lookup(device=None):
    """``(DeviceSpec, peak_source)`` for a device: the spec-sheet row
    matched by device-kind prefix (``peak_source="spec"``), or the
    nominal fallback (``peak_source="nominal-fallback"``)."""
    kind = device_kind(device)
    for k, spec in DEVICE_SPECS.items():
        if kind.startswith(k):
            return spec, "spec"
    return DEFAULT_SPEC, "nominal-fallback"


def peak_flops(device=None):
    """Peak dense bf16 FLOP/s by device kind (nominal fallback for
    CPU/unknown — check :func:`peak_source` before headlining it)."""
    return lookup(device)[0].peak_flops_per_s


def hbm_bandwidth(device=None):
    """Main-memory (HBM) bandwidth in bytes/s by device kind."""
    return lookup(device)[0].hbm_bytes_per_s


def link_bandwidth(device=None):
    """One-directional inter-chip link bandwidth in bytes/s by device
    kind (the commscheck wire-time model's denominator)."""
    return lookup(device)[0].link_bytes_per_s


def ridge_intensity(device=None):
    """The roofline ridge point in FLOP/byte: kernels whose arithmetic
    intensity sits below it are memory-bound at any utilization."""
    spec, _ = lookup(device)
    return spec.peak_flops_per_s / spec.hbm_bytes_per_s


def peak_source(device=None):
    """``"spec"`` when the device kind matched a spec-sheet row,
    ``"nominal-fallback"`` otherwise."""
    return lookup(device)[1]
