"""Legacy executor-manager API (ref: python/mxnet/executor_manager.py:278).

The FeedForward-era data-parallel driver. The heavy lifting lives in
module/executor_group.py (the modern path); this module keeps the old
surface — ``_split_input_slice`` workload-weighted batch splitting and
``DataParallelExecutorManager`` — so reference training scripts written
against ``mx.executor_manager`` run unchanged.
"""
from __future__ import annotations

import logging

from .base import MXNetError
from .module.executor_group import DataParallelExecutorGroup


def _split_input_slice(batch_size, work_load_list):
    """Workload-weighted batch slices (ref: executor_manager.py:14-49)."""
    total = sum(work_load_list)
    nums = [round(w * batch_size / total) for w in work_load_list]
    if sum(nums) < batch_size:
        nums[-1] += batch_size - sum(nums)
    slices = []
    end = 0
    for n in nums:
        begin = int(min(end, batch_size))
        end = int(min(begin + n, batch_size))
        if begin >= end:
            raise ValueError(
                "Too many slices such that some splits are empty")
        slices.append(slice(begin, end))
    return slices


def _check_arguments(symbol):
    """Reject duplicate argument/aux names (ref: executor_manager.py:51)."""
    arg_names = symbol.list_arguments()
    if len(set(arg_names)) != len(arg_names):
        raise ValueError("Find duplicated argument name: %s" % arg_names)
    aux_names = symbol.list_auxiliary_states()
    if len(set(aux_names)) != len(aux_names):
        raise ValueError("Find duplicated auxiliary name: %s" % aux_names)


class DataParallelExecutorManager(object):
    """Multi-device train-loop helper (ref: executor_manager.py:278-427).
    Delegates to DataParallelExecutorGroup; kept for FeedForward and legacy
    scripts."""

    def __init__(self, symbol, ctx, train_data, arg_names=None,
                 param_names=None, aux_names=None, work_load_list=None,
                 logger=None, sym_gen=None):
        if logger is None:
            logger = logging
        self.symbol = symbol
        self.ctx = ctx if isinstance(ctx, (list, tuple)) else [ctx]
        num_device = len(self.ctx)
        logger.info("Start training with %s", str(self.ctx))
        if work_load_list is None:
            work_load_list = [1] * num_device
        if len(work_load_list) != num_device:
            raise MXNetError("Invalid settings for work load.")
        self.work_load_list = work_load_list
        _check_arguments(symbol)
        self.arg_names = arg_names or symbol.list_arguments()
        self.aux_names = aux_names or symbol.list_auxiliary_states()
        data_names = [d[0] for d in train_data.provide_data]
        label_names = [l[0] for l in (train_data.provide_label or [])]
        self.param_names = param_names or [
            n for n in self.arg_names
            if n not in data_names and n not in label_names]
        self.sym_gen = sym_gen
        self.execgrp = DataParallelExecutorGroup(
            symbol, self.ctx, work_load_list,
            train_data.provide_data, train_data.provide_label,
            for_training=True, inputs_need_grad=False,
            param_names=self.param_names)
        self.execgrp_bucket = {}
        if sym_gen is not None:
            self.execgrp_bucket[train_data.default_bucket_key] = self.execgrp
        self.curr_execgrp = self.execgrp

    # -- parameters ----------------------------------------------------
    def set_params(self, arg_params, aux_params):
        self.execgrp.set_params(arg_params, aux_params)

    def copy_to(self, arg_params, aux_params):
        self.execgrp.get_params(arg_params, aux_params)

    @property
    def param_arrays(self):
        ex = self.curr_execgrp.executor
        return [ex.arg_dict[n] for n in self.param_names]

    @property
    def grad_arrays(self):
        ex = self.curr_execgrp.executor
        return [ex.grad_dict.get(n) for n in self.param_names]

    @property
    def aux_arrays(self):
        ex = self.curr_execgrp.executor
        return [ex.aux_dict[n] for n in self.aux_names]

    # -- stepping ------------------------------------------------------
    def load_data_batch(self, data_batch):
        if self.sym_gen is not None:
            key = data_batch.bucket_key
            if key not in self.execgrp_bucket:
                symbol = self.sym_gen(key)
                self.execgrp_bucket[key] = DataParallelExecutorGroup(
                    symbol, self.ctx, self.work_load_list,
                    data_batch.provide_data, data_batch.provide_label,
                    for_training=True, inputs_need_grad=False,
                    param_names=self.param_names,
                    shared_group=self.execgrp)
            self.curr_execgrp = self.execgrp_bucket[key]
        self._batch = data_batch

    def forward(self, is_train=False):
        self.curr_execgrp.forward(self._batch, is_train=is_train)

    def backward(self):
        self.curr_execgrp.backward()

    def update_metric(self, metric, labels):
        self.curr_execgrp.update_metric(metric, labels)

    def install_monitor(self, monitor):
        monitor.install(self.curr_execgrp.executor)
