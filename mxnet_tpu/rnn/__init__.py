"""RNN toolkit (ref: python/mxnet/rnn/ — cells, bucketing IO, checkpoints)."""
