"""RNN checkpoint helpers (ref: python/mxnet/rnn/rnn.py): save/load model
params with cell-aware weight packing."""
from __future__ import annotations

from .. import model as _model


def save_rnn_checkpoint(cells, prefix, epoch, symbol, arg_params, aux_params):
    """Pack fused weights via the cells then save (ref: rnn.py)."""
    if not isinstance(cells, (list, tuple)):
        cells = [cells]
    for cell in cells:
        arg_params = cell.pack_weights(arg_params)
    _model.save_checkpoint(prefix, epoch, symbol, arg_params, aux_params)


def load_rnn_checkpoint(cells, prefix, epoch):
    """Load then unpack weights via the cells (ref: rnn.py)."""
    sym, arg, aux = _model.load_checkpoint(prefix, epoch)
    if not isinstance(cells, (list, tuple)):
        cells = [cells]
    for cell in cells:
        arg = cell.unpack_weights(arg)
    return sym, arg, aux


def do_rnn_checkpoint(cells, prefix, period=1):
    """Epoch-end callback variant (ref: rnn.py do_rnn_checkpoint)."""
    period = int(max(1, period))

    def _callback(iter_no, sym=None, arg=None, aux=None):
        if (iter_no + 1) % period == 0:
            save_rnn_checkpoint(cells, prefix, iter_no + 1, sym, arg, aux)
    return _callback
