"""RNN cells and unrolling (ref: python/mxnet/rnn/rnn_cell.py, 962 LoC).

API parity: BaseRNNCell(__call__/unroll/begin_state/pack_weights/
unpack_weights), RNNCell, LSTMCell, GRUCell, FusedRNNCell (wraps the fused
RNN op and can ``unfuse()`` into explicit cells), SequentialRNNCell,
BidirectionalCell, DropoutCell, ZoneoutCell, ModifierCell
(ref: rnn_cell.py:90-316 unroll, :497 FusedRNNCell).

Gate order i,f,g,o for LSTM and r,z,n for GRU — identical between the
explicit cells and the fused RNN op so fused-vs-unrolled consistency tests
hold (ref strategy: tests/python/unittest/test_rnn.py).
"""
from __future__ import annotations

import numpy as np

from ..base import MXNetError
from .. import symbol as sym
from ..ops.rnn_op import rnn_param_size, _param_slices, _GATES


class RNNParams(object):
    """Container for cell parameter symbols (ref: rnn_cell.py RNNParams)."""

    def __init__(self, prefix=""):
        self._prefix = prefix
        self._params = {}

    def get(self, name, **kwargs):
        name = self._prefix + name
        if name not in self._params:
            self._params[name] = sym.Variable(name, **kwargs)
        return self._params[name]


class BaseRNNCell(object):
    def __init__(self, prefix="", params=None):
        if params is None:
            params = RNNParams(prefix)
            self._own_params = True
        else:
            self._own_params = False
        self._prefix = prefix
        self._params = params
        self._init_counter = -1
        self._counter = -1

    @property
    def params(self):
        self._own_params = False
        return self._params

    @property
    def state_info(self):
        raise NotImplementedError()

    @property
    def state_shape(self):
        return [info["shape"] if info else None for info in self.state_info]

    @property
    def _gate_names(self):
        return ("",)

    def __call__(self, inputs, states):
        raise NotImplementedError()

    def reset(self):
        self._init_counter = -1
        self._counter = -1

    def begin_state(self, func=None, **kwargs):
        """Initial states. Default: Variables (fed like the reference's
        init_h/init_c iterator-provided states); pass func=sym.zeros-like
        factories for constant init."""
        assert not getattr(self, "_modified", False)
        states = []
        for info in self.state_info:
            self._init_counter += 1
            name = "%sbegin_state_%d" % (self._prefix, self._init_counter)
            if func is None:
                state = sym.Variable(name, **kwargs)
            else:
                if info is not None:
                    kw = dict(kwargs)
                    kw.update(info)
                    state = func(name=name, **kw)
                else:
                    state = func(name=name, **kwargs)
            states.append(state)
        return states

    # -- weight (un)packing (ref: rnn_cell.py unpack_weights) -----------
    def unpack_weights(self, args):
        args = dict(args)
        h = getattr(self, "_num_hidden", None)
        if h is None:
            return args
        for group in ("i2h", "h2h"):
            weight = args.pop("%s%s_weight" % (self._prefix, group))
            bias = args.pop("%s%s_bias" % (self._prefix, group))
            for j, gate in enumerate(self._gate_names):
                wname = "%s%s%s_weight" % (self._prefix, group, gate)
                args[wname] = weight[j * h:(j + 1) * h].copy()
                bname = "%s%s%s_bias" % (self._prefix, group, gate)
                args[bname] = bias[j * h:(j + 1) * h].copy()
        return args

    def pack_weights(self, args):
        from .. import ndarray as nd
        args = dict(args)
        h = getattr(self, "_num_hidden", None)
        if h is None:
            return args
        for group in ("i2h", "h2h"):
            ws = []
            bs = []
            for gate in self._gate_names:
                ws.append(args.pop("%s%s%s_weight" % (self._prefix, group,
                                                      gate)))
                bs.append(args.pop("%s%s%s_bias" % (self._prefix, group,
                                                    gate)))
            args["%s%s_weight" % (self._prefix, group)] = nd.concatenate(ws)
            args["%s%s_bias" % (self._prefix, group)] = nd.concatenate(bs)
        return args

    # -- unroll (ref: rnn_cell.py:90-316) -------------------------------
    def unroll(self, length, inputs=None, begin_state=None, input_prefix="",
               layout="NTC", merge_outputs=None):
        self.reset()
        if inputs is None:
            inputs = [sym.Variable("%st%d_data" % (input_prefix, i))
                      for i in range(length)]
        elif isinstance(inputs, sym.Symbol):
            assert len(inputs.list_outputs()) == 1, \
                "unroll doesn't allow grouped symbol as input"
            axis = layout.find("T")
            inputs = sym.SliceChannel(data=inputs, axis=axis,
                                      num_outputs=length, squeeze_axis=1)
            inputs = [inputs[i] for i in range(length)]
        else:
            assert len(inputs) == length
        if begin_state is None:
            begin_state = self.begin_state()
        states = begin_state
        outputs = []
        for i in range(length):
            output, states = self(inputs[i], states)
            outputs.append(output)
        if merge_outputs:
            outputs = [sym.expand_dims(data=o, axis=1) for o in outputs]
            outputs = sym.Concat(*outputs, dim=1)
        return outputs, states


class RNNCell(BaseRNNCell):
    """Vanilla RNN cell (ref: rnn_cell.py RNNCell)."""

    def __init__(self, num_hidden, activation="tanh", prefix="rnn_",
                 params=None):
        super().__init__(prefix=prefix, params=params)
        self._num_hidden = num_hidden
        self._activation = activation
        self._iW = self.params.get("i2h_weight")
        self._iB = self.params.get("i2h_bias")
        self._hW = self.params.get("h2h_weight")
        self._hB = self.params.get("h2h_bias")

    @property
    def state_info(self):
        return [{"shape": (0, self._num_hidden), "__layout__": "NC"}]

    def __call__(self, inputs, states):
        self._counter += 1
        name = "%st%d_" % (self._prefix, self._counter)
        i2h = sym.FullyConnected(data=inputs, weight=self._iW, bias=self._iB,
                                 num_hidden=self._num_hidden,
                                 name="%si2h" % name)
        h2h = sym.FullyConnected(data=states[0], weight=self._hW,
                                 bias=self._hB, num_hidden=self._num_hidden,
                                 name="%sh2h" % name)
        output = sym.Activation(data=i2h + h2h, act_type=self._activation,
                                name="%sout" % name)
        return output, [output]


class LSTMCell(BaseRNNCell):
    """LSTM cell, gate order i,f,g,o (ref: rnn_cell.py LSTMCell)."""

    def __init__(self, num_hidden, prefix="lstm_", params=None,
                 forget_bias=1.0):
        super().__init__(prefix=prefix, params=params)
        self._num_hidden = num_hidden
        from ..initializer import LSTMBias
        self._iW = self.params.get("i2h_weight")
        self._iB = self.params.get("i2h_bias",
                                   init=LSTMBias(forget_bias=forget_bias))
        self._hW = self.params.get("h2h_weight")
        self._hB = self.params.get("h2h_bias")

    @property
    def state_info(self):
        return [{"shape": (0, self._num_hidden), "__layout__": "NC"},
                {"shape": (0, self._num_hidden), "__layout__": "NC"}]

    @property
    def _gate_names(self):
        return ["_i", "_f", "_c", "_o"]

    def __call__(self, inputs, states):
        self._counter += 1
        name = "%st%d_" % (self._prefix, self._counter)
        i2h = sym.FullyConnected(data=inputs, weight=self._iW, bias=self._iB,
                                 num_hidden=self._num_hidden * 4,
                                 name="%si2h" % name)
        h2h = sym.FullyConnected(data=states[0], weight=self._hW,
                                 bias=self._hB,
                                 num_hidden=self._num_hidden * 4,
                                 name="%sh2h" % name)
        gates = i2h + h2h
        slice_gates = sym.SliceChannel(data=gates, num_outputs=4, axis=1,
                                       name="%sslice" % name)
        in_gate = sym.Activation(data=slice_gates[0], act_type="sigmoid")
        forget_gate = sym.Activation(data=slice_gates[1], act_type="sigmoid")
        in_transform = sym.Activation(data=slice_gates[2], act_type="tanh")
        out_gate = sym.Activation(data=slice_gates[3], act_type="sigmoid")
        next_c = forget_gate * states[1] + in_gate * in_transform
        next_h = out_gate * sym.Activation(data=next_c, act_type="tanh")
        return next_h, [next_h, next_c]


class GRUCell(BaseRNNCell):
    """GRU cell, gate order r,z,n (ref: rnn_cell.py GRUCell)."""

    def __init__(self, num_hidden, prefix="gru_", params=None):
        super().__init__(prefix=prefix, params=params)
        self._num_hidden = num_hidden
        self._iW = self.params.get("i2h_weight")
        self._iB = self.params.get("i2h_bias")
        self._hW = self.params.get("h2h_weight")
        self._hB = self.params.get("h2h_bias")

    @property
    def state_info(self):
        return [{"shape": (0, self._num_hidden), "__layout__": "NC"}]

    @property
    def _gate_names(self):
        return ["_r", "_z", "_o"]

    def __call__(self, inputs, states):
        self._counter += 1
        name = "%st%d_" % (self._prefix, self._counter)
        prev_h = states[0]
        i2h = sym.FullyConnected(data=inputs, weight=self._iW, bias=self._iB,
                                 num_hidden=self._num_hidden * 3,
                                 name="%si2h" % name)
        h2h = sym.FullyConnected(data=prev_h, weight=self._hW, bias=self._hB,
                                 num_hidden=self._num_hidden * 3,
                                 name="%sh2h" % name)
        i2h_s = sym.SliceChannel(data=i2h, num_outputs=3, axis=1)
        h2h_s = sym.SliceChannel(data=h2h, num_outputs=3, axis=1)
        reset_gate = sym.Activation(data=i2h_s[0] + h2h_s[0],
                                    act_type="sigmoid")
        update_gate = sym.Activation(data=i2h_s[1] + h2h_s[1],
                                     act_type="sigmoid")
        next_h_tmp = sym.Activation(data=i2h_s[2] + reset_gate * h2h_s[2],
                                    act_type="tanh")
        next_h = (1.0 - update_gate) * next_h_tmp + update_gate * prev_h
        return next_h, [next_h]


class FusedRNNCell(BaseRNNCell):
    """Fused multi-layer RNN over the RNN op (ref: rnn_cell.py:497)."""

    def __init__(self, num_hidden, num_layers=1, mode="lstm",
                 bidirectional=False, dropout=0.0, get_next_state=False,
                 forget_bias=1.0, prefix=None, params=None):
        if prefix is None:
            prefix = "%s_" % mode
        super().__init__(prefix=prefix, params=params)
        self._num_hidden = num_hidden
        self._num_layers = num_layers
        self._mode = mode
        self._bidirectional = bidirectional
        self._dropout = dropout
        self._get_next_state = get_next_state
        self._forget_bias = forget_bias
        self._parameter = self.params.get("parameters")
        self._directions = 2 if bidirectional else 1

    @property
    def state_info(self):
        b = self._directions * self._num_layers
        n = 2 if self._mode == "lstm" else 1
        return [{"shape": (b, 0, self._num_hidden), "__layout__": "LNC"}
                for _ in range(n)]

    @property
    def _gate_names(self):
        return {"rnn_relu": [""], "rnn_tanh": [""],
                "lstm": ["_i", "_f", "_c", "_o"],
                "gru": ["_r", "_z", "_o"]}[self._mode]

    @property
    def _num_gates(self):
        return len(self._gate_names)

    def __call__(self, inputs, states):
        raise MXNetError("FusedRNNCell cannot be stepped. Please use unroll")

    def unroll(self, length, inputs=None, begin_state=None, input_prefix="",
               layout="NTC", merge_outputs=None):
        self.reset()
        assert inputs is not None, "FusedRNNCell requires symbolic inputs"
        axis = layout.find("T")
        if isinstance(inputs, list):
            inputs = [sym.expand_dims(data=i, axis=axis) for i in inputs]
            inputs = sym.Concat(*inputs, dim=axis)
        if layout == "NTC":
            inputs = sym.SwapAxis(data=inputs, dim1=0, dim2=1)  # -> TNC
        if begin_state is None:
            begin_state = self.begin_state()
        states = list(begin_state)
        rnn_args = dict(data=inputs, parameters=self._parameter,
                        state=states[0])
        if self._mode == "lstm":
            rnn_args["state_cell"] = states[1]
        rnn = sym.RNN(state_size=self._num_hidden,
                      num_layers=self._num_layers, mode=self._mode,
                      bidirectional=self._bidirectional, p=self._dropout,
                      state_outputs=self._get_next_state,
                      name="%srnn" % self._prefix, **rnn_args)
        if self._get_next_state:
            outputs = rnn[0]
            states = ([rnn[1], rnn[2]] if self._mode == "lstm" else [rnn[1]])
        else:
            outputs = rnn if isinstance(rnn, sym.Symbol) and \
                len(rnn.list_outputs()) == 1 else rnn[0]
            states = []
        if layout == "NTC":
            outputs = sym.SwapAxis(data=outputs, dim1=0, dim2=1)
        if merge_outputs is False:
            outputs = sym.SliceChannel(data=outputs, axis=axis,
                                       num_outputs=length, squeeze_axis=1)
            outputs = [outputs[i] for i in range(length)]
        return outputs, states

    # -- pack/unpack between the flat vector and per-gate weights -------
    def unpack_weights(self, args):
        from .. import ndarray as nd
        args = dict(args)
        arr = args.pop("%sparameters" % self._prefix).asnumpy()
        h = self._num_hidden
        cells = self._slice_cells()
        input_size = self._infer_input_size(arr)
        slices, _total = _param_slices(self._mode, input_size, h,
                                       self._num_layers, self._bidirectional)
        for (layer, dr), cell_prefix in cells.items():
            wx, wh, bx, bh = slices[(layer, dr)]
            for spec, nm in ((wx, "i2h_weight"), (wh, "h2h_weight"),
                             (bx, "i2h_bias"), (bh, "h2h_bias")):
                off, nsz, shape = spec
                args[cell_prefix + nm] = nd.array(
                    arr[off:off + nsz].reshape(shape))
        return args

    def pack_weights(self, args):
        from .. import ndarray as nd
        args = dict(args)
        h = self._num_hidden
        cells = self._slice_cells()
        sample = args["%sl0_i2h_weight" % self._prefix].asnumpy()
        input_size = sample.shape[1]
        slices, total = _param_slices(self._mode, input_size, h,
                                      self._num_layers, self._bidirectional)
        flat = np.zeros(total, np.float32)
        for (layer, dr), cell_prefix in cells.items():
            wx, wh, bx, bh = slices[(layer, dr)]
            for spec, nm in ((wx, "i2h_weight"), (wh, "h2h_weight"),
                             (bx, "i2h_bias"), (bh, "h2h_bias")):
                off, nsz, shape = spec
                flat[off:off + nsz] = args.pop(
                    cell_prefix + nm).asnumpy().reshape(-1)
        args["%sparameters" % self._prefix] = nd.array(flat)
        return args

    def _slice_cells(self):
        cells = {}
        for layer in range(self._num_layers):
            for dr in range(self._directions):
                suffix = "" if dr == 0 else "_r"
                cells[(layer, dr)] = "%sl%d%s_" % (self._prefix, layer, suffix)
        return cells

    def _infer_input_size(self, arr):
        # invert rnn_param_size for layer-0 input size
        g = self._num_gates
        h = self._num_hidden
        d = self._directions
        L = self._num_layers
        total = arr.size
        # total = d*(g*h*i + g*h*h) + (L-1)*d*(g*h*h*d + g*h*h) + L*d*2*g*h
        rest = (L - 1) * d * (g * h * h * d + g * h * h) + L * d * 2 * g * h
        return (total - rest - d * g * h * h) // (d * g * h)

    def unfuse(self):
        """Equivalent SequentialRNNCell of explicit cells (ref: unfuse())."""
        stack = SequentialRNNCell()
        get_cell = {
            "rnn_relu": lambda p: RNNCell(self._num_hidden, "relu", p),
            "rnn_tanh": lambda p: RNNCell(self._num_hidden, "tanh", p),
            "lstm": lambda p: LSTMCell(self._num_hidden, p),
            "gru": lambda p: GRUCell(self._num_hidden, p)}[self._mode]
        for i in range(self._num_layers):
            if self._bidirectional:
                stack.add(BidirectionalCell(
                    get_cell("%sl%d_" % (self._prefix, i)),
                    get_cell("%sl%d_r_" % (self._prefix, i)),
                    output_prefix="%sbi_l%d_" % (self._prefix, i)))
            else:
                stack.add(get_cell("%sl%d_" % (self._prefix, i)))
            if self._dropout > 0 and i != self._num_layers - 1:
                stack.add(DropoutCell(self._dropout,
                                      prefix="%s_dropout%d_" % (self._prefix,
                                                                i)))
        return stack


class SequentialRNNCell(BaseRNNCell):
    """Stack of cells (ref: rnn_cell.py SequentialRNNCell)."""

    def __init__(self, params=None):
        super().__init__(prefix="", params=params)
        self._override_cell_params = params is not None
        self._cells = []

    def add(self, cell):
        self._cells.append(cell)
        if self._override_cell_params:
            assert cell._own_params
            cell.params._params.update(self.params._params)
            self.params._params.update(cell.params._params)

    @property
    def state_info(self):
        return sum([c.state_info for c in self._cells], [])

    def begin_state(self, **kwargs):
        assert not getattr(self, "_modified", False)
        return sum([c.begin_state(**kwargs) for c in self._cells], [])

    def unpack_weights(self, args):
        for cell in self._cells:
            args = cell.unpack_weights(args)
        return args

    def pack_weights(self, args):
        for cell in self._cells:
            args = cell.pack_weights(args)
        return args

    def __call__(self, inputs, states):
        self._counter += 1
        next_states = []
        p = 0
        for cell in self._cells:
            n = len(cell.state_info)
            state = states[p:p + n]
            p += n
            inputs, state = cell(inputs, state)
            next_states.append(state)
        return inputs, sum(next_states, [])


class DropoutCell(BaseRNNCell):
    def __init__(self, dropout=0.0, prefix="dropout_", params=None):
        super().__init__(prefix=prefix, params=params)
        self.dropout = dropout

    @property
    def state_info(self):
        return []

    def __call__(self, inputs, states):
        if self.dropout > 0:
            inputs = sym.Dropout(data=inputs, p=self.dropout)
        return inputs, states


class ModifierCell(BaseRNNCell):
    """Base for cells wrapping another cell (ref: rnn_cell.py ModifierCell)."""

    def __init__(self, base_cell):
        super().__init__()
        base_cell._modified = True
        self.base_cell = base_cell

    @property
    def params(self):
        self._own_params = False
        return self.base_cell.params

    @property
    def state_info(self):
        return self.base_cell.state_info

    def begin_state(self, init_sym=None, **kwargs):
        assert not getattr(self, "_modified", False)
        self.base_cell._modified = False
        begin = self.base_cell.begin_state(init_sym, **kwargs) \
            if init_sym is not None else self.base_cell.begin_state(**kwargs)
        self.base_cell._modified = True
        return begin

    def unpack_weights(self, args):
        return self.base_cell.unpack_weights(args)

    def pack_weights(self, args):
        return self.base_cell.pack_weights(args)


class ZoneoutCell(ModifierCell):
    """Zoneout regularization (ref: rnn_cell.py ZoneoutCell)."""

    def __init__(self, base_cell, zoneout_outputs=0.0, zoneout_states=0.0):
        super().__init__(base_cell)
        self.zoneout_outputs = zoneout_outputs
        self.zoneout_states = zoneout_states
        self.prev_output = None

    def reset(self):
        super().reset()
        self.prev_output = None

    def __call__(self, inputs, states):
        cell = self.base_cell
        next_output, next_states = cell(inputs, states)
        mask = (lambda p, like: sym.Dropout(
            data=sym.ones_like(data=like), p=p))
        prev_output = self.prev_output if self.prev_output is not None \
            else next_output * 0
        output = (sym.where(condition=mask(self.zoneout_outputs, next_output),
                            x=next_output, y=prev_output)
                  if self.zoneout_outputs > 0.0 else next_output)
        new_states = ([sym.where(condition=mask(self.zoneout_states, ns),
                                 x=ns, y=os)
                       for ns, os in zip(next_states, states)]
                      if self.zoneout_states > 0.0 else next_states)
        self.prev_output = output
        return output, new_states


class BidirectionalCell(BaseRNNCell):
    """Bidirectional wrapper (ref: rnn_cell.py BidirectionalCell)."""

    def __init__(self, l_cell, r_cell, params=None, output_prefix="bi_"):
        super().__init__(prefix="", params=params)
        self._output_prefix = output_prefix
        self._cells = [l_cell, r_cell]

    def unpack_weights(self, args):
        for c in self._cells:
            args = c.unpack_weights(args)
        return args

    def pack_weights(self, args):
        for c in self._cells:
            args = c.pack_weights(args)
        return args

    def __call__(self, inputs, states):
        raise MXNetError("Bidirectional cannot be stepped. Please use unroll")

    @property
    def state_info(self):
        return sum([c.state_info for c in self._cells], [])

    def begin_state(self, **kwargs):
        assert not getattr(self, "_modified", False)
        return sum([c.begin_state(**kwargs) for c in self._cells], [])

    def unroll(self, length, inputs=None, begin_state=None, input_prefix="",
               layout="NTC", merge_outputs=None):
        self.reset()
        if isinstance(inputs, sym.Symbol):
            axis = layout.find("T")
            inputs = sym.SliceChannel(data=inputs, axis=axis,
                                      num_outputs=length, squeeze_axis=1)
            inputs = [inputs[i] for i in range(length)]
        if begin_state is None:
            begin_state = self.begin_state()
        l_cell, r_cell = self._cells
        n_l = len(l_cell.state_info)
        l_outputs, l_states = l_cell.unroll(
            length, inputs=inputs, begin_state=begin_state[:n_l],
            layout=layout, merge_outputs=False)
        r_outputs, r_states = r_cell.unroll(
            length, inputs=list(reversed(inputs)),
            begin_state=begin_state[n_l:], layout=layout,
            merge_outputs=False)
        outputs = [sym.Concat(l_o, r_o, dim=1,
                              name="%st%d" % (self._output_prefix, i))
                   for i, (l_o, r_o) in enumerate(
                       zip(l_outputs, reversed(r_outputs)))]
        if merge_outputs:
            outputs = [sym.expand_dims(data=o, axis=1) for o in outputs]
            outputs = sym.Concat(*outputs, dim=1)
        return outputs, l_states + r_states
