"""Control-plane ring for elastic multi-process training.

The reference's distributed tier rides ps-lite: a tracker rendezvouses
workers and servers, Van/Postoffice move key-value messages, and
GetDeadNodes watches heartbeats (ref: src/kvstore/kvstore_dist.h,
ps-lite Van). Our rendezvous is ``jax.distributed`` — but XLA
collectives are the WRONG substrate for elasticity: a peer that dies
inside a psum leaves the survivors wedged in an uncancellable device
wait. So cross-process reduction for `dist_sync` rides this module
instead: a bulk-synchronous exchange over the coordination service's
key-value store, where every wait loop aborts the moment a peer's
heartbeat goes stale. Losing a worker surfaces as
:class:`~mxnet_tpu.kvstore.WorkerLostError` in bounded time — never a
hang — and the surviving members can re-form the ring at N-1 and keep
training (docs/robustness.md "Elastic distributed training").

Pieces:

* :class:`LocalClient` — in-memory, thread-safe KV + liveness, the
  tier-1 test double (threads stand in for processes).
* :class:`CoordClient` — the same interface over jax's
  DistributedRuntimeClient; liveness is heartbeat-stamp staleness.
* :class:`Ring` — allreduce_sum / broadcast / barrier over the KV
  plane, generation-tagged so a re-formed ring never reads a dead
  generation's keys, plus the first-write-wins re-form protocol and
  the epoch-boundary join protocol.

Fault sites (docs/robustness.md "Fault injection"): ``kv.worker_die``
fires at the top of every collective op ("die" SIGKILLs the process,
the injector's raising kinds propagate), and ``kv.partition`` fires in
the per-peer poll loop ("drop" models a dropped control-plane message:
the read is requeued and retried, so a finite partition heals and a
persistent one ends in KVStoreTimeoutError, never a hang).
"""
from __future__ import annotations

import io
import json
import os
import threading
import time

import numpy as np

from . import faults as _faults
from .base import MXNetError

__all__ = ["LocalClient", "CoordClient", "Ring", "DIST_HEALTH"]

#: heartbeat key prefix shared with kvstore._Heartbeat
HB_PREFIX = "mxtpu_hb/"


def _env_float(name, default):
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


class DistHealth(object):
    """Process-global distributed-tier counters, mirrored into the obs
    registry as the ``dist_health`` view (the ``TRAINING_HEALTH``
    pattern, docs/observability.md)."""

    def __init__(self):
        self.reset()

    def reset(self):
        self.rank = -1
        self.workers = 0
        self.generation = 0
        self.reforms = 0
        self.worker_lost = 0
        self.requeued = 0          # control-plane reads retried (partition)
        self.heartbeats = 0        # beats published by this process
        self.staleness_lag = 0     # dist_async: my_ver - min(peer_ver)
        self.joins = 0
        self.last_dead = ()

    def report(self):
        return {"rank": self.rank, "workers": self.workers,
                "generation": self.generation, "reforms": self.reforms,
                "worker_lost": self.worker_lost, "requeued": self.requeued,
                "heartbeats": self.heartbeats,
                "staleness_lag": self.staleness_lag, "joins": self.joins,
                "last_dead": ",".join(str(r) for r in self.last_dead)}


DIST_HEALTH = DistHealth()


def _flight_dump(reason, extra=None):
    try:
        from .obs import flight
        flight.dump(reason, extra=extra)
    except Exception:
        pass


# --------------------------------------------------------------------------
# KV-plane clients
# --------------------------------------------------------------------------

class LocalClient(object):
    """In-memory control plane for tier-1 tests: threads play workers,
    liveness is explicit (:meth:`mark_dead`), and there is no clock in
    the loop — tests inject faults, not sleeps."""

    def __init__(self):
        self._lock = threading.Lock()
        self._store = {}
        self._dead = set()

    def set(self, key, value, overwrite=True):
        if isinstance(value, str):
            value = value.encode()
        with self._lock:
            if not overwrite and key in self._store:
                return False
            self._store[key] = bytes(value)
            return True

    def get(self, key):
        with self._lock:
            return self._store.get(key)

    def dir(self, prefix):
        with self._lock:
            return {k: v for k, v in self._store.items()
                    if k.startswith(prefix)}

    def delete(self, key):
        with self._lock:
            self._store.pop(key, None)

    def alive(self, rank):
        with self._lock:
            return rank not in self._dead

    def mark_dead(self, rank):
        with self._lock:
            self._dead.add(rank)

    def revive(self, rank):
        with self._lock:
            self._dead.discard(rank)


class CoordClient(object):
    """The same interface over jax's coordination-service client.

    Liveness: a rank is alive while its ``mxtpu_hb/<rank>`` stamp is
    fresher than ``dead_for`` seconds (kvstore._Heartbeat publishes
    every 2s). A rank with NO stamp is given ``grace`` seconds from
    this client's creation — "not up yet" is not "dead"."""

    def __init__(self, client, dead_for=None, grace=None):
        self._c = client
        self.dead_for = dead_for if dead_for is not None else \
            _env_float("MXTPU_DIST_DEAD_FOR", 6.0)
        self.grace = grace if grace is not None else \
            _env_float("MXTPU_DIST_GRACE", 30.0)
        self._started = time.time()

    # -- kv --
    def set(self, key, value, overwrite=True):
        if isinstance(value, str):
            value = value.encode()
        try:
            try:
                self._c.key_value_set_bytes(key, bytes(value),
                                            allow_overwrite=overwrite)
            except TypeError:   # older binding: no allow_overwrite kwarg
                if overwrite:
                    try:
                        self._c.key_value_delete(key)
                    except Exception:
                        pass
                self._c.key_value_set_bytes(key, bytes(value))
            return True
        except Exception as e:
            if not overwrite and "already exists" in str(e).lower():
                return False
            if not overwrite:
                return False
            raise

    def get(self, key):
        # no try_get on this binding, and dir-get treats its argument as
        # a DIRECTORY (a probe without a trailing "/" gets one appended,
        # so an exact-key probe always misses) — the non-blocking read is
        # a parent-directory scan picking the exact key
        parent = key.rsplit("/", 1)[0] + "/" if "/" in key else ""
        return self._dir_raw(parent).get(key)

    def dir(self, prefix):
        return self._dir_raw(prefix)

    def _dir_raw(self, prefix):
        out = {}
        try:
            got = self._c.key_value_dir_get_bytes(prefix)
        except Exception:
            try:
                got = self._c.key_value_dir_get(prefix)
            except Exception:
                return out
        items = got.items() if hasattr(got, "items") else got
        for k, v in items:
            if isinstance(v, str):
                v = v.encode()
            out[k] = v
        return out

    def delete(self, key):
        try:
            self._c.key_value_delete(key)
        except Exception:
            pass

    # -- liveness --
    def alive(self, rank):
        v = self.get(HB_PREFIX + "%d" % rank)
        if v is None:
            return time.time() - self._started <= self.grace
        try:
            stamp = float(v.decode())
        except (ValueError, UnicodeDecodeError):
            return True
        return time.time() - stamp <= self.dead_for


# --------------------------------------------------------------------------
# array / payload codec
# --------------------------------------------------------------------------

def _encode_array(arr):
    bio = io.BytesIO()
    np.lib.format.write_array(bio, np.ascontiguousarray(arr),
                              allow_pickle=False)
    return bio.getvalue()


def _decode_array(data):
    return np.lib.format.read_array(io.BytesIO(data), allow_pickle=False)


# --------------------------------------------------------------------------
# the ring
# --------------------------------------------------------------------------

class Ring(object):
    """Bulk-synchronous exchange group over a KV plane.

    The BSP contract (every member runs the same collectives in the
    same order — exactly what `dist_sync` training guarantees) makes a
    monotonic sequence number a sufficient message tag. Keys live under
    ``<ns>/g<gen>/...``: a re-formed ring bumps the generation, so
    stragglers of the old membership can never read the new ring's
    traffic. Determinism: reductions sum in member order, so every
    worker computes a bitwise-identical result.
    """

    def __init__(self, client, rank, members, ns="mxring", poll=None,
                 op_timeout=None):
        self.client = client
        self.rank = int(rank)
        self.members = sorted(int(m) for m in members)
        assert self.rank in self.members
        self.ns = ns
        self.gen = 0
        self.seq = 0
        self.poll = poll if poll is not None else \
            _env_float("MXTPU_DIST_POLL", 0.005)
        self.op_timeout = op_timeout if op_timeout is not None else \
            _env_float("MXTPU_DIST_OP_TIMEOUT", 120.0)
        self.dead = ()          # ranks found dead by the last failed op
        self._published = []    # [(seq, [keys])] for trailing-edge GC
        DIST_HEALTH.rank = self.rank
        DIST_HEALTH.workers = len(self.members)

    # -- membership helpers --
    @property
    def size(self):
        return len(self.members)

    @property
    def index(self):
        """This worker's logical position in the live membership (the
        data-shard index after a re-form; the process rank is identity,
        this is placement)."""
        return self.members.index(self.rank)

    def liveness_table(self):
        return {str(r): ("self" if r == self.rank
                         else ("alive" if self.client.alive(r) else "dead"))
                for r in self.members}

    # -- key layout --
    def _key(self, kind, seq, rank):
        return "%s/g%d/%s/%d/%d" % (self.ns, self.gen, kind, seq, rank)

    # -- core exchange --
    def _exchange(self, kind, payload, roots=None):
        """Publish ``payload`` under this op's sequence number, collect
        every member's payload (or only ``roots``'), GC the trailing
        sequence. Raises WorkerLostError naming the dead ranks if a
        peer's key never lands and its heartbeat is stale."""
        act = _faults.fire("kv.worker_die")
        if act == "die":
            import signal
            os.kill(os.getpid(), signal.SIGKILL)
        seq = self.seq
        self.seq += 1
        mine = self._key(kind, seq, self.rank)
        # 2-byte frame: a stored value SHORTER THAN 2 BYTES segfaults
        # this jaxlib's key_value_dir_get binding, and broadcast
        # non-roots publish b"" — so every exchange payload is framed to
        # at least 2 bytes on the plane (stripped in _fetch)
        if isinstance(payload, str):
            payload = payload.encode()
        self.client.set(mine, b"MX" + payload)
        self._published.append((seq, [mine]))
        out = {self.rank: payload}
        want = self.members if roots is None else \
            [r for r in roots if r != self.rank]
        for r in want:
            if r == self.rank:
                continue
            out[r] = self._fetch(kind, seq, r)
        # trailing-edge GC: by BSP lockstep, once THIS op completed
        # everywhere a key two ops old has been read by every peer
        while self._published and self._published[0][0] <= seq - 2:
            _, keys = self._published.pop(0)
            for k in keys:
                self.client.delete(k)
        return out

    def _fetch(self, kind, seq, r):
        key = self._key(kind, seq, r)
        deadline = time.time() + self.op_timeout
        reform_prefix = "%s/reform/%d/prop/" % (self.ns, self.gen + 1)
        while True:
            act = _faults.fire("kv.partition")
            if act == "drop":
                # a dropped control-plane message: requeue the read —
                # falling THROUGH to the deadline check, so a persistent
                # partition ends in the timeout below, never a spin
                DIST_HEALTH.requeued += 1
            else:
                v = self.client.get(key)
                if v is not None:
                    return v[2:]  # strip the exchange frame bytes
                # a peer already gave up on this generation: join the
                # re-form instead of waiting on traffic that never comes
                if self.client.dir(reform_prefix):
                    self._lost([],
                               "re-form of generation %d already proposed"
                               % (self.gen + 1))
                if not self.client.alive(r):
                    self._lost([r], "no heartbeat and no g%d/%s/%d key"
                               % (self.gen, kind, seq))
            if time.time() >= deadline:
                from .kvstore import KVStoreTimeoutError
                raise KVStoreTimeoutError(
                    "ring %s op (gen %d seq %d) timed out after %.0fs "
                    "waiting on rank %d" % (kind, self.gen, seq,
                                            self.op_timeout, r),
                    started=True)
            if self.poll:
                time.sleep(self.poll)

    def _lost(self, dead, why):
        from .kvstore import WorkerLostError
        self.dead = tuple(sorted(dead))
        DIST_HEALTH.worker_lost += 1
        DIST_HEALTH.last_dead = self.dead
        table = self.liveness_table()
        _flight_dump("ring worker lost (gen %d): %s" % (self.gen, why),
                     extra={"liveness": table, "generation": self.gen,
                            "members": list(self.members)})
        raise WorkerLostError(
            "worker(s) %s lost from ring generation %d (%s); liveness=%s"
            % (list(self.dead) or "?", self.gen, why, table))

    # -- collectives --
    def allreduce_sum(self, arr):
        """Deterministic cross-worker sum: every member's array, summed
        in member order (bitwise-identical on every worker)."""
        arr = np.asarray(arr)
        if self.size == 1:
            return arr.copy()
        got = self._exchange("red", _encode_array(arr))
        out = None
        for r in self.members:
            a = arr if r == self.rank else _decode_array(got[r])
            out = a.copy() if out is None else out + a
        return out

    def broadcast_bytes(self, payload, root_index=0):
        """Raw-bytes broadcast from the member at ``root_index``."""
        root = self.members[root_index]
        if self.size == 1:
            return payload
        data = payload if self.rank == root else b""
        got = self._exchange("bcast", data)
        return got[root]

    def broadcast(self, arr=None, root_index=0):
        root = self.members[root_index]
        if self.size == 1:
            return np.asarray(arr)
        data = _encode_array(arr) if self.rank == root else b""
        got = self._exchange("bcast", data)
        return np.asarray(arr) if self.rank == root \
            else _decode_array(got[root])

    def barrier(self):
        if self.size > 1:
            self._exchange("bar", b"1")

    # -- re-form protocol --
    def reform(self, extra_members=(), timeout=None):
        """Re-form the ring around the live members (plus any pending
        joiners). First-write-wins proposals under
        ``<ns>/reform/<gen+1>/prop/<attempt>`` converge every survivor
        on ONE membership; all-member acks double as the commit
        barrier. Returns the new member list.

        A member that died mid-reform is handled by attempt
        escalation: any member that sees a dead rank in the current
        proposal (and leads the live set) proposes attempt+1, and
        ack-waiters abort to the newer attempt.
        """
        gen2 = self.gen + 1
        deadline = time.time() + (timeout if timeout is not None
                                  else self.op_timeout)
        prop_prefix = "%s/reform/%d/prop/" % (self.ns, gen2)
        joiners = set(int(j) for j in extra_members)
        joiners |= set(self.poll_joiners())

        while True:
            if time.time() >= deadline:
                from .kvstore import KVStoreTimeoutError
                raise KVStoreTimeoutError(
                    "ring re-form to generation %d did not converge "
                    "within %.0fs" % (gen2, self.op_timeout), started=True)
            live = sorted(r for r in self.members
                          if r == self.rank or self.client.alive(r))
            props = self.client.dir(prop_prefix)
            attempts = sorted(int(k.rsplit("/", 1)[1]) for k in props)
            if not attempts:
                if self.rank == min(live):
                    # kv.reform_delay: a slow LEADER — the proposal lands
                    # late; followers keep polling (they converge once it
                    # appears) or hit the re-form deadline above, so a
                    # straggling leader is bounded, never a hang
                    _faults.fire("kv.reform_delay")
                    prop = sorted(set(live) | joiners)
                    self.client.set(
                        prop_prefix + "0",
                        json.dumps({"members": prop,
                                    "joiners": sorted(joiners)}),
                        overwrite=False)
                if self.poll:
                    time.sleep(self.poll)
                continue
            att = attempts[-1]
            d = json.loads(props[prop_prefix + "%d" % att].decode())
            members = [int(m) for m in d["members"]]
            # the PROPOSAL's joiner list is the authoritative one: a
            # member whose own poll raced the join request must still
            # reach the same verdict as everyone else
            prop_joiners = set(int(j) for j in d.get("joiners", []))
            if self.rank not in members:
                self._lost([self.rank],
                           "this rank was evicted by re-form attempt %d"
                           % att)
            stale = [r for r in members
                     if r != self.rank and r not in prop_joiners
                     and not self.client.alive(r)]
            if stale:
                if self.rank == min(r for r in live if r in members):
                    _faults.fire("kv.reform_delay")
                    prop = sorted((set(members) - set(stale)) | joiners)
                    self.client.set(
                        prop_prefix + "%d" % (att + 1),
                        json.dumps({"members": prop,
                                    "joiners": sorted(joiners)}),
                        overwrite=False)
                if self.poll:
                    time.sleep(self.poll)
                continue
            # joiners don't ack — they learn the membership only from the
            # commit ticket; the barrier is across incumbents
            if self._ack_and_wait(
                    gen2, att,
                    [m for m in members if m not in prop_joiners],
                    deadline):
                self._commit(gen2, members, sorted(prop_joiners))
                return list(self.members)
            # a newer attempt superseded this one; loop and re-read

    def _ack_and_wait(self, gen2, att, members, deadline):
        ack = "%s/reform/%d/ack/%d/" % (self.ns, gen2, att)
        # "ok", not "1": sub-2-byte values segfault jaxlib's dir-get
        self.client.set(ack + "%d" % self.rank, b"ok")
        newer = "%s/reform/%d/prop/%d" % (self.ns, gen2, att + 1)
        while True:
            have = self.client.dir(ack)
            if all((ack + "%d" % r) in have for r in members):
                return True
            if self.client.get(newer) is not None:
                return False
            if time.time() >= deadline:
                from .kvstore import KVStoreTimeoutError
                raise KVStoreTimeoutError(
                    "re-form ack wait (gen %d attempt %d) timed out"
                    % (gen2, att), started=True)
            if self.poll:
                time.sleep(self.poll)

    def _commit(self, gen2, members, joiners):
        old = list(self.members)
        self.gen = gen2
        self.seq = 0
        self.members = sorted(members)
        self.dead = ()
        self._published = []
        DIST_HEALTH.reforms += 1
        DIST_HEALTH.workers = len(self.members)
        DIST_HEALTH.generation = self.gen
        # the new leader publishes the admission ticket for each joiner
        # and clears their requests
        if joiners and self.rank == self.members[0]:
            for j in joiners:
                self.client.set(
                    "%s/joined/%d" % (self.ns, j),
                    json.dumps({"gen": self.gen, "members": self.members}))
                self.client.delete("%s/join/%d" % (self.ns, j))
        _flight_dump(
            "ring re-formed: generation %d" % self.gen,
            extra={"members": list(self.members), "was": old,
                   "joiners": list(joiners),
                   "liveness": self.liveness_table()})

    # -- join protocol (late worker, epoch boundary) --
    def request_join(self, timeout=None):
        """Called by a late/rejoining worker: announce, then wait for an
        incumbent re-form to admit us. Adopts the committed generation
        and membership; the caller then warm-pulls current params
        (kvstore broadcast) before taking its first step."""
        # "ok", not "1": sub-2-byte values segfault jaxlib's dir-get
        self.client.set("%s/join/%d" % (self.ns, self.rank), b"ok")
        DIST_HEALTH.joins += 1
        key = "%s/joined/%d" % (self.ns, self.rank)
        deadline = time.time() + (timeout if timeout is not None
                                  else self.op_timeout)
        while True:
            v = self.client.get(key)
            if v is not None:
                d = json.loads(v.decode())
                self.gen = int(d["gen"])
                self.seq = 0
                self.members = sorted(int(m) for m in d["members"])
                self._published = []
                self.client.delete(key)
                DIST_HEALTH.workers = len(self.members)
                DIST_HEALTH.generation = self.gen
                return list(self.members)
            if time.time() >= deadline:
                from .kvstore import KVStoreTimeoutError
                raise KVStoreTimeoutError(
                    "join request was not admitted within %.0fs"
                    % self.op_timeout, started=True)
            if self.poll:
                time.sleep(self.poll)

    def poll_joiners(self):
        """Ranks currently requesting admission (non-blocking)."""
        prefix = "%s/join/" % self.ns
        out = []
        for k in self.client.dir(prefix):
            try:
                out.append(int(k.rsplit("/", 1)[1]))
            except ValueError:
                pass
        return sorted(r for r in out if r not in self.members)


# --------------------------------------------------------------------------
# process-global ring over the jax coordination service
# --------------------------------------------------------------------------

_shared = {}


def shared_ring():
    """The ONE process-wide ring over jax's coordination service (every
    dist kvstore shares it, so the BSP sequence stream is unified).
    Returns None when single-process."""
    r = _shared.get("ring")
    if r is not None:
        return r
    import jax
    if jax.process_count() <= 1:
        return None
    from jax._src.distributed import global_state
    client = getattr(global_state, "client", None)
    if client is None:
        raise MXNetError("dist kvstore requires jax.distributed.initialize "
                         "(tools/launch.py sets MXTPU_COORD/RANK/NPROC)")
    ring = Ring(CoordClient(client), jax.process_index(),
                range(jax.process_count()))
    _shared["ring"] = ring
    return ring


def _reset_shared_ring():
    _shared.pop("ring", None)
