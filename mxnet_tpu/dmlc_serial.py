"""Reference-compatible binary NDArray serialization (the ``.params`` format).

Byte layout reproduced from the reference implementation
(ref: src/ndarray/ndarray.cc:605-693, include/mxnet/ndarray.h:360-373,
include/mxnet/base.h:163-176; dmlc::Stream vector/string framing):

    uint64  magic = 0x112 (kMXAPINDArrayListMagic)
    uint64  reserved = 0
    uint64  ndarray count
    per NDArray:
        uint32  ndim, uint32 dims[ndim]      (mshadow TShape::Save)
        int32   dev_type, int32 dev_id       (Context::Save)
        int32   type_flag                    (mshadow type flags)
        raw little-endian tensor bytes
    uint64  name count (0 when saved as a bare list)
    per name: uint64 length, utf-8 bytes

mshadow type flags: 0=float32 1=float64 2=float16 3=uint8 4=int32. The era
has no bfloat16/int64; extension flags ≥100 cover them for round-tripping
repo checkpoints while staying out of the reference's flag space.
"""
from __future__ import annotations

import struct

import numpy as np

from .base import MXNetError

MAGIC = 0x112

_FLAG2DTYPE = {
    0: np.dtype(np.float32),
    1: np.dtype(np.float64),
    2: np.dtype(np.float16),
    3: np.dtype(np.uint8),
    4: np.dtype(np.int32),
    # extension flags (not emitted by the reference)
    100: np.dtype("bfloat16"),
    101: np.dtype(np.int64),
    102: np.dtype(np.uint64),
    103: np.dtype(np.int8),
    104: np.dtype(np.bool_),
}
_DTYPE2FLAG = {v: k for k, v in _FLAG2DTYPE.items()}


def _dtype_flag(dt):
    dt = np.dtype(dt)
    if dt in _DTYPE2FLAG:
        return _DTYPE2FLAG[dt]
    raise MXNetError("save: dtype %s has no .params type flag" % dt)


def dump(fo, arrays, names):
    """Stream numpy arrays (+ optional names) to a file object in the
    reference .params layout — one write per tensor, no full-blob copy."""
    fo.write(struct.pack("<QQ", MAGIC, 0))
    fo.write(struct.pack("<Q", len(arrays)))
    for arr in arrays:
        arr = np.ascontiguousarray(arr)
        flag = _dtype_flag(arr.dtype)
        fo.write(struct.pack("<I", arr.ndim))
        fo.write(struct.pack("<%dI" % arr.ndim, *arr.shape))
        fo.write(struct.pack("<ii", 1, 0))          # Context: kCPU, dev 0
        fo.write(struct.pack("<i", flag))
        if arr.dtype == np.dtype("bfloat16"):
            arr = arr.view(np.uint16)
        fo.write(arr.data if arr.ndim else arr.tobytes())
    fo.write(struct.pack("<Q", len(names)))
    for n in names:
        b = n.encode("utf-8")
        fo.write(struct.pack("<Q", len(b)))
        fo.write(b)


def dumps(arrays, names):
    """Serialize to bytes (testing convenience; save() streams via dump)."""
    import io
    buf = io.BytesIO()
    dump(buf, arrays, names)
    return buf.getvalue()


class _Reader(object):
    def __init__(self, buf):
        self.buf = buf
        self.pos = 0

    def take(self, n):
        if self.pos + n > len(self.buf):
            raise MXNetError("Invalid NDArray file format (truncated)")
        b = self.buf[self.pos:self.pos + n]
        self.pos += n
        return b

    def u64(self):
        return struct.unpack("<Q", self.take(8))[0]

    def u32(self):
        return struct.unpack("<I", self.take(4))[0]

    def i32(self):
        return struct.unpack("<i", self.take(4))[0]


def loads(buf):
    """Parse reference .params bytes -> (list of np arrays, list of names)."""
    r = _Reader(buf)
    if r.u64() != MAGIC:
        raise MXNetError("Invalid NDArray file format (bad magic)")
    r.u64()                                          # reserved
    arrays = []
    for _ in range(r.u64()):
        ndim = r.u32()
        if ndim == 0:                                # is_none() NDArray
            arrays.append(np.zeros((), np.float32))
            continue
        shape = struct.unpack("<%dI" % ndim, r.take(4 * ndim))
        r.i32(); r.i32()                             # Context (ignored: host load)
        flag = r.i32()
        if flag not in _FLAG2DTYPE:
            raise MXNetError("load: unknown type flag %d" % flag)
        dt = _FLAG2DTYPE[flag]
        n = int(np.prod(shape)) if shape else 1
        raw = r.take(n * dt.itemsize)
        if dt == np.dtype("bfloat16"):
            arr = np.frombuffer(raw, np.uint16).view(dt).reshape(shape)
        else:
            arr = np.frombuffer(raw, dt).reshape(shape)
        arrays.append(arr.copy())
    names = []
    nname = r.u64()
    if nname not in (0, len(arrays)):
        raise MXNetError("Invalid NDArray file format (name count)")
    for _ in range(nname):
        names.append(r.take(r.u64()).decode("utf-8"))
    return arrays, names


def sniff(buf):
    """True when buf starts with the reference list magic."""
    return len(buf) >= 8 and struct.unpack("<Q", buf[:8])[0] == MAGIC
