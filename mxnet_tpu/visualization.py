"""Network visualization (ref: python/mxnet/visualization.py, 328 LoC):
print_summary and plot_network (graphviz, optional)."""
from __future__ import annotations

import json

from .base import MXNetError
from .symbol import Symbol


def print_summary(symbol, shape=None, line_length=120, positions=(.44, .64, .74, 1.)):
    """Tabular per-layer summary with params/shape (ref: visualization.py)."""
    if not isinstance(symbol, Symbol):
        raise TypeError("symbol must be Symbol")
    show_shape = False
    shape_dict = {}
    if shape is not None:
        show_shape = True
        interals = symbol.get_internals()
        _, out_shapes, _ = interals.infer_shape_partial(**shape)
        shape_dict = dict(zip(interals.list_outputs(), out_shapes))
    conf = json.loads(symbol.tojson())
    nodes = conf["nodes"]
    if positions[-1] <= 1:
        positions = [int(line_length * p) for p in positions]
    to_display = ["Layer (type)", "Output Shape", "Param #", "Previous Layer"]

    def print_row(fields, positions):
        line = ""
        for i, field in enumerate(fields):
            line += str(field)
            line = line[:positions[i]]
            line += " " * (positions[i] - len(line))
        print(line)

    print("_" * line_length)
    print_row(to_display, positions)
    print("=" * line_length)
    total_params = [0]

    def print_layer_summary(node, out_shape):
        op = node["op"]
        pre_node = []
        pre_filter = 0
        if op != "null":
            inputs = node["inputs"]
            for item in inputs:
                input_node = nodes[item[0]]
                input_name = input_node["name"]
                if input_node["op"] != "null" or item[0] in heads:
                    pre_node.append(input_name)
        cur_param = 0
        attrs = node.get("attrs", {})
        if op == "null":
            cur_param = 0
        else:
            key = node["name"] + "_output"
            shape_key = shape_dict.get(key)
        if show_shape:
            key = node["name"] + ("_output" if op != "null" else "")
            out_shape = shape_dict.get(key, "")
        name = node["name"]
        print_row(["%s(%s)" % (name, op), str(out_shape) if out_shape else "",
                   cur_param, ",".join(pre_node)], positions)
        total_params[0] += cur_param

    heads = set(h[0] for h in conf["heads"])
    for node in nodes:
        print_layer_summary(node, "")
        print("_" * line_length)
    print("Total params: %s" % total_params[0])
    print("_" * line_length)


def plot_network(symbol, title="plot", save_format="pdf", shape=None,
                 node_attrs={}, hide_weights=True):
    """Graphviz rendering of the DAG (ref: visualization.py plot_network).
    Requires the optional graphviz package."""
    try:
        from graphviz import Digraph
    except ImportError:
        raise MXNetError("plot_network requires graphviz (optional dep)")
    if not isinstance(symbol, Symbol):
        raise TypeError("symbol must be a Symbol")
    conf = json.loads(symbol.tojson())
    nodes = conf["nodes"]
    node_attr = {"shape": "box", "fixedsize": "true", "width": "1.3",
                 "height": "0.8034", "style": "filled"}
    node_attr.update(node_attrs)
    dot = Digraph(name=title, format=save_format)
    hidden_nodes = set()
    for i, node in enumerate(nodes):
        op = node["op"]
        name = node["name"]
        if op == "null":
            if hide_weights and (name.endswith("_weight")
                                 or name.endswith("_bias")
                                 or name.endswith("_gamma")
                                 or name.endswith("_beta")):
                hidden_nodes.add(i)
                continue
            dot.node(name=name, label=name, fillcolor="#8dd3c7")
        else:
            dot.node(name=name, label="%s\n%s" % (op, name),
                     fillcolor="#fb8072" if "Output" in op or op == "MakeLoss"
                     else "#80b1d3")
    for i, node in enumerate(nodes):
        if node["op"] == "null":
            continue
        for item in node["inputs"]:
            if item[0] in hidden_nodes:
                continue
            dot.edge(tail_name=nodes[item[0]]["name"], head_name=node["name"])
    return dot
