"""Data iterators (ref: python/mxnet/io.py, 826 LoC; C++ iterator stack at
src/io/ — SURVEY.md section 2.5).

The DataDesc/DataBatch/DataIter contract matches the reference. NDArrayIter,
ResizeIter and the python-threaded PrefetchingIter are full ports of behavior
(ref: io.py:470, :220, :298). The RecordIO/image pipeline lives in
mxnet_tpu.recordio / mxnet_tpu.image (C++-backed path planned per SURVEY §7
stage 8).
"""
from __future__ import annotations

import hashlib
import threading
import time
from collections import namedtuple

import numpy as np

from .base import MXNetError
from .ndarray import NDArray, array


# ---------------------------------------------------------------------------
# fault tolerance for the data plane (docs/robustness.md): a bounded
# exponential-backoff retry for transient read failures, a skip-with-counter
# path for corrupt records, and a DataHealth stat surfacing both.
# ---------------------------------------------------------------------------

class CorruptRecordError(MXNetError):
    """A record that decoded/parsed as garbage (NOT transient: retrying the
    same bytes cannot help; iterators either skip it or raise)."""


class DataHealth(object):
    """Thread-safe counters for data-pipeline degradation.

    Every retry, skipped corrupt record and hard failure is recorded here
    (and mirrored into the process-global ``io.DATA_HEALTH`` aggregate), so
    a training run can report "healthy" vs "limping on retries" instead of
    silently eating IO errors.
    """

    def __init__(self, parent=None):
        self._lock = threading.Lock()
        self._parent = parent
        self.retries = 0
        self.skipped_records = 0
        self.failures = 0
        self.last_error = None

    def record_retry(self, site, exc):
        with self._lock:
            self.retries += 1
            self.last_error = "%s: %s" % (site, exc)
        if self._parent is not None:
            self._parent.record_retry(site, exc)

    def record_skip(self, site, exc):
        with self._lock:
            self.skipped_records += 1
            self.last_error = "%s: %s" % (site, exc)
        if self._parent is not None:
            self._parent.record_skip(site, exc)

    def record_failure(self, site, exc):
        with self._lock:
            self.failures += 1
            self.last_error = "%s: %s" % (site, exc)
        if self._parent is not None:
            self._parent.record_failure(site, exc)

    def report(self):
        with self._lock:
            return {"retries": self.retries,
                    "skipped_records": self.skipped_records,
                    "failures": self.failures,
                    "last_error": self.last_error}

    def reset(self):
        with self._lock:
            self.retries = 0
            self.skipped_records = 0
            self.failures = 0
            self.last_error = None

    def __repr__(self):
        return "DataHealth(%r)" % (self.report(),)


#: process-global aggregate every per-iterator DataHealth mirrors into
DATA_HEALTH = DataHealth()


class RetryPolicy(object):
    """Bounded exponential backoff with deterministic jitter.

    ``delay(attempt, site)``: ``base_delay * 2**(attempt-1)`` capped at
    ``max_delay``, plus up to ``jitter`` fraction derived from a hash of
    (worker rank, site, attempt) — repeatable run-to-run for a given rank
    layout, yet de-correlated across sites AND workers (N workers retrying
    the same site don't thundering-herd a recovering filesystem).
    """

    def __init__(self, max_retries=3, base_delay=0.01, max_delay=0.5,
                 jitter=0.5):
        self.max_retries = int(max_retries)
        self.base_delay = float(base_delay)
        self.max_delay = float(max_delay)
        self.jitter = float(jitter)
        import os
        self._worker_salt = os.environ.get("MXTPU_RANK", "0")

    def delay(self, attempt, site=""):
        d = min(self.base_delay * (2.0 ** max(0, attempt - 1)),
                self.max_delay)
        if self.jitter and d > 0:
            h = hashlib.sha256(("%s/%s#%d" % (self._worker_salt, site,
                                              attempt)).encode())
            frac = int.from_bytes(h.digest()[:4], "big") / float(1 << 32)
            d *= 1.0 + self.jitter * frac
        return d


#: OSError subclasses that retrying cannot fix — surface them immediately
#: with their real cause instead of burning the budget
_PERMANENT_OSERRORS = (FileNotFoundError, PermissionError, IsADirectoryError,
                       NotADirectoryError)


def _transient_types():
    from . import faults as _faults
    return (_faults.InjectedTransientFault, OSError)


def retry_call(fn, site, policy=None, health=None):
    """Call ``fn`` with the policy's bounded retry on transient errors
    (OSError and injected transient faults). Exhausting the budget raises
    :class:`MXNetError` naming the site and attempt count; non-transient
    errors — including permanent OSErrors like FileNotFoundError —
    propagate untouched."""
    policy = policy or RetryPolicy()
    health = health or DATA_HEALTH
    transient = _transient_types()
    attempt = 0
    while True:
        try:
            return fn()
        except _PERMANENT_OSERRORS:
            raise
        except transient as e:
            attempt += 1
            if attempt > policy.max_retries:
                health.record_failure(site, e)
                raise MXNetError(
                    "%s: giving up after %d attempts (retry budget %d "
                    "exhausted): %s" % (site, attempt, policy.max_retries,
                                        e)) from e
            health.record_retry(site, e)
            d = policy.delay(attempt, site)
            if d > 0:
                time.sleep(d)


class DataDesc(namedtuple("DataDesc", ["name", "shape"])):
    """Data description: name, shape, dtype, layout (ref: io.py DataDesc)."""

    def __new__(cls, name, shape, dtype=np.float32, layout="NCHW"):
        ret = super().__new__(cls, name, tuple(shape))
        ret.dtype = dtype
        ret.layout = layout
        return ret

    def __repr__(self):
        return "DataDesc[%s,%s,%s,%s]" % (self.name, self.shape, self.dtype,
                                          self.layout)

    @staticmethod
    def get_batch_axis(layout):
        if layout is None:
            return 0
        return layout.find("N")


class DataBatch(object):
    """A mini-batch (ref: io.py DataBatch)."""

    def __init__(self, data, label=None, pad=None, index=None,
                 bucket_key=None, provide_data=None, provide_label=None):
        self.data = data
        self.label = label
        self.pad = pad
        self.index = index
        self.bucket_key = bucket_key
        self.provide_data = provide_data
        self.provide_label = provide_label


class DataIter(object):
    """Base data iterator (ref: io.py DataIter)."""

    def __init__(self, batch_size=0):
        self.batch_size = batch_size

    def __iter__(self):
        return self

    def superbatch(self, k, prefetch=True, **kwargs):
        """Bulk this iterator for K-steps-per-dispatch training: returns a
        :class:`SuperBatchIter` that stacks K consecutive batches into one
        (k, batch, ...) superbatch, assembled and landed on device by a
        prefetch thread. Feeds ``TrainStep.run_steps`` /
        ``Module.fit(steps_per_dispatch=k)``."""
        return SuperBatchIter(self, k, prefetch=prefetch, **kwargs)

    def reset(self):
        pass

    def next(self):
        if self.iter_next():
            return DataBatch(data=self.getdata(), label=self.getlabel(),
                             pad=self.getpad(), index=self.getindex())
        raise StopIteration

    def __next__(self):
        return self.next()

    def iter_next(self):
        pass

    def getdata(self):
        pass

    def getlabel(self):
        pass

    def getindex(self):
        return None

    def getpad(self):
        pass


class ResizeIter(DataIter):
    """Resize an iterator to a fixed number of batches per epoch
    (ref: io.py ResizeIter)."""

    def __init__(self, data_iter, size, reset_internal=True):
        super().__init__()
        self.data_iter = data_iter
        self.size = size
        self.reset_internal = reset_internal
        self.cur = 0
        self.current_batch = None
        self.provide_data = data_iter.provide_data
        self.provide_label = data_iter.provide_label
        self.batch_size = data_iter.batch_size
        if hasattr(data_iter, "default_bucket_key"):
            self.default_bucket_key = data_iter.default_bucket_key

    def reset(self):
        self.cur = 0
        if self.reset_internal:
            self.data_iter.reset()

    def iter_next(self):
        if self.cur == self.size:
            return False
        try:
            self.current_batch = self.data_iter.next()
        except StopIteration:
            self.data_iter.reset()
            self.current_batch = self.data_iter.next()
        self.cur += 1
        return True

    def next(self):
        if self.iter_next():
            return self.current_batch
        raise StopIteration

    def getdata(self):
        return self.current_batch.data

    def getlabel(self):
        return self.current_batch.label

    def getindex(self):
        return self.current_batch.index

    def getpad(self):
        return self.current_batch.pad


class PrefetchingIter(DataIter):
    """Python-threaded producer/consumer prefetcher (ref: io.py:298
    PrefetchingIter; the C++ analogue is dmlc::ThreadedIter in
    src/io/iter_prefetcher.h:129)."""

    def __init__(self, iters, rename_data=None, rename_label=None):
        super().__init__()
        if not isinstance(iters, list):
            iters = [iters]
        self.n_iter = len(iters)
        assert self.n_iter > 0
        self.iters = iters
        self.rename_data = rename_data
        self.rename_label = rename_label
        self.batch_size = self.provide_data[0][1][0]
        self.data_ready = [threading.Event() for _ in range(self.n_iter)]
        self.data_taken = [threading.Event() for _ in range(self.n_iter)]
        for e in self.data_taken:
            e.set()
        self.started = True
        self.current_batch = [None for _ in range(self.n_iter)]
        self.next_batch = [None for _ in range(self.n_iter)]

        def prefetch_func(self, i):
            while True:
                self.data_taken[i].wait()
                if not self.started:
                    break
                try:
                    self.next_batch[i] = self.iters[i].next()
                except StopIteration:
                    self.next_batch[i] = None
                self.data_taken[i].clear()
                self.data_ready[i].set()

        self.prefetch_threads = [
            threading.Thread(target=prefetch_func, args=[self, i])
            for i in range(self.n_iter)]
        for thread in self.prefetch_threads:
            thread.daemon = True
            thread.start()

    def __del__(self):
        self.started = False
        for e in self.data_taken:
            e.set()
        for thread in self.prefetch_threads:
            thread.join(timeout=1.0)

    @property
    def provide_data(self):
        if self.rename_data is None:
            return sum([i.provide_data for i in self.iters], [])
        return sum([[DataDesc(r[x.name], x.shape, x.dtype)
                     if isinstance(x, DataDesc) else DataDesc(r[x[0]], x[1])
                     for x in i.provide_data]
                    for r, i in zip(self.rename_data, self.iters)], [])

    @property
    def provide_label(self):
        if self.rename_label is None:
            return sum([i.provide_label for i in self.iters], [])
        return sum([[DataDesc(r[x.name], x.shape, x.dtype)
                     if isinstance(x, DataDesc) else DataDesc(r[x[0]], x[1])
                     for x in i.provide_label]
                    for r, i in zip(self.rename_label, self.iters)], [])

    def reset(self):
        for e in self.data_ready:
            e.wait()
        for i in self.iters:
            i.reset()
        for e in self.data_ready:
            e.clear()
        for e in self.data_taken:
            e.set()

    def iter_next(self):
        for e in self.data_ready:
            e.wait()
        if self.next_batch[0] is None:
            for i in self.next_batch:
                assert i is None, "Number of entry mismatches between iterators"
            return False
        for batch in self.next_batch:
            assert batch.pad == self.next_batch[0].pad, \
                "Different pad number within internal iters"
        self.current_batch = DataBatch(
            sum([batch.data for batch in self.next_batch], []),
            sum([batch.label for batch in self.next_batch], []),
            self.next_batch[0].pad,
            self.next_batch[0].index,
            provide_data=self.provide_data,
            provide_label=self.provide_label)
        for e in self.data_ready:
            e.clear()
        for e in self.data_taken:
            e.set()
        return True

    def next(self):
        if self.iter_next():
            return self.current_batch
        raise StopIteration

    def getdata(self):
        return self.current_batch.data

    def getlabel(self):
        return self.current_batch.label

    def getindex(self):
        return self.current_batch.index

    def getpad(self):
        return self.current_batch.pad


class SuperDataBatch(DataBatch):
    """K stacked mini-batches: every array carries a leading (k,) step axis.

    ``num_steps`` may be smaller than the configured K for the epoch tail
    (or for a bucket-run cut — see :class:`SuperBatchIter`); consumers
    that compiled for a fixed K should route such a tail through
    :meth:`unstack` (per-step views) instead of compiling a second scan.

    ``bucket_key`` (variable-length/bucketed iterators) names the bucket
    every stacked step shares; ``step_provide_data``/``step_provide_label``
    are the UNstacked per-step descriptors, so an unstacked view can
    re-bind a bucketed executor (BucketingModule.switch_bucket needs
    them).
    """

    def __init__(self, data, label=None, pads=None, num_steps=1,
                 provide_data=None, provide_label=None, bucket_key=None,
                 step_provide_data=None, step_provide_label=None):
        pads = list(pads) if pads is not None else [0] * num_steps
        super().__init__(data, label=label, pad=pads[-1] if pads else 0,
                         bucket_key=bucket_key,
                         provide_data=provide_data,
                         provide_label=provide_label)
        self.num_steps = num_steps
        self.pads = pads
        self.step_provide_data = step_provide_data
        self.step_provide_label = step_provide_label

    def unstack(self):
        """Per-step DataBatch views (on-device slices along the step axis)."""
        out = []
        for i in range(self.num_steps):
            out.append(DataBatch(
                data=[a[i] for a in self.data],
                label=[a[i] for a in (self.label or [])],
                pad=self.pads[i] if i < len(self.pads) else 0,
                bucket_key=self.bucket_key,
                provide_data=self.step_provide_data,
                provide_label=self.step_provide_label))
        return out


class SuperBatchIter(DataIter):
    """Device-resident batch queue for multi-step dispatch.

    Pulls K consecutive batches from ``base``, stacks them host-side into one
    (k, batch, ...) superbatch and lands it on device as ONE H2D transfer —
    all on a producer thread, with ``queue_depth`` superbatches in flight so
    the transfer of superbatch n+1 overlaps the K-step scan of superbatch n
    (the ``iter_prefetcher.h`` role, one level up: the unit in flight is a
    whole dispatch, not a batch).

    When ``base`` exposes ``next_host()`` (host-numpy batches, e.g.
    ``image.ImageIter``) stacking happens before any device transfer; batches
    that are already device-resident are stacked with ``jnp.stack`` instead.
    The epoch tail (fewer than K batches left) is yielded as a partial
    superbatch with ``num_steps < k``, or dropped with
    ``last_group_handle='discard'``.

    ``sharding`` (a ``jax.sharding.Sharding``, normally
    ``parallel.mesh.superbatch_sharding(mesh)``) makes the producer land
    every stacked array PER-CHIP SHARDED: the single H2D device_put splits
    the batch axis across the mesh's 'data' axis, so each chip receives
    only its own shard and the K-step dispatch consumes the superbatch
    with zero resharding copies (docs/perf.md "Data-parallel scaling").
    ``Module.fit`` wires this automatically when its fused path runs over
    a mesh.
    """

    def __init__(self, base, k, prefetch=True, queue_depth=None,
                 last_group_handle="partial", retry_policy=None,
                 data_health=None, sharding=None):
        super().__init__(getattr(base, "batch_size", 0))
        if queue_depth is None:
            # keep the producer ahead of fit's dispatch pipeline
            # (docs/perf.md "Host off the critical path"): a depth-D
            # deferred readback holds D+1 dispatches' inputs in flight, so
            # fewer than D+1 queue slots would stall the consumer exactly
            # when the pipeline is hiding host latency
            from . import engine as _engine
            queue_depth = max(2, _engine.dispatch_pipeline() + 1)
        if k < 1:
            raise MXNetError("superbatch: k must be >= 1, got %r" % (k,))
        if last_group_handle not in ("partial", "discard"):
            raise MXNetError("superbatch: last_group_handle must be "
                             "'partial' or 'discard'")
        self.base = base
        self.k = int(k)
        self.sharding = sharding
        self.last_group_handle = last_group_handle
        self.retry_policy = retry_policy or RetryPolicy()
        self.data_health = (data_health if data_health is not None
                            else DataHealth(parent=DATA_HEALTH))
        self._prefetch = prefetch
        self._depth = max(1, int(queue_depth))
        self._queue = None
        self._thread = None
        self._stop = None
        self._done = False
        self._held = None  # first batch of the NEXT bucket run (bucketed)
        # superbatch sequence counter: the end-to-end correlation ID for
        # host-span tracing (docs/observability.md) — the producer stamps
        # each assembled superbatch with ``sb_seq``, fit's dispatch /
        # readback / checkpoint spans carry the same index, so one
        # dispatch reads as one Perfetto timeline across threads. Only
        # the assembly thread touches these (single producer).
        self._sb_seq = 0
        self._cur_sb = None
        if prefetch:
            self._start_producer()

    def _stacked_descs(self, descs):
        # legacy (name, shape) tuple descriptors are accepted everywhere
        # DataDesc is (executor_group, module) — here too
        out = []
        for d in descs:
            if hasattr(d, "name"):
                out.append(DataDesc(d.name, (self.k,) + tuple(d.shape),
                                    d.dtype))
            else:
                out.append(DataDesc(d[0], (self.k,) + tuple(d[1])))
        return out

    @property
    def provide_data(self):
        return self._stacked_descs(self.base.provide_data)

    @property
    def provide_label(self):
        return self._stacked_descs(self.base.provide_label)

    # -- assembly ------------------------------------------------------
    def _pull_one(self):
        """One batch from the base iterator, with transient read failures
        retried per the policy (fault site ``io.batch_read``)."""
        from . import faults as _faults
        next_host = getattr(self.base, "next_host", None)

        def pull():
            _faults.fire("io.batch_read")
            return next_host() if next_host is not None else self.base.next()

        return retry_call(pull, "io.batch_read", self.retry_policy,
                          self.data_health)

    def _pull_group(self):
        """Up to K consecutive batches — cut EARLY when the bucket key
        changes (variable-length/bucketed iterators): a stacked superbatch
        must be shape-homogeneous, so a bucket switch emits the run
        collected so far as a partial group and holds the first
        differing batch for the next group. Batch order is preserved, so
        bucketed K-step training stays step-for-step identical to k=1."""
        while True:
            group = [self._held] if self._held is not None else []
            self._held = None
            while len(group) < self.k:
                try:
                    b = self._pull_one()
                except StopIteration:
                    break
                if group and (getattr(b, "bucket_key", None)
                              != getattr(group[0], "bucket_key", None)):
                    self._held = b
                    break
                group.append(b)
            if not group:
                return None
            if len(group) < self.k and self.last_group_handle == "discard":
                if self._held is not None:
                    # a bucket cut, NOT the epoch tail: drop this short
                    # run per the discard contract but KEEP iterating —
                    # returning None here would silently end the epoch
                    # with the held batch (and everything after it)
                    # untrained
                    continue
                return None
            return group

    def _note_stage(self, stage, seconds, n=1):
        """Per-stage timing hook (stack / h2d), a no-op here; the input
        tier's :class:`~mxnet_tpu.data.prefetch.DevicePrefetcher` overrides
        it to charge :class:`~mxnet_tpu.data.stats.PipelineStats`."""

    def _stack(self, parts):
        """One stacked array per slot; host parts take a single np.stack +
        device put (ONE H2D for the whole superbatch slot), device parts
        stack on device. Under ``sharding`` the device_put itself splits
        the batch axis, so the land IS the per-chip scatter — no follow-up
        resharding. The device transfer (fault site ``io.h2d``) is retried
        like any transient IO: a flaky transfer costs a retry, not the
        run."""
        from . import faults as _faults
        from .obs import trace as _obs
        raw = [p.data if isinstance(p, NDArray) else p for p in parts]
        if all(isinstance(r, np.ndarray) for r in raw):
            t0 = time.perf_counter()
            stacked = np.stack(raw)
            dt = time.perf_counter() - t0
            self._note_stage("stack", dt)
            _obs.complete("stack", dt, dispatch=self._cur_sb)

            def land():
                _faults.fire("io.h2d")
                if self.sharding is not None:
                    import jax
                    # mirror array()'s dtype policy: a default-dtype f64
                    # host batch must land f32 on the sharded path too, or
                    # the mesh run retraces (and numerically diverges from)
                    # the single-device program under jax_enable_x64
                    src = (stacked.astype(np.float32)
                           if stacked.dtype == np.float64 else stacked)
                    return NDArray(jax.device_put(src, self.sharding))
                return array(stacked)

            t0 = time.perf_counter()
            try:
                return retry_call(land, "io.h2d", self.retry_policy,
                                  self.data_health)
            finally:
                dt = time.perf_counter() - t0
                self._note_stage("h2d", dt, n=len(parts))
                _obs.complete("h2d", dt, dispatch=self._cur_sb)
        import jax.numpy as jnp
        t0 = time.perf_counter()
        out = jnp.stack([jnp.asarray(r) for r in raw])
        if self.sharding is not None:
            import jax
            out = jax.device_put(out, self.sharding)
        dt = time.perf_counter() - t0
        self._note_stage("h2d", dt, n=len(parts))
        _obs.complete("h2d", dt, dispatch=self._cur_sb)
        return NDArray(out)

    def _assemble(self, group):
        from .obs import trace as _obs
        self._cur_sb = self._sb_seq
        self._sb_seq += 1
        n_data = len(group[0].data)
        n_label = len(group[0].label or [])
        with _obs.span("superbatch_assemble", dispatch=self._cur_sb,
                       k=len(group)):
            data = [self._stack([b.data[i] for b in group])
                    for i in range(n_data)]
            label = [self._stack([b.label[i] for b in group])
                     for i in range(n_label)]
        # bucketed batches carry their own per-bucket descriptors: the
        # stacked descs must come from the GROUP's shapes, not the base
        # iterator's default-bucket ones
        step_pd = group[0].provide_data
        step_pl = group[0].provide_label
        provide_data = (self._stacked_descs(step_pd)
                        if step_pd is not None else self.provide_data)
        provide_label = (self._stacked_descs(step_pl)
                         if step_pl is not None else self.provide_label)
        sb = SuperDataBatch(
            data=data, label=label, pads=[b.pad or 0 for b in group],
            num_steps=len(group), provide_data=provide_data,
            provide_label=provide_label,
            bucket_key=getattr(group[0], "bucket_key", None),
            step_provide_data=step_pd, step_provide_label=step_pl)
        # stamp the correlation ID so fit's dispatch/readback/checkpoint
        # spans share this superbatch's index (docs/observability.md)
        sb.sb_seq = self._cur_sb
        return sb

    # -- producer thread -----------------------------------------------
    def _start_producer(self):
        import queue as _queue
        import weakref
        self._queue = _queue.Queue(maxsize=self._depth)
        self._stop = threading.Event()
        self._done = False
        # the thread must NOT hold a strong ref to self: an abandoned
        # iterator (consumer breaks out of the epoch early and drops it)
        # could then never be garbage-collected and the producer would spin
        # forever pinning queue_depth superbatches of device memory
        wr = weakref.ref(self)

        def produce(stop, q):
            from . import faults as _faults
            while not stop.is_set():
                if _faults.fire("superbatch.producer") == "die":
                    return  # simulated abrupt thread death (no sentinel)
                it = wr()
                if it is None:
                    return
                group = None
                try:
                    group = it._pull_group()
                    item = it._assemble(group) if group else None
                except Exception as exc:  # surface in the consumer, don't
                    item = exc            # leave it blocked on an empty queue
                it = group = None  # drop the strong ref before blocking below
                while not stop.is_set() and wr() is not None:
                    try:
                        q.put(item, timeout=0.1)
                        break
                    except _queue.Full:
                        continue
                if item is None or isinstance(item, Exception):
                    return

        self._thread = threading.Thread(target=produce,
                                        args=(self._stop, self._queue))
        self._thread.daemon = True
        self._thread.start()

    def _shutdown_producer(self):
        if self._thread is None:
            return
        self._stop.set()
        while self._thread.is_alive():
            try:  # unblock a producer stuck on a full queue
                self._queue.get_nowait()
            except Exception:
                pass
            self._thread.join(timeout=0.05)
        self._thread = None

    def __del__(self):
        try:
            self._shutdown_producer()
        except Exception:
            pass

    def _queue_get_checked(self):
        """Blocking queue get that detects a dead producer: a thread that
        died without delivering its sentinel (crash, injected death) would
        otherwise block the training loop forever. Raises MXNetError with
        the site name instead."""
        import queue as _queue
        while True:
            try:
                return self._queue.get(timeout=0.1)
            except _queue.Empty:
                t = self._thread
                if t is None or not t.is_alive():
                    self._done = True
                    raise MXNetError(
                        "superbatch.producer: prefetch thread died without "
                        "delivering a batch (DataHealth=%r)"
                        % (self.data_health.report(),))

    # -- DataIter interface --------------------------------------------
    def reset(self):
        if self._prefetch:
            self._shutdown_producer()
        self.base.reset()
        self._done = False
        self._held = None
        if self._prefetch:
            self._start_producer()

    def close(self):
        """Stop the producer thread and release the in-flight superbatches
        WITHOUT resetting the base iterator. Call when done consuming (e.g.
        fit() after its final epoch) — otherwise the producer keeps the base
        iterator advanced by up to queue_depth prefetched superbatches and
        their device buffers alive."""
        if self._prefetch:
            self._shutdown_producer()
        self._queue = None
        self._done = True
        self._held = None

    def next(self):
        if self._done:
            raise StopIteration
        if self._prefetch:
            item = self._queue_get_checked()
        else:
            group = self._pull_group()
            item = self._assemble(group) if group else None
        if item is None:
            self._done = True
            raise StopIteration
        if isinstance(item, Exception):
            self._done = True
            raise item
        return item


def _init_data(data, allow_empty, default_name):
    """Normalize data into list of (name, array) (ref: io.py _init_data).

    NDArray input stays device-backed (jax.Array) so per-batch slicing is an
    on-device gather — the reference's NDArrayIter likewise keeps mx.nd data
    wherever the user placed it. numpy input stays host-side.
    """
    assert data is not None or allow_empty
    if data is None:
        data = []
    if isinstance(data, (np.ndarray, NDArray)):
        data = [data]
    if isinstance(data, list):
        if not allow_empty:
            assert len(data) > 0
        if len(data) == 1:
            data = {default_name: data[0]}
        else:
            data = {"_%d_%s" % (i, default_name): d for i, d in enumerate(data)}
    if not isinstance(data, dict):
        raise TypeError("Input must be NDArray, numpy.ndarray, a list of them "
                        "or dict with them as values")
    out = {}
    for k, v in data.items():
        if isinstance(v, NDArray):
            out[k] = v.data  # device-resident jax.Array
        else:
            out[k] = np.asarray(v)
    return list(sorted(out.items()))


class NDArrayIter(DataIter):
    """Iterate over in-memory arrays (ref: io.py:470 NDArrayIter), with
    shuffle and last_batch_handle pad/discard/roll_over semantics."""

    def __init__(self, data, label=None, batch_size=1, shuffle=False,
                 last_batch_handle="pad", data_name="data",
                 label_name="softmax_label"):
        super().__init__(batch_size)
        self.data = _init_data(data, allow_empty=False, default_name=data_name)
        self.label = _init_data(label, allow_empty=True, default_name=label_name)
        self.num_data = self.data[0][1].shape[0]
        if shuffle:
            from . import random as _random
            idx = _random.np_rng().permutation(self.num_data)
            self.data = [(k, v[idx]) for k, v in self.data]
            self.label = [(k, v[idx]) for k, v in self.label]
        if last_batch_handle == "discard":
            new_n = self.num_data - self.num_data % batch_size
            self.data = [(k, v[:new_n]) for k, v in self.data]
            self.label = [(k, v[:new_n]) for k, v in self.label]
            self.num_data = new_n
        self.data_list = [x[1] for x in self.data] + [x[1] for x in self.label]
        self.num_source = len(self.data_list)
        assert self.num_data >= batch_size, \
            "batch_size needs to be smaller than data size."
        self.cursor = -batch_size
        self.last_batch_handle = last_batch_handle

    @property
    def provide_data(self):
        return [DataDesc(k, tuple([self.batch_size] + list(v.shape[1:])),
                         v.dtype) for k, v in self.data]

    @property
    def provide_label(self):
        return [DataDesc(k, tuple([self.batch_size] + list(v.shape[1:])),
                         v.dtype) for k, v in self.label]

    def hard_reset(self):
        self.cursor = -self.batch_size

    def reset(self):
        if (self.last_batch_handle == "roll_over"
                and self.cursor > self.num_data):
            self.cursor = -self.batch_size + (self.cursor % self.num_data) \
                % self.batch_size
        else:
            self.cursor = -self.batch_size

    def iter_next(self):
        self.cursor += self.batch_size
        return self.cursor < self.num_data

    def next(self):
        if self.iter_next():
            return DataBatch(data=self.getdata(), label=self.getlabel(),
                             pad=self.getpad(), index=None)
        raise StopIteration

    def _getdata(self, data_source):
        assert self.cursor < self.num_data, "DataIter needs reset."
        if self.cursor + self.batch_size <= self.num_data:
            return [array(x[1][self.cursor:self.cursor + self.batch_size])
                    for x in data_source]
        # padding with wrap-around (ref: io.py NDArrayIter _getdata);
        # device-backed sources concatenate on-device
        pad = self.batch_size - self.num_data + self.cursor

        def cat(v):
            if isinstance(v, np.ndarray):
                return np.concatenate((v[self.cursor:], v[:pad]), axis=0)
            import jax.numpy as jnp
            return jnp.concatenate((v[self.cursor:], v[:pad]), axis=0)

        return [array(cat(x[1])) for x in data_source]

    def getdata(self):
        return self._getdata(self.data)

    def getlabel(self):
        return self._getdata(self.label)

    def getpad(self):
        if (self.last_batch_handle == "pad"
                and self.cursor + self.batch_size > self.num_data):
            return self.cursor + self.batch_size - self.num_data
        return 0


class CSVIter(DataIter):
    """CSV file iterator (ref: src/io/iter_csv.cc:213; host-side numpy here)."""

    def __init__(self, data_csv, data_shape, label_csv=None, label_shape=(1,),
                 batch_size=1, round_batch=True, **kwargs):
        data = np.loadtxt(data_csv, delimiter=",", dtype=np.float32)
        data = data.reshape((-1,) + tuple(data_shape))
        label = None
        if label_csv is not None:
            label = np.loadtxt(label_csv, delimiter=",", dtype=np.float32)
            label = label.reshape((-1,) + tuple(label_shape))
            if label_shape == (1,):
                label = label.reshape(-1)
        self._inner = NDArrayIter(
            data, label, batch_size=batch_size,
            last_batch_handle="pad" if round_batch else "discard",
            label_name="label")
        super().__init__(batch_size)

    @property
    def provide_data(self):
        return self._inner.provide_data

    @property
    def provide_label(self):
        return self._inner.provide_label

    def reset(self):
        self._inner.reset()

    def next(self):
        return self._inner.next()


class MNISTIter(DataIter):
    """MNIST idx-format file iterator (ref: src/io/iter_mnist.cc:254)."""

    def __init__(self, image, label, batch_size=128, shuffle=True, flat=False,
                 seed=0, silent=False, num_parts=1, part_index=0, **kwargs):
        import gzip
        import struct

        def read_idx(path):
            opener = gzip.open if path.endswith(".gz") else open
            with opener(path, "rb") as f:
                magic = struct.unpack(">HBB", f.read(4))
                ndim = magic[2]
                dims = struct.unpack(">" + "I" * ndim, f.read(4 * ndim))
                return np.frombuffer(f.read(), dtype=np.uint8).reshape(dims)

        img = read_idx(image).astype(np.float32) / 255.0
        lab = read_idx(label).astype(np.float32)
        # distributed sharding (ref: part_index/num_parts in image_iter_common.h)
        if num_parts > 1:
            n = img.shape[0] // num_parts
            img = img[part_index * n:(part_index + 1) * n]
            lab = lab[part_index * n:(part_index + 1) * n]
        if flat:
            img = img.reshape(img.shape[0], -1)
        else:
            img = img.reshape(img.shape[0], 1, img.shape[1], img.shape[2])
        self._inner = NDArrayIter(img, lab, batch_size=batch_size,
                                  shuffle=shuffle)
        super().__init__(batch_size)

    @property
    def provide_data(self):
        return self._inner.provide_data

    @property
    def provide_label(self):
        return self._inner.provide_label

    def reset(self):
        self._inner.reset()

    def next(self):
        return self._inner.next()

    def iter_next(self):
        return self._inner.iter_next()
