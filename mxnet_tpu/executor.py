"""Executor: binds a Symbol to devices and runs forward/backward.

Re-design of the reference GraphExecutor (ref: src/executor/graph_executor.cc,
include/mxnet/executor.h, python/mxnet/executor.py). The reference compiles
the graph itself — Gradient pass, PlaceDevice, InferShape/Type, PlanMemory,
op bulking (graph_executor.cc:336-759). Here the DAG lowers to one pure JAX
function and XLA performs all of those roles: ``forward`` is a jitted call,
``backward`` differentiates the same function with ``jax.vjp`` (no per-op
backward graph), memory planning/fusion/bulking are XLA's, and gradient
accumulation honors grad_req write/add/null semantics
(ref: OpReqType kWriteTo/kAddTo/kNullOp, include/mxnet/op_attr_types.h).

Laziness: ``forward()`` snapshots inputs and defers compute; reading
``.outputs`` forces a forward-only jit, while calling ``backward()`` first
runs a single fused forward+backward jit — so a fit() step costs exactly one
XLA invocation, mirroring the reference's engine overlap for free.
"""
from __future__ import annotations

import os

import jax
import jax.numpy as jnp
import numpy as np

from .base import MXNetError
from .context import Context, current_context
from .ndarray import NDArray
from .ops.registry import OpContext
from .symbol import Symbol, _topo
from . import random as _random


def _build_graph_runner(symbol, placement=None, node_constraint=None):
    """Lower the symbol DAG to a pure function
    run(arg_vals: dict, aux_vals: dict, key, is_train) -> (outputs, aux_updates).

    ``placement`` (parallel.placement.GroupPlacement) lowers ctx_group
    annotations to per-node sharding constraints — the SPMD analog of the
    reference's PlaceDevice pass + _CrossDeviceCopy insertion
    (ref: src/executor/graph_executor.cc:244-334).

    ``node_constraint`` (callable ``(node, outs) -> outs``, trace-time) is
    a caller-supplied sharding hook applied to every non-variable node's
    outputs — the serving tier uses it to keep activations replicated at
    the graph edges of a model-axis-sharded engine (docs/serving.md
    "Model-parallel replicas") without annotating the symbol."""
    nodes = _topo(symbol._out_nodes())
    node_groups = {}
    if placement is not None:
        from .parallel.placement import node_group, param_groups
        node_groups = {id(n): node_group(n) for n in nodes}
        var_groups = param_groups(nodes)

    # Conv(1x1 NHWC)+BN fusion pass (the Pallas conv+stats epilogue — see
    # ops/pallas_fused.py). The TPU analog of the reference's cuDNN fused
    # epilogues; peephole over the DAG like nnvm's DetectInplaceAddTo
    # (ref: src/executor/inplace_addto_detect_pass.cc pattern).
    # OPT-IN: measured 2x slower than letting XLA fuse on v5e
    # (docs/perf.md r4) — "1" enables on TPU, "interpret" for CPU tests.
    fuse_mode = os.environ.get("MXTPU_FUSE_CONV_BN", "0")
    fused_convs = {}        # id(conv node) -> conv node
    bn_stats_src = {}       # id(bn node) -> id(conv node)
    if fuse_mode != "0" and placement is None:
        from .ops import pallas_fused as _pf
        for node in nodes:
            if node.is_variable or node.op.name != "BatchNorm":
                continue
            if not node.inputs or not _pf.bn_fusable(node.attrs):
                continue
            src, src_idx = node.inputs[0]
            if (src_idx == 0 and not src.is_variable
                    and src.op.name == "Convolution"
                    and _pf.conv1x1_fusable(src.attrs)):
                fused_convs[id(src)] = src
                bn_stats_src[id(node)] = id(src)

    def run(arg_vals, aux_vals, key, is_train):
        if fused_convs and is_train:
            from .ops import pallas_fused as _pf
            interp = (fuse_mode == "interpret"
                      or jax.default_backend() != "tpu")
            use_fusion = fuse_mode == "interpret" or not interp
        else:
            use_fusion = False
        env = {}
        stats_env = {}
        aux_updates = {}
        for k, node in enumerate(nodes):
            if node.is_variable:
                v = arg_vals[node.name]
                if placement is not None:
                    g = var_groups.get(node.name)
                    if g is not None:
                        # is_param: confirm the allocation-time layout
                        # (first-dim rule) rather than forcing an
                        # activation-style reshard of every weight per step
                        v = placement.constrain(g, v, is_param=True)
                env[(id(node), 0)] = v
                continue
            ins = [env[(id(n), i)] for n, i in node.inputs]
            aux_names = node.op.list_aux(node.attrs)
            aux_in = [aux_vals["%s_%s" % (node.name, a)] for a in aux_names]
            rng = None
            if node.op.needs_rng and key is not None:
                rng = jax.random.fold_in(key, k)
            fused_stats = (stats_env.get(bn_stats_src.get(id(node)))
                           if use_fusion else None)
            op_ctx = OpContext(is_train=is_train, rng=rng,
                               fused_stats=fused_stats)
            # named_scope threads op names into XLA metadata so profiler
            # traces show MXNet op names, not anonymous fusions (ref:
            # PROFILER_MESSAGE threading names through every engine push,
            # include/mxnet/base.h:79-83)
            if use_fusion and id(node) in fused_convs:
                with jax.named_scope("ConvBNStats:%s" % node.name):
                    y, stats = _pf.apply_conv1x1_stats(ins[0], ins[1],
                                                       interpret=interp)
                stats_env[id(node)] = stats
                outs, aux_up = (y,), None
            else:
                with jax.named_scope("%s:%s" % (node.op.name, node.name)):
                    outs, aux_up = node.op.apply(op_ctx, node.attrs, ins,
                                                 aux_in)
            if node_constraint is not None:
                outs = node_constraint(node, outs)
            g = node_groups.get(id(node))
            if g is not None:
                outs = [placement.constrain(g, o) for o in outs]
            for i, o in enumerate(outs):
                env[(id(node), i)] = o
            if aux_up is not None:
                for a, u in zip(aux_names, aux_up):
                    aux_updates["%s_%s" % (node.name, a)] = u
        outputs = [env[(id(n), i)] for n, i in symbol._outputs]
        return outputs, aux_updates

    return run, nodes


class _LazyOutputs(object):
    """Sequence proxy returned by forward(is_train=True): preserves the
    reference contract (forward returns outputs) without forcing computation
    unless the caller actually reads it — so fit()'s forward+backward still
    fuses into one XLA call."""

    __slots__ = ("_exec",)

    def __init__(self, executor):
        self._exec = executor

    def _force(self):
        return self._exec.outputs

    def __getitem__(self, i):
        return self._force()[i]

    def __len__(self):
        return len(self._exec.output_names)

    def __iter__(self):
        return iter(self._force())

    def __repr__(self):
        return "<LazyOutputs of %d outputs>" % len(self)


class Executor(object):
    """Executor over a bound symbol (ref: python/mxnet/executor.py)."""

    def __init__(self, symbol, ctx, args, args_grad=None, grad_req="write",
                 aux_states=None, group2ctx=None, shared_exec=None):
        self._symbol = symbol
        self._ctx = ctx if isinstance(ctx, Context) else Context(ctx)
        # ctx_group model parallelism: lower group annotations to mesh
        # sharding constraints (see parallel/placement.py); simple_bind
        # passes an already-resolved GroupPlacement
        from .parallel import placement as _placement
        if isinstance(group2ctx, _placement.GroupPlacement):
            self._placement = group2ctx
            self._group2ctx = dict(group2ctx.raw)
        else:
            self._group2ctx = group2ctx or {}
            self._placement = _placement.resolve(self._group2ctx)
        self.arg_names = symbol.list_arguments()
        self.aux_names = symbol.list_auxiliary_states()
        self.output_names = symbol.list_outputs()

        self.arg_dict = self._normalize(args, self.arg_names, "args")
        self.arg_arrays = [self.arg_dict[n] for n in self.arg_names]
        if args_grad is None:
            self.grad_dict = {}
        else:
            self.grad_dict = self._normalize(args_grad, self.arg_names,
                                             "args_grad", allow_missing=True)
        self.grad_arrays = [self.grad_dict.get(n) for n in self.arg_names]
        self.aux_dict = self._normalize(aux_states, self.aux_names, "aux",
                                        allow_missing=False) if self.aux_names else {}
        self.aux_arrays = [self.aux_dict[n] for n in self.aux_names]

        if isinstance(grad_req, str):
            self._grad_req = {n: (grad_req if n in self.grad_dict else "null")
                              for n in self.arg_names}
        elif isinstance(grad_req, (list, tuple)):
            self._grad_req = dict(zip(self.arg_names, grad_req))
        else:
            self._grad_req = {n: grad_req.get(n, "null") for n in self.arg_names}
        for n in self.arg_names:
            if self._grad_req.get(n, "null") != "null" and n not in self.grad_dict:
                if not jnp.issubdtype(self.arg_dict[n].data.dtype,
                                      jnp.floating):
                    # integer inputs have no gradient (reference kNullOp)
                    self._grad_req[n] = "null"
                    continue
                raise MXNetError("grad_req %r for %s but no grad array bound"
                                 % (self._grad_req[n], n))

        self._run, self._nodes = _build_graph_runner(symbol, self._placement)
        self._diff_args = [n for n in self.arg_names
                           if self._grad_req.get(n, "null") != "null"]
        # group diff args by grad-buffer identity: a buffer shared across
        # several arguments (weight tying) receives the SUM of their
        # gradients, written once (ref: DeduplicateVarHandle + kAddTo
        # semantics, include/mxnet/engine.h:231-249)
        self._grad_groups = []   # list of (buffer, [arg names])
        _by_buf = {}
        for n in self._diff_args:
            buf = self.grad_dict[n]
            if id(buf) in _by_buf:
                self._grad_groups[_by_buf[id(buf)]][1].append(n)
            else:
                _by_buf[id(buf)] = len(self._grad_groups)
                self._grad_groups.append((buf, [n]))
        self._has_add = any(self._grad_req.get(n) == "add"
                            for n in self._diff_args)
        self._needs_rng = any((not n.is_variable) and n.op.needs_rng
                              for n in self._nodes)
        self._base_key = _random.split()
        self._step = 0
        self._monitor_callback = None

        # pending forward snapshot
        self._pending = None       # (arg_vals, aux_vals, key, is_train)
        self._outputs_nd = None
        self._jit_fwd = {}
        self._jit_fused = {}

    # ------------------------------------------------------------------
    def _normalize(self, arrays, names, what, allow_missing=False):
        if arrays is None:
            arrays = {}
        if isinstance(arrays, (list, tuple)):
            if len(arrays) != len(names):
                raise MXNetError("%s: expected %d arrays, got %d"
                                 % (what, len(names), len(arrays)))
            return {n: a for n, a in zip(names, arrays) if a is not None}
        out = {}
        for n in names:
            if n in arrays:
                out[n] = arrays[n]
            elif not allow_missing and what in ("args", "aux"):
                raise MXNetError("%s: missing array for %r" % (what, n))
        return out

    # ------------------------------------------------------------------
    @property
    def outputs(self):
        self._ensure_forward()
        return self._outputs_nd

    def forward(self, is_train=False, **kwargs):
        # deferred MXNET_PROFILER_AUTOSTART (docs/observability.md): the
        # device trace starts at the FIRST dispatch, after any
        # profiler_set_config — one boolean check once resolved
        from . import profiler as _profiler
        _profiler.maybe_autostart()
        for k, v in kwargs.items():
            if k not in self.arg_dict:
                raise MXNetError("forward: unknown argument %r" % k)
            if isinstance(v, NDArray):
                self.arg_dict[k]._set_data(v.data)
            else:
                self.arg_dict[k]._set_data(jnp.asarray(np.asarray(v)))
        key = None
        if self._needs_rng:
            key = jax.random.fold_in(self._base_key, self._step)
            self._step += 1
        arg_vals = {n: self.arg_dict[n].data for n in self.arg_names}
        aux_vals = {n: self.aux_dict[n].data for n in self.aux_names}
        self._pending = (arg_vals, aux_vals, key, bool(is_train))
        self._outputs_nd = None
        if self._monitor_callback is not None:
            self._ensure_forward()
            return self._outputs_nd
        if not is_train:
            # eval path: force now (async dispatch, does not block)
            self._ensure_forward()
            return self._outputs_nd
        # training path stays lazy so backward() fuses fwd+bwd into one jit;
        # the proxy forces computation only if the caller actually reads it
        return _LazyOutputs(self)

    def _ensure_forward(self):
        if self._outputs_nd is not None:
            return
        if self._pending is None:
            raise MXNetError("call forward() first")
        arg_vals, aux_vals, key, is_train = self._pending
        if self._monitor_callback is not None:
            self._forward_monitored(arg_vals, aux_vals, key, is_train)
            return
        if is_train not in self._jit_fwd:
            run = self._run

            def fwd(arg_vals, aux_vals, key):
                return run(arg_vals, aux_vals, key, is_train)

            self._jit_fwd[is_train] = jax.jit(fwd)
        outs, aux_up = self._jit_fwd[is_train](arg_vals, aux_vals, key)
        self._finish(outs, aux_up, is_train)

    def _finish(self, outs, aux_up, is_train):
        self._outputs_nd = [NDArray(o) for o in outs]
        if is_train:
            for n, u in aux_up.items():
                self.aux_dict[n]._set_data(u)

    def _forward_monitored(self, arg_vals, aux_vals, key, is_train):
        """Un-jitted per-node execution invoking the monitor callback on every
        op output (ref: GraphExecutor::SetMonitorCallback,
        graph_executor.cc:63-70,:761-781)."""
        env = {}
        aux_updates = {}
        for k, node in enumerate(self._nodes):
            if node.is_variable:
                env[(id(node), 0)] = arg_vals[node.name]
                continue
            ins = [env[(id(n), i)] for n, i in node.inputs]
            aux_names = node.op.list_aux(node.attrs)
            aux_in = [aux_vals["%s_%s" % (node.name, a)] for a in aux_names]
            rng = (jax.random.fold_in(key, k)
                   if node.op.needs_rng and key is not None else None)
            outs, aux_up = node.op.apply(OpContext(is_train, rng),
                                         node.attrs, ins, aux_in)
            for i, (oname, o) in enumerate(zip(node.output_names(), outs)):
                env[(id(node), i)] = o
                self._monitor_callback(oname, NDArray(o))
            if aux_up is not None:
                for a, u in zip(aux_names, aux_up):
                    aux_updates["%s_%s" % (node.name, a)] = u
        outs = [env[(id(n), i)] for n, i in self._symbol._outputs]
        self._finish(outs, aux_updates, is_train)

    # ------------------------------------------------------------------
    def backward(self, out_grads=None):
        """Run backward; fills bound gradient arrays honoring grad_req.

        If outputs were not yet forced, runs ONE fused forward+backward jit.
        """
        if self._pending is None:
            raise MXNetError("call forward(is_train=True) before backward()")
        arg_vals, aux_vals, key, is_train = self._pending
        if not is_train:
            raise MXNetError("backward called on forward(is_train=False)")
        if not self._diff_args:
            self._ensure_forward()
            return
        if out_grads is not None and not isinstance(out_grads, (list, tuple)):
            out_grads = [out_grads]
        use_default_head = out_grads is None
        jkey = (use_default_head,)
        if jkey not in self._jit_fused:
            self._jit_fused[jkey] = self._make_fused(use_default_head)
        head_vals = ([] if use_default_head
                     else [g.data if isinstance(g, NDArray) else jnp.asarray(g)
                           for g in out_grads])
        prev_grads = ([buf.data for buf, _names in self._grad_groups]
                      if self._has_add else [])
        outs, grads, aux_up = self._jit_fused[jkey](
            arg_vals, aux_vals, key, head_vals, prev_grads)
        self._finish(outs, aux_up, is_train=True)
        for (buf, _names), g in zip(self._grad_groups, grads):
            buf._set_data(g)

    def _make_fused(self, use_default_head):
        run = self._run
        diff_args = list(self._diff_args)
        grad_req = dict(self._grad_req)
        groups = [tuple(names) for _buf, names in self._grad_groups]

        def fused(arg_vals, aux_vals, key, head_vals, prev_grads):
            def f(diff_vals):
                full = dict(arg_vals)
                for n, v in zip(diff_args, diff_vals):
                    full[n] = v
                outs, aux_up = run(full, aux_vals, key, True)
                return outs, aux_up

            primal_in = [arg_vals[n] for n in diff_args]
            (outs, aux_up), vjp_fn = jax.vjp(f, primal_in, has_aux=False)
            # vjp over the (outs, aux_up) pair: zero-cotangent the aux part
            cots_aux = jax.tree_util.tree_map(jnp.zeros_like, aux_up)
            if use_default_head:
                cots = [jnp.ones_like(o) for o in outs]
            else:
                cots = list(head_vals)
            (dgrads,) = vjp_fn((cots, cots_aux))
            by_name = dict(zip(diff_args, dgrads))
            final = []
            for gi, names in enumerate(groups):
                g = by_name[names[0]]
                for n in names[1:]:
                    g = g + by_name[n]
                if grad_req[names[0]] == "add":
                    g = prev_grads[gi] + g
                final.append(g)
            return outs, final, aux_up

        donate = (4,) if self._has_add else ()
        return jax.jit(fused, donate_argnums=donate)

    # ------------------------------------------------------------------
    def set_monitor_callback(self, callback):
        self._monitor_callback = callback

    def copy_params_from(self, arg_params, aux_params=None,
                         allow_extra_params=False):
        for name, array in arg_params.items():
            if name in self.arg_dict:
                self.arg_dict[name]._set_data(
                    array.data if isinstance(array, NDArray)
                    else jnp.asarray(np.asarray(array)))
            elif not allow_extra_params:
                raise MXNetError("copy_params_from: %r not an argument" % name)
        if aux_params:
            for name, array in aux_params.items():
                if name in self.aux_dict:
                    self.aux_dict[name]._set_data(
                        array.data if isinstance(array, NDArray)
                        else jnp.asarray(np.asarray(array)))
                elif not allow_extra_params:
                    raise MXNetError("copy_params_from: %r not aux" % name)

    def reshape(self, partial_shaping=False, allow_up_sizing=False, **kwargs):
        """Return a new executor bound to new input shapes, sharing parameter
        arrays whose shapes are unchanged (ref: executor.py reshape — the
        bucketing re-bind path; jit caching makes this cheap).

        Flag semantics match the reference: without ``partial_shaping`` only
        the explicitly passed inputs may change shape — a derived (weight/
        aux) shape change raises; without ``allow_up_sizing`` a resized
        array may not grow beyond its current element count."""
        new_shapes = {}
        for n in self.arg_names:
            if n in kwargs:
                new_shapes[n] = tuple(kwargs[n])

        def _resize(name, cur, sh, explicit):
            if not (explicit or partial_shaping):
                raise MXNetError(
                    "reshape: %r changes shape %s -> %s; pass "
                    "partial_shaping=True to allow reshaping arguments "
                    "beyond the given inputs" % (name, tuple(cur.shape),
                                                 tuple(sh)))
            new_size = int(np.prod(sh)) if sh else 1
            cur_size = cur.size
            if new_size > cur_size and not allow_up_sizing:
                raise MXNetError(
                    "reshape: %r grows %d -> %d elements; pass "
                    "allow_up_sizing=True to allocate larger arrays"
                    % (name, cur_size, new_size))
            return NDArray(jnp.zeros(sh, cur.data.dtype))

        arg_shapes, _, aux_shapes = self._symbol.infer_shape_partial(**new_shapes)
        args = {}
        grads = {}
        for n, sh in zip(self.arg_names, arg_shapes):
            cur = self.arg_dict[n]
            if sh is None or tuple(cur.shape) == tuple(sh):
                args[n] = cur
                if n in self.grad_dict:
                    grads[n] = self.grad_dict[n]
            else:
                args[n] = _resize(n, cur, sh, n in kwargs)
                if n in self.grad_dict:
                    grads[n] = NDArray(jnp.zeros(sh, cur.data.dtype))
        aux = {}
        for n, sh in zip(self.aux_names, aux_shapes):
            cur = self.aux_dict[n]
            aux[n] = (cur if sh is None or tuple(cur.shape) == tuple(sh)
                      else _resize(n, cur, sh, False))
        return Executor(self._symbol, self._ctx, args, grads or None,
                        self._grad_req, aux,
                        group2ctx=(self._placement if self._placement
                                   is not None else self._group2ctx))

    @property
    def symbol(self):
        return self._symbol

    def debug_str(self):
        lines = ["Symbol outputs: %s" % ", ".join(self.output_names)]
        for node in self._nodes:
            if node.is_variable:
                lines.append("Variable:%s" % node.name)
            else:
                lines.append("Op:%s, Name=%s" % (node.op.name, node.name))
        return "\n".join(lines)


def simple_bind(symbol, ctx, grad_req="write", type_dict=None, group2ctx=None,
                shared_exec=None, **kwargs):
    """Allocate all arrays from inferred shapes then bind
    (ref: python/mxnet/symbol.py:1114 simple_bind)."""
    arg_shapes, out_shapes, aux_shapes = symbol.infer_shape(**kwargs)
    if arg_shapes is None:
        raise MXNetError("simple_bind: cannot infer shapes from %r" % kwargs)
    arg_names = symbol.list_arguments()
    aux_names = symbol.list_auxiliary_states()
    # complete dtypes through the graph: one typed input (bf16 data, int32
    # label) types every parameter the way the reference's InferType pass
    # does (ref: c_api_symbolic.cc infer-type; tests/python/train/test_dtype)
    type_dict = dict(type_dict or {})
    arg_types, _out_t, aux_types = symbol.infer_type_partial(**type_dict)
    for n, t in zip(arg_names, arg_types):
        if n not in type_dict and t is not None:
            type_dict[n] = t
    aux_type_of = dict(zip(aux_names, aux_types))

    # group2ctx: allocate each group's parameters SHARDED over the mesh so
    # weight memory distributes across devices (the capacity win that
    # motivated the reference's layer-per-GPU placement)
    from .parallel import placement as _placement
    gp = _placement.resolve(group2ctx)
    pgroups = (_placement.param_groups(_topo(symbol._out_nodes()))
               if gp is not None else {})

    def _alloc(n, sh, dt):
        arr = jnp.zeros(sh, dt)
        g = pgroups.get(n)
        if g is not None:
            spec = gp.param_spec(g, sh)
            if spec is not None:
                arr = jax.device_put(
                    arr, jax.sharding.NamedSharding(gp.mesh, spec))
        return NDArray(arr)

    def _shared(pool, n, sh, dt):
        # reuse the shared executor's arrays when shape AND dtype match
        # (ref: shared_exec memory pool, graph_executor.cc:352-355,:505-512 —
        # bucketing executors share parameter storage)
        if shared_exec is not None and n in pool \
                and tuple(pool[n].shape) == tuple(sh) \
                and pool[n].dtype == dt:
            return pool[n]
        return None

    args = {}
    grads = {}
    for n, sh in zip(arg_names, arg_shapes):
        dt = np.dtype(type_dict.get(n, np.float32))
        shared = _shared(shared_exec.arg_dict if shared_exec else {}, n, sh, dt)
        args[n] = shared if shared is not None else _alloc(n, sh, dt)
        req = grad_req if isinstance(grad_req, str) else (
            grad_req[arg_names.index(n)] if isinstance(grad_req, (list, tuple))
            else grad_req.get(n, "null"))
        # integer inputs (labels, lookup ids) carry no gradient, matching
        # the reference's kNullOp for non-float storage types
        if req != "null" and np.issubdtype(dt, np.floating):
            sg = _shared(shared_exec.grad_dict if shared_exec else {}, n, sh,
                         dt)
            grads[n] = sg if sg is not None else _alloc(n, sh, dt)
    aux = {}
    for n, sh in zip(aux_names, aux_shapes):
        adt = np.dtype(aux_type_of.get(n) or np.float32)
        sa = _shared(shared_exec.aux_dict if shared_exec else {}, n, sh, adt)
        aux[n] = sa if sa is not None else NDArray(jnp.zeros(sh, adt))
    return Executor(symbol, ctx, args, grads or None, grad_req, aux,
                    group2ctx=gp if gp is not None else group2ctx,
                    shared_exec=shared_exec)
