"""commscheck: a static collective-communication analyzer for compiled
partitioned programs.

tracecheck (PR 5) audits the *semantics* of a compiled step program and
memcheck (PR 9) audits its *HBM*; this module completes the analyzer
trilogy with the third resource every partitioned program spends:
inter-chip bandwidth. The reference hand-routed its communication
(CommDevice reduce, ps-lite push/pull) so every byte on the wire was an
explicit line of code; on the XLA substrate GSPMD *places* the
collectives at compile time, and nothing audited what it placed — a
sharding mistake that sneaks an all-gather into the K-step scan body
replays its bandwidth K times per dispatch and is invisible until a
multichip run gets slow. The same motivation as TVM's static cost model
closing the loop between program structure and predicted performance
(arXiv:1802.04799), and TensorFlow's explicit Send/Recv accounting on its
dataflow edges (arXiv:1605.08695).

``commscheck`` compiles a program WITHOUT executing it (arguments may be
``ShapeDtypeStruct``s carrying real shardings — unsharded args compile an
unpartitioned program with no collectives at all) and walks the scheduled
partitioned HLO to build a per-program **collective inventory**: every
all-reduce / all-gather / reduce-scatter / all-to-all / collective-permute
with its mesh axes (inferred from replica groups against the mesh's device
grid), payload bytes (per HLO dtype width — memcheck's shape parser),
execution count (a ``while``-body collective runs K times per dispatch),
op path and source provenance. On top of the inventory ride four lints in
tracecheck's :class:`~mxnet_tpu.tracecheck.Finding` framework:

====================  ====================================================
lint id               fires when
====================  ====================================================
``resharding-copy``   an entry argument's declared sharding is re-laid-out
                      (a collective consumes the parameter directly)
                      before first use — the silent resharding copy the
                      PR 7 pre-sharded superbatch landing eliminated by
                      construction
``replicated-large``  an intermediate above
                      ``MXTPU_COMMSCHECK_REPL_BYTES`` (default 1 MiB) is
                      materialized replicated across a mesh axis where a
                      sharded operand exists (an all-gather that big means
                      every chip holds the full array)
``gather-in-loop``    a gather-type collective (anything but all-reduce /
                      collective-permute) sits inside the compiled while
                      body — it pays its bandwidth K times per dispatch
                      (generalizes the compiled half of tracecheck's
                      ``collective-in-scan``, which is now a thin alias
                      over this pass)
``comms-bound``       the static roofline predicts scaling efficiency
                      below ``MXTPU_COMMSCHECK_MIN_EFF`` (default 0.5):
                      predicted collective time (wire bytes / link
                      bandwidth per device kind) vs predicted compute
                      time (XLA cost-model FLOPs / peak) — the finding
                      carries the full inventory
====================  ====================================================

The roofline is a MODEL, not a measurement: ring-algorithm wire bytes
(all-reduce moves ``2(n-1)/n``x its payload, gather/scatter ``(n-1)/n``x,
ppermute 1x), a per-device-kind link-bandwidth table, and the existing
FLOPs lowering (``compiled.cost_analysis()`` — the same source bench.py's
MFU uses; the XLA cost model counts a while body ONCE, so compute and
per-iteration comm compare like with like). The multichip gate
(``__graft_entry__.dryrun_multichip``) cross-checks the prediction against
the measured 8-device efficiency and records both — a big gap is a note,
not a failure.

CLI::

    python -m mxnet_tpu.commscheck --zoo                  # 28 programs
    python -m mxnet_tpu.commscheck --zoo --sharded        # + the PR 7 set
    python -m mxnet_tpu.commscheck --models mlp,lenet --json
    python -m mxnet_tpu.commscheck --zoo --sharded \\
        --write-baseline COMMSCHECK_baseline.json

``--baseline`` is the CI drift gate (``ci/commscheck.sh``): every
program's per-dispatch collective count and payload bytes are compared
against the committed ``COMMSCHECK_baseline.json`` with a tolerance band
(``MXTPU_COMMSCHECK_TOL``, default 10%) — a refactor that sneaks an
all-gather into the scan body or triples the psum payload fails CI with
byte count and source provenance, before any multichip run. Exit status
is non-zero iff any unsuppressed finding or baseline regression remains.
"""
from __future__ import annotations

import itertools
import json
import re

import numpy as np

from .base import MXNetError, env_str
from .tracecheck import (Finding, COMM_LINTS, _is_suppressed, unsuppressed,
                         ZOO)
# ONE HLO-metadata parser set across the analyzer trilogy: byte/shape
# helpers and the op_name/source provenance regexes all live in memcheck
from .memcheck import (_parse_bytes, _shape_bytes, _fmt_bytes, _unescape,
                       _OPNAME_RE, _SOURCE_RE)

__all__ = [
    "CollectiveEntry", "CommsReport", "parse_collectives", "analyze",
    "analyze_compiled", "struct_args", "lint_report", "loop_findings",
    "check_program", "check_train_step", "check_zoo", "sharded_programs",
    "check_sharded", "compare_baseline", "write_baseline", "repl_bytes",
    "min_efficiency", "tolerance", "link_bandwidth", "peak_flops", "main",
    "COMM_LINTS",
]

#: collective kinds ordered as the lint catalog lists them; ``all-reduce``
#: is the expected grad/metric psum and ``collective-permute`` the
#: ring/pipeline schedule — the default in-loop allow list
COLLECTIVE_KINDS = ("all-reduce", "all-gather", "reduce-scatter",
                    "all-to-all", "collective-permute",
                    "collective-broadcast")

DEFAULT_LOOP_ALLOW = ("all-reduce", "collective-permute")

# the per-device-kind capability rows live in ONE shared table
# (mxnet_tpu.devspec) consumed by this roofline, bench MFU and
# flopcheck; these module-level names are kept as backward-compatible
# views (bench importing PEAK_FLOPS_PER_S from here keeps working)
from .devspec import (DEVICE_SPECS, DEFAULT_SPEC,
                      link_bandwidth, peak_flops)

#: one-directional inter-chip link bandwidth per device kind (bytes/s) —
#: a VIEW of :data:`mxnet_tpu.devspec.DEVICE_SPECS`
LINK_BYTES_PER_S = {k: s.link_bytes_per_s for k, s in DEVICE_SPECS.items()}
#: CPU / unknown backends: a nominal shared-memory "link" so predictions
#: stay finite and deterministic on the forced-host CI mesh
DEFAULT_LINK_BYTES_PER_S = DEFAULT_SPEC.link_bytes_per_s

#: peak dense bf16 FLOP/s per device kind — the same devspec rows
#: bench.py's MFU and flopcheck's roofline use
PEAK_FLOPS_PER_S = {k: s.peak_flops_per_s for k, s in DEVICE_SPECS.items()}
DEFAULT_PEAK_FLOPS_PER_S = DEFAULT_SPEC.peak_flops_per_s


def repl_bytes():
    """``replicated-large`` threshold (``MXTPU_COMMSCHECK_REPL_BYTES``,
    bytes with K/M/G/T binary suffixes; default 1 MiB)."""
    env = _parse_bytes(env_str("MXTPU_COMMSCHECK_REPL_BYTES"),
                       "MXTPU_COMMSCHECK_REPL_BYTES")
    return env if env is not None else (1 << 20)


def min_efficiency():
    """``comms-bound`` floor: predicted scaling efficiency below this
    fails (``MXTPU_COMMSCHECK_MIN_EFF``, default 0.5)."""
    from .base import env_float
    return env_float("MXTPU_COMMSCHECK_MIN_EFF", 0.5)


def tolerance():
    """Baseline drift-gate tolerance band (``MXTPU_COMMSCHECK_TOL``,
    default 0.1 = 10% growth allowed per program per metric)."""
    from .base import env_float
    return env_float("MXTPU_COMMSCHECK_TOL", 0.1)


# ---------------------------------------------------------------------------
# scheduled-HLO parsing: collectives, groups, axis attribution
# ---------------------------------------------------------------------------

# one collective instruction; the result type may be a TUPLE (a tiled
# all-to-all or a combined all-reduce returns one entry per shard/operand),
# so the type segment is matched lazily up to the opcode. ``-start``
# variants count; ``-done`` halves (the async retire) never match — the
# opcode must be followed directly by "(".
# a result type is either one array (`f32[8,4]{1,0}`) or a tuple of
# them. TPU layouts carry TILING PARENS inside the braces
# (`bf16[256,256]{1,0:T(8,128)}`), so the tuple alternative must allow
# one nesting level — a lazy `\(.*?\)` would truncate at T(…)'s `)` and
# the combined gradient all-reduce (tuple-typed, the dominant wire
# traffic on real chips) would silently vanish from the inventory
# NOTE the single-char `[^()]` branch: with `[^()]+` the star becomes
# ambiguous (many ways to chunk the same text) and a long non-matching
# paren line backtracks exponentially
_TYPE_PAT = (r"(?:\((?:[^()]|\([^()]*\))*\)"
             r"|[a-z][a-z0-9]*\[[0-9,]*\](?:\{[^}]*\})?)")
_COLL_RE = re.compile(
    r"^\s*(?:ROOT\s+)?%(?P<instr>[\w.\-]+)\s*=\s*"
    r"(?P<type>" + _TYPE_PAT + r")\s+"
    r"(?P<kind>" + "|".join(COLLECTIVE_KINDS) + r")(?P<start>-start)?\(")
# the async retire half: its (single) result type IS the collective's
# true payload — an async -start's own type is a (operand..., result...)
# tuple whose naive sum double-counts
_DONE_RE = re.compile(
    r"^\s*(?:ROOT\s+)?%[\w.\-]+\s*=\s*"
    r"(?P<type>" + _TYPE_PAT + r")\s+"
    r"(?:" + "|".join(COLLECTIVE_KINDS) + r")-done\("
    r"[^%]*%(?P<operand>[\w.\-]+)")

_SHAPE_RE = re.compile(r"([a-z][a-z0-9]*)\[([0-9,]*)\]")
# replica_groups={{0,1},{2,3}} (explicit) or [G,S]<=[dims]T(perm) (iota);
# the bare {} spelling means "every participating device, one group"
_GROUPS_EMPTY_RE = re.compile(r"replica_groups=\{\s*\}")
_GROUPS_EXPL_RE = re.compile(
    r"replica_groups=\{(\{[0-9,\s]*\}(?:,\s*\{[0-9,\s]*\})*)\}")
_GROUP_RE = re.compile(r"\{([0-9,\s]*)\}")
_GROUPS_IOTA_RE = re.compile(
    r"replica_groups=\[(\d+),(\d+)\]<=\[([0-9,]+)\](?:T\(([0-9,]+)\))?")
_PAIRS_RE = re.compile(r"source_target_pairs=\{((?:\{\d+,\d+\},?\s*)*)\}")
_PAIR_RE = re.compile(r"\{(\d+),(\d+)\}")
# entry-computation parameters (for resharding-copy: a collective whose
# operand IS an entry parameter re-lays-out a declared input sharding)
_ENTRY_RE = re.compile(r"^ENTRY\s+%[\w.\-]+\s*\(.*\{\s*$")
_COMP_END_RE = re.compile(r"^\}\s*$")
_PARAM_RE = re.compile(
    r"^\s*(?:ROOT\s+)?%(?P<instr>[\w.\-]+)\s*=\s*[^ ]+\s+parameter\(\d+\)")


def _type_bytes(type_str):
    """Total bytes of an HLO result type (array or tuple of arrays)."""
    total = 0
    for dtype, dims in _SHAPE_RE.findall(type_str):
        total += _shape_bytes(dtype, dims)
    return total


def _parse_groups(line):
    """Replica groups of one collective line as a tuple of tuples of
    partition ids, handling both the explicit and the iota spelling.
    Returns None when the line carries no replica_groups."""
    if _GROUPS_EMPTY_RE.search(line):
        return ()  # all devices, one group
    m = _GROUPS_EXPL_RE.search(line)
    if m:
        groups = []
        for g in _GROUP_RE.findall(m.group(1)):
            ids = tuple(int(x) for x in g.split(",") if x.strip())
            if ids:
                groups.append(ids)
        return tuple(groups) or None
    m = _GROUPS_IOTA_RE.search(line)
    if m:
        ngroups, gsize = int(m.group(1)), int(m.group(2))
        dims = [int(d) for d in m.group(3).split(",")]
        ids = np.arange(int(np.prod(dims))).reshape(dims)
        if m.group(4):
            perm = [int(p) for p in m.group(4).split(",")]
            ids = np.transpose(ids, perm)
        ids = ids.reshape(ngroups, gsize)
        return tuple(tuple(int(x) for x in row) for row in ids)
    return None


def _parse_pairs(line):
    m = _PAIRS_RE.search(line)
    if not m:
        return None
    return tuple((int(a), int(b)) for a, b in _PAIR_RE.findall(m.group(1)))


def _mesh_axis_groups(mesh):
    """``{axes_tuple: frozenset of frozensets of flat ids}`` for every
    single mesh axis and every axis pair: the partition-id groups a
    collective communicating over exactly those axes would carry (XLA's
    partition ids follow the mesh's flat device order)."""
    shape = tuple(mesh.devices.shape)
    names = tuple(mesh.axis_names)
    idx = np.arange(int(np.prod(shape)) or 1).reshape(shape)
    out = {}
    for r in (1, 2):
        for combo in itertools.combinations(range(len(names)), r):
            others = [a for a in range(len(names)) if a not in combo]
            t = np.transpose(idx, others + list(combo))
            gsize = int(np.prod([shape[a] for a in combo]) or 1)
            rows = t.reshape(-1, gsize)
            out[tuple(names[a] for a in combo)] = frozenset(
                frozenset(int(x) for x in row) for row in rows)
    return out


def _axes_of_groups(groups, axis_groups):
    """Mesh axis names a collective's replica groups communicate over
    (smallest matching axis set wins); None when nothing matches."""
    if not groups:
        return None
    gset = frozenset(frozenset(g) for g in groups)
    best = None
    for axes, expected in axis_groups.items():
        if expected == gset and (best is None or len(axes) < len(best)):
            best = axes
    return best


def _axis_of_pairs(pairs, mesh):
    """Mesh axis a collective-permute's source→target pairs move along:
    every pair must differ in exactly one (and the same) mesh
    coordinate."""
    if not pairs or mesh is None:
        return None
    shape = tuple(mesh.devices.shape)
    names = tuple(mesh.axis_names)
    axis = None
    for s, t in pairs:
        try:
            cs = np.unravel_index(s, shape)
            ct = np.unravel_index(t, shape)
        except ValueError:
            return None
        diff = [i for i in range(len(shape)) if cs[i] != ct[i]]
        if len(diff) != 1:
            return None
        if axis is None:
            axis = diff[0]
        elif axis != diff[0]:
            return None
    return (names[axis],) if axis is not None else None


def _wire_bytes(kind, payload, group_size):
    """Predicted on-the-wire bytes per device for one execution of a
    collective (ring-algorithm costs): all-reduce moves 2(n-1)/n x its
    payload, all-gather/all-to-all (n-1)/n x the gathered result,
    reduce-scatter (n-1) x its (scattered) result, collective-permute
    exactly its payload (one hop). An UNKNOWN group size (groups the
    parser could not attribute, no mesh to default against) charges one
    full payload rather than zero — a collective that exists moves bytes,
    and pricing it at 0 would silently disarm the comms-bound roofline
    for exactly the instructions we understand least."""
    if group_size is None:
        return payload
    n = group_size
    if n <= 1:
        return 0 if kind != "collective-permute" else payload
    if kind == "all-reduce":
        return int(2 * (n - 1) * payload / n)
    if kind in ("all-gather", "all-to-all", "collective-broadcast"):
        return int((n - 1) * payload / n)
    if kind == "reduce-scatter":
        return int((n - 1) * payload)
    return payload  # collective-permute


class CollectiveEntry(object):
    """One collective instruction of the scheduled partitioned HLO."""

    __slots__ = ("instruction", "kind", "bytes", "wire_bytes", "group_size",
                 "axes", "groups", "in_loop", "multiplier", "op_path",
                 "provenance", "operand_params")

    def __init__(self, instruction, kind, nbytes, wire_bytes, group_size,
                 axes, groups, in_loop, multiplier, op_path, provenance,
                 operand_params=()):
        self.instruction = instruction
        self.kind = kind
        self.bytes = int(nbytes)
        self.wire_bytes = int(wire_bytes)
        self.group_size = group_size
        #: mesh axis names the groups communicate over (None = unknown)
        self.axes = axes
        self.groups = groups
        #: inside the compiled while body: runs K times per dispatch
        self.in_loop = bool(in_loop)
        #: executions per dispatch (loop trips when in_loop, else 1)
        self.multiplier = int(multiplier)
        self.op_path = op_path
        self.provenance = provenance
        #: entry-parameter labels this collective consumes DIRECTLY (a
        #: non-empty list means a declared input sharding is re-laid-out)
        self.operand_params = list(operand_params)

    def as_dict(self):
        return {
            "instruction": self.instruction, "kind": self.kind,
            "bytes": self.bytes, "wire_bytes": self.wire_bytes,
            "group_size": self.group_size,
            "axes": list(self.axes) if self.axes else None,
            "in_loop": self.in_loop, "multiplier": self.multiplier,
            "op_path": self.op_path, "provenance": self.provenance,
            "operand_params": list(self.operand_params),
        }

    def format(self):
        where = self.op_path or self.instruction
        if self.provenance:
            where += " @ " + self.provenance
        ax = "axes=%s" % ",".join(self.axes) if self.axes else "axes=?"
        return ("%10s x%-3d %-18s %-12s %s"
                % (_fmt_bytes(self.bytes), self.multiplier, self.kind,
                   ax, where))

    def __repr__(self):
        return "CollectiveEntry(%s)" % self.format()


def parse_collectives(hlo_text, mesh=None, loop_trips=1):
    """Walk the scheduled partitioned HLO text and return the collective
    inventory: one :class:`CollectiveEntry` per collective instruction
    (``-start``/``-done`` async pairs counted once), with payload bytes
    from the result type (tuple types — combined all-reduces, tiled
    all-to-alls — summed), mesh-axis attribution from the replica groups
    against ``mesh``'s device grid, the in-loop flag from the ``op_name``
    metadata (``/while/`` path = the scan body, runs ``loop_trips`` times
    per dispatch), op path and source provenance, and the entry-parameter
    labels of directly-consumed arguments (the ``resharding-copy``
    evidence)."""
    axis_groups = _mesh_axis_groups(mesh) if mesh is not None else {}
    lines = hlo_text.splitlines()  # multi-MB text: split once, scan thrice
    # entry-computation parameter instruction names -> op_name label
    entry_params = {}
    in_entry = False
    for line in lines:
        if _ENTRY_RE.match(line):
            in_entry = True
            continue
        if in_entry and _COMP_END_RE.match(line):
            in_entry = False
            continue
        if not in_entry:
            continue
        pm = _PARAM_RE.match(line)
        if pm:
            op = _OPNAME_RE.search(line)
            entry_params[pm.group("instr")] = (
                _unescape(op.group(1)) if op else pm.group("instr"))
    # async retire halves: start-instruction name -> true result type
    done_types = {}
    for line in lines:
        dm = _DONE_RE.match(line)
        if dm:
            done_types[dm.group("operand")] = dm.group("type")
    entries = []
    for line in lines:
        m = _COLL_RE.match(line)
        if not m:
            continue
        kind = m.group("kind")
        type_str = m.group("type")
        if m.group("start"):
            # an async -start's own result type bundles operands next to
            # results ((f32[shard], f32[full]) for all-gather-start, plus
            # context scalars for collective-permute-start): prefer the
            # matching -done's single result type; fall back to the
            # largest tuple element rather than the double-counting sum
            done = done_types.get(m.group("instr"))
            if done is not None:
                type_str = done
            elif type_str.startswith("("):
                parts = _SHAPE_RE.findall(type_str)
                if parts:
                    best = max(parts,
                               key=lambda p: _shape_bytes(p[0], p[1]))
                    type_str = "%s[%s]" % best
        payload = _type_bytes(type_str)
        groups = _parse_groups(line)
        pairs = _parse_pairs(line) if kind == "collective-permute" else None
        if groups:  # non-empty parsed groups
            gsize = max(len(g) for g in groups)
            axes = _axes_of_groups(groups, axis_groups)
        elif pairs is not None:
            gsize = None
            axes = _axis_of_pairs(pairs, mesh)
        elif mesh is not None:
            # the bare replica_groups={} spelling (groups == ()) — and a
            # group collective with no parseable attribute — mean every
            # partition participates: default the group to the whole mesh
            # instead of silently pricing the collective at zero wire
            gsize = int(mesh.devices.size)
            axes = tuple(mesh.axis_names) if groups == () else None
        else:
            gsize = None
            axes = None
        op = _OPNAME_RE.search(line)
        op_path = _unescape(op.group(1)) if op else None
        src = _SOURCE_RE.search(line)
        prov = ("%s:%s" % (src.group(1), src.group(2))) if src else None
        in_loop = bool(op_path and "/while/" in op_path)
        # direct operands that are entry parameters: the operand list runs
        # from the opcode's "(" to its matching close — collectives take
        # plain array operands, so the first ")" ends it
        operand_seg = line[m.end():].split(")", 1)[0]
        consumed = [entry_params[nm]
                    for nm in re.findall(r"%([\w.\-]+)", operand_seg)
                    if nm in entry_params]
        entries.append(CollectiveEntry(
            m.group("instr"), kind, payload,
            _wire_bytes(kind, payload, gsize), gsize, axes, groups,
            in_loop, loop_trips if in_loop else 1, op_path, prov,
            operand_params=consumed))
    entries.sort(key=lambda e: e.bytes * e.multiplier, reverse=True)
    return entries


# ---------------------------------------------------------------------------
# the report + roofline
# ---------------------------------------------------------------------------

class CommsReport(object):
    """Static communication profile of ONE compiled partitioned program.

    ``collective_count`` / ``collective_bytes`` are PER-DISPATCH totals
    (in-loop entries multiplied by the loop trip count) — the two numbers
    the baseline drift gate pins. The roofline fields predict one
    iteration: ``comm_seconds`` spreads outside-loop wire bytes over the
    trips, ``compute_seconds`` is the XLA cost-model FLOPs (which counts
    a while body once) over the device-kind peak, and
    ``predicted_efficiency = compute / (compute + comm)`` — the
    zero-overlap scaling-efficiency bound the multichip gate compares
    against its measurement."""

    __slots__ = ("program", "platform", "n_devices", "entries",
                 "loop_trips", "flops", "link_bytes_per_s",
                 "peak_flops_per_s", "hlo_unavailable")

    def __init__(self, program, platform, n_devices, entries, loop_trips=1,
                 flops=None, link_bytes_per_s=None, peak_flops_per_s=None,
                 hlo_unavailable=False):
        self.program = program
        self.platform = platform
        self.n_devices = int(n_devices)
        self.entries = list(entries)
        self.loop_trips = max(1, int(loop_trips))
        self.flops = None if flops is None else float(flops)
        self.link_bytes_per_s = (link_bandwidth() if link_bytes_per_s is None
                                 else float(link_bytes_per_s))
        self.peak_flops_per_s = (peak_flops() if peak_flops_per_s is None
                                 else float(peak_flops_per_s))
        #: the executable's HLO text could not be read: the (empty)
        #: inventory is ABSENCE OF EVIDENCE, not a clean audit — the
        #: drift gate fails such programs and the roofline claims nothing
        self.hlo_unavailable = bool(hlo_unavailable)

    @property
    def collective_count(self):
        return sum(e.multiplier for e in self.entries)

    @property
    def collective_bytes(self):
        return sum(e.bytes * e.multiplier for e in self.entries)

    @property
    def wire_bytes(self):
        return sum(e.wire_bytes * e.multiplier for e in self.entries)

    @property
    def comm_seconds(self):
        """Predicted collective seconds per loop iteration (outside-loop
        collectives amortize over the trips)."""
        per_iter = sum(
            e.wire_bytes * (1.0 if e.in_loop else 1.0 / self.loop_trips)
            for e in self.entries)
        return per_iter / self.link_bytes_per_s

    @property
    def compute_seconds(self):
        if self.flops is None:
            return None
        return self.flops / self.peak_flops_per_s

    @property
    def predicted_efficiency(self):
        """Zero-overlap roofline bound on scaling efficiency; 1.0 for a
        collective-free program, None when the cost model reported no
        FLOPs for a program that does communicate — or when the HLO text
        was unavailable (an unreadable program is not a collective-free
        one)."""
        if self.hlo_unavailable:
            return None
        if not self.entries:
            return 1.0
        tc = self.compute_seconds
        if tc is None:
            return None
        comm = self.comm_seconds
        return tc / (tc + comm) if (tc + comm) > 0 else 1.0

    def counts_by_kind(self):
        out = {}
        for e in self.entries:
            out[e.kind] = out.get(e.kind, 0) + e.multiplier
        return out

    def breakdown(self, top=6):
        return [e.format() for e in self.entries[:top]]

    def as_dict(self):
        return {
            "program": self.program,
            "platform": self.platform,
            "n_devices": self.n_devices,
            "hlo_unavailable": self.hlo_unavailable,
            "collective_count": self.collective_count,
            "collective_bytes": self.collective_bytes,
            "wire_bytes": self.wire_bytes,
            "counts_by_kind": self.counts_by_kind(),
            "loop_trips": self.loop_trips,
            "flops": self.flops,
            "predicted_efficiency": self.predicted_efficiency,
            "entries": [e.as_dict() for e in self.entries],
        }

    def format(self):
        eff = self.predicted_efficiency
        return ("%s: %d collective(s)/dispatch, %s payload, predicted "
                "efficiency %s"
                % (self.program, self.collective_count,
                   _fmt_bytes(self.collective_bytes),
                   "?" if eff is None else "%.3f" % eff))

    def __repr__(self):
        return "CommsReport(%s)" % self.format()


def _infer_mesh(args, kwargs=None):
    """First mesh found on any argument leaf's NamedSharding (arguments
    carry the real shardings; the mesh names the axes for
    attribution)."""
    import jax
    for leaf in jax.tree_util.tree_leaves((tuple(args),
                                           dict(kwargs or {}))):
        sh = getattr(leaf, "sharding", None)
        mesh = getattr(sh, "mesh", None)
        if mesh is not None and getattr(mesh, "axis_names", None):
            return mesh
    return None


def struct_args(args):
    """args pytree -> ``ShapeDtypeStruct``s PRESERVING shardings: the
    abstract call signature of a sharded program, safe to build from
    donated (already-deleted) arrays — only metadata is read."""
    import jax

    def to_struct(x):
        if hasattr(x, "shape") and hasattr(x, "dtype"):
            sh = getattr(x, "sharding", None)
            # only MESH-aware shardings are worth pinning: a stray
            # SingleDeviceSharding (e.g. the uncommitted RNG key) pinned
            # into a struct would conflict with the mesh-sharded
            # arguments at lowering — left unspecified, the compiler
            # replicates it like the live dispatch does
            if getattr(getattr(sh, "mesh", None), "axis_names", None):
                try:
                    return jax.ShapeDtypeStruct(tuple(x.shape), x.dtype,
                                                sharding=sh)
                except (TypeError, ValueError):
                    pass
            return jax.ShapeDtypeStruct(tuple(x.shape), x.dtype)
        return x

    return jax.tree_util.tree_map(to_struct, args)


def analyze_compiled(compiled, name, mesh=None, loop_trips=1):
    """Build a :class:`CommsReport` from an ALREADY-compiled program
    (``jax.stages.Compiled`` — e.g. the executable bench just measured).
    Never executes anything."""
    import jax
    text_ok = True
    try:
        hlo_text = compiled.as_text()
        if not hlo_text:
            text_ok = False
    except Exception as exc:
        import logging
        logging.warning("commscheck: %s: compiled HLO text unavailable "
                        "(%r) — the inventory is empty for lack of "
                        "EVIDENCE, not because the program is "
                        "collective-free", name, exc)
        hlo_text = ""
        text_ok = False
    flops = None
    try:
        ca = compiled.cost_analysis()
        if isinstance(ca, list):
            ca = ca[0]
        if ca:
            flops = float(ca.get("flops", 0.0)) or None
    except Exception:
        flops = None
    n_dev = 1
    if mesh is not None:
        n_dev = int(mesh.devices.size)
    entries = parse_collectives(hlo_text, mesh=mesh, loop_trips=loop_trips)
    return CommsReport(name, jax.devices()[0].platform, n_dev, entries,
                       loop_trips=loop_trips, flops=flops,
                       hlo_unavailable=not text_ok)


def analyze(fn, args=(), kwargs=None, name=None, mesh=None, loop_trips=1):
    """Compile ``fn`` (never executed — args may be ``ShapeDtypeStruct``s
    but MUST carry the real shardings: partitioning happens at compile
    time, and unsharded arguments compile an unpartitioned program with
    no collectives at all) and return its :class:`CommsReport`.
    ``mesh`` defaults to the first mesh found on an argument's sharding;
    ``loop_trips`` is the scan depth K — a while-body collective counts
    K executions per dispatch."""
    import jax
    kwargs = dict(kwargs or {})
    if name is None:
        name = getattr(fn, "__name__", None) or repr(fn)
    if mesh is None:
        mesh = _infer_mesh(args, kwargs)
    jitted = fn if hasattr(fn, "lower") else jax.jit(fn)
    compiled = jitted.lower(*args, **kwargs).compile()
    return analyze_compiled(compiled, name, mesh=mesh,
                            loop_trips=loop_trips)


# ---------------------------------------------------------------------------
# lints
# ---------------------------------------------------------------------------

def loop_findings(report_or_entries, name, lint="gather-in-loop",
                  allow=DEFAULT_LOOP_ALLOW):
    """In-loop collective findings over an inventory: every while-body
    collective whose kind is not in ``allow``. Shared by this module's
    ``gather-in-loop`` lint and tracecheck's ``collective-in-scan``
    compiled pass (which is a thin alias over this — one collective
    parser for both). Suppressions are NOT applied here; callers do."""
    entries = (report_or_entries.entries
               if isinstance(report_or_entries, CommsReport)
               else report_or_entries)
    findings = []
    for e in entries:
        if not e.in_loop or e.kind in (allow or ()):
            continue
        # only claim a concrete per-dispatch count when the caller told
        # us the trip count — the check_collectives alias analyzes with
        # loop_trips=1 and must not assert a false "x1"
        mult = (", x%d per dispatch" % e.multiplier
                if e.multiplier > 1 else "")
        findings.append(Finding(
            lint, name,
            "compiled program runs %r inside the scan body (%s per "
            "execution%s) — the partitioned K-step dispatch should sync "
            "only by all-reduce (grad + metric psum) and ppermute (the "
            "ring schedule); this collective pays its bandwidth every "
            "loop trip" % (e.kind, _fmt_bytes(e.bytes), mult),
            op_path=e.op_path or "while/body", provenance=e.provenance))
    return findings


def lint_report(report, repl_threshold=None, min_eff=None,
                allow=DEFAULT_LOOP_ALLOW):
    """The four communication lints over one :class:`CommsReport`:
    ``resharding-copy``, ``replicated-large``, ``gather-in-loop``,
    ``comms-bound``. Returns findings with suppressions applied (like
    ``tracecheck.check_program``)."""
    repl_threshold = (repl_bytes() if repl_threshold is None
                      else int(repl_threshold))
    min_eff = min_efficiency() if min_eff is None else float(min_eff)
    name = report.program
    findings = []

    for e in report.entries:
        # resharding-copy: a collective consuming an entry parameter
        # DIRECTLY re-lays-out a declared input sharding before first use
        # (all-reduce excluded: reducing a parameter is an application
        # sum, not a layout change)
        if e.operand_params and e.kind != "all-reduce":
            findings.append(Finding(
                "resharding-copy", name,
                "entry argument %s is re-laid-out by %r (%s%s) before "
                "first use — its declared sharding does not match what "
                "the program computes with; land it pre-sharded (the way "
                "the superbatch H2D does) or fix the declared sharding"
                % (", ".join(repr(p) for p in e.operand_params), e.kind,
                   _fmt_bytes(e.bytes),
                   ", axes " + ",".join(e.axes) if e.axes else ""),
                op_path=e.op_path or e.instruction,
                provenance=e.provenance))
        # replicated-large: an all-gather materializing a buffer this big
        # means every chip in the group holds the full array — a
        # replicated intermediate where a sharded operand existed
        if (e.kind in ("all-gather", "collective-broadcast")
                and e.bytes > repl_threshold):
            findings.append(Finding(
                "replicated-large", name,
                "%r materializes %s replicated%s (> %s, "
                "MXTPU_COMMSCHECK_REPL_BYTES): every chip in the group "
                "holds the full array where a sharded operand existed — "
                "keep it sharded (with_sharding_constraint) or raise the "
                "threshold if replication is intended"
                % (e.kind, _fmt_bytes(e.bytes),
                   " across axis " + ",".join(e.axes) if e.axes else "",
                   _fmt_bytes(repl_threshold)),
                op_path=e.op_path or e.instruction,
                provenance=e.provenance))

    findings += loop_findings(report, name, lint="gather-in-loop",
                              allow=allow)

    eff = report.predicted_efficiency
    if eff is not None and report.entries and eff < min_eff:
        findings.append(Finding(
            "comms-bound", name,
            "predicted scaling efficiency %.3f is below the floor %.2f "
            "(MXTPU_COMMSCHECK_MIN_EFF): predicted compute %.3g s vs "
            "collective %.3g s per iteration at %s/s link bandwidth — "
            "the program is communication-bound before it ever runs. "
            "Inventory:\n  %s"
            % (eff, min_eff, report.compute_seconds, report.comm_seconds,
               _fmt_bytes(int(report.link_bytes_per_s)),
               "\n  ".join(report.breakdown())),
            op_path=(report.entries[0].op_path
                     or report.entries[0].instruction),
            provenance=report.entries[0].provenance))

    for f in findings:
        f.suppressed = _is_suppressed(f)
    return findings


def check_program(fn, args=(), kwargs=None, name=None, mesh=None,
                  loop_trips=1, repl_threshold=None, min_eff=None,
                  allow=DEFAULT_LOOP_ALLOW):
    """Analyze + lint ONE program; returns ``(findings, report)``."""
    report = analyze(fn, args, kwargs=kwargs, name=name, mesh=mesh,
                     loop_trips=loop_trips)
    return lint_report(report, repl_threshold=repl_threshold,
                       min_eff=min_eff, allow=allow), report


# ---------------------------------------------------------------------------
# runtime hook (MXTPU_COMMSCHECK / engine.commscheck_mode)
# ---------------------------------------------------------------------------

#: program names already audited by the dispatch hook — the audit pays
#: one extra compile, so it runs once per compiled program per process
_AUDITED = set()


def maybe_audit_dispatch(name, jitfn, call_args, loop_trips=1, mesh=None):
    """One-time comms audit of a freshly-compiled SHARDED dispatch
    program (``TrainStep`` calls this at first registration when it has
    a mesh): under ``MXTPU_COMMSCHECK=warn`` unsuppressed findings are
    logged, under ``error`` they raise — a gather sneaked into the scan
    body fails at the FIRST dispatch instead of after a slow multichip
    run. Costs one extra compile of the program; ``off`` (the default)
    skips entirely. The call arguments are reduced to sharded
    ``ShapeDtypeStruct``s first, so already-donated buffers are never
    touched."""
    from . import engine
    mode = engine.commscheck_mode()
    if mode == "off" or name in _AUDITED:
        return None
    _AUDITED.add(name)
    # knobs resolve BEFORE the analyzer guard: a malformed env var must
    # propagate as MXNetError instead of silently disarming the gate the
    # operator just configured (memcheck's load-audit hardening)
    repl = repl_bytes()
    floor = min_efficiency()
    try:
        findings, report = check_program(
            jitfn, struct_args(tuple(call_args)), name=name, mesh=mesh,
            loop_trips=loop_trips, repl_threshold=repl, min_eff=floor)
    except Exception as exc:
        import logging
        logging.warning("commscheck: dispatch audit of %s failed (%r) — "
                        "skipping", name, exc)
        return None
    if report.hlo_unavailable:
        # the armed gate must not pass vacuously: no HLO text means NO
        # audit ran (same contract as the CLI / baseline / multichip
        # consumers of this flag)
        msg = ("commscheck: compiled HLO text unavailable for %s — the "
               "MXTPU_COMMSCHECK audit could not run" % name)
        if mode == "error":
            raise MXNetError(msg)
        import logging
        logging.warning(msg)
        return report
    bad = unsuppressed(findings)
    if bad:
        msg = ("commscheck: %d finding(s) on sharded program %s "
               "(MXTPU_COMMSCHECK):\n%s"
               % (len(bad), name, "\n".join(f.format() for f in bad)))
        if mode == "error":
            raise MXNetError(msg)
        import logging
        logging.warning(msg)
    return report


# ---------------------------------------------------------------------------
# TrainStep / zoo / sharded-set auditing
# ---------------------------------------------------------------------------

def check_train_step(ts, data_shapes, label_shapes, k=2, guard=True,
                     name=None, repl_threshold=None, min_eff=None):
    """Comms-audit a :class:`~mxnet_tpu.train_step.TrainStep`'s full
    program set (``tracecheck.train_step_programs`` — THE shared recipe,
    so the three analyzers can never drift apart on program shape).
    Returns ``(findings, reports)``. Single-device program sets carry no
    collectives — their inventory pins ZERO in the baseline, so a
    refactor that makes a nominally-local program communicate fails the
    drift gate."""
    from .tracecheck import train_step_programs
    name = name or "TrainStep(%s)" % ts.symbol.name
    findings = []
    reports = {}
    for pname, jitfn, pargs in train_step_programs(
            ts, data_shapes, label_shapes, k=k, guard=guard, name=name):
        trips = k if "/scan[" in pname or "-scan[" in pname else 1
        fs, rep = check_program(jitfn, pargs, name=pname, mesh=ts.mesh,
                                loop_trips=trips,
                                repl_threshold=repl_threshold,
                                min_eff=min_eff)
        findings += fs
        reports[pname] = rep
    return findings, reports


def check_zoo(names=None, k=2, guard=True, repl_threshold=None,
              min_eff=None, log=None):
    """Comms-audit the model zoo's step programs (same configs as
    ``tracecheck.ZOO``); returns ``(findings, reports)``."""
    from .tracecheck import zoo_train_step
    names = list(names) if names else sorted(ZOO)
    findings = []
    reports = {}
    for mname in names:
        if mname not in ZOO:
            raise MXNetError("commscheck: unknown zoo model %r (have %s)"
                             % (mname, ", ".join(sorted(ZOO))))
        if log:
            log("commscheck: analyzing %s ..." % mname)
        ts, data_shapes, label_shapes = zoo_train_step(mname)
        fs, reps = check_train_step(
            ts, data_shapes, label_shapes,
            k=k, guard=guard, name=mname, repl_threshold=repl_threshold,
            min_eff=min_eff)
        findings += fs
        reports.update(reps)
    return findings, reports


def _sds(shape, dtype, sharding=None):
    import jax
    if sharding is None:
        return jax.ShapeDtypeStruct(tuple(shape), dtype)
    return jax.ShapeDtypeStruct(tuple(shape), dtype, sharding=sharding)


def sharded_programs(n_devices=8, k=2):
    """The PR 7 sharded gate program set (docs/perf.md "Data-parallel
    scaling"), as ``(name, jitfn, args, loop_trips, mesh, scope_mesh)``
    tuples with arguments carrying REAL shardings:

    * ``dp8/lenet/scan[k=2]`` — the fused K-step scan over an 8-way
      'data' mesh (the multichip gate's measured workload: in-scan grad
      psum, pre-sharded superbatch, replicated params);
    * ``dp4xtp2/resnet18/step`` — the fused step over data x model with
      the classifier FC tensor-parallel;
    * ``dp4xsp2/transformer-ring/step`` — the ring-attention transformer
      over data x seq (ppermute ring in the attention body).

    ``scope_mesh`` (when set) must be entered as the ambient
    ``MeshScope`` while tracing — the attention op resolves its 'seq'
    axis from it."""
    import jax
    from jax.sharding import Mesh, NamedSharding
    from . import models
    from .train_step import TrainStep
    from .parallel.mesh import data_parallel_mesh, MeshScope
    P = jax.sharding.PartitionSpec
    devices = jax.devices()
    if len(devices) < n_devices:
        raise MXNetError(
            "commscheck --sharded needs %d devices but only %d are "
            "visible — on CPU raise the count with XLA_FLAGS="
            "--xla_force_host_platform_device_count=%d"
            % (n_devices, len(devices), n_devices))
    f32 = np.float32
    progs = []

    def state_structs(ts, data_shapes, label_shapes):
        state = ts.init(data_shapes, label_shapes,
                        initializer=lambda desc, arr: None, seed=0)
        return struct_args(state)

    # 1) dp lenet fused scan — the measured multichip workload
    mesh = data_parallel_mesh(n_devices)
    batch = 64
    ts = TrainStep(models.lenet(num_classes=10), optimizer="sgd",
                   learning_rate=0.1, momentum=0.9, mesh=mesh)
    st = state_structs(ts, {"data": (batch, 1, 28, 28)},
                       {"softmax_label": (batch,)})
    sb_shard = NamedSharding(mesh, P(None, "data"))
    repl = NamedSharding(mesh, P())
    sb = {"data": _sds((k, batch, 1, 28, 28), f32, sb_shard),
          "softmax_label": _sds((k, batch), f32, sb_shard)}
    progs.append(("dp%d/lenet/scan[k=%d]" % (n_devices, k),
                  ts._build_scan(batch, k),
                  (st, sb, ts._dispatch_key(), _sds((k,), f32, repl)),
                  k, mesh, None))

    # 2) resnet18 dp x tp fused step — classifier FC tensor-parallel
    tp = 2 if n_devices % 2 == 0 else 1
    dp = n_devices // tp
    mesh2 = Mesh(np.array(devices[:n_devices]).reshape(dp, tp),
                 ("data", "model"))
    ts2 = TrainStep(models.resnet(num_classes=64, num_layers=18,
                                  image_shape="3,32,32"),
                    optimizer="sgd", learning_rate=0.1, momentum=0.9,
                    mesh=mesh2,
                    param_shardings={"fc1_weight": P("model", None),
                                     "fc1_bias": P("model")})
    b2 = 2 * dp
    st2 = state_structs(ts2, {"data": (b2, 3, 32, 32)},
                        {"softmax_label": (b2,)})
    dsh = NamedSharding(mesh2, P("data"))
    batch2 = {"data": _sds((b2, 3, 32, 32), f32, dsh),
              "softmax_label": _sds((b2,), f32, dsh)}
    progs.append(("dp%dxtp%d/resnet18/step" % (dp, tp), ts2._build(b2),
                  (st2, batch2, ts2._dispatch_key(),
                   _sds((), f32, NamedSharding(mesh2, P()))),
                  1, mesh2, None))

    # 3) ring-attention transformer dp x sp fused step
    sp = max(n_devices // dp, 1)
    mesh3 = Mesh(np.array(devices[:n_devices]).reshape(dp, sp),
                 ("data", "seq"))
    seq_len = 8 * sp
    sym3 = models.transformer(vocab_size=64, embed=32, num_heads=4,
                              num_layers=2, seq_len=seq_len,
                              seq_parallel="ring")
    with MeshScope(mesh3):
        ts3 = TrainStep(sym3, optimizer="sgd", learning_rate=0.1,
                        mesh=mesh3)
        b3 = 2 * dp
        st3 = state_structs(ts3, {"data": (b3, seq_len)},
                            {"softmax_label": (b3, seq_len)})
    bsh = NamedSharding(mesh3, P("data", "seq"))
    batch3 = {"data": _sds((b3, seq_len), f32, bsh),
              "softmax_label": _sds((b3, seq_len), f32, bsh)}
    progs.append(("dp%dxsp%d/transformer-ring/step" % (dp, sp),
                  ts3._build(b3),
                  (st3, batch3, ts3._dispatch_key(),
                   _sds((), f32, NamedSharding(mesh3, P()))),
                  1, mesh3, mesh3))

    # 4) the flagship-LM multi-axis fused K-step scan (docs/perf.md
    # "Flagship LM"): the dp x sp ring transformer with the rank-3
    # preserve_shape head through the scan path Module.fit dispatches —
    # in-scan grad psum over 'data' composed with the ppermute ring over
    # 'seq', carry pinned by the jit-root state out_shardings, and no
    # batch x seq dim merge anywhere (the flat head's reshape would pay
    # an all-gather over 'seq' every trip)
    sym4 = models.transformer(vocab_size=64, embed=32, num_heads=4,
                              num_layers=2, seq_len=seq_len,
                              seq_parallel="ring", preserve_shape=True)
    with MeshScope(mesh3):
        # pos_embed rows live with their 'seq' shard — replicated, the
        # naturally seq-sharded grad would all-gather every trip in the
        # optimizer update
        ts4 = TrainStep(sym4, optimizer="sgd", learning_rate=0.1,
                        mesh=mesh3,
                        param_shardings={"pos_embed_weight":
                                         P("seq", None)})
        st4 = state_structs(ts4, {"data": (b3, seq_len)},
                            {"softmax_label": (b3, seq_len)})
        scan4 = ts4._build_scan(b3, k, state=st4)
    sbsh = NamedSharding(mesh3, P(None, "data", "seq"))
    sb4 = {"data": _sds((k, b3, seq_len), f32, sbsh),
           "softmax_label": _sds((k, b3, seq_len), f32, sbsh)}
    progs.append(("dp%dxsp%d/transformer-ring/scan[k=%d]" % (dp, sp, k),
                  scan4,
                  (st4, sb4, ts4._dispatch_key(),
                   _sds((k,), f32, NamedSharding(mesh3, P()))),
                  k, mesh3, mesh3))
    return progs


def check_sharded(n_devices=8, k=2, repl_threshold=None, min_eff=None,
                  log=None):
    """Comms-audit the sharded gate program set; returns ``(findings,
    reports)``."""
    import contextlib
    from .parallel.mesh import MeshScope
    findings = []
    reports = {}
    for name, jitfn, args, trips, mesh, scope in sharded_programs(
            n_devices=n_devices, k=k):
        if log:
            log("commscheck: analyzing %s ..." % name)
        ambient = (MeshScope(scope) if scope is not None
                   else contextlib.nullcontext())
        with ambient:
            fs, rep = check_program(jitfn, args, name=name, mesh=mesh,
                                    loop_trips=trips,
                                    repl_threshold=repl_threshold,
                                    min_eff=min_eff)
        findings += fs
        reports[name] = rep
    return findings, reports


# ---------------------------------------------------------------------------
# the baseline drift gate (ci/commscheck.sh)
# ---------------------------------------------------------------------------

#: metrics the baseline pins per program — HLO-deterministic counts, so
#: unlike memcheck's byte bands there is NO absolute slack: a collective
#: appearing where the baseline pinned zero fails at any tolerance
_BASELINE_METRICS = ("collective_count", "collective_bytes")


def write_baseline(reports, path, tol=None):
    """Write the committed baseline: per-program collective count/bytes,
    keyed by platform (a CPU baseline must not gate a TPU run). Refuses
    evidence-free reports — committing a fabricated zero for a program
    whose HLO text could not be read would pin the drift gate on
    nothing."""
    import jax
    from .model import atomic_write_bytes
    blind = sorted(n for n, r in reports.items()
                   if getattr(r, "hlo_unavailable", False))
    if blind:
        raise MXNetError(
            "write_baseline: compiled HLO text was unavailable for %s — "
            "their inventories are absence of evidence, not zeros; "
            "refusing to commit a fabricated baseline" % ", ".join(blind))
    data = {
        "platform": jax.devices()[0].platform,
        "tolerance": tolerance() if tol is None else float(tol),
        "programs": {
            name: {m: int(getattr(rep, m)) for m in _BASELINE_METRICS}
            for name, rep in sorted(reports.items())},
    }
    atomic_write_bytes(path, (json.dumps(data, indent=2, sort_keys=True)
                              + "\n").encode())
    return data


def compare_baseline(reports, baseline, tol=None):
    """The drift gate: compare every report against the committed
    baseline. Returns ``(failures, notes)`` — a program whose collective
    count or payload bytes grew past the tolerance band fails WITH its
    inventory breakdown (byte counts + source provenance); a program
    missing from the baseline fails too (new programs are added
    deliberately). Shrinks and stale entries are notes; a
    platform-mismatched baseline skips the gate with one note."""
    import jax
    if isinstance(baseline, str):
        with open(baseline) as f:
            baseline = json.load(f)
    if tol is None:
        # precedence: explicit arg > MXTPU_COMMSCHECK_TOL env > the
        # baseline's stored band > 0.1 (memcheck's hardened ordering)
        from .base import env_float
        tol = env_float("MXTPU_COMMSCHECK_TOL",
                        float(baseline.get("tolerance", 0.1)))
    else:
        tol = float(tol)
    platform = jax.devices()[0].platform
    failures, notes = [], []
    if baseline.get("platform") != platform:
        notes.append(
            "commscheck baseline was written on platform %r but this run "
            "is %r — skipping the drift gate (re-run --write-baseline on "
            "this platform to arm it)"
            % (baseline.get("platform"), platform))
        return failures, notes
    base_progs = dict(baseline.get("programs") or {})
    for name, rep in sorted(reports.items()):
        base = base_progs.pop(name, None)
        if getattr(rep, "hlo_unavailable", False):
            # no HLO text = no evidence: the gate must not read the empty
            # inventory as a clean (or nicely-shrunk) audit
            failures.append(
                "%s: compiled HLO text unavailable on this backend — the "
                "collective inventory could not be audited; the drift "
                "gate refuses to pass on absence of evidence" % name)
            continue
        if base is None:
            failures.append(
                "%s: not in the baseline — a new program must be added "
                "deliberately (run `python -m mxnet_tpu.commscheck --zoo "
                "--sharded --write-baseline COMMSCHECK_baseline.json` and "
                "commit the diff)" % name)
            continue
        for metric in _BASELINE_METRICS:
            b = int(base.get(metric, 0))
            cur = int(getattr(rep, metric))
            allowed = b + int(b * tol)
            if cur > allowed:
                breakdown = "\n  ".join(rep.breakdown()) or "(empty)"
                failures.append(
                    "%s: %s grew %d -> %d (tolerance %.0f%%, "
                    "MXTPU_COMMSCHECK_TOL) — a collective was added or "
                    "its payload grew. Inventory:\n  %s"
                    % (name, metric, b, cur, 100.0 * tol, breakdown))
            elif cur == 0 and b > 0:
                # a nonzero-pinned program collapsing to ZERO collectives
                # is indistinguishable from a parser/HLO-format
                # regression that blinded the whole audit — fail, don't
                # note; a real de-communication is locked in deliberately
                # via --write-baseline
                failures.append(
                    "%s: %s collapsed %d -> 0 — either the program "
                    "genuinely stopped communicating (refresh the "
                    "baseline deliberately) or the HLO parser went blind "
                    "(an XLA text-format drift); the gate refuses to "
                    "treat a total collapse as a win" % (name, metric, b))
            elif cur < b - int(b * tol) and b > 0:
                notes.append(
                    "%s: %s shrank %d -> %d — nice; refresh the baseline "
                    "to lock the win in" % (name, metric, b, cur))
    for name in sorted(base_progs):
        notes.append("baseline entry %r matches no audited program "
                     "(stale — refresh the baseline)" % name)
    return failures, notes


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------

def report_table(reports, out=None):
    import sys
    out = out or sys.stdout
    w = max([len(n) for n in reports] + [8])
    out.write("%-*s  %6s %12s %12s %8s\n"
              % (w, "program", "coll", "payload", "wire", "pred-eff"))
    for name in sorted(reports):
        r = reports[name]
        eff = r.predicted_efficiency
        out.write("%-*s  %6d %12s %12s %8s\n"
                  % (w, name, r.collective_count,
                     _fmt_bytes(r.collective_bytes),
                     _fmt_bytes(r.wire_bytes),
                     "?" if eff is None else "%.3f" % eff))


def main(argv=None):
    import argparse
    import sys
    from . import tracecheck as _tc
    p = argparse.ArgumentParser(
        prog="python -m mxnet_tpu.commscheck",
        description="Static collective-communication analyzer: per-program"
                    " collective inventory (kind/axes/bytes/loop"
                    " multiplier), resharding/replication/in-loop-gather"
                    " lints, a comms roofline, and the baseline drift gate"
                    " (docs/static_analysis.md \"Communication lints\").")
    p.add_argument("--zoo", action="store_true",
                   help="analyze every shipped model's step/scan programs")
    p.add_argument("--models", default=None,
                   help="comma-separated zoo subset (implies --zoo)")
    p.add_argument("--sharded", action="store_true",
                   help="also analyze the PR 7 sharded gate set (dp lenet "
                        "scan, dp x tp resnet18, dp x sp ring transformer;"
                        " needs 8 visible devices)")
    p.add_argument("--devices", type=int, default=8,
                   help="device count for --sharded (default 8)")
    p.add_argument("--k", type=int, default=2,
                   help="scan depth for the K-step programs (default 2)")
    p.add_argument("--no-guard", action="store_true",
                   help="skip the guarded program variants")
    p.add_argument("--repl-bytes", default=None,
                   help="replicated-large threshold (K/M/G/T suffixes ok; "
                        "default MXTPU_COMMSCHECK_REPL_BYTES or 1 MiB)")
    p.add_argument("--min-eff", type=float, default=None,
                   help="comms-bound efficiency floor (default "
                        "MXTPU_COMMSCHECK_MIN_EFF or 0.5)")
    p.add_argument("--baseline", default=None, metavar="FILE",
                   help="compare against a committed baseline (the CI "
                        "drift gate); exit non-zero on collective "
                        "count/byte growth past tolerance")
    p.add_argument("--write-baseline", default=None, metavar="FILE",
                   help="write the per-program baseline JSON and exit 0 "
                        "(refreshing the baseline is a deliberate act)")
    p.add_argument("--tol", type=float, default=None,
                   help="baseline tolerance band (default "
                        "MXTPU_COMMSCHECK_TOL, the baseline's own, or "
                        "0.1)")
    p.add_argument("--json", action="store_true", help="JSON output")
    p.add_argument("--list", action="store_true",
                   help="list zoo models and exit")
    p.add_argument("--quiet", action="store_true",
                   help="suppress progress lines")
    args = p.parse_args(argv)
    if args.list:
        for n in sorted(ZOO):
            print(n)
        return 0
    if not (args.zoo or args.models or args.sharded):
        p.error("nothing to check: pass --zoo, --models or --sharded")
    names = ([s.strip() for s in args.models.split(",") if s.strip()]
             if args.models else None)
    log = (lambda m: None) if (args.quiet or args.json) \
        else (lambda m: print(m, file=sys.stderr))
    repl = (None if args.repl_bytes is None
            else _parse_bytes(args.repl_bytes, "--repl-bytes"))
    findings, reports = [], {}
    if args.zoo or args.models:
        findings, reports = check_zoo(names=names, k=args.k,
                                      guard=not args.no_guard,
                                      repl_threshold=repl,
                                      min_eff=args.min_eff, log=log)
    if args.sharded:
        fs, reps = check_sharded(n_devices=args.devices, k=args.k,
                                 repl_threshold=repl,
                                 min_eff=args.min_eff, log=log)
        findings += fs
        reports.update(reps)
    if args.write_baseline:
        write_baseline(reports, args.write_baseline, tol=args.tol)
        log("commscheck: baseline written to %s (%d programs)"
            % (args.write_baseline, len(reports)))
        return 0
    failures, notes = [], []
    if args.baseline:
        # compare_baseline already fails hlo_unavailable reports
        failures, notes = compare_baseline(reports, args.baseline,
                                           tol=args.tol)
    else:
        # no baseline gate running: the absence-of-evidence contract
        # still holds — an audit that never saw any HLO must not pass
        for n in sorted(reports):
            if reports[n].hlo_unavailable:
                failures.append(
                    "%s: compiled HLO text unavailable on this backend — "
                    "nothing was audited; refusing to pass on absence of "
                    "evidence" % n)
    bad = unsuppressed(findings)
    if args.json:
        import jax
        print(json.dumps({
            "platform": jax.devices()[0].platform,
            "programs": {n: r.as_dict() for n, r in sorted(reports.items())},
            "findings": [f.as_dict() for f in findings],
            "suppressed": len(findings) - len(bad),
            "baseline_failures": failures,
            "baseline_notes": notes,
        }, indent=2))
    else:
        report_table(reports)
        _tc.report(findings)
        for n in notes:
            print("note: %s" % n)
        for f in failures:
            print("BASELINE REGRESSION: %s" % f)
        print("commscheck: %d finding(s) (%d suppressed), %d baseline "
              "regression(s) over %d program(s)"
              % (len(findings), len(findings) - len(bad), len(failures),
                 len(reports)))
    return 1 if (bad or failures) else 0


if __name__ == "__main__":
    import sys
    sys.exit(main())
