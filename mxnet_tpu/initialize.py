"""Process initialization: crash backtraces + profiler autostart.

Python analog of the reference's startup hooks (ref: src/initialize.cc:1-61
— SIGSEGV backtrace handler and MXNET_PROFILER_AUTOSTART). Native crashes
in the JAX/XLA substrate get a Python-side traceback dump via faulthandler;
set MXNET_USE_SIGNAL_HANDLER=0 to opt out (embedding hosts that install
their own handlers, e.g. language bindings over the C API).
"""
from __future__ import annotations

import os
import sys


def install():
    if os.environ.get("MXNET_USE_SIGNAL_HANDLER", "1") == "0":
        return
    try:
        import faulthandler
        # stderr may be closed/replaced in embedded interpreters
        if getattr(sys.stderr, "fileno", None) is not None:
            faulthandler.enable(file=sys.stderr, all_threads=True)
    except Exception:
        pass


install()
